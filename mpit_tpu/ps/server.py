"""ParamServer — one process/role per shard, service loops per client.

Rebuild of reference asyncsgd/pserver.lua (plus the BiCNN variant's
server-side optimizer state, BiCNN/pserver.lua:50-83) with TPU-native
mechanics:

- The shard and its optimizer state are JAX arrays; every incoming
  gradient triggers one jitted ``rule.apply`` XLA program (the analog of
  the in-place ``p:add(g)`` / server-side Adam etc., reference
  pserver.lua:83, BiCNN/pserver.lua:123-197).  By default they live on
  the **host CPU backend** — the server is a host role and the
  reference's servers are CPU torch; on a tunneled-accelerator platform
  the old default-device placement shipped every shard over the tunnel
  twice per message (measured 43 -> 129 MB/s aggregate on the 640 MB
  ptest from this one change, before the scheduler idle backoff took it
  further).  Pass ``device="default"`` to keep shards on the platform
  default (e.g. a local accelerator whose HBM you want).
- Service loops are generator tasks on the cooperative scheduler — the
  direct analog of the reference's per-client coroutines
  (pserver.lua:131-157): ``recv_init``, one-shot ``recv_param`` from the
  seeding client, perpetual ``send_param`` / ``recv_grad`` loops, and the
  stop counter (pserver.lua:115-129).
- The reference's deliberate lock-free read ("expect inconsistent read",
  pserver.lua:74) maps to serve-latest-committed: ``send_param`` snapshots
  the current immutable device array — writers are never quiesced, and no
  torn read is possible.

Wire codecs (beyond-reference): each client negotiates a codec in its
INIT v2 announcement (mpit_tpu/comm/codec.py; the 16-byte legacy INIT
means 'none').  Gradient frames are decoded *inside* the jitted shard
update — ``decode(wire) -> rule.apply`` is one XLA program, so the
quantized path keeps today's one-call-per-grad shape.  Parameter reads
are served from a **version-counted encoded snapshot cache**: the
version bumps on every apply/seed, and N clients pulling the same
committed version cost one device->host copy plus one encode, not N
(``snapshot_copies`` / ``snapshot_hits`` count the win).

Fault tolerance (mpit_tpu.ft): the server's pre-FT failure mode was to
block forever on a dead client — every per-client service loop recv'd
unboundedly and the stop protocol counted STOPs from all clients.  Now:

- a :class:`LeaseRegistry` tracks per-client liveness from HEARTBEAT
  beacons (INIT v3 announces them); an expired lease **evicts** the
  client: its service loops unblock via their ``abort`` predicate, its
  staging is released, and the stop condition becomes "every client
  STOPPED or EVICTED" — the gang survives the loss;
- framed clients' GRAD / PARAM_PUSH frames carry [epoch, seq] headers,
  admitted through a :class:`DedupTable` so a retried op is applied at
  most once and its ack re-sent (the client's retry makes delivery
  at-least-once; dedup makes the apply exactly-once);
- when rejoin is enabled, a per-client INIT listener accepts a new
  incarnation mid-run (epoch+1), tears down the old generation's
  services, and respawns them against the new epoch;
- checkpoints carry the dedup table and each client's negotiated state,
  so a *restarted server* resumes serving retried ops without fresh
  INITs (clients never learn the server died — their deadlines cover
  the gap).
"""

from __future__ import annotations

import time
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from mpit_tpu.aio import (
    EXEC,
    DeadlineExceeded,
    LiveFlag,
    Scheduler,
    aio_recv,
    aio_send,
    aio_sleep,
    deadline_at,
)
from mpit_tpu.comm import codec as codec_mod
from mpit_tpu.comm import pool as comm_pool
from mpit_tpu.comm.transport import Transport
from mpit_tpu.cells import wire as _cellwire
from mpit_tpu.ft import (
    ACK_TIMING_WORDS,
    CHUNK_ACK_TIMING_WORDS,
    CHUNK_ACK_WORDS,
    DUP,
    FLAG_CHUNKED,
    FLAG_FRAMED,
    FLAG_HEARTBEAT,
    FLAG_READONLY,
    FLAG_STALENESS,
    FLAG_SUBSCRIBE,
    FLAG_TIMING,
    HDR_BYTES,
    STALE,
    TIMING_TAIL_BYTES,
    DedupTable,
    FTConfig,
    LeaseRegistry,
    chunk_hdr_bytes,
    chunk_reply_hdr_bytes,
    chunk_spans,
    chunk_stride,
    hdr_bytes,
    pack_chunk_reply,
    pack_reply_stamps,
    pack_version,
    reply_hdr_bytes,
    unpack_chunk_header,
    unpack_header,
    unpack_tx_stamp,
    unpack_version,
)
from mpit_tpu.dplane import exchange as _dpexchange
from mpit_tpu.dplane import hbm as _dphbm
from mpit_tpu.obs import (
    get_flight,
    get_recorder,
    obs_enabled,
    register_status_provider,
    registry_or_local,
)
from mpit_tpu.obs import clock as obs_clock
from mpit_tpu.optim.rules import ShardRule, make as make_rule
from mpit_tpu.ps import serve as _psserve
from mpit_tpu.ps import tags
from mpit_tpu.shardctl import migrate as _scmigrate
from mpit_tpu.shardctl import wire as _scwire
from mpit_tpu.shardctl.migrate import ShardSlot
from mpit_tpu.shardctl.shardmap import ShardMap
from mpit_tpu.utils.logging import get_logger


class ParamServer:
    def __init__(
        self,
        rank: int,
        client_ranks: list[int],
        transport: Transport,
        rule: ShardRule | str = "add",
        scheduler: Optional[Scheduler] = None,
        dtype=np.float32,
        single_mode: bool = False,
        ckpt_dir: Optional[str] = None,
        ckpt_interval: float = 30.0,
        device: str = "cpu",  # "cpu" (host role, reference-faithful) | "default"
        codec: Optional[str] = None,  # None: adopt each client's announcement;
        #                               a name pins it — mismatches fail loudly
        ft: Optional[FTConfig] = None,
        controller_rank: Optional[int] = None,  # shardctl control plane
        reader_ranks: Optional[list] = None,  # serving tier (§8): READ-ONLY
        #                                       attachers, not protocol clients
        serve: Optional["_psserve.ServeConfig"] = None,
        shardctl: bool = False,  # joiner mode (§9.1): a controller-spawned
        #                          server enters an sc gang mid-run — no
        #                          phase-1 INIT wait; clients greet lazily
        #                          and shards arrive via ACQUIRE
        admit_ranks: Optional[list] = None,  # late-join candidates (§9.6):
        #                                      client ranks that may INIT
        #                                      mid-run without being part of
        #                                      the launch-time set
        preempt: "Optional[Any]" = None,  # ft.elastic.PreemptionNotice —
        #                                   checkpoint-on-notice + PREEMPT
        #                                   report when it fires (§9.3)
        cell_ranks: Optional[list] = None,  # multi-cell serving fabric
        #                          (§11): replica cells that SUBSCRIBE to
        #                          this server's committed version stream
        #                          and serve READ-ONLY traffic from their
        #                          own installed copy.  Not protocol
        #                          clients: no grads, no reads — one diff
        #                          stream each.
        cell_history: int = 16,  # encoded frame versions kept per codec
        #                          for delta production; a cell further
        #                          behind resyncs with a FULL frame.
        dplane: "Optional[_dphbm.PlaneConfig]" = None,  # device-resident
        #                          data plane (mpit_tpu.dplane): shard +
        #                          rule state live as (mesh-sharded) HBM
        #                          arrays with donated jitted applies;
        #                          publish=True additionally offers the
        #                          in-process device exchange.  Wins over
        #                          the `device` placement knob.
    ):
        self.rank = rank
        self.cranks = list(client_ranks)
        # Serving tier (docs/PROTOCOL.md §8): expected reader ranks.
        # Readers are outside the client phases (no seeding, no grad
        # services) — each gets a lazy attach listener, a read service
        # behind the admission budget, and a stop/lease slot, so the
        # gang ends when every writer AND every expected reader is
        # terminal.
        self.readers = list(reader_ranks or [])
        self._reader_set = set(self.readers)
        if self._reader_set & set(self.cranks):
            raise ValueError(
                f"reader_ranks {sorted(self._reader_set & set(self.cranks))}"
                " overlap client_ranks — a rank is a writer or a reader,"
                " not both")
        # Multi-cell serving fabric (§11): subscriber cells are a third
        # role — like readers they are outside the client phases (lease
        # slot, lazy attach, stop accounting) but they receive the
        # pushed diff stream instead of requesting reads.
        self.cells = list(cell_ranks or [])
        self._cell_set = set(self.cells)
        overlap = self._cell_set & (set(self.cranks) | self._reader_set)
        if overlap:
            raise ValueError(
                f"cell_ranks {sorted(overlap)} overlap client/reader "
                "ranks — a rank is a writer, a reader, or a cell, never "
                "two of them")
        self._cell_keep = int(cell_history)
        self.serve_cfg = (serve if serve is not None
                          else _psserve.ServeConfig.from_env())
        self.transport = transport
        self.rule = make_rule(rule) if isinstance(rule, str) else rule
        self.sched = scheduler or Scheduler()
        from mpit_tpu.utils.serialize import resolve_dtype

        self.dtype = resolve_dtype(dtype)
        self.single_mode = single_mode  # perpetual param-push service
        self.live = LiveFlag()
        self.log = get_logger("pserver", rank)

        self.offset = -1
        self.size = -1
        self.param: Optional[jnp.ndarray] = None  # device-resident shard
        self.rule_state = None
        self.grad_bufs: Dict[int, np.ndarray] = {}  # host recv staging, per client
        # Codec negotiation state (INIT v2).  codec=None adopts whatever
        # each client announces (per-pair negotiation — mixed-codec
        # gangs are legal); an explicit name validates every
        # announcement against it and raises on mismatch rather than
        # decoding frames with the wrong codec.
        if codec:  # fail at construction, not first INIT
            codec_mod.get(codec)
        self._codec_pin = codec or None
        self._codecs: Dict[int, codec_mod.Codec] = {}
        self._grad_views: Dict[int, List[np.ndarray]] = {}
        self._grad_data: Dict[int, np.ndarray] = {}  # identity typed view
        self._push_bufs: Dict[int, np.ndarray] = {}
        self._push_host: Dict[int, np.ndarray] = {}
        self._apply_cache: Dict[str, Callable] = {}
        # FT state (mpit_tpu.ft): lease per client, dedup on
        # (client, epoch, seq), per-client service generation (bumped on
        # rejoin/eviction so stale loops abort), framed/heartbeat flags
        # from INIT v3, and the reply staging the framed paths need.
        self.ft = ft if ft is not None else FTConfig.from_env()
        self.leases = LeaseRegistry(self.cranks + self.readers + self.cells,
                                    ttl_s=self.ft.lease_ttl_s)
        self.dedup = DedupTable()
        self._framed: Dict[int, bool] = {}
        self._hb: Dict[int, bool] = {}
        # READ-ONLY postures (FLAG_READONLY, §8) + the admission
        # budget's live in-flight accounting: reply bytes/count queued
        # to the transport but not yet accepted, across all readers.
        self._readonly: Dict[int, bool] = {}
        self._serve_inflight_bytes = 0
        self._serve_inflight_reads = 0
        # Staleness telemetry (FLAG_STALENESS, negotiated per pair like
        # framing): frames from these clients carry the 24-byte
        # [epoch, seq, version] header; PARAM replies are stamped with
        # the served snapshot version and each applied GRAD's basis gap
        # feeds the mpit_ps_grad_staleness histogram.
        self._stale_track: Dict[int, bool] = {}
        self._stale_hists: Dict[int, Any] = {}
        # Causal-timing posture (FLAG_TIMING, §6.7): frames from these
        # clients carry a trailing send stamp; their acks/replies grow
        # the [t_tx_echo, t_recv, t_ack] tail the client's clock-offset
        # estimator consumes, and their heartbeats are echoed back on
        # HEARTBEAT_ECHO so the estimate refreshes between ops.
        self._timing: Dict[int, bool] = {}
        # Pipelined streaming posture (FLAG_CHUNKED, §12): elements per
        # chunk announced in INIT v5 (0/absent = whole-frame transfers),
        # the per-client fixed-size chunk receive staging (separate
        # buffers for the concurrent GRAD and PARAM_PUSH services), the
        # PARAM_PUSH assembly frames, and the per-(codec, chunk-size)
        # jitted chunk-apply cache.
        self._chunk: Dict[int, int] = {}
        self._chunk_rx: Dict[int, np.ndarray] = {}
        self._chunk_rx_push: Dict[int, np.ndarray] = {}
        self._chunk_asm: Dict[int, np.ndarray] = {}
        self._chunk_apply_cache: Dict[Tuple[str, int], Callable] = {}
        _members = self.cranks + self.readers + self.cells
        self._gen: Dict[int, int] = {c: 0 for c in _members}
        self._svc_live: Dict[int, int] = {c: 0 for c in _members}
        # Diff-stream producer state (§11.2): SUBSCRIBE postures, the
        # last version shipped per cell (-1 = owes a FULL frame), one
        # in-flight push flag per cell (FIFO per channel), and the
        # per-codec encoded frame history deltas are drawn from.
        self._subscribe: Dict[int, bool] = {}
        self._cell_sent: Dict[int, int] = {}
        self._cell_push_live: Dict[int, bool] = {}
        self._cell_hist: Dict[str, _cellwire.FrameHistory] = {}
        self._param_send: Dict[int, np.ndarray] = {}
        self._ack_send: Dict[int, np.ndarray] = {}
        self._req_buf: Dict[int, np.ndarray] = {}
        self._hb_buf: Dict[int, np.ndarray] = {}
        self._restored_clients: set = set()
        # shardctl (mpit_tpu.shardctl): a versioned map replaces the
        # single (offset, size) registration; owned shards live in
        # per-shard slots (param + rule state + shard-scoped dedup +
        # snapshot cache) that migrate as a unit.  Activated by the
        # first INIT v4 announcement; mixing v4 and pre-v4 clients on
        # one server is rejected loudly.
        self.controller_rank = controller_rank
        self.smap: Optional[ShardMap] = None
        self._slots: Dict[int, ShardSlot] = {}
        self._sc = bool(shardctl)
        self._sc_join = bool(shardctl)  # spawned mid-run: no INIT phase
        # Elastic membership (§9): late-join candidates, the preemption
        # notice to poll, retirement posture (a clean goodbye, observable
        # as `retired` after start() returns), and the serving-tier
        # successor announced to readers once retiring.
        self.admit_ranks = list(admit_ranks or [])
        if set(self.admit_ranks) & set(self.cranks):
            raise ValueError(
                f"admit_ranks {sorted(set(self.admit_ranks) & set(self.cranks))}"
                " overlap client_ranks — launch-time members need no admission")
        self._preempt = preempt
        self._preempt_handled = False
        self.retired = False
        self._serve_successor: Optional[int] = None
        self._sc_apply_cache: Dict[Tuple[str, int], Callable] = {}
        self._sc_last_report: Dict[int, Tuple[int, float]] = {}
        self._sc_beat_seq = 0
        # Observability (mpit_tpu.obs): every protocol counter lives in
        # a real registry (the global one when obs is enabled, a private
        # one otherwise — they are load-bearing results either way) and
        # the attribute names below stay readable as properties.  Op
        # processing records spans through the recorder (the null
        # recorder when obs is off: no clock reads).
        self.metrics = registry_or_local()
        self._spans = get_recorder()
        _m, _r = self.metrics, rank
        self._m_grads = _m.counter("mpit_ps_grads_applied_total", rank=_r)
        self._m_served = _m.counter("mpit_ps_params_served_total", rank=_r)
        self._m_dups = _m.counter("mpit_ps_dup_ops_total", rank=_r)
        self._m_stale = _m.counter("mpit_ps_stale_drops_total", rank=_r)
        self._m_hb_seen = _m.counter("mpit_ps_heartbeats_seen_total", rank=_r)
        self._m_rejoins = _m.counter("mpit_ps_rejoins_total", rank=_r)
        self._m_snap_copies = _m.counter(
            "mpit_ps_snapshot_copies_total", rank=_r)
        self._m_snap_hits = _m.counter("mpit_ps_snapshot_hits_total", rank=_r)
        self._m_ckpts = _m.counter("mpit_ps_ckpts_written_total", rank=_r)
        self._m_busy = _m.counter("mpit_ps_busy_replies_total", rank=_r)
        self._m_readers = _m.gauge("mpit_ps_readers", rank=_r)
        self._m_cells = _m.gauge("mpit_ps_cells", rank=_r)
        self._m_diff_full = _m.counter("mpit_ps_diffs_sent_total",
                                       rank=_r, kind="full")
        self._m_diff_delta = _m.counter("mpit_ps_diffs_sent_total",
                                        rank=_r, kind="delta")
        self._m_diff_chunks = _m.counter("mpit_ps_diff_chunks_sent_total",
                                         rank=_r)
        self._m_evictions = _m.counter("mpit_ft_evictions_total", rank=_r)
        self._m_sc_nacks = _m.counter("mpit_shardctl_nacks_sent_total",
                                      rank=_r)
        self._m_sc_busy = _m.counter("mpit_shardctl_busy_replies_total",
                                     rank=_r)
        self._m_sc_out = _m.counter("mpit_shardctl_migrations_total",
                                    rank=_r, direction="out")
        self._m_sc_in = _m.counter("mpit_shardctl_migrations_total",
                                   rank=_r, direction="in")
        self._m_sc_adopt = _m.counter("mpit_shardctl_adoptions_total",
                                      rank=_r)
        self._m_admits = _m.counter("mpit_ps_admits_total", rank=_r)
        self._m_preempt = _m.counter("mpit_ft_preempt_notices_total",
                                     rank=_r)
        self._m_sc_ver = _m.gauge("mpit_shardctl_map_version", rank=_r)
        self._m_sc_owned = _m.gauge("mpit_shardctl_owned_shards", rank=_r)
        # Flight recorder + live introspection (obs/flight, obs/statusd):
        # evictions dump the recent-event ring (the gang just lost a
        # member) and the status provider feeds /status when an endpoint
        # is serving.  Null objects when obs is disabled.
        self._flight = get_flight()
        if obs_enabled():
            register_status_provider(f"server{rank}", self._status_section)
        # Version-counted snapshot cache: _snap_version bumps on every
        # committed write (grad apply / seed / restore); _snap_host is
        # the one device->host copy for that version and _snap_wire the
        # per-codec encoded frame.  Serving allocates a fresh frame per
        # version — an in-flight zero-copy send of the previous version
        # must never see its buffer rewritten.
        self._snap_version = 0
        self._snap_host: Optional[Tuple[int, np.ndarray]] = None
        self._snap_wire: Dict[str, Tuple[int, np.ndarray]] = {}
        # Device-resident data plane (mpit_tpu.dplane): the shard lives
        # in an HbmSlot (donated jitted applies, per-version snapshot +
        # pull caches) and, when published, an in-process DevicePlane
        # serves same-backend clients without touching the wire.
        self._dp_cfg = dplane
        self._hbm: "Optional[_dphbm.HbmSlot]" = None
        self._plane: "Optional[_dpexchange.DevicePlane]" = None
        self._m_dp_ops: Dict[str, Any] = {}
        if device not in ("cpu", "default"):
            raise ValueError(f"device must be 'cpu' or 'default', got {device!r}")
        self._device = None
        if dplane is not None:
            pass  # plane placement wins: slots live on the default backend
        elif device == "cpu":
            try:
                self._device = jax.local_devices(backend="cpu")[0]
            except RuntimeError:
                # Some accelerator plugins (e.g. the axon tunnel) replace
                # the in-process CPU backend entirely.  Fall back to the
                # platform default and say so — on a tunneled platform
                # that means every shard op rides the tunnel.
                self.log.warning(
                    "no CPU jax backend in this process; server shard "
                    "state falls back to the default device (set "
                    "JAX_PLATFORMS=cpu for host-resident serving)"
                )
        # Placement discipline: every jnp array this server creates is
        # built inside _dev_ctx(), so shard + optimizer state live (and
        # the jitted apply runs) on the configured backend.
        self._restored = False
        # Periodic shard checkpointing (the resume flow's producer side).
        self._ckpt_dir = str(ckpt_dir) if ckpt_dir else None
        self._ckpt_interval = float(ckpt_interval)

    # -- live introspection (obs/statusd) ------------------------------------

    def _status_section(self) -> Dict[str, Any]:
        """This server's /status section: shard + snapshot state, the
        per-client lease/negotiation table, shardctl placement, and the
        live task table.  Runs on the statusd thread — plain-attribute
        reads only, never the scheduler."""
        try:
            tasks = [t.name for t in list(self.sched.queue)]
        except RuntimeError:  # deque mutated mid-snapshot; next poll wins
            tasks = ["<scheduler busy>"]
        return {
            "role": "server",
            "rank": self.rank,
            "shard": {"offset": self.offset, "size": self.size},
            "snap_version": self._snap_version,
            "map_version": getattr(self.smap, "version", None),
            "owned_shards": sorted(self._slots),
            "readers": int(self._m_readers.value),
            "cells": {
                str(c): {
                    "state": self.leases.state(c),
                    "sent_version": self._cell_sent.get(c, -1),
                }
                for c in self.cells
            },
            "busy_replies": int(self._m_busy.value),
            "retired": self.retired,
            "retiring_to": self._serve_successor,
            "dplane": (self._hbm.describe()
                       if self._hbm is not None else None),
            "serve_inflight_bytes": self._serve_inflight_bytes,
            "clients": {
                str(c): {
                    "state": self.leases.state(c),
                    "epoch": self.leases.epoch(c),
                    "framed": self._framed.get(c, False),
                    "stale": self._stale_track.get(c, False),
                    "timing": self._timing.get(c, False),
                    "chunk": self._chunk.get(c, 0),
                    "codec": getattr(self._codecs.get(c), "name", None),
                }
                for c in self.cranks
            },
            "tasks": tasks,
        }

    # -- registry-backed counter reads (the pre-obs attribute surface) -------

    @property
    def grads_applied(self) -> int:
        return int(self._m_grads.value)

    @grads_applied.setter
    def grads_applied(self, v: int) -> None:
        self._m_grads.value = int(v)  # checkpoint restore continuity

    @property
    def params_served(self) -> int:
        return int(self._m_served.value)

    @property
    def dup_ops(self) -> int:
        return int(self._m_dups.value)

    @property
    def stale_drops(self) -> int:
        return int(self._m_stale.value)

    @property
    def heartbeats_seen(self) -> int:
        return int(self._m_hb_seen.value)

    @property
    def rejoins(self) -> int:
        return int(self._m_rejoins.value)

    @property
    def snapshot_copies(self) -> int:
        return int(self._m_snap_copies.value)

    @property
    def snapshot_hits(self) -> int:
        return int(self._m_snap_hits.value)

    @property
    def ckpts_written(self) -> int:
        return int(self._m_ckpts.value)

    @property
    def busy_replies(self) -> int:
        """Admission-control rejections issued (serving tier, §8)."""
        return int(self._m_busy.value)

    # -- shardctl reads (tests / observability) ------------------------------

    @property
    def owned_shards(self) -> "List[int]":
        """Shard ids this server currently holds (shardctl mode)."""
        return sorted(self._slots)

    def shard_param(self, sid: int):
        return self._slots[sid].param

    def _dev_ctx(self):
        """Context placing jnp array creation + jit execution on the
        configured backend (no-op for device='default')."""
        if self._device is None:
            import contextlib

            return contextlib.nullcontext()
        return jax.default_device(self._device)

    # -- codec + FT negotiation ---------------------------------------------

    def _negotiate(self, crank: int, payload: bytes) -> "codec_mod.Codec":
        """Parse the INIT announcement (v1/v2/v3) into (offset, size) on
        self, the negotiated codec, and the client's FT posture (epoch +
        framed/heartbeat flags).  Every failure here is loud — a codec
        disagreement must never reach the frame decoders, where it would
        corrupt parameters silently."""
        raw = np.frombuffer(payload, dtype=np.int64)
        epoch, flags = 0, 0
        if raw.size >= 8 and int(raw[0]) == -1:  # INIT v4 (shardctl)
            return self._negotiate_v4(crank, raw)
        if self._sc:
            raise ValueError(
                f"client {crank} announced a legacy INIT on a shardctl "
                "server — a gang is shardctl everywhere or nowhere"
            )
        chunk_elems = 0
        if raw.size == 2:  # legacy 16-byte v1 announcement
            offset, size, wire_id = int(raw[0]), int(raw[1]), 0
        elif raw.size == 3:
            offset, size, wire_id = (int(x) for x in raw)
        elif raw.size == 5:  # INIT v3: [offset, size, codec_id, epoch, flags]
            offset, size, wire_id, epoch, flags = (int(x) for x in raw)
        elif raw.size == 6:  # INIT v5: v3 + [chunk_elems] (FLAG_CHUNKED)
            offset, size, wire_id, epoch, flags, chunk_elems = (
                int(x) for x in raw)
        else:
            raise ValueError(
                f"client {crank} INIT announcement is {len(payload)} bytes; "
                "expected 16 (legacy [offset, size]), 24 "
                "([offset, size, codec_id]), 40 (v3 + [epoch, flags]) or "
                "48 (v5 + [chunk_elems])"
            )
        chunked = bool(flags & FLAG_CHUNKED)
        if chunked != (raw.size == 6):
            raise ValueError(
                f"client {crank} INIT is malformed: FLAG_CHUNKED and the "
                "48-byte v5 announcement (which carries the chunk cut) "
                "must travel together (docs/PROTOCOL.md §12.1)")
        # READ-ONLY attach (serving tier, §8): the posture is a property
        # of the *rank role*, so a reader announcing as a writer (or
        # vice versa) is a misconfiguration, caught here loudly.  The
        # SUBSCRIBE posture (§11) extends it: a replica cell announces
        # FLAG_READONLY | FLAG_SUBSCRIBE and receives the pushed diff
        # stream instead of requesting reads.
        ro = bool(flags & FLAG_READONLY)
        sub = bool(flags & FLAG_SUBSCRIBE)
        if sub and not ro:
            raise ValueError(
                f"rank {crank} announced FLAG_SUBSCRIBE without "
                "FLAG_READONLY — a cell is a read-only role (§11.1)")
        if sub and crank not in self._cell_set:
            raise ValueError(
                f"rank {crank} announced FLAG_SUBSCRIBE but is not in "
                f"this server's cell_ranks {sorted(self._cell_set)}")
        if crank in self._cell_set and not sub:
            raise ValueError(
                f"rank {crank} is a cell rank but announced without "
                "FLAG_SUBSCRIBE — cells attach with the subscribe "
                "posture")
        if ro and not sub and crank not in self._reader_set:
            raise ValueError(
                f"rank {crank} announced FLAG_READONLY but is not in this "
                f"server's reader_ranks {sorted(self._reader_set)}")
        if crank in self._reader_set and not ro:
            raise ValueError(
                f"rank {crank} is a reader rank but announced without "
                "FLAG_READONLY — readers attach with the read-only posture")
        if ro and not (flags & FLAG_FRAMED):
            raise ValueError(
                f"reader {crank} announced FLAG_READONLY without "
                "FLAG_FRAMED — status-framed replies echo the request "
                "identity")
        self._readonly[crank] = ro
        self._subscribe[crank] = sub
        codec = codec_mod.by_wire_id(wire_id)
        if self._codec_pin is not None and codec.name != self._codec_pin:
            raise ValueError(
                f"codec negotiation mismatch: client {crank} announced "
                f"{codec.name!r} but server {self.rank} is pinned to "
                f"{self._codec_pin!r} — align MPIT_PS_CODEC (or the codec "
                "config) across the gang"
            )
        if not codec.identity and np.dtype(self.dtype) != np.float32:
            raise ValueError(
                f"codec {codec.name!r} quantizes float32 shards; server "
                f"{self.rank} holds dtype {np.dtype(self.dtype).name} "
                "(use codec='none' for other dtypes)"
            )
        if self.offset == -1:
            self.offset, self.size = offset, size
            if self._dp_cfg is not None:
                self._hbm = _dphbm.HbmSlot(size, self.rule, self.dtype,
                                           config=self._dp_cfg,
                                           rank=self.rank)
                self.param = self._hbm.param
                self.rule_state = self._hbm.rule_state
            else:
                with self._dev_ctx():
                    self.param = jnp.zeros((size,), dtype=self.dtype)
                    self.rule_state = self.rule.init(self.param)
        else:
            # All clients must agree on this server's shard (reference :87-88).
            assert (self.offset, self.size) == (offset, size), (
                f"client {crank} announced shard ({offset},{size}) but server "
                f"{self.rank} already holds ({self.offset},{self.size})"
            )
        self._framed[crank] = bool(flags & FLAG_FRAMED)
        self._hb[crank] = bool(flags & FLAG_HEARTBEAT)
        # Pipelined streaming (§12): a framed posture — the writer path,
        # plus chunk-framed diff streams for SUBSCRIBE cells (§11.8).
        if chunked:
            if ro and not sub:
                raise ValueError(
                    f"rank {crank} announced FLAG_CHUNKED with the "
                    "READONLY posture — reads are served by the §8 "
                    "dispatcher; chunked streaming is the writer path "
                    "(§12.1) or a chunk-framed subscription (§11.8)")
            if not self._framed[crank]:
                raise ValueError(
                    f"client {crank} announced FLAG_CHUNKED without "
                    "FLAG_FRAMED — chunk retry/dedup rides the framed "
                    "identity (§12.1)")
            if chunk_elems <= 0 or chunk_elems % codec_mod.BLOCK:
                raise ValueError(
                    f"client {crank} announced chunk_elems={chunk_elems}; "
                    f"must be a positive multiple of {codec_mod.BLOCK} "
                    "(the codec block boundary, §12.2)")
            if not sub:
                self._require_splittable_rule(crank)
        self._chunk[crank] = chunk_elems if chunked else 0
        # Staleness telemetry only rides the framed wire: the version
        # word extends the [epoch, seq] header, so a FLAG_STALENESS
        # without FLAG_FRAMED negotiates off (nothing to extend).
        # Readers negotiate both extensions off: their replies use the
        # §8 status header, which carries the version in its own word.
        # Chunked pairs negotiate it off too — the chunked PARAM reply
        # header carries the version in its own word (§12.3).
        self._stale_track[crank] = (self._framed[crank] and not ro
                                    and not chunked
                                    and bool(flags & FLAG_STALENESS))
        # Same rule for the timing extension: no frame, no stamp slot.
        self._timing[crank] = (self._framed[crank] and not ro
                               and bool(flags & FLAG_TIMING))
        self.leases.arm(crank, epoch, heartbeats=self._hb[crank])
        return codec

    def _require_splittable_rule(self, crank: int) -> None:
        """Chunked streaming applies chunk *k* before chunk *k+1* has
        arrived, which is only bitwise-equal to the whole-shard apply
        when the rule is element-wise over (param, grad, state) — i.e.
        every state leaf is param-shaped (or the state is empty).  A
        scalar leaf (Adam's step counter ``t``) would advance once per
        chunk instead of once per op; refuse loudly at negotiation
        rather than corrupt the math quietly (§12.5)."""
        state = (self._hbm.rule_state if self._hbm is not None
                 else self.rule_state)
        bad = sorted(k for k, v in (state or {}).items()
                     if tuple(np.shape(v)) != (self.size,))
        if bad:
            raise ValueError(
                f"client {crank} announced FLAG_CHUNKED but this "
                f"server's rule carries non-element-wise state leaves "
                f"{bad} (e.g. a scalar step counter) — per-chunk apply "
                "would not be bitwise-equal to the whole-shard apply. "
                "Use a splittable rule (add/rmsprop/adadelta) or turn "
                "chunking off (docs/PROTOCOL.md §12.5)")
        if self._hbm is None and self.rule_state:
            # The chunk applies DONATE param + state (in-place slice
            # updates; §12.3), and rule inits may alias several leaves
            # to one zeros buffer (rmsprop) — donating one buffer
            # twice is an XLA error.  Break the aliasing now (the
            # dplane slot does the same at construction).
            self.rule_state = _dphbm.dedupe_state(self.rule_state)

    def _negotiate_v4(self, crank: int, raw: np.ndarray) -> "codec_mod.Codec":
        """INIT v4: codec + FT posture + the versioned shard map.  The
        map replaces the per-pair (offset, size); owned shards become
        slots.  Shardctl implies framing — re-routable ops need the
        retry/dedup identity under them."""
        if self.readers or self.cells:
            raise ValueError(
                "the serving tier (reader_ranks / cell_ranks) and "
                "shardctl are mutually exclusive for now — readers and "
                "cells address a static shard cut")
        codec_id, epoch, flags, smap = _scwire.parse_init_v4(raw)
        if not (flags & FLAG_FRAMED):
            raise ValueError(
                f"client {crank} announced shardctl without FLAG_FRAMED — "
                "shardctl ops ride the framed retry machinery"
            )
        if self.offset != -1:
            raise ValueError(
                f"client {crank} announced shardctl but server {self.rank} "
                "already holds a legacy (offset, size) registration"
            )
        codec = codec_mod.by_wire_id(codec_id)
        if self._codec_pin is not None and codec.name != self._codec_pin:
            raise ValueError(
                f"codec negotiation mismatch: client {crank} announced "
                f"{codec.name!r} but server {self.rank} is pinned to "
                f"{self._codec_pin!r} — align MPIT_PS_CODEC (or the codec "
                "config) across the gang"
            )
        if not codec.identity and np.dtype(self.dtype) != np.float32:
            raise ValueError(
                f"codec {codec.name!r} quantizes float32 shards; server "
                f"{self.rank} holds dtype {np.dtype(self.dtype).name} "
                "(use codec='none' for other dtypes)"
            )
        self._sc = True
        self._sc_install_map(smap)
        # Slot creation is a *boot-time* act (the version-0 cut, filled
        # by the seeder's pushes).  Any later map — a late client's
        # stale v0 announce after migrations, a greeting that carries a
        # newer map, anything a joiner sees — must never conjure a
        # zeroed slot: mid-run slots only ever arrive through
        # ACQUIRE/ADOPT with their real state (§9.1).
        if (not self._sc_join and self.smap is not None
                and self.smap.version == 0):
            for e in smap.shards_of(self.rank):
                if e.shard_id not in self._slots:
                    self._sc_make_slot(e.shard_id, e.shard)
        self._framed[crank] = True
        self._hb[crank] = bool(flags & FLAG_HEARTBEAT)
        # The 32-byte shard-addressed header has no version slot; the
        # staleness and timing extensions negotiate off under shardctl
        # (§6.6, §6.7).
        self._stale_track[crank] = False
        self._timing[crank] = False
        self.leases.arm(crank, epoch, heartbeats=self._hb[crank])
        return codec

    def _sc_install_map(self, smap: ShardMap) -> None:
        if self.smap is None or smap.version > self.smap.version:
            self.smap = smap
            self._m_sc_ver.set(smap.version)

    def _sc_make_slot(self, sid: int, shard) -> ShardSlot:
        slot = ShardSlot(sid, shard.offset, shard.size)
        slot.param = self._place_param(np.zeros(shard.size, self.dtype))
        slot.rule_state = self._init_state(slot.param)
        self._slots[sid] = slot
        self._m_sc_owned.set(len(self._slots))
        return slot

    def _place_param(self, arr):
        """Place one flat param vector on this server's backend: the
        dplane placement (mesh-sharded HBM) when configured, else the
        legacy device context.  Rule state built from the result
        inherits the placement (zeros_like preserves sharding).
        Always re-owned on device (dplane.hbm.device_copy): slot
        params feed donated applies under dplane, and a numpy-aliased
        buffer there is a use-after-free."""
        if self._dp_cfg is not None:
            return _dphbm.device_copy(_dphbm.place_flat(arr, self._dp_cfg))
        with self._dev_ctx():
            return _dphbm.device_copy(jnp.asarray(arr))

    def _place_state(self, state):
        """Place a restored rule-state dict next to its param."""
        if self._dp_cfg is not None:
            return _dphbm.place_state(state, self._dp_cfg)
        with self._dev_ctx():
            return {k: jnp.asarray(v) for k, v in state.items()}

    def _init_state(self, param):
        """Fresh rule state for ``param``.  Donated applies (dplane)
        need the aliased zeros_like leaves some rules share broken
        apart — donating one buffer twice is an XLA error."""
        state = self.rule.init(param)
        if self._dp_cfg is not None and self._dp_cfg.donate:
            state = _dphbm.dedupe_state(state)
        return state

    def _hdr_for(self, crank: int) -> int:
        """Header size of this client's data frames (GRAD/PARAM_PUSH)."""
        if not self._framed.get(crank):
            return 0
        return hdr_bytes(self._stale_track.get(crank, False),
                         self._timing.get(crank, False))

    def _reply_hdr_for(self, crank: int) -> int:
        """Header size of PARAM replies to this client (the timing tail
        makes replies wider than data frames)."""
        if not self._framed.get(crank):
            return 0
        return reply_hdr_bytes(self._stale_track.get(crank, False),
                               self._timing.get(crank, False))

    def _stale_hist(self, crank: int):
        """The per-client staleness histogram, cached (one get-or-create
        per client lifetime, plain attribute updates per observe)."""
        hist = self._stale_hists.get(crank)
        if hist is None:
            hist = self.metrics.histogram(
                "mpit_ps_grad_staleness", rank=self.rank, client=crank)
            self._stale_hists[crank] = hist
        return hist

    def _alloc_client(self, crank: int, codec: "codec_mod.Codec") -> None:
        """(Re)allocate every per-client staging buffer for the client's
        negotiated codec + framing — initial INIT and rejoin both land
        here, so a rejoining incarnation may change codec freely."""
        if self._readonly.get(crank):
            # Readers cost a request header, not a shard: no gradient
            # or push staging, no ack buffers — the read replies are
            # fresh 32-byte headers plus zero-copy views of the shared
            # snapshot cache.
            self._codecs[crank] = codec
            self._req_buf[crank] = np.zeros(2, np.int64)
            if self._hb.get(crank):
                self._hb_buf[crank] = np.zeros(2, np.int64)
            return
        if self._sc:
            # Shardctl frames are shard-addressed and variable-size per
            # shard, so the data paths receive by allocation — the only
            # fixed-size staging is the 32-byte PARAM_REQ header.
            self._codecs[crank] = codec
            self._req_buf[crank] = np.zeros(4, np.int64)
            if self._hb.get(crank):
                self._hb_buf[crank] = np.zeros(2, np.int64)
            return
        if self._chunk.get(crank):
            # Streamed pairs receive fixed-size chunk frames into
            # per-service staging (GRAD and PARAM_PUSH run concurrently
            # — one buffer each); assembly/serve staging is lazy.
            timing = self._timing.get(crank, False)
            stride = self._chunk_stride_for(crank, codec)
            self._codecs[crank] = codec
            for store in (self._grad_views, self._grad_data,
                          self.grad_bufs, self._push_bufs,
                          self._push_host, self._param_send,
                          self._chunk_asm):
                store.pop(crank, None)
            self._chunk_rx[crank] = np.zeros(stride, np.uint8)
            self._chunk_rx_push[crank] = np.zeros(stride, np.uint8)
            self._ack_send[crank] = np.zeros(
                CHUNK_ACK_TIMING_WORDS if timing else CHUNK_ACK_WORDS,
                np.int64)
            self._req_buf[crank] = np.zeros(3 if timing else 2, np.int64)
            if self._hb.get(crank):
                self._hb_buf[crank] = np.zeros(3 if timing else 2, np.int64)
            return
        hdr = self._hdr_for(crank)
        self._codecs[crank] = codec
        self._grad_views.pop(crank, None)
        self._grad_data.pop(crank, None)
        self._push_bufs.pop(crank, None)
        self._push_host.pop(crank, None)
        self._param_send.pop(crank, None)
        self._chunk_rx.pop(crank, None)
        self._chunk_rx_push.pop(crank, None)
        self._chunk_asm.pop(crank, None)
        if codec.identity:
            buf = np.zeros(hdr + self.size * np.dtype(self.dtype).itemsize,
                           np.uint8)
            self.grad_bufs[crank] = buf
            self._grad_data[crank] = buf[hdr:].view(self.dtype)
        else:
            buf = np.zeros(hdr + codec.wire_nbytes(self.size), np.uint8)
            self.grad_bufs[crank] = buf
            self._grad_views[crank] = codec.split_wire(buf[hdr:], self.size)
        timing = self._timing.get(crank, False)
        if hdr:
            self._ack_send[crank] = np.zeros(
                ACK_TIMING_WORDS if timing else 2, np.int64)
            self._req_buf[crank] = np.zeros(3 if timing else 2, np.int64)
        if self._hb.get(crank):
            self._hb_buf[crank] = np.zeros(3 if timing else 2, np.int64)

    def _release_client(self, crank: int) -> None:
        """Drop an evicted client's staging (its shard registration's
        per-client footprint); the shard itself is shared state."""
        for store in (self.grad_bufs, self._grad_views, self._grad_data,
                      self._push_bufs, self._push_host, self._param_send,
                      self._codecs, self._ack_send, self._req_buf,
                      self._hb_buf, self._chunk_rx, self._chunk_rx_push,
                      self._chunk_asm):
            store.pop(crank, None)

    def _apply_for(self, codec: "codec_mod.Codec") -> Callable:
        """The jitted shard update for one codec: frame decode fused with
        ``rule.apply`` into a single XLA program (one call per grad, same
        as the fp32 path)."""
        fn = self._apply_cache.get(codec.name)
        if fn is None:
            rule_apply = self.rule.apply
            if codec.identity:
                fn = jax.jit(rule_apply)
            else:
                size = self.size

                def _decode_apply(param, parts, state):
                    return rule_apply(param, codec.decode_parts(parts, size), state)

                fn = jax.jit(_decode_apply)
            self._apply_cache[codec.name] = fn
        return fn

    def _sc_apply_for(self, codec: "codec_mod.Codec", size: int) -> Callable:
        """The jitted decode+apply for one (codec, shard size) — the
        per-slot analog of :meth:`_apply_for` (frame layouts are a pure
        function of (codec, n), so the cache key carries both)."""
        key = (codec.name, size)
        fn = self._sc_apply_cache.get(key)
        if fn is None:
            rule_apply = self.rule.apply
            # Device-resident slots (dplane) donate param + rule state:
            # the update consumes its HBM footprint in place instead of
            # reallocating it (the MT-J303 contract, load-bearing here).
            donate = ((0, 2) if self._dp_cfg is not None
                      and self._dp_cfg.donate else ())
            if codec.identity:
                fn = jax.jit(rule_apply, donate_argnums=donate)
            else:
                def _decode_apply(param, parts, state):
                    return rule_apply(param, codec.decode_parts(parts, size),
                                      state)

                fn = jax.jit(_decode_apply, donate_argnums=donate)
            self._sc_apply_cache[key] = fn
        return fn

    def _push_staging(self, crank: int) -> np.ndarray:
        """Lazily-allocated PARAM_PUSH recv staging for one client, sized
        to its codec's wire format plus the FT header when framed (cold
        path: seeding / single mode)."""
        buf = self._push_bufs.get(crank)
        if buf is None:
            codec = self._codecs[crank]
            hdr = self._hdr_for(crank)
            if codec.identity and not hdr:
                buf = np.zeros((self.size,), dtype=self.dtype)
            elif codec.identity:
                buf = np.zeros(hdr + self.size * np.dtype(self.dtype).itemsize,
                               np.uint8)
            else:
                buf = np.zeros(hdr + codec.wire_nbytes(self.size), np.uint8)
                self._push_host[crank] = np.zeros((self.size,), np.float32)
            self._push_bufs[crank] = buf
        return buf

    def _committed(self) -> None:
        """A new shard version exists (grad applied / params seeded).
        With a device-resident slot the slot's counter is authoritative
        (device-exchange applies bump it too); mirror it here so the
        wire snapshot cache keys on the same stream."""
        if self._hbm is not None:
            self._snap_version = self._hbm.version
        else:
            self._snap_version += 1

    def _snapshot_wire(self, codec: "codec_mod.Codec") -> np.ndarray:
        """The current version's PARAM frame for ``codec``, cached: N
        clients reading one committed version share one device->host
        copy and one encode.  Runs between scheduler yields, so version
        read + copy + encode are atomic w.r.t. grad applies."""
        version = self._snap_version
        cached = self._snap_wire.get(codec.name)
        if cached is not None and cached[0] == version:
            self._m_snap_hits.inc()
            return cached[1]
        if self._snap_host is None or self._snap_host[0] != version:
            # Serve-latest-committed: np.asarray snapshots the current
            # immutable device array (the one device->host copy).  A
            # device-resident slot shares its own per-version d2h cache
            # here, so wire reads, checkpoints and the device exchange
            # all draw from the same single copy.
            host = (self._hbm.snapshot_host() if self._hbm is not None
                    else np.asarray(self.param))
            if self._hbm is None and not host.flags.owndata \
                    and any(self._chunk.values()):
                # Chunked clients (§12): their donated per-chunk
                # applies update the param in place, which jax rightly
                # declines while a zero-copy snapshot view pins the
                # buffer — and a declined donation re-copies the WHOLE
                # shard on the next chunk.  Materialize the snapshot
                # instead: one extra sweep per committed version buys
                # in-place applies for every chunk after it.
                host = np.array(host)
            self._snap_host = (version, host)
            self._m_snap_copies.inc()
        host = self._snap_host[1]
        if codec.identity:
            wire = host
        else:
            # Through the pool seam's synchronous entry: this helper is
            # part of the declared 'ps-read-path-helpers' no-yield
            # window, so the encode runs inline (never queued — a pool
            # wait here would block the scheduler mid-atomic-section,
            # lint rule MT-C204).  The kernel itself is the GIL-free
            # native one when available.
            wire = np.empty(codec.wire_nbytes(self.size), np.uint8)
            comm_pool.get_pool().encode_sync(codec, host, wire)
        self._snap_wire[codec.name] = (version, wire)
        return wire

    # -- FT service plumbing -------------------------------------------------

    def _svc_abort(self, crank: int, gen: int) -> Callable[[], bool]:
        """Abort predicate for one service generation: fire when the
        client left (evicted/stopped) or a newer incarnation's services
        superseded this generation."""
        return lambda: self.leases.gone(crank) or self._gen[crank] != gen

    def _svc(self, crank: int, gen: int, fn: Callable, *args, **kw):
        """Run one service generator while tracking per-client service
        liveness, so a rejoin can wait for the old generation to clear
        before respawning (two generations recv'ing one channel would
        scramble the seq stream)."""
        self._svc_live[crank] += 1
        try:
            yield from fn(crank, *args, gen=gen, **kw)
        finally:
            self._svc_live[crank] -= 1

    def _send_ack(self, crank: int, tag: int, epoch: int, seq: int, gen: int,
                  t_tx: int = 0, t_recv: int = 0):
        buf = self._ack_send[crank]
        buf[0], buf[1] = epoch, seq
        if self._timing.get(crank):
            # FLAG_TIMING tail: the echoed client send stamp, this
            # frame's receive stamp, and the ack-send stamp taken now —
            # one complete NTP exchange per ack (§6.7).
            buf[2], buf[3], buf[4] = t_tx, t_recv, obs_clock.wall_us()
        yield from aio_send(self.transport, buf, crank, tag, live=self.live,
                            abort=self._svc_abort(crank, gen))

    # -- pipelined streaming services (FLAG_CHUNKED, PROTOCOL.md §12) --------

    def _chunk_body_for(self, codec: "codec_mod.Codec", elems: int) -> int:
        """Logical body bytes of a chunk covering ``elems`` elements."""
        if codec.identity:
            return elems * np.dtype(self.dtype).itemsize
        return codec.wire_nbytes(elems)

    def _chunk_stride_for(self, crank: int,
                          codec: "Optional[codec_mod.Codec]" = None) -> int:
        """The uniform chunk data-frame size for one client (§12.2)."""
        codec = codec if codec is not None else self._codecs[crank]
        full = min(self._chunk[crank], self.size)
        return chunk_stride(chunk_hdr_bytes(self._timing.get(crank, False)),
                            self._chunk_body_for(codec, full))

    def _send_chunk_ack(self, crank: int, tag: int, epoch: int, seq: int,
                        idx: int, gen: int, t_tx: int = 0, t_recv: int = 0):
        """One per-chunk ack: [epoch, seq, chunk_idx] (+ the timing
        tail) — the unit the client's resend-missing-chunks loop keys
        on."""
        buf = self._ack_send[crank]
        buf[0], buf[1], buf[2] = epoch, seq, idx
        if self._timing.get(crank):
            buf[3], buf[4], buf[5] = t_tx, t_recv, obs_clock.wall_us()
        yield from aio_send(self.transport, buf, crank, tag, live=self.live,
                            abort=self._svc_abort(crank, gen))

    def _chunk_apply_for(self, codec: "Optional[codec_mod.Codec]",
                         csize: int) -> Callable:
        """The jitted per-chunk decode+apply for the host-resident
        shard — element-wise slice math, one XLA call per chunk,
        cached per (codec, chunk size) with ``lo`` traced.  Param and
        state are DONATED: XLA then updates the slice in place (38x
        measured over the reallocating program at 64 MB/16 chunks —
        without donation every chunk apply copies the WHOLE shard, so
        a K-chunk op costs O(K·size) instead of O(size)).  Donation
        on the host backend is best-effort and numerics-neutral: jax
        declines it while a snapshot view pins the buffer, which is
        exactly the safety the version-keyed snapshot cache needs."""
        key = (codec.name if codec is not None else None, csize)
        fn = self._chunk_apply_cache.get(key)
        if fn is None:
            rule_apply = self.rule.apply

            def _chunk_apply(param, payload, state, lo):
                g = (payload if codec is None or codec.identity
                     else codec.decode_parts(payload, csize))
                psl = jax.lax.dynamic_slice(param, (lo,), (csize,))
                ssl = {k: jax.lax.dynamic_slice(v, (lo,), (csize,))
                       for k, v in state.items()}
                pn, sn = rule_apply(psl, g, ssl)
                return (jax.lax.dynamic_update_slice(param, pn, (lo,)),
                        {k: jax.lax.dynamic_update_slice(state[k], sn[k],
                                                         (lo,))
                         for k in state})

            fn = jax.jit(_chunk_apply, donate_argnums=(0, 2))
            self._chunk_apply_cache[key] = fn
        return fn

    def _chunk_fused_ok(self) -> bool:
        """Whether the per-chunk apply may fuse the codec decode into
        the same XLA call as the rule (§12.5).  XLA contracts a decode
        multiply feeding the apply into an fma — a single rounding —
        but only when the decode is one piece; the whole-shard program
        concatenates (and double-rounds) whenever the shard has a
        partial trailing block.  Bitwise equality to the unchunked
        apply therefore requires matching its rounding: fuse when the
        full-shard decode is concat-free, otherwise decode the chunk
        host-side (bit-identical to the host oracle) and apply the
        materialized f32 — exactly the two-rounding sequence the
        concatenated program produces."""
        return self.size % codec_mod.BLOCK == 0 \
            or self.size <= codec_mod.BLOCK

    def _chunk_decoded(self, crank: int, codec: "codec_mod.Codec",
                       body: np.ndarray, csize: int) -> np.ndarray:
        """Host-decode one chunk into a FRESH f32 buffer (the non-fused
        rounding path of :meth:`_chunk_fused_ok`).  Fresh per chunk on
        purpose — see :meth:`_chunk_owned`: jax aliases aligned host
        arrays, so a reused scratch would race the async apply."""
        out = np.empty(csize, np.float32)
        codec.decode_into(body, out)
        return out

    @staticmethod
    def _chunk_owned(view: np.ndarray) -> np.ndarray:
        """An *owned* copy of a chunk-receive view for handing to jax.
        Chunk frames arrive back-to-back into one reused staging buffer
        — unlike whole-frame ops, there is no ack round trip between a
        chunk's dispatch and the next chunk's receive, so jax's own
        (asynchronous) host transfer can still be reading the staging
        when the next chunk lands.  Copying synchronously here and
        letting jax zero-copy-alias the owned result costs the same
        one sweep the internal transfer would have, with no race."""
        return np.array(view)

    def _apply_chunk(self, crank: int, codec: "codec_mod.Codec",
                     body: np.ndarray, lo: int, hi: int,
                     commit: bool) -> None:
        """Decode+apply one GRAD chunk — fused into one XLA call when
        that matches the unchunked rounding (:meth:`_chunk_fused_ok`);
        the version commits once per op (on the final chunk), so the
        snapshot cache and diff stream keep op-granular versions."""
        csize = hi - lo
        fused = codec.identity or self._chunk_fused_ok()
        if self._hbm is not None:
            if codec.identity:
                payload: Any = self._chunk_owned(body.view(self.dtype))
                self._hbm.apply_wire_chunk(codec, payload, lo, csize,
                                           commit=commit)
            elif fused:
                self._hbm.apply_wire_chunk(
                    codec,
                    [self._chunk_owned(v)
                     for v in codec.split_wire(body, csize)],
                    lo, csize, commit=commit)
            else:
                self._hbm.apply_wire_chunk(
                    None, self._chunk_decoded(crank, codec, body, csize),
                    lo, csize, commit=commit)
            self.param = self._hbm.param
            self.rule_state = self._hbm.rule_state
            return
        with self._dev_ctx():
            if codec.identity:
                grad_in: Any = jnp.asarray(
                    self._chunk_owned(body.view(self.dtype)))
                apply_fn = self._chunk_apply_for(codec, csize)
            elif fused:
                grad_in = [jnp.asarray(self._chunk_owned(v))
                           for v in codec.split_wire(body, csize)]
                apply_fn = self._chunk_apply_for(codec, csize)
            else:
                grad_in = jnp.asarray(
                    self._chunk_decoded(crank, codec, body, csize))
                apply_fn = self._chunk_apply_for(None, csize)
            self.param, self.rule_state = apply_fn(
                self.param, grad_in, self.rule_state, np.int32(lo))

    def _recv_grad_chunked(self, crank: int, gen: int = 0):
        """The streamed GRAD service: each chunk frame is admitted per
        (op, chunk), applied the moment it lands — while later chunks
        are still on the wire — and acked individually.  The op commits
        (version bump, counters) on the admission that completed it;
        duplicate chunks re-ack without a second apply, so the client's
        encode-once staging keeps int8 error feedback exact under any
        retry pattern."""
        codec = self._codecs.get(crank)
        if codec is None:
            return
        timing = self._timing.get(crank, False)
        chdr = chunk_hdr_bytes(timing)
        rxbuf = self._chunk_rx[crank]
        spans_ = chunk_spans(self.size, self._chunk[crank])
        cur: "Optional[Tuple[int, int]]" = None
        span = None
        while self.live.on:
            got = yield from aio_recv(
                self.transport, crank, tags.GRAD, live=self.live,
                out=rxbuf, abort=self._svc_abort(crank, gen),
            )
            if got is None:
                if span is not None:
                    span.end("aborted")
                return
            epoch, seq, idx, cnt = unpack_chunk_header(rxbuf)
            t_tx = t_recv = 0
            if timing:
                t_recv = obs_clock.wall_us()
                t_tx = unpack_tx_stamp(rxbuf, chdr)
            self.leases.renew(crank, epoch)
            if not (0 <= idx < len(spans_)) or cnt != len(spans_):
                raise ValueError(
                    f"chunked GRAD from client {crank} addresses chunk "
                    f"{idx}/{cnt} but this shard cuts into "
                    f"{len(spans_)} chunks — chunk layouts diverged "
                    "(INIT v5 carries the cut; §12.2)")
            verdict, done = self.dedup.admit_chunk(
                crank, tags.GRAD, epoch, seq, idx, cnt)
            if verdict == STALE:
                self._m_stale.inc()
                continue
            if verdict == DUP:
                self._m_dups.inc()
                yield from self._send_chunk_ack(
                    crank, tags.GRAD_ACK, epoch, seq, idx, gen,
                    t_tx=t_tx, t_recv=t_recv)
                continue
            if cur != (epoch, seq):
                if span is not None:
                    # The client abandoned an op mid-stream (teardown
                    # races only — the pump never overlaps ops).
                    span.end("aborted")
                cur = (epoch, seq)
                span = self._spans.op("GRAD", peer=crank, side="server",
                                      rank=self.rank)
                span.note(epoch=epoch, seq=seq, chunks=cnt)
            lo, hi = spans_[idx]
            span.mark("apply")
            body = rxbuf[chdr: chdr + self._chunk_body_for(codec, hi - lo)]
            self._apply_chunk(crank, codec, body, lo, hi, commit=done)
            if done:
                self._m_grads.inc()
                self._committed()
            if not self.live.on:
                span.end("aborted")
                span, cur = None, None
                continue
            span.mark("ack")
            yield from self._send_chunk_ack(
                crank, tags.GRAD_ACK, epoch, seq, idx, gen,
                t_tx=t_tx, t_recv=t_recv)
            if done:
                span.end("applied")
                span, cur = None, None

    def _serve_param_chunks(self, crank: int, codec: "codec_mod.Codec",
                            epoch: int, seq: int, req, t_recv: int,
                            gen: int, span):
        """Answer one chunked PARAM read: cut the shared snapshot
        cache's full frame into K independent chunk frames — every one
        stamped with the snapshot version — and post each without
        waiting, so the gather of chunk k+1 overlaps the wire time of
        chunk k.  The staging is per-client; the sends are awaited
        before returning so the next request cannot rewrite frames
        still in flight."""
        timing = self._timing.get(crank, False)
        chdr = chunk_reply_hdr_bytes(timing)
        spans_ = chunk_spans(self.size, self._chunk[crank])
        full = min(self._chunk[crank], self.size)
        stride = chunk_stride(chdr, self._chunk_body_for(codec, full))
        span.mark("snapshot")
        wire = self._snapshot_wire(codec)
        wire_u8 = wire.view(np.uint8) if wire.dtype != np.uint8 else wire
        version = self._snap_version
        staging = self._param_send.get(crank)
        if staging is None or len(staging) != stride * len(spans_):
            staging = np.zeros(stride * len(spans_), np.uint8)
            self._param_send[crank] = staging
        itemsize = np.dtype(self.dtype).itemsize
        handles = []
        span.mark("send")
        # Gather jobs are pure: the snapshot wire is immutable for its
        # version (a new version allocates a fresh frame, never rewrites
        # this one — the Job pins it) and each chunk's staging slot is
        # disjoint.  With workers, the gather of chunk k+1 runs on the
        # pool while chunk k is on the wire; serial keeps today's order.
        pool = comm_pool.get_pool()
        jobs: Dict[int, object] = {}
        lookahead = 0 if pool.serial else 1
        for k, (lo, hi) in enumerate(spans_):
            for j in range(k, min(k + 1 + lookahead, len(spans_))):
                if j not in jobs:
                    jlo, jhi = spans_[j]
                    jframe = staging[j * stride: (j + 1) * stride]
                    jobs[j] = pool.submit_gather(
                        codec, wire_u8, self.size, jlo, jhi,
                        jframe[chdr:], itemsize=itemsize)
            frame = staging[k * stride: (k + 1) * stride]
            pack_chunk_reply(frame, epoch, seq, k, len(spans_), version)
            if timing:
                pack_reply_stamps(frame, chdr - TIMING_TAIL_BYTES,
                                  int(req[2]), t_recv, obs_clock.wall_us())
            if not jobs[k].done():
                span.mark("pool_collect")
                while not jobs[k].done():
                    yield EXEC
            if k:
                span.mark("chunk")
            handles.append(self.transport.isend(frame, crank, tags.PARAM))
            yield EXEC
        for handle in handles:
            while not self.transport.test(handle):
                if not self.live.io or self._svc_abort(crank, gen)():
                    self.transport.cancel(handle)
                    span.end("aborted")
                    return
                yield EXEC
        self._m_served.inc()
        span.end("served")

    def _recv_param_chunked(self, crank: int, once: bool = True,
                            warn_unexpected: bool = False, gen: int = 0):
        """The streamed PARAM_PUSH service: chunk frames scatter into a
        full-frame assembly buffer and the shard seeds exactly once,
        when the last chunk lands.  Chunks ack on admission (like GRAD
        — a commit-only ack would deadlock against periodic drop plans,
        which hit the same chunk index on every full resend), but the
        admissions are NOT checkpoint-persisted: the assembly bytes die
        with the process, so a server restarted mid-push answers the
        retried remainder with a fresh partial that can never complete
        and the push fails loudly (RetryExhausted) instead of seeding a
        torn vector (§12.6)."""
        codec = self._codecs.get(crank)
        if codec is None:
            return
        timing = self._timing.get(crank, False)
        chdr = chunk_hdr_bytes(timing)
        rxbuf = self._chunk_rx_push[crank]
        spans_ = chunk_spans(self.size, self._chunk[crank])
        itemsize = np.dtype(self.dtype).itemsize
        pool = comm_pool.get_pool()
        jobs: Dict[int, object] = {}
        while self.live.on:
            got = yield from aio_recv(
                self.transport, crank, tags.PARAM_PUSH, live=self.live,
                out=rxbuf, abort=self._svc_abort(crank, gen),
            )
            if got is None:
                return
            epoch, seq, idx, cnt = unpack_chunk_header(rxbuf)
            t_tx = t_recv = 0
            if timing:
                t_recv = obs_clock.wall_us()
                t_tx = unpack_tx_stamp(rxbuf, chdr)
            self.leases.renew(crank, epoch)
            if not (0 <= idx < len(spans_)) or cnt != len(spans_):
                raise ValueError(
                    f"chunked PARAM_PUSH from client {crank} addresses "
                    f"chunk {idx}/{cnt} but this shard cuts into "
                    f"{len(spans_)} chunks (§12.2)")
            verdict, done = self.dedup.admit_chunk(
                crank, tags.PARAM_PUSH, epoch, seq, idx, cnt)
            if verdict == STALE:
                self._m_stale.inc()
                continue
            if verdict == DUP:
                self._m_dups.inc()
                yield from self._send_chunk_ack(
                    crank, tags.PARAM_PUSH_ACK, epoch, seq, idx, gen,
                    t_tx=t_tx, t_recv=t_recv)
                continue
            asm = self._chunk_asm.get(crank)
            need = self._chunk_body_for(codec, self.size)
            if asm is None or len(asm) != need:
                asm = np.zeros(need, np.uint8)
                self._chunk_asm[crank] = asm
            lo, hi = spans_[idx]
            body = rxbuf[chdr: chdr + self._chunk_body_for(codec, hi - lo)]
            if pool.serial:
                codec_mod.scatter_chunk(codec, asm, self.size, lo, hi, body,
                                        itemsize=itemsize)
            else:
                # ``rxbuf`` is the reused push rx buffer: the next recv
                # overwrites it while a worker reads, so the job's input
                # must be an owned snapshot (discipline
                # 'pool-server-scatter-owned').  A resent chunk under a
                # new (epoch, seq) reuses the same assembly region, so
                # any prior job on this index must land first.
                prior = jobs.pop(idx, None)
                if prior is not None:
                    while not prior.done():
                        yield EXEC
                jobs[idx] = pool.submit_scatter(
                    codec, asm, self.size, lo, hi, np.array(body),
                    itemsize=itemsize)
            if not done:
                yield from self._send_chunk_ack(
                    crank, tags.PARAM_PUSH_ACK, epoch, seq, idx, gen,
                    t_tx=t_tx, t_recv=t_recv)
                continue
            span = self._spans.op("PARAM_PUSH", peer=crank, side="server",
                                  rank=self.rank)
            span.note(epoch=epoch, seq=seq, chunks=cnt)
            if warn_unexpected:
                self.log.warning(
                    "client %d seeded a RESTORED server: checkpointed "
                    "params overwritten (optimizer state kept) — start "
                    "resume clients with seed_servers=False", crank,
                )
            span.mark("apply")
            # Every scatter must have landed before the assembly buffer
            # is read (jobs write disjoint regions; collection order is
            # irrelevant to the bytes).
            for job in jobs.values():
                while not job.done():
                    yield EXEC
            jobs.clear()
            if codec.identity:
                # Owned copy: the assembly buffer is reused by the next
                # push while jax may still alias this seed's bytes
                # (see _chunk_owned).
                host: Any = self._chunk_owned(asm.view(self.dtype))
            else:
                host = np.empty(self.size, np.float32)
                codec.decode_into(asm, host)
            if self._hbm is not None:
                self._hbm.seed(host)
                self.param = self._hbm.param
            else:
                with self._dev_ctx():
                    # device_copy: a numpy-aliased param entering the
                    # donated chunk applies would hand XLA memory it
                    # does not own (dplane.hbm.device_copy docstring).
                    self.param = _dphbm.device_copy(jnp.asarray(host))
            self._committed()
            span.mark("ack")
            yield from self._send_chunk_ack(
                crank, tags.PARAM_PUSH_ACK, epoch, seq, idx, gen,
                t_tx=t_tx, t_recv=t_recv)
            span.end("applied")
            if once:
                return

    # -- service generators (reference pserver.lua coroutines) --------------

    def _recv_init(self, crank: int, gen: int = 0):
        """Receive [offset, size(, codec_id(, epoch, flags))]; negotiate
        codec + FT posture and allocate shard + staging state
        (reference :33-57)."""
        payload = yield from aio_recv(self.transport, crank, tags.INIT,
                                      live=self.live)
        if payload is None:
            return
        codec = self._negotiate(crank, payload)
        self._alloc_client(crank, codec)

    def _init_listener(self, crank: int):
        """Perpetual rejoin listener (phase 3, FT only): a restarted
        incarnation re-announces on INIT; accept it, supersede the old
        generation's services, and respawn against the new epoch.  The
        INIT v3 handshake is the whole rejoin protocol — the client then
        simply pulls current params and resumes."""
        while self.live.on:
            payload = yield from aio_recv(self.transport, crank, tags.INIT,
                                          live=self.live)
            if payload is None:
                return
            codec = self._negotiate(crank, payload)
            self._gen[crank] += 1
            gen = self._gen[crank]
            self.leases.rejoin(crank, self.leases.epoch(crank))
            self.leases.arm(crank, self.leases.epoch(crank),
                            heartbeats=self._hb.get(crank, False))
            self._alloc_client(crank, codec)
            self._m_rejoins.inc()
            # Two generations must never recv one channel concurrently —
            # wait for the superseded loops to abort out.
            while self._svc_live[crank] > 0:
                yield EXEC
            self._spawn_services(crank)
            self.log.info(
                "client %d rejoined (epoch %d, gen %d)",
                crank, self.leases.epoch(crank), gen,
            )

    def _recv_param(self, crank: int, once: bool = True,
                    warn_unexpected: bool = False, gen: int = 0):
        """Whole-shard write from a client: one-shot seeding from the first
        client (reference :92-102) or perpetual in single mode (the
        BiCNN recvparam_always service, BiCNN/pserver.lua:220-232).
        Framed pushes are dedup-admitted: a retried seed is applied once
        and re-acked."""
        if self._chunk.get(crank):
            yield from self._recv_param_chunked(
                crank, once=once, warn_unexpected=warn_unexpected, gen=gen)
            return
        codec = self._codecs.get(crank)
        if codec is None:  # init never completed (stopped before announce)
            return
        framed = self._framed.get(crank, False)
        timing = self._timing.get(crank, False)
        hdr = self._hdr_for(crank)
        staging = self._push_staging(crank)
        while self.live.on:
            got = yield from aio_recv(
                self.transport, crank, tags.PARAM_PUSH,
                live=self.live, out=staging, abort=self._svc_abort(crank, gen),
            )
            if got is None:
                return
            epoch = seq = t_tx = t_recv = 0
            if timing:
                t_recv = obs_clock.wall_us()
                t_tx = unpack_tx_stamp(staging, hdr)
            span = self._spans.op("PARAM_PUSH", peer=crank, side="server",
                                  rank=self.rank)
            if framed:
                epoch, seq = unpack_header(staging)
                span.note(epoch=epoch, seq=seq)
                self.leases.renew(crank, epoch)
                verdict = self.dedup.admit(crank, tags.PARAM_PUSH, epoch, seq)
                if verdict == STALE:
                    self._m_stale.inc()
                    span.end("stale")
                    continue
                if verdict == DUP:
                    self._m_dups.inc()
                    span.mark("ack")
                    yield from self._send_ack(
                        crank, tags.PARAM_PUSH_ACK, epoch, seq, gen,
                        t_tx=t_tx, t_recv=t_recv)
                    span.end("dup")
                    continue
            if warn_unexpected:
                self.log.warning(
                    "client %d seeded a RESTORED server: checkpointed "
                    "params overwritten (optimizer state kept) — start "
                    "resume clients with seed_servers=False", crank,
                )
            span.mark("apply")
            if codec.identity and not hdr:
                host = staging
            elif codec.identity:
                host = staging[hdr:].view(self.dtype)
            else:  # cold path: host decode, then one h2d
                host = self._push_host[crank]
                codec.decode_into(staging[hdr:], host)
            if self._hbm is not None:
                self._hbm.seed(host)
                self.param = self._hbm.param
            else:
                with self._dev_ctx():
                    # device_copy: a chunked sibling client's donated
                    # chunk applies may consume this param — it must
                    # be device-owned, not a staging alias (cold path;
                    # dplane.hbm.device_copy).
                    self.param = _dphbm.device_copy(jnp.asarray(host))
            self._committed()
            span.mark("ack")
            if framed:
                yield from self._send_ack(
                    crank, tags.PARAM_PUSH_ACK, epoch, seq, gen,
                    t_tx=t_tx, t_recv=t_recv)
            else:
                yield from aio_send(
                    self.transport, tags.EMPTY, crank, tags.PARAM_PUSH_ACK,
                    live=self.live, abort=self._svc_abort(crank, gen),
                )
            span.end("applied")
            if once:
                return

    def _send_param(self, crank: int, gen: int = 0):
        """Loop: await the read request, send the current version's
        encoded snapshot (reference :59-72).  Framed requests carry
        [epoch, seq]; the reply echoes it so the client can discard
        snapshots answering an earlier (retried) request.  Reads are
        idempotent — duplicates are served, never dedup'd."""
        codec = self._codecs.get(crank)
        if codec is None:  # init never completed (stopped before announce)
            return
        framed = self._framed.get(crank, False)
        timing = self._timing.get(crank, False)
        while self.live.on:
            req = self._req_buf.get(crank) if framed else None
            got = yield from aio_recv(
                self.transport, crank, tags.PARAM_REQ, live=self.live,
                out=req, abort=self._svc_abort(crank, gen),
            )
            if got is None:
                return
            if not self.live.io:
                continue
            t_recv = obs_clock.wall_us() if timing else 0
            span = self._spans.op("PARAM", peer=crank, side="server",
                                  rank=self.rank)
            if not framed:
                span.mark("snapshot")
                snapshot = self._snapshot_wire(codec)
                span.mark("send")
                yield from aio_send(
                    self.transport, snapshot, crank, tags.PARAM,
                    live=self.live, abort=self._svc_abort(crank, gen),
                )
                self._m_served.inc()
                span.end("served")
                continue
            epoch, seq = int(req[0]), int(req[1])
            span.note(epoch=epoch, seq=seq)
            if epoch < self.leases.epoch(crank):
                self._m_stale.inc()  # dead incarnation's request
                span.end("stale")
                continue
            self.leases.renew(crank, epoch)
            if self._chunk.get(crank):
                span.note(chunks=len(chunk_spans(self.size,
                                                 self._chunk[crank])))
                yield from self._serve_param_chunks(
                    crank, codec, epoch, seq, req, t_recv, gen, span)
                continue
            span.mark("snapshot")
            hdr = self._reply_hdr_for(crank)
            wire = self._snapshot_wire(codec)
            wire_u8 = wire.view(np.uint8) if wire.dtype != np.uint8 else wire
            reply = self._param_send.get(crank)
            if reply is None or len(reply) != hdr + len(wire_u8):
                reply = np.zeros(hdr + len(wire_u8), np.uint8)
                self._param_send[crank] = reply
            reply[:HDR_BYTES].view(np.int64)[:] = (epoch, seq)
            if self._stale_track.get(crank):
                # Stamp the served snapshot's version — the basis the
                # client's next gradient will echo (staleness telemetry).
                pack_version(reply, self._snap_version)
            reply[hdr:] = wire_u8
            span.mark("send")
            if timing:
                # The reply's timing tail (§6.7): echoed request stamp,
                # the request's receive stamp, and the send stamp now.
                pack_reply_stamps(reply, hdr - TIMING_TAIL_BYTES,
                                  int(req[2]), t_recv, obs_clock.wall_us())
            yield from aio_send(
                self.transport, reply, crank, tags.PARAM, live=self.live,
                abort=self._svc_abort(crank, gen),
            )
            self._m_served.inc()
            span.end("served")

    # -- serving tier: READ-ONLY readers + admission control (§8) ------------

    def _update_reader_gauge(self) -> None:
        live = sum(1 for r in self.readers
                   if r in self._codecs and not self.leases.gone(r))
        self._m_readers.set(live)

    def retire_serving(self, successor: int) -> None:
        """Serving-tier retirement (§9.4): from now on every reader
        request is answered ``GOODBYE`` carrying ``successor`` — the
        reader re-attaches there instead of burning its retry budget
        against a disappearing rank.  The redirected reader is marked
        STOPPED here (it will never send this rank another frame), so
        the stop protocol completes without it."""
        if successor == self.rank:
            raise ValueError("a retiring server cannot be its own successor")
        self._serve_successor = int(successor)
        self.log.info("serving tier retiring: readers redirected to %d",
                      successor)

    def _read_gate(self) -> "Optional[Tuple[int, int]]":
        """Admission gate hook for the reader dispatcher: None grants;
        a ``(status, word)`` pair answers the request with that status
        instead (a lagging cell returns ``(BUSY, hint_us)``, §11.4).
        The base server serves the head itself — never gated."""
        return None

    def _serve_ok_header(self, epoch: int, seq: int) -> np.ndarray:
        """The OK reply header for a granted read.  A cell overrides
        this to the 5-word form that also stamps its known head version
        (readers derive their observed lag from it, §11.5)."""
        return _psserve.serve_reply(epoch, seq, _scwire.OK,
                                    self._snap_version)

    # -- multi-cell serving fabric: the diff-stream producer (§11.2) ---------

    def _update_cell_gauge(self) -> None:
        live = sum(1 for c in self.cells
                   if c in self._codecs and not self.leases.gone(c))
        self._m_cells.set(live)

    def _cell_frame(self, crank: int) -> "List[np.ndarray]":
        """The next DIFF message sequence for one subscriber: a DELTA
        against the last version shipped to it when the history still
        holds that frame, else a FULL frame at the head — as ONE
        message, or as chunk messages when the subscription negotiated
        FLAG_CHUNKED (§11.8: a 640 MB resync must not head-of-line-
        block the stream).  Either way the head frame comes out of (and
        is recorded into) the same snapshot cache wire reads share — N
        same-codec cells cost one encode and one XOR per committed
        version, not N."""
        codec = self._codecs[crank]
        head = self._snap_version
        wire = self._snapshot_wire(codec)
        hist = self._cell_hist.get(codec.name)
        if hist is None:
            hist = _cellwire.FrameHistory(keep=self._cell_keep)
            self._cell_hist[codec.name] = hist
        hist.record(head, wire)
        sent = self._cell_sent.get(crank, -1)
        if 0 <= sent < head and hist.has(sent):
            self._m_diff_delta.inc()
            kind, from_v = _cellwire.DIFF_DELTA, sent
            body = hist.delta(sent, head)
        else:
            self._m_diff_full.inc()
            kind, from_v = _cellwire.DIFF_FULL, -1
            body = wire
        chunk_elems = self._chunk.get(crank, 0)
        if chunk_elems:
            msgs = _cellwire.pack_diff_chunks(kind, from_v, head, head,
                                              body, 4 * chunk_elems)
            self._m_diff_chunks.inc(len(msgs))
            return msgs
        return [_cellwire.pack_diff(kind, from_v, head, head, body)]

    def _cell_push(self, crank: int, gen: int, frames: "List[np.ndarray]",
                   push_live: Dict[int, bool]):
        """One in-flight diff push to one cell (FIFO per cell: the next
        frame waits until this one is accepted, so the stream coalesces
        to head under backpressure instead of queueing every version).
        A chunk-framed subscription ships the frame as its message
        sequence on the same FIFO channel — `chunk`-marked per message.
        A cell that dies mid-push costs this task, never the server."""
        span = self._spans.op("DIFF", peer=crank, side="server",
                              rank=self.rank)
        try:
            span.mark("send")
            for i, frame in enumerate(frames):
                if i:
                    span.mark("chunk")
                yield from aio_send(self.transport, frame, crank,
                                    tags.DIFF, live=self.live,
                                    abort=self._svc_abort(crank, gen))
        except (RuntimeError, DeadlineExceeded) as exc:
            self.log.debug("diff to cell %d dropped: %r", crank, exc)
            span.end("aborted")
            return
        finally:
            push_live[crank] = False
        span.end("served")

    def _cell_dispatcher(self):
        """ONE task serves every subscriber cell (the §11 counterpart of
        the reader dispatcher): probes attach/re-attach INITs, STOPs,
        HEARTBEATs (renewing the lease and answering the 3-word head
        echo — head knowledge must never ride the possibly-delayed DIFF
        channel), DIFF_REQ resync requests, and pushes one diff per
        cell whenever the committed version moved past what that cell
        was last shipped."""
        push_live: Dict[int, bool] = {c: False for c in self.cells}
        self._cell_push_live = push_live
        scan = 0
        while self.live.on:
            progressed = False
            slot = scan & 7
            for crank in self.cells:
                attached = crank in self._codecs
                slow_turn = (crank & 7) == slot
                try:
                    if ((not attached or slow_turn)
                            and self.transport.iprobe(crank, tags.INIT)):
                        payload = yield from self._dispatch_recv(
                            crank, tags.INIT)
                        codec = self._negotiate(crank, payload)
                        self._gen[crank] += 1
                        self.leases.rejoin(crank, self.leases.epoch(crank))
                        self.leases.arm(crank, self.leases.epoch(crank),
                                        heartbeats=self._hb.get(crank, False))
                        self._alloc_client(crank, codec)
                        self._cell_sent[crank] = -1  # owes a FULL frame
                        self._update_cell_gauge()
                        attached = True
                        progressed = True
                        self.log.info(
                            "cell %d subscribed (epoch %d, gen %d, "
                            "codec %s)", crank, self.leases.epoch(crank),
                            self._gen[crank], codec.name)
                    if not attached or self.leases.gone(crank):
                        continue
                    gen = self._gen[crank]
                    if slow_turn and self.transport.iprobe(crank, tags.STOP):
                        yield from self._dispatch_recv(crank, tags.STOP)
                        self.leases.stop(crank)
                        self._update_cell_gauge()
                        progressed = True
                        if self.leases.all_done():
                            self.live.stop()
                        continue
                    while self.transport.iprobe(crank, tags.HEARTBEAT):
                        beat = yield from self._dispatch_recv(
                            crank, tags.HEARTBEAT, out=self._hb_buf[crank])
                        if beat is None:
                            break
                        self._m_hb_seen.inc()
                        self.leases.renew(crank, int(beat[0]))
                        # Head echo (§11.3): the staleness bound's
                        # ground truth rides the heartbeat channel.
                        yield from aio_send(
                            self.transport,
                            _cellwire.head_echo(int(beat[0]), int(beat[1]),
                                                self._snap_version),
                            crank, tags.HEARTBEAT_ECHO, live=self.live,
                            abort=self._svc_abort(crank, gen))
                        progressed = True
                    if self.transport.iprobe(crank, tags.DIFF_REQ):
                        req = yield from self._dispatch_recv(
                            crank, tags.DIFF_REQ)
                        if req is not None:
                            epoch, _seq, have = _cellwire.parse_diff_req(req)
                            if epoch >= self.leases.epoch(crank):
                                self.leases.renew(crank, epoch)
                                # Chain broke at the cell: next push is
                                # a FULL frame at head.
                                self._cell_sent[crank] = -1
                                self.log.info(
                                    "cell %d requested resync (has "
                                    "version %d, head %d)", crank, have,
                                    self._snap_version)
                        progressed = True
                    if push_live[crank]:
                        continue  # FIFO per cell: one diff in flight
                    sent = self._cell_sent.get(crank, -1)
                    if self.param is None or self._snap_version <= sent:
                        continue
                    frame = self._cell_frame(crank)
                    push_live[crank] = True
                    self._cell_sent[crank] = self._snap_version
                    self.sched.spawn(
                        self._cell_push(crank, gen, frame, push_live),
                        name=f"cell_diff:{crank}")
                    progressed = True
                except RuntimeError:
                    # Torn connection (fail-loud probe): the cell is
                    # gone without a STOP — its lease evicts it, and a
                    # restarted cell re-attaches via a fresh INIT.
                    continue
            scan += 1
            if progressed:
                yield EXEC
            else:
                if not (yield from aio_sleep(0.002, live=self.live)):
                    return

    def _dispatch_recv(self, crank: int, tag: int, out=None):
        """Receive a message the dispatcher's probe already saw (fully
        assembled, so this completes without waiting on the peer)."""
        handle = self.transport.irecv(crank, tag, out=out)
        while not self.transport.test(handle):
            yield EXEC
        return self.transport.payload(handle)

    def _reader_dispatcher(self):
        """ONE task serves every reader (serving tier, §8).  A
        per-reader service trio would put O(attached readers) perpetual
        tasks on the cooperative scheduler — at 512 readers every
        scheduler pass walks ~1500 parked generators, and per-op
        latency scales with attachment, not load.  Instead this single
        task probes each reader's channels nonblockingly per scan
        (attach/re-attach INIT, STOP, HEARTBEAT, read requests) and
        spawns one bounded *reply task* per granted read: the scheduler
        holds O(in-flight replies) tasks — and in-flight is exactly
        what the admission budget bounds, so admission control is also
        what keeps the scheduler flat under fan-out."""
        reply_live: Dict[int, bool] = {r: False for r in self.readers}
        self._reader_reply_live = reply_live  # introspection/tests
        scan = 0
        while self.live.on:
            progressed = False
            # Rare-event probes (re-attach, STOP, beats) are staggered
            # over 8 scans so a steady-state scan costs ~one probe per
            # reader — the hot path is PARAM_REQ, everything else can
            # tolerate a few scans of latency.
            slot = scan & 7
            for crank in self.readers:
                if reply_live[crank]:
                    # FIFO per reader: one reply (or re-attach gate) at
                    # a time — two in-flight replies to one reader
                    # could interleave their header/body pairs.
                    continue
                attached = crank in self._codecs
                slow_turn = (crank & 7) == slot
                try:
                    if ((not attached or slow_turn)
                            and self.transport.iprobe(crank, tags.INIT)):
                        payload = yield from self._dispatch_recv(
                            crank, tags.INIT)
                        codec = self._negotiate(crank, payload)
                        self._gen[crank] += 1
                        self.leases.rejoin(crank, self.leases.epoch(crank))
                        self.leases.arm(crank, self.leases.epoch(crank),
                                        heartbeats=self._hb.get(crank, False))
                        self._alloc_client(crank, codec)
                        self._update_reader_gauge()
                        attached = True
                        progressed = True
                        self.log.info(
                            "reader %d attached (epoch %d, gen %d)",
                            crank, self.leases.epoch(crank),
                            self._gen[crank])
                    if not attached or self.leases.gone(crank):
                        continue
                    if slow_turn and self.transport.iprobe(crank, tags.STOP):
                        yield from self._dispatch_recv(crank, tags.STOP)
                        self.leases.stop(crank)
                        self._update_reader_gauge()
                        progressed = True
                        if self.leases.all_done():
                            self.live.stop()
                        continue
                    if slow_turn and self._hb.get(crank):
                        while self.transport.iprobe(crank, tags.HEARTBEAT):
                            beat = yield from self._dispatch_recv(
                                crank, tags.HEARTBEAT, out=self._hb_buf[crank])
                            if beat is None:
                                break
                            self._m_hb_seen.inc()
                            self.leases.renew(crank, int(beat[0]))
                    if self.transport.iprobe(crank, tags.PARAM_REQ):
                        yield from self._dispatch_read(crank, reply_live)
                        progressed = True
                except RuntimeError:
                    # Torn connection (the transport's fail-loud probe):
                    # the reader is gone without a STOP — its lease (when
                    # armed) evicts it; a replacement attaches through a
                    # fresh INIT on a revived channel.
                    continue
            scan += 1
            if progressed:
                yield EXEC  # hot: scan again next step
            else:
                # Idle scan: pace the next one — two servers
                # busy-scanning N channels would eat the very core the
                # gang's replies are produced on (the IDLE_USEC lesson).
                if not (yield from aio_sleep(0.002, live=self.live)):
                    return

    def _dispatch_read(self, crank: int, reply_live: Dict[int, bool]):
        """Admit one read request: grant it a reply task, or answer
        BUSY-with-retry-hint when the in-flight budget is spent."""
        codec = self._codecs[crank]
        cfg = self.serve_cfg
        req = yield from self._dispatch_recv(crank, tags.PARAM_REQ,
                                             out=self._req_buf[crank])
        if req is None:
            return
        epoch, seq = int(req[0]), int(req[1])
        span = self._spans.op("PARAM", peer=crank, side="server",
                              rank=self.rank)
        span.note(epoch=epoch, seq=seq, reader=1)
        if epoch < self.leases.epoch(crank):
            self._m_stale.inc()  # dead incarnation's request
            span.end("stale")
            return
        self.leases.renew(crank, epoch)
        gen = self._gen[crank]
        if self._serve_successor is not None:
            # Retiring (§9.4): a goodbye-with-successor, not a grant —
            # and not a silent vanish that costs the reader its budget.
            succ = self._serve_successor
            span.note(successor=succ)
            span.mark("send")
            header = _psserve.serve_reply(epoch, seq, _scwire.GOODBYE, succ)
            reply_live[crank] = True
            self.sched.spawn(
                self._serve_reply(crank, gen, span, header, None, 0,
                                  reply_live),
                name=f"serve_goodbye:{crank}")
            self.leases.stop(crank)
            self._update_reader_gauge()
            return
        # Role-specific admission gate (§11.4): the base server never
        # gates — a cell overrides this hook to shed reads while its
        # installed version trails the head beyond max_lag (BUSY with a
        # catch-up hint), which is what makes the staleness bound
        # *enforced* rather than advisory.
        # Declared atomic section `ps-read-gate-window` (MT-Y801): no
        # scheduler yield between this gate and the stamped reply header
        # — the (version, head) bound in the OK header is only exact
        # because nothing can park the task inside this window.
        gate = self._read_gate()
        if gate is not None:
            status, word = gate
            self._m_busy.inc()
            span.note(hint_us=word)
            span.mark("send")
            header = _psserve.serve_reply(epoch, seq, status, word)
            reply_live[crank] = True
            self.sched.spawn(
                self._serve_reply(crank, gen, span, header, None, 0,
                                  reply_live),
                name=f"serve_gate:{crank}")
            return
        nbytes = (self.size * np.dtype(self.dtype).itemsize
                  if codec.identity else codec.wire_nbytes(self.size))
        # An idle rank always grants (a frame larger than the whole
        # budget must not be rejectable forever); past that, the budget
        # bounds what may queue behind in-flight replies.
        if self._serve_inflight_reads > 0 and (
                self._serve_inflight_bytes + nbytes > cfg.budget_bytes
                or (cfg.budget_reads > 0
                    and self._serve_inflight_reads >= cfg.budget_reads)):
            self._m_busy.inc()
            hint = cfg.hint_us(self._serve_inflight_bytes)
            span.note(hint_us=hint)
            span.mark("send")
            header = _psserve.serve_reply(epoch, seq, _scwire.BUSY, hint)
            reply_live[crank] = True
            self.sched.spawn(
                self._serve_reply(crank, gen, span, header, None, 0,
                                  reply_live),
                name=f"serve_busy:{crank}")
            return
        span.mark("snapshot")
        wire = self._snapshot_wire(codec)
        header = self._serve_ok_header(epoch, seq)
        self._serve_inflight_bytes += nbytes
        self._serve_inflight_reads += 1
        reply_live[crank] = True
        self.sched.spawn(
            self._serve_reply(crank, gen, span, header, wire, nbytes,
                              reply_live),
            name=f"serve_reply:{crank}")

    def _serve_reply(self, crank: int, gen: int, span, header,
                     body, nbytes: int, reply_live: Dict[int, bool]):
        """One granted (or BUSY) reply: the 32-byte status header, then
        — on a grant — the snapshot frame as its own message.  The body
        is a zero-copy view of this version's cached frame, so N
        readers of one version share one device->host copy and one
        encode however many connections are attached.  A reader that
        dies mid-reply costs this task, never the server."""
        span.mark("send")
        try:
            yield from aio_send(self.transport, header, crank, tags.PARAM,
                                live=self.live,
                                abort=self._svc_abort(crank, gen))
            if body is not None:
                yield from aio_send(self.transport, body, crank, tags.PARAM,
                                    live=self.live,
                                    abort=self._svc_abort(crank, gen))
        except (RuntimeError, DeadlineExceeded) as exc:
            # Dead reader mid-reply (transport fail-loud): drop the
            # reply; the lease reaper / re-attach path owns the rank.
            self.log.debug("reply to reader %d dropped: %r", crank, exc)
            span.end("aborted")
            return
        finally:
            if body is not None:
                self._serve_inflight_bytes -= nbytes
                self._serve_inflight_reads -= 1
            reply_live[crank] = False
        if body is not None:
            self._m_served.inc()
            span.end("served")
        else:
            span.end("busy")
        # A goodbye may have marked the last non-terminal rank STOPPED;
        # re-check the stop condition now that the reply is on the wire.
        if self.leases.all_done():
            self.live.stop()

    def _recv_grad(self, crank: int, gen: int = 0):
        """Loop: receive gradient frame, decode+apply the shard rule in
        one jitted call, ack (reference :75-90 — the server hot loop).
        Framed frames are dedup-admitted on (epoch, seq): duplicates are
        re-acked without a second apply — with the client's encode-once
        staging this is what keeps error feedback exact under retries."""
        if self._chunk.get(crank):
            yield from self._recv_grad_chunked(crank, gen=gen)
            return
        codec = self._codecs.get(crank)
        if codec is None:  # init never completed (stopped before announce)
            return
        framed = self._framed.get(crank, False)
        timing = self._timing.get(crank, False)
        hdr = self._hdr_for(crank)
        gbuf = self.grad_bufs[crank]
        parts = self._grad_views.get(crank)
        data = self._grad_data.get(crank)
        apply_fn = self._apply_for(codec)
        while self.live.on:
            got = yield from aio_recv(
                self.transport, crank, tags.GRAD, live=self.live, out=gbuf,
                abort=self._svc_abort(crank, gen),
            )
            if got is None:
                return
            epoch = seq = t_tx = t_recv = 0
            if timing:
                t_recv = obs_clock.wall_us()
                t_tx = unpack_tx_stamp(gbuf, hdr)
            span = self._spans.op("GRAD", peer=crank, side="server",
                                  rank=self.rank)
            if framed:
                epoch, seq = unpack_header(gbuf)
                span.note(epoch=epoch, seq=seq)
                self.leases.renew(crank, epoch)
                verdict = self.dedup.admit(crank, tags.GRAD, epoch, seq)
                if verdict == STALE:
                    self._m_stale.inc()
                    span.end("stale")
                    continue
                if verdict == DUP:
                    self._m_dups.inc()
                    span.mark("ack")
                    yield from self._send_ack(crank, tags.GRAD_ACK,
                                              epoch, seq, gen,
                                              t_tx=t_tx, t_recv=t_recv)
                    span.end("dup")
                    continue
                if self._stale_track.get(crank):
                    # Gradient staleness: the gap between the version the
                    # client computed against (echoed in the header) and
                    # the version this gradient lands on.  Observed once
                    # per *applied* op — dups/stales above never count,
                    # so under a deterministic fault plan the histogram
                    # matches the plan arithmetic exactly.
                    staleness = self._snap_version - unpack_version(gbuf)
                    span.note(staleness=staleness)
                    self._stale_hist(crank).observe(staleness)
            span.mark("apply")
            # The apply's operands are owned copies of the rx views
            # (:meth:`_chunk_owned` — `ps-grad-apply-owned`, MT-D901).
            # The GRAD_ACK below does NOT serialize buffer reuse: the
            # jitted apply only *dispatches* before the ack goes out,
            # and jax zero-copy-aliases aligned host arrays, so the
            # next GRAD landing in ``gbuf`` would race the in-flight
            # execution (visible as wrong applied bytes whenever the
            # backend queue is backed up, e.g. first-call compiles).
            if self._hbm is not None:
                # Device-resident path: the slot's donated fused
                # decode+apply — same math, same operand order as the
                # legacy jit below, so both runs stay bitwise equal.
                self._hbm.apply_wire(
                    codec,
                    self._chunk_owned(data if data is not None else gbuf)
                    if parts is None
                    else [self._chunk_owned(v) for v in parts])
                self.param = self._hbm.param
                self.rule_state = self._hbm.rule_state
            else:
                with self._dev_ctx():
                    if parts is None:
                        grad_in: Any = jnp.asarray(self._chunk_owned(
                            data if data is not None else gbuf))
                    else:
                        grad_in = [jnp.asarray(self._chunk_owned(v))
                                   for v in parts]
                    self.param, self.rule_state = apply_fn(
                        self.param, grad_in, self.rule_state
                    )
            self._m_grads.inc()
            self._committed()
            if not self.live.on:
                span.end("aborted")
                continue
            span.mark("ack")
            if framed:
                yield from self._send_ack(crank, tags.GRAD_ACK, epoch, seq,
                                          gen, t_tx=t_tx, t_recv=t_recv)
            else:
                yield from aio_send(
                    self.transport, tags.EMPTY, crank, tags.GRAD_ACK,
                    live=self.live, abort=self._svc_abort(crank, gen),
                )
            span.end("applied")

    # -- shardctl services: shard-addressed ops over the versioned map -------

    def _sc_verdict(self, sid: int) -> int:
        """Route an op addressing shard ``sid``: OK to serve, NACK_MAP
        when the map says someone else owns it (the reply carries our
        newer map), BUSY while its state is frozen or in flight to us."""
        try:
            owner = self.smap.owner(sid) if self.smap is not None else -1
        except KeyError:
            owner = -1
        if owner != self.rank:
            return _scwire.NACK_MAP
        slot = self._slots.get(sid)
        if slot is None or slot.frozen:
            return _scwire.BUSY
        return _scwire.OK

    def _sc_ops_counter(self, sid: int):
        return self.metrics.counter("mpit_shardctl_shard_ops_total",
                                    rank=self.rank, shard=sid)

    def _sc_busy_timer(self, sid: int):
        """Busy-seconds timer for one slot (clock lives in obs — the
        MT-O4xx contract).  Spans dedup→apply→ack-complete, cooperative
        suspensions included: that *is* the time the shard's service
        occupied, which is what the rebalance policy weighs."""
        return self.metrics.timer("mpit_shardctl_shard_busy_seconds",
                                  rank=self.rank, shard=sid)

    def _sc_recv_grad(self, crank: int, gen: int = 0):
        """Shardctl GRAD loop: alloc-receive the shard-addressed frame,
        route by map, dedup on the *slot's* table (it migrates with the
        shard — at-most-once holds across owners), decode+apply in one
        jitted call, status-ack."""
        codec = self._codecs.get(crank)
        if codec is None:
            return
        while self.live.on:
            raw = yield from aio_recv(
                self.transport, crank, tags.GRAD, live=self.live,
                abort=self._svc_abort(crank, gen),
            )
            if raw is None:
                return
            buf = np.frombuffer(raw, np.uint8)
            epoch, seq, _mapver, sid = _scwire.unpack_sc_header(buf)
            span = self._spans.op("GRAD", peer=crank, side="server",
                                  rank=self.rank)
            span.note(epoch=epoch, seq=seq, shard=sid)
            self.leases.renew(crank, epoch)
            verdict = self._sc_verdict(sid)
            if verdict != _scwire.OK:
                (self._m_sc_nacks if verdict == _scwire.NACK_MAP
                 else self._m_sc_busy).inc()
                span.mark("ack")
                yield from aio_send(
                    self.transport,
                    _scwire.reply_frame(epoch, seq, verdict, sid,
                                        body=self.smap.to_wire()),
                    crank, tags.GRAD_ACK, live=self.live,
                    abort=self._svc_abort(crank, gen),
                )
                span.end("nack" if verdict == _scwire.NACK_MAP else "busy")
                continue
            slot = self._slots[sid]
            with self._sc_busy_timer(sid):
                admitted = slot.dedup.admit(crank, tags.GRAD, epoch, seq)
                if admitted == STALE:
                    self._m_stale.inc()
                    span.end("stale")
                    continue
                if admitted == DUP:
                    self._m_dups.inc()
                    span.mark("ack")
                    yield from aio_send(
                        self.transport,
                        _scwire.reply_frame(epoch, seq, _scwire.OK, sid),
                        crank, tags.GRAD_ACK, live=self.live,
                        abort=self._svc_abort(crank, gen),
                    )
                    span.end("dup")
                    continue
                span.mark("apply")
                body = buf[_scwire.SC_HDR_BYTES:]
                apply_fn = self._sc_apply_for(codec, slot.size)
                with self._dev_ctx():
                    if codec.identity:
                        grad_in: Any = jnp.asarray(body.view(self.dtype))
                    else:
                        grad_in = [jnp.asarray(v) for v in
                                   codec.split_wire(body, slot.size)]
                    slot.param, slot.rule_state = apply_fn(
                        slot.param, grad_in, slot.rule_state)
                slot.committed()
                slot.grads_applied += 1
                self._m_grads.inc()
                self._sc_ops_counter(sid).inc()
                if not self.live.on:
                    span.end("aborted")
                    continue
                span.mark("ack")
                yield from aio_send(
                    self.transport,
                    _scwire.reply_frame(epoch, seq, _scwire.OK, sid),
                    crank, tags.GRAD_ACK, live=self.live,
                    abort=self._svc_abort(crank, gen),
                )
            span.end("applied")

    def _sc_send_param(self, crank: int, gen: int = 0):
        """Shardctl read loop: fixed 32-byte PARAM_REQ header in, the
        slot's cached snapshot frame (or a NACK/BUSY status) out."""
        codec = self._codecs.get(crank)
        if codec is None:
            return
        req = self._req_buf[crank]
        while self.live.on:
            got = yield from aio_recv(
                self.transport, crank, tags.PARAM_REQ, live=self.live,
                out=req, abort=self._svc_abort(crank, gen),
            )
            if got is None:
                return
            if not self.live.io:
                continue
            epoch, seq, _mapver, sid = (int(x) for x in req)
            span = self._spans.op("PARAM", peer=crank, side="server",
                                  rank=self.rank)
            span.note(epoch=epoch, seq=seq, shard=sid)
            if epoch < self.leases.epoch(crank):
                self._m_stale.inc()  # dead incarnation's request
                span.end("stale")
                continue
            self.leases.renew(crank, epoch)
            verdict = self._sc_verdict(sid)
            if verdict != _scwire.OK:
                (self._m_sc_nacks if verdict == _scwire.NACK_MAP
                 else self._m_sc_busy).inc()
                span.mark("send")
                yield from aio_send(
                    self.transport,
                    _scwire.reply_frame(epoch, seq, verdict, sid,
                                        body=self.smap.to_wire()),
                    crank, tags.PARAM, live=self.live,
                    abort=self._svc_abort(crank, gen),
                )
                span.end("nack" if verdict == _scwire.NACK_MAP else "busy")
                continue
            slot = self._slots[sid]
            with self._sc_busy_timer(sid):
                span.mark("snapshot")
                frame, hit = slot.snapshot_wire(codec)
                (self._m_snap_hits if hit else self._m_snap_copies).inc()
                reply = _scwire.reply_frame(epoch, seq, _scwire.OK, sid,
                                            body=frame)
                span.mark("send")
                yield from aio_send(
                    self.transport, reply, crank, tags.PARAM,
                    live=self.live, abort=self._svc_abort(crank, gen),
                )
                self._m_served.inc()
                self._sc_ops_counter(sid).inc()
            span.end("served")

    def _sc_recv_push(self, crank: int, gen: int = 0):
        """Shardctl PARAM_PUSH loop (seeding and whole-shard writes):
        dedup-admitted per slot, decoded host-side, one h2d per write."""
        codec = self._codecs.get(crank)
        if codec is None:
            return
        while self.live.on:
            raw = yield from aio_recv(
                self.transport, crank, tags.PARAM_PUSH, live=self.live,
                abort=self._svc_abort(crank, gen),
            )
            if raw is None:
                return
            buf = np.frombuffer(raw, np.uint8)
            epoch, seq, _mapver, sid = _scwire.unpack_sc_header(buf)
            span = self._spans.op("PARAM_PUSH", peer=crank, side="server",
                                  rank=self.rank)
            span.note(epoch=epoch, seq=seq, shard=sid)
            self.leases.renew(crank, epoch)
            verdict = self._sc_verdict(sid)
            if verdict != _scwire.OK:
                (self._m_sc_nacks if verdict == _scwire.NACK_MAP
                 else self._m_sc_busy).inc()
                span.mark("ack")
                yield from aio_send(
                    self.transport,
                    _scwire.reply_frame(epoch, seq, verdict, sid,
                                        body=self.smap.to_wire()),
                    crank, tags.PARAM_PUSH_ACK, live=self.live,
                    abort=self._svc_abort(crank, gen),
                )
                span.end("nack" if verdict == _scwire.NACK_MAP else "busy")
                continue
            slot = self._slots[sid]
            with self._sc_busy_timer(sid):
                admitted = slot.dedup.admit(crank, tags.PARAM_PUSH, epoch,
                                            seq)
                if admitted == STALE:
                    self._m_stale.inc()
                    span.end("stale")
                    continue
                if admitted != DUP:
                    span.mark("apply")
                    body = buf[_scwire.SC_HDR_BYTES:]
                    if codec.identity:
                        host: Any = body.view(self.dtype)
                    else:
                        host = np.empty(slot.size, np.float32)
                        codec.decode_into(body, host)
                    with self._dev_ctx():
                        slot.param = jnp.asarray(host)
                    slot.committed()
                    self._sc_ops_counter(sid).inc()
                else:
                    self._m_dups.inc()
                span.mark("ack")
                yield from aio_send(
                    self.transport,
                    _scwire.reply_frame(epoch, seq, _scwire.OK, sid),
                    crank, tags.PARAM_PUSH_ACK, live=self.live,
                    abort=self._svc_abort(crank, gen),
                )
            span.end("dup" if admitted == DUP else "applied")

    # -- shardctl control plane: directives, migration, beats ----------------

    def _sc_live_abort(self) -> Callable[[], bool]:
        return lambda: not self.live.on

    def _sc_map_listener(self):
        """Perpetual MAP_UPDATE service (controller channel): INSTALL
        adopts a map; RELEASE/ACQUIRE run the live-migration handshake;
        ADOPT restores a dead peer's shard from its checkpoint."""
        while self.live.on:
            raw = yield from aio_recv(
                self.transport, self.controller_rank, tags.MAP_UPDATE,
                live=self.live, abort=self._sc_live_abort(),
            )
            if raw is None:
                return
            kind, sid, peer, smap = _scwire.parse_map_update(bytes(raw))
            if kind == _scwire.RELEASE:
                yield from self._sc_release(sid, peer, smap)
            elif kind == _scwire.ACQUIRE:
                yield from self._sc_acquire(sid, peer, smap)
            elif kind == _scwire.ADOPT:
                yield from self._sc_adopt(sid, peer, smap)
            elif kind == _scwire.RETIRE:
                yield from self._sc_retire(smap)
                return
            else:  # INSTALL / RETIRED broadcasts: adopt the newer map
                self._sc_install_map(smap)

    def _sc_release(self, sid: int, dst: int, new_map: ShardMap):
        """Source side of a live migration: flip to the new map first
        (every later op for the shard drains via NACK_MAP), freeze the
        slot, serve exactly one SHARD_PULL, ship the state, drop it."""
        span = self._spans.op("MIGRATE", peer=dst, side="server",
                              rank=self.rank)
        span.note(shard=sid, direction="out")
        slot = self._slots.get(sid)
        if slot is None:
            self.log.warning(
                "RELEASE for shard %d but this server does not hold it "
                "(raced directive?) — ignoring", sid)
            span.end("aborted")
            return
        self._sc_install_map(new_map)
        slot.frozen = True
        span.mark("freeze")
        deadline = deadline_at(_scmigrate.SC_DEADLINE_S)
        buf = np.zeros(1, np.int64)
        got = yield from aio_recv(self.transport, dst, tags.SHARD_PULL,
                                  live=self.live, out=buf,
                                  deadline=deadline)
        if got is None:
            span.end("aborted")
            return
        span.mark("snapshot")
        msgs = _scmigrate.pack_shard_state(slot)
        span.mark("send")
        for msg in msgs:
            yield from aio_send(self.transport, msg, dst, tags.SHARD_STATE,
                                live=self.live, deadline=deadline)
        del self._slots[sid]
        self._m_sc_owned.set(len(self._slots))
        self._m_sc_out.inc()
        self.log.info("released shard %d to server %d (map v%d)",
                      sid, dst, new_map.version)
        span.end("released")

    def _sc_acquire(self, sid: int, src: int, new_map: ShardMap):
        """Destination side: adopt the map, pull the frozen state, place
        it on this server's backend, echo DONE to the controller."""
        span = self._spans.op("MIGRATE", peer=src, side="server",
                              rank=self.rank)
        span.note(shard=sid, direction="in")
        self._sc_install_map(new_map)
        deadline = deadline_at(_scmigrate.SC_DEADLINE_S)
        span.mark("pull")
        yield from aio_send(self.transport, np.asarray([sid], np.int64),
                            src, tags.SHARD_PULL, live=self.live,
                            deadline=deadline)
        slot = yield from _scmigrate.recv_shard_state(
            self.transport, src, self.live, deadline=deadline)
        if slot is None:
            span.end("aborted")
            return
        span.mark("install")
        slot.param = self._place_param(slot.param)
        if slot.rule_state:
            slot.rule_state = self._place_state(slot.rule_state)
        else:
            slot.rule_state = self._init_state(slot.param)
        self._slots[sid] = slot
        self._m_sc_owned.set(len(self._slots))
        self._m_sc_in.inc()
        span.mark("ack")
        yield from aio_send(
            self.transport,
            _scwire.map_update(_scwire.DONE, sid, self.rank, self.smap),
            self.controller_rank, tags.MAP_UPDATE, live=self.live,
            deadline=deadline)
        self.log.info("acquired shard %d from server %d (map v%d)",
                      sid, src, new_map.version)
        span.end("acquired")

    def _sc_adopt(self, sid: int, dead: int, new_map: ShardMap):
        """Failover: the previous owner is gone — restore the shard from
        its latest checkpoint (shard<id>_latest.npz) and serve it.  Ops
        the dead server applied-and-checkpointed dedup as DUP; ops after
        its last checkpoint are still unacked client-side and re-apply
        exactly once (the checkpoint is the consistency cut, §6.3)."""
        span = self._spans.op("MIGRATE", peer=dead, side="server",
                              rank=self.rank)
        span.note(shard=sid, direction="adopt")
        self._sc_install_map(new_map)
        if not self._ckpt_dir:
            span.end("exhausted")
            raise RuntimeError(
                f"ADOPT shard {sid}: server {self.rank} has no ckpt_dir — "
                "failover needs shard checkpoints")
        span.mark("restore")
        slot = _scmigrate.load_shard_state(self._ckpt_dir, sid)
        slot.param = self._place_param(slot.param)
        if slot.rule_state:
            slot.rule_state = self._place_state(slot.rule_state)
        else:
            slot.rule_state = self._init_state(slot.param)
        self._slots[sid] = slot
        self._m_sc_owned.set(len(self._slots))
        self._m_sc_adopt.inc()
        span.mark("ack")
        yield from aio_send(
            self.transport,
            _scwire.map_update(_scwire.DONE, sid, self.rank, self.smap),
            self.controller_rank, tags.MAP_UPDATE, live=self.live,
            deadline=deadline_at(_scmigrate.SC_DEADLINE_S))
        self.log.warning("adopted shard %d from dead server %d (map v%d)",
                         sid, dead, new_map.version)
        span.end("adopted")

    def _sc_retire(self, new_map: ShardMap):
        """The RETIRE handshake's server side (§9.2): the controller
        drained every shard off this rank before sending the directive,
        so holding any slot here is a protocol violation — fail loud
        rather than silently drop state.  Echo DONE (shard -1) as the
        goodbye receipt, then stop: start() returns normally and the
        process exits 0 — retirement is distinguishable from a crash
        by exit shape *and* by the controller's RETIRED lease state."""
        span = self._spans.op("RETIRE", peer=self.controller_rank,
                              side="server", rank=self.rank)
        self._sc_install_map(new_map)
        if self._slots:
            span.end("exhausted")
            raise RuntimeError(
                f"RETIRE directive while still owning shards "
                f"{sorted(self._slots)} — the controller must drain "
                "before retiring (docs/PROTOCOL.md §9.2)")
        span.mark("ack")
        yield from aio_send(
            self.transport,
            _scwire.map_update(_scwire.DONE, -1, self.rank, self.smap),
            self.controller_rank, tags.MAP_UPDATE, live=self.live,
            deadline=deadline_at(_scmigrate.SC_DEADLINE_S))
        self.retired = True
        self.log.info("retired: drained, goodbye sent (map v%d)",
                      self.smap.version)
        span.end("retired")
        self.live.stop()

    def _admit_listener(self, crank: int):
        """Perpetual late-join listener (§9.6): ``crank`` was *not* in
        the launch-time client set, but is provisioned rank space that
        may announce itself any time mid-run — INIT v3/v4 is the whole
        admission handshake, exactly like a rejoin except the first
        arrival also registers the rank with the lease/stop machinery.
        Subsequent INITs from the same rank are ordinary rejoins."""
        first = True
        while self.live.on:
            payload = yield from aio_recv(self.transport, crank, tags.INIT,
                                          live=self.live,
                                          abort=self._sc_live_abort())
            if payload is None:
                return
            if first:
                # Register before negotiating: a loud negotiation
                # failure should name a known member, and the stop
                # protocol must count this rank from its first frame.
                self.cranks.append(crank)
                self.leases.admit(crank)
                self._gen.setdefault(crank, 0)
                self._svc_live.setdefault(crank, 0)
            codec = self._negotiate(crank, payload)
            if first:
                first = False
                self._m_admits.inc()
                self.log.info("admitted late client %d (epoch %d)",
                              crank, self.leases.epoch(crank))
            else:
                self._m_rejoins.inc()
            self._gen[crank] += 1
            self.leases.rejoin(crank, self.leases.epoch(crank))
            self.leases.arm(crank, self.leases.epoch(crank),
                            heartbeats=self._hb.get(crank, False))
            self._alloc_client(crank, codec)
            while self._svc_live[crank] > 0:
                yield EXEC
            self._spawn_services(crank)

    def _check_preemption(self) -> None:
        """Checkpoint-on-notice (§9.3), called from the checkpoint
        loop's safe point (between scheduler passes — no grad is
        mid-apply).  One shot: stamped atomic publish of every owned
        shard, then a PREEMPT report so the controller can decide
        whether the grace window is worth a drain.  The handler itself
        only set a flag (mtlint MT-P204); everything here runs on the
        serving thread."""
        notice = self._preempt
        if notice is None or not notice.poll() or self._preempt_handled:
            return
        self._preempt_handled = True
        self._m_preempt.inc()
        self.log.warning(
            "preemption notice: %.1fs grace — checkpointing %s now",
            notice.grace_s,
            f"shards {sorted(self._slots)}" if self._sc else "shard")
        if self._ckpt_dir and (self.param is not None or self._slots):
            self.save_state(self._ckpt_dir)
            self._m_ckpts.inc()
        self._flight.record("preemption", rank=self.rank,
                            grace_s=notice.grace_s)
        self._flight.dump("preemption", rank=self.rank)
        if self._sc and self.controller_rank is not None \
                and self.smap is not None:
            self.sched.spawn(self._send_preempt_notice(notice.grace_ms),
                             name="preempt_notice")

    def _send_preempt_notice(self, grace_ms: int):
        try:
            yield from aio_send(
                self.transport,
                _scwire.map_update(_scwire.PREEMPT, grace_ms, self.rank,
                                   self.smap),
                self.controller_rank, tags.MAP_UPDATE, live=self.live,
                deadline=deadline_at(_scmigrate.SC_DEADLINE_S))
        except DeadlineExceeded:
            pass  # controller gone too; the checkpoint already landed

    def _sc_beat(self):
        """Beat to the controller: liveness plus the per-shard load
        report (ops and busy-seconds deltas, read from this server's obs
        instruments) the rebalance policy consumes."""
        interval = self.ft.heartbeat_s if self.ft.heartbeat_s > 0 else 0.1
        while self.live.on:
            if not (yield from aio_sleep(interval, live=self.live)):
                return
            self._sc_beat_seq += 1
            words = [self.ft.epoch, self._sc_beat_seq, len(self._slots)]
            for sid in sorted(self._slots):
                ops = int(self._sc_ops_counter(sid).value)
                busy = float(self.metrics.histogram(
                    "mpit_shardctl_shard_busy_seconds",
                    rank=self.rank, shard=sid).total)
                last_ops, last_busy = self._sc_last_report.get(sid, (0, 0.0))
                words += [sid, ops - last_ops,
                          int((busy - last_busy) * 1e6)]
                self._sc_last_report[sid] = (ops, busy)
            try:
                yield from aio_send(
                    self.transport, np.asarray(words, np.int64),
                    self.controller_rank, tags.HEARTBEAT, live=self.live,
                    deadline=deadline_at(4 * interval))
            except DeadlineExceeded:
                pass  # best-effort; the next beat tries again

    def _recv_heartbeat(self, crank: int, gen: int = 0):
        """Loop: consume HEARTBEAT beacons, renew the client's lease
        (current-epoch beats only — a dead incarnation's leftovers must
        not keep its successor's lease alive).  Timing pairs get each
        beat echoed back (HEARTBEAT_ECHO with the §6.7 tail), so the
        client's clock-offset estimator refreshes from the heartbeat
        stream even while no op is in flight."""
        buf = self._hb_buf.get(crank)
        if buf is None:
            return
        timing = self._timing.get(crank, False)
        echo = np.zeros(ACK_TIMING_WORDS, np.int64) if timing else None
        while self.live.on:
            got = yield from aio_recv(
                self.transport, crank, tags.HEARTBEAT, live=self.live,
                out=buf, abort=self._svc_abort(crank, gen),
            )
            if got is None:
                return
            t_recv = obs_clock.wall_us() if timing else 0
            self._m_hb_seen.inc()
            self.leases.renew(crank, int(buf[0]))
            if timing:
                echo[0], echo[1] = buf[0], buf[1]
                echo[2], echo[3] = buf[2], t_recv
                echo[4] = obs_clock.wall_us()
                yield from aio_send(
                    self.transport, echo, crank, tags.HEARTBEAT_ECHO,
                    live=self.live, abort=self._svc_abort(crank, gen),
                )

    # -- device exchange service (mpit_tpu.dplane, docs/DEVICE.md §4) --------

    def _dp_op_counter(self, op: str):
        c = self._m_dp_ops.get(op)
        if c is None:
            c = self.metrics.counter("mpit_dplane_device_ops_total",
                                     rank=self.rank, op=op)
            self._m_dp_ops[op] = c
        return c

    def _dplane_service(self):
        """Drain the in-process device-exchange queue: tickets execute
        between scheduler passes on this server's own thread, so device
        ops serialize with wire ops under the same single-writer
        discipline — serve-latest-committed reads stay untorn, and a
        lockstep gang applies in the identical cross-client order on
        either path."""
        plane = self._plane
        try:
            while self.live.on:
                ticket = plane.pop()
                if ticket is None:
                    # Idle pacing, not a busy scan (the IDLE_USEC lesson
                    # from the reader dispatcher).
                    if not (yield from aio_sleep(0.0005, live=self.live)):
                        return
                    continue
                try:
                    self._dplane_execute(ticket)
                except BaseException as exc:
                    # A failed op fails ITS client loudly; the service
                    # (and every other client) keeps running.
                    ticket.error = exc
                finally:
                    ticket.event.set()
                yield EXEC
        finally:
            plane.close("server service exited")

    def _dplane_execute(self, ticket) -> None:
        slot = self._hbm
        if slot is None:
            raise RuntimeError(
                f"device {ticket.kind} op from client {ticket.crank} "
                "before the shard exists (INIT/seed not complete, or a "
                "shardctl gang — the device exchange serves the static "
                "cut only; see docs/DEVICE.md §3)")
        kind = ticket.kind
        name = {"grad": "GRAD", "push": "PARAM_PUSH"}.get(kind, "PARAM")
        span = self._spans.op(name, peer=ticket.crank, side="server",
                              rank=self.rank)
        span.note(dplane=1)
        if kind == "grad":
            span.mark("apply")
            slot.apply_grad(ticket.payload)
            self.param, self.rule_state = slot.param, slot.rule_state
            self._committed()
            self._m_grads.inc()
            self._dp_op_counter("grad").inc()
            span.end("applied")
        elif kind == "push":
            span.mark("apply")
            slot.seed(ticket.payload)
            self.param = slot.param
            self._committed()
            self._dp_op_counter("push").inc()
            span.end("applied")
        elif kind == "pull":
            span.mark("snapshot")
            ticket.result = slot.snapshot_host()
            self._m_served.inc()
            self._dp_op_counter("pull").inc()
            span.end("served")
        elif kind == "pull_dev":
            span.mark("snapshot")
            ticket.result = slot.pull_device()
            self._m_served.inc()
            self._dp_op_counter("pull_dev").inc()
            span.end("served")
        else:
            span.end("aborted")
            raise ValueError(f"unknown device op kind {kind!r}")

    def _recv_stop(self, crank: int, gen: int = 0):
        """Await the stop signal; all clients terminal (stopped or
        evicted) => shut down I/O (reference :115-129)."""
        got = yield from aio_recv(self.transport, crank, tags.STOP,
                                  live=self.live,
                                  abort=self._svc_abort(crank, gen))
        if got is None:
            return
        self.leases.stop(crank)
        if crank in self._reader_set:
            self._update_reader_gauge()
        if self.leases.all_done():
            self.live.stop()

    def _lease_reaper(self):
        """Periodic scan: evict ACTIVE clients whose lease lapsed.  The
        evicted client's services abort, its staging is released, and the
        stop condition re-checks — one dead worker no longer wedges the
        gang (the MXNET-MPI elasticity argument, PAPERS.md)."""
        interval = max(min(self.ft.lease_ttl_s / 4.0, 1.0), 0.005)
        while self.live.on:
            if not (yield from aio_sleep(interval, live=self.live)):
                return
            for crank in self.leases.expired():
                self.log.warning(
                    "evicting client %d: lease expired after %.3fs without "
                    "a heartbeat (pending ops dropped, staging released; "
                    "it may rejoin with a bumped epoch)",
                    crank, self.ft.lease_ttl_s,
                )
                self.leases.evict(crank)
                self._m_evictions.inc()
                self._gen[crank] += 1  # stale loops abort at next poll
                self._release_client(crank)
                if crank in self._reader_set:
                    self._update_reader_gauge()
                if crank in self._cell_set:
                    self._update_cell_gauge()
                # Postmortem: the gang just lost a member — dump the
                # recent-event ring + live task table (obs/flight.py;
                # no-op when obs is disabled).
                self._flight.record("eviction", client=crank,
                                    rank=self.rank)
                self._flight.dump(
                    "eviction", client=crank,
                    tasks=[(t.name, t.state) for t in list(self.sched.queue)])
            if self.leases.all_done():
                self.live.stop()
                return

    # -- checkpoint / resume (beyond-reference: SURVEY §5 notes server
    # state is never checkpointed there; here Adam/RMSProp moments —
    # and now the FT dedup table + per-client negotiation — survive a
    # restart) --------------------------------------------------------------

    def _client_meta(self) -> Dict[str, Dict[str, Any]]:
        """Per-client negotiated state for the checkpoint: enough for a
        restarted server to serve retried ops without fresh INITs."""
        return {
            str(c): {
                "codec": self._codecs[c].name,
                "framed": self._framed.get(c, False),
                "hb": self._hb.get(c, False),
                "stale": self._stale_track.get(c, False),
                "timing": self._timing.get(c, False),
                "chunk": self._chunk.get(c, 0),
                "epoch": self.leases.epoch(c),
            }
            for c in self._codecs
            if c not in self._reader_set and c not in self._cell_set
            # Readers and cells are excluded on purpose: they re-attach
            # through the perpetual listeners, so a restarted server
            # need not carry their negotiation.
        }

    def save_state(self, directory) -> "str":
        """Checkpoint this server's shard param + rule state (+ the FT
        dedup table and client negotiation map).  Call from the owning
        thread while no grad is mid-apply (e.g. after start() returns, or
        from a service hook between applies).  Published via the stamped
        atomic-publish path: versioned history plus a ``_latest`` alias a
        concurrent loader can always trust."""
        from mpit_tpu.utils.checkpoint import save_server_state

        if self._sc:
            # Shard-oriented checkpoints: one shard<id>_latest.npz per
            # owned slot, so failover ADOPTs by shard id regardless of
            # which server wrote the file (shardctl/migrate.py).
            if not self._slots:
                raise RuntimeError(
                    "server owns no shards to checkpoint (init not run, "
                    "or every slot migrated away)")
            path = ""
            for _sid, slot in sorted(self._slots.items()):
                path = str(_scmigrate.save_shard_state(
                    directory, slot, self.rank))
            return path
        if self.param is None:
            raise RuntimeError("server holds no shard yet (init not run)")
        if self._snap_host is not None and self._snap_host[0] == self._snap_version:
            host = self._snap_host[1]  # reuse the snapshot cache's d2h copy
        elif self._hbm is not None:
            host = self._hbm.snapshot_host()
            self._snap_host = (self._snap_version, host)
        else:
            host = np.asarray(self.param)
            self._snap_host = (self._snap_version, host)
            self._m_snap_copies.inc()
        return str(save_server_state(
            directory, self.rank, self.offset, self.size,
            host,
            {k: np.asarray(v) for k, v in (self.rule_state or {}).items()},
            meta={
                "grads_applied": self.grads_applied,
                "snap_version": self._snap_version,
                "dedup": self.dedup.state(),
                # In-flight chunk admissions for the GRAD immediate-
                # apply path ONLY: those chunks are already folded into
                # the param bytes above, so set + state cut together.
                # PARAM_PUSH partials stay out — their assembly staging
                # dies with the process (ft/dedup.py partial_state).
                "dedup_chunks": self.dedup.partial_state(
                    tags={tags.GRAD}),
                "clients": self._client_meta(),
            },
        ))

    def restore_state(self, path) -> None:
        """Load a shard checkpoint before start().  A restored server
        skips the client-seeding phase — start the clients with
        ``seed_servers=False`` (the resume flow; reference resume instead
        reloads params on the client and reseeds, plaunch.lua:62).  FT
        checkpoints also restore the dedup table and each client's
        negotiated codec/framing, so a *restarted server* rejoins a live
        gang: clients keep retrying into the new process and their
        already-applied ops dedup instead of double-counting."""
        from mpit_tpu.utils.checkpoint import load_server_state

        if self.param is not None or self.offset != -1:
            raise RuntimeError("restore_state must run before start()")
        offset, size, param, state, meta = load_server_state(path)
        self.offset, self.size = offset, size
        self.grads_applied = int(meta.get("grads_applied", 0))
        self._snap_version = int(meta.get("snap_version", 0))
        self.dedup.restore(meta.get("dedup", {}))
        self.dedup.restore_partial(meta.get("dedup_chunks", {}))
        if self._dp_cfg is not None:
            self._hbm = _dphbm.HbmSlot(size, self.rule, self.dtype,
                                       config=self._dp_cfg, rank=self.rank)
            self._hbm.seed(param)
            if state:
                self._hbm.rule_state = self._place_state(state)
            # Version continuity across the restart (the staleness
            # stamps ride it): resume the checkpointed stream, +1 for
            # the seed commit — same arithmetic as the legacy path.
            self._hbm.version = self._snap_version + 1
            self.param = self._hbm.param
            self.rule_state = self._hbm.rule_state
        else:
            with self._dev_ctx():
                # device_copy on the restore path: checkpointed arrays
                # are numpy-backed, and a restored chunked client's
                # donated applies must never consume numpy-owned
                # memory (dplane.hbm.device_copy).  Cold path — one
                # extra copy per restore.
                self.param = _dphbm.device_copy(jnp.asarray(param))
                if state:
                    self.rule_state = {
                        k: _dphbm.device_copy(jnp.asarray(v))
                        for k, v in state.items()}
                else:  # stateless rule (plain add) or legacy checkpoint
                    self.rule_state = self.rule.init(self.param)
        for crank_s, info in (meta.get("clients") or {}).items():
            crank = int(crank_s)
            if crank not in self.cranks:
                continue
            self._framed[crank] = bool(info.get("framed", False))
            self._hb[crank] = bool(info.get("hb", False))
            self._stale_track[crank] = bool(info.get("stale", False))
            self._timing[crank] = bool(info.get("timing", False))
            self._chunk[crank] = int(info.get("chunk", 0))
            self.leases.arm(crank, int(info.get("epoch", 0)),
                            heartbeats=self._hb[crank])
            self._alloc_client(crank, codec_mod.get(info.get("codec", "none")))
            self._restored_clients.add(crank)
        self._committed()
        self._restored = True

    def _serve_with_checkpoints(self) -> None:
        """Drive the service queue like ``Scheduler.wait`` while writing
        the shard checkpoint every ``ckpt_interval`` seconds and once
        more at stop.  Safe point: a ping runs one generator step, and a
        grad apply commits within one step — between pings the shard is
        never torn."""
        next_save = time.monotonic() + self._ckpt_interval
        while self.sched.queue:
            self.sched.ping_pass()
            self._check_preemption()
            if time.monotonic() >= next_save:
                # A joiner that has not acquired a shard yet (or a
                # fully-drained rank awaiting RETIRE) has nothing to cut.
                if self.param is not None or self._slots:
                    self.save_state(self._ckpt_dir)
                    self._m_ckpts.inc()
                next_save = time.monotonic() + self._ckpt_interval
        if self.param is not None or self._slots:
            self.save_state(self._ckpt_dir)  # final state at stop
            self._m_ckpts.inc()
        if self.sched.errors:
            raise self.sched.errors.pop(0)

    # -- orchestration (reference pserver.lua:131-157) ----------------------

    def _spawn_services(self, crank: int) -> None:
        """Phase-3 perpetual services for one client (also the rejoin
        respawn path — hence per-generation naming)."""
        gen = self._gen[crank]
        self.sched.spawn(self._svc(crank, gen, self._recv_stop),
                         name=f"recv_stop:{crank}.g{gen}")
        if self._sc:
            self.sched.spawn(self._svc(crank, gen, self._sc_recv_grad),
                             name=f"recv_grad:{crank}.g{gen}")
            self.sched.spawn(self._svc(crank, gen, self._sc_send_param),
                             name=f"send_param:{crank}.g{gen}")
            self.sched.spawn(self._svc(crank, gen, self._sc_recv_push),
                             name=f"recv_param:{crank}.g{gen}")
            if self._hb.get(crank):
                self.sched.spawn(self._svc(crank, gen, self._recv_heartbeat),
                                 name=f"recv_heartbeat:{crank}.g{gen}")
            return
        self.sched.spawn(self._svc(crank, gen, self._recv_grad),
                         name=f"recv_grad:{crank}.g{gen}")
        self.sched.spawn(self._svc(crank, gen, self._send_param),
                         name=f"send_param:{crank}.g{gen}")
        if self._hb.get(crank):
            self.sched.spawn(self._svc(crank, gen, self._recv_heartbeat),
                             name=f"recv_heartbeat:{crank}.g{gen}")
        if self.single_mode:
            self.sched.spawn(self._svc(crank, gen, self._recv_param,
                                       once=False),
                             name=f"recv_param:{crank}.g{gen}")
        elif self._framed.get(crank):
            # Framed clients may retry a push whose first ack was lost;
            # someone must keep absorbing the duplicates and re-acking
            # after the one-shot seed service exits.  (FRESH post-seed
            # pushes only occur in the restored-server resume flow.)
            self.sched.spawn(
                self._svc(crank, gen, self._recv_param, once=False,
                          warn_unexpected=self._restored),
                name=f"recv_param:{crank}.g{gen}")

    def _drive(self) -> None:
        """Run the service queue to completion through whichever loop
        this server's posture needs (checkpoints and/or preemption
        polling; plain wait otherwise)."""
        if self._ckpt_dir:
            self._serve_with_checkpoints()
        elif self._preempt is not None:
            while self.sched.queue:
                self.sched.ping_pass()
                self._check_preemption()
            if self.sched.errors:
                raise self.sched.errors.pop(0)
        else:
            self.sched.wait()

    def start(self) -> None:
        """Run the server to completion (returns after the stop protocol).
        With a published device plane, the plane is offered for the
        server's whole lifetime and torn down loudly — a client blocked
        on a dead server's plane raises, never hangs."""
        publish = (self._dp_cfg is not None and self._dp_cfg.publish
                   and not self._sc_join)
        if not publish:
            self._run()
            return
        self._plane = _dpexchange.DevicePlane(
            self.rank, _dpexchange.backend_fingerprint())
        _dpexchange.publish(self.rank, self._plane, self._dp_cfg.namespace)
        try:
            self._run()
        finally:
            _dpexchange.withdraw(self.rank, self._dp_cfg.namespace)
            self._plane.close("server stopped")

    def _run(self) -> None:
        if self._sc_join:
            # Joiner (§9.1): spawned into a live gang by the controller.
            # No phase-1 rendezvous — nobody owes us an INIT.  Every
            # client gets a stop listener now (STOPs fan out to every
            # owner at gang end) and an admission-style INIT listener
            # (clients greet lazily before their first op to us); shards
            # arrive via ACQUIRE, beats start immediately so the
            # controller's scale_up sees the lease arm.
            if self.controller_rank is None:
                raise ValueError("a joiner server needs controller_rank — "
                                 "it exists only under a control plane")
            for crank in self.cranks:
                self.sched.spawn(self._svc(crank, 0, self._recv_stop),
                                 name=f"recv_stop:{crank}.g0")
                self.sched.spawn(self._init_listener(crank),
                                 name=f"init_listener:{crank}")
            for crank in self.admit_ranks:
                self.sched.spawn(self._admit_listener(crank),
                                 name=f"admit_listener:{crank}")
            if self.ft.lease_ttl_s > 0:
                self.sched.spawn(self._lease_reaper(), name="lease_reaper")
            self.sched.spawn(self._sc_map_listener(), name="sc_map_listener")
            self.sched.spawn(self._sc_beat(), name="sc_beat")
            self._drive()
            self.log.debug("stopped: %s",
                           self.metrics.format_summary(prefix="mpit_"))
            return
        # Phase 1: shard announcements from every client (skipped for
        # clients restored from an FT checkpoint — their negotiation is
        # already in hand and no fresh INIT is coming).
        for crank in self.cranks:
            if crank not in self._restored_clients:
                self.sched.spawn(self._svc(crank, 0, self._recv_init),
                                 name=f"recv_init:{crank}")
        self.sched.wait()
        # Phase 2: parameter seeding from the first client only
        # (init once & only once, reference README:64-67) — skipped on
        # resume, where the checkpoint already seeded the shard, and in
        # shardctl mode, where seeding arrives as ordinary dedup'd
        # PARAM_PUSH ops into the perpetual per-slot push service.
        seeder = self.cranks[0]
        if not self._restored and not self._sc:
            self.sched.spawn(self._svc(seeder, 0, self._recv_param, once=True),
                             name="seed_param")
            self.sched.wait()
        # Phase 3: perpetual services per client + stop counters.
        if self._restored and not self.single_mode and not self._framed.get(seeder):
            # A resume client wired with seed_servers=True would otherwise
            # block forever on its unconsumed push — accept it (client is
            # authoritative for params, as in the reference's -loadmodel
            # reseed, plaunch.lua:62) and warn loudly.  Framed clients get
            # the perpetual absorb service from _spawn_services instead.
            self.sched.spawn(
                self._svc(seeder, 0, self._recv_param, once=True,
                          warn_unexpected=True),
                name="unexpected_seed",
            )
        for crank in self.cranks:
            self._spawn_services(crank)
        if self._plane is not None:
            # Device exchange (mpit_tpu.dplane): ONE service task drains
            # the in-process ticket queue for every same-backend client.
            self.sched.spawn(self._dplane_service(), name="dplane_service")
        if self.readers:
            # Serving tier: ONE dispatcher task for every reader —
            # readers attach lazily, any time mid-run, and the
            # scheduler's task count stays O(in-flight replies).
            self.sched.spawn(self._reader_dispatcher(),
                             name="reader_dispatcher")
        if self.cells:
            # Multi-cell fabric (§11): ONE dispatcher pushes the diff
            # stream to every subscriber cell.
            self.sched.spawn(self._cell_dispatcher(),
                             name="cell_dispatcher")
        if self.ft.server_rejoin:
            for crank in self.cranks:
                self.sched.spawn(self._init_listener(crank),
                                 name=f"init_listener:{crank}")
        for crank in self.admit_ranks:
            self.sched.spawn(self._admit_listener(crank),
                             name=f"admit_listener:{crank}")
        if self.ft.lease_ttl_s > 0:
            self.sched.spawn(self._lease_reaper(), name="lease_reaper")
        if self._sc and self.controller_rank is not None:
            self.sched.spawn(self._sc_map_listener(), name="sc_map_listener")
            self.sched.spawn(self._sc_beat(), name="sc_beat")
        self._drive()
        # End-of-run summary rendered straight from the registry — every
        # number here (and any new instrument a layer adds) shows up
        # without touching this line.
        self.log.debug("stopped: %s",
                       self.metrics.format_summary(prefix="mpit_"))
