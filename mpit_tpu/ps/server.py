"""ParamServer — one process/role per shard, service loops per client.

Rebuild of reference asyncsgd/pserver.lua (plus the BiCNN variant's
server-side optimizer state, BiCNN/pserver.lua:50-83) with TPU-native
mechanics:

- The shard and its optimizer state are JAX arrays; every incoming
  gradient triggers one jitted ``rule.apply`` XLA program (the analog of
  the in-place ``p:add(g)`` / server-side Adam etc., reference
  pserver.lua:83, BiCNN/pserver.lua:123-197).  By default they live on
  the **host CPU backend** — the server is a host role and the
  reference's servers are CPU torch; on a tunneled-accelerator platform
  the old default-device placement shipped every shard over the tunnel
  twice per message (measured 43 -> 129 MB/s aggregate on the 640 MB
  ptest from this one change, before the scheduler idle backoff took it
  further).  Pass ``device="default"`` to keep shards on the platform
  default (e.g. a local accelerator whose HBM you want).
- Service loops are generator tasks on the cooperative scheduler — the
  direct analog of the reference's per-client coroutines
  (pserver.lua:131-157): ``recv_init``, one-shot ``recv_param`` from the
  seeding client, perpetual ``send_param`` / ``recv_grad`` loops, and the
  stop counter (pserver.lua:115-129).
- The reference's deliberate lock-free read ("expect inconsistent read",
  pserver.lua:74) maps to serve-latest-committed: ``send_param`` snapshots
  the current immutable device array — writers are never quiesced, and no
  torn read is possible.

Wire codecs (beyond-reference): each client negotiates a codec in its
INIT v2 announcement (mpit_tpu/comm/codec.py; the 16-byte legacy INIT
means 'none').  Gradient frames are decoded *inside* the jitted shard
update — ``decode(wire) -> rule.apply`` is one XLA program, so the
quantized path keeps today's one-call-per-grad shape.  Parameter reads
are served from a **version-counted encoded snapshot cache**: the
version bumps on every apply/seed, and N clients pulling the same
committed version cost one device->host copy plus one encode, not N
(``snapshot_copies`` / ``snapshot_hits`` count the win).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from mpit_tpu.aio import LiveFlag, Scheduler, aio_recv, aio_send
from mpit_tpu.comm import codec as codec_mod
from mpit_tpu.comm.transport import Transport
from mpit_tpu.optim.rules import ShardRule, make as make_rule
from mpit_tpu.ps import tags
from mpit_tpu.utils.logging import get_logger


class ParamServer:
    def __init__(
        self,
        rank: int,
        client_ranks: list[int],
        transport: Transport,
        rule: ShardRule | str = "add",
        scheduler: Optional[Scheduler] = None,
        dtype=np.float32,
        single_mode: bool = False,
        ckpt_dir: Optional[str] = None,
        ckpt_interval: float = 30.0,
        device: str = "cpu",  # "cpu" (host role, reference-faithful) | "default"
        codec: Optional[str] = None,  # None: adopt each client's announcement;
        #                               a name pins it — mismatches fail loudly
    ):
        self.rank = rank
        self.cranks = list(client_ranks)
        self.transport = transport
        self.rule = make_rule(rule) if isinstance(rule, str) else rule
        self.sched = scheduler or Scheduler()
        from mpit_tpu.utils.serialize import resolve_dtype

        self.dtype = resolve_dtype(dtype)
        self.single_mode = single_mode  # perpetual param-push service
        self.live = LiveFlag()
        self.log = get_logger("pserver", rank)

        self.offset = -1
        self.size = -1
        self.param: Optional[jnp.ndarray] = None  # device-resident shard
        self.rule_state = None
        self.grad_bufs: Dict[int, np.ndarray] = {}  # host recv staging, per client
        self._stopped_clients = 0
        # Codec negotiation state (INIT v2).  codec=None adopts whatever
        # each client announces (per-pair negotiation — mixed-codec
        # gangs are legal); an explicit name validates every
        # announcement against it and raises on mismatch rather than
        # decoding frames with the wrong codec.
        if codec:  # fail at construction, not first INIT
            codec_mod.get(codec)
        self._codec_pin = codec or None
        self._codecs: Dict[int, codec_mod.Codec] = {}
        self._grad_views: Dict[int, List[np.ndarray]] = {}
        self._push_bufs: Dict[int, np.ndarray] = {}
        self._push_host: Dict[int, np.ndarray] = {}
        self._apply_cache: Dict[str, Callable] = {}
        # Version-counted snapshot cache: _snap_version bumps on every
        # committed write (grad apply / seed / restore); _snap_host is
        # the one device->host copy for that version and _snap_wire the
        # per-codec encoded frame.  Serving allocates a fresh frame per
        # version — an in-flight zero-copy send of the previous version
        # must never see its buffer rewritten.
        self._snap_version = 0
        self._snap_host: Optional[Tuple[int, np.ndarray]] = None
        self._snap_wire: Dict[str, Tuple[int, np.ndarray]] = {}
        self.snapshot_copies = 0  # device->host copies actually performed
        self.snapshot_hits = 0  # PARAM serves satisfied from the cache
        if device not in ("cpu", "default"):
            raise ValueError(f"device must be 'cpu' or 'default', got {device!r}")
        self._device = None
        if device == "cpu":
            try:
                self._device = jax.local_devices(backend="cpu")[0]
            except RuntimeError:
                # Some accelerator plugins (e.g. the axon tunnel) replace
                # the in-process CPU backend entirely.  Fall back to the
                # platform default and say so — on a tunneled platform
                # that means every shard op rides the tunnel.
                self.log.warning(
                    "no CPU jax backend in this process; server shard "
                    "state falls back to the default device (set "
                    "JAX_PLATFORMS=cpu for host-resident serving)"
                )
        # Placement discipline: every jnp array this server creates is
        # built inside _dev_ctx(), so shard + optimizer state live (and
        # the jitted apply runs) on the configured backend.
        self.grads_applied = 0
        self.params_served = 0
        self._restored = False
        # Periodic shard checkpointing (the resume flow's producer side).
        self._ckpt_dir = str(ckpt_dir) if ckpt_dir else None
        self._ckpt_interval = float(ckpt_interval)
        self.ckpts_written = 0

    def _dev_ctx(self):
        """Context placing jnp array creation + jit execution on the
        configured backend (no-op for device='default')."""
        if self._device is None:
            import contextlib

            return contextlib.nullcontext()
        return jax.default_device(self._device)

    # -- codec plumbing ------------------------------------------------------

    def _negotiate(self, crank: int, payload: bytes) -> "codec_mod.Codec":
        """Parse the INIT announcement (v1 or v2) into (offset, size) on
        self and the negotiated codec for this client.  Every failure
        here is loud — a codec disagreement must never reach the frame
        decoders, where it would corrupt parameters silently."""
        raw = np.frombuffer(payload, dtype=np.int64)
        if raw.size == 2:  # legacy 16-byte v1 announcement
            offset, size, wire_id = int(raw[0]), int(raw[1]), 0
        elif raw.size == 3:
            offset, size, wire_id = (int(x) for x in raw)
        else:
            raise ValueError(
                f"client {crank} INIT announcement is {len(payload)} bytes; "
                "expected 16 (legacy [offset, size]) or 24 "
                "([offset, size, codec_id])"
            )
        codec = codec_mod.by_wire_id(wire_id)
        if self._codec_pin is not None and codec.name != self._codec_pin:
            raise ValueError(
                f"codec negotiation mismatch: client {crank} announced "
                f"{codec.name!r} but server {self.rank} is pinned to "
                f"{self._codec_pin!r} — align MPIT_PS_CODEC (or the codec "
                "config) across the gang"
            )
        if not codec.identity and np.dtype(self.dtype) != np.float32:
            raise ValueError(
                f"codec {codec.name!r} quantizes float32 shards; server "
                f"{self.rank} holds dtype {np.dtype(self.dtype).name} "
                "(use codec='none' for other dtypes)"
            )
        if self.offset == -1:
            self.offset, self.size = offset, size
            with self._dev_ctx():
                self.param = jnp.zeros((size,), dtype=self.dtype)
                self.rule_state = self.rule.init(self.param)
        else:
            # All clients must agree on this server's shard (reference :87-88).
            assert (self.offset, self.size) == (offset, size), (
                f"client {crank} announced shard ({offset},{size}) but server "
                f"{self.rank} already holds ({self.offset},{self.size})"
            )
        return codec

    def _apply_for(self, codec: "codec_mod.Codec") -> Callable:
        """The jitted shard update for one codec: frame decode fused with
        ``rule.apply`` into a single XLA program (one call per grad, same
        as the fp32 path)."""
        fn = self._apply_cache.get(codec.name)
        if fn is None:
            rule_apply = self.rule.apply
            if codec.identity:
                fn = jax.jit(rule_apply)
            else:
                size = self.size

                def _decode_apply(param, parts, state):
                    return rule_apply(param, codec.decode_parts(parts, size), state)

                fn = jax.jit(_decode_apply)
            self._apply_cache[codec.name] = fn
        return fn

    def _push_staging(self, crank: int) -> np.ndarray:
        """Lazily-allocated PARAM_PUSH recv staging for one client, sized
        to its codec's wire format (cold path: seeding / single mode)."""
        buf = self._push_bufs.get(crank)
        if buf is None:
            codec = self._codecs[crank]
            if codec.identity:
                buf = np.zeros((self.size,), dtype=self.dtype)
            else:
                buf = np.zeros(codec.wire_nbytes(self.size), np.uint8)
                self._push_host[crank] = np.zeros((self.size,), np.float32)
            self._push_bufs[crank] = buf
        return buf

    def _committed(self) -> None:
        """A new shard version exists (grad applied / params seeded)."""
        self._snap_version += 1

    def _snapshot_wire(self, codec: "codec_mod.Codec") -> np.ndarray:
        """The current version's PARAM frame for ``codec``, cached: N
        clients reading one committed version share one device->host
        copy and one encode.  Runs between scheduler yields, so version
        read + copy + encode are atomic w.r.t. grad applies."""
        version = self._snap_version
        cached = self._snap_wire.get(codec.name)
        if cached is not None and cached[0] == version:
            self.snapshot_hits += 1
            return cached[1]
        if self._snap_host is None or self._snap_host[0] != version:
            # Serve-latest-committed: np.asarray snapshots the current
            # immutable device array (the one device->host copy).
            self._snap_host = (version, np.asarray(self.param))
            self.snapshot_copies += 1
        host = self._snap_host[1]
        if codec.identity:
            wire = host
        else:
            wire = np.empty(codec.wire_nbytes(self.size), np.uint8)
            codec.encode_into(host, wire)
        self._snap_wire[codec.name] = (version, wire)
        return wire

    # -- service generators (reference pserver.lua coroutines) --------------

    def _recv_init(self, crank: int):
        """Receive [offset, size(, codec_id)]; negotiate the codec and
        allocate shard + staging state (reference :33-57)."""
        payload = yield from aio_recv(self.transport, crank, tags.INIT, live=self.live)
        if payload is None:
            return
        codec = self._negotiate(crank, payload)
        self._codecs[crank] = codec
        if codec.identity:
            self.grad_bufs[crank] = np.zeros((self.size,), dtype=self.dtype)
        else:
            buf = np.zeros(codec.wire_nbytes(self.size), np.uint8)
            self.grad_bufs[crank] = buf
            self._grad_views[crank] = codec.split_wire(buf, self.size)

    def _recv_param(self, crank: int, once: bool = True,
                    warn_unexpected: bool = False):
        """Whole-shard write from a client: one-shot seeding from the first
        client (reference :92-102) or perpetual in single mode (the
        BiCNN recvparam_always service, BiCNN/pserver.lua:220-232)."""
        codec = self._codecs.get(crank)
        if codec is None:  # init never completed (stopped before announce)
            return
        staging = self._push_staging(crank)
        while self.live.on:
            got = yield from aio_recv(
                self.transport, crank, tags.PARAM_PUSH,
                live=self.live, out=staging,
            )
            if got is None:
                return
            if warn_unexpected:
                self.log.warning(
                    "client %d seeded a RESTORED server: checkpointed "
                    "params overwritten (optimizer state kept) — start "
                    "resume clients with seed_servers=False", crank,
                )
            if codec.identity:
                host = staging
            else:  # cold path: host decode, then one h2d
                host = self._push_host[crank]
                codec.decode_into(staging, host)
            with self._dev_ctx():
                self.param = jnp.asarray(host)
            self._committed()
            yield from aio_send(
                self.transport, tags.EMPTY, crank, tags.PARAM_PUSH_ACK, live=self.live
            )
            if once:
                return

    def _send_param(self, crank: int):
        """Loop: await 0-byte read request, send the current version's
        encoded snapshot (reference :59-72)."""
        codec = self._codecs.get(crank)
        if codec is None:  # init never completed (stopped before announce)
            return
        while self.live.on:
            got = yield from aio_recv(
                self.transport, crank, tags.PARAM_REQ, live=self.live
            )
            if got is None:
                return
            if self.live.io:
                snapshot = self._snapshot_wire(codec)
                yield from aio_send(
                    self.transport, snapshot, crank, tags.PARAM, live=self.live
                )
                self.params_served += 1

    def _recv_grad(self, crank: int):
        """Loop: receive gradient frame, decode+apply the shard rule in
        one jitted call, ack (reference :75-90 — the server hot loop)."""
        codec = self._codecs.get(crank)
        if codec is None:  # init never completed (stopped before announce)
            return
        gbuf = self.grad_bufs[crank]
        parts = self._grad_views.get(crank)
        apply_fn = self._apply_for(codec)
        while self.live.on:
            got = yield from aio_recv(
                self.transport, crank, tags.GRAD, live=self.live, out=gbuf
            )
            if got is None:
                return
            with self._dev_ctx():
                if parts is None:
                    grad_in: Any = jnp.asarray(gbuf)
                else:
                    grad_in = [jnp.asarray(v) for v in parts]
                self.param, self.rule_state = apply_fn(
                    self.param, grad_in, self.rule_state
                )
            self.grads_applied += 1
            self._committed()
            if self.live.on:
                yield from aio_send(
                    self.transport, tags.EMPTY, crank, tags.GRAD_ACK, live=self.live
                )

    def _recv_stop(self, crank: int):
        """Count stop signals; all clients stopped => shut down I/O
        (reference :115-129)."""
        got = yield from aio_recv(self.transport, crank, tags.STOP, live=self.live)
        if got is None:
            return
        self._stopped_clients += 1
        if self._stopped_clients == len(self.cranks):
            self.live.stop()

    # -- checkpoint / resume (beyond-reference: SURVEY §5 notes server
    # state is never checkpointed there; here Adam/RMSProp moments
    # survive a restart) --------------------------------------------------

    def save_state(self, directory) -> "str":
        """Checkpoint this server's shard param + rule state.  Call from
        the owning thread while no grad is mid-apply (e.g. after start()
        returns, or from a service hook between applies)."""
        from mpit_tpu.utils.checkpoint import save_server_state

        if self.param is None:
            raise RuntimeError("server holds no shard yet (init not run)")
        return str(save_server_state(
            directory, self.rank, self.offset, self.size,
            np.asarray(self.param),
            {k: np.asarray(v) for k, v in (self.rule_state or {}).items()},
            meta={"grads_applied": self.grads_applied},
        ))

    def restore_state(self, path) -> None:
        """Load a shard checkpoint before start().  A restored server
        skips the client-seeding phase — start the clients with
        ``seed_servers=False`` (the resume flow; reference resume instead
        reloads params on the client and reseeds, plaunch.lua:62)."""
        from mpit_tpu.utils.checkpoint import load_server_state

        if self.param is not None or self.offset != -1:
            raise RuntimeError("restore_state must run before start()")
        offset, size, param, state, meta = load_server_state(path)
        self.offset, self.size = offset, size
        self.grads_applied = int(meta.get("grads_applied", 0))
        with self._dev_ctx():
            self.param = jnp.asarray(param)
            if state:
                self.rule_state = {k: jnp.asarray(v) for k, v in state.items()}
            else:  # stateless rule (plain add) or legacy checkpoint
                self.rule_state = self.rule.init(self.param)
        self._committed()
        self._restored = True

    def _serve_with_checkpoints(self) -> None:
        """Drive the service queue like ``Scheduler.wait`` while writing
        the shard checkpoint every ``ckpt_interval`` seconds and once
        more at stop.  Safe point: a ping runs one generator step, and a
        grad apply commits within one step — between pings the shard is
        never torn."""
        import time as _time

        next_save = _time.monotonic() + self._ckpt_interval
        while self.sched.queue:
            self.sched.ping_pass()
            if _time.monotonic() >= next_save:
                self.save_state(self._ckpt_dir)
                self.ckpts_written += 1
                next_save = _time.monotonic() + self._ckpt_interval
        if self.param is not None:
            self.save_state(self._ckpt_dir)  # final state at stop
            self.ckpts_written += 1
        if self.sched.errors:
            raise self.sched.errors.pop(0)

    # -- orchestration (reference pserver.lua:131-157) ----------------------

    def start(self) -> None:
        """Run the server to completion (returns after the stop protocol)."""
        # Phase 1: shard announcements from every client.
        for crank in self.cranks:
            self.sched.spawn(self._recv_init(crank), name=f"recv_init:{crank}")
        self.sched.wait()
        # Phase 2: parameter seeding from the first client only
        # (init once & only once, reference README:64-67) — skipped on
        # resume, where the checkpoint already seeded the shard.
        seeder = self.cranks[0]
        if not self._restored:
            self.sched.spawn(self._recv_param(seeder, once=True), name="seed_param")
            self.sched.wait()
        # Phase 3: perpetual services per client + stop counters.
        if self._restored and not self.single_mode:
            # A resume client wired with seed_servers=True would otherwise
            # block forever on its unconsumed push — accept it (client is
            # authoritative for params, as in the reference's -loadmodel
            # reseed, plaunch.lua:62) and warn loudly.
            self.sched.spawn(
                self._recv_param(seeder, once=True, warn_unexpected=True),
                name="unexpected_seed",
            )
        for crank in self.cranks:
            self.sched.spawn(self._recv_stop(crank), name=f"recv_stop:{crank}")
            self.sched.spawn(self._recv_grad(crank), name=f"recv_grad:{crank}")
            self.sched.spawn(self._send_param(crank), name=f"send_param:{crank}")
            if self.single_mode:
                self.sched.spawn(
                    self._recv_param(crank, once=False), name=f"recv_param:{crank}"
                )
        if self._ckpt_dir:
            self._serve_with_checkpoints()
        else:
            self.sched.wait()
        self.log.debug(
            "stopped: %d grads applied, %d params served "
            "(%d snapshot copies, %d cache hits)",
            self.grads_applied,
            self.params_served,
            self.snapshot_copies,
            self.snapshot_hits,
        )
