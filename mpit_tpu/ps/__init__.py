"""L2 — the sharded asynchronous parameter-server protocol.

The reference implements one pServer process per shard with per-client
service coroutines (recvinit / recvparam / sendparam / recvgrad / recvstop,
reference asyncsgd/pserver.lua:131-157) and a pClient that splits the flat
parameter vector across servers and drives async shard transfers (reference
asyncsgd/pclient.lua:84-179), over an 8-tag wire protocol (reference
asyncsgd/init.lua:3-10).

This package is the TPU-native rebuild: shards are device-HBM-resident JAX
arrays updated by jitted shard rules (mpit_tpu.optim.rules); service loops
are generator tasks on the cooperative scheduler (mpit_tpu.aio); transfers
go through a pluggable Transport (mpit_tpu.comm).  The reference's
deliberate lock-free stale reads (pserver.lua:74 "expect inconsistent
read") become serve-latest-committed snapshots — JAX immutability gives the
same algorithmic tolerance without torn reads.
"""

from mpit_tpu.ps.sharding import Shard, shard_layout, weighted_layout
from mpit_tpu.ps.client import ParamClient
from mpit_tpu.ps.server import ParamServer
from mpit_tpu.ps.serve import ReaderClient, ServeConfig
from mpit_tpu.ps import tags

__all__ = ["Shard", "shard_layout", "weighted_layout", "ParamClient",
           "ParamServer", "ReaderClient", "ServeConfig", "tags"]
