"""Wire-protocol tags (analog of reference asyncsgd/init.lua:3-10).

Eight channels, renamed by direction and purpose rather than the
reference's server-perspective naming.  0-byte messages serve as the
rendezvous conventions the reference relies on: PARAM_REQ is the "header"
a client sends to request a shard read (reference pclient.lua:74-75 ->
pserver.lua:100-101); *_ACK are the "tail" completion acks after writes
(reference pserver.lua:85-86, pclient.lua:55-56)."""

INIT = 1  # client -> server: int64 [offset, size, codec_id] shard
#           announcement (INIT v2).  The 16-byte legacy v1 payload
#           [offset, size] is still accepted and means codec_id=0
#           ('none').  codec_id values: mpit_tpu/comm/codec.py wire ids;
#           unknown ids fail loudly at the server.  See docs/PROTOCOL.md.
GRAD = 2  # client -> server: gradient/delta frame for the shard, in the
#           negotiated codec's wire format (raw dtype bytes for 'none')
GRAD_ACK = 3  # server -> client: 0-byte ack after the update is applied
PARAM_REQ = 4  # client -> server: 0-byte request-to-read header
PARAM = 5  # server -> client: current shard snapshot frame (negotiated codec)
PARAM_PUSH = 6  # client -> server: whole-shard parameter write frame
PARAM_PUSH_ACK = 7  # server -> client: 0-byte ack after the write lands
STOP = 8  # client -> server: 0-byte graceful-shutdown signal

EMPTY = b""  # the canonical 0-byte payload
