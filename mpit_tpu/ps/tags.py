"""Wire-protocol tags (analog of reference asyncsgd/init.lua:3-10).

Eight channels, renamed by direction and purpose rather than the
reference's server-perspective naming.  0-byte messages serve as the
rendezvous conventions the reference relies on: PARAM_REQ is the "header"
a client sends to request a shard read (reference pclient.lua:74-75 ->
pserver.lua:100-101); *_ACK are the "tail" completion acks after writes
(reference pserver.lua:85-86, pclient.lua:55-56)."""

INIT = 1  # client -> server: int64 shard announcement.  Three wire
#           generations, distinguished by payload length (docs/PROTOCOL.md):
#           v1 (16 B) [offset, size] = codec 'none', no fault tolerance;
#           v2 (24 B) [offset, size, codec_id];
#           v3 (40 B) [offset, size, codec_id, epoch, flags] — epoch is
#           the client incarnation number (bumped on restart/rejoin) and
#           flags bit0 enables FT frame headers (mpit_tpu/ft/wire.py).
GRAD = 2  # client -> server: gradient/delta frame for the shard, in the
#           negotiated codec's wire format (raw dtype bytes for 'none');
#           FT-framed clients prepend an int64 [epoch, seq] header
GRAD_ACK = 3  # server -> client: ack after the update is applied — 0-byte
#               legacy, int64 [epoch, seq] echo for FT-framed clients
PARAM_REQ = 4  # client -> server: request-to-read header — 0-byte legacy,
#                int64 [epoch, seq] for FT-framed clients
PARAM = 5  # server -> client: current shard snapshot frame (negotiated
#            codec); FT-framed replies echo the request's [epoch, seq]
PARAM_PUSH = 6  # client -> server: whole-shard parameter write frame
#                 (FT-framed clients prepend [epoch, seq])
PARAM_PUSH_ACK = 7  # server -> client: ack after the write lands — 0-byte
#                     legacy, [epoch, seq] echo for FT-framed clients
STOP = 8  # client -> server: 0-byte graceful-shutdown signal
HEARTBEAT = 9  # client -> server: int64 [epoch, seq] liveness beacon; the
#                server's lease registry (mpit_tpu/ft/leases.py) renews
#                the client's lease on every beat and evicts on expiry.
#                Under shardctl, servers also beat to the controller with
#                a per-shard load report appended (docs/PROTOCOL.md §7.4).
MAP_UPDATE = 10  # controller -> server/client (and server -> controller
#                  as the DONE echo): a shard-map directive
#                  [kind, shard_id, peer] + serialized ShardMap
#                  (mpit_tpu/shardctl/wire.py; docs/PROTOCOL.md §7.3)
SHARD_PULL = 11  # server(dst) -> server(src): int64 [shard_id] — "I was
#                  directed to acquire this shard; send its state"
SHARD_STATE = 12  # server(src) -> server(dst): the frozen shard's full
#                   state (meta json + param bytes + rule-state arrays),
#                   a multi-message sequence on this one FIFO channel
HEARTBEAT_ECHO = 13  # server -> client: int64 [epoch, seq, t_tx_echo,
#                      t_recv, t_ack] — the FLAG_TIMING reply to a timed
#                      HEARTBEAT beacon (docs/PROTOCOL.md §6.7).  NOT an
#                      ack tail: heartbeats stay fire-and-forget, and the
#                      client drains echoes opportunistically (iprobe in
#                      ping/wait) to refresh its clock-offset estimator
#                      while compute-bound; a lost echo costs nothing.
#                      Subscriber (FLAG_SUBSCRIBE) beats get the 3-word
#                      [epoch, seq, head_version] form instead — the
#                      head announcement a replica cell's staleness
#                      admission keys on (docs/PROTOCOL.md §11.3).
DIFF = 14  # server -> cell: one snapshot-diff frame of the committed
#            version stream (docs/PROTOCOL.md §11.2): int64
#            [kind, from_version, to_version, head_version, body_nbytes]
#            then the body bytes in the SAME message (message-atomic
#            under fault injection).  kind FULL carries the whole
#            encoded snapshot frame at to_version (attach/resync); kind
#            DELTA carries the XOR of the to/from encoded frames — the
#            cell reconstructs to_version's frame bit-exactly from its
#            installed from_version copy.
DIFF_REQ = 15  # cell -> server: int64 [epoch, seq, have_version] — the
#                resync request.  Sent when the diff chain broke (a
#                dropped DELTA: from_version != the installed version)
#                or the cell fell beyond its resync horizon; the server
#                answers with a FULL frame at the current head.
REDUCE = 16  # client -> client: one partial-gradient chunk frame of the
#              hierarchical aggregation tree (docs/PROTOCOL.md §13):
#              int64 [epoch, seq, chunk_idx, chunk_count, nfold] then
#              the chunk's codec frame, padded to the uniform stride.
#              ``nfold`` is the number of leaf contributions already
#              folded into the partial; the receiving interior node
#              folds the decoded chunk into its own partial sum in
#              fixed child-rank order and forwards chunk k upstream
#              while chunk k+1 is still arriving.
REDUCE_ACK = 17  # client -> client: int64 [epoch, seq, chunk_idx,
#                  status] — per-admitted-chunk ack on the REDUCE hop.
#                  status OK means received (retries resend only
#                  unacked chunks, the §12 discipline); status LATE
#                  means the round already folded without this sender
#                  (straggler deadline fired) — the sender must fall
#                  back to a direct GRAD push of its partial, so a
#                  late contribution is counted and re-routed, never
#                  silently dropped and never double-folded.

EMPTY = b""  # the canonical 0-byte payload

# Protocol-conformance pairing table (machine-checked: mtlint MT-P5xx).
# Every tag above MUST have an entry naming its sender and receiver
# roles; client<->server rows are additionally cross-checked against the
# actual role-file call sites (MT-P102), while rows involving the
# controller or server<->server traffic are exempt from that binary
# role model and are validated against this table + docs/PROTOCOL.md.
TAG_PAIRS = {
    "INIT": ("client", "server"),
    "GRAD": ("client", "server"),
    "GRAD_ACK": ("server", "client"),
    "PARAM_REQ": ("client", "server"),
    "PARAM": ("server", "client"),
    "PARAM_PUSH": ("client", "server"),
    "PARAM_PUSH_ACK": ("server", "client"),
    "STOP": ("client", "server|controller"),
    "HEARTBEAT": ("client|server", "server|controller"),
    "MAP_UPDATE": ("controller|server", "server|client|controller"),
    "SHARD_PULL": ("server", "server"),
    "SHARD_STATE": ("server", "server"),
    "HEARTBEAT_ECHO": ("server", "client"),
    # Multi-cell serving fabric (docs/PROTOCOL.md §11): a replica cell
    # attaches to its upstream server like a client (SUBSCRIBE posture
    # on INIT) but is its own role — the diff-stream rows live outside
    # the binary client<->server model (like controller traffic) and
    # are validated against this table + PROTOCOL.md (MT-P5xx).
    "DIFF": ("server", "cell"),
    "DIFF_REQ": ("cell", "server"),
    # Hierarchical aggregation (docs/PROTOCOL.md §13): reduction-tree
    # hops travel client<->client — like the server<->server shard
    # handoff, these rows live outside the binary client<->server role
    # model and are validated against this table + PROTOCOL.md.
    "REDUCE": ("client", "client"),
    "REDUCE_ACK": ("client", "client"),
}
