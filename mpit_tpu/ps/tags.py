"""Wire-protocol tags (analog of reference asyncsgd/init.lua:3-10).

Eight channels, renamed by direction and purpose rather than the
reference's server-perspective naming.  0-byte messages serve as the
rendezvous conventions the reference relies on: PARAM_REQ is the "header"
a client sends to request a shard read (reference pclient.lua:74-75 ->
pserver.lua:100-101); *_ACK are the "tail" completion acks after writes
(reference pserver.lua:85-86, pclient.lua:55-56)."""

INIT = 1  # client -> server: int64 shard announcement.  Three wire
#           generations, distinguished by payload length (docs/PROTOCOL.md):
#           v1 (16 B) [offset, size] = codec 'none', no fault tolerance;
#           v2 (24 B) [offset, size, codec_id];
#           v3 (40 B) [offset, size, codec_id, epoch, flags] — epoch is
#           the client incarnation number (bumped on restart/rejoin) and
#           flags bit0 enables FT frame headers (mpit_tpu/ft/wire.py).
GRAD = 2  # client -> server: gradient/delta frame for the shard, in the
#           negotiated codec's wire format (raw dtype bytes for 'none');
#           FT-framed clients prepend an int64 [epoch, seq] header
GRAD_ACK = 3  # server -> client: ack after the update is applied — 0-byte
#               legacy, int64 [epoch, seq] echo for FT-framed clients
PARAM_REQ = 4  # client -> server: request-to-read header — 0-byte legacy,
#                int64 [epoch, seq] for FT-framed clients
PARAM = 5  # server -> client: current shard snapshot frame (negotiated
#            codec); FT-framed replies echo the request's [epoch, seq]
PARAM_PUSH = 6  # client -> server: whole-shard parameter write frame
#                 (FT-framed clients prepend [epoch, seq])
PARAM_PUSH_ACK = 7  # server -> client: ack after the write lands — 0-byte
#                     legacy, [epoch, seq] echo for FT-framed clients
STOP = 8  # client -> server: 0-byte graceful-shutdown signal
HEARTBEAT = 9  # client -> server: int64 [epoch, seq] liveness beacon; the
#                server's lease registry (mpit_tpu/ft/leases.py) renews
#                the client's lease on every beat and evicts on expiry

EMPTY = b""  # the canonical 0-byte payload
