"""ParamClient — shards the flat parameter vector across servers and
drives asynchronous shard transfers.

Rebuild of reference asyncsgd/pclient.lua.  The client registers two host
buffers (``param``, ``grad``) whose per-server contiguous slices are the
transfer units (numpy views = the reference's zero-copy storage-offset
views, pclient.lua:50-52).  Public surface mirrors pclient.lua:84-179:
``start``, ``async_send_grad``, ``async_recv_param``, ``async_send_param``,
``ping``, ``wait``, ``reset``, ``stop``.

The comm-aware optimizers (mpit_tpu.optim.downpour/easgd/shells) drive this
class through the ParamClientAPI protocol; device arrays stay in the
optimizer layer — the client only ever touches the registered host mirrors.

Wire codecs (beyond-reference — the EQuARX direction, PAPERS.md): the
client announces a codec in its INIT (``MPIT_PS_CODEC`` or the ``codec``
argument; mpit_tpu/comm/codec.py) and every GRAD/PARAM/PARAM_PUSH frame
to/from that server travels in that format.  For the lossy ``int8`` codec
the client holds one error-feedback residual per shard: the gradient
quantization error is added back into the next shipped gradient instead
of being lost, so DOWNPOUR/EASGD converge as if uncompressed (the shells
in mpit_tpu.optim need no changes — they keep writing fp32 into
``grad``; encode happens here at ship time).  ``codec='none'`` keeps
today's zero-copy slice sends byte-for-byte.
"""

from __future__ import annotations

from collections import deque
from typing import Deque, Dict, Generator, List, Optional, Tuple

import numpy as np

from mpit_tpu.aio import LiveFlag, Scheduler, aio_recv, aio_send
from mpit_tpu.comm import codec as codec_mod
from mpit_tpu.comm.transport import Transport
from mpit_tpu.ps import tags
from mpit_tpu.ps.sharding import Shard, shard_layout
from mpit_tpu.utils.logging import get_logger


class ParamClient:
    def __init__(
        self,
        rank: int,
        server_ranks: list[int],
        transport: Transport,
        scheduler: Optional[Scheduler] = None,
        seed_servers: bool = False,
        codec: Optional[str] = None,
    ):
        self.rank = rank
        self.sranks = list(server_ranks)
        self.transport = transport
        self.sched = scheduler or Scheduler()
        self.seed_servers = seed_servers  # this is the first client
        self.codec = codec_mod.get(codec)  # None/'' -> $MPIT_PS_CODEC
        self.live = LiveFlag()
        self.log = get_logger("pclient", rank)
        self.param: Optional[np.ndarray] = None
        self.grad: Optional[np.ndarray] = None
        self.shards: List[Shard] = []
        self._started = False
        # Per-server codec state: encode/decode staging sized to the wire
        # format, plus the int8 error-feedback residual (grad path only).
        self._grad_wire: Dict[int, np.ndarray] = {}
        self._param_wire: Dict[int, np.ndarray] = {}
        self._residual: Dict[int, np.ndarray] = {}
        # Per-server FIFO op chains: ops addressed to the same server run in
        # issue order (a send_grad's ack completes before a later param
        # request is sent), while different servers stay fully concurrent.
        # Strictly stronger than the reference (which relies on coroutine
        # spawn order for freshness, pclient.lua:84-109) — this removes the
        # stale-own-write race without giving up cross-server overlap.
        self._opq: Dict[int, Deque[Tuple[Generator, str]]] = {}
        self._pump_live: Dict[int, bool] = {}
        self._pump_task: Dict[int, Optional[object]] = {}

    # -- lifecycle ----------------------------------------------------------

    def start(self, param: np.ndarray, grad: np.ndarray) -> None:
        """Announce shard layout + codec to every server; the first client
        seeds the servers' shards from ``param`` (reference
        pclient.lua:111-129).  INIT v2: int64 [offset, size, codec_id]."""
        self._register(param, grad)
        self.shards = shard_layout(len(param), len(self.sranks))
        for srank, shard in zip(self.sranks, self.shards):
            if not self.codec.identity:
                nbytes = self.codec.wire_nbytes(shard.size)
                self._grad_wire[srank] = np.zeros(nbytes, np.uint8)
                self._param_wire[srank] = np.zeros(nbytes, np.uint8)
                if self.codec.uses_residual:
                    self._residual[srank] = np.zeros(shard.size, np.float32)
            cinfo = np.asarray(
                [shard.offset, shard.size, self.codec.wire_id], dtype=np.int64
            )
            self.sched.spawn(
                aio_send(self.transport, cinfo, srank, tags.INIT, live=self.live),
                name=f"send_init:{srank}",
            )
        self.wait()
        if self.seed_servers:
            self.async_send_param()
            self.wait()
        self._started = True

    def _register(self, param: np.ndarray, grad: np.ndarray) -> None:
        # Dtype-agnostic: shards are element ranges; transports move bytes.
        if not isinstance(param, np.ndarray) or not isinstance(grad, np.ndarray):
            raise TypeError("param and grad must be numpy arrays (host mirrors)")
        if param.ndim != 1 or grad.shape != param.shape or grad.dtype != param.dtype:
            raise ValueError("param and grad must be 1-D with equal shape and dtype")
        if not param.flags["C_CONTIGUOUS"] or not grad.flags["C_CONTIGUOUS"]:
            raise ValueError("param and grad must be contiguous (zero-copy rule)")
        if not self.codec.identity and param.dtype != np.float32:
            raise ValueError(
                f"codec {self.codec.name!r} quantizes float32 shards; got "
                f"dtype {param.dtype} (use codec='none' for other dtypes)"
            )
        self.param, self.grad = param, grad

    def reset(self, param: np.ndarray, grad: np.ndarray) -> None:
        """Retarget transfer buffers without re-announcing shards
        (reference pclient.lua:138-151).  Error-feedback residuals are
        keyed by shard, not by buffer — they survive the retarget."""
        if self.shards and len(param) != self.shards[-1].end:
            raise ValueError("reset buffers must keep the registered length")
        self._register(param, grad)

    # -- per-server transfer generators -------------------------------------

    def _send_grad(self, srank: int, shard: Shard):
        """Ship the grad slice, await the applied ack
        (reference pclient.lua:48-58).  Non-identity codecs encode into
        the per-server staging frame at ship time; the int8 residual is
        folded in and refreshed by the same pass."""
        view = self.grad[shard.offset : shard.end]
        payload = self._encode(view, self._grad_wire.get(srank),
                               residual=self._residual.get(srank))
        yield from aio_send(self.transport, payload, srank, tags.GRAD, live=self.live)
        yield from aio_recv(self.transport, srank, tags.GRAD_ACK, live=self.live)

    def _recv_param(self, srank: int, shard: Shard):
        """Request-to-read header, then receive into the param slice
        (reference pclient.lua:72-82) — via the wire staging frame when
        the codec is not identity."""
        yield from aio_send(
            self.transport, tags.EMPTY, srank, tags.PARAM_REQ, live=self.live
        )
        out = self.param[shard.offset : shard.end]
        wire = self._param_wire.get(srank)
        got = yield from aio_recv(
            self.transport, srank, tags.PARAM, live=self.live,
            out=out if wire is None else wire,
        )
        if got is not None and wire is not None:
            self.codec.decode_into(wire, out)

    def _send_param(self, srank: int, shard: Shard):
        """Whole-shard write, await ack (reference pclient.lua:60-70).
        No residual: parameter pushes (seeding / single-worker mirror)
        are one-shot state transfers, not an accumulating signal."""
        view = self.param[shard.offset : shard.end]
        payload = self._encode(view, self._param_wire.get(srank))
        yield from aio_send(self.transport, payload, srank, tags.PARAM_PUSH, live=self.live)
        yield from aio_recv(self.transport, srank, tags.PARAM_PUSH_ACK, live=self.live)

    def _encode(self, view: np.ndarray, wire: Optional[np.ndarray],
                residual: Optional[np.ndarray] = None) -> np.ndarray:
        """The slice itself for the identity codec (zero-copy send);
        otherwise the encoded frame in the per-server staging buffer."""
        if wire is None:
            return view
        self.codec.encode_into(view, wire, residual=residual)
        return wire

    def residual_norm(self) -> float:
        """L2 norm of the error-feedback residuals across shards — 0.0
        for residual-free codecs.  Observability/test hook."""
        if not self._residual:
            return 0.0
        return float(np.sqrt(sum(
            float(np.dot(r, r)) for r in self._residual.values()
        )))

    # -- public async API (reference pclient.lua:84-109) --------------------

    def _enqueue(self, srank: int, gen: Generator, name: str) -> None:
        queue = self._opq.setdefault(srank, deque())
        queue.append((gen, name))
        if not self._pump_live.get(srank, False):
            self._pump_live[srank] = True
            self._pump_task[srank] = None
            task = self.sched.spawn(self._pump(srank), name=f"pump:{srank}:{name}")
            self._pump_task[srank] = task

    def _pump(self, srank: int):
        """Run this server's queued ops strictly in order, renaming the
        task per dequeued op — a pump that kept its spawn-time name
        (e.g. ``pump:3:send_grad``) for life would misattribute every
        later op in scheduler error/debug output."""
        queue = self._opq[srank]
        try:
            while queue:
                op, opname = queue.popleft()
                task = self._pump_task.get(srank)
                if task is not None:
                    task.name = f"pump:{srank}:{opname}"
                yield from op
        finally:
            self._pump_live[srank] = False

    def async_send_grad(self) -> None:
        for srank, shard in zip(self.sranks, self.shards):
            self._enqueue(srank, self._send_grad(srank, shard), "send_grad")

    def async_recv_param(self) -> None:
        for srank, shard in zip(self.sranks, self.shards):
            self._enqueue(srank, self._recv_param(srank, shard), "recv_param")

    def async_send_param(self) -> None:
        for srank, shard in zip(self.sranks, self.shards):
            self._enqueue(srank, self._send_param(srank, shard), "send_param")

    def ping(self, n: int = 1) -> None:
        """Single-step I/O progress to overlap with compute
        (reference pclient.lua:131-136)."""
        for _ in range(n):
            self.sched.ping()

    def wait(self) -> None:
        self.sched.wait()

    # -- shutdown (reference pclient.lua:153-164) ---------------------------

    def stop(self) -> None:
        # Chained per server, so the stop cannot overtake in-flight ops
        # (the reference's drain-then-stop care, init.lua:50-58, README:71).
        for srank in self.sranks:
            self._enqueue(
                srank,
                aio_send(self.transport, tags.EMPTY, srank, tags.STOP, live=self.live),
                "send_stop",
            )
        self.wait()
        self.live.stop()
