"""ParamClient — shards the flat parameter vector across servers and
drives asynchronous shard transfers.

Rebuild of reference asyncsgd/pclient.lua.  The client registers two host
buffers (``param``, ``grad``) whose per-server contiguous slices are the
transfer units (numpy views = the reference's zero-copy storage-offset
views, pclient.lua:50-52).  Public surface mirrors pclient.lua:84-179:
``start``, ``async_send_grad``, ``async_recv_param``, ``async_send_param``,
``ping``, ``wait``, ``reset``, ``stop``.

The comm-aware optimizers (mpit_tpu.optim.downpour/easgd/shells) drive this
class through the ParamClientAPI protocol; device arrays stay in the
optimizer layer — the client only ever touches the registered host mirrors.

Wire codecs (beyond-reference — the EQuARX direction, PAPERS.md): the
client announces a codec in its INIT (``MPIT_PS_CODEC`` or the ``codec``
argument; mpit_tpu/comm/codec.py) and every GRAD/PARAM/PARAM_PUSH frame
to/from that server travels in that format.  For the lossy ``int8`` codec
the client holds one error-feedback residual per shard: the gradient
quantization error is added back into the next shipped gradient instead
of being lost, so DOWNPOUR/EASGD converge as if uncompressed (the shells
in mpit_tpu.optim need no changes — they keep writing fp32 into
``grad``; encode happens here at ship time).  ``codec='none'`` keeps
today's zero-copy slice sends byte-for-byte.

Fault tolerance (mpit_tpu.ft): an :class:`FTConfig` adds, each
independently opt-in,

- **heartbeats** — 16-byte HEARTBEAT beacons to every server, emitted
  opportunistically from ``ping``/``wait`` (the trainer's comm-overlap
  cadence) so liveness costs no dedicated thread;
- **op deadlines + retry** — every op encodes its frame *once* into a
  staged buffer with an int64 ``[epoch, seq]`` header (ft/wire.py) and
  resends those exact bytes on timeout under capped backoff.  Resending
  the staged frame — never re-encoding — is what keeps the int8
  error-feedback residual exact across retries: the residual was folded
  at the single encode, so a retry cannot double-count it.  Acks and
  PARAM replies echo the seq; stale echoes from earlier attempts are
  consumed and discarded, never mistaken for the awaited one.  An op
  that exhausts its attempts raises :class:`RetryExhausted` — loud
  failure, never a hang.

The header framing costs one staging copy per identity-codec frame, so
it is only active when deadlines are (``FTConfig.framed``); a default
FTConfig keeps the pre-FT zero-copy wire byte-for-byte.
"""

from __future__ import annotations

import time
from collections import deque
from typing import Deque, Dict, Generator, List, Optional, Tuple

import numpy as np

from mpit_tpu.aio import (
    EXEC,
    DeadlineExceeded,
    LiveFlag,
    Scheduler,
    aio_recv,
    aio_send,
    aio_sleep,
    deadline_at,
)
from mpit_tpu.comm import codec as codec_mod
from mpit_tpu.comm import pool as comm_pool
from mpit_tpu.comm.transport import Transport
from mpit_tpu.ft import (
    ACK_TIMING_WORDS,
    CHUNK_ACK_TIMING_WORDS,
    CHUNK_ACK_WORDS,
    FLAG_CHUNKED,
    FLAG_FRAMED,
    FLAG_HEARTBEAT,
    FLAG_STALENESS,
    FLAG_TIMING,
    HDR_BYTES,
    FTConfig,
    RetryExhausted,
    RetryPolicy,
    chunk_elems_for,
    chunk_hdr_bytes,
    chunk_reply_hdr_bytes,
    chunk_spans,
    chunk_stride,
    hdr_bytes,
    header_frame,
    init_v3,
    init_v5,
    pack_chunk_header,
    pack_header,
    pack_tx_stamp,
    pack_version,
    reply_hdr_bytes,
    timed_frame,
    unpack_chunk_reply,
    unpack_header,
    unpack_reply_stamps,
    unpack_version,
)
from mpit_tpu.obs import (
    NULL_SPAN,
    get_flight,
    get_recorder,
    obs_enabled,
    register_status_provider,
    registry_or_local,
)
from mpit_tpu.obs import clock as obs_clock
from mpit_tpu.ps import tags
from mpit_tpu.ps.sharding import Shard
from mpit_tpu.shardctl import shardmap as _shardmap
from mpit_tpu.shardctl import wire as _scwire
from mpit_tpu.utils.logging import get_logger


class ParamClient:
    def __init__(
        self,
        rank: int,
        server_ranks: list[int],
        transport: Transport,
        scheduler: Optional[Scheduler] = None,
        seed_servers: bool = False,
        codec: Optional[str] = None,
        ft: Optional[FTConfig] = None,
        shard_map: "Optional[_shardmap.ShardMap]" = None,
        shardctl: bool = False,
        controller_rank: Optional[int] = None,
        sc_shards_per_server: int = 1,
        layout: "Optional[List[Shard]]" = None,
    ):
        self.rank = rank
        self.sranks = list(server_ranks)
        self.transport = transport
        self.sched = scheduler or Scheduler()
        self.seed_servers = seed_servers  # this is the first client
        self.codec = codec_mod.get(codec)  # None/'' -> $MPIT_PS_CODEC
        self.ft = ft if ft is not None else FTConfig.from_env()
        # shardctl (mpit_tpu.shardctl): ops address *shards*, not
        # servers — the versioned map routes them, a NACK_MAP reply
        # re-routes them, and the controller's MAP_UPDATE broadcasts are
        # polled opportunistically.  Requires the FT framed machinery:
        # re-routing is retry, and at-most-once across owners is the
        # transferred dedup state.
        self._sc = bool(shardctl or shard_map is not None)
        self.smap = shard_map
        # Static weighted layout (mpit_tpu.lm flagship path): an explicit
        # contiguous cut — one Shard per server in rank order — that
        # replaces the equal-split default at start() WITHOUT turning on
        # shardctl.  The servers adopt whatever cut the first INIT
        # announces, so an uneven layout is purely a client-side choice;
        # crucially ``_sc`` stays False, so chunked streaming, staleness,
        # timing and the §13 agg tree all still negotiate on.  Every
        # client and reader of one gang must pass the identical layout
        # (servers reject mismatched re-announcements).
        self._layout = list(layout) if layout is not None else None
        if self._layout is not None:
            if self._sc:
                raise ValueError(
                    "layout= is the static weighted cut; it cannot combine "
                    "with shardctl/shard_map (which own placement already)"
                )
            if len(self._layout) != len(self.sranks):
                raise ValueError(
                    f"layout has {len(self._layout)} shards for "
                    f"{len(self.sranks)} servers (need exactly one each)"
                )
        self.controller_rank = controller_rank
        # Over-partitioning (§9.1): cut the vector into k shards per
        # launch-time server so elasticity has units to move — a gang
        # that cut one shard per server can widen only by whole-shard
        # handoff, never by sharing.
        self._sc_cut = max(int(sc_shards_per_server), 1)
        #: servers this incarnation has announced itself to (INIT); a
        #: map may route shards to ranks that joined after launch —
        #: first contact greets them (the lazy INIT v4, §9.1).
        self._sc_greeted: set = set()
        self._sc_flags = 0
        #: ranks that left on purpose (RETIRED broadcasts): dropped
        #: from heartbeat and STOP fan-out — a goodbye needs no goodbye.
        self._sc_retired: set = set()
        if self._sc and self.ft.op_deadline_s <= 0:
            raise ValueError(
                "shardctl needs op deadlines + retry (FTConfig."
                "op_deadline_s > 0): map re-routing rides the retry path"
            )
        self._retry = RetryPolicy(self.ft, key=rank)
        self.live = LiveFlag()
        self.log = get_logger("pclient", rank)
        self.param: Optional[np.ndarray] = None
        self.grad: Optional[np.ndarray] = None
        self.shards: List[Shard] = []
        self._started = False
        # Staleness telemetry (mpit_tpu.obs): with FLAG_STALENESS
        # negotiated, PARAM replies carry the served snapshot version and
        # the next GRAD echoes the version this client computed against —
        # the server's mpit_ps_grad_staleness histogram measures the gap.
        # Rides the framed wire (the header grows 16 -> 24 bytes);
        # shardctl's shard-addressed header has no version slot yet, so
        # the flag negotiates off there (docs/PROTOCOL.md §6.6).
        # Pipelined streaming (PROTOCOL.md §12): with FLAG_CHUNKED
        # negotiated, GRAD/PARAM/PARAM_PUSH bodies ship as K independent
        # chunk frames so encode, wire and apply overlap.  Rides the
        # framed wire; off under shardctl (shard ops re-route — a chunk
        # stream split across owners has no single admission point).
        self._chunked = self.ft.chunked and not self._sc
        # Staleness negotiates off under chunking: the chunked PARAM
        # reply header carries the version in its own word (§12.3), and
        # the 32-byte chunk header has no basis-echo slot.
        self._stale = (self.ft.stale_track and not self._sc
                       and not self._chunked)
        # Causal-timing telemetry (obs/clock, obs/causal): with
        # FLAG_TIMING negotiated, data frames carry a wall-µs send stamp
        # and every ack/reply a [t_tx_echo, t_recv, t_ack] tail — the
        # four NTP marks that feed the per-server clock-offset estimator
        # below.  Rides the framed wire like staleness; off under
        # shardctl (the 32-byte shard header has no stamp slot, §6.7).
        self._timing = self.ft.timing_track and not self._sc
        #: per-server param version this client last read (the basis the
        #: next gradient is computed against); 0 until the first read.
        self._basis: Dict[int, int] = {}
        # Per-server codec state: encode/decode staging sized to the wire
        # format (plus the FT header when framed), plus the int8
        # error-feedback residual (grad path only).  Data frames and
        # PARAM replies size their headers independently — the timing
        # tail makes a reply header wider than a data-frame header.
        self._hdr = (hdr_bytes(self._stale, self._timing)
                     if self.ft.framed else 0)
        self._hdr_rx = (reply_hdr_bytes(self._stale, self._timing)
                        if self.ft.framed else 0)
        # Chunked header sizes + the per-server chunk plan (built at
        # start(), when the dtype is known): spans [(lo, hi)], uniform
        # frame strides — the last chunk's frame is padded to the full
        # stride so both sides receive into fixed-size staging (§12.2).
        self._chdr = chunk_hdr_bytes(self._timing)
        self._chdr_rx = chunk_reply_hdr_bytes(self._timing)
        self._chunk_elems = 0
        self._chunk_spans: Dict[int, list] = {}
        self._chunk_stride: Dict[int, int] = {}
        self._chunk_stride_rx: Dict[int, int] = {}
        self._grad_wire: Dict[int, np.ndarray] = {}
        self._param_wire: Dict[int, np.ndarray] = {}
        self._param_rx: Dict[int, np.ndarray] = {}
        self._residual: Dict[int, np.ndarray] = {}
        self._ack_buf: Dict[int, np.ndarray] = {}
        #: per-server clock-offset estimator (fed by FLAG_TIMING tails;
        #: registered so trace exports / flight dumps embed the state).
        self._clock = obs_clock.ClockEstimator()
        obs_clock.register(f"client{rank}", self._clock)
        self._m_clock: Dict[int, object] = {}
        #: per-(server, tag) op sequence numbers (FT framing identity)
        self._seq: Dict[Tuple[int, int], int] = {}
        self._hb_last = 0.0
        self._hb_seq = 0
        # Observability (mpit_tpu.obs): protocol counters live in a real
        # registry always (they are load-bearing results — the global
        # one when obs is enabled, a private one otherwise), and every
        # PS op records a span through the recorder (the null recorder
        # when disabled — no clock reads, no allocation).
        self.metrics = registry_or_local()
        self._spans = get_recorder()
        self._m_retries = self.metrics.counter(
            "mpit_ft_retries_total", rank=rank)
        self._m_backoff = self.metrics.counter(
            "mpit_ft_backoff_seconds_total", rank=rank)
        self._m_hb = self.metrics.counter(
            "mpit_ft_heartbeats_sent_total", rank=rank)
        self._m_nacks = self.metrics.counter(
            "mpit_shardctl_nacks_seen_total", rank=rank)
        self._m_reroutes = self.metrics.counter(
            "mpit_shardctl_reroutes_total", rank=rank)
        self._m_mapver = self.metrics.gauge(
            "mpit_shardctl_map_version", rank=rank)
        # Flight recorder + live introspection (obs/flight, obs/statusd):
        # the retry-exhaustion paths dump the recent-event ring so a
        # failed op leaves a postmortem; the status provider feeds the
        # /status endpoint when one is serving.  Both are null/no-op when
        # obs is disabled.
        self._flight = get_flight()
        if obs_enabled():
            register_status_provider(f"client{rank}", self._status_section)
        # shardctl per-shard state: encode staging + residual keyed by
        # shard_id (stable across migrations — placement moves, the cut
        # never does), per-(shard, tag) seq streams, one global FIFO op
        # pump (ops to different owners of one map serialize, so the
        # shared reply channels never interleave two ops' echoes).
        self._sc_wire: Dict[int, np.ndarray] = {}
        self._sc_residual: Dict[int, np.ndarray] = {}
        self._sc_seq: Dict[Tuple[int, int], int] = {}
        self._scq: Deque[Tuple[Generator, str]] = deque()
        self._sc_pump_live = False
        self._sc_pump_task: Optional[object] = None
        # Per-server FIFO op chains: ops addressed to the same server run in
        # issue order (a send_grad's ack completes before a later param
        # request is sent), while different servers stay fully concurrent.
        # Strictly stronger than the reference (which relies on coroutine
        # spawn order for freshness, pclient.lua:84-109) — this removes the
        # stale-own-write race without giving up cross-server overlap.
        self._opq: Dict[int, Deque[Tuple[Generator, str]]] = {}
        self._pump_live: Dict[int, bool] = {}
        self._pump_task: Dict[int, Optional[object]] = {}

    # -- lifecycle ----------------------------------------------------------

    def start(self, param: np.ndarray, grad: np.ndarray) -> None:
        """Announce shard layout + codec to every server; the first client
        seeds the servers' shards from ``param`` (reference
        pclient.lua:111-129).  INIT v2: int64 [offset, size, codec_id];
        with any FT feature active, INIT v3 adds [epoch, flags]; under
        shardctl, INIT v4 announces the whole versioned shard map."""
        self._register(param, grad)
        if self._sc:
            self._sc_start(param)
            return
        # Placement is a ShardMap even on the static path: version-0,
        # one equal shard per server in rank order — byte-identical to
        # the raw shard_layout() cut this call site used to make.  An
        # explicit ``layout=`` swaps in its weighted cut here; everything
        # downstream (chunk plans, codec staging, INIT bodies) is already
        # per-(srank, shard) and never assumes the shards are equal.
        if self._layout is not None:
            if self._layout[-1].end != len(param):
                raise ValueError(
                    f"layout covers [0, {self._layout[-1].end}) but the "
                    f"registered vector has {len(param)} elements"
                )
            self.smap = _shardmap.ShardMap.from_shards(self._layout,
                                                       self.sranks)
        else:
            self.smap = _shardmap.ShardMap.initial(len(param), self.sranks)
        self.shards = [e.shard for e in self.smap.entries]
        flags = (FLAG_FRAMED if self.ft.framed else 0) | (
            FLAG_HEARTBEAT if self.ft.heartbeat_s > 0 else 0
        ) | (FLAG_STALENESS if self._stale else 0) | (
            FLAG_TIMING if self._timing else 0) | (
            FLAG_CHUNKED if self._chunked else 0)
        if self._chunked:
            self._chunk_elems = chunk_elems_for(self.ft.chunk_bytes,
                                                param.dtype.itemsize)
        for srank, shard in zip(self.sranks, self.shards):
            body = (self.codec.wire_nbytes(shard.size)
                    if not self.codec.identity
                    else shard.size * param.dtype.itemsize)
            if self._chunked:
                # Streamed staging (§12.2): K uniform [chunk hdr | body]
                # frames, one contiguous buffer per direction.  Encode
                # lands each chunk behind its own header, so a retry
                # resends any chunk's exact bytes zero-copy, and the
                # error-feedback residual (whole-shard, sliced per
                # chunk) folds exactly once per block.
                spans = chunk_spans(shard.size, self._chunk_elems)
                full = min(self._chunk_elems, shard.size)
                cbody = (self.codec.wire_nbytes(full)
                         if not self.codec.identity
                         else full * param.dtype.itemsize)
                stride = chunk_stride(self._chdr, cbody)
                self._chunk_spans[srank] = spans
                self._chunk_stride[srank] = stride
                self._chunk_stride_rx[srank] = chunk_stride(self._chdr_rx,
                                                            cbody)
                self._grad_wire[srank] = np.zeros(stride * len(spans),
                                                  np.uint8)
                self._param_wire[srank] = np.zeros(stride * len(spans),
                                                   np.uint8)
                if self.codec.uses_residual:
                    self._residual[srank] = np.zeros(shard.size, np.float32)
                # One reusable reply-frame buffer: chunked PARAM replies
                # are uniform-size messages received one at a time.
                self._param_rx[srank] = np.zeros(self._chunk_stride_rx[srank],
                                                 np.uint8)
                self._ack_buf[srank] = np.zeros(
                    CHUNK_ACK_TIMING_WORDS if self._timing
                    else CHUNK_ACK_WORDS, np.int64)
            elif not self.codec.identity:
                self._grad_wire[srank] = np.zeros(self._hdr + body, np.uint8)
                self._param_wire[srank] = np.zeros(self._hdr + body, np.uint8)
                if self.codec.uses_residual:
                    self._residual[srank] = np.zeros(shard.size, np.float32)
            elif self._hdr:
                # Identity codec under FT framing: raw dtype bytes behind
                # the header (the one staging copy framing costs).
                self._grad_wire[srank] = np.zeros(self._hdr + body, np.uint8)
                self._param_wire[srank] = np.zeros(self._hdr + body, np.uint8)
            if self._hdr and not self._chunked:
                # PARAM replies carry the (possibly wider) reply header —
                # the timing tail rides there — so reads stage separately
                # from the identically-bodied push frames.
                self._param_rx[srank] = np.zeros(self._hdr_rx + body,
                                                 np.uint8)
                self._ack_buf[srank] = np.zeros(
                    ACK_TIMING_WORDS if self._timing else 2, np.int64)
            if self._chunked:
                cinfo = init_v5(shard.offset, shard.size,
                                self.codec.wire_id, self.ft.epoch, flags,
                                self._chunk_elems)
            elif self.ft.active:
                cinfo = init_v3(shard.offset, shard.size,
                                self.codec.wire_id, self.ft.epoch, flags)
            else:
                cinfo = np.asarray(
                    [shard.offset, shard.size, self.codec.wire_id],
                    dtype=np.int64,
                )
            self.sched.spawn(
                aio_send(self.transport, cinfo, srank, tags.INIT,
                         live=self.live, deadline=self._op_deadline()),
                name=f"send_init:{srank}",
            )
        self.wait()
        # Beat from the moment the servers know this client's epoch —
        # seeding a large shard below can outlast any reasonable lease
        # TTL, and the wait() loop is what pumps the beacons out.
        self._started = True
        self._hb_last = 0.0
        if self.seed_servers:
            self.async_send_param()
            self.wait()

    def _register(self, param: np.ndarray, grad: np.ndarray) -> None:
        # Dtype-agnostic: shards are element ranges; transports move bytes.
        if not isinstance(param, np.ndarray) or not isinstance(grad, np.ndarray):
            raise TypeError("param and grad must be numpy arrays (host mirrors)")
        if param.ndim != 1 or grad.shape != param.shape or grad.dtype != param.dtype:
            raise ValueError("param and grad must be 1-D with equal shape and dtype")
        if not param.flags["C_CONTIGUOUS"] or not grad.flags["C_CONTIGUOUS"]:
            raise ValueError("param and grad must be contiguous (zero-copy rule)")
        if not self.codec.identity and param.dtype != np.float32:
            raise ValueError(
                f"codec {self.codec.name!r} quantizes float32 shards; got "
                f"dtype {param.dtype} (use codec='none' for other dtypes)"
            )
        self.param, self.grad = param, grad

    def reset(self, param: np.ndarray, grad: np.ndarray) -> None:
        """Retarget transfer buffers without re-announcing shards
        (reference pclient.lua:138-151).  Error-feedback residuals are
        keyed by shard, not by buffer — they survive the retarget."""
        if self.shards and len(param) != self.shards[-1].end:
            raise ValueError("reset buffers must keep the registered length")
        self._register(param, grad)

    # -- live introspection (obs/statusd) ------------------------------------

    def _status_section(self) -> Dict[str, object]:
        """This client's /status section: identity, negotiation posture,
        per-server basis versions and the pending op-pump task table.
        Runs on the statusd thread — reads plain attributes only."""
        try:
            tasks = [t.name for t in list(self.sched.queue)]
        except RuntimeError:  # deque mutated mid-snapshot; next poll wins
            tasks = ["<scheduler busy>"]
        return {
            "role": "client",
            "rank": self.rank,
            "servers": self.sranks,
            "codec": self.codec.name,
            "epoch": self.ft.epoch,
            "framed": self.ft.framed,
            "staleness": self._stale,
            "chunked": self._chunked,
            "basis_versions": {str(s): v for s, v in self._basis.items()},
            "map_version": getattr(self.smap, "version", None),
            "retries": self.retries,
            "tasks": tasks,
        }

    def _flight_dump(self, reason: str, **fields) -> None:
        """Record + dump the flight ring on a terminal failure (no-op
        when obs is off).  The dump rides next to the raised exception:
        the exception names the op, the dump shows the ring of events
        that led to it plus the live task table."""
        self._flight.record(reason, rank=self.rank, **fields)
        try:
            tasks = [(t.name, t.state) for t in list(self.sched.queue)]
        except RuntimeError:
            tasks = None
        path = self._flight.dump(reason, tasks=tasks, **fields)
        if path:
            self.log.warning("%s: flight recorder dumped to %s", reason, path)

    # -- observability back-compat reads ------------------------------------

    @property
    def retries(self) -> int:
        """Resends performed (registry-backed; observability/test hook)."""
        return int(self._m_retries.value)

    @property
    def heartbeats_sent(self) -> int:
        return int(self._m_hb.value)

    # -- FT plumbing ---------------------------------------------------------

    def _op_deadline(self) -> Optional[float]:
        """Absolute deadline for one attempt (None when deadlines off)."""
        return deadline_at(self.ft.deadline_s)

    def _next_seq(self, srank: int, tag: int) -> int:
        seq = self._seq.get((srank, tag), 0) + 1
        self._seq[(srank, tag)] = seq
        return seq

    def _op_with_retry(self, srank: int, payload: np.ndarray, tag: int,
                       ack_tag: int, seq: int, what: str, span=NULL_SPAN):
        """Send the staged frame, await its seq-matched ack; resend the
        same bytes on deadline under the backoff policy.  Exhaustion
        raises :class:`RetryExhausted` — the never-hang guarantee.
        ``span`` (an obs op span) gets per-attempt phase marks and the
        terminal outcome, so a retried op is attributable in the trace."""
        last: Optional[BaseException] = None
        for attempt in range(self._retry.attempts):
            if attempt:
                backoff = self._retry.backoff_s(attempt)
                self._m_retries.inc()
                self._m_backoff.inc(backoff)
                span.mark("backoff")
                span.note(retries=attempt)
                self.log.debug("%s: retry %d after %r", what, attempt, last)
                if not (yield from aio_sleep(backoff, live=self.live)):
                    span.end("aborted")
                    return None
            deadline = self._op_deadline()
            try:
                span.mark("send")
                if self._timing:
                    # Re-stamped per attempt; the server echoes whichever
                    # stamp rode the frame it saw, so the NTP pairing is
                    # exact even when acks and resends cross.
                    pack_tx_stamp(payload, self._hdr, obs_clock.wall_us())
                yield from aio_send(self.transport, payload, srank, tag,
                                    live=self.live, deadline=deadline)
                span.mark("ack")
                got = yield from self._await_ack(srank, ack_tag, seq,
                                                 deadline, span=span)
                if got is not None or not self.live.io:
                    span.end("ok" if got is not None else "aborted")
                    return got
            except DeadlineExceeded as exc:
                last = exc
        span.end("exhausted")
        self._flight_dump("retry_exhausted", what=what,
                          attempts=self._retry.attempts, peer=srank)
        raise RetryExhausted(what, self._retry.attempts, last)

    def _feed_clock(self, srank: int, t_tx: int, t_recv: int,
                    t_ack: int) -> None:
        """One FLAG_TIMING exchange into the per-server estimator
        (t4 = now on this client's time base); accepted samples surface
        on the mpit_clock_offset_us gauge."""
        if self._clock.add_exchange(srank, t_tx, t_recv, t_ack,
                                    obs_clock.wall_us()):
            gauge = self._m_clock.get(srank)
            if gauge is None:
                gauge = self.metrics.gauge("mpit_clock_offset_us",
                                           rank=self.rank, peer=srank)
                self._m_clock[srank] = gauge
            gauge.set(self._clock.peer(srank).offset_us)

    def _await_ack(self, srank: int, ack_tag: int, seq: int,
                   deadline: Optional[float], span=NULL_SPAN):
        """Receive acks until the one echoing ``seq`` for the current
        epoch arrives.  Stale echoes (an earlier attempt's duplicate, a
        previous incarnation's leftovers) are consumed and dropped — on
        the attempt's unchanged deadline, so a trickle of stale acks
        cannot extend it.  Under FLAG_TIMING every current-epoch ack —
        matched or stale — is a complete NTP exchange and feeds the
        clock estimator; the matched one also lands its server stamps
        on the op span, so the trace carries both halves' marks."""
        buf = self._ack_buf[srank]
        while True:
            got = yield from aio_recv(self.transport, srank, ack_tag,
                                      live=self.live, out=buf,
                                      deadline=deadline)
            if got is None:
                return None
            epoch, aseq = int(buf[0]), int(buf[1])
            if self._timing and epoch == self.ft.epoch:
                self._feed_clock(srank, int(buf[2]), int(buf[3]),
                                 int(buf[4]))
            if epoch == self.ft.epoch and aseq == seq:
                if self._timing:
                    span.note(tx_us=int(buf[2]), srv_recv_us=int(buf[3]),
                              srv_ack_us=int(buf[4]))
                return got
            if epoch > self.ft.epoch or (epoch == self.ft.epoch and aseq > seq):
                raise RuntimeError(
                    f"ack from server {srank} is ahead of the op stream: "
                    f"got (epoch={epoch}, seq={aseq}), awaiting "
                    f"(epoch={self.ft.epoch}, seq={seq})"
                )

    def _maybe_heartbeat(self) -> None:
        """Emit a HEARTBEAT to every server when the interval elapsed.
        Piggybacks on ping()/wait() — the cadence the trainers already
        drive for comm overlap — so liveness needs no thread.  Sends are
        fire-and-forget with a bounded deadline: a dead server must not
        accumulate unbounded heartbeat tasks in the queue."""
        hb = self.ft.heartbeat_s
        if hb <= 0 or not self._started or not self.live.io:
            return
        now = time.monotonic()
        if now - self._hb_last < hb:
            return
        self._hb_last = now
        self._hb_seq += 1
        # Timing pairs stamp the beat: the server echoes the stamp back
        # with its own receive/send marks (HEARTBEAT_ECHO), so the clock
        # estimator refreshes from the heartbeat stream even when no op
        # is in flight.
        payload = (timed_frame(self.ft.epoch, self._hb_seq,
                               obs_clock.wall_us())
                   if self._timing
                   else header_frame(self.ft.epoch, self._hb_seq))
        self._m_hb.inc()
        targets = self._sc_beat_targets() if self._sc else self.sranks
        for srank in targets:
            self.sched.spawn(
                self._hb_send(payload, srank), name=f"heartbeat:{srank}"
            )

    def _hb_send(self, payload: np.ndarray, srank: int):
        try:
            yield from aio_send(
                self.transport, payload, srank, tags.HEARTBEAT,
                live=self.live, deadline=deadline_at(4 * self.ft.heartbeat_s),
            )
        except DeadlineExceeded:
            pass  # liveness is best-effort; the next beat tries again

    def _drain_clock_echoes(self) -> None:
        """Consume pending HEARTBEAT_ECHO replies (probed, never
        blocking — the _sc_poll_map pattern): each carries a complete
        [t_tx_echo, t_recv, t_ack] exchange, refreshing the per-server
        clock offset while the trainer is compute-bound between ops.  A
        lost or late echo costs nothing — the next beat makes another."""
        if not self._timing or not self._started:
            return
        for srank in self.sranks:
            while self.transport.iprobe(srank, tags.HEARTBEAT_ECHO):
                handle = self.transport.irecv(srank, tags.HEARTBEAT_ECHO)
                while not self.transport.test(handle):
                    pass  # iprobe saw a fully-assembled message
                tail = np.frombuffer(
                    bytes(self.transport.payload(handle)), np.int64)
                if (len(tail) >= ACK_TIMING_WORDS
                        and int(tail[0]) == self.ft.epoch):
                    self._feed_clock(srank, int(tail[2]), int(tail[3]),
                                     int(tail[4]))

    # -- shardctl: shard-addressed ops over the versioned map ----------------

    def _sc_start(self, param: np.ndarray) -> None:
        """INIT v4 to every server: codec + FT posture + the whole map.
        Per-shard staging is keyed by shard_id — placement moves, the
        cut never does, so buffers survive any number of migrations."""
        if self.smap is None:
            owners = [s for s in self.sranks for _ in range(self._sc_cut)]
            self.smap = _shardmap.ShardMap.initial(len(param), owners)
        if self.smap.plong != len(param):
            raise ValueError(
                f"shard map covers {self.smap.plong} elements but the "
                f"registered vector has {len(param)}")
        self.shards = [e.shard for e in self.smap.entries]
        self._m_mapver.set(self.smap.version)
        flags = FLAG_FRAMED | _scwire.FLAG_SHARDCTL | (
            FLAG_HEARTBEAT if self.ft.heartbeat_s > 0 else 0
        )
        self._sc_flags = flags
        self._sc_greeted = set(self.sranks)
        for e in self.smap.entries:
            if self.codec.identity:
                nbytes = e.shard.size * param.dtype.itemsize
            else:
                nbytes = self.codec.wire_nbytes(e.shard.size)
                if self.codec.uses_residual:
                    self._sc_residual[e.shard_id] = np.zeros(
                        e.shard.size, np.float32)
            self._sc_wire[e.shard_id] = np.zeros(
                _scwire.SC_HDR_BYTES + nbytes, np.uint8)
        cinfo = _scwire.init_v4(self.codec.wire_id, self.ft.epoch,
                                flags, self.smap)
        for srank in self.sranks:
            self.sched.spawn(
                aio_send(self.transport, cinfo, srank, tags.INIT,
                         live=self.live, deadline=self._op_deadline()),
                name=f"send_init:{srank}",
            )
        self.wait()
        self._started = True
        self._hb_last = 0.0
        if self.controller_rank is not None and self.seed_servers:
            # Hand the controller its first map (it starts blank so it
            # never has to know plong before the clients do).
            self.sched.spawn(
                aio_send(self.transport,
                         _scwire.map_update(_scwire.INSTALL, -1, self.rank,
                                            self.smap),
                         self.controller_rank, tags.MAP_UPDATE,
                         live=self.live, deadline=self._op_deadline()),
                name="send_map:controller",
            )
            self.wait()
        if self.seed_servers:
            self.async_send_param()
            self.wait()

    def _sc_next_seq(self, sid: int, tag: int) -> int:
        seq = self._sc_seq.get((sid, tag), 0) + 1
        self._sc_seq[(sid, tag)] = seq
        return seq

    def _sc_install_wire(self, body) -> bool:
        """Adopt a serialized map if it is newer than ours."""
        m = _shardmap.ShardMap.from_wire(np.frombuffer(bytes(body), np.int64))
        if self.smap is None or m.version > self.smap.version:
            self.smap = m
            self._m_mapver.set(m.version)
            return True
        return False

    def _sc_poll_map(self) -> None:
        """Drain any MAP_UPDATE broadcasts from the controller (probed,
        never blocking): proactive re-routing, and the only way to learn
        a failover map while the old owner is dead air."""
        if not self._sc or self.controller_rank is None:
            return
        while self.transport.iprobe(self.controller_rank, tags.MAP_UPDATE):
            handle = self.transport.irecv(self.controller_rank,
                                          tags.MAP_UPDATE)
            while not self.transport.test(handle):
                pass  # iprobe saw a fully-assembled message
            kind, _sid, peer, m = _scwire.parse_map_update(
                bytes(self.transport.payload(handle)))
            if kind == _scwire.RETIRED:
                # A goodbye, not a crash: drop the rank from beat/STOP
                # fan-out.  Its shards already drained (the map carried
                # here no longer routes anything to it).
                self._sc_retired.add(peer)
            if self.smap is None or m.version > self.smap.version:
                self.smap = m
                self._m_mapver.set(m.version)

    def _sc_write_op(self, sid: int, tag: int, ack_tag: int, what: str):
        """One shard write (GRAD / PARAM_PUSH): encode once into the
        shard's staging frame, then run the attempt loop.  The residual
        folds at this single encode; re-routes resend the same bytes."""
        shard = self.smap.entry(sid).shard
        span = self._spans.op(what, peer=sid, side="client",
                              rank=self.rank)
        view = (self.grad if tag == tags.GRAD else
                self.param)[shard.offset: shard.end]
        wire = self._sc_wire[sid]
        span.mark("encode")
        body = wire[_scwire.SC_HDR_BYTES:]
        if self.codec.identity:
            body[:] = view.view(np.uint8)
        else:
            residual = (self._sc_residual.get(sid)
                        if tag == tags.GRAD else None)
            self.codec.encode_into(view, body, residual=residual)
        seq = self._sc_next_seq(sid, tag)
        span.note(epoch=self.ft.epoch, seq=seq, shard=sid)
        yield from self._sc_attempts(sid, seq, wire, tag, ack_tag,
                                     out=None, span=span,
                                     what=f"{what} for shard {sid}")

    def _sc_read_op(self, sid: int):
        """One shard read: request-by-header, decode the OK reply's
        snapshot frame into the param slice."""
        shard = self.smap.entry(sid).shard
        span = self._spans.op("PARAM", peer=sid, side="client",
                              rank=self.rank)
        out = self.param[shard.offset: shard.end]
        seq = self._sc_next_seq(sid, tags.PARAM_REQ)
        span.note(epoch=self.ft.epoch, seq=seq, shard=sid)
        yield from self._sc_attempts(sid, seq, None, tags.PARAM_REQ,
                                     tags.PARAM, out=out, span=span,
                                     what=f"PARAM read for shard {sid}")

    def _sc_attempts(self, sid: int, seq: int, wire: Optional[np.ndarray],
                     tag: int, ack_tag: int, out: Optional[np.ndarray],
                     span, what: str):
        """The shardctl attempt loop: send to the shard's current owner,
        await the status reply; DeadlineExceeded retries under backoff
        (polling controller broadcasts), NACK_MAP installs the carried
        map and re-routes, BUSY backs off through a migration window.
        A re-route to a *different* owner resets the attempt budget —
        monotone map versions bound the total work.  Exhaustion raises
        :class:`RetryExhausted`; the never-hang guarantee holds."""
        attempt = 0
        nacks = 0
        max_nacks = 16 * (self._retry.attempts + 1)
        last: Optional[BaseException] = None
        while self.live.io:
            owner = self.smap.owner(sid)
            if owner not in self._sc_greeted:
                # First contact with a scaled-up server (§9.1): announce
                # this incarnation before the op — the lazy INIT v4 that
                # makes late membership transparent to the op stream.
                yield from self._sc_greet(owner)
            if wire is not None:
                _scwire.pack_sc_header(wire, self.ft.epoch, seq,
                                       self.smap.version, sid)
                payload: np.ndarray = wire
            else:
                payload = _scwire.sc_header(self.ft.epoch, seq,
                                            self.smap.version, sid)
            deadline = self._op_deadline()
            try:
                span.mark("send")
                yield from aio_send(self.transport, payload, owner, tag,
                                    live=self.live, deadline=deadline)
                span.mark("recv" if out is not None else "ack")
                while True:
                    raw = yield from aio_recv(self.transport, owner, ack_tag,
                                              live=self.live,
                                              deadline=deadline)
                    if raw is None:
                        span.end("aborted")
                        return None
                    epoch, aseq, status, rsid, body = _scwire.parse_reply(
                        bytes(raw))
                    if epoch == self.ft.epoch and rsid == sid and aseq == seq:
                        break
                    if epoch > self.ft.epoch or (
                            epoch == self.ft.epoch and rsid == sid
                            and aseq > seq):
                        raise RuntimeError(
                            f"reply from server {owner} is ahead of the op "
                            f"stream: got (epoch={epoch}, seq={aseq}, "
                            f"shard={rsid}), awaiting (epoch="
                            f"{self.ft.epoch}, seq={seq}, shard={sid})")
                    # stale echo (earlier attempt / other shard): drop on
                    # the unchanged attempt deadline
            except DeadlineExceeded as exc:
                last = exc
                attempt += 1
                if attempt >= self._retry.attempts:
                    span.end("exhausted")
                    self._flight_dump("retry_exhausted", what=what,
                                      attempts=self._retry.attempts,
                                      shard=sid)
                    raise RetryExhausted(what, self._retry.attempts, last)
                backoff = self._retry.backoff_s(attempt)
                self._m_retries.inc()
                self._m_backoff.inc(backoff)
                span.mark("backoff")
                span.note(retries=attempt)
                if not (yield from aio_sleep(backoff, live=self.live)):
                    span.end("aborted")
                    return None
                self._sc_poll_map()
                if self.smap.owner(sid) != owner:
                    # A broadcast re-routed us (failover away from a dead
                    # owner): the new destination gets a fresh budget.
                    self._m_reroutes.inc()
                    span.mark("reroute")
                    attempt = 0
                continue
            if status == _scwire.OK:
                if out is not None:
                    span.mark("decode")
                    self._sc_decode(body, out)
                span.end("ok")
                return True
            # NACK_MAP / BUSY — both may carry the server's newer map.
            nacks += 1
            self._m_nacks.inc()
            span.mark("nack")
            if nacks > max_nacks:
                span.end("exhausted")
                self._flight_dump("retry_exhausted",
                                  what=f"{what} (map churn)", nacks=nacks,
                                  shard=sid)
                raise RetryExhausted(f"{what} (map churn)", nacks, last)
            if len(body) and self._sc_install_wire(body) \
                    and self.smap.owner(sid) != owner:
                self._m_reroutes.inc()
                span.mark("reroute")
                attempt = 0
            if status == _scwire.BUSY:
                # Mid-migration freeze window: give the handoff a beat.
                if not (yield from aio_sleep(self._retry.backoff_s(1),
                                             live=self.live)):
                    span.end("aborted")
                    return None
                self._sc_poll_map()
        span.end("aborted")
        return None

    def _sc_greet(self, owner: int):
        """Announce this client (INIT v4 with the current map) to a
        server that joined after launch.  The server's listener
        negotiates and spawns services before it sees our first op —
        both tags are FIFO per channel, so ordering is the transport's."""
        cinfo = _scwire.init_v4(self.codec.wire_id, self.ft.epoch,
                                self._sc_flags, self.smap)
        yield from aio_send(self.transport, cinfo, owner, tags.INIT,
                            live=self.live, deadline=self._op_deadline())
        self._sc_greeted.add(owner)

    def _sc_beat_targets(self) -> "List[int]":
        """Liveness fan-out under shardctl: everyone this incarnation
        announced itself to, minus clean departures."""
        return sorted(self._sc_greeted - self._sc_retired)

    def _sc_decode(self, body, out: np.ndarray) -> None:
        frame = np.frombuffer(bytes(body), np.uint8)
        if self.codec.identity:
            out.view(np.uint8)[:] = frame
        else:
            self.codec.decode_into(frame, out)

    def _sc_enqueue(self, gen: Generator, name: str) -> None:
        self._scq.append((gen, name))
        if not self._sc_pump_live:
            self._sc_pump_live = True
            self._sc_pump_task = None
            task = self.sched.spawn(self._sc_pump(), name=f"scpump:{name}")
            self._sc_pump_task = task

    def _sc_pump(self):
        """One global FIFO for shardctl ops: strictly serialized, so the
        per-(owner, tag) reply channels never interleave two in-flight
        ops' echoes even when one server owns several shards.  (The
        static path keeps its per-server pumps and full cross-server
        overlap — serialization is the price of re-routable ops, paid
        only in shardctl mode.)"""
        queue = self._scq
        try:
            while queue:
                op, opname = queue.popleft()
                task = self._sc_pump_task
                if task is not None:
                    task.name = f"scpump:{opname}"
                yield from op
        finally:
            self._sc_pump_live = False

    # -- per-server transfer generators -------------------------------------

    def _send_grad(self, srank: int, shard: Shard):
        """Ship the grad slice, await the applied ack
        (reference pclient.lua:48-58).  Non-identity codecs encode into
        the per-server staging frame at ship time; the int8 residual is
        folded in and refreshed by the same pass.  Framed mode stamps
        [epoch, seq] and retries the staged bytes on deadline."""
        if self._chunked:
            yield from self._chunked_write(srank, shard, tags.GRAD,
                                           tags.GRAD_ACK, "GRAD")
            return
        span = self._spans.op("GRAD", peer=srank, side="client",
                              rank=self.rank)
        view = self.grad[shard.offset : shard.end]
        wire = self._grad_wire.get(srank)
        span.mark("encode")
        payload = self._encode(view, wire, residual=self._residual.get(srank))
        if not self.ft.framed:
            span.mark("send")
            yield from aio_send(self.transport, payload, srank, tags.GRAD,
                                live=self.live, deadline=self._op_deadline())
            span.mark("ack")
            yield from aio_recv(self.transport, srank, tags.GRAD_ACK,
                                live=self.live, deadline=self._op_deadline())
            span.end("ok")
            return
        seq = self._next_seq(srank, tags.GRAD)
        span.note(epoch=self.ft.epoch, seq=seq)
        pack_header(payload, self.ft.epoch, seq)
        if self._stale:
            # Echo the param version this gradient was computed against
            # (the last PARAM read from this server); the server measures
            # the staleness gap at apply time.
            basis = self._basis.get(srank, 0)
            pack_version(payload, basis)
            span.note(basis=basis)
        yield from self._op_with_retry(
            srank, payload, tags.GRAD, tags.GRAD_ACK, seq,
            f"GRAD to server {srank}", span=span,
        )

    def _recv_param(self, srank: int, shard: Shard):
        """Request-to-read header, then receive into the param slice
        (reference pclient.lua:72-82) — via the wire staging frame when
        the codec is not identity.  Framed mode seq-tags the request and
        discards snapshot frames that echo an earlier request."""
        if self._chunked:
            yield from self._chunked_read(srank, shard)
            return
        span = self._spans.op("PARAM", peer=srank, side="client",
                              rank=self.rank)
        out = self.param[shard.offset : shard.end]
        wire = self._param_wire.get(srank)
        if not self.ft.framed:
            span.mark("send")
            yield from aio_send(self.transport, tags.EMPTY, srank,
                                tags.PARAM_REQ, live=self.live,
                                deadline=self._op_deadline())
            span.mark("recv")
            got = yield from aio_recv(
                self.transport, srank, tags.PARAM, live=self.live,
                out=out if wire is None else wire,
                deadline=self._op_deadline(),
            )
            if got is not None and wire is not None:
                span.mark("decode")
                self.codec.decode_into(wire, out)
            span.end("ok" if got is not None else "aborted")
            return
        seq = self._next_seq(srank, tags.PARAM_REQ)
        span.note(epoch=self.ft.epoch, seq=seq)
        wire = self._param_rx[srank]
        req = (timed_frame(self.ft.epoch, seq, 0) if self._timing
               else header_frame(self.ft.epoch, seq))
        last: Optional[BaseException] = None
        for attempt in range(self._retry.attempts):
            if attempt:
                backoff = self._retry.backoff_s(attempt)
                self._m_retries.inc()
                self._m_backoff.inc(backoff)
                span.mark("backoff")
                span.note(retries=attempt)
                if not (yield from aio_sleep(backoff, live=self.live)):
                    span.end("aborted")
                    return
            deadline = self._op_deadline()
            try:
                span.mark("send")
                if self._timing:
                    req[2] = obs_clock.wall_us()  # re-stamped per attempt
                yield from aio_send(self.transport, req, srank,
                                    tags.PARAM_REQ, live=self.live,
                                    deadline=deadline)
                span.mark("recv")
                while True:
                    got = yield from aio_recv(
                        self.transport, srank, tags.PARAM, live=self.live,
                        out=wire, deadline=deadline,
                    )
                    if got is None:
                        span.end("aborted")
                        return
                    epoch, aseq = unpack_header(wire)
                    if self._timing and epoch == self.ft.epoch:
                        # Any current-epoch reply — matched or a stale
                        # duplicate — is a complete NTP exchange.
                        t_tx, t_recv, t_ack = unpack_reply_stamps(
                            wire, self._hdr_rx - 24)
                        self._feed_clock(srank, t_tx, t_recv, t_ack)
                    if epoch == self.ft.epoch and aseq == seq:
                        if self._stale:
                            # The reply's version word is the basis the
                            # next gradient to this server will echo.
                            self._basis[srank] = unpack_version(wire)
                        if self._timing:
                            span.note(tx_us=t_tx, srv_recv_us=t_recv,
                                      srv_ack_us=t_ack)
                        span.mark("decode")
                        self._decode_framed(wire, out)
                        span.end("ok")
                        return
                    # stale snapshot (earlier request's duplicate): drop
            except DeadlineExceeded as exc:
                last = exc
        span.end("exhausted")
        self._flight_dump("retry_exhausted",
                          what=f"PARAM read from server {srank}",
                          attempts=self._retry.attempts, peer=srank)
        raise RetryExhausted(
            f"PARAM read from server {srank}", self._retry.attempts, last)

    def _send_param(self, srank: int, shard: Shard):
        """Whole-shard write, await ack (reference pclient.lua:60-70).
        No residual: parameter pushes (seeding / single-worker mirror)
        are one-shot state transfers, not an accumulating signal."""
        if self._chunked:
            yield from self._chunked_write(srank, shard, tags.PARAM_PUSH,
                                           tags.PARAM_PUSH_ACK, "PARAM_PUSH")
            return
        span = self._spans.op("PARAM_PUSH", peer=srank, side="client",
                              rank=self.rank)
        view = self.param[shard.offset : shard.end]
        wire = self._param_wire.get(srank)
        span.mark("encode")
        payload = self._encode(view, wire)
        if not self.ft.framed:
            span.mark("send")
            yield from aio_send(self.transport, payload, srank,
                                tags.PARAM_PUSH, live=self.live,
                                deadline=self._op_deadline())
            span.mark("ack")
            yield from aio_recv(self.transport, srank, tags.PARAM_PUSH_ACK,
                                live=self.live, deadline=self._op_deadline())
            span.end("ok")
            return
        seq = self._next_seq(srank, tags.PARAM_PUSH)
        span.note(epoch=self.ft.epoch, seq=seq)
        pack_header(payload, self.ft.epoch, seq)
        if self._stale:
            # Pushes fill the version word too (uniform 24-byte layout);
            # the server ignores it — a whole-shard write is a state
            # transfer, not a gradient with a basis.
            pack_version(payload, self._basis.get(srank, 0))
        yield from self._op_with_retry(
            srank, payload, tags.PARAM_PUSH, tags.PARAM_PUSH_ACK, seq,
            f"PARAM_PUSH to server {srank}", span=span,
        )

    # -- pipelined streaming transfers (FLAG_CHUNKED, PROTOCOL.md §12) -------

    def _chunked_write(self, srank: int, shard: Shard, tag: int,
                       ack_tag: int, what: str):
        """One streamed shard write: the body ships as K independent
        chunk frames, each encoded into its own staging slot and posted
        *without* waiting — the transport moves chunk k while this
        thread encodes chunk k+1 (the double-buffered encode; on the
        event-loop TCP transport the I/O thread writes concurrently,
        on shm the peer drains concurrently).  The server acks each
        admitted chunk; a deadline resends only the chunks whose acks
        never arrived, from the same staged bytes — so the int8
        residual, folded at the single encode pass, stays exact under
        any retry pattern."""
        span = self._spans.op(what, peer=srank, side="client",
                              rank=self.rank)
        spans_ = self._chunk_spans[srank]
        stride = self._chunk_stride[srank]
        staging = (self._grad_wire if tag == tags.GRAD
                   else self._param_wire)[srank]
        view = (self.grad if tag == tags.GRAD
                else self.param)[shard.offset: shard.end]
        residual = (self._residual.get(srank)
                    if tag == tags.GRAD and self.codec.uses_residual
                    else None)
        seq = self._next_seq(srank, tag)
        nchunks = len(spans_)
        span.note(epoch=self.ft.epoch, seq=seq, chunks=nchunks)
        span.mark("encode")
        pool = comm_pool.get_pool()
        jobs: Dict[int, object] = {}

        def _stage_chunk(k: int) -> None:
            # One pure encode job per chunk: disjoint staging slot,
            # disjoint BLOCK-aligned residual slice (the int8 EF state
            # rides in the job), input views quiescent until collect.
            lo, hi = spans_[k]
            frame = staging[k * stride: (k + 1) * stride]
            body = frame[self._chdr: self._chdr + self._chunk_body(hi - lo)]
            if self.codec.identity:
                jobs[k] = pool.submit_copy(view[lo:hi].view(np.uint8), body)
            else:
                jobs[k] = pool.submit_encode(
                    self.codec, view[lo:hi], body,
                    residual=None if residual is None else residual[lo:hi])

        # With workers, chunk k+1 encodes on the pool while chunk k is
        # on the wire; serial (lookahead 0) keeps today's exact order.
        lookahead = 0 if pool.serial else 1
        pending: Dict[int, object] = {}
        for k, (lo, hi) in enumerate(spans_):
            for j in range(k, min(k + 1 + lookahead, nchunks)):
                if j not in jobs:
                    _stage_chunk(j)
            if not jobs[k].done():
                span.mark("pool_collect")
                while not jobs[k].done():
                    yield EXEC
            frame = staging[k * stride: (k + 1) * stride]
            pack_chunk_header(frame, self.ft.epoch, seq, k, nchunks)
            if self._timing:
                pack_tx_stamp(frame, self._chdr, obs_clock.wall_us())
            span.mark("send" if k == 0 else "chunk")
            pending[k] = self.transport.isend(frame, srank, tag)
            # Yield between chunks: the transport pumps chunk k toward
            # the peer (and sibling pumps get their turn) while this
            # generator comes back to collect/encode chunk k+1.
            yield EXEC
        yield from self._chunk_acks(srank, tag, ack_tag, seq, staging,
                                    pending, span, what)

    def _chunk_body(self, elems: int) -> int:
        """Logical body bytes of a chunk covering ``elems`` elements
        (the frame itself is padded to the uniform stride, §12.2)."""
        if self.codec.identity:
            return elems * self.param.dtype.itemsize
        return self.codec.wire_nbytes(elems)

    def _chunk_acks(self, srank: int, tag: int, ack_tag: int, seq: int,
                    staging: np.ndarray, pending: Dict[int, object],
                    span, what: str):
        """Await one ack per chunk; on deadline, resend only the
        missing chunks under the backoff policy.  While waiting, the
        loop also drains send-handle completions and marks ``flush``
        when the last chunk left this rank — the wall-clock point the
        causal analyzer compares against the server's first apply to
        *see* the wire/apply overlap (obs/causal.py)."""
        buf = self._ack_buf[srank]
        spans_ = self._chunk_spans[srank]
        stride = self._chunk_stride[srank]
        nchunks = len(spans_)
        acked = [False] * nchunks
        remaining = nchunks
        flushed = False
        attempt = 0
        last: Optional[BaseException] = None
        while self.live.io:
            deadline = self._op_deadline()
            try:
                while remaining:
                    if pending:
                        # Drive outstanding chunk sends (transports
                        # whose progress rides test()) and record the
                        # moment the last chunk left this rank.  FIFO
                        # prefix only: sends complete in post order, so
                        # stopping at the first incomplete handle keeps
                        # this O(1) amortized — testing every pending
                        # handle per pass is O(K²) over a big stream.
                        for k in list(pending):
                            if not self.transport.test(pending[k]):
                                break
                            del pending[k]
                    if not pending and not flushed:
                        flushed = True
                        span.mark("flush")
                    if not self.transport.iprobe(srank, ack_tag):
                        if not self.live.io:
                            span.end("aborted")
                            return None
                        if deadline is not None \
                                and time.monotonic() > deadline:
                            raise DeadlineExceeded(
                                "recv", srank, ack_tag,
                                time.monotonic() - deadline)
                        yield EXEC
                        continue
                    handle = self.transport.irecv(srank, ack_tag, out=buf)
                    while not self.transport.test(handle):
                        yield EXEC
                    epoch, aseq, idx = int(buf[0]), int(buf[1]), int(buf[2])
                    if self._timing and epoch == self.ft.epoch:
                        self._feed_clock(srank, int(buf[3]), int(buf[4]),
                                         int(buf[5]))
                    if epoch == self.ft.epoch and aseq == seq:
                        if 0 <= idx < nchunks and not acked[idx]:
                            acked[idx] = True
                            remaining -= 1
                    elif epoch > self.ft.epoch or (
                            epoch == self.ft.epoch and aseq > seq):
                        raise RuntimeError(
                            f"chunk ack from server {srank} is ahead of "
                            f"the op stream: got (epoch={epoch}, "
                            f"seq={aseq}), awaiting (epoch="
                            f"{self.ft.epoch}, seq={seq})")
                    # stale chunk ack (an earlier op's re-ack): drop on
                    # the unchanged attempt deadline
                span.mark("ack")
                span.end("ok")
                return True
            except DeadlineExceeded as exc:
                last = exc
                attempt += 1
                if attempt >= self._retry.attempts:
                    span.end("exhausted")
                    self._flight_dump("retry_exhausted", what=what,
                                      attempts=self._retry.attempts,
                                      peer=srank)
                    raise RetryExhausted(what, self._retry.attempts, last)
                backoff = self._retry.backoff_s(attempt)
                self._m_retries.inc()
                self._m_backoff.inc(backoff)
                span.mark("backoff")
                span.note(retries=attempt)
                if not (yield from aio_sleep(backoff, live=self.live)):
                    span.end("aborted")
                    return None
                # Resend ONLY the unacked chunks — identical staged
                # bytes (re-stamped send time under FLAG_TIMING).  A
                # still-pending stale handle is cancelled first so
                # buffer ownership returns before the re-post; the
                # server dedups any frame that made it through anyway.
                span.mark("send")
                for k in range(nchunks):
                    if acked[k]:
                        continue
                    stale = pending.pop(k, None)
                    if stale is not None and not self.transport.test(stale):
                        self.transport.cancel(stale)
                    frame = staging[k * stride: (k + 1) * stride]
                    if self._timing:
                        pack_tx_stamp(frame, self._chdr, obs_clock.wall_us())
                    span.mark("chunk")
                    pending[k] = self.transport.isend(frame, srank, tag)
                    yield EXEC
        span.end("aborted")
        return None

    def _chunked_read(self, srank: int, shard: Shard):
        """One streamed shard read: request-by-header as usual, then
        assemble K chunk replies — each decoded straight into its slice
        of ``param`` on arrival, so decode overlaps the remaining
        chunks' wire time.  Every chunk stamps its snapshot version;
        the assembly restarts whenever a newer version appears (a
        retried request re-served at the head), so the delivered vector
        is always a single committed version (§12.4).  FIFO channels
        guarantee no stale-version chunk arrives after a newer one."""
        span = self._spans.op("PARAM", peer=srank, side="client",
                              rank=self.rank)
        out = self.param[shard.offset: shard.end]
        seq = self._next_seq(srank, tags.PARAM_REQ)
        span.note(epoch=self.ft.epoch, seq=seq,
                  chunks=len(self._chunk_spans[srank]))
        spans_ = self._chunk_spans[srank]
        frame = self._param_rx[srank]
        req = (timed_frame(self.ft.epoch, seq, 0) if self._timing
               else header_frame(self.ft.epoch, seq))
        last: Optional[BaseException] = None
        # Decode jobs are per-op, not per-attempt: a timed-out attempt's
        # in-flight job must be collected before the retry re-decodes
        # the same slice, or the older bytes could land second.
        pool = comm_pool.get_pool()
        jobs: Dict[int, object] = {}
        for attempt in range(self._retry.attempts):
            if attempt:
                backoff = self._retry.backoff_s(attempt)
                self._m_retries.inc()
                self._m_backoff.inc(backoff)
                span.mark("backoff")
                span.note(retries=attempt)
                if not (yield from aio_sleep(backoff, live=self.live)):
                    span.end("aborted")
                    return
            deadline = self._op_deadline()
            try:
                span.mark("send")
                if self._timing:
                    req[2] = obs_clock.wall_us()  # re-stamped per attempt
                yield from aio_send(self.transport, req, srank,
                                    tags.PARAM_REQ, live=self.live,
                                    deadline=deadline)
                span.mark("recv")
                seen: set = set()
                version: Optional[int] = None
                while True:
                    while not self.transport.iprobe(srank, tags.PARAM):
                        if not self.live.io:
                            span.end("aborted")
                            return
                        if deadline is not None \
                                and time.monotonic() > deadline:
                            raise DeadlineExceeded(
                                "recv", srank, tags.PARAM,
                                time.monotonic() - deadline)
                        yield EXEC
                    handle = self.transport.irecv(srank, tags.PARAM,
                                                  out=frame)
                    while not self.transport.test(handle):
                        yield EXEC
                    epoch, aseq, idx, cnt, ver = unpack_chunk_reply(frame)
                    if self._timing and epoch == self.ft.epoch:
                        t_tx, t_recv, t_ack = unpack_reply_stamps(
                            frame, self._chdr_rx - 24)
                        self._feed_clock(srank, t_tx, t_recv, t_ack)
                    if epoch > self.ft.epoch or (
                            epoch == self.ft.epoch and aseq > seq):
                        raise RuntimeError(
                            f"chunked PARAM reply from server {srank} is "
                            f"ahead of the op stream: got (epoch={epoch}, "
                            f"seq={aseq}), awaiting (epoch={self.ft.epoch},"
                            f" seq={seq})")
                    if epoch != self.ft.epoch or aseq != seq \
                            or not (0 <= idx < len(spans_)):
                        continue  # stale reply chunk: drop
                    if version is None or ver > version:
                        version, seen = ver, set()
                    elif ver < version:
                        continue  # an earlier serve's straggler: drop
                    if idx in seen:
                        continue  # duplicated chunk: already decoded
                    seen.add(idx)
                    lo, hi = spans_[idx]
                    span.mark("decode")
                    body = frame[self._chdr_rx:
                                 self._chdr_rx + self._chunk_body(hi - lo)]
                    if self.codec.identity:
                        # One memcpy — pooling would only add a second.
                        out[lo:hi].view(np.uint8)[:] = body
                    elif pool.serial:
                        self.codec.decode_into(body, out[lo:hi])
                    else:
                        # ``frame`` is the reused rx staging buffer: the
                        # next irecv overwrites it while a worker reads,
                        # so the job's input must be an owned snapshot
                        # (discipline 'pool-client-decode-owned').  A
                        # version restart re-decodes a chunk; the prior
                        # job must land first so the newer bytes win.
                        prior = jobs.pop(idx, None)
                        if prior is not None and not prior.done():
                            span.mark("pool_collect")
                            while not prior.done():
                                yield EXEC
                        jobs[idx] = pool.submit_decode(
                            self.codec, np.array(body), out[lo:hi])
                    if len(seen) == cnt:
                        for job in jobs.values():
                            if not job.done():
                                span.mark("pool_collect")
                                while not job.done():
                                    yield EXEC
                        span.end("ok")
                        return
            except DeadlineExceeded as exc:
                last = exc
        span.end("exhausted")
        self._flight_dump("retry_exhausted",
                          what=f"chunked PARAM read from server {srank}",
                          attempts=self._retry.attempts, peer=srank)
        raise RetryExhausted(
            f"chunked PARAM read from server {srank}",
            self._retry.attempts, last)

    def _encode(self, view: np.ndarray, wire: Optional[np.ndarray],
                residual: Optional[np.ndarray] = None) -> np.ndarray:
        """The slice itself for the identity codec (zero-copy send);
        otherwise the encoded frame in the per-server staging buffer —
        behind the [epoch, seq] header slot when FT framing is on.  The
        encode (and its residual fold) happens exactly once per op;
        retries resend these bytes."""
        if wire is None:
            return view
        body = wire[self._hdr :]
        if self.codec.identity:
            body[:] = view.view(np.uint8)
        else:
            self.codec.encode_into(view, body, residual=residual)
        return wire

    def _decode_framed(self, wire: np.ndarray, out: np.ndarray) -> None:
        body = wire[self._hdr_rx :]
        if self.codec.identity:
            out.view(np.uint8)[:] = body
        else:
            self.codec.decode_into(body, out)

    def residual_norm(self) -> float:
        """L2 norm of the error-feedback residuals across shards — 0.0
        for residual-free codecs.  Observability/test hook."""
        residuals = list(self._residual.values()) + \
            list(self._sc_residual.values())
        if not residuals:
            return 0.0
        return float(np.sqrt(sum(float(np.dot(r, r)) for r in residuals)))

    # -- public async API (reference pclient.lua:84-109) --------------------

    def _enqueue(self, srank: int, gen: Generator, name: str) -> None:
        queue = self._opq.setdefault(srank, deque())
        queue.append((gen, name))
        if not self._pump_live.get(srank, False):
            self._pump_live[srank] = True
            self._pump_task[srank] = None
            task = self.sched.spawn(self._pump(srank), name=f"pump:{srank}:{name}")
            self._pump_task[srank] = task

    def _pump(self, srank: int):
        """Run this server's queued ops strictly in order, renaming the
        task per dequeued op — a pump that kept its spawn-time name
        (e.g. ``pump:3:send_grad``) for life would misattribute every
        later op in scheduler error/debug output."""
        queue = self._opq[srank]
        try:
            while queue:
                op, opname = queue.popleft()
                task = self._pump_task.get(srank)
                if task is not None:
                    task.name = f"pump:{srank}:{opname}"
                yield from op
        finally:
            self._pump_live[srank] = False

    def enqueue_wire_op(self, srank: int, gen: Generator,
                        name: str) -> None:
        """Public hook for the device exchange (mpit_tpu.dplane): run
        one wire op generator through ``srank``'s FIFO pump, exactly as
        the ``async_*`` conveniences do.  The dplane ExchangeClient
        routes per-server — device-eligible servers bypass the wire,
        everyone else enters here with codecs/framing/retry intact."""
        self._enqueue(srank, gen, name)

    def async_send_grad(self) -> None:
        if self._sc:
            for e in self.smap.entries:
                self._sc_enqueue(
                    self._sc_write_op(e.shard_id, tags.GRAD, tags.GRAD_ACK,
                                      "GRAD"), "send_grad")
            return
        for srank, shard in zip(self.sranks, self.shards):
            self._enqueue(srank, self._send_grad(srank, shard), "send_grad")

    def async_recv_param(self) -> None:
        if self._sc:
            for e in self.smap.entries:
                self._sc_enqueue(self._sc_read_op(e.shard_id), "recv_param")
            return
        for srank, shard in zip(self.sranks, self.shards):
            self._enqueue(srank, self._recv_param(srank, shard), "recv_param")

    def async_send_param(self) -> None:
        if self._sc:
            for e in self.smap.entries:
                self._sc_enqueue(
                    self._sc_write_op(e.shard_id, tags.PARAM_PUSH,
                                      tags.PARAM_PUSH_ACK, "PARAM_PUSH"),
                    "send_param")
            return
        for srank, shard in zip(self.sranks, self.shards):
            self._enqueue(srank, self._send_param(srank, shard), "send_param")

    def ping(self, n: int = 1) -> None:
        """Single-step I/O progress to overlap with compute
        (reference pclient.lua:131-136)."""
        self._maybe_heartbeat()
        self._sc_poll_map()
        self._drain_clock_echoes()
        for _ in range(n):
            self.sched.ping()

    def wait(self) -> None:
        if self.ft.heartbeat_s > 0:
            # Keep beating while blocked on slow servers: the wait loop is
            # exactly where a stalled gang would otherwise go silent and
            # get this client evicted.
            while self.sched.queue:
                self._maybe_heartbeat()
                self._sc_poll_map()
                self._drain_clock_echoes()
                self.sched.ping_pass()
            if self.sched.errors:
                raise self.sched.errors.pop(0)
            return
        self.sched.wait()

    # -- shutdown (reference pclient.lua:153-164) ---------------------------

    def stop(self) -> None:
        # Chained per server, so the stop cannot overtake in-flight ops
        # (the reference's drain-then-stop care, init.lua:50-58, README:71).
        if self._sc:
            # The global shardctl pump gives the same drain-then-stop
            # ordering; the controller counts client STOPs too — its
            # exit condition mirrors the servers'.  Membership may have
            # changed since launch: STOP every server this incarnation
            # greeted plus every current owner (a scaled-up joiner waits
            # for our STOP like any launch member), and never a retired
            # rank — it already said goodbye and exited.
            self._sc_poll_map()
            owners = set(self.smap.owners()) if self.smap is not None else set()
            stop_to = sorted(
                (set(self._sc_greeted or self.sranks) | owners)
                - self._sc_retired) + (
                [self.controller_rank] if self.controller_rank is not None
                else [])
            for dst in stop_to:
                self._sc_enqueue(
                    aio_send(self.transport, tags.EMPTY, dst, tags.STOP,
                             live=self.live, deadline=self._op_deadline()),
                    "send_stop",
                )
            self.wait()
            self.live.stop()
            return
        for srank in self.sranks:
            self._enqueue(
                srank,
                aio_send(self.transport, tags.EMPTY, srank, tags.STOP,
                         live=self.live, deadline=self._op_deadline()),
                "send_stop",
            )
        self.wait()
        self.live.stop()
