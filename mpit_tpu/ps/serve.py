"""Read-mostly parameter-serving tier — reader clients + admission control.

The north-star workload ("serve heavy traffic from millions of users")
is read-dominated: many consumers pulling the current parameters, few
writers training them.  This module is the client half and the shared
wire/config of that tier (the server half lives in
:class:`mpit_tpu.ps.server.ParamServer` — ``reader_ranks=``):

- **READ-ONLY attach** (``FLAG_READONLY``, INIT v3 bit 4): a
  :class:`ReaderClient` announces the same ``[offset, size, codec_id,
  epoch, flags]`` words as a worker but promises to only ever send
  ``PARAM_REQ`` / ``HEARTBEAT`` / ``STOP``.  The server allocates no
  gradient/push staging for it and spawns only the read + stop (+
  heartbeat) services, so a reader costs bytes proportional to one
  request header, not one shard — hundreds of readers attach to one
  rank (the epoll event-loop transport holds the connections;
  ``comm/tcp.py``).  Readers attach lazily at any point mid-run.
- **Status-framed replies** (docs/PROTOCOL.md §8): the server answers a
  reader's ``PARAM_REQ [epoch, seq]`` with a 32-byte int64 header
  ``[epoch, seq, status, word]`` — reusing the shardctl status words
  (``OK``/``BUSY``, :mod:`mpit_tpu.shardctl.wire`) — followed, on
  ``OK`` only, by the snapshot frame **as its own message**.  The body
  message is a zero-copy view of the PR 2 version-counted snapshot
  cache's encoded frame, which is what pushes the N-readers = 1-copy +
  1-encode invariant to hundreds of connections: every reader's reply
  views the same cached buffer, and ``snapshot_copies`` stays at one
  per committed version.  ``word`` carries the snapshot version on
  ``OK`` (readers assert monotonicity) and the **retry hint in
  microseconds** on ``BUSY``.
- **Admission control** (:class:`ServeConfig`): the server grants a
  read only while its in-flight reply bytes (and optionally reply
  count) fit a per-rank budget; past it, the reply is
  ``BUSY``-with-retry-hint instead of an unbounded queue of
  multi-megabyte snapshot sends.  The hint scales with the bytes ahead
  of the reader (``inflight / drain_bytes_per_s``), and the reader
  honors it through the PR 3 backoff machinery: deterministic jitter,
  capped escalation on repeated BUSY, a hard bound that raises
  :class:`~mpit_tpu.ft.RetryExhausted` — never a hang, never a
  stampede.
"""

from __future__ import annotations

import os
import time
from collections import deque
from dataclasses import dataclass
from typing import Any, Deque, Dict, Generator, List, Optional, Tuple

import numpy as np

from mpit_tpu.aio import (
    DeadlineExceeded,
    LiveFlag,
    Scheduler,
    aio_recv,
    aio_send,
    aio_sleep,
    deadline_at,
)
from mpit_tpu.comm import codec as codec_mod
from mpit_tpu.comm.transport import Transport
from mpit_tpu.ft import (
    FLAG_FRAMED,
    FLAG_HEARTBEAT,
    FLAG_READONLY,
    FTConfig,
    RetryExhausted,
    RetryPolicy,
    header_frame,
    init_v3,
)
from mpit_tpu.obs import (
    get_flight,
    get_recorder,
    obs_enabled,
    register_status_provider,
    registry_or_local,
)
from mpit_tpu.ps import tags
from mpit_tpu.ps.sharding import Shard
from mpit_tpu.shardctl import shardmap as _shardmap
from mpit_tpu.shardctl.wire import GOODBYE, OK
from mpit_tpu.utils.logging import get_logger

#: reader reply header: int64 [epoch, seq, status, word]
SERVE_HDR_BYTES = 32


def serve_reply(epoch: int, seq: int, status: int, word: int) -> np.ndarray:
    """A fresh 32-byte reader reply header (fresh per reply: an
    in-flight zero-copy send must never see its header rewritten)."""
    return np.asarray([epoch, seq, status, word], dtype=np.int64)


def parse_serve_header(payload) -> Tuple[int, int, int, int]:
    """(epoch, seq, status, word) from a reader reply header message.
    Accepts both the 4-word §8 form and the 5-word §11 cell form (whose
    extra word — the serving rank's known head version — is read by
    :func:`serve_head`)."""
    words = np.frombuffer(bytes(payload), np.int64)
    if words.size not in (4, 5):
        raise ValueError(
            f"reader reply header must be 4 or 5 int64 words, got "
            f"{words.size}")
    return int(words[0]), int(words[1]), int(words[2]), int(words[3])


def serve_head(payload) -> Optional[int]:
    """The head-version word of a 5-word cell OK reply (None on the
    4-word direct-server form — a training server IS the head)."""
    words = np.frombuffer(bytes(payload), np.int64)
    return int(words[4]) if words.size == 5 else None


@dataclass(frozen=True)
class ServeConfig:
    """Per-server-rank admission budget for the read-serving tier.

    ``budget_bytes`` bounds the reply bytes in flight (queued to the
    transport but not yet accepted) across all readers; ``budget_reads``
    optionally bounds the reply *count* (0 = unbounded — byte budgets
    are the primary control).  A read that would exceed either gets a
    ``BUSY`` reply whose hint estimates the drain time of the bytes
    ahead of it: ``hint_floor_us + inflight_bytes / drain_bytes_per_s``.
    """

    budget_bytes: int = 64 << 20
    budget_reads: int = 0
    hint_floor_us: int = 2_000
    drain_bytes_per_s: int = 128 << 20

    @classmethod
    def from_env(cls, **overrides) -> "ServeConfig":
        fields = dict(
            budget_bytes=int(float(
                os.environ.get("MPIT_SERVE_BUDGET_MB", "64")) * (1 << 20)),
            budget_reads=int(os.environ.get("MPIT_SERVE_BUDGET_READS", "0")),
            hint_floor_us=int(
                os.environ.get("MPIT_SERVE_HINT_FLOOR_US", "2000")),
            drain_bytes_per_s=int(float(
                os.environ.get("MPIT_SERVE_DRAIN_MBPS", "128")) * (1 << 20)),
        )
        fields.update(overrides)
        return cls(**fields)

    def hint_us(self, inflight_bytes: int) -> int:
        """Retry hint for a rejected read: the estimated drain time of
        the reply bytes already in flight, floored so a hint can never
        tell a reader to hammer."""
        return self.hint_floor_us + int(
            inflight_bytes * 1_000_000 // max(self.drain_bytes_per_s, 1))


class ReaderClient:
    """A lightweight read-only consumer of the PS gang: announces the
    READ-ONLY posture to every server, then pulls whole-vector
    snapshots with :meth:`read_params` (or the async pair
    :meth:`async_read_params` / :meth:`poll` for many readers
    multiplexed on one driver thread).  Tracks the per-server snapshot
    version of every read and asserts monotonicity (``monotone``).

    Requires op deadlines (``FTConfig.op_deadline_s > 0``): BUSY
    recovery and dead-server detection both ride the PR 3 retry
    machinery — a reader can never hang on a wedged server."""

    def __init__(
        self,
        rank: int,
        server_ranks: "list[int]",
        transport: Transport,
        scheduler: Optional[Scheduler] = None,
        codec: Optional[str] = None,
        ft: Optional[FTConfig] = None,
        cells: "Optional[Dict[int, list]]" = None,  # fabric routing
        #   (§11.5): {launch server rank -> its replica cell ranks}.
        #   Reads route to one cell per shard by consistent hashing and
        #   fail over to ring siblings instead of exhausting the retry
        #   budget against a dead cell.
        failover_after: int = 2,  # deadline-exceeded attempts against
        #   one cell before failing over to the next ring sibling.
        layout: "Optional[List[Shard]]" = None,  # static weighted cut
        #   (mpit_tpu.lm): one Shard per server, identical to the cut
        #   the gang's ParamClients announced — servers reject a reader
        #   whose announcement disagrees with the adopted shard.
    ):
        self.rank = rank
        self.sranks = list(server_ranks)
        self._layout = list(layout) if layout is not None else None
        if self._layout is not None and len(self._layout) != len(self.sranks):
            raise ValueError(
                f"layout has {len(self._layout)} shards for "
                f"{len(self.sranks)} servers (need exactly one each)")
        self.transport = transport
        self.sched = scheduler or Scheduler()
        self.codec = codec_mod.get(codec)
        self.ft = ft if ft is not None else FTConfig.from_env()
        if self.ft.op_deadline_s <= 0:
            raise ValueError(
                "ReaderClient needs op deadlines (FTConfig.op_deadline_s"
                " > 0): BUSY recovery and dead-server detection ride the"
                " retry machinery")
        self._retry = RetryPolicy(self.ft, key=rank)
        self.live = LiveFlag()
        self.log = get_logger("reader", rank)
        self.param: Optional[np.ndarray] = None
        self.shards: List[Shard] = []
        self._started = False
        self._seq: Dict[int, int] = {}
        # Protocol-state carry-over: True when an earlier (timed-out)
        # attempt consumed an OK header but not its body — the next
        # recv on that channel is the orphaned body, not a header.
        self._half_pair: Dict[int, bool] = {}
        #: last snapshot version observed per server (reads must be
        #: monotone: the serving tier never goes back in time).  Keyed
        #: by the *physical* serving rank — each cell's version stream
        #: is monotone on its own; a fail-over lands on a fresh key.
        self.versions: Dict[int, int] = {}
        #: per *launch server slot* (§11.5), from the last completed
        #: read: the served snapshot version and the observed lag (the
        #: serving rank's stamped head minus that version; 0 against a
        #: direct server — it is the head).  The pair is the client's
        #: staleness envelope: "I hold version v, at most ``lag``
        #: behind what existed when it was served".
        self.read_versions: Dict[int, int] = {}
        self.lags: Dict[int, int] = {}
        self.monotone = True
        self.reads_done = 0
        # Fabric routing (§11.5): a consistent-hash ring of replica
        # cells per launch server slot.  The primary cell is the ring
        # lookup of this reader's rank; deadline exhaustion against a
        # cell marks it down and fails over to the next live sibling
        # with a FRESH attempt budget — RetryExhausted is reserved for
        # "no live cell remains".
        self._rings: Dict[int, Any] = {}
        self._failover_after = max(int(failover_after), 1)
        self.failovers = 0
        if cells:
            from mpit_tpu.cells.ring import CellRing

            for srank in self.sranks:
                fabric = cells.get(srank)
                if not fabric:
                    raise ValueError(
                        f"fabric routing needs cells for every server "
                        f"slot; server {srank} has none")
                self._rings[srank] = CellRing(fabric)
        # Server retirement (§9.4): a GOODBYE reply re-routes this
        # attach slot to the named successor instead of burning the
        # retry budget against a disappearing rank.  ``_route`` maps
        # the launch-time server to wherever its slot is served now;
        # ``_attached`` tracks who has seen our INIT.
        self._route: Dict[int, int] = {}
        self._attached: set = set()
        self._goodbyes: set = set()
        self._announce: Dict[int, Shard] = {}
        self._flags = 0
        self._hb_last = 0.0
        self._hb_seq = 0
        self.metrics = registry_or_local()
        self._spans = get_recorder()
        self._flight = get_flight()
        self._m_busy = self.metrics.counter(
            "mpit_ps_busy_honored_total", rank=rank)
        self._m_reroutes = self.metrics.counter(
            "mpit_ps_reader_reroutes_total", rank=rank)
        self._m_retries = self.metrics.counter(
            "mpit_ft_retries_total", rank=rank)
        self._m_hb = self.metrics.counter(
            "mpit_ft_heartbeats_sent_total", rank=rank)
        #: observed staleness per completed read (§11.5): stamped head
        #: minus served version — 0 against a direct server.
        self._m_lag = self.metrics.histogram(
            "mpit_serve_read_lag", rank=rank)
        if obs_enabled():
            register_status_provider(f"reader{rank}", self._status_section)
        # Per-server FIFO op pumps (the ParamClient pattern): reads to
        # one server serialize, different servers overlap.
        self._opq: Dict[int, Deque[Tuple[Generator, str]]] = {}
        self._pump_live: Dict[int, bool] = {}

    # -- introspection -------------------------------------------------------

    def _status_section(self) -> Dict[str, object]:
        return {
            "role": "reader",
            "rank": self.rank,
            "servers": self.sranks,
            "codec": self.codec.name,
            "epoch": self.ft.epoch,
            "versions": {str(s): v for s, v in self.versions.items()},
            "lags": {str(s): v for s, v in self.lags.items()},
            "monotone": self.monotone,
            "reads_done": self.reads_done,
            "busy_honored": int(self._m_busy.value),
            "fabric": {str(s): {"route": self._route.get(s, s),
                                "live_cells": r.live}
                       for s, r in self._rings.items()},
            "failovers": self.failovers,
        }

    @property
    def busy_honored(self) -> int:
        """BUSY replies absorbed-and-retried (registry-backed)."""
        return int(self._m_busy.value)

    @property
    def retries(self) -> int:
        return int(self._m_retries.value)

    # -- lifecycle -----------------------------------------------------------

    def start(self, param: np.ndarray) -> None:
        """Announce the READ-ONLY posture to every server.  ``param`` is
        the whole-vector host mirror reads decode into; the shard cut is
        the same version-0 equal split every static client derives."""
        if not isinstance(param, np.ndarray) or param.ndim != 1:
            raise TypeError("param must be a 1-D numpy array (host mirror)")
        if not param.flags["C_CONTIGUOUS"]:
            raise ValueError("param must be contiguous (zero-copy rule)")
        if not self.codec.identity and param.dtype != np.float32:
            raise ValueError(
                f"codec {self.codec.name!r} quantizes float32 shards; got "
                f"dtype {param.dtype} (use codec='none' for other dtypes)")
        self.param = param
        if self._layout is not None:
            if self._layout[-1].end != len(param):
                raise ValueError(
                    f"layout covers [0, {self._layout[-1].end}) but the "
                    f"mirror has {len(param)} elements")
            smap = _shardmap.ShardMap.from_shards(self._layout, self.sranks)
        else:
            smap = _shardmap.ShardMap.initial(len(param), self.sranks)
        self.shards = [e.shard for e in smap.entries]
        flags = FLAG_FRAMED | FLAG_READONLY | (
            FLAG_HEARTBEAT if self.ft.heartbeat_s > 0 else 0)
        self._flags = flags
        attached = set()
        for srank, shard in zip(self.sranks, self.shards):
            self._announce[srank] = shard
            cinfo = init_v3(shard.offset, shard.size, self.codec.wire_id,
                            self.ft.epoch, flags)
            ring = self._rings.get(srank)
            if ring is None:
                targets = [srank]
            else:
                # Fabric (§11.5): announce to EVERY replica cell of the
                # slot — attach is one message, and it buys lazy STOP
                # accounting plus instant fail-over (the sibling already
                # holds our negotiation) — then route reads to the
                # ring's pick for this reader.
                targets = ring.members
                self._route[srank] = ring.lookup(self.rank)
            for target in targets:
                self.sched.spawn(
                    aio_send(self.transport, cinfo, target, tags.INIT,
                             live=self.live, deadline=self._op_deadline()),
                    name=f"send_init:{target}",
                )
            attached.update(targets)
        self.wait()
        self._attached = attached
        self._started = True
        self._hb_last = 0.0

    # -- FT plumbing ---------------------------------------------------------

    def _op_deadline(self) -> Optional[float]:
        return deadline_at(self.ft.deadline_s)

    def _next_seq(self, srank: int) -> int:
        seq = self._seq.get(srank, 0) + 1
        self._seq[srank] = seq
        return seq

    def _busy_sleep_s(self, hint_us: int, busy: int) -> float:
        """Honor the server's retry hint through the PR 3 backoff
        policy: the hint is the floor (the server's own drain
        estimate), the capped-exponential-with-deterministic-jitter
        schedule escalates repeated rejections so N readers never
        resynchronize into a retry stampede."""
        return max(max(hint_us, 0) / 1e6,
                   self._retry.backoff_s(min(max(busy, 1), 8)))

    def _maybe_heartbeat(self) -> None:
        hb = self.ft.heartbeat_s
        if hb <= 0 or not self._started or not self.live.io:
            return
        now = time.monotonic()
        if now - self._hb_last < hb:
            return
        self._hb_last = now
        self._hb_seq += 1
        payload = header_frame(self.ft.epoch, self._hb_seq)
        self._m_hb.inc()
        for srank in self._targets():
            self.sched.spawn(self._hb_send(payload, srank),
                             name=f"heartbeat:{srank}")

    def _hb_send(self, payload: np.ndarray, srank: int):
        try:
            yield from aio_send(
                self.transport, payload, srank, tags.HEARTBEAT,
                live=self.live, deadline=deadline_at(4 * self.ft.heartbeat_s),
            )
        except DeadlineExceeded:
            pass  # liveness is best-effort; the next beat tries again

    # -- the read op ---------------------------------------------------------

    def _read_op(self, srank: int, shard: Shard):
        """One shard read: request, await the status-framed reply; BUSY
        honors the hint and re-requests the same seq (reads are
        idempotent and never dedup'd); DeadlineExceeded retries under
        the backoff policy; both are bounded — exhaustion raises."""
        span = self._spans.op("PARAM", peer=srank, side="client",
                              rank=self.rank)
        out = self.param[shard.offset: shard.end]
        seq = self._next_seq(srank)
        span.note(epoch=self.ft.epoch, seq=seq)
        req = header_frame(self.ft.epoch, seq)
        attempt = 0
        busy = 0
        max_busy = 64 * self._retry.attempts
        last: Optional[BaseException] = None
        while self.live.io:
            target = self._route.get(srank, srank)
            deadline = self._op_deadline()
            try:
                span.mark("send")
                yield from aio_send(self.transport, req, target,
                                    tags.PARAM_REQ, live=self.live,
                                    deadline=deadline)
                span.mark("recv")
                got_busy_hint: Optional[int] = None
                rerouted = False
                while got_busy_hint is None and not rerouted:
                    if self._half_pair.pop(target, None):
                        # A previous attempt died between an OK header
                        # and its body: the channel's next message is
                        # that orphaned body — consume it to stay in
                        # sync before parsing headers again.
                        stale = yield from aio_recv(
                            self.transport, target, tags.PARAM,
                            live=self.live, deadline=deadline)
                        if stale is None:
                            span.end("aborted")
                            return None
                    raw = yield from aio_recv(
                        self.transport, target, tags.PARAM, live=self.live,
                        deadline=deadline)
                    if raw is None:
                        span.end("aborted")
                        return None
                    epoch, aseq, status, word = parse_serve_header(raw)
                    if status == OK:
                        head = serve_head(raw)
                        self._half_pair[target] = True
                        body = yield from aio_recv(
                            self.transport, target, tags.PARAM,
                            live=self.live, deadline=deadline)
                        if body is None:
                            span.end("aborted")
                            return None
                        self._half_pair.pop(target, None)
                        if epoch == self.ft.epoch and aseq == seq:
                            span.mark("decode")
                            self._decode(body, out)
                            self._note_version(target, word)
                            # Observed staleness (§11.5): the serving
                            # rank's stamped head minus the version we
                            # got — surfaced per read so clients can
                            # assert their own envelope.
                            lag = (max(head - word, 0)
                                   if head is not None else 0)
                            self.read_versions[srank] = word
                            self.lags[srank] = lag
                            self._m_lag.observe(lag)
                            span.note(version=word, lag=lag)
                            span.end("ok")
                            return word
                        continue  # stale pair (earlier attempt): dropped
                    if status == GOODBYE and epoch == self.ft.epoch \
                            and aseq == seq:
                        # Retirement (§9.4): re-attach at the announced
                        # successor and re-issue the same request there —
                        # a redirect, not a failure, so the retry budget
                        # is untouched.
                        yield from self._reroute(srank, target, int(word))
                        span.mark("reroute")
                        rerouted = True
                        continue
                    if epoch == self.ft.epoch and aseq == seq:
                        got_busy_hint = max(int(word), 0)
                    # stale BUSY echoes drop on the unchanged deadline
                if rerouted:
                    continue  # re-issue against the successor
                busy += 1
                self._m_busy.inc()
                span.mark("backoff")
                span.note(busy=busy)
                if busy > max_busy:
                    span.end("exhausted")
                    self._flight_dump("retry_exhausted",
                                      what=f"PARAM read from server {srank}"
                                           " (admission)", busy=busy)
                    raise RetryExhausted(
                        f"PARAM read from server {srank} (admission "
                        f"control never granted it)", busy, last)
                if not (yield from aio_sleep(
                        self._busy_sleep_s(got_busy_hint, busy),
                        live=self.live)):
                    span.end("aborted")
                    return None
                continue  # re-request the same seq after honoring the hint
            except RetryExhausted:
                raise
            except (DeadlineExceeded, RuntimeError) as exc:
                # DeadlineExceeded: the target never answered in time.
                # RuntimeError: the transport's fail-loud raise-once on
                # a torn link (a SIGKILLed cell) — both are the same
                # retryable fact: this target is not answering.
                last = exc
                attempt += 1
                ring = self._rings.get(srank)
                if (ring is not None and attempt >= self._failover_after
                        and len(ring.live) > 1):
                    # Fabric fail-over (§11.5): a dead cell must cost a
                    # reroute, not the retry budget — mark it down,
                    # take the next ring sibling with a FRESH attempt
                    # budget.  Bounded: once no live sibling remains,
                    # the ordinary exhaustion path below is the truth.
                    target = self._route.get(srank, srank)
                    yield from self._cell_failover(srank, target, ring)
                    attempt = 0
                    span.mark("reroute")
                    continue
                if attempt >= self._retry.attempts:
                    span.end("exhausted")
                    self._flight_dump(
                        "retry_exhausted",
                        what=f"PARAM read from server {srank}",
                        attempts=self._retry.attempts)
                    raise RetryExhausted(
                        f"PARAM read from server {srank}",
                        self._retry.attempts, last)
                backoff = self._retry.backoff_s(attempt)
                self._m_retries.inc()
                span.mark("backoff")
                span.note(retries=attempt)
                if not (yield from aio_sleep(backoff, live=self.live)):
                    span.end("aborted")
                    return None
        span.end("aborted")
        return None

    def _reroute(self, srank: int, old: int, succ: int):
        """Follow a GOODBYE to the named successor: record the route
        and, on first contact, announce the same READ-ONLY posture for
        the same shard (the successor's dispatcher attaches us lazily,
        any time mid-run)."""
        if succ < 0 or succ == old:
            raise RetryExhausted(
                f"server {old} retired without a usable successor "
                f"({succ})", 0, None)
        self._m_reroutes.inc()
        self._route[srank] = succ
        self._goodbyes.add(old)
        ring = self._rings.get(srank)
        if ring is not None:
            # A retiring cell leaves the ring for good; the successor
            # may be a fresh (autoscaled) cell outside it — the route
            # override wins either way.
            ring.mark_down(old)
        self.log.warning("server %d retiring: re-attaching its shard "
                         "reads to server %d", old, succ)
        if succ not in self._attached:
            shard = self._announce[srank]
            cinfo = init_v3(shard.offset, shard.size, self.codec.wire_id,
                            self.ft.epoch, self._flags)
            yield from aio_send(self.transport, cinfo, succ, tags.INIT,
                                live=self.live,
                                deadline=self._op_deadline())
            self._attached.add(succ)

    def _cell_failover(self, srank: int, dead: int, ring):
        """Fail a read over to the next live ring sibling after the
        current cell stopped answering (§11.5): mark it down, route to
        the ring's next pick for this reader, re-announce if it never
        saw our INIT, and leave a ``cell_failover`` postmortem naming
        the version window we crossed it with."""
        ring.mark_down(dead)
        try:
            succ = ring.lookup(self.rank)
        except LookupError:
            raise RetryExhausted(
                f"cell {dead} dead and no live sibling remains for "
                f"server slot {srank}", self.failovers, None)
        self._m_reroutes.inc()
        self.failovers += 1
        self._route[srank] = succ
        self.log.warning(
            "cell %d stopped answering: failing shard %d reads over to "
            "cell %d", dead, srank, succ)
        self._flight.record("cell_failover", rank=self.rank,
                            dead=dead, successor=succ)
        self._flight.dump(
            "cell_failover",
            window={"version": self.versions.get(dead, -1),
                    "lag": self.lags.get(srank, 0),
                    "dead": dead, "successor": succ},
            server_slot=srank)
        if succ not in self._attached:
            shard = self._announce[srank]
            cinfo = init_v3(shard.offset, shard.size, self.codec.wire_id,
                            self.ft.epoch, self._flags)
            yield from aio_send(self.transport, cinfo, succ, tags.INIT,
                                live=self.live,
                                deadline=self._op_deadline())
            self._attached.add(succ)

    def _targets(self) -> "List[int]":
        """The physical ranks currently serving this reader's slots."""
        return sorted({self._route.get(s, s) for s in self.sranks})

    def _decode(self, body, out: np.ndarray) -> None:
        frame = np.frombuffer(bytes(body), np.uint8)
        if self.codec.identity:
            out.view(np.uint8)[:] = frame
        else:
            self.codec.decode_into(frame, out)

    def _note_version(self, srank: int, version: int) -> None:
        if version < self.versions.get(srank, -1):
            self.monotone = False
            self.log.warning(
                "server %d served version %d after %d — snapshot "
                "versions must be monotone", srank, version,
                self.versions[srank])
        self.versions[srank] = version

    def _flight_dump(self, reason: str, **fields) -> None:
        self._flight.record(reason, rank=self.rank, **fields)
        self._flight.dump(reason, **fields)

    # -- public surface ------------------------------------------------------

    def _enqueue(self, srank: int, gen: Generator, name: str) -> None:
        queue = self._opq.setdefault(srank, deque())
        queue.append((gen, name))
        if not self._pump_live.get(srank, False):
            self._pump_live[srank] = True
            self.sched.spawn(self._pump(srank), name=f"pump:{srank}:{name}")

    def _pump(self, srank: int):
        queue = self._opq[srank]
        try:
            while queue:
                op, _name = queue.popleft()
                yield from op
        finally:
            self._pump_live[srank] = False

    def async_read_params(self) -> None:
        """Enqueue one whole-vector read (every server's shard)."""
        for srank, shard in zip(self.sranks, self.shards):
            self._enqueue(srank, self._read_op(srank, shard), "read_param")

    def poll(self) -> bool:
        """One scheduler step; True while reads are still in flight.
        Raises the first op error once everything drained — the
        many-readers-one-thread driver primitive."""
        self._maybe_heartbeat()
        self.sched.ping()
        if self.sched.queue:
            return True
        if self.sched.errors:
            raise self.sched.errors.pop(0)
        return False

    def ping(self, n: int = 1) -> None:
        self._maybe_heartbeat()
        for _ in range(n):
            self.sched.ping()

    def wait(self) -> None:
        while self.sched.queue:
            self._maybe_heartbeat()
            self.sched.ping_pass()
        if self.sched.errors:
            raise self.sched.errors.pop(0)

    def read_params(self) -> Dict[int, int]:
        """Blocking whole-vector read; returns {server: version}."""
        self.async_read_params()
        self.wait()
        self.reads_done += 1
        return dict(self.versions)

    def _stop_op(self, srank: int):
        """One best-effort STOP: a target that died (a SIGKILLed cell)
        must not fail the reader's shutdown — the serving side's lease
        machinery owns counting a dead reader out."""
        try:
            yield from aio_send(self.transport, tags.EMPTY, srank,
                                tags.STOP, live=self.live,
                                deadline=self._op_deadline())
        except (DeadlineExceeded, RuntimeError) as exc:
            self.log.debug("STOP to %d undeliverable: %r", srank, exc)

    def stop(self) -> None:
        # STOP goes to every rank that saw our INIT and is still
        # serving: in fabric mode that is every replica cell (each one
        # counts every expected reader), otherwise wherever each slot
        # is served *now*.  A retired rank already counted us out when
        # it said GOODBYE (§9.4).
        targets = (self._attached | set(self._targets())) - self._goodbyes
        for srank in sorted(targets):
            self._enqueue(srank, self._stop_op(srank), "send_stop")
        self.wait()
        self.live.stop()
