"""mpit_tpu — a TPU-native asynchronous parameter-server training framework.

A brand-new JAX/XLA framework with the capabilities of the reference system
mpiT ("MPI for Torch", see /root/reference): a sharded asynchronous parameter
server with the msgd / DOWNPOUR / EASGD / EAMSGD family of distributed
optimizers (plus server-side RMSProp/Adam/Adamax/Adagrad/Adadelta shard
rules), driving real workloads (MNIST classification, BiCNN answer
selection).

It is *not* a port.  Where the reference stacks Lua coroutines over a
generated Lua<->MPI C binding (reference: mpiT.c, lua-mpi.h, mpifuncs.c,
init.lua, queue.lua), this framework is built TPU-first:

- compute lives in jitted XLA programs (Flax models, pure-functional
  optimizers, jitted shard-update rules) running on HBM-resident arrays;
- multi-chip scaling is expressed with ``jax.sharding.Mesh`` + ``pjit`` /
  ``shard_map`` and XLA collectives (psum / all_gather / ppermute) over ICI;
- the truly-asynchronous host paths (the analog of the reference's
  MPI_Isend/Irecv coroutine machinery) are a native C++ transport
  (shared-memory rings for same-host processes, TCP for cross-host) driven
  through ctypes bindings generated from JSON specs — mirroring the
  reference's readspec.py codegen, but emitting Python, not C.

Layer map (cf. SURVEY.md section 1):

====  ==============================  ==========================================
L5    launchers / experiment drivers  mpit_tpu.train.launch
L4    workloads (models+train loops)  mpit_tpu.train, mpit_tpu.models, mpit_tpu.data
L3    distributed optimizers          mpit_tpu.optim
L2    parameter-server protocol       mpit_tpu.ps
L1    async engine (scheduler/queue)  mpit_tpu.aio
L0    transports (native C++ / ICI)   mpit_tpu.comm
====  ==============================  ==========================================

Cross-cutting: ``mpit_tpu.ft`` (fault tolerance — heartbeats/leases, op
deadlines with dedup'd retry, checkpoint/rejoin, deterministic fault
injection) threads through L0-L5, and ``mpit_tpu.analysis`` (mtlint)
statically checks the protocol, concurrency, and hot-path invariants the
other layers rely on.
"""

__version__ = "0.1.0"

from mpit_tpu.utils.config import Config  # noqa: F401
