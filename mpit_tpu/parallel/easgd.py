"""Mesh-native (synchronous) EASGD/EAMSGD — elastic averaging as sharded
XLA programs over a (dp, shard) device mesh.

The reference realizes elastic averaging with *asynchronous* host-mediated
push/pull against sharded parameter servers (reference
asyncsgd/optim-eamsgd.lua, asyncsgd/pserver.lua).  That path exists here
too (:mod:`mpit_tpu.optim.easgd` + :mod:`mpit_tpu.ps`).  This module is
the ICI-resident expression of the same algorithm:

- every worker's parameters live as one row of a ``(n_dp, plong)`` array,
  rows sharded over ``dp`` and columns over ``shard`` — each device holds
  exactly one worker-shard tile in HBM;
- the center variable w* is a ``(plong,)`` array sharded over ``shard``
  (the mesh form of the reference's per-server shard slices,
  pclient.lua:111-129);
- the local Nesterov update (identical math to
  :mod:`mpit_tpu.optim.msgd`) is vmapped over the ``dp`` axis;
- the elastic exchange — every su-th step — is
  ``w* += mva * sum_i(w_i - w*)``, ``w_i -= mva * (w_i - w*)``
  (the simultaneous application of every worker's push, reference
  optim-eamsgd.lua:58-66 / pserver.lua:83), which XLA lowers to one
  reduce + broadcast over the ``dp`` ICI ring.

With ``mva = beta/p`` (the mlaunch config, reference mlaunch.lua:42) the
center moves by ``beta * (mean_i(w_i) - w*)`` per sync — the synchronous
EASGD of the paper.  All state stays in HBM across steps; nothing touches
the host.

Note on the historic intermittent ``Fatal Python error: Aborted`` under
the virtual-CPU test platform: an XLA:CPU collective-rendezvous
thread-starvation limitation, not a defect in this program — root cause
and workaround in docs/xla_cpu_rendezvous_abort.md.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from mpit_tpu.ops.fused_update import fused_enabled
from mpit_tpu.optim.msgd import (
    MSGDConfig,
    _effective_lr,
    msgd_commit,
    msgd_lookahead,
)
from mpit_tpu.parallel.fused import mesh_fused_commit
from mpit_tpu.parallel.mesh import put_global, put_local


class MeshEASGD:
    """Synchronous elastic-averaging trainer over a (dp, shard) mesh.

    ``value_and_grad_fn(w, xb, yb) -> (loss, grad)`` operates on one
    worker's flat parameter vector.  Batches are fed stacked per worker:
    ``(n_dp, batch, ...)``.
    """

    def __init__(
        self,
        mesh: Mesh,
        value_and_grad_fn: Callable[..., Tuple[jnp.ndarray, jnp.ndarray]],
        cfg: MSGDConfig,
        *,
        mva: float,
        su: int = 1,
    ):
        if not (su > 0 and mva > 0):
            raise ValueError("easgd requires su>0 and mva>0 (reference :86)")
        self.mesh = mesh
        self.cfg = cfg
        self.mva = float(mva)
        self.su = int(su)
        self.n_dp = mesh.shape["dp"]
        self._steps = 0
        # Fused pallas commit: a pallas call can't be auto-partitioned by
        # the sharded jit, but shard_map runs the 1-D sweep on each
        # device's own (worker-row, shard) tile (parallel/fused.py).  The
        # kernel always folds the velocity update, so it needs mom > 0.
        use_fused = cfg.mom > 0 and fused_enabled(cfg.use_fused)
        self._use_fused = use_fused
        cfg_inner = cfg._replace(use_fused=False)  # vmapped halves stay XLA

        ws = NamedSharding(mesh, P("dp", "shard"))   # per-worker param rows
        ks = NamedSharding(mesh, P("dp"))            # per-worker counters
        cs = NamedSharding(mesh, P("shard"))         # center shards
        bs = NamedSharding(mesh, P("dp"))            # per-worker batches
        self._shardings = {"w": ws, "k": ks, "center": cs, "batch": bs}

        if use_fused:
            fused_local = mesh_fused_commit(
                mesh, P("dp", "shard"), P("dp"), l2wd=cfg.l2wd
            )
            fused_sync = mesh_fused_commit(
                mesh, P("dp", "shard"), P("dp"), l2wd=cfg.l2wd, retract=True
            )

        def _grads(w, vt, k, *args):
            def _one(w_i, vt_i, k_i, *a):
                st = {"k": k_i, "vt": vt_i}
                w_la, st = msgd_lookahead(w_i, st, cfg_inner)
                loss, grad = value_and_grad_fn(w_la, *a)
                return w_la, st["vt"], grad, loss

            return jax.vmap(_one)(w, vt, k, *args)

        def _commit(w_la, vt, g, k, sug=None):
            if use_fused:
                clr = jax.vmap(lambda ki: _effective_lr(cfg, ki))(k)
                if sug is not None:
                    return fused_sync(w_la, vt, g, clr, sug)
                return fused_local(w_la, vt, g, clr)

            def _c(w_i, g_i, vt_i, k_i, *s):
                w2, st = msgd_commit(
                    w_i, g_i, {"k": k_i, "vt": vt_i}, cfg_inner
                )
                if s:  # elastic retract after the local update (ref :66)
                    w2 = w2 - s[0]
                return w2, st["vt"]

            if sug is not None:
                return jax.vmap(_c)(w_la, g, vt, k, sug)
            return jax.vmap(_c)(w_la, g, vt, k)

        def _local(w, vt, k, *args):
            w_la, vt2, g, loss = _grads(w, vt, k, *args)
            w_n, vt_n = _commit(w_la, vt2, g, k)
            return w_n, vt_n, k + 1, loss

        def _step_sync(w, vt, k, center, *args):
            # Sync round: pull+push around the local update, same ordering
            # as the reference (elastic delta uses pre-update w,
            # optim-eamsgd.lua:54-61; retract after localupdate, :66 —
            # the retract rides the fused commit sweep when enabled).
            sug = self.mva * (w - center[None, :])  # every worker's push
            new_center = center + jnp.sum(sug, axis=0)
            w_la, vt2, g, loss = _grads(w, vt, k, *args)
            w_n, vt_n = _commit(w_la, vt2, g, k, sug)
            return w_n, vt_n, k + 1, new_center, loss

        self._local_jit = jax.jit(
            _local,
            in_shardings=(ws, ws, ks) + (bs, bs),
            out_shardings=(ws, ws, ks, ks),
            donate_argnums=(0, 1, 2),
        )
        self._sync_jit = jax.jit(
            _step_sync,
            in_shardings=(ws, ws, ks, cs) + (bs, bs),
            out_shardings=(ws, ws, ks, cs, ks),
            donate_argnums=(0, 1, 2, 3),
        )

        # Whole-epoch program: lax.scan over a staged (nsteps, ...) epoch
        # with the elastic exchange as a lax.cond on the device-resident
        # step counter.  ONE dispatch trains a whole epoch — on tunneled
        # platforms the per-call dispatch round-trip (~ms) otherwise
        # bounds small-model throughput, not the TPU (measured: the
        # step-loop path swung 17k-34k samples/s with tunnel load while
        # the scan path holds the device-limited rate).
        def _epoch(w, vt, k, center, xs, ys):
            def body(carry, xy):
                w, vt, k, center = carry
                xb, yb = xy

                def _sync(ops):
                    w, vt, k, center = ops
                    w2, vt2, k2, c2, loss = _step_sync(w, vt, k, center,
                                                       xb, yb)
                    return (w2, vt2, k2, c2), loss

                def _loc(ops):
                    w, vt, k, center = ops
                    w2, vt2, k2, loss = _local(w, vt, k, xb, yb)
                    return (w2, vt2, k2, center), loss

                # Sync schedule from the device-resident counter (k rows
                # advance in lockstep; row 0 stands for all).  Fresh runs
                # match step()'s host-side ``_steps % su`` schedule
                # exactly; resumed runs continue the *global* schedule,
                # which step() (counting from process start) does not.
                return jax.lax.cond(
                    (k[0] % self.su) == 0, _sync, _loc, (w, vt, k, center)
                )

            (w, vt, k, center), losses = jax.lax.scan(
                body, (w, vt, k, center), (xs, ys)
            )
            return w, vt, k, center, losses

        ls = NamedSharding(mesh, P())  # per-step losses, replicated
        ebs = NamedSharding(mesh, P(None, *bs.spec))  # staged epoch batches
        self._epoch_jit = jax.jit(
            _epoch,
            in_shardings=(ws, ws, ks, cs, ebs, ebs),
            out_shardings=(ws, ws, ks, cs, ls),
            donate_argnums=(0, 1, 2, 3),
        )

    # -- state ---------------------------------------------------------------

    def init(self, w0: jnp.ndarray) -> Dict[str, Any]:
        """Replicate a single flat param vector into per-worker rows + the
        center, placed with their mesh shardings (all workers and the
        center start identical — the reference's init-once protocol,
        pserver.lua:92-102)."""
        w = jnp.broadcast_to(w0[None, :], (self.n_dp, w0.shape[0]))
        state = {
            "w": put_global(w, self._shardings["w"]),
            "vt": put_global(jnp.zeros_like(w), self._shardings["w"]),
            "k": put_global(
                jnp.zeros((self.n_dp,), jnp.int32), self._shardings["k"]
            ),
            # Copy w0: device_put may alias the caller's buffer for the
            # shard landing on the same device, and _sync_jit donates the
            # center — without the copy the first sync round deletes the
            # caller's w0.
            "center": put_global(
                jnp.array(w0, copy=True), self._shardings["center"]
            ),
        }
        self._steps = 0
        return state

    @property
    def batch_sharding(self):
        return self._shardings["batch"]

    def shard_batch(self, *arrays: jnp.ndarray):
        """Place (n_dp, batch, ...) stacked arrays with the dp sharding.
        Multi-process: pass only this process's worker rows
        (:func:`mpit_tpu.parallel.mesh.process_local_rows`)."""
        return tuple(put_local(a, self._shardings["batch"]) for a in arrays)

    # -- stepping ------------------------------------------------------------

    def step(self, state: Dict[str, Any], *batch: jnp.ndarray):
        """One training step for every worker; elastic exchange on every
        su-th call (first call included, as in the reference's
        ``k % su == 0`` test, optim-eamsgd.lua:47)."""
        if self._steps % self.su == 0:
            w, vt, k, center, loss = self._sync_jit(
                state["w"], state["vt"], state["k"], state["center"], *batch
            )
        else:
            w, vt, k, loss = self._local_jit(
                state["w"], state["vt"], state["k"], *batch
            )
            center = state["center"]
        self._steps += 1
        return {"w": w, "vt": vt, "k": k, "center": center}, loss

    def center_params(self, state: Dict[str, Any]) -> jnp.ndarray:
        return state["center"]

    def set_steps(self, n: int) -> None:
        """Resynchronize the host-side sync-schedule counter after steps
        were advanced outside :meth:`step`/:meth:`run_epoch` — e.g. the
        device_loop trainer runs the epoch scan inside a
        ``lax.while_loop``, advancing the device-resident schedule
        without touching this counter.  Trainer-owned so the invariant
        lives where the counter does."""
        self._steps = int(n)

    def run_epoch(self, state: Dict[str, Any], x_ep: jnp.ndarray,
                  y_ep: jnp.ndarray):
        """Train a whole staged epoch — ``(nsteps, n_dp, batch, ...)``
        arrays already placed with the epoch sharding — in ONE jitted
        scan.  Returns the new state and the (nsteps,) per-step losses.
        Equivalent trajectory to ``nsteps`` :meth:`step` calls for runs
        whose state counter started at 0 (regression-tested); the sync
        schedule reads the device-resident counter, so a resumed run
        continues the global schedule."""
        w, vt, k, center, losses = self._epoch_jit(
            state["w"], state["vt"], state["k"], state["center"], x_ep, y_ep
        )
        self._steps += int(x_ep.shape[0])
        return {"w": w, "vt": vt, "k": k, "center": center}, losses

    def precompile_epoch(self, state: Dict[str, Any], x_ep: jnp.ndarray,
                         y_ep: jnp.ndarray) -> None:
        """Compile-and-warm the whole-epoch scan program for this epoch
        shape without consuming the caller's buffers or advancing
        ``_steps``.

        Deliberately EXECUTES the program (on copied state) rather than
        AOT ``lower().compile()``: AOT compilation does not populate the
        jit's dispatch cache, so the first timed epoch would still pay
        tracing + cache deserialization — exactly the cost this warmup
        exists to move before t0.  One warm scan pass is milliseconds of
        device compute; the copies are transient."""
        cp = {k: jnp.copy(v) for k, v in state.items()}
        out = self._epoch_jit(cp["w"], cp["vt"], cp["k"], cp["center"],
                              x_ep, y_ep)
        from mpit_tpu.utils.timing import fetch_scalar

        fetch_scalar(out[-1])

    def precompile(self, state: Dict[str, Any], *batch: jnp.ndarray) -> None:
        """Compile-and-warm BOTH step programs (local and sync) against
        the real state/batch shardings, without advancing the sync
        schedule or consuming the caller's buffers.

        The jits donate their state arguments, so fresh copies are run
        through them and the outputs discarded; ``self._steps`` is
        untouched — a subsequent :meth:`step` sequence hits the elastic
        exchange on exactly the same schedule as an unwarmed run."""
        cp = {k: jnp.copy(v) for k, v in state.items()}
        self._sync_jit(cp["w"], cp["vt"], cp["k"], cp["center"], *batch)
        cp = {k: jnp.copy(v) for k, v in state.items()}
        out_l = self._local_jit(cp["w"], cp["vt"], cp["k"], *batch)
        from mpit_tpu.utils.timing import fetch_scalar

        # Devices execute their queue in order: fetching from the LAST
        # enqueued program fences both executions.
        fetch_scalar(out_l[-1])
