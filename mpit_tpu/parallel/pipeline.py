"""Pipeline parallelism: GPipe-style microbatch pipeline over a ``pp``
mesh axis.

Not in the reference (SURVEY §2 parallelism table: PP absent) — added as
the TPU-native expression: each device owns one stage's parameters
(stacked pytree leaves sharded on their leading axis), microbatched
activations flow stage-to-stage over ``ppermute`` (one ICI neighbor hop
per tick), and the schedule is a ``lax.scan`` of ``m + n - 1`` ticks
(the GPipe fill+drain bubble).  Differentiable end-to-end: scan,
ppermute and psum all have transpose rules, so pipelined training steps
backprop through the same ring.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
from mpit_tpu.parallel.collective import shard_map  # version shim
from jax.sharding import Mesh, PartitionSpec as P


def pipeline(
    mesh: Mesh,
    stage_fn: Callable[[Any, jnp.ndarray], jnp.ndarray],
    axis: str = "pp",
):
    """Build ``fn(stacked_params, microbatches) -> outputs``.

    - ``stacked_params``: pytree whose leaves have a leading stage axis
      of size ``n = mesh.shape[axis]`` (stage i's slice lives on device
      i); under jit they are sharded ``P(axis)`` so each device holds
      only its stage.
    - ``microbatches``: ``(m, B, ...)`` — m microbatches, replicated.
    - ``stage_fn(params_i, x) -> y`` with ``y.shape == x.shape`` (equal
      inter-stage width, the GPipe contract).

    Returns ``(m, B, ...)`` outputs (replicated; the last stage's results
    are broadcast with one masked psum).
    """
    n = mesh.shape[axis]
    perm = [(i, (i + 1) % n) for i in range(n)]

    def _local(stacked, xs):
        params = jax.tree_util.tree_map(lambda a: a[0], stacked)  # my stage
        idx = jax.lax.axis_index(axis)
        m = xs.shape[0]
        is_first = idx == 0
        is_last = idx == n - 1

        def tick(carry, t):
            # Stage 0 feeds microbatch t (clamped past the end during
            # drain); everyone else consumes what arrived on the ring.
            x0 = jax.lax.dynamic_index_in_dim(
                xs, jnp.clip(t, 0, m - 1), 0, keepdims=False
            )
            x_in = jnp.where(is_first, x0, carry)
            y = stage_fn(params, x_in)
            # The microbatch leaving the last stage this tick.
            out_t = t - (n - 1)
            emit = jnp.where(is_last & (out_t >= 0), y, jnp.zeros_like(y))
            carry_next = jax.lax.ppermute(y, axis, perm)
            return carry_next, (emit, out_t)

        carry0 = jnp.zeros_like(xs[0])
        _, (emits, out_ts) = jax.lax.scan(
            tick, carry0, jnp.arange(m + n - 1)
        )
        # Scatter ticks back to microbatch order: tick t emitted
        # microbatch t-(n-1); ticks before the pipe filled emitted zeros
        # with out_t < 0, which the clip parks on row 0 — add them there
        # first, they are zero.
        outs = jnp.zeros_like(xs)
        outs = outs.at[jnp.clip(out_ts, 0, m - 1)].add(emits)
        # Broadcast from the last stage to every device.
        return jax.lax.psum(outs, axis)

    return shard_map(
        _local,
        mesh=mesh,
        in_specs=(P(axis), P()),
        out_specs=P(),
        check_vma=False,
    )


def stack_stage_params(params_per_stage) -> Any:
    """Stack a list of per-stage pytrees into the stacked layout
    ``pipeline`` expects (leading stage axis on every leaf)."""
    return jax.tree_util.tree_map(
        lambda *leaves: jnp.stack(leaves), *params_per_stage
    )
