"""Sequence-parallel ring attention over an ICI ring.

The reference has no long-context machinery at all (short per-example QA
sentences, SURVEY.md §5) — this module is the TPU-native long-context
capability built on the same collective-permute primitive the PS mesh
layer uses (:func:`mpit_tpu.parallel.collective.ring_shift`):

- the sequence axis of ``(B, L, H, D)`` activations is sharded over a
  mesh axis (``sp``): every device holds one contiguous chunk of the
  sequence and ALL heads — attention memory per device is
  O(B·(L/n)·H·D) regardless of L;
- each of the n ring steps computes blockwise attention of the local Q
  chunk against the KV chunk currently in hand — masked by **global**
  positions via the q/kv offsets of
  :func:`mpit_tpu.ops.flash_attention.block_attention_partial` — then
  passes the KV chunk to the next device with ``ppermute`` (one ICI
  neighbor hop; XLA overlaps the transfer with the block compute);
- per-step unnormalized partials ``(acc, m, l)`` are merged with the
  online-softmax combine (:func:`merge_partials`), so the result is
  *exactly* full attention, not an approximation.

Two block implementations: ``jnp`` (differentiable end-to-end; XLA fuses
the blockwise math) and ``pallas`` (the flash kernel emitting partials;
forward wrapped in a custom VJP whose backward is a second ring over the
pallas flash-backward pair kernels — (dk, dv) accumulators ride the KV
rotation, P is re-derived blockwise from the saved row log-sum-exp, so
backward memory is O(block) scratch per pair, never an (L, L) or even
per-chunk (C, C) score matrix).

Causal ring attention has two layouts: ``contiguous`` (every device
computes all n steps, most of them fully masked on low-rank devices) and
``zigzag`` (each device owns an early + late half-chunk, balancing the
causal work — see :func:`_ring_chunks_zigzag`).
"""

from __future__ import annotations

import contextlib
import functools
from typing import Callable, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from mpit_tpu.parallel.collective import shard_map  # version shim
from jax.sharding import Mesh, PartitionSpec as P

from mpit_tpu.ops.flash_attention import (
    _lse_of,
    block_attention_partial,
    finalize_partials,
    flash_attention_bwd_pair,
    flash_attention_partial,
    merge_partials,
)


def sp_mesh(devices: Sequence[jax.Device] | None = None, axis: str = "sp") -> Mesh:
    """1-D sequence-parallel mesh over all (or the given) devices."""
    from mpit_tpu.utils.platform import default_devices

    devs = list(devices if devices is not None else default_devices())
    return Mesh(np.array(devs), (axis,))


def _ring_chunks(q, k, v, *, axis, n, partial_fn, with_lse=False):
    """Shared ring loop: local (B, H, C, D) chunks, returns (B, H, C, D)
    (with ``with_lse``: also the (B, H, C) row log-sum-exp residual the
    flash backward needs).

    ``partial_fn(q, k, v, q_offset, kv_offset) -> (acc, m, l)``.
    """
    my = jax.lax.axis_index(axis)
    chunk = q.shape[-2]
    q_off = my * chunk
    perm = [(i, (i + 1) % n) for i in range(n)]

    acc = jnp.zeros(q.shape[:-1] + (v.shape[-1],), jnp.float32)
    m = jnp.full(q.shape[:-1], float("-inf"), jnp.float32)
    l = jnp.zeros(q.shape[:-1], jnp.float32)

    kb, vb = k, v
    for s in range(n):
        # KV chunk in hand after s hops started at device (my - s).
        owner = (my + (n - s)) % n
        part = partial_fn(q, kb, vb, q_off, owner * chunk)
        acc, m, l = merge_partials((acc, m, l), part)
        if s + 1 < n:
            kb = jax.lax.ppermute(kb, axis, perm)
            vb = jax.lax.ppermute(vb, axis, perm)
    out = finalize_partials(acc, l, dtype=q.dtype)
    return (out, _lse_of(m, l)) if with_lse else out


def _ring_bwd_chunks(q, k, v, do, o, lse, *, axis, n, pair_bwd):
    """Backward ring for the contiguous layout.

    ``pair_bwd(q, k, v, do, lse, delta, q_offset, kv_offset) ->
    (dq, dk, dv)`` is the per-pair flash backward.  KV chunks rotate
    around the ring *together with* their accumulated (dk, dv); after the
    n-th visit one final hop delivers each chunk's gradient back to its
    owner.  dq accumulates locally.  Peak memory per device: the local
    chunks plus one rotating (k, v, dk, dv) set — O(L/n), matching the
    forward."""
    my = jax.lax.axis_index(axis)
    chunk = q.shape[-2]
    q_off = my * chunk
    perm = [(i, (i + 1) % n) for i in range(n)]

    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32), -1)
    dq = jnp.zeros(q.shape, jnp.float32)
    dk = jnp.zeros(k.shape, jnp.float32)
    dv = jnp.zeros(v.shape, jnp.float32)

    kb, vb = k, v
    for s in range(n):
        owner = (my + (n - s)) % n
        dqi, dki, dvi = pair_bwd(q, kb, vb, do, lse, delta, q_off,
                                 owner * chunk)
        dq = dq + dqi.astype(jnp.float32)
        dk = dk + dki.astype(jnp.float32)
        dv = dv + dvi.astype(jnp.float32)
        if s + 1 < n:
            kb = jax.lax.ppermute(kb, axis, perm)
            vb = jax.lax.ppermute(vb, axis, perm)
            dk = jax.lax.ppermute(dk, axis, perm)
            dv = jax.lax.ppermute(dv, axis, perm)
    # The chunk in hand after the loop belongs to (my+1)%n: one final hop
    # brings every accumulated (dk, dv) home.
    dk = jax.lax.ppermute(dk, axis, perm)
    dv = jax.lax.ppermute(dv, axis, perm)
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


def _ring_chunks_zigzag(q, k, v, *, axis, n, partial_fn, with_lse=False):
    """Load-balanced causal ring: each device holds TWO half-chunks of the
    zigzag layout — global chunk ``d`` and chunk ``2n-1-d`` — so causal
    useful work is ~2 half-blocks per device per step instead of the
    contiguous layout's all-or-nothing (device 0 would mask away n-1 of
    its n steps while device n-1 computes all of them).

    Liveness per (q-half, kv-half) pair at ring step s (owner ``o``):
    (early_q=d, early_kv=o) live iff d >= o (runtime); (early_q,
    late_kv=2n-1-o) never live (late chunks are always ahead of early
    ones); (late_q=2n-1-d, early_kv) always live; (late_q, late_kv) live
    iff o >= d (runtime).  The two static cases are resolved at trace
    time; the two data-dependent ones are ``lax.cond`` so dead blocks
    cost nothing at runtime.
    """
    my = jax.lax.axis_index(axis)
    if q.shape[-2] % 2:
        raise ValueError(
            f"zigzag layout needs an even per-device chunk, got "
            f"{q.shape[-2]} (global L must divide evenly by 2n={2 * n})"
        )
    c = q.shape[-2] // 2
    q_halves = (q[..., :c, :], q[..., c:, :])
    q_offs = (my * c, (2 * n - 1 - my) * c)
    perm = [(i, (i + 1) % n) for i in range(n)]

    def zero_like_part(qh):
        return (
            jnp.zeros(qh.shape[:-1] + (v.shape[-1],), jnp.float32),
            jnp.full(qh.shape[:-1], float("-inf"), jnp.float32),
            jnp.zeros(qh.shape[:-1], jnp.float32),
        )

    parts = [zero_like_part(qh) for qh in q_halves]
    kb, vb = k, v
    for s in range(n):
        owner = (my + (n - s)) % n
        kv_halves = (
            (kb[..., :c, :], vb[..., :c, :]),
            (kb[..., c:, :], vb[..., c:, :]),
        )
        kv_offs = (owner * c, (2 * n - 1 - owner) * c)

        def compute(qi, ki):
            return partial_fn(
                q_halves[qi], kv_halves[ki][0], kv_halves[ki][1],
                q_offs[qi], kv_offs[ki],
            )

        # (late_q, early_kv): statically live.
        parts[1] = merge_partials(parts[1], compute(1, 0))
        # (early_q, early_kv): live iff my >= owner.
        parts[0] = merge_partials(
            parts[0],
            jax.lax.cond(
                my >= owner, lambda: compute(0, 0),
                lambda: zero_like_part(q_halves[0]),
            ),
        )
        # (late_q, late_kv): live iff owner >= my.
        parts[1] = merge_partials(
            parts[1],
            jax.lax.cond(
                owner >= my, lambda: compute(1, 1),
                lambda: zero_like_part(q_halves[1]),
            ),
        )
        # (early_q, late_kv): statically dead — skipped.
        if s + 1 < n:
            kb = jax.lax.ppermute(kb, axis, perm)
            vb = jax.lax.ppermute(vb, axis, perm)
    outs = [
        finalize_partials(acc, l, dtype=q.dtype) for (acc, _m, l) in parts
    ]
    out = jnp.concatenate(outs, axis=-2)
    if with_lse:
        lse = jnp.concatenate(
            [_lse_of(m, l) for (_acc, m, l) in parts], axis=-1
        )
        return out, lse
    return out


def _ring_bwd_chunks_zigzag(q, k, v, do, o, lse, *, axis, n, pair_bwd):
    """Backward ring for the zigzag layout: same two-half decomposition
    and static/dynamic pair liveness as the forward (see
    :func:`_ring_chunks_zigzag`), with (dk, dv) riding the KV rotation
    exactly as in :func:`_ring_bwd_chunks`."""
    my = jax.lax.axis_index(axis)
    c = q.shape[-2] // 2
    q_halves = (q[..., :c, :], q[..., c:, :])
    do_halves = (do[..., :c, :], do[..., c:, :])
    lse_halves = (lse[..., :c], lse[..., c:])
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32), -1)
    delta_halves = (delta[..., :c], delta[..., c:])
    q_offs = (my * c, (2 * n - 1 - my) * c)
    perm = [(i, (i + 1) % n) for i in range(n)]

    dq_halves = [jnp.zeros(qh.shape, jnp.float32) for qh in q_halves]
    dk = jnp.zeros(k.shape, jnp.float32)
    dv = jnp.zeros(v.shape, jnp.float32)

    kb, vb = k, v
    for s in range(n):
        owner = (my + (n - s)) % n
        kv_halves = (
            (kb[..., :c, :], vb[..., :c, :]),
            (kb[..., c:, :], vb[..., c:, :]),
        )
        kv_offs = (owner * c, (2 * n - 1 - owner) * c)

        def pair(qi, ki):
            return pair_bwd(
                q_halves[qi], kv_halves[ki][0], kv_halves[ki][1],
                do_halves[qi], lse_halves[qi], delta_halves[qi],
                q_offs[qi], kv_offs[ki],
            )

        def zeros(qi, ki):
            return lambda: (
                jnp.zeros(q_halves[qi].shape, q.dtype),
                jnp.zeros(kv_halves[ki][0].shape, k.dtype),
                jnp.zeros(kv_halves[ki][1].shape, v.dtype),
            )

        def add(qi, ki, grads):
            dqi, dki, dvi = grads
            dq_halves[qi] = dq_halves[qi] + dqi.astype(jnp.float32)
            lo, hi = (0, c) if ki == 0 else (c, 2 * c)
            return (
                dk.at[..., lo:hi, :].add(dki.astype(jnp.float32)),
                dv.at[..., lo:hi, :].add(dvi.astype(jnp.float32)),
            )

        # (late_q, early_kv): statically live.
        dk, dv = add(1, 0, pair(1, 0))
        # (early_q, early_kv): live iff my >= owner.
        dk, dv = add(0, 0, jax.lax.cond(
            my >= owner, lambda: pair(0, 0), zeros(0, 0)))
        # (late_q, late_kv): live iff owner >= my.
        dk, dv = add(1, 1, jax.lax.cond(
            owner >= my, lambda: pair(1, 1), zeros(1, 1)))
        # (early_q, late_kv): statically dead — skipped.
        if s + 1 < n:
            kb = jax.lax.ppermute(kb, axis, perm)
            vb = jax.lax.ppermute(vb, axis, perm)
            dk = jax.lax.ppermute(dk, axis, perm)
            dv = jax.lax.ppermute(dv, axis, perm)
    dk = jax.lax.ppermute(dk, axis, perm)
    dv = jax.lax.ppermute(dv, axis, perm)
    dq = jnp.concatenate(dq_halves, axis=-2)
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


def zigzag_order(n: int):
    """Global chunk ids in device order for the zigzag layout: device d
    owns chunks (d, 2n-1-d)."""
    order = []
    for d in range(n):
        order.extend([d, 2 * n - 1 - d])
    return order


def zigzag_permute(x: jnp.ndarray, n: int, axis: int = 1) -> jnp.ndarray:
    """Reorder a sequence axis of 2n equal chunks into the zigzag device
    layout (inverse: :func:`zigzag_unpermute`)."""
    L = x.shape[axis]
    if L % (2 * n):
        raise ValueError(f"sequence length {L} not divisible by 2n={2 * n}")
    c = L // (2 * n)
    idx = jnp.concatenate(
        [jnp.arange(g * c, (g + 1) * c) for g in zigzag_order(n)]
    )
    return jnp.take(x, idx, axis=axis)


def zigzag_unpermute(x: jnp.ndarray, n: int, axis: int = 1) -> jnp.ndarray:
    L = x.shape[axis]
    if L % (2 * n):
        raise ValueError(f"sequence length {L} not divisible by 2n={2 * n}")
    c = L // (2 * n)
    order = zigzag_order(n)
    inv = [0] * (2 * n)
    for pos, g in enumerate(order):
        inv[g] = pos
    idx = jnp.concatenate(
        [jnp.arange(p * c, (p + 1) * c) for p in inv]
    )
    return jnp.take(x, idx, axis=axis)


def _precision_ctx(precision):
    return (jax.default_matmul_precision(precision) if precision
            else contextlib.nullcontext())


_RING_LOOPS = {"contiguous": _ring_chunks, "zigzag": _ring_chunks_zigzag}


def _ring_jnp(q, k, v, *, axis, n, causal, sm_scale, precision=None,
              layout="contiguous"):
    fn = lambda q2, k2, v2, qo, ko: block_attention_partial(
        q2, k2, v2, causal=causal, sm_scale=sm_scale, q_offset=qo, kv_offset=ko
    )
    with _precision_ctx(precision):
        return _RING_LOOPS[layout](q, k, v, axis=axis, n=n, partial_fn=fn)


def _ring_pallas(q, k, v, *, axis, n, causal, sm_scale, block_q, block_k,
                 interpret, precision, layout="contiguous", with_lse=False):
    fn = lambda q2, k2, v2, qo, ko: flash_attention_partial(
        q2, k2, v2, causal=causal, sm_scale=sm_scale, q_offset=qo,
        kv_offset=ko, block_q=block_q, block_k=block_k, interpret=interpret,
        precision=precision,
    )
    return _RING_LOOPS[layout](
        q, k, v, axis=axis, n=n, partial_fn=fn, with_lse=with_lse
    )


_RING_BWD_LOOPS = {
    "contiguous": _ring_bwd_chunks, "zigzag": _ring_bwd_chunks_zigzag,
}


def _ring_pallas_bwd(q, k, v, do, o, lse, *, axis, n, causal, sm_scale,
                     block_q, block_k, interpret, precision,
                     layout="contiguous"):
    fn = lambda q2, k2, v2, do2, lse2, delta2, qo, ko: (
        flash_attention_bwd_pair(
            q2, k2, v2, do2, lse2, delta=delta2, causal=causal,
            sm_scale=sm_scale, q_offset=qo, kv_offset=ko, block_q=block_q,
            block_k=block_k, interpret=interpret, precision=precision,
        )
    )
    return _RING_BWD_LOOPS[layout](
        q, k, v, do, o, lse, axis=axis, n=n, pair_bwd=fn
    )


@functools.lru_cache(maxsize=64)
def _make_local_fn(axis, n, causal, sm_scale, impl, block_q, block_k,
                   interpret, precision, layout="contiguous"):
    jnp_fn = functools.partial(
        _ring_jnp, axis=axis, n=n, causal=causal, sm_scale=sm_scale,
        precision=precision, layout=layout,
    )
    if impl == "jnp":
        return jnp_fn

    cfg = dict(
        axis=axis, n=n, causal=causal, sm_scale=sm_scale, block_q=block_q,
        block_k=block_k, interpret=interpret, precision=precision,
        layout=layout,
    )
    pallas_fwd = functools.partial(_ring_pallas, **cfg)

    @jax.custom_vjp
    def fn(q, k, v):
        return pallas_fwd(q, k, v)

    def fwd(q, k, v):
        # One forward with the LSE residual kept: the backward ring then
        # needs no O(C^2) recompute — each pair re-derives P blockwise
        # inside the pallas backward kernels.
        out, lse = pallas_fwd(q, k, v, with_lse=True)
        return out, (q, k, v, out, lse)

    def bwd(res, g):
        q, k, v, o, lse = res
        return _ring_pallas_bwd(q, k, v, g.astype(q.dtype), o, lse, **cfg)

    fn.defvjp(fwd, bwd)
    return fn


def ring_attention(
    mesh: Mesh,
    axis: str = "sp",
    *,
    causal: bool = True,
    sm_scale: float | None = None,
    impl: str = "auto",
    block_q: int | None = None,
    block_k: int | None = None,
    interpret: bool | None = None,
    precision: str | None = None,
    layout: str = "contiguous",
    permute_inputs: bool = True,
    batch_axis: str | None = None,
) -> Callable[[jnp.ndarray, jnp.ndarray, jnp.ndarray], jnp.ndarray]:
    """Build the sequence-parallel attention fn over ``mesh[axis]``.

    Takes/returns global ``(B, L, H, D)`` arrays with L sharded over
    ``axis`` (L must divide evenly).  ``impl``: 'jnp', 'pallas', or
    'auto' (pallas on TPU, jnp elsewhere).  Callable from inside jit.

    ``batch_axis`` additionally shards B over another mesh axis (the
    dp x sp composition: independent rings run per data-parallel group;
    without it, calling from a dp-sharded program would all-gather the
    batch at the shard_map boundary).

    ``layout='zigzag'`` (causal only) balances causal work across the
    ring — each device owns an early and a late half-chunk, halving the
    worst-device compute per step.  With ``permute_inputs`` (default) the
    returned fn takes/returns natural sequence order, paying one
    cross-shard permutation per call; a model calling attention per layer
    can instead pre-permute activations once with
    :func:`zigzag_permute`, pass ``permute_inputs=False``, and
    un-permute final outputs with :func:`zigzag_unpermute`.
    """
    if impl == "auto":
        impl = "pallas" if jax.default_backend() == "tpu" else "jnp"
    if impl not in ("jnp", "pallas"):
        raise ValueError(f"impl must be auto|jnp|pallas, got {impl!r}")
    if layout not in ("contiguous", "zigzag"):
        raise ValueError(f"layout must be contiguous|zigzag, got {layout!r}")
    if layout == "zigzag" and not causal:
        raise ValueError(
            "layout='zigzag' requires causal=True (the static block-"
            "liveness it exploits is the causal structure)"
        )
    n = mesh.shape[axis]
    local = _make_local_fn(
        axis, n, bool(causal),
        # Static cache key: reject traced sm_scale with a clear error.
        None if sm_scale is None else float(sm_scale),
        impl,
        None if block_q is None else int(block_q),
        None if block_k is None else int(block_k),
        interpret, precision, layout,
    )

    def _local(q, k, v):
        # (B, C, H, D) chunk -> heads-major for the block math, and back.
        qh, kh, vh = (x.transpose(0, 2, 1, 3) for x in (q, k, v))
        return local(qh, kh, vh).transpose(0, 2, 1, 3)

    spec = P(batch_axis, axis, None, None)
    mapped = shard_map(
        _local, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False,
    )
    if layout == "contiguous" or not permute_inputs:
        return mapped

    def zigzagged(q, k, v):
        qz, kz, vz = (zigzag_permute(x, n, axis=1) for x in (q, k, v))
        return zigzag_unpermute(mapped(qz, kz, vz), n, axis=1)

    return zigzagged
