"""Sequence-parallel ring attention over an ICI ring.

The reference has no long-context machinery at all (short per-example QA
sentences, SURVEY.md §5) — this module is the TPU-native long-context
capability built on the same collective-permute primitive the PS mesh
layer uses (:func:`mpit_tpu.parallel.collective.ring_shift`):

- the sequence axis of ``(B, L, H, D)`` activations is sharded over a
  mesh axis (``sp``): every device holds one contiguous chunk of the
  sequence and ALL heads — attention memory per device is
  O(B·(L/n)·H·D) regardless of L;
- each of the n ring steps computes blockwise attention of the local Q
  chunk against the KV chunk currently in hand — masked by **global**
  positions via the q/kv offsets of
  :func:`mpit_tpu.ops.flash_attention.block_attention_partial` — then
  passes the KV chunk to the next device with ``ppermute`` (one ICI
  neighbor hop; XLA overlaps the transfer with the block compute);
- per-step unnormalized partials ``(acc, m, l)`` are merged with the
  online-softmax combine (:func:`merge_partials`), so the result is
  *exactly* full attention, not an approximation.

Two block implementations: ``jnp`` (differentiable end-to-end; XLA fuses
the blockwise math) and ``pallas`` (the flash kernel emitting partials;
forward wrapped in a custom VJP whose backward recomputes through the
jnp ring — per-chunk blockwise memory, no O(L²) materialization).

Causal ring attention computes all n steps on every device (the usual
non-load-balanced ring; a zigzag layout is a later optimization).
"""

from __future__ import annotations

import contextlib
import functools
from typing import Callable, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from mpit_tpu.ops.flash_attention import (
    block_attention_partial,
    finalize_partials,
    flash_attention_partial,
    merge_partials,
)


def sp_mesh(devices: Sequence[jax.Device] | None = None, axis: str = "sp") -> Mesh:
    """1-D sequence-parallel mesh over all (or the given) devices."""
    devs = list(devices if devices is not None else jax.devices())
    return Mesh(np.array(devs), (axis,))


def _ring_chunks(q, k, v, *, axis, n, partial_fn):
    """Shared ring loop: local (B, H, C, D) chunks, returns (B, H, C, D).

    ``partial_fn(q, k, v, q_offset, kv_offset) -> (acc, m, l)``.
    """
    my = jax.lax.axis_index(axis)
    chunk = q.shape[-2]
    q_off = my * chunk
    perm = [(i, (i + 1) % n) for i in range(n)]

    acc = jnp.zeros(q.shape[:-1] + (v.shape[-1],), jnp.float32)
    m = jnp.full(q.shape[:-1], float("-inf"), jnp.float32)
    l = jnp.zeros(q.shape[:-1], jnp.float32)

    kb, vb = k, v
    for s in range(n):
        # KV chunk in hand after s hops started at device (my - s).
        owner = (my + (n - s)) % n
        part = partial_fn(q, kb, vb, q_off, owner * chunk)
        acc, m, l = merge_partials((acc, m, l), part)
        if s + 1 < n:
            kb = jax.lax.ppermute(kb, axis, perm)
            vb = jax.lax.ppermute(vb, axis, perm)
    return finalize_partials(acc, l, dtype=q.dtype)


def _precision_ctx(precision):
    return (jax.default_matmul_precision(precision) if precision
            else contextlib.nullcontext())


def _ring_jnp(q, k, v, *, axis, n, causal, sm_scale, precision=None):
    fn = lambda q2, k2, v2, qo, ko: block_attention_partial(
        q2, k2, v2, causal=causal, sm_scale=sm_scale, q_offset=qo, kv_offset=ko
    )
    with _precision_ctx(precision):
        return _ring_chunks(q, k, v, axis=axis, n=n, partial_fn=fn)


def _ring_pallas(q, k, v, *, axis, n, causal, sm_scale, block_q, block_k,
                 interpret, precision):
    fn = lambda q2, k2, v2, qo, ko: flash_attention_partial(
        q2, k2, v2, causal=causal, sm_scale=sm_scale, q_offset=qo,
        kv_offset=ko, block_q=block_q, block_k=block_k, interpret=interpret,
        precision=precision,
    )
    return _ring_chunks(q, k, v, axis=axis, n=n, partial_fn=fn)


@functools.lru_cache(maxsize=None)
def _make_local_fn(axis, n, causal, sm_scale, impl, block_q, block_k,
                   interpret, precision):
    jnp_fn = functools.partial(
        _ring_jnp, axis=axis, n=n, causal=causal, sm_scale=sm_scale,
        precision=precision,
    )
    if impl == "jnp":
        return jnp_fn

    pallas_fwd = functools.partial(
        _ring_pallas, axis=axis, n=n, causal=causal, sm_scale=sm_scale,
        block_q=block_q, block_k=block_k, interpret=interpret,
        precision=precision,
    )

    @jax.custom_vjp
    def fn(q, k, v):
        return pallas_fwd(q, k, v)

    def fwd(q, k, v):
        return pallas_fwd(q, k, v), (q, k, v)

    def bwd(res, g):
        q, k, v = res
        # jnp_fn already carries the precision context, so the recompute
        # matches the forward's matmul precision.
        _, vjp = jax.vjp(jnp_fn, q, k, v)
        return vjp(g.astype(q.dtype))

    fn.defvjp(fwd, bwd)
    return fn


def ring_attention(
    mesh: Mesh,
    axis: str = "sp",
    *,
    causal: bool = True,
    sm_scale: float | None = None,
    impl: str = "auto",
    block_q: int = 256,
    block_k: int = 512,
    interpret: bool | None = None,
    precision: str | None = None,
) -> Callable[[jnp.ndarray, jnp.ndarray, jnp.ndarray], jnp.ndarray]:
    """Build the sequence-parallel attention fn over ``mesh[axis]``.

    Takes/returns global ``(B, L, H, D)`` arrays with L sharded over
    ``axis`` (L must divide evenly).  ``impl``: 'jnp', 'pallas', or
    'auto' (pallas on TPU, jnp elsewhere).  Callable from inside jit.
    """
    if impl == "auto":
        impl = "pallas" if jax.default_backend() == "tpu" else "jnp"
    if impl not in ("jnp", "pallas"):
        raise ValueError(f"impl must be auto|jnp|pallas, got {impl!r}")
    n = mesh.shape[axis]
    local = _make_local_fn(
        axis, n, bool(causal), sm_scale, impl, int(block_q), int(block_k),
        interpret, precision,
    )

    def _local(q, k, v):
        # (B, C, H, D) chunk -> heads-major for the block math, and back.
        qh, kh, vh = (x.transpose(0, 2, 1, 3) for x in (q, k, v))
        return local(qh, kh, vh).transpose(0, 2, 1, 3)

    spec = P(None, axis, None, None)
    return shard_map(
        _local, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False,
    )
