"""Sequence-parallel ring attention over an ICI ring.

The reference has no long-context machinery at all (short per-example QA
sentences, SURVEY.md §5) — this module is the TPU-native long-context
capability built on the same collective-permute primitive the PS mesh
layer uses (:func:`mpit_tpu.parallel.collective.ring_shift`):

- the sequence axis of ``(B, L, H, D)`` activations is sharded over a
  mesh axis (``sp``): every device holds one contiguous chunk of the
  sequence and ALL heads — attention memory per device is
  O(B·(L/n)·H·D) regardless of L;
- each of the n ring steps computes blockwise attention of the local Q
  chunk against the KV chunk currently in hand — masked by **global**
  positions via the q/kv offsets of
  :func:`mpit_tpu.ops.flash_attention.block_attention_partial` — then
  passes the KV chunk to the next device with ``ppermute`` (one ICI
  neighbor hop; XLA overlaps the transfer with the block compute);
- per-step unnormalized partials ``(acc, m, l)`` are merged with the
  online-softmax combine (:func:`merge_partials`), so the result is
  *exactly* full attention, not an approximation.

Two block implementations: ``jnp`` (differentiable end-to-end; XLA fuses
the blockwise math) and ``pallas`` (the flash kernel emitting partials;
forward wrapped in a custom VJP whose backward recomputes through the
jnp ring — per-chunk blockwise memory, no O(L²) materialization).

Causal ring attention has two layouts: ``contiguous`` (every device
computes all n steps, most of them fully masked on low-rank devices) and
``zigzag`` (each device owns an early + late half-chunk, balancing the
causal work — see :func:`_ring_chunks_zigzag`).
"""

from __future__ import annotations

import contextlib
import functools
from typing import Callable, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from mpit_tpu.ops.flash_attention import (
    block_attention_partial,
    finalize_partials,
    flash_attention_partial,
    merge_partials,
)


def sp_mesh(devices: Sequence[jax.Device] | None = None, axis: str = "sp") -> Mesh:
    """1-D sequence-parallel mesh over all (or the given) devices."""
    from mpit_tpu.utils.platform import default_devices

    devs = list(devices if devices is not None else default_devices())
    return Mesh(np.array(devs), (axis,))


def _ring_chunks(q, k, v, *, axis, n, partial_fn):
    """Shared ring loop: local (B, H, C, D) chunks, returns (B, H, C, D).

    ``partial_fn(q, k, v, q_offset, kv_offset) -> (acc, m, l)``.
    """
    my = jax.lax.axis_index(axis)
    chunk = q.shape[-2]
    q_off = my * chunk
    perm = [(i, (i + 1) % n) for i in range(n)]

    acc = jnp.zeros(q.shape[:-1] + (v.shape[-1],), jnp.float32)
    m = jnp.full(q.shape[:-1], float("-inf"), jnp.float32)
    l = jnp.zeros(q.shape[:-1], jnp.float32)

    kb, vb = k, v
    for s in range(n):
        # KV chunk in hand after s hops started at device (my - s).
        owner = (my + (n - s)) % n
        part = partial_fn(q, kb, vb, q_off, owner * chunk)
        acc, m, l = merge_partials((acc, m, l), part)
        if s + 1 < n:
            kb = jax.lax.ppermute(kb, axis, perm)
            vb = jax.lax.ppermute(vb, axis, perm)
    return finalize_partials(acc, l, dtype=q.dtype)


def _ring_chunks_zigzag(q, k, v, *, axis, n, partial_fn):
    """Load-balanced causal ring: each device holds TWO half-chunks of the
    zigzag layout — global chunk ``d`` and chunk ``2n-1-d`` — so causal
    useful work is ~2 half-blocks per device per step instead of the
    contiguous layout's all-or-nothing (device 0 would mask away n-1 of
    its n steps while device n-1 computes all of them).

    Liveness per (q-half, kv-half) pair at ring step s (owner ``o``):
    (early_q=d, early_kv=o) live iff d >= o (runtime); (early_q,
    late_kv=2n-1-o) never live (late chunks are always ahead of early
    ones); (late_q=2n-1-d, early_kv) always live; (late_q, late_kv) live
    iff o >= d (runtime).  The two static cases are resolved at trace
    time; the two data-dependent ones are ``lax.cond`` so dead blocks
    cost nothing at runtime.
    """
    my = jax.lax.axis_index(axis)
    if q.shape[-2] % 2:
        raise ValueError(
            f"zigzag layout needs an even per-device chunk, got "
            f"{q.shape[-2]} (global L must divide evenly by 2n={2 * n})"
        )
    c = q.shape[-2] // 2
    q_halves = (q[..., :c, :], q[..., c:, :])
    q_offs = (my * c, (2 * n - 1 - my) * c)
    perm = [(i, (i + 1) % n) for i in range(n)]

    def zero_like_part(qh):
        return (
            jnp.zeros(qh.shape[:-1] + (v.shape[-1],), jnp.float32),
            jnp.full(qh.shape[:-1], float("-inf"), jnp.float32),
            jnp.zeros(qh.shape[:-1], jnp.float32),
        )

    parts = [zero_like_part(qh) for qh in q_halves]
    kb, vb = k, v
    for s in range(n):
        owner = (my + (n - s)) % n
        kv_halves = (
            (kb[..., :c, :], vb[..., :c, :]),
            (kb[..., c:, :], vb[..., c:, :]),
        )
        kv_offs = (owner * c, (2 * n - 1 - owner) * c)

        def compute(qi, ki):
            return partial_fn(
                q_halves[qi], kv_halves[ki][0], kv_halves[ki][1],
                q_offs[qi], kv_offs[ki],
            )

        # (late_q, early_kv): statically live.
        parts[1] = merge_partials(parts[1], compute(1, 0))
        # (early_q, early_kv): live iff my >= owner.
        parts[0] = merge_partials(
            parts[0],
            jax.lax.cond(
                my >= owner, lambda: compute(0, 0),
                lambda: zero_like_part(q_halves[0]),
            ),
        )
        # (late_q, late_kv): live iff owner >= my.
        parts[1] = merge_partials(
            parts[1],
            jax.lax.cond(
                owner >= my, lambda: compute(1, 1),
                lambda: zero_like_part(q_halves[1]),
            ),
        )
        # (early_q, late_kv): statically dead — skipped.
        if s + 1 < n:
            kb = jax.lax.ppermute(kb, axis, perm)
            vb = jax.lax.ppermute(vb, axis, perm)
    outs = [
        finalize_partials(acc, l, dtype=q.dtype) for (acc, _m, l) in parts
    ]
    return jnp.concatenate(outs, axis=-2)


def zigzag_order(n: int):
    """Global chunk ids in device order for the zigzag layout: device d
    owns chunks (d, 2n-1-d)."""
    order = []
    for d in range(n):
        order.extend([d, 2 * n - 1 - d])
    return order


def zigzag_permute(x: jnp.ndarray, n: int, axis: int = 1) -> jnp.ndarray:
    """Reorder a sequence axis of 2n equal chunks into the zigzag device
    layout (inverse: :func:`zigzag_unpermute`)."""
    L = x.shape[axis]
    if L % (2 * n):
        raise ValueError(f"sequence length {L} not divisible by 2n={2 * n}")
    c = L // (2 * n)
    idx = jnp.concatenate(
        [jnp.arange(g * c, (g + 1) * c) for g in zigzag_order(n)]
    )
    return jnp.take(x, idx, axis=axis)


def zigzag_unpermute(x: jnp.ndarray, n: int, axis: int = 1) -> jnp.ndarray:
    L = x.shape[axis]
    if L % (2 * n):
        raise ValueError(f"sequence length {L} not divisible by 2n={2 * n}")
    c = L // (2 * n)
    order = zigzag_order(n)
    inv = [0] * (2 * n)
    for pos, g in enumerate(order):
        inv[g] = pos
    idx = jnp.concatenate(
        [jnp.arange(p * c, (p + 1) * c) for p in inv]
    )
    return jnp.take(x, idx, axis=axis)


def _precision_ctx(precision):
    return (jax.default_matmul_precision(precision) if precision
            else contextlib.nullcontext())


_RING_LOOPS = {"contiguous": _ring_chunks, "zigzag": _ring_chunks_zigzag}


def _ring_jnp(q, k, v, *, axis, n, causal, sm_scale, precision=None,
              layout="contiguous"):
    fn = lambda q2, k2, v2, qo, ko: block_attention_partial(
        q2, k2, v2, causal=causal, sm_scale=sm_scale, q_offset=qo, kv_offset=ko
    )
    with _precision_ctx(precision):
        return _RING_LOOPS[layout](q, k, v, axis=axis, n=n, partial_fn=fn)


def _ring_pallas(q, k, v, *, axis, n, causal, sm_scale, block_q, block_k,
                 interpret, precision, layout="contiguous"):
    fn = lambda q2, k2, v2, qo, ko: flash_attention_partial(
        q2, k2, v2, causal=causal, sm_scale=sm_scale, q_offset=qo,
        kv_offset=ko, block_q=block_q, block_k=block_k, interpret=interpret,
        precision=precision,
    )
    return _RING_LOOPS[layout](q, k, v, axis=axis, n=n, partial_fn=fn)


@functools.lru_cache(maxsize=64)
def _make_local_fn(axis, n, causal, sm_scale, impl, block_q, block_k,
                   interpret, precision, layout="contiguous"):
    jnp_fn = functools.partial(
        _ring_jnp, axis=axis, n=n, causal=causal, sm_scale=sm_scale,
        precision=precision, layout=layout,
    )
    if impl == "jnp":
        return jnp_fn

    pallas_fwd = functools.partial(
        _ring_pallas, axis=axis, n=n, causal=causal, sm_scale=sm_scale,
        block_q=block_q, block_k=block_k, interpret=interpret,
        precision=precision, layout=layout,
    )

    @jax.custom_vjp
    def fn(q, k, v):
        return pallas_fwd(q, k, v)

    def fwd(q, k, v):
        return pallas_fwd(q, k, v), (q, k, v)

    def bwd(res, g):
        q, k, v = res
        # jnp_fn already carries the precision context, so the recompute
        # matches the forward's matmul precision.
        _, vjp = jax.vjp(jnp_fn, q, k, v)
        return vjp(g.astype(q.dtype))

    fn.defvjp(fwd, bwd)
    return fn


def ring_attention(
    mesh: Mesh,
    axis: str = "sp",
    *,
    causal: bool = True,
    sm_scale: float | None = None,
    impl: str = "auto",
    block_q: int = 256,
    block_k: int = 512,
    interpret: bool | None = None,
    precision: str | None = None,
    layout: str = "contiguous",
    permute_inputs: bool = True,
) -> Callable[[jnp.ndarray, jnp.ndarray, jnp.ndarray], jnp.ndarray]:
    """Build the sequence-parallel attention fn over ``mesh[axis]``.

    Takes/returns global ``(B, L, H, D)`` arrays with L sharded over
    ``axis`` (L must divide evenly).  ``impl``: 'jnp', 'pallas', or
    'auto' (pallas on TPU, jnp elsewhere).  Callable from inside jit.

    ``layout='zigzag'`` (causal only) balances causal work across the
    ring — each device owns an early and a late half-chunk, halving the
    worst-device compute per step.  With ``permute_inputs`` (default) the
    returned fn takes/returns natural sequence order, paying one
    cross-shard permutation per call; a model calling attention per layer
    can instead pre-permute activations once with
    :func:`zigzag_permute`, pass ``permute_inputs=False``, and
    un-permute final outputs with :func:`zigzag_unpermute`.
    """
    if impl == "auto":
        impl = "pallas" if jax.default_backend() == "tpu" else "jnp"
    if impl not in ("jnp", "pallas"):
        raise ValueError(f"impl must be auto|jnp|pallas, got {impl!r}")
    if layout not in ("contiguous", "zigzag"):
        raise ValueError(f"layout must be contiguous|zigzag, got {layout!r}")
    if layout == "zigzag" and not causal:
        raise ValueError(
            "layout='zigzag' requires causal=True (the static block-"
            "liveness it exploits is the causal structure)"
        )
    n = mesh.shape[axis]
    local = _make_local_fn(
        axis, n, bool(causal),
        # Static cache key: reject traced sm_scale with a clear error.
        None if sm_scale is None else float(sm_scale),
        impl, int(block_q), int(block_k),
        interpret, precision, layout,
    )

    def _local(q, k, v):
        # (B, C, H, D) chunk -> heads-major for the block math, and back.
        qh, kh, vh = (x.transpose(0, 2, 1, 3) for x in (q, k, v))
        return local(qh, kh, vh).transpose(0, 2, 1, 3)

    spec = P(None, axis, None, None)
    mapped = shard_map(
        _local, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False,
    )
    if layout == "contiguous" or not permute_inputs:
        return mapped

    def zigzagged(q, k, v):
        qz, kz, vz = (zigzag_permute(x, n, axis=1) for x in (q, k, v))
        return zigzag_unpermute(mapped(qz, kz, vz), n, axis=1)

    return zigzagged
