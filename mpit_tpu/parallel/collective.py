"""ICI collective primitives — the on-mesh analog of the PS wire protocol.

The reference moves parameter/gradient shards between ranks with tagged
MPI Isend/Irecv driven by coroutines (reference init.lua:40-102,
mpifuncs.c:1488-1532).  On a TPU mesh the same traffic pattern is three
XLA collectives, all riding ICI:

- **pull** (client fetches full params from all servers, reference
  pclient.lua:72-82) = ``all_gather`` over the shard axis;
- **push** (clients ship grads, each server applies its shard's sum,
  reference pserver.lua:75-90) = ``psum_scatter`` (reduce-scatter) over
  the shard axis;
- **ring transfer** (point-to-point neighbor exchange; also the building
  block for ring attention, §5 of SURVEY.md) = ``ppermute``.

These run inside ``shard_map`` so the collective schedule is explicit;
the higher-level trainers in :mod:`mpit_tpu.parallel` instead use jit +
sharding annotations and let XLA insert the identical collectives.
"""

from __future__ import annotations

from typing import Callable, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

try:  # jax >= 0.6: public API (check_vma kwarg) — pass through as-is.
    from jax import shard_map
except ImportError:
    # Older jax: experimental home.  Audited against the live signature
    # instead of assuming a kwarg name: 0.4.x spells the replication
    # check ``check_rep``; some intermediate builds renamed it to
    # ``check_vma`` in place, and dropping the check entirely is the
    # safe degradation for anything else (every call site here passes
    # check_vma=False anyway — the collectives below are deliberately
    # replication-breaking).  Siblings (moe, fused, ring_attention,
    # tensor_parallel, pipeline) import shard_map from here.
    import inspect

    from jax.experimental.shard_map import shard_map as _shard_map_exp

    _CHECK_KW = next(
        (kw for kw in ("check_rep", "check_vma")
         if kw in inspect.signature(_shard_map_exp).parameters), None)

    def shard_map(f, mesh, in_specs, out_specs, check_vma=True, **kwargs):
        if _CHECK_KW is not None:
            kwargs[_CHECK_KW] = check_vma
        return _shard_map_exp(f, mesh=mesh, in_specs=in_specs,
                              out_specs=out_specs, **kwargs)


def ps_pull(mesh: Mesh, axis: str = "shard") -> Callable[[jnp.ndarray], jnp.ndarray]:
    """Full-param fetch: every mesh cell receives the concatenation of all
    shards (reference pclient.lua:72-82's recv of every server's slice)."""

    def _pull(shard_slice):
        return jax.lax.all_gather(shard_slice, axis, tiled=True)

    return shard_map(
        _pull, mesh=mesh, in_specs=P(axis), out_specs=P(), check_vma=False
    )


def ps_push(
    mesh: Mesh, axis: str = "shard", reduce_axis: str | None = None
) -> Callable[[jnp.ndarray], jnp.ndarray]:
    """Grad push: deliver to each shard owner the gradient slice it owns
    (the collective form of clients streaming grads to servers, reference
    pclient.lua:48-58 / pserver.lua:75-90).

    Without ``reduce_axis`` the input grad is replicated over ``axis``, so
    ownership transfer is a local slice, not a collective — XLA keeps it a
    zero-cost view.  With ``reduce_axis`` (the worker axis) the input is a
    ``(n_workers, plong)`` stack of per-worker grads, summed with ``psum``
    over that axis first — the server-side per-client ``p:add(g)``
    accumulation collapsed into one reduce (pserver.lua:83)."""

    def _push(full_grad):
        if reduce_axis is not None:
            full_grad = jax.lax.psum(full_grad, reduce_axis)[0]
        n = mesh.shape[axis]
        idx = jax.lax.axis_index(axis)
        size = full_grad.shape[0] // n
        return jax.lax.dynamic_slice_in_dim(full_grad, idx * size, size)

    in_spec = P(reduce_axis, None) if reduce_axis is not None else P()
    return shard_map(
        _push, mesh=mesh, in_specs=in_spec, out_specs=P(axis), check_vma=False
    )


def ps_pushpull(
    mesh: Mesh, apply_fn: Callable[[jnp.ndarray, jnp.ndarray], jnp.ndarray],
    axis: str = "shard",
) -> Callable[[jnp.ndarray, jnp.ndarray], Tuple[jnp.ndarray, jnp.ndarray]]:
    """One full PS round on-mesh: push grads (reduce-scatter), apply the
    server rule on each shard, pull updated params (all-gather).

    ``apply_fn(p_shard, g_shard) -> p_shard`` is the jitted shard rule —
    plain add in the reference's pserver hot loop (pserver.lua:83).
    Returns ``(new_full_params, new_param_shard)``.
    """

    def _round(p_shard, full_grad):
        n = mesh.shape[axis]
        idx = jax.lax.axis_index(axis)
        size = full_grad.shape[0] // n
        g_shard = jax.lax.dynamic_slice_in_dim(full_grad, idx * size, size)
        p_shard = apply_fn(p_shard, g_shard)
        full = jax.lax.all_gather(p_shard, axis, tiled=True)
        return full, p_shard

    return shard_map(
        _round, mesh=mesh, in_specs=(P(axis), P()), out_specs=(P(), P(axis)),
        check_vma=False,
    )


def ring_shift(mesh: Mesh, axis: str, *, reverse: bool = False):
    """Neighbor exchange over ``axis``: each cell hands its block to the
    next cell on the ring (``ppermute``).  The mesh analog of a tagged
    point-to-point Isend/Irecv pair; also the step primitive of ring
    attention."""
    n = mesh.shape[axis]
    step = -1 if reverse else 1
    perm = [(i, (i + step) % n) for i in range(n)]

    def _shift(block):
        return jax.lax.ppermute(block, axis, perm)

    return shard_map(
        _shift, mesh=mesh, in_specs=P(axis), out_specs=P(axis), check_vma=False
    )


def allreduce_mean(mesh: Mesh, axis: str = "dp"):
    """Mean over the worker axis — the sync-DP gradient combine
    (the trained-in analog of the reference's Allreduce smoke tests,
    reference test/testreduceall.lua:31-33)."""

    def _mean(x):
        return jax.lax.pmean(x, axis)

    return shard_map(
        _mean, mesh=mesh, in_specs=P(axis), out_specs=P(axis), check_vma=False
    )


def measure_ps_pushpull(mb: float, rounds: int = 20) -> dict:
    """Measured PS push/pull bandwidth over the mesh ``shard`` axis — the
    one shared implementation of the asyncsgd/ptest.lua:58-67 measurement
    (``2*T*ssize*4/elapsed`` MB/s), used by both ``benchmarks/ptest.py``
    and the repo-root ``bench.py`` so the formula and payload sizing
    cannot drift apart.  Timing is the latency-cancelled fetch-fenced
    recipe of :mod:`mpit_tpu.utils.timing`."""
    from mpit_tpu.parallel.mesh import make_mesh, param_sharding
    from mpit_tpu.utils.platform import default_devices
    from mpit_tpu.utils.timing import timed_per_call

    devs = default_devices()
    mesh = make_mesh(devs, dp=1)  # all devices on the shard axis
    n = mesh.shape["shard"]
    size = int(mb * (1 << 20) / 4 // n * n)

    roundtrip = jax.jit(ps_pushpull(mesh, lambda p, g: p + g))
    p_shard = jax.device_put(
        jnp.zeros((size,), jnp.float32), param_sharding(mesh)
    )
    grad = jnp.ones((size,), jnp.float32)
    # auto_scale + min_ratio: a ms-scale round under the tunnel's
    # ~100 ms dispatch latency needs the iteration count grown until the
    # differenced legs clear 8x the observed jitter (this number is
    # published — the default stop rule permits ~100% relative error).
    per_round = timed_per_call(roundtrip, p_shard, grad, iters=rounds,
                               auto_scale=True, min_ratio=8.0)
    mbs = 2 * size * 4 / per_round / 2**20  # reference formula, per round
    return {
        "mbs": mbs, "per_chip": mbs / n, "devices": n,
        "payload_mb": size * 4 / 2**20, "ms_per_round": per_round * 1e3,
    }
