"""Multi-host process bootstrap — the mpirun/hostfile analog.

The reference scales across nodes with ``mpirun --hostfile`` (6 nodes x 16
slots, reference BiCNN/hostfiles; README.md:57-61), MPI assigning ranks and
wiring the wire protocol.  The TPU-native equivalent is multi-controller
JAX: every host runs the same program, ``jax.distributed.initialize()``
forms the process group, and ``jax.devices()`` then spans every chip on
every host — after which the whole of :mod:`mpit_tpu.parallel` (meshes,
collective PS, ring attention) works unchanged, with XLA routing
cross-host collective hops over DCN.

This module provides the bootstrap glue:

- :func:`read_hostfile` — parse the reference's ``host:slots`` format;
- :func:`bootstrap` — derive (coordinator, num_processes, process_id)
  from explicit args, a hostfile + rank env, cloud TPU metadata (all
  args None), or MPIT_* / standard env vars, then call
  ``jax.distributed.initialize``;
- :class:`ProcessGroup` — the post-init identity handle (process index,
  count, local devices) that launchers hand to role assignment exactly
  like an MPI rank/size pair (reference mlaunch.lua:16-17).
"""

from __future__ import annotations

import dataclasses
import os
import pathlib
from typing import List, Optional, Sequence, Tuple

import jax


@dataclasses.dataclass(frozen=True)
class HostEntry:
    host: str
    slots: int = 1


def read_hostfile(path: str | pathlib.Path) -> List[HostEntry]:
    """Parse ``host:slots`` lines (reference BiCNN/hostfiles; blank lines
    and ``#`` comments ignored; missing ``:slots`` means 1)."""
    entries: List[HostEntry] = []
    for raw in pathlib.Path(path).read_text().splitlines():
        line = raw.split("#", 1)[0].strip()
        if not line:
            continue
        host, _, slots = line.partition(":")
        if not host:
            raise ValueError(f"bad hostfile line: {raw!r}")
        entries.append(HostEntry(host, int(slots) if slots else 1))
    if not entries:
        raise ValueError(f"hostfile {path} is empty")
    return entries


def coordinator_from_hostfile(
    entries: Sequence[HostEntry], port: int = 8476
) -> Tuple[str, int]:
    """(coordinator_address, num_processes): first host coordinates (the
    mpirun convention of rank 0 on the first hostfile line); one JAX
    process per hostfile line — slots describe per-host worker threads
    or local gang size, not extra controllers."""
    return f"{entries[0].host}:{port}", len(entries)


@dataclasses.dataclass(frozen=True)
class ProcessGroup:
    """Identity after bootstrap — the rank/size pair of mlaunch.lua:16-17
    plus device topology."""

    process_id: int
    num_processes: int
    coordinator: Optional[str]

    @property
    def devices(self) -> List[jax.Device]:
        return jax.devices()

    @property
    def local_devices(self) -> List[jax.Device]:
        return jax.local_devices()

    def describe(self) -> str:
        return (
            f"process {self.process_id}/{self.num_processes} "
            f"coordinator={self.coordinator or 'single-host'} "
            f"local={len(self.local_devices)} global={len(self.devices)}"
        )


def bootstrap(
    coordinator: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
    hostfile: Optional[str] = None,
    port: int = 8476,
) -> ProcessGroup:
    """Form the multi-host process group and return the identity handle.

    Resolution order for each field: explicit argument > MPIT_* env
    (MPIT_COORDINATOR / MPIT_NUM_PROCESSES / MPIT_PROCESS_ID) > hostfile
    (+ MPIT_PROCESS_ID for our line index) > single-process fallback
    (no initialize call — ``jax.devices()`` is already correct on one
    host, and cloud TPU pods auto-initialize from metadata when
    ``jax.distributed.initialize()`` is called with no args by the
    runtime).
    """
    # Process entry point: apply the JAX_PLATFORMS override before any
    # backend init (jax.distributed.initialize / device queries below) —
    # the env var alone loses to preloaded accelerator plugins, which
    # also makes this path hang when a tunneled TPU is unreachable.
    from mpit_tpu.utils.platform import honor_jax_platforms

    honor_jax_platforms()

    env = os.environ
    coordinator = coordinator or env.get("MPIT_COORDINATOR") or None
    if num_processes is None:
        num_processes = (
            int(env["MPIT_NUM_PROCESSES"]) if "MPIT_NUM_PROCESSES" in env else None
        )
    if process_id is None:
        process_id = (
            int(env["MPIT_PROCESS_ID"]) if "MPIT_PROCESS_ID" in env else None
        )
    hostfile = hostfile or env.get("MPIT_HOSTFILE") or None

    if hostfile and (coordinator is None or num_processes is None):
        entries = read_hostfile(hostfile)
        hf_coord, hf_n = coordinator_from_hostfile(entries, port)
        coordinator = coordinator or hf_coord
        num_processes = num_processes if num_processes is not None else hf_n

    if coordinator is None and num_processes is None and process_id is None:
        # Single-host run, or a cloud TPU pod whose runtime auto-initialized
        # the group from metadata — report the real identity either way.
        return ProcessGroup(jax.process_index(), jax.process_count(), None)

    num_processes = 1 if num_processes is None else num_processes
    if process_id is None:
        if num_processes > 1:
            # Defaulting to 0 here would make every host claim the
            # coordinator rank and hang the rendezvous — fail with the fix.
            raise ValueError(
                f"process_id required for a {num_processes}-process group: "
                "pass --process_id / MPIT_PROCESS_ID (unique per host)"
            )
        process_id = 0
    if not 0 <= process_id < num_processes:
        raise ValueError(
            f"process_id {process_id} out of range for {num_processes} processes"
        )
    jax.distributed.initialize(
        coordinator_address=coordinator,
        num_processes=num_processes,
        process_id=process_id,
    )
    return ProcessGroup(process_id, num_processes, coordinator)


def shutdown() -> None:
    """Tear down the process group (safe to call when never initialized)."""
    try:
        jax.distributed.shutdown()
    except Exception:
        pass
