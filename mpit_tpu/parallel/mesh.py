"""Device-mesh construction and role helpers.

The reference assigns roles by MPI rank parity (reference
asyncsgd/mlaunch.lua:25-31 — even ranks are servers, odd are clients) and
scales by adding ranks.  TPU-native scaling is a 2-D ``jax.sharding.Mesh``
instead:

- axis ``dp`` — data-parallel workers (the reference's *clients*);
- axis ``shard`` — the 1-D parameter/optimizer-state shard axis (the
  reference's *servers*: the flat param vector split by offset,
  reference pclient.lua:111-129, maps onto ``PartitionSpec('shard')``).

Collectives over these axes ride ICI.  Multi-host meshes come for free:
``jax.devices()`` after ``jax.distributed.initialize()`` spans all hosts
and the same axis names apply (XLA routes cross-host hops over DCN).
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec


def make_mesh(
    devices: Optional[Sequence[jax.Device]] = None,
    *,
    dp: Optional[int] = None,
    shard: Optional[int] = None,
    axis_names: Tuple[str, str] = ("dp", "shard"),
) -> Mesh:
    """Build a 2-D (dp, shard) mesh over ``devices`` (default: all).

    If only one of ``dp``/``shard`` is given the other is inferred; if
    neither is given the device count is factored with ``dp`` taking the
    larger factor (workers usually outnumber shard groups, as in the
    reference's 6-worker/6-server mlaunch split).
    """
    from mpit_tpu.utils.platform import default_devices

    devs = list(devices if devices is not None else default_devices())
    n = len(devs)
    if dp is None and shard is None:
        shard = _largest_divisor_at_most(n, int(np.sqrt(n)))
        dp = n // shard
    elif dp is None:
        if n % shard:
            raise ValueError(f"{n} devices not divisible by shard={shard}")
        dp = n // shard
    elif shard is None:
        if n % dp:
            raise ValueError(f"{n} devices not divisible by dp={dp}")
        shard = n // dp
    if dp * shard != n:
        raise ValueError(f"dp*shard = {dp}*{shard} != {n} devices")
    arr = np.array(devs).reshape(dp, shard)
    return Mesh(arr, axis_names)


def _largest_divisor_at_most(n: int, cap: int) -> int:
    for d in range(max(cap, 1), 0, -1):
        if n % d == 0:
            return d
    return 1


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, PartitionSpec())


def param_sharding(mesh: Mesh, axis: str = "shard") -> NamedSharding:
    """1-D sharding of a flat parameter vector over the shard axis —
    the mesh expression of the reference's offset-sliced server shards
    (reference pclient.lua:111-129)."""
    return NamedSharding(mesh, PartitionSpec(axis))


def worker_sharding(mesh: Mesh, *, shard_params: bool = True) -> NamedSharding:
    """Sharding for a (n_dp, plong) stack of per-worker flat params:
    rows over ``dp``, columns optionally over ``shard``."""
    spec = PartitionSpec("dp", "shard" if shard_params else None)
    return NamedSharding(mesh, spec)


def batch_sharding(mesh: Mesh) -> NamedSharding:
    """Per-worker batches: leading dp axis, unsharded feature axes."""
    return NamedSharding(mesh, PartitionSpec("dp"))


def put_global(arr, sharding: NamedSharding):
    """Place a host-*global* array (every process holds the same full
    array — init-style broadcast) with ``sharding`` across the possibly
    multi-host mesh.

    Single-process: plain ``device_put``.  Multi-process:
    ``make_array_from_callback`` hands each addressable device its slice
    of the global array — ``device_put`` of a host-global array cannot
    place data on another host's devices.
    """
    if jax.process_count() == 1:
        return jax.device_put(arr, sharding)
    host = np.asarray(arr)
    return jax.make_array_from_callback(host.shape, sharding, lambda idx: host[idx])


def put_local(arr, sharding: NamedSharding):
    """Place per-process data (data-parallel batches): each process
    passes only the rows its addressable devices own; the global array is
    assembled with ``jax.make_array_from_process_local_data``."""
    if jax.process_count() == 1:
        return jax.device_put(arr, sharding)
    return jax.make_array_from_process_local_data(sharding, np.asarray(arr))


def process_local_rows(sharding: NamedSharding, n_rows: int) -> slice:
    """The contiguous block of leading-axis rows this process feeds to
    :func:`put_local` for an array whose axis 0 is sharded by
    ``sharding`` — i.e. the rows living on this process's addressable
    devices.  Launchers that build a *global* batch on every host (same
    seed, same shuffle) slice with this before ``shard_batch``.

    Raises if the process's rows are not one contiguous block (cannot
    happen with the row-major device layouts :func:`make_mesh` builds).
    """
    idx_map = sharding.addressable_devices_indices_map((n_rows,))
    starts = sorted(
        (0 if sl[0].start is None else sl[0].start,
         n_rows if sl[0].stop is None else sl[0].stop)
        for sl in idx_map.values()
    )
    lo = min(s for s, _ in starts)
    hi = max(e for _, e in starts)
    covered = sorted(set(starts))
    span = 0
    for s, e in covered:
        if s > lo + span:
            raise ValueError(
                f"process rows not contiguous: {covered} over {n_rows}"
            )
        span = max(span, e - lo)
    return slice(lo, hi)
