"""Expert parallelism: a Switch-style top-1 MoE layer over an ``ep``
mesh axis.

Not in the reference (SURVEY §2: EP absent).  TPU-native shape:

- experts' MLP weights are stacked on a leading expert axis and sharded
  over ``ep`` — each device owns ``E/n`` experts in HBM;
- routing is **dense dispatch**: every device runs all tokens through
  its local experts and masks by the router's one-hot choice, combining
  across devices with one ``psum``.  No sort/ragged all-to-all — for
  small expert counts this trades redundant FLOPs for a fully static,
  fusable program (the usual small-scale TPU MoE trade);
- top-1 routing with the Switch combine (chosen expert scaled by its
  softmax probability) keeps the router differentiable.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from mpit_tpu.parallel.collective import shard_map  # version shim
from jax.sharding import Mesh, PartitionSpec as P


def ep_moe(
    mesh: Mesh,
    axis: str = "ep",
    activation: Callable[[jnp.ndarray], jnp.ndarray] = jax.nn.gelu,
):
    """Build ``fn(x, gate_w, w1, b1, w2, b2) -> y``.

    ``x (..., d)``; ``gate_w (d, E)``; expert weights stacked:
    ``w1 (E, d, h)``, ``b1 (E, h)``, ``w2 (E, h, d)``, ``b2 (E, d)``,
    with ``E`` divisible by the axis size.  Output matches ``x``.
    """

    def _local(x, gate_w, w1, b1, w2, b2):
        e_local = w1.shape[0]
        idx = jax.lax.axis_index(axis)
        scores = jnp.einsum("...d,de->...e", x, gate_w)  # global experts
        probs = jax.nn.softmax(scores, axis=-1)
        choice = jnp.argmax(probs, axis=-1)  # (...,) global expert id
        # Switch combine weight: the chosen expert's probability.
        combine = jnp.take_along_axis(probs, choice[..., None], axis=-1)[..., 0]
        # Mask for MY experts: local one-hot over e_local slots.
        local_ids = idx * e_local + jnp.arange(e_local)
        dispatch = (choice[..., None] == local_ids).astype(x.dtype)  # (..., El)

        h = activation(jnp.einsum("...d,edh->e...h", x, w1)
                       + jnp.expand_dims(b1, tuple(range(1, x.ndim))))
        y_exp = jnp.einsum("e...h,ehd->e...d", h, w2) + jnp.expand_dims(
            b2, tuple(range(1, x.ndim))
        )
        y_local = jnp.einsum("...e,e...d->...d", dispatch, y_exp)
        y = jax.lax.psum(y_local, axis)
        return y * combine[..., None]

    return shard_map(
        _local,
        mesh=mesh,
        in_specs=(
            P(), P(),
            P(axis, None, None), P(axis, None),
            P(axis, None, None), P(axis, None),
        ),
        out_specs=P(),
        check_vma=False,
    )


def moe_reference(x, gate_w, w1, b1, w2, b2, activation=jax.nn.gelu):
    """Unsharded top-1 MoE with the same routing — the test oracle."""
    scores = jnp.einsum("...d,de->...e", x, gate_w)
    probs = jax.nn.softmax(scores, axis=-1)
    choice = jnp.argmax(probs, axis=-1)
    combine = jnp.take_along_axis(probs, choice[..., None], axis=-1)[..., 0]
    h = activation(jnp.einsum("...d,edh->e...h", x, w1)
                   + jnp.expand_dims(b1, tuple(range(1, x.ndim))))
    y_exp = jnp.einsum("e...h,ehd->e...d", h, w2) + jnp.expand_dims(
        b2, tuple(range(1, x.ndim))
    )
    onehot = jax.nn.one_hot(choice, w1.shape[0], dtype=x.dtype)
    y = jnp.einsum("...e,e...d->...d", onehot, y_exp)
    return y * combine[..., None]
