"""ICI-resident parallelism: device meshes, collectives, sharded trainers.

The host-mediated asynchronous path (true PS semantics over the native
transport) lives in :mod:`mpit_tpu.ps` / :mod:`mpit_tpu.comm`; this
package is the on-mesh expression of the same capabilities — sharded
state, collective push/pull, elastic averaging — for when workers share
one ICI domain and loose lockstep is acceptable (SURVEY.md §7 "measure
both, keep both").
"""

from mpit_tpu.parallel.mesh import (  # noqa: F401
    batch_sharding,
    make_mesh,
    param_sharding,
    replicated,
    worker_sharding,
)
from mpit_tpu.parallel.collective import (  # noqa: F401
    allreduce_mean,
    ps_pull,
    ps_push,
    ps_pushpull,
    ring_shift,
)
from mpit_tpu.parallel.distributed import (  # noqa: F401
    ProcessGroup,
    bootstrap,
    read_hostfile,
)
from mpit_tpu.parallel.easgd import MeshEASGD  # noqa: F401
from mpit_tpu.parallel.moe import ep_moe, moe_reference  # noqa: F401
from mpit_tpu.parallel.pipeline import (  # noqa: F401
    pipeline,
    stack_stage_params,
)
from mpit_tpu.parallel.tensor_parallel import (  # noqa: F401
    tp_mlp,
    tp_self_attention,
)
from mpit_tpu.parallel.ring_attention import (  # noqa: F401
    ring_attention,
    sp_mesh,
    zigzag_permute,
    zigzag_unpermute,
)
from mpit_tpu.parallel.sync_dp import SyncDataParallel  # noqa: F401
