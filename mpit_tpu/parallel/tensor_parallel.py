"""Tensor parallelism: Megatron-style column/row sharded matmul pairs.

The reference has no TP (SURVEY §2 parallelism table — model *compute* is
never sharded, only server state); this module adds it the TPU way:
``shard_map`` programs over a ``tp`` mesh axis where weights are sharded
by output (column) or input (row) dimension, and exactly one ``psum``
per sharded block pays the ICI cost:

- **column-parallel**: ``W1`` split over its output dim — each device
  computes a slice of the hidden activations, no communication;
- **row-parallel**: ``W2`` split over its input dim — each device
  contributes a partial product, combined with one ``psum``;
- the pair (column → elementwise → row) is the canonical TP MLP; the
  same layout over attention heads gives head-parallel attention (heads
  are embarrassingly parallel until the output projection).

All fns are differentiable (shard_map + psum have transpose rules) and
callable from inside jit on global arrays.
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp
from mpit_tpu.parallel.collective import shard_map  # version shim
from jax.sharding import Mesh, PartitionSpec as P


def tp_mlp(
    mesh: Mesh,
    axis: str = "tp",
    activation: Callable[[jnp.ndarray], jnp.ndarray] = jax.nn.gelu,
):
    """Two-layer MLP with hidden dim sharded over ``axis``.

    ``fn(x, w1, b1, w2, b2)``: ``x (..., d)``, ``w1 (d, h)``,
    ``b1 (h,)``, ``w2 (h, d)``, ``b2 (d,)``, hidden ``h`` divisible by
    the axis size.  One psum on the way out; activations between the two
    matmuls never materialize unsharded.
    """

    def _local(x, w1, b1, w2, b2):
        h = activation(
            jnp.einsum("...d,dh->...h", x, w1) + b1
        )  # local hidden slice
        partial = jnp.einsum("...h,hd->...d", h, w2)
        out = jax.lax.psum(partial, axis)
        return out + b2  # bias after the reduce (replicated)

    return shard_map(
        _local,
        mesh=mesh,
        in_specs=(P(), P(None, axis), P(axis), P(axis, None), P()),
        out_specs=P(),
        check_vma=False,
    )


def tp_self_attention(
    mesh: Mesh,
    axis: str = "tp",
    *,
    causal: bool = True,
    sm_scale: Optional[float] = None,
):
    """Head-parallel self-attention: heads sharded over ``axis``.

    ``fn(x, wqkv, wo)``: ``x (B, L, d)``, ``wqkv (d, 3, H, Dh)``,
    ``wo (H, Dh, d)``; ``H`` divisible by the axis size.  QKV projection
    and per-head attention are local; the output projection is
    row-parallel with one psum.
    """

    def _local(x, wqkv, wo):
        from mpit_tpu.ops.flash_attention import attention_reference

        qkv = jnp.einsum("bld,dthk->btlhk", x, wqkv)  # t in {q,k,v}
        q, k, v = qkv[:, 0], qkv[:, 1], qkv[:, 2]  # (B, L, Hl, Dh)
        heads = attention_reference(
            q.transpose(0, 2, 1, 3), k.transpose(0, 2, 1, 3),
            v.transpose(0, 2, 1, 3), causal=causal, sm_scale=sm_scale,
        ).transpose(0, 2, 1, 3)  # (B, L, Hl, Dh)
        partial = jnp.einsum("blhk,hkd->bld", heads, wo)
        return jax.lax.psum(partial, axis)

    return shard_map(
        _local,
        mesh=mesh,
        in_specs=(P(), P(None, None, axis, None), P(axis, None, None)),
        out_specs=P(),
        check_vma=False,
    )
