"""shard_map bridges from the 1-D fused pallas sweeps to mesh-sharded
trainer state.

A pallas call cannot be auto-partitioned by XLA inside a sharded jit, so
the mesh trainers historically forced the plain-XLA commit
(``use_fused=False`` — VERDICT r1/r2 weak-item).  The fix is the standard
pattern: wrap the kernel in :func:`jax.shard_map` over the same mesh, so
every device runs the sweep on exactly the tile it already holds in HBM —
the (dp, shard) worker-row tiles of :class:`MeshEASGD` or the 1-D shard
slices of :class:`SyncDataParallel` — and the surrounding jit keeps the
collectives.  One HBM read/write of (w, vt, g) per step, with the EASGD
elastic retract riding the same sweep on sync rounds
(:func:`mpit_tpu.ops.fused_update.fused_nesterov_commit` ``sug=``).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from mpit_tpu.parallel.collective import shard_map  # version shim
from jax.sharding import Mesh, PartitionSpec

from mpit_tpu.ops.fused_update import fused_nesterov_commit


def mesh_fused_commit(
    mesh: Mesh,
    w_spec: PartitionSpec,
    clr_spec: PartitionSpec,
    *,
    l2wd: float = 0.0,
    retract: bool = False,
):
    """Build a jit-callable fused Nesterov commit over ``mesh``.

    Returns ``commit(w, vt, g, clr[, sug]) -> (w_new, vt_new)`` where the
    array args carry ``w_spec`` and ``clr`` carries ``clr_spec`` (a
    per-worker vector for the EASGD row layout, a replicated scalar for
    sync-DP).  Each device flattens its local tile, runs the one-sweep
    kernel, and reshapes back — no cross-device traffic is introduced.
    """

    def _tile(w_t, vt_t, g_t, clr_t, *sug_t):
        shape = w_t.shape
        flat = lambda a: a.reshape(-1)
        # Per-tile scalar: EASGD tiles hold one worker row (clr_t shape
        # (1,)); sync-DP replicates a 0-d scalar.
        c = clr_t.reshape(-1)[0] if clr_t.ndim else clr_t
        kw = dict(l2wd=l2wd)
        if sug_t:
            kw["sug"] = flat(sug_t[0])
        w2, vt2 = fused_nesterov_commit(flat(w_t), flat(vt_t), flat(g_t), c, **kw)
        return w2.reshape(shape), vt2.reshape(shape)

    in_specs = [w_spec, w_spec, w_spec, clr_spec]
    if retract:
        in_specs.append(w_spec)
    return shard_map(
        _tile, mesh=mesh, in_specs=tuple(in_specs),
        out_specs=(w_spec, w_spec), check_vma=False,
    )
