"""Synchronous data-parallel trainer — the allreduce path, trained-in.

The reference exposes and smoke-tests Allreduce/Iallreduce
(reference mpifuncs.c:83,:1357; test/testreduceall.lua:31-33) but never
wires them into training.  SURVEY.md §2 calls for a sync-DP trainer as the
"testreduceall analog": here it is, the idiomatic way — the global batch
is sharded over the ``dp`` mesh axis, parameters are sharded 1-D over
``shard`` (so optimizer state also lives distributed, the mesh form of
the reference's server-resident shards), and XLA inserts the gradient
all-reduce and the parameter all-gathers automatically from the sharding
annotations.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from mpit_tpu.ops.fused_update import fused_enabled
from mpit_tpu.optim.msgd import (
    MSGDConfig,
    _effective_lr,
    msgd_commit,
    msgd_init,
    msgd_lookahead,
)
from mpit_tpu.parallel.fused import mesh_fused_commit
from mpit_tpu.parallel.mesh import put_global, put_local


class SyncDataParallel:
    """Jitted Nesterov-SGD step over a (dp, shard) mesh.

    ``value_and_grad_fn(w, xb, yb) -> (loss, grad)`` sees the *global*
    batch; sharding the batch over ``dp`` makes XLA compute per-device
    partial grads and psum them — the trained-in Allreduce.
    """

    def __init__(
        self,
        mesh: Mesh,
        value_and_grad_fn: Callable[..., Tuple[jnp.ndarray, jnp.ndarray]],
        cfg: MSGDConfig,
    ):
        self.mesh = mesh
        self.cfg = cfg
        # Fused pallas commit via shard_map over the 1-D shard slices
        # (parallel/fused.py); the kernel folds the velocity update, so
        # it needs mom > 0.
        use_fused = cfg.mom > 0 and fused_enabled(cfg.use_fused)
        self._use_fused = use_fused
        cfg_inner = cfg._replace(use_fused=False)
        ps = NamedSharding(mesh, P("shard"))  # 1-D param/state sharding
        bs = NamedSharding(mesh, P("dp"))     # batch rows over workers
        self._param_sharding = ps
        self._batch_sharding = bs
        if use_fused:
            fused = mesh_fused_commit(mesh, P("shard"), P(), l2wd=cfg.l2wd)

        def _step(w, vt, k, xb, yb):
            st = {"k": k, "vt": vt}
            w_la, st = msgd_lookahead(w, st, cfg_inner)
            loss, grad = value_and_grad_fn(w_la, xb, yb)
            if use_fused:
                w_n, vt_n = fused(w_la, st["vt"], grad, _effective_lr(cfg, k))
                return w_n, vt_n, k + 1, loss
            w_n, st = msgd_commit(w_la, grad, st, cfg_inner)
            return w_n, st["vt"], st["k"], loss

        self._step_jit = jax.jit(
            _step,
            in_shardings=(ps, ps, NamedSharding(mesh, P()), bs, bs),
            out_shardings=(ps, ps, NamedSharding(mesh, P()), NamedSharding(mesh, P())),
            donate_argnums=(0, 1),
        )

        # Whole-epoch scan: one dispatch per staged epoch (see
        # MeshEASGD._epoch for why this matters on tunneled platforms).
        def _epoch(w, vt, k, xs, ys):
            def body(carry, xy):
                w, vt, k = carry
                w2, vt2, k2, loss = _step(w, vt, k, *xy)
                return (w2, vt2, k2), loss

            (w, vt, k), losses = jax.lax.scan(body, (w, vt, k), (xs, ys))
            return w, vt, k, losses

        rs = NamedSharding(mesh, P())
        ebs = NamedSharding(mesh, P(None, *bs.spec))
        self._epoch_jit = jax.jit(
            _epoch,
            in_shardings=(ps, ps, rs, ebs, ebs),
            out_shardings=(ps, ps, rs, rs),
            donate_argnums=(0, 1),
        )

    def init(self, w0: jnp.ndarray) -> Dict[str, Any]:
        # Copy w0: device_put may alias the caller's buffer on the device
        # whose shard stays put, and step() donates "w" — without the copy
        # the first step deletes the caller's w0 out from under them.
        return {
            "w": put_global(jnp.array(w0, copy=True), self._param_sharding),
            "vt": put_global(jnp.zeros_like(w0), self._param_sharding),
            "k": jnp.zeros((), jnp.int32),
        }

    @property
    def batch_sharding(self):
        return self._batch_sharding

    def shard_batch(self, *arrays: jnp.ndarray):
        """Multi-process: pass only this process's batch rows
        (:func:`mpit_tpu.parallel.mesh.process_local_rows`)."""
        return tuple(put_local(a, self._batch_sharding) for a in arrays)

    def step(self, state: Dict[str, Any], xb: jnp.ndarray, yb: jnp.ndarray):
        w, vt, k, loss = self._step_jit(state["w"], state["vt"], state["k"], xb, yb)
        return {"w": w, "vt": vt, "k": k}, loss

    def set_steps(self, n: int) -> None:
        """Sync-DP keeps no host-side schedule (the step count ``k``
        lives in device state) — accepted for trainer-interface parity
        with :class:`~mpit_tpu.parallel.easgd.MeshEASGD.set_steps`."""

    def precompile(self, state: Dict[str, Any], *batch: jnp.ndarray) -> None:
        """Compile-and-warm the step program against the real shardings
        without consuming the caller's buffers (the jit donates w/vt, so
        fresh copies are run through it and discarded)."""
        cp = {k: jnp.copy(v) for k, v in state.items()}
        out = self._step_jit(cp["w"], cp["vt"], cp["k"], *batch)
        from mpit_tpu.utils.timing import fetch_scalar

        fetch_scalar(out[-1])

    def run_epoch(self, state: Dict[str, Any], x_ep: jnp.ndarray,
                  y_ep: jnp.ndarray):
        """Train a whole staged epoch in one jitted scan; returns the new
        state and the (nsteps,) per-step losses."""
        w, vt, k, losses = self._epoch_jit(
            state["w"], state["vt"], state["k"], x_ep, y_ep
        )
        return {"w": w, "vt": vt, "k": k}, losses

    def precompile_epoch(self, state: Dict[str, Any], x_ep: jnp.ndarray,
                         y_ep: jnp.ndarray) -> None:
        """Compile-and-warm the whole-epoch scan for this epoch shape
        without consuming the caller's buffers."""
        cp = {k: jnp.copy(v) for k, v in state.items()}
        out = self._epoch_jit(cp["w"], cp["vt"], cp["k"], x_ep, y_ep)
        from mpit_tpu.utils.timing import fetch_scalar

        fetch_scalar(out[-1])
