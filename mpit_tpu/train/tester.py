"""Dedicated tester role — pull params, evaluate, checkpoint the best.

The reference's BiCNN tester rank loops forever: pull current params from
the servers, evaluate the datasets, save a checkpoint, sleep (reference
bicnn.lua:580-596; its never-stopping is a flagged TODO at :581).  This
rebuild gives the tester a bounded lifecycle: ``tester_rounds`` pulls at
``tester_interval`` seconds apart, then a clean stop — the server counts
the tester among its clients, so the stop protocol stays exact.
"""

from __future__ import annotations

import time
from typing import Any, Dict

from mpit_tpu.ps import ParamClient
from mpit_tpu.train.trainer import MnistTrainer
from mpit_tpu.utils.checkpoint import save_flat
from mpit_tpu.utils.config import Config
from mpit_tpu.utils.logging import get_logger

import jax.numpy as jnp
import numpy as np


def run_tester(
    rank: int,
    server_ranks: list[int],
    cfg: Config,
    transport: Any,
    data: Any = None,
) -> Dict[str, Any]:
    log = get_logger("tester", rank)
    trainer = MnistTrainer(cfg, pclient=None, data=data, rank=rank)
    plong = trainer.flat.size
    from mpit_tpu.utils.serialize import resolve_dtype

    dtype = resolve_dtype(cfg.get("dtype", "float32"))
    param = np.zeros(plong, dtype)
    grad = np.zeros_like(param)
    pclient = ParamClient(rank, server_ranks, transport, seed_servers=False,
                          codec=str(cfg.get("codec", "") or "") or None)
    pclient.start(param, grad)

    rounds = int(cfg.get("tester_rounds", 10))
    interval = float(cfg.get("tester_interval", 1.0))
    ckpt_dir = cfg.get("ckpt_dir")
    best_err = float("inf")
    history = []
    for round_idx in range(rounds):
        pclient.async_recv_param()
        pclient.wait()
        test_err = trainer.test_error(jnp.asarray(param))
        history.append({"round": round_idx, "test_err": test_err})
        if test_err < best_err:
            best_err = test_err
            if ckpt_dir:
                save_flat(ckpt_dir, param, {"test_err": test_err, "round": round_idx})
        log.info("round %d test_err %.4f (best %.4f)", round_idx, test_err, best_err)
        if round_idx != rounds - 1:
            time.sleep(interval)
    pclient.stop()
    return {"history": history, "best_test_err": best_err}
