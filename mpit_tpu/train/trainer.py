"""MNIST trainer — the goot.lua analog, TPU-first.

Mirrors the reference trainer's shape (reference asyncsgd/goot.lua):
model + flat params (:29-36), data load/flatten (:43-57), optimizer
dispatch (:66-89), the feval closure (:101-126), the epoch x minibatch
loop with sequential unshuffled batches (:129-146), and per-phase timers
(:20-22, :152-157).  Differences, by design:

- the whole feval (forward+backward over the flat vector) is one jitted
  XLA program; the epoch loop feeds device-resident data slices;
- test-set error is evaluated every epoch — the reference only reports
  train avg_err (goot.lua:123,144-145) but the north-star metric is
  wall-clock to 1% *test* error (BASELINE.md), so the rebuild adds it;
- optimizer dispatch covers the full 12-name surface of the reference
  family (goot.lua:66-89 plus the BiCNN shells, bicnn.lua:127-252).
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from mpit_tpu.data.mnist import load_mnist
from mpit_tpu.models import MnistCNN, MnistLinear, MnistMLP, flatten_module
from mpit_tpu.optim import EAMSGD, MSGD, Downpour, RuleShell, SingleWorker
from mpit_tpu.optim.msgd import MSGDConfig
from mpit_tpu.utils.config import Config
from mpit_tpu.utils.logging import get_logger
from mpit_tpu.obs import PhaseTimers, profiler_trace

TRAINER_DEFAULTS = Config(
    model="linear",  # linear | mlp | cnn
    opt="msgd",  # msgd|sgd|downpour|eamsgd|easgd|rmsprop|adam|adamax|adagrad|
    #              adadelta|rmsprop-local|<rule>-single
    lr=1e-2,
    lrd=0.0,
    lrp=0.0,
    mom=0.99,
    mommax=1.0,
    momdecay=0.0,
    l2wd=0.0,
    mva=0.0,  # easgd moving rate; mlaunch uses beta/p = 0.9/nclients
    su=1,  # communication period
    epochs=10,
    batch=128,
    seed=1,
    side=32,
    shuffle=False,  # reference uses sequential batches (goot.lua:133)
    target_test_err=0.01,  # north-star threshold; loop records first hit
    dtype="float32",
    profile_dir="",  # jax.profiler trace of the epoch loop when set
)

MODELS = {"linear": MnistLinear, "mlp": MnistMLP, "cnn": MnistCNN}


class MnistTrainer:
    def __init__(
        self,
        cfg: Optional[Config] = None,
        pclient: Any = None,
        data: Any = None,
        rank: int = 0,
    ):
        self.cfg = TRAINER_DEFAULTS.merged(cfg.to_dict() if cfg else None)
        self.pc = pclient
        self.rank = rank
        self.log = get_logger("train", rank)
        self.tm = PhaseTimers()

        if data is None:
            data, source = load_mnist(side=self.cfg.side)
            self.log.info("data source: %s", source)
        x_train, y_train, x_test, y_test = data
        dtype = jnp.dtype(self.cfg.dtype)
        self.x_train = jnp.asarray(x_train, dtype)
        self.y_train = jnp.asarray(y_train)
        self.x_test = jnp.asarray(x_test, dtype)
        self.y_test = jnp.asarray(y_test)

        if self.cfg.model == "cnn":
            module = MnistCNN(num_classes=10, side=self.cfg.side)
        else:
            module = MODELS[self.cfg.model](num_classes=10)
        rng = jax.random.PRNGKey(self.cfg.seed + rank)
        self.flat = flatten_module(module, rng, self.x_train[:2])
        self.w = self.flat.w0.astype(dtype)

        def loss_fn(w, xb, yb):
            logp = self.flat.apply_flat(w, xb)
            nll = -jnp.mean(jnp.take_along_axis(logp, yb[:, None], axis=1))
            return nll

        self._vgf = jax.value_and_grad(loss_fn)

        def err_fn(w, xb, yb):
            logp = self.flat.apply_flat(w, xb)
            return jnp.mean((jnp.argmax(logp, axis=1) != yb).astype(jnp.float32))

        self._err = jax.jit(err_fn)
        self._optimizer = None  # built lazily: eval-only roles (the tester,
        # reference bicnn.lua:580-596) never need one

    @property
    def optimizer(self):
        if self._optimizer is None:
            self._optimizer = self._make_optimizer()
        return self._optimizer

    # -- optimizer dispatch (reference goot.lua:66-89, bicnn.lua:127-252) ----

    KNOWN_OPTS = (
        "sgd", "msgd", "downpour", "eamsgd", "easgd",
        "rmsprop", "adam", "adamax", "adagrad", "adadelta", "rmsprop-local",
        "msgd-single", "rmsprop-single", "adam-single", "adamax-single",
        "adagrad-single", "adadelta-single",
    )

    def _make_optimizer(self):
        cfg = self.cfg
        name = cfg.opt
        if name not in self.KNOWN_OPTS:
            raise ValueError(f"unknown optimizer {name!r}; have {self.KNOWN_OPTS}")
        if name in ("sgd", "msgd"):
            mcfg = MSGDConfig(
                lr=cfg.lr, lrd=cfg.lrd, lrp=cfg.lrp, mom=cfg.mom,
                mommax=cfg.mommax, momdecay=cfg.momdecay, l2wd=cfg.l2wd,
            )
            return MSGD(mcfg, self._vgf)
        if self.pc is None:
            raise ValueError(
                f"optimizer {name!r} needs a parameter client "
                "(single-process runs use msgd — reference claunch.lua:6-12)"
            )
        if name == "downpour":
            return Downpour(self._vgf, self.pc, lr=cfg.lr, lrd=cfg.lrd,
                            l2wd=cfg.l2wd, su=cfg.su)
        if name in ("eamsgd", "easgd"):
            mom = 0.0 if name == "easgd" else cfg.mom
            return EAMSGD(self._vgf, self.pc, lr=cfg.lr, lrd=cfg.lrd,
                          lrp=cfg.lrp, mom=mom, l2wd=cfg.l2wd,
                          mva=cfg.mva, su=cfg.su)
        if name == "rmsprop-local":
            return RuleShell(self._vgf, self.pc, su=cfg.su, mode="local",
                             lr=cfg.lr)
        if name.endswith("-single"):
            rule = name[: -len("-single")]
            hp = {"lr": cfg.lr} if rule != "msgd" else {"lr": cfg.lr, "mom": cfg.mom}
            return SingleWorker(self._vgf, self.pc, rule=rule, **hp)
        if name in ("rmsprop", "adam", "adamax", "adagrad", "adadelta"):
            # Server-stateful: the launcher configures the matching server
            # rule (reference plaunch wires pserver the same way).
            return RuleShell(self._vgf, self.pc, su=cfg.su, mode="global")
        raise ValueError(f"unknown optimizer {name!r}")

    # -- evaluation ----------------------------------------------------------

    def test_error(self, w: Optional[jnp.ndarray] = None) -> float:
        return float(self._err(self.w if w is None else w, self.x_test, self.y_test))

    def train_error(self, w: Optional[jnp.ndarray] = None) -> float:
        return float(self._err(self.w if w is None else w, self.x_train, self.y_train))

    # -- the epoch loop (reference goot.lua:129-146) -------------------------

    def run(self) -> Dict[str, Any]:
        cfg = self.cfg
        n = self.x_train.shape[0]
        steps_per_epoch = max(n // cfg.batch, 1)
        opt = self.optimizer
        if hasattr(opt, "start"):  # comm-aware optimizers; MSGD has none
            with self.tm.phase("start"):
                self.w = opt.start(self.w)
        history = []
        time_to_target = None
        rng = np.random.default_rng(cfg.seed + self.rank)
        with profiler_trace(cfg.get("profile_dir", "")):
            self._run_epochs(cfg, n, steps_per_epoch, opt, history, rng)
        # first epoch that reached the target, by cumulative wall clock
        for h in history:
            if h["test_err"] <= cfg.target_test_err:
                time_to_target = h["at"]
                break
        sync_time = getattr(opt, "dusync", 0.0)
        self.tm.add("sync", sync_time)
        # The blocking-sync seconds accrued inside opt.step were measured
        # under the 'feval' phase too; report feval net of sync so the
        # comm/compute split is honest.
        self.tm.total["feval"] = max(self.tm.total["feval"] - sync_time, 0.0)
        if hasattr(opt, "stop"):
            with self.tm.phase("stop"):
                opt.stop()
        return {
            "history": history,
            "final_test_err": history[-1]["test_err"] if history else None,
            "time_to_target": time_to_target,
            "elapsed": self.tm.elapsed(),
            "timers": dict(self.tm.total),
        }

    def _run_epochs(self, cfg, n, steps_per_epoch, opt, history, rng):
        for epoch in range(cfg.epochs):
            if cfg.shuffle:
                order = rng.permutation(n)
            losses = []
            for step in range(steps_per_epoch):
                lo = step * cfg.batch
                idx = order[lo : lo + cfg.batch] if cfg.shuffle else slice(lo, lo + cfg.batch)
                xb, yb = self.x_train[idx], self.y_train[idx]
                with self.tm.phase("feval"):
                    self.w, loss = opt.step(self.w, xb, yb)
                losses.append(loss)
            avg_loss = float(jnp.mean(jnp.stack(losses)))
            with self.tm.phase("eval"):
                test_err = self.test_error()
            history.append({"epoch": epoch, "avg_loss": avg_loss,
                            "test_err": test_err, "at": self.tm.elapsed()})
            self.log.info("epoch %d avg_loss %.5f test_err %.4f", epoch, avg_loss, test_err)
