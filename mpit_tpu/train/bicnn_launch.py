"""BiCNN launcher — the plaunch.lua analog.

Reproduces the reference's start-point semantics (BiCNN/plaunch.lua):
the ~50-flag config surface (:7-69, here BICNN_DEFAULTS), ``maxrank``
parking of excess ranks (:90-96), per-rank seeding (:113-115), and the
role table (:123-163):

- ``testerfirst``: rank 0 is the dedicated tester ('pe'); among ranks
  1..size-1 every ``master_freq``-th is a server ('ps'), the rest are
  training clients ('pt');
- ``testerlast``: among ranks 0..size-2 every rank with
  ``(i+1) % master_freq == 0`` is a server; rank size-1 is the tester;
- ``valid_mode='lastClient'`` marks the last client to ALSO run test3
  in-train every commperiod (plaunch.lua:166-167, bicnn.lua:625-633);
  ``'additionalTester'`` requires testerfirst or testerlast
  (plaunch.lua:169-177).

Parked ranks return immediately with role='parked' instead of the
reference's infinite sleep loop (plaunch.lua:92-95) so gangs always
terminate.

Usage:
    python -m mpit_tpu.train.bicnn_launch --np 4 --optimization downpour \\
        --valid_mode none
    python -m mpit_tpu.train.bicnn_launch --np 6 --optimization eamsgd \\
        --testerfirst true --valid_mode additionalTester

(The default ``valid_mode='additionalTester'`` needs ``testerfirst`` or
``testerlast``, exactly like the reference errors on its defaults,
plaunch.lua:169-177; the parent validates the combination before
spawning so a bad config never strands a gang.)
"""

from __future__ import annotations

import json
import sys
import time
from typing import Any, Dict, List, Optional, Set, Tuple

from mpit_tpu.ps import ParamClient, ParamServer
from mpit_tpu.train.bicnn import BICNN_DEFAULTS, BiCNNTrainer, server_rule_for
from mpit_tpu.utils.config import Config
from mpit_tpu.utils.logging import get_logger

BICNN_LAUNCH_DEFAULTS = BICNN_DEFAULTS.merged(
    np=1,
    ring_mb=64,
    namespace="",
    # Canonical tester surface shared with train.launch: none|first|last.
    # The reference-parity booleans (testerfirst/testerlast,
    # plaunch.lua:10-12) remain as aliases; setting both surfaces
    # inconsistently is an error.
    tester="",
    gang_barrier=True,  # startup rendezvous before any role traffic
)


def resolve_tester_flags(cfg: Config) -> tuple[bool, bool]:
    """Unify the two tester dialects into (testerfirst, testerlast).

    ``tester=none|first|last`` (the :mod:`mpit_tpu.train.launch` surface)
    wins when set; the plaunch-parity booleans are aliases.  A conflict
    between the two surfaces raises rather than silently preferring one.
    """
    t = str(cfg.get("tester", "") or "").strip().lower()
    tf, tl = bool(cfg.get("testerfirst", False)), bool(cfg.get("testerlast", False))
    if not t:
        return tf, tl
    if t not in ("none", "first", "last"):
        raise ValueError(f"tester must be none|first|last, got {t!r}")
    want = (t == "first", t == "last")
    if (tf or tl) and (tf, tl) != want:
        raise ValueError(
            f"conflicting tester config: tester={t!r} vs "
            f"testerfirst={tf} testerlast={tl}"
        )
    return want


def assign_roles(
    size: int,
    master_freq: int = 2,
    testerfirst: bool = False,
    testerlast: bool = False,
    valid_mode: str = "additionalTester",
) -> Tuple[List[int], List[int], Optional[int], Set[int]]:
    """(server_ranks, client_ranks, tester_rank, tranks) per
    plaunch.lua:123-177.  ``client_ranks`` includes the tester — it joins
    the PS protocol as a pull-only client, exactly like conf.cranks there.
    ``tranks`` marks ranks that run test3 (the conf.tranks table)."""
    if testerfirst and testerlast:
        raise ValueError("testerfirst and testerlast are mutually exclusive")
    sranks: List[int] = []
    cranks: List[int] = []
    tester_rank: Optional[int] = None
    if testerfirst:
        tester_rank = 0
        cranks.append(0)
        for i in range(1, size):
            (cranks if i % master_freq != 0 else sranks).append(i)
    elif testerlast:
        for i in range(size - 1):
            (cranks if (i + 1) % master_freq != 0 else sranks).append(i)
        tester_rank = size - 1
        cranks.append(tester_rank)
    else:
        # No dedicated tester: the asyncsgd parity split (mlaunch.lua:25-31).
        for i in range(size):
            (sranks if i % master_freq == 0 else cranks).append(i)
    training_clients = [c for c in cranks if c != tester_rank]
    if not sranks or not training_clients:
        raise ValueError(
            f"role split produced {len(sranks)} servers and no training "
            f"clients from size={size}, master_freq={master_freq}"
        )
    tranks: Set[int] = set()
    if valid_mode == "lastClient":
        # The highest-ranked *training client* (plaunch.lua:166-167 adds
        # size-1, which there is always a client; here the last rank may
        # be a server, so pick the last rank that actually trains).
        tranks.add(training_clients[-1])
    elif valid_mode == "additionalTester":
        if tester_rank is None:
            # plaunch.lua:169-177 errors on this combination too.
            raise ValueError(
                "valid_mode='additionalTester' requires testerfirst or testerlast"
            )
        tranks.add(tester_rank)
    elif valid_mode != "none":
        raise ValueError(f"unknown valid_mode {valid_mode!r}")
    return sranks, cranks, tester_rank, tranks


def run_rank(
    rank: int,
    size: int,
    cfg: Config,
    transport: Any,
    data: Any = None,
) -> Dict[str, Any]:
    """One rank's role to completion; returns its result dict."""
    log = get_logger("plaunch", rank)
    # maxrank parking (plaunch.lua:90-96): the effective world is
    # min(size, maxrank+1); excess ranks do nothing.
    effective = min(size, int(cfg.maxrank) + 1)
    if rank >= effective:
        log.info("rank %d > maxrank %d: parked", rank, cfg.maxrank)
        return {"role": "parked"}
    if effective == 1:
        # Single-process = the claunch analog: only local optimizers make
        # sense (SURVEY.md section 3.2); refusing beats silently training
        # with a different rule than the one configured.
        if cfg.optimization != "sgd":
            raise ValueError(
                f"single-process runs support optimization='sgd' only "
                f"(got {cfg.optimization!r}); distributed optimizers need "
                f"--np > 1"
            )
        trainer = BiCNNTrainer(cfg, None, data, rank)
        return {"role": "local", **trainer.run()}
    testerfirst, testerlast = resolve_tester_flags(cfg)
    sranks, cranks, tester_rank, tranks = assign_roles(
        effective, int(cfg.master_freq), testerfirst, testerlast,
        str(cfg.valid_mode),
    )
    if rank in sranks:
        server = ParamServer(
            rank, cranks, transport,
            rule=server_rule_for(cfg),
            single_mode=bool(cfg.singlemode)
            or cfg.optimization.endswith("single"),
            dtype=cfg.get("dtype", "float32"),
        )
        log.info("server for clients %s", cranks)
        server.start()
        return {
            "role": "server",
            "grads_applied": server.grads_applied,
            "params_served": server.params_served,
        }
    # The FIRST entry of cranks seeds the initial params (reference
    # pclient.lua:125-128 — with testerfirst that is the tester itself,
    # whose freshly-built model provides the init, bicnn.lua:268-271).
    pclient = ParamClient(
        rank, sranks, transport, seed_servers=(rank == cranks[0])
    )
    trainer = BiCNNTrainer(cfg, pclient=pclient, data=data, rank=rank)
    if rank == tester_rank:
        log.info("tester with servers %s", sranks)
        return {"role": "tester", **trainer.run_tester()}
    log.info("worker with servers %s", sranks)
    return {"role": "worker", **trainer.run(is_last_client=rank in tranks)}


def _child_main() -> None:
    from mpit_tpu.train.gang import child_env, child_transport, write_result

    rank, size, cfg = child_env()
    # Live introspection endpoint (obs/statusd; no-op unless
    # MPIT_OBS_HTTP is set) — same hook as train/launch.py children.
    from mpit_tpu.obs import maybe_start_statusd

    maybe_start_statusd(rank)
    transport = child_transport(cfg, rank, size)
    result = run_rank(rank, size, cfg, transport)
    transport.close()
    write_result(result)


def main(argv: Optional[List[str]] = None) -> None:
    argv = list(sys.argv[1:] if argv is None else argv)
    if "--child" in argv:
        _child_main()
        return
    # Honor JAX_PLATFORMS for the in-process np=1 path too (gang children
    # already do): environments that pre-import an accelerator plugin
    # otherwise ignore the env var and a CPU-intended run lands on the
    # accelerator.
    from mpit_tpu.utils.platform import honor_jax_platforms

    honor_jax_platforms()
    cfg = BICNN_LAUNCH_DEFAULTS.parse_args(argv)
    # Fail fast in the parent: a bad optimizer name or role split discovered
    # only inside a child would strand its gang peers in the stop protocol.
    if cfg.optimization not in BiCNNTrainer.KNOWN_OPTS:
        raise ValueError(
            f"unknown optimization {cfg.optimization!r}; "
            f"have {BiCNNTrainer.KNOWN_OPTS}"
        )
    from mpit_tpu.train.bicnn import explicit_qa_files

    if cfg.get("docqa", False) and not explicit_qa_files(cfg):
        # Explicit --*_file flags take precedence over the fixture (the
        # trainer's _load_data order), so only the fixture-needing case
        # is validated here — in the parent, so a gang is never spawned
        # to fail rank by rank.
        from mpit_tpu.data.qa import docqa_paths

        if docqa_paths() is None:
            raise FileNotFoundError(
                "--docqa 1 but data/fixtures/docqa is absent — run "
                "tools/make_docqa.py or pass explicit --*_file flags"
            )
    effective = min(int(cfg.np), int(cfg.maxrank) + 1)
    tester_flags = resolve_tester_flags(cfg)  # validate even for np=1
    if effective > 1:
        assign_roles(
            effective, int(cfg.master_freq), *tester_flags,
            str(cfg.valid_mode),
        )
    t0 = time.monotonic()
    if int(cfg.np) == 1:
        result = run_rank(0, 1, cfg, transport=None)
        print(json.dumps({"rank0": _summarize(result)}, indent=2))
    else:
        from mpit_tpu.train.gang import launch_gang

        results = launch_gang("mpit_tpu.train.bicnn_launch", cfg)
        print(json.dumps(
            {str(r): _summarize(res) for r, res in sorted(results.items())},
            indent=2,
        ))
    print(f"total {time.monotonic() - t0:.1f}s")


def _summarize(result: Dict[str, Any]) -> Dict[str, Any]:
    out = {k: v for k, v in result.items() if k != "history"}
    history = result.get("history")
    if history:
        out["last"] = history[-1]
    return out


if __name__ == "__main__":
    main()
