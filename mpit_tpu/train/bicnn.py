"""BiCNN trainer — the bicnn.lua workload, TPU-first.

Covers the reference's whole training file (BiCNN/bicnn.lua): the
negative-sampling feval (:305-410), margin ranking loss (:121),
L1/L2 regularization and gradient clamp (:387-409), the loss print every
2000 fevals (:414-418), the test3 evaluation over valid/test1/test2 with
best-accuracy tracking (:465-571), the dedicated-tester pull/eval/save
loop (:580-596), the shuffled train loop with commperiod-gated lastClient
testing (:598-638), and the 12-name optimizer dispatch (:127-252) mapped
onto this framework's optimizer family.

TPU-native feval (the key redesign). The reference scores negatives one
at a time in a data-dependent rejection loop (bicnn.lua:321-359) — a
shape/control-flow pattern XLA cannot compile.  Here each example draws
its ``maxnegsample`` candidate labels up front (host RNG, rejecting gold
labels exactly like the inner ``while`` at :325-330), and ONE jitted
program scores all (B, K) candidates batched, selects per example the
FIRST margin-violating candidate (the reference's early-``break``
semantics, :348-358), and computes loss + grad for the selected pairs.
Examples with no violating candidate among K contribute zero loss and
zero gradient — the ``goto continue`` path (:361-371).  Same sampling
semantics, but the candidate scoring rides the MXU as one batched matmul
instead of up to 100 sequential single-pair forwards.

Deliberate trajectory-level differences (async SGD has no golden
trajectory — SURVEY.md section 7):
- the reference clamps the *accumulated* gradient after every example
  (:398-409); here the batch gradient is clamped once — both end within
  ±grad_clip;
- regularization is added once per contributing example there; here the
  batch term is scaled by the number of contributing examples — same sum.
"""

from __future__ import annotations

import pathlib
import time
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from mpit_tpu.data.qa import QAData, EvalSet, load_qa
from mpit_tpu.models.bicnn import BiCNN, gesd, margin_ranking_loss
from mpit_tpu.models.flat import FlatModel
from mpit_tpu.optim import EAMSGD, MSGD, Downpour, RuleShell, SingleWorker
from mpit_tpu.optim import rules as rules_mod
from mpit_tpu.optim.msgd import MSGDConfig
from mpit_tpu.utils.checkpoint import load_flat, save_flat
from mpit_tpu.utils.config import Config
from mpit_tpu.utils.logging import get_logger
from mpit_tpu.obs import PhaseTimers

# The full plaunch.lua flag surface (reference BiCNN/plaunch.lua:7-69),
# snake_cased; rebuild-only knobs at the bottom.
QA_FILE_KEYS = ("embedding_file", "train_file", "valid_file",
                "test_file1", "test_file2", "label2answ_file")


def explicit_qa_files(cfg) -> bool:
    """True when ALL six corpus files are given explicitly — the ONE
    predicate deciding whether file flags take precedence over the
    docqa fixture (shared by the trainer's _load_data and the launcher's
    parent-side validation, which must agree)."""
    return all(cfg.get(k, "none") != "none" for k in QA_FILE_KEYS)


BICNN_DEFAULTS = Config(
    optimization="downpour",  # sgd|downpour|eamsgd|adam|adamax|adamsingle|
    #   adamaxsingle|rmsprop|rmspropsingle|adagrad|adagradsingle|adadelta|
    #   adadeltasingle (plaunch.lua:11)
    learning_rate=1e-2,
    batch_size=1,  # plaunch.lua:13 (1 = pure stochastic)
    lr_adagrad=1e-3,
    lr_decay_adagrad=1e-6,
    epsilon_adagrad=1e-10,
    rho_adadelta=0.9,
    lr_adadelta=1.0,
    epsilon_adadelta=1e-6,
    lr_adam=1e-3,
    beta1_adam=0.9,
    beta2_adam=0.999,
    epsilon_adam=1e-8,
    step_div_adam=72,
    grad_clip=0.5,
    weight_decay=1e-6,
    decay_rmsprop=0.95,
    lr_rmsprop=1e-4,
    momentum_rmsprop=0.9,
    epsilon_rmsprop=1e-4,
    momentum=0.0,
    commperiod=1,
    movingrate=0.05,
    dtype="float32",  # the 'type' flag: double|float|cuda -> array dtype
    train_file="none",
    valid_file="none",
    test_file1="none",
    test_file2="none",
    label2answ_file="none",
    embedding_file="none",
    embedding_dim=100,
    cont_conv_width=2,
    word_hidden_dim=200,
    num_filters=3000,
    epoch=50,
    l1reg=0.0,
    l2reg=1e-4,
    margin=0.02,
    maxnegsample=100,
    valid_mode="additionalTester",  # none | lastClient | additionalTester
    valid_sleep_time=1.0,
    mmode=1,  # 1|2 — graph-plumbing variants of the same math (models/bicnn.py)
    outputprefix="none",
    prevtime=0.0,
    loadmodel="none",
    preload_binary=False,
    binary_path="",  # where the preload_binary cache lives (.npz)
    testerfirst=False,
    testerlast=False,
    master_freq=2,
    maxrank=120,
    singlemode=False,
    docqa=False,  # train on the committed real stdlib-docstring corpus
    #   (data/fixtures/docqa; wins over synthetic when no --*_file given)
    # -- rebuild-only ------------------------------------------------------
    seed=1,
    loss_report_every=2000,  # bicnn.lua:414 prints every 2000 fevals
    tester_rounds=10,  # bounded tester lifecycle (the reference's never
    #   stops — flagged TODO at bicnn.lua:581)
    eval_chunk=64,  # batch size for answer/query embedding at eval
)

_SINGLE = {
    "adamsingle": "adam", "adamaxsingle": "adamax", "rmspropsingle": "rmsprop",
    "adagradsingle": "adagrad", "adadeltasingle": "adadelta",
}
_GLOBAL = ("adam", "adamax", "rmsprop", "adagrad", "adadelta")


def rule_hyperparams(cfg: Config, rule: str) -> Dict[str, Any]:
    """Per-method hyperparameters from the plaunch flag groups
    (reference plaunch.lua:15-36 -> pserver dispatch BiCNN/pserver.lua:123-197)."""
    if rule == "adam":
        return dict(lr=cfg.lr_adam, beta1=cfg.beta1_adam,
                    beta2=cfg.beta2_adam, epsilon=cfg.epsilon_adam)
    if rule == "adamax":
        return dict(lr=cfg.lr_adam, beta1=cfg.beta1_adam,
                    beta2=cfg.beta2_adam, epsilon=cfg.epsilon_adam)
    if rule == "rmsprop":
        return dict(lr=cfg.lr_rmsprop, decay=cfg.decay_rmsprop,
                    momentum=cfg.momentum_rmsprop, epsilon=cfg.epsilon_rmsprop)
    if rule == "adagrad":
        return dict(lr=cfg.lr_adagrad, lrd=cfg.lr_decay_adagrad,
                    epsilon=cfg.epsilon_adagrad)
    if rule == "adadelta":
        return dict(lr=cfg.lr_adadelta, rho=cfg.rho_adadelta,
                    epsilon=cfg.epsilon_adadelta)
    raise ValueError(f"no hyperparameter group for rule {rule!r}")


def server_rule_for(cfg: Config):
    """Server-side shard rule matching the client optimizer — the BiCNN
    pserver's conf.opt dispatch (reference BiCNN/pserver.lua:123-197)."""
    name = cfg.optimization
    if name in _GLOBAL:
        hp = rule_hyperparams(cfg, name)
        if name == "adam":
            # Adam's server-side bias correction is stepDiv-scaled
            # (reference BiCNN/pserver.lua:140-155).
            hp["step_div"] = cfg.step_div_adam
        return rules_mod.make(name, **hp)
    return rules_mod.make("add")


def gesd_np(q: np.ndarray, a: np.ndarray) -> np.ndarray:
    """Host-side GESD over (F,) x (P, F) — the eval-time inlined formula
    (reference bicnn.lua:440-443).  Kept as the semantic oracle for the
    device scorer (:func:`_pool_score`); tests compare the two."""
    dot = a @ q
    l2 = np.sqrt(np.maximum(((a - q) ** 2).sum(axis=-1), 0.0))
    return 1.0 / ((1.0 + l2) * (1.0 + np.exp(-(dot + 1.0))))


def _pool_score(q_emb, ans_emb, idx, mask, hit):
    """Device-side pool-restricted selection: correct count over all
    questions in one XLA program (replaces the reference's per-question
    host loop, bicnn.lua:426-460 — quadratic host pain at real pool
    sizes).

    Each question's padded candidate pool is gathered from the answer
    matrix and scored with the *direct* GESD form — same arithmetic as
    the host oracle :func:`gesd_np` (an expanded |q|^2+|a|^2-2qa form
    would catastrophically cancel exactly for the near-ties that decide
    argmax).  ``lax.map`` over question chunks bounds memory at
    O(chunk * P * F) regardless of question count.  ``idx/mask`` encode
    the pools (mask: candidate known to the answer space, bicnn.lua:434
    filter), ``hit`` whether a slot's label is gold.  Ties keep the
    LAST maximum (reference bicnn.lua:444-447), via argmax of the
    reversed pool axis."""
    chunk = 32
    qf = q_emb.astype(jnp.float32)
    af = ans_emb.astype(jnp.float32)
    n, p = idx.shape
    pad = (-n) % chunk
    if pad:
        qf = jnp.pad(qf, ((0, pad), (0, 0)))
        idx = jnp.pad(idx, ((0, pad), (0, 0)))
        mask = jnp.pad(mask, ((0, pad), (0, 0)))  # False: never counted
        hit = jnp.pad(hit, ((0, pad), (0, 0)))

    def score_chunk(args):
        qc, ic, mc, hc = args  # (C, F), (C, P), (C, P), (C, P)
        ac = af[ic]  # (C, P, F)
        dot = jnp.einsum("cpf,cf->cp", ac, qc)
        l2 = jnp.sqrt(jnp.maximum(
            jnp.sum((ac - qc[:, None, :]) ** 2, axis=-1), 0.0))
        sims = 1.0 / ((1.0 + l2) * (1.0 + jnp.exp(-(dot + 1.0))))
        sims = jnp.where(mc, sims, -jnp.inf)
        best = p - 1 - jnp.argmax(sims[:, ::-1], axis=1)  # LAST max
        chosen_hit = jnp.take_along_axis(hc, best[:, None], axis=1)[:, 0]
        return jnp.sum((chosen_hit & jnp.any(mc, axis=1)).astype(jnp.int32))

    counts = jax.lax.map(score_chunk, (
        qf.reshape(-1, chunk, qf.shape[1]),
        idx.reshape(-1, chunk, p),
        mask.reshape(-1, chunk, p),
        hit.reshape(-1, chunk, p),
    ))
    return jnp.sum(counts)


class BiCNNTrainer:
    """The bicnn.lua workload driver (train or tester role)."""

    def __init__(
        self,
        cfg: Optional[Config] = None,
        pclient: Any = None,
        data: Optional[QAData] = None,
        rank: int = 0,
    ):
        self.cfg = cfg = BICNN_DEFAULTS.merged(cfg.to_dict() if cfg else None)
        self.pc = pclient
        self.rank = rank
        self.log = get_logger("bicnn", rank)
        self.tm = PhaseTimers()
        self.rng = np.random.default_rng(cfg.seed + rank)

        if data is None:
            data = self._load_data()
        self.data = data
        self.log.info(
            "data: %s (%d train, %d answers, vocab %d)",
            data.source, len(data.train), data.answer_space, len(data.vocab),
        )

        vocab_matrix = data.vocab.matrix()
        # Pretrained-vector initialization of the lookup table
        # (reference bicnn.lua:34).
        def embedding_init(key, shape, dtype=jnp.float32):
            assert tuple(shape) == vocab_matrix.shape, (shape, vocab_matrix.shape)
            return jnp.asarray(vocab_matrix, dtype)

        self.module = BiCNN(
            vocab_size=len(data.vocab),
            # the data's embedding width is authoritative — a corpus
            # loaded from files (e.g. the 50-dim docqa fixture) wins
            # over the config default
            embedding_dim=data.vocab.embedding_dim,
            word_hidden_dim=cfg.word_hidden_dim,
            num_filters=cfg.num_filters,
            conv_width=cfg.cont_conv_width,
            embedding_init=embedding_init,
        )
        rng_key = jax.random.PRNGKey(cfg.seed)
        sample_tok = jnp.asarray(data.train.q_tokens[:1])
        sample_len = jnp.asarray(data.train.q_len[:1])
        params = self.module.init(
            rng_key, sample_tok, sample_len, sample_tok, sample_len,
            sample_tok, sample_len,
        )["params"]
        self.flat = FlatModel(self.module, params)
        self.w = self.flat.w0.astype(jnp.dtype(cfg.dtype))
        if cfg.loadmodel != "none":
            w, meta = load_flat(cfg.loadmodel)
            self.w = jnp.asarray(w, self.w.dtype)  # bicnn.lua:259-261
            self.log.info("resumed from %s (meta %s)", cfg.loadmodel, meta)

        self._embed = jax.jit(
            lambda w, t, l: self.flat.module.apply(
                {"params": self.flat.unravel(w)}, t, l, method=BiCNN.embed
            )
        )
        self._pool_cache: Dict[str, tuple] = {}
        self._pool_score = jax.jit(_pool_score)
        self._vgf = self._build_vgf()
        self._optimizer = None
        # loss-print accumulators (bicnn.lua:283, :414-418).  A running
        # *device* scalar sum, fetched only at report time — a float()
        # per step would fence the dispatch pipeline on every batch, and
        # a list of per-step scalars would grow without bound when
        # reporting is disabled.
        self._loss_acc: Any = None
        self._loss_count = 0
        self.best = {}  # per-dataset best accuracy/epoch (bicnn.lua:505-571)
        self.epoch = 0

    # -- data ----------------------------------------------------------------

    def _load_data(self) -> QAData:
        cfg = self.cfg
        explicit_files = explicit_qa_files(cfg)
        # Effective embedding width, resolved ONCE so every branch
        # (binary cache validation included) agrees: docqa's 50-dim
        # files override an untouched 100-dim config default — but only
        # when the docqa branch would actually load the data (explicit
        # --*_file flags take precedence over the fixture).
        want_dim = cfg.embedding_dim
        if (cfg.get("docqa", False) and not explicit_files
                and cfg.embedding_dim == BICNN_DEFAULTS.embedding_dim):
            from mpit_tpu.data.qa import DOCQA_EMBEDDING_DIM

            want_dim = DOCQA_EMBEDDING_DIM
        cache = pathlib.Path(cfg.binary_path) if (
            cfg.preload_binary and cfg.binary_path
        ) else None
        if cache is not None and cache.exists():
            return load_qa(
                binary_path=cache,
                conv_width=cfg.cont_conv_width,
                embedding_dim=want_dim,
            )
        if explicit_files:
            data = load_qa(
                embedding_dim=cfg.embedding_dim,
                conv_width=cfg.cont_conv_width,
                paths={k: pathlib.Path(cfg.get(k)) for k in QA_FILE_KEYS},
                oov_seed=cfg.seed,
            )
        elif cfg.get("docqa", False):
            # The committed REAL corpus (stdlib docstrings).
            from mpit_tpu.data.qa import docqa_paths

            paths = docqa_paths()
            if paths is None:
                raise FileNotFoundError(
                    "docqa=1 but data/fixtures/docqa is absent — run "
                    "tools/make_docqa.py or use explicit --*_file flags"
                )
            data = load_qa(
                embedding_dim=want_dim, conv_width=cfg.cont_conv_width,
                paths=paths, oov_seed=cfg.seed,
            )
            data.source = "docqa fixture (real stdlib-docstring corpus)"
        else:
            data = load_qa(
                embedding_dim=cfg.embedding_dim,
                conv_width=cfg.cont_conv_width,
                oov_seed=cfg.seed,
            )
        if cache is not None:
            # First run with preload_binary populates the cache — the
            # analog of generating the reference's checked-in binaries
            # (plaunch.lua:218-229).
            from mpit_tpu.data.qa import save_binary

            save_binary(data, cache)
            self.log.info("wrote binary cache %s (from %s)", cache, data.source)
        return data

    # -- feval ---------------------------------------------------------------

    def _build_vgf(self):
        cfg = self.cfg
        margin = float(cfg.margin)
        l1, l2 = float(cfg.l1reg), float(cfg.l2reg)
        clip = float(cfg.grad_clip)
        apply_flat = self.flat.apply_flat

        def loss_fn(w, q, ql, ap, apl, nt, nl):
            b, k, la = nt.shape
            # One tower pass per distinct input — tying by construction.
            eq = apply_flat(w, q, ql, method=BiCNN.embed)  # (B, F)
            ep = apply_flat(w, ap, apl, method=BiCNN.embed)  # (B, F)
            en = apply_flat(
                w, nt.reshape(b * k, la), nl.reshape(b * k), method=BiCNN.embed
            ).reshape(b, k, -1)  # batched candidate towers, (B, K, F)
            s_pos = gesd(eq, ep)  # (B,)
            en_scores = gesd(eq[:, None, :], en)  # (B, K)
            # First margin-violating candidate per example — the
            # sequential-break semantics (bicnn.lua:348-358).
            viol = (s_pos[:, None] - en_scores) < margin
            has = jnp.any(viol, axis=1)
            first = jnp.argmax(viol, axis=1)
            onehot = jax.nn.one_hot(first, k, dtype=en_scores.dtype)
            s_neg = jnp.sum(onehot * en_scores, axis=1)
            per_ex = margin_ranking_loss(s_pos, s_neg, margin) * has
            n_contrib = jnp.sum(has.astype(w.dtype))
            f = jnp.sum(per_ex)
            # Per-contributing-example regularization (bicnn.lua:387-397).
            if l1:
                f = f + n_contrib * l1 * jnp.sum(jnp.abs(w))
            if l2:
                f = f + n_contrib * l2 * 0.5 * jnp.sum(w * w)
            return f

        raw = jax.value_and_grad(loss_fn)

        def vgf(w, *args):
            loss, g = raw(w, *args)
            return loss, jnp.clip(g, -clip, clip)  # bicnn.lua:398-409

        return vgf

    def sample_negatives(self, batch_labels: List[List[int]]) -> Tuple[np.ndarray, np.ndarray]:
        """Draw (B, K) candidate answer rows, rejecting gold labels — the
        host half of the rejection loop (bicnn.lua:325-330)."""
        data, k = self.data, int(self.cfg.maxnegsample)
        a = data.answer_space
        rows = self.rng.integers(0, a, size=(len(batch_labels), k))
        l2r = data.label2row
        for i, gold in enumerate(batch_labels):
            gold_rows = {l2r[g] for g in gold if g in l2r}
            if not gold_rows or len(gold_rows) >= a:
                continue
            bad = np.isin(rows[i], list(gold_rows))
            while bad.any():
                rows[i, bad] = self.rng.integers(0, a, size=int(bad.sum()))
                bad = np.isin(rows[i], list(gold_rows))
        nt = data.answer_tokens[rows]  # (B, K, La)
        nl = data.answer_len[rows]  # (B, K)
        return nt.astype(np.int32), nl.astype(np.int32)

    # -- optimizer dispatch (bicnn.lua:127-252, plaunch names) ---------------

    KNOWN_OPTS = ("sgd", "downpour", "eamsgd", "easgd") + _GLOBAL + tuple(_SINGLE)

    @property
    def optimizer(self):
        if self._optimizer is None:
            self._optimizer = self._make_optimizer()
        return self._optimizer

    def _make_optimizer(self):
        cfg = self.cfg
        name = cfg.optimization
        if name not in self.KNOWN_OPTS:
            raise ValueError(f"unknown optimization {name!r}; have {self.KNOWN_OPTS}")
        if name == "sgd":
            return MSGD(
                MSGDConfig(lr=cfg.learning_rate, mom=cfg.momentum,
                           l2wd=cfg.weight_decay),
                self._vgf,
            )
        if self.pc is None:
            raise ValueError(f"optimization {name!r} needs a parameter client")
        if name == "downpour":
            return Downpour(self._vgf, self.pc, lr=cfg.learning_rate,
                            su=cfg.commperiod)
        if name in ("eamsgd", "easgd"):
            mom = 0.0 if name == "easgd" else cfg.momentum
            return EAMSGD(self._vgf, self.pc, lr=cfg.learning_rate, mom=mom,
                          mva=cfg.movingrate, su=cfg.commperiod)
        if name in _GLOBAL:
            # Accumulate-and-ship; the server applies the stateful rule
            # (reference BiCNN/optim-adam.lua etc. + pserver dispatch).
            return RuleShell(self._vgf, self.pc, su=cfg.commperiod, mode="global")
        rule = _SINGLE[name]
        return SingleWorker(self._vgf, self.pc, rule=rule,
                            **rule_hyperparams(cfg, rule))

    # -- evaluation (test3, bicnn.lua:465-571) -------------------------------

    def _embed_chunked(self, w, tokens: np.ndarray, lengths: np.ndarray) -> jnp.ndarray:
        """Embed (N, L) in fixed-size chunks (static shapes; one compile).
        Returns a device array — the scorer consumes it in place, so
        eval never round-trips embeddings through the host."""
        chunk = int(self.cfg.eval_chunk)
        n = tokens.shape[0]
        pad = (-n) % chunk
        if pad:
            tokens = np.concatenate([tokens, np.repeat(tokens[:1], pad, 0)])
            lengths = np.concatenate([lengths, np.repeat(lengths[:1], pad)])
        outs = [
            self._embed(w, jnp.asarray(tokens[i : i + chunk]),
                        jnp.asarray(lengths[i : i + chunk]))
            for i in range(0, tokens.shape[0], chunk)
        ]
        return jnp.concatenate(outs)[:n]

    def _pool_tables(self, eval_set: EvalSet, name: str):
        """Padded device tables for one eval set, built once and cached
        (pools and labels never change during a run): ``idx`` (N, P)
        answer-matrix rows, ``mask`` slot validity (candidate known to
        the answer space, bicnn.lua:434 filter), ``hit`` whether the
        slot's label is gold for its question."""
        cached = self._pool_cache.get(name)
        if cached is not None and cached[0] is eval_set:
            return cached[1:]
        l2r = self.data.label2row
        n = len(eval_set)
        p = max((len(pool) for pool in eval_set.pools), default=1) or 1
        idx = np.zeros((n, p), np.int32)
        mask = np.zeros((n, p), bool)
        hit = np.zeros((n, p), bool)
        for i, pool in enumerate(eval_set.pools):
            gold = set(eval_set.labels[i])
            for j, v in enumerate(pool):
                row = l2r.get(v)
                if row is None:
                    continue
                idx[i, j] = row
                mask[i, j] = True
                hit[i, j] = v in gold
        tables = (jnp.asarray(idx), jnp.asarray(mask), jnp.asarray(hit))
        self._pool_cache[name] = (eval_set,) + tables
        return tables

    def evaluate(
        self, eval_set: EvalSet, name: str, w=None, ans_emb: Optional[np.ndarray] = None
    ) -> float:
        """Pool-restricted answer selection accuracy for one dataset —
        one leg of test3 (bicnn.lua:465-510).  ``ans_emb`` lets test3
        embed the answer space once for all three datasets."""
        w = self.w if w is None else w
        data = self.data
        with self.tm.phase("test"):
            if ans_emb is None:
                ans_emb = self._embed_chunked(w, data.answer_tokens, data.answer_len)
            q_emb = self._embed_chunked(w, eval_set.q_tokens, eval_set.q_len)
            idx, mask, hit = self._pool_tables(eval_set, name)
            correct = int(self._pool_score(q_emb, ans_emb, idx, mask, hit))
            acc = correct / max(len(eval_set), 1)
        prev = self.best.get(name, (0.0, -1))
        if acc > prev[0]:
            self.best[name] = (acc, self.epoch)
        best_acc = self.best.get(name, (acc, self.epoch))[0]
        self.log.info(
            "curr time: %.2f, Accuracy: %.4f, best Accuracy: %.4f on %s",
            self.tm.elapsed() + float(self.cfg.prevtime), acc, best_acc, name,
        )
        return acc

    def test3(self, w=None) -> Dict[str, float]:
        """Evaluate valid + test1 + test2 (bicnn.lua:465-571, :589).
        The answer space is embedded once and shared across the three
        datasets (the reference re-embeds it per dataset, :467-470)."""
        w_eval = self.w if w is None else w
        with self.tm.phase("test"):
            ans_emb = self._embed_chunked(
                w_eval, self.data.answer_tokens, self.data.answer_len
            )
        return {
            "valid": self.evaluate(self.data.valid, "valid", w_eval, ans_emb),
            "test1": self.evaluate(self.data.test1, "test1", w_eval, ans_emb),
            "test2": self.evaluate(self.data.test2, "test2", w_eval, ans_emb),
        }

    def _save_checkpoint(self) -> None:
        """Runtime-stamped whole-param save (bicnn.lua:590-594)."""
        prefix = self.cfg.outputprefix
        if prefix == "none" or not prefix:
            return
        path = pathlib.Path(prefix)
        runtime = self.tm.elapsed() + float(self.cfg.prevtime)
        save_flat(
            path.parent if path.parent != pathlib.Path("") else pathlib.Path("."),
            self.w,
            {"runtime": runtime, "epoch": self.epoch, "best": dict(self.best)},
            prefix=path.name,
        )

    # -- the train loop (bicnn.lua:598-638) ----------------------------------

    def _batches(self, order: np.ndarray):
        """Static-shape batch assembly: the trailing partial batch wraps
        around the shuffled order (the reference's variable last batch,
        bicnn.lua:612-623, would force an XLA recompile per shape)."""
        b = int(self.cfg.batch_size)
        n = len(order)
        for lo in range(0, n, b):
            idx = order[lo : lo + b]
            if len(idx) < b:
                idx = np.concatenate([idx, order[: b - len(idx)]])
            yield idx

    def step(self, idx: np.ndarray) -> jnp.ndarray:
        """One feval + optimizer step on the batch rows ``idx``.  Returns
        the loss as a device scalar — fetched lazily (report window,
        epoch average) so the dispatch pipeline is never fenced
        per-batch."""
        tr = self.data.train
        labels = [tr.labels[i] for i in idx]
        with self.tm.phase("sample"):
            nt, nl = self.sample_negatives(labels)
        q, ql = jnp.asarray(tr.q_tokens[idx]), jnp.asarray(tr.q_len[idx])
        ap, apl = jnp.asarray(tr.a_tokens[idx]), jnp.asarray(tr.a_len[idx])
        with self.tm.phase("feval"):
            self.w, loss = self.optimizer.step(
                self.w, q, ql, ap, apl, jnp.asarray(nt), jnp.asarray(nl)
            )
        self._loss_acc = loss if self._loss_acc is None else self._loss_acc + loss
        self._loss_count += 1
        if self._loss_count % int(self.cfg.loss_report_every) == 0:
            # One fetch for the whole window.
            self.log.info(
                "curr time: %.2f, training loss avg. : %.5f",
                self.tm.elapsed() + float(self.cfg.prevtime),
                float(self._loss_acc) / self._loss_count,
            )
            self._loss_acc, self._loss_count = None, 0
        return loss

    def run(self, is_last_client: bool = False) -> Dict[str, Any]:
        """Train for cfg.epoch epochs (the non-tester branch,
        bicnn.lua:598-638)."""
        cfg = self.cfg
        opt = self.optimizer
        if hasattr(opt, "start"):
            with self.tm.phase("start"):
                self.w = opt.start(self.w)
        n = len(self.data.train)
        pversion = 0
        history = []
        for epoch in range(int(cfg.epoch)):
            self.epoch = epoch
            t_epoch = time.monotonic()
            order = self.rng.permutation(n)  # shuffle (bicnn.lua:609)
            loss_sum, steps = None, 0
            for idx in self._batches(order):
                loss = self.step(idx)
                loss_sum = loss if loss_sum is None else loss_sum + loss
                steps += 1
                # lastClient in-train testing every commperiod steps
                # (bicnn.lua:625-633).
                if (
                    cfg.valid_mode == "lastClient"
                    and is_last_client
                    and pversion % int(cfg.commperiod) == 0
                ):
                    self.test3()
                    self._save_checkpoint()
                pversion += 1
            history.append({
                "epoch": epoch,
                # One fetch per epoch (not one per step).
                "avg_loss": float(loss_sum) / steps if steps else 0.0,
                "seconds": time.monotonic() - t_epoch,
            })
            self.log.info(
                "epoch %d done, for %.2f seconds", epoch, history[-1]["seconds"]
            )
        accs = self.test3()
        sync = getattr(opt, "dusync", 0.0)
        self.tm.add("sync", sync)
        if hasattr(opt, "stop"):
            with self.tm.phase("stop"):
                opt.stop()
        return {
            "history": history,
            "accuracy": accs,
            "best": {k: {"acc": v[0], "epoch": v[1]} for k, v in self.best.items()},
            "elapsed": self.tm.elapsed(),
            "timers": dict(self.tm.total),
        }

    # -- tester role (additionalTester, bicnn.lua:580-596) -------------------

    def run_tester(self) -> Dict[str, Any]:
        """Pull params -> test3 -> checkpoint -> sleep, for a bounded
        number of rounds (the reference loops forever — TODO at
        bicnn.lua:581; a bounded lifecycle keeps the stop protocol exact)."""
        cfg = self.cfg
        if self.pc is None:
            raise ValueError("tester role needs a parameter client")
        # The tester's freshly-built model params back the client buffers —
        # with testerfirst the tester IS cranks[1] and seeds the servers'
        # initial params from them (reference bicnn.lua:268-271,
        # pclient.lua:125-128).
        param = np.array(self.w, np.dtype(cfg.dtype))
        grad = np.zeros_like(param)
        self.pc.start(param, grad)
        rounds = int(cfg.tester_rounds)
        history = []
        for r in range(rounds):
            self.epoch = r
            t0 = time.monotonic()
            self.pc.async_recv_param()
            self.pc.wait()
            self.log.info("communication time: %.2f", time.monotonic() - t0)
            self.w = jnp.asarray(param)
            accs = self.test3()
            history.append({"round": r, **accs})
            self._save_checkpoint()
            if r != rounds - 1:
                time.sleep(float(cfg.valid_sleep_time))
        self.pc.stop()
        return {
            "history": history,
            "best": {k: {"acc": v[0], "epoch": v[1]} for k, v in self.best.items()},
        }
