"""Launchers — the claunch/glaunch/mlaunch analogs.

Role assignment follows the reference's conventions: with the default
``master_freq=2``, even ranks become parameter servers and odd ranks become
workers (reference mlaunch.lua:25-31); BiCNN generalizes to every
``masterFreq``-th rank a server plus optional dedicated tester ranks
(reference plaunch.lua:123-163) — the same rule implemented here.

Three entry modes:

- ``--np 1``: single-process local training, no comm (claunch.lua analog —
  proves L4 is decoupled from L2/L1, SURVEY.md section 3.2);
- ``--np N``: this process forks N role processes wired over the native
  shm transport — the built-in ``mpirun -np N`` analog;
- library use: :func:`run_rank` with injected transports, so tests run
  whole topologies in threads on the in-process router.

Usage:
    python -m mpit_tpu.train.launch --np 4 --opt downpour --lr 0.01
    python -m mpit_tpu.train.launch --np 12 --opt eamsgd --su 100 \\
        --mom 0.99 --mva 0.15 --epochs 10
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import tempfile
import time
from typing import Any, Dict, List, Optional, Tuple

from mpit_tpu.optim import rules as rules_mod
from mpit_tpu.ps import ParamClient, ParamServer
from mpit_tpu.train.trainer import TRAINER_DEFAULTS, MnistTrainer
from mpit_tpu.utils.config import Config
from mpit_tpu.utils.logging import get_logger

LAUNCH_DEFAULTS = TRAINER_DEFAULTS.merged(
    np=1,
    master_freq=2,  # every master_freq-th rank is a server (mlaunch parity)
    tester="none",  # none | first | last  (plaunch testerfirst/testerlast)
    tester_rounds=10,
    tester_interval=1.0,
    ckpt_dir="",
    ring_mb=64,
    namespace="",
)


def assign_roles(
    size: int, master_freq: int = 2, tester: str = "none"
) -> Tuple[List[int], List[int], Optional[int]]:
    """Returns (server_ranks, client_ranks, tester_rank)."""
    ranks = list(range(size))
    tester_rank: Optional[int] = None
    if tester == "first":
        tester_rank = 0
        ranks = ranks[1:]
    elif tester == "last":
        tester_rank = size - 1
        ranks = ranks[:-1]
    sranks = [r for r in ranks if r % master_freq == 0]
    cranks = [r for r in ranks if r % master_freq != 0]
    if not sranks or not cranks:
        raise ValueError(
            f"role split produced {len(sranks)} servers / {len(cranks)} "
            f"clients from size={size}, master_freq={master_freq}"
        )
    return sranks, cranks, tester_rank


def server_rule_for(cfg: Config) -> Any:
    """The server-side shard rule matching the client optimizer
    (reference BiCNN/pserver.lua:123-197 dispatch)."""
    name = cfg.opt
    if name in ("rmsprop", "adam", "adamax", "adagrad", "adadelta"):
        return rules_mod.make(name, lr=cfg.lr)
    return rules_mod.make("add")  # downpour/easgd/eamsgd ship pre-scaled deltas


def run_rank(
    rank: int,
    size: int,
    cfg: Config,
    transport: Any,
    data: Any = None,
) -> Dict[str, Any]:
    """Run one rank's role to completion; returns its result dict."""
    log = get_logger("launch", rank)
    if size == 1:
        trainer = MnistTrainer(cfg, pclient=None, data=data, rank=rank)
        return {"role": "local", **trainer.run()}

    sranks, cranks, tester_rank = assign_roles(
        size, cfg.get("master_freq", 2), cfg.get("tester", "none")
    )
    single_mode = str(cfg.opt).endswith("-single")
    if rank == tester_rank:
        from mpit_tpu.train.tester import run_tester

        return {"role": "tester", **run_tester(rank, sranks, cfg, transport, data)}
    if rank in sranks:
        # The tester counts as a (pull-only) client: it announces shards and
        # participates in the stop protocol like any worker.
        all_clients = cranks + ([tester_rank] if tester_rank is not None else [])
        server = ParamServer(
            rank, all_clients, transport, rule=server_rule_for(cfg),
            single_mode=single_mode, dtype=cfg.get("dtype", "float32"),
        )
        log.info("server for clients %s", cranks)
        server.start()
        return {
            "role": "server",
            "grads_applied": server.grads_applied,
            "params_served": server.params_served,
        }
    pclient = ParamClient(
        rank, sranks, transport, seed_servers=(rank == cranks[0])
    )
    trainer = MnistTrainer(cfg, pclient=pclient, data=data, rank=rank)
    log.info("worker with servers %s", sranks)
    return {"role": "worker", **trainer.run()}


# -- process-mode launcher (the mpirun analog) -------------------------------


def _child_main() -> None:
    rank = int(os.environ["MPIT_RANK"])
    size = int(os.environ["MPIT_SIZE"])
    cfg = Config(**json.loads(os.environ["MPIT_CFG"]))
    from mpit_tpu.comm.shm import ShmTransport

    transport = ShmTransport(
        cfg.namespace, rank, size, ring_bytes=int(cfg.ring_mb) << 20
    )
    result = run_rank(rank, size, cfg, transport)
    transport.close()
    # Results travel over a dedicated file, not stdout: log lines from
    # library threads could interleave with (and corrupt) a stdout protocol.
    result_file = os.environ.get("MPIT_RESULT_FILE")
    if result_file:
        with open(result_file, "w") as fh:
            json.dump(result, fh)
    else:
        print(f"MPIT_RESULT {rank} {json.dumps(result)}", flush=True)


def launch_processes(cfg: Config, timeout: float = 3600.0) -> Dict[int, Dict[str, Any]]:
    size = int(cfg.np)
    # Fail fast in the parent: a bad optimizer name discovered only inside a
    # worker child would strand the server children in their stop protocol.
    if cfg.opt not in MnistTrainer.KNOWN_OPTS:
        raise ValueError(
            f"unknown optimizer {cfg.opt!r}; have {MnistTrainer.KNOWN_OPTS}"
        )
    namespace = cfg.namespace or f"mpit{os.getpid()}"
    cfg = cfg.merged(namespace=namespace)
    env_base = {**os.environ, "MPIT_SIZE": str(size), "MPIT_CFG": json.dumps(cfg.to_dict())}
    # Children write to per-rank log files, not pipes: nobody needs to
    # drain them while the gang runs, so a log-heavy child can never block
    # on a full pipe buffer mid-run.
    logdir = tempfile.mkdtemp(prefix=f"{namespace}_logs_")
    procs = []
    logfiles = []
    resultfiles = []
    for rank in range(size):
        logpath = os.path.join(logdir, f"rank{rank}.log")
        resultpath = os.path.join(logdir, f"rank{rank}.result.json")
        logfiles.append(logpath)
        resultfiles.append(resultpath)
        env = {
            **env_base,
            "MPIT_RANK": str(rank),
            "MPIT_RESULT_FILE": resultpath,
        }
        with open(logpath, "w") as fh:
            procs.append(
                subprocess.Popen(
                    [sys.executable, "-m", "mpit_tpu.train.launch", "--child"],
                    env=env,
                    stdout=fh,
                    stderr=subprocess.STDOUT,
                    text=True,
                )
            )
    # Monitor the gang: one dead rank starves its peers (servers wait for
    # STOPs that will never arrive), so a failure tears the whole gang down.
    deadline = time.monotonic() + timeout
    failed: Optional[int] = None
    timed_out = False
    while True:
        states = [p.poll() for p in procs]
        if all(s is not None for s in states):
            break
        bad = next((i for i, s in enumerate(states) if s not in (None, 0)), None)
        timed_out = time.monotonic() > deadline
        if bad is not None or timed_out:
            failed = bad
            for p in procs:
                if p.poll() is None:
                    p.terminate()
            break
        time.sleep(0.2)
    for proc in procs:
        try:
            proc.wait(timeout=30)
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.wait()
    results: Dict[int, Dict[str, Any]] = {}
    for rank, (logpath, resultpath) in enumerate(zip(logfiles, resultfiles)):
        with open(logpath) as fh:
            for line in fh:
                print(line.rstrip("\n"))
        if os.path.exists(resultpath):
            with open(resultpath) as fh:
                results[rank] = json.load(fh)
    if timed_out and failed is None:
        alive = [r for r, s in enumerate(states) if s is None]
        raise RuntimeError(
            f"gang timed out after {timeout:.0f}s; ranks still running at "
            f"teardown: {alive}; gang torn down (logs: {logdir})"
        )
    if failed is not None:
        raise RuntimeError(
            f"rank {failed} exited with {procs[failed].returncode}; "
            f"gang torn down (logs: {logdir})"
        )
    for rank, proc in enumerate(procs):
        if proc.returncode != 0:
            raise RuntimeError(f"rank {rank} exited with {proc.returncode}")
    missing = [r for r in range(size) if r not in results]
    if missing:
        raise RuntimeError(
            f"ranks {missing} exited 0 but reported no result (logs: {logdir})"
        )
    import shutil

    shutil.rmtree(logdir, ignore_errors=True)  # only useful on failure
    return results


def main(argv: Optional[List[str]] = None) -> None:
    argv = list(sys.argv[1:] if argv is None else argv)
    if "--child" in argv:
        _child_main()
        return
    cfg = LAUNCH_DEFAULTS.parse_args(argv)
    t0 = time.monotonic()
    if int(cfg.np) == 1:
        result = run_rank(0, 1, cfg, transport=None)
        print(json.dumps({"rank0": _summarize(result)}, indent=2))
    else:
        results = launch_processes(cfg)
        print(
            json.dumps(
                {str(r): _summarize(res) for r, res in sorted(results.items())},
                indent=2,
            )
        )
    print(f"total wall time: {time.monotonic() - t0:.1f}s")


def _summarize(result: Dict[str, Any]) -> Dict[str, Any]:
    keep = {"role", "final_test_err", "time_to_target", "elapsed",
            "grads_applied", "params_served", "best_test_err"}
    return {k: v for k, v in result.items() if k in keep}


if __name__ == "__main__":
    main()
