"""Launchers — the claunch/glaunch/mlaunch analogs.

Role assignment follows the reference's conventions: with the default
``master_freq=2``, even ranks become parameter servers and odd ranks become
workers (reference mlaunch.lua:25-31); BiCNN generalizes to every
``masterFreq``-th rank a server plus optional dedicated tester ranks
(reference plaunch.lua:123-163) — the same rule implemented here.

Three entry modes:

- ``--np 1``: single-process local training, no comm (claunch.lua analog —
  proves L4 is decoupled from L2/L1, SURVEY.md section 3.2);
- ``--np N``: this process forks N role processes wired over the native
  shm transport — the built-in ``mpirun -np N`` analog;
- library use: :func:`run_rank` with injected transports, so tests run
  whole topologies in threads on the in-process router.

Usage:
    python -m mpit_tpu.train.launch --np 4 --opt downpour --lr 0.01
    python -m mpit_tpu.train.launch --np 12 --opt eamsgd --su 100 \\
        --mom 0.99 --mva 0.15 --epochs 10
"""

from __future__ import annotations

import json
import sys
import time
from typing import Any, Dict, List, Optional, Tuple

from mpit_tpu.optim import rules as rules_mod
from mpit_tpu.ps import ParamClient, ParamServer
from mpit_tpu.train.trainer import TRAINER_DEFAULTS, MnistTrainer
from mpit_tpu.utils.config import Config
from mpit_tpu.utils.logging import get_logger

LAUNCH_DEFAULTS = TRAINER_DEFAULTS.merged(
    np=1,
    master_freq=2,  # every master_freq-th rank is a server (mlaunch parity)
    tester="none",  # none | first | last  (plaunch testerfirst/testerlast)
    tester_rounds=10,
    tester_interval=1.0,
    ckpt_dir="",
    ring_mb=64,
    namespace="",
    # Per-rank device assignment (the reference's AGPU map,
    # mlaunch.lua:56-62): inherit | cpu | workers_accel (one compute rank
    # — tester else first client — owns the accelerator, rest CPU).
    device_policy="inherit",
    # Gang wire: shm (one host) | tcp (cross-host; tcp_addrs = one
    # host:port per rank, comma-separated — the hostfile analog).
    transport="shm",
    tcp_addrs="",
    gang_barrier=True,  # startup rendezvous before any role traffic
    # Server shard checkpointing + resume (beyond-reference — SURVEY §5:
    # the reference never checkpoints server state).  server_ckpt_dir
    # activates periodic per-server shard+rule-state snapshots; --resume
    # restores them and skips client seeding so Adam/RMSProp moments
    # survive a restart.
    server_ckpt_dir="",
    server_ckpt_interval=30.0,
    resume=False,
    # Wire codec for every client<->server shard transfer (comm/codec.py:
    # none | bf16 | int8).  "" defers to $MPIT_PS_CODEC (default none).
    # When set explicitly the servers are PINNED to it, so a rank whose
    # environment disagrees fails its INIT loudly instead of training on
    # corrupt frames.
    codec="",
    # Fault tolerance (mpit_tpu.ft; 0 = off, the legacy wire).  Heartbeat
    # interval for workers, lease TTL for servers (expired => eviction),
    # per-op deadline for workers (enables retry + FT frame headers), and
    # supervise = restarts allowed per rank (the supervisor respawns dead
    # ranks with a bumped epoch; workers rejoin via INIT v3, servers
    # resume from their stamped shard snapshot — needs server_ckpt_dir).
    ft_heartbeat_s=0.0,
    ft_lease_ttl_s=0.0,
    ft_op_deadline_s=0.0,
    ft_max_retries=8,
    # Gradient-staleness telemetry (obs): frames carry the 24-byte
    # [epoch, seq, version] header so servers measure the basis gap per
    # applied grad (mpit_ps_grad_staleness).  Needs ft_op_deadline_s > 0
    # (rides the framed wire); silently off otherwise.
    ft_staleness=False,
    # Causal-timing telemetry (obs/clock, obs/causal; PROTOCOL.md §6.7):
    # frames carry a send stamp, acks/replies a [t_tx, t_recv, t_ack]
    # tail, and heartbeats are echoed — feeding the per-peer clock
    # offset estimator so `python -m mpit_tpu.obs analyze` can join and
    # decompose the gang's trace.  Needs ft_op_deadline_s > 0.
    ft_timing=False,
    # Pipelined streaming transfers (docs/PROTOCOL.md §12): GRAD /
    # PARAM / PARAM_PUSH bodies ship as ~this-many-byte chunk frames so
    # encode, wire and apply overlap on big shards.  Needs
    # ft_op_deadline_s > 0 (chunk retry/dedup ride the framed
    # machinery) and an element-wise server rule; off under shardctl.
    ft_chunk_bytes=0,
    supervise=0,
    # shardctl (mpit_tpu.shardctl): the LAST rank becomes the shard-map
    # controller (the rest split into servers/clients as usual), clients
    # address shards through a versioned map, and the controller
    # rebalances hot shards / fails over a dead server's shards from its
    # checkpoints.  Requires ft_op_deadline_s > 0 (re-routing rides the
    # retry machinery).  shardctl_ratio tunes the rebalance trigger;
    # shardctl_lease_ttl_s > 0 arms server leases at the controller
    # (expiry => shard failover; pair with server_ckpt_dir).
    shardctl=False,
    shardctl_ratio=3.0,
    shardctl_lease_ttl_s=0.0,
    # Serving tier (mpit_tpu.ps.serve; docs/PROTOCOL.md §8): the LAST
    # serve_readers ranks become READ-ONLY readers — they attach to the
    # servers with the lightweight read-only posture, pull the current
    # params serve_rounds times (pacing serve_interval_s apart), assert
    # the observed snapshot version is monotone, and stop.  Servers run
    # the admission budget (serve_budget_mb in-flight reply bytes;
    # serve_budget_reads optionally bounds the reply count) and answer
    # over-budget reads BUSY-with-retry-hint.  Requires
    # ft_op_deadline_s > 0 (BUSY recovery rides the retry machinery).
    serve_readers=0,
    serve_rounds=10,
    serve_interval_s=0.05,
    serve_budget_mb=64.0,
    serve_budget_reads=0,
    # Multi-cell serving fabric (mpit_tpu.cells; docs/PROTOCOL.md §11):
    # --cells N inserts N replica serving cells between the training
    # roles and the readers.  Cells SUBSCRIBE to their upstream server's
    # committed version stream (one diff stream each), serve the reader
    # traffic under the cell_max_lag staleness bound, and readers route
    # across the cells of each shard by consistent hashing, failing
    # over to ring siblings on cell death (zero RetryExhausted while a
    # sibling lives).  Requires serve_readers > 0 (someone to serve),
    # ft_op_deadline_s > 0 and ft_heartbeat_s > 0 (cell leases + head
    # echoes ride the beat channel), and N >= the server count (every
    # shard needs a replica).
    cells=0,
    cell_max_lag=4,
    # Cell subscription codec (ROADMAP item 3): the diff stream's XOR
    # deltas ride the *encoded* domain, so an int8 subscription is ~4x
    # cheaper per hop than fp32 — and bit-exact by the same induction
    # (the cell installs the upstream's encoded frame byte-for-byte;
    # readers decode exactly what a direct int8 read would).  Empty =
    # default the fleet to int8; --cell_codec none opts out (e.g. a
    # non-f32 dtype, which the quantizers refuse).  Fabric readers
    # negotiate the same codec — a cell serves its subscription codec
    # only (§11.1).
    cell_codec="",
    # Elastic gangs (mpit_tpu.ft.elastic; docs/PROTOCOL.md §9): --elastic
    # composes shardctl + the supervisor into dynamic membership.
    # elastic_spares reserves that many joiner-server rank slots beyond
    # --np (membership has a provisioned rank-space ceiling; spares
    # spawn only when the controller asks the supervisor through the
    # scale mailbox — or an operator hits the controller's /scale
    # route).  Servers install a SIGTERM preemption notice
    # (checkpoint-on-notice + a PREEMPT report; elastic_grace_s is the
    # window they announce), and the initial cut makes
    # elastic_shards_per_server shards per launch server so scale
    # events have units to move.  Implies shardctl; requires
    # supervise >= 1, ft_op_deadline_s > 0 and server_ckpt_dir; forces
    # the startup barrier off when spares > 0 (spare ranks are not
    # running at launch).
    elastic=False,
    elastic_spares=1,
    elastic_grace_s=5.0,
    elastic_shards_per_server=2,
    # Closed-loop autoscaling (mpit_tpu.shardctl.autoscale;
    # docs/OPERATIONS.md): --autoscale implies --elastic and attaches
    # an SLO-driven policy engine to the controller, which samples the
    # gang through every rank's statusd endpoint (requires
    # MPIT_OBS_HTTP — the same read path `mpit top` uses) and drives
    # the §9 scale verbs automatically; the operator /scale route keeps
    # precedence.  Targets: 0 disables a signal.  The policy's
    # hysteresis/cooldown/flap knobs take the AutoscaleConfig defaults
    # unless overridden here.
    autoscale=False,
    autoscale_p99_ms=0.0,
    autoscale_busy_ratio=0.0,
    autoscale_staleness=0.0,
    autoscale_sendq=0.0,
    autoscale_window_s=2.0,
    autoscale_cooldown_s=20.0,
    autoscale_flap_budget=3,
    autoscale_min_servers=1,
    autoscale_max_servers=0,  # 0 = every provisioned server slot
    # Hierarchical aggregation (mpit_tpu.agg; docs/PROTOCOL.md §13):
    # --agg off|prereduce|tree.  prereduce folds colocated client
    # groups on-device behind a representative; tree additionally
    # reduces representatives through a deterministic REDUCE tree so
    # the servers see ONE gradient per round for the whole gang.
    # agg_groups declares colocation ("4,5;6,7" — ranks sharing a
    # process/backend; empty = every client its own representative),
    # verified against the dplane fingerprint at start.  Requires
    # ft_op_deadline_s > 0 (REDUCE hops ride the framed retry/dedup
    # machinery); off under shardctl and --dplane (the exchange client
    # wraps the same seam).  agg_deadline_s is the straggler wall
    # deadline (§13.4); agg_chunk_bytes cuts the REDUCE hops (0 =
    # ft_chunk_bytes, then 1 MiB).
    agg="off",
    agg_groups="",
    agg_fanin=2,
    agg_tree_seed=0,
    agg_deadline_s=5.0,
    agg_chunk_bytes=0,
    # Flagship LM workload (mpit_tpu.lm; docs/WORKLOADS.md): --lm 1
    # swaps the MNIST trainer for the sharded transformer-LM loop.  The
    # shared optimizer knobs (--opt/--lr/--mom/--mva/--su/--batch/
    # --seed/--dtype) carry over; the lm_* knobs size the model and the
    # step loop.  Unless shardctl owns placement, every client AND
    # reader announces the same weighted aligned-cut layout
    # (mpit_tpu.lm.plan over the params+optimizer pytree) instead of
    # the equal split — lm_weights skews it ("3,1" = server 0 aims at
    # 3/4 of the vector), empty = balanced cut on parameter boundaries.
    lm=0,
    lm_d_model=64,
    lm_heads=4,
    lm_layers=2,
    lm_seq=128,
    lm_steps=200,
    lm_eval_every=50,
    lm_use_flash=-1,  # -1 auto (flash on TPU) | 0 jnp reference | 1 flash
    lm_weights="",
    # Device-resident data plane (mpit_tpu.dplane; docs/DEVICE.md):
    # servers hold shard + optimizer state as (mesh-sharded) HBM arrays
    # with donated jitted applies and publish an in-process device
    # exchange; workers route through an ExchangeClient that takes the
    # device path to same-backend servers and falls back to the wire
    # (codecs/retry/dedup intact) everywhere else — in the process-mode
    # gang every pair crosses a process boundary, so the win there is
    # the server-side slot (no per-apply reallocation, shared snapshot
    # caches); the np=1 path and in-process harnesses get the full
    # device exchange.
    dplane=0,
)


def parse_agg_groups(spec: str) -> "Tuple[Tuple[int, ...], ...]":
    """--agg_groups "4,5;6,7" -> ((4, 5), (6, 7)): semicolon-separated
    colocation groups of comma-separated client ranks (PROTOCOL.md
    §13.0).  Empty spec = no declared colocation (every client its own
    representative)."""
    return tuple(
        tuple(int(x) for x in part.split(",") if x.strip() != "")
        for part in spec.split(";") if part.strip())


def ft_from_cfg(cfg: Config):
    """FTConfig for one rank: env base (the supervisor's restart env —
    MPIT_FT_EPOCH/MPIT_FT_REJOIN — rides there) with the launch config's
    non-zero knobs layered on top."""
    from mpit_tpu.ft import FTConfig

    overrides = {}
    for ck, fk, cast in (
        ("ft_heartbeat_s", "heartbeat_s", float),
        ("ft_lease_ttl_s", "lease_ttl_s", float),
        ("ft_op_deadline_s", "op_deadline_s", float),
    ):
        value = cast(cfg.get(ck, 0) or 0)
        if value:
            overrides[fk] = value
    if overrides.get("op_deadline_s"):
        overrides["max_retries"] = int(cfg.get("ft_max_retries", 8))
    if overrides.get("lease_ttl_s") or int(cfg.get("supervise", 0)):
        overrides["rejoin"] = True
    if bool(cfg.get("ft_staleness", False)):
        overrides["staleness"] = True
    if bool(cfg.get("ft_timing", False)):
        overrides["timing"] = True
    chunk = int(cfg.get("ft_chunk_bytes", 0) or 0)
    if chunk:
        overrides["chunk_bytes"] = chunk
    return FTConfig.from_env(**overrides)


def assign_roles(
    size: int, master_freq: int = 2, tester: str = "none"
) -> Tuple[List[int], List[int], Optional[int]]:
    """Returns (server_ranks, client_ranks, tester_rank)."""
    ranks = list(range(size))
    tester_rank: Optional[int] = None
    if tester == "first":
        tester_rank = 0
        ranks = ranks[1:]
    elif tester == "last":
        tester_rank = size - 1
        ranks = ranks[:-1]
    sranks = [r for r in ranks if r % master_freq == 0]
    cranks = [r for r in ranks if r % master_freq != 0]
    if not sranks or not cranks:
        raise ValueError(
            f"role split produced {len(sranks)} servers / {len(cranks)} "
            f"clients from size={size}, master_freq={master_freq}"
        )
    return sranks, cranks, tester_rank


def _dplane_cfg(cfg: Config):
    """PlaneConfig for --dplane servers: mesh over the default devices
    when more than one exists, single-device HBM placement otherwise."""
    from mpit_tpu.dplane import PlaneConfig

    return PlaneConfig.auto(namespace=str(cfg.get("namespace", "") or ""))


def server_rule_for(cfg: Config) -> Any:
    """The server-side shard rule matching the client optimizer
    (reference BiCNN/pserver.lua:123-197 dispatch)."""
    name = cfg.opt
    if name in ("rmsprop", "adam", "adamax", "adagrad", "adadelta"):
        return rules_mod.make(name, lr=cfg.lr)
    return rules_mod.make("add")  # downpour/easgd/eamsgd ship pre-scaled deltas


def serve_cfg_for(cfg: Config):
    """The serving tier's admission budget from the launch config."""
    from mpit_tpu.ps import ServeConfig

    return ServeConfig.from_env(
        budget_bytes=int(float(cfg.get("serve_budget_mb", 64.0)) * (1 << 20)),
        budget_reads=int(cfg.get("serve_budget_reads", 0) or 0),
    )


def lm_trainer_cfg(cfg: Config) -> Config:
    """The :data:`mpit_tpu.lm.trainer.LM_DEFAULTS`-shaped config for one
    launch config: shared optimizer/loop knobs carried over verbatim,
    lm_* knobs mapped onto the trainer's names."""
    return Config(
        d_model=int(cfg.get("lm_d_model", 64)),
        n_heads=int(cfg.get("lm_heads", 4)),
        n_layers=int(cfg.get("lm_layers", 2)),
        seq_len=int(cfg.get("lm_seq", 128)),
        steps=int(cfg.get("lm_steps", 200)),
        eval_every=int(cfg.get("lm_eval_every", 50)),
        use_flash=int(cfg.get("lm_use_flash", -1)),
        opt=cfg.opt, lr=cfg.lr, lrd=cfg.lrd, lrp=cfg.lrp, mom=cfg.mom,
        mommax=cfg.mommax, momdecay=cfg.momdecay, l2wd=cfg.l2wd,
        mva=cfg.mva, su=cfg.su, batch=cfg.batch, seed=cfg.seed,
        dtype=cfg.dtype, profile_dir=cfg.get("profile_dir", ""),
    )


def lm_layout(cfg: Config, n_servers: int):
    """The gang's static weighted aligned-cut layout (one Shard per
    server) under --lm: the deterministic cut every client and reader
    must announce identically.  ``lm_weights`` ("3,1") skews the
    targets; empty keeps balanced targets (still boundary-aligned, so
    it differs from the raw equal split)."""
    from mpit_tpu.lm import build, plan

    tcfg = lm_trainer_cfg(cfg)
    # Param *shapes* don't depend on the attention implementation, so
    # layout derivation never touches the accelerator kernels.
    model = build(d_model=tcfg.d_model, n_heads=tcfg.n_heads,
                  n_layers=tcfg.n_layers, seq_len=tcfg.seq_len,
                  seed=tcfg.seed, use_flash=False)
    params = model.flat.unravel(model.flat.w0)
    spec = str(cfg.get("lm_weights", "") or "")
    weights = ([float(x) for x in spec.split(",") if x.strip() != ""]
               if spec else None)
    if weights is not None and len(weights) != n_servers:
        raise ValueError(
            f"--lm_weights names {len(weights)} servers but the role "
            f"split made {n_servers}")
    rule = cfg.opt if cfg.opt in rules_mod.names() else "add"
    return plan(params, n_servers, rule=rule, server_weights=weights).layout


def _serve_vec_len(cfg: Config, rank: int) -> int:
    """The flat parameter-vector length a reader must mirror — derived
    exactly the way the trainer derives it (same model ctor + flatten),
    so the reader's shard announcement matches the writers' cut."""
    import jax
    import jax.numpy as jnp

    from mpit_tpu.data.mnist import load_mnist
    from mpit_tpu.models import MnistCNN, flatten_module
    from mpit_tpu.train.trainer import MODELS

    full = TRAINER_DEFAULTS.merged(cfg.to_dict())
    if int(cfg.get("lm", 0)):
        from mpit_tpu.lm import build

        tcfg = lm_trainer_cfg(cfg)
        model = build(d_model=tcfg.d_model, n_heads=tcfg.n_heads,
                      n_layers=tcfg.n_layers, seq_len=tcfg.seq_len,
                      seed=tcfg.seed, use_flash=False)
        return int(model.flat.size)
    x_train = load_mnist(side=full.side)[0][0]
    if full.model == "cnn":
        module = MnistCNN(num_classes=10, side=full.side)
    else:
        module = MODELS[full.model](num_classes=10)
    rng = jax.random.PRNGKey(full.seed + rank)
    sample = jnp.asarray(x_train[:2], jnp.dtype(full.dtype))
    return int(flatten_module(module, rng, sample).w0.size)


def cell_codec_for(cfg: Config) -> str:
    """The cell fleet's subscription codec: ``--cell_codec`` when set,
    else int8 — the XOR diff stream is ~4x cheaper in the int8 domain
    and bit-exact by construction (§11.2), so compressed subscriptions
    are the default and ``--cell_codec none`` is the opt-out.  Falls
    back to 'none' for non-f32 dtypes (the quantizers refuse them)."""
    from mpit_tpu.comm import codec as codec_mod

    name = str(cfg.get("cell_codec", "") or "")
    if not name:
        dtype = str(cfg.get("dtype", "float32"))
        name = "int8" if dtype == "float32" else "none"
    codec_mod.get(name)  # unknown names fail at launch, not mid-gang
    return name


def cell_map_for(sranks: List[int], cell_ranks: List[int]) -> Dict[int, List[int]]:
    """Round-robin assignment of replica cells to server slots: cell i
    mirrors sranks[i % S], so every shard gets ceil(N/S) replicas and
    siblings exist whenever N >= 2S (§11.5)."""
    out: Dict[int, List[int]] = {s: [] for s in sranks}
    for i, c in enumerate(cell_ranks):
        out[sranks[i % len(sranks)]].append(c)
    return out


def run_cell(rank: int, sranks: List[int], cell_ranks: List[int],
             reader_ranks: List[int], cfg: Config,
             transport: Any) -> Dict[str, Any]:
    """One replica serving cell (§11): subscribe to the assigned
    upstream server's version stream, serve the fabric's readers under
    the staleness bound, stop when every reader is terminal."""
    from mpit_tpu.cells.cell import ServingCell
    from mpit_tpu.shardctl import shardmap as _shardmap

    log = get_logger("cell", rank)
    cmap = cell_map_for(sranks, cell_ranks)
    upstream = next(s for s, cs in cmap.items() if rank in cs)
    vec_len = _serve_vec_len(cfg, rank)
    smap = _shardmap.ShardMap.initial(vec_len, sranks)
    shard = dict(zip(sranks, (e.shard for e in smap.entries)))[upstream]
    cell = ServingCell(
        rank, upstream, transport, reader_ranks,
        offset=shard.offset, size=shard.size,
        dtype=cfg.get("dtype", "float32"),
        codec=cell_codec_for(cfg),
        max_lag=int(cfg.get("cell_max_lag", 4)),
        ft=ft_from_cfg(cfg),
        serve=serve_cfg_for(cfg),
    )
    log.info("cell for upstream %d, shard (%d,%d), readers %s",
             upstream, shard.offset, shard.size, reader_ranks)
    cell.start()
    return {
        "role": "cell",
        "upstream": upstream,
        "version": cell.version,
        "head": cell.head,
        "params_served": cell.params_served,
        "busy_replies": cell.busy_replies,
        "diffs_installed": cell.diffs_installed,
        "resyncs": cell.resyncs,
        "lag_sheds": cell.lag_sheds,
    }


def run_reader(rank: int, sranks: List[int], cfg: Config,
               transport: Any,
               cell_ranks: Optional[List[int]] = None) -> Dict[str, Any]:
    """One READ-ONLY reader rank (serve mode): attach, pull the current
    params ``serve_rounds`` times at ``serve_interval_s`` pacing, check
    version monotonicity, stop.  With a cell fabric the reads route
    across the replica cells instead of the training servers (§11.5)."""
    import numpy as np

    from mpit_tpu.ps import ReaderClient

    log = get_logger("serve", rank)
    rc = ReaderClient(
        rank, sranks, transport,
        # Fabric-routed readers negotiate the cells' subscription codec
        # (a cell serves its subscription codec only, §11.1); direct
        # readers keep the gang codec.
        codec=(cell_codec_for(cfg) if cell_ranks
               else str(cfg.get("codec", "") or "") or None),
        ft=ft_from_cfg(cfg),
        cells=(cell_map_for(sranks, cell_ranks) if cell_ranks else None),
        # --lm readers must announce the identical weighted cut the
        # writers announced (servers reject a disagreeing attach).
        layout=(lm_layout(cfg, len(sranks)) if int(cfg.get("lm", 0))
                else None),
    )
    mirror = np.zeros(_serve_vec_len(cfg, rank),
                      np.dtype(str(cfg.get("dtype", "float32"))))
    rc.start(mirror)
    rounds = int(cfg.get("serve_rounds", 10))
    interval = float(cfg.get("serve_interval_s", 0.05))
    for _ in range(rounds):
        rc.read_params()
        if interval > 0:
            time.sleep(interval)
    rc.stop()
    log.info("reader done: %d reads, monotone=%s, busy honored %d",
             rc.reads_done, rc.monotone, rc.busy_honored)
    return {
        "role": "reader",
        "reads": rc.reads_done,
        "monotone": bool(rc.monotone),
        "busy_honored": rc.busy_honored,
        "retries": rc.retries,
        "versions": {str(k): v for k, v in rc.versions.items()},
        "read_versions": {str(k): v for k, v in rc.read_versions.items()},
        "lags": {str(k): v for k, v in rc.lags.items()},
        "failovers": rc.failovers,
    }


def _autoscaler_for(cfg: Config, ctl, size: int):
    """The controller rank's Autoscaler under --autoscale: SLO targets
    from the launch knobs, telemetry pooled over every rank's statusd
    endpoint (HttpSampler — launch_processes validated MPIT_OBS_HTTP)."""
    from mpit_tpu.obs.statusd import base_port
    from mpit_tpu.shardctl.autoscale import (
        AutoscaleConfig,
        Autoscaler,
        HttpSampler,
        SLOConfig,
    )

    slo = SLOConfig(
        p99_ms=float(cfg.get("autoscale_p99_ms", 0) or 0),
        busy_ratio=float(cfg.get("autoscale_busy_ratio", 0) or 0),
        staleness=float(cfg.get("autoscale_staleness", 0) or 0),
        send_queue=float(cfg.get("autoscale_sendq", 0) or 0),
    )
    max_servers = int(cfg.get("autoscale_max_servers", 0) or 0)
    if max_servers <= 0:
        max_servers = len(ctl.sranks) + len(ctl.spares)
    acfg = AutoscaleConfig(
        slo=slo,
        window_s=float(cfg.get("autoscale_window_s", 2.0)),
        cooldown_s=float(cfg.get("autoscale_cooldown_s", 20.0)),
        flap_budget=int(cfg.get("autoscale_flap_budget", 3)),
        min_servers=int(cfg.get("autoscale_min_servers", 1)),
        max_servers=max_servers,
    )
    sampler = HttpSampler(base_port(), nranks=size)
    return Autoscaler(ctl, acfg, sampler=sampler)


def _maybe_preemption(cfg: Config):
    """A server's SIGTERM preemption notice under --elastic (installed
    in the child's main thread — run_rank runs there); None otherwise.
    The handler only sets a flag (mtlint MT-P204); checkpoint-on-notice
    and the PREEMPT report run from the serving loop (§9.3)."""
    if not bool(cfg.get("elastic", False)):
        return None
    from mpit_tpu.ft.elastic import PreemptionNotice

    return PreemptionNotice.from_env(
        default_grace_s=float(cfg.get("elastic_grace_s", 5.0))).install()


def run_joiner_server(rank: int, cranks: List[int], cfg: Config,
                      transport: Any, ctl_rank: Optional[int]
                      ) -> Dict[str, Any]:
    """One controller-spawned joiner server (--elastic spare slot)."""
    log = get_logger("launch", rank)
    ckpt_dir = str(cfg.get("server_ckpt_dir", "") or "")
    server = ParamServer(
        rank, cranks, transport, rule=server_rule_for(cfg),
        dtype=cfg.get("dtype", "float32"),
        ckpt_dir=ckpt_dir or None,
        ckpt_interval=float(cfg.get("server_ckpt_interval", 30.0)),
        codec=str(cfg.get("codec", "") or "") or None,
        ft=ft_from_cfg(cfg),
        controller_rank=ctl_rank,
        shardctl=True,
        preempt=_maybe_preemption(cfg),
    )
    log.info("joiner server for clients %s (controller %s)", cranks, ctl_rank)
    server.start()
    return {
        "role": "server",
        "joiner": True,
        "retired": server.retired,
        "grads_applied": server.grads_applied,
        "params_served": server.params_served,
        "ckpts_written": server.ckpts_written,
    }


def run_rank(
    rank: int,
    size: int,
    cfg: Config,
    transport: Any,
    data: Any = None,
) -> Dict[str, Any]:
    """Run one rank's role to completion; returns its result dict."""
    log = get_logger("launch", rank)
    if size == 1:
        if bool(cfg.get("resume", False)):
            # Server-shard resume needs servers; silently restarting from
            # scratch would look like a successful resume.
            raise ValueError(
                "--resume restores parameter-server shards and needs "
                "--np > 1 (single-process runs have no servers)"
            )
        if int(cfg.get("lm", 0)):
            from mpit_tpu.lm import LmTrainer

            return {"role": "local",
                    **LmTrainer(lm_trainer_cfg(cfg), rank=rank).run()}
        trainer = MnistTrainer(cfg, pclient=None, data=data, rank=rank)
        return {"role": "local", **trainer.run()}

    elastic_on = bool(cfg.get("elastic", False))
    sc_on = bool(cfg.get("shardctl", False)) or elastic_on
    lm_on = int(cfg.get("lm", 0))
    if lm_on:
        if str(cfg.get("tester", "none")) != "none":
            raise ValueError("--lm and a tester rank are mutually "
                             "exclusive (the tester is MNIST-only)")
        if int(cfg.get("cells", 0) or 0):
            raise ValueError("--lm and --cells are not composed yet: the "
                             "cell fabric derives the equal split, not "
                             "the LM plan's weighted cut")
    # Under --elastic the transport spans the provisioned ceiling
    # (np0 + spares); roles split over the initial membership np0 and
    # ranks beyond it are joiner-server slots the controller may spawn.
    np0 = int(cfg.get("elastic_np0", 0) or 0) if elastic_on else size
    if elastic_on and not np0:
        np0 = size
    ctl_rank: Optional[int] = None
    role_size = size
    n_readers = int(cfg.get("serve_readers", 0) or 0)
    n_cells = int(cfg.get("cells", 0) or 0)
    reader_ranks: List[int] = []
    cell_ranks: List[int] = []
    if n_cells and not n_readers:
        raise ValueError("--cells without --serve_readers: a cell fabric "
                         "exists to serve readers")
    if n_readers:
        if sc_on:
            raise ValueError("serve_readers and shardctl are mutually "
                             "exclusive for now")
        if str(cfg.get("tester", "none")) != "none":
            raise ValueError("serve_readers and a tester rank are mutually "
                             "exclusive for now (both claim edge ranks)")
        if float(cfg.get("ft_op_deadline_s", 0) or 0) <= 0:
            raise ValueError("serve_readers needs --ft_op_deadline_s > 0: "
                             "BUSY recovery rides the FT retry machinery")
        if n_cells and float(cfg.get("ft_heartbeat_s", 0) or 0) <= 0:
            raise ValueError("--cells needs --ft_heartbeat_s > 0: cell "
                             "leases and the head echoes ride the beat "
                             "channel (§11.3)")
        if size - n_readers - n_cells < 2:
            raise ValueError(
                f"serve_readers={n_readers} + cells={n_cells} leave "
                f"{size - n_readers - n_cells} role ranks; need >= 1 "
                "server + >= 1 worker")
        role_size = size - n_readers - n_cells
        cell_ranks = list(range(role_size, role_size + n_cells))
        reader_ranks = list(range(role_size + n_cells, size))
    if sc_on:
        if str(cfg.get("tester", "none")) != "none":
            raise ValueError("shardctl and a tester rank are mutually "
                             "exclusive for now (both claim an edge rank)")
        if np0 < 3:
            raise ValueError("shardctl needs np >= 3 "
                             "(>=1 server + >=1 worker + the controller)")
        if float(cfg.get("ft_op_deadline_s", 0) or 0) <= 0:
            raise ValueError("shardctl needs --ft_op_deadline_s > 0: map "
                             "re-routing rides the FT retry machinery")
        ctl_rank = np0 - 1
        role_size = np0 - 1
    sranks, cranks, tester_rank = assign_roles(
        role_size, cfg.get("master_freq", 2), cfg.get("tester", "none")
    )
    single_mode = str(cfg.opt).endswith("-single")
    if cell_ranks and len(cell_ranks) < len(sranks):
        raise ValueError(
            f"cells={n_cells} < {len(sranks)} servers: every shard "
            "needs at least one replica cell")
    if rank in reader_ranks:
        return run_reader(rank, sranks, cfg, transport,
                          cell_ranks=cell_ranks or None)
    if rank in cell_ranks:
        return run_cell(rank, sranks, cell_ranks, reader_ranks, cfg,
                        transport)
    if elastic_on and rank >= np0:
        # A spare slot the controller asked the supervisor to spawn:
        # a joiner server — no INIT rendezvous, shards arrive by
        # ACQUIRE, clients greet lazily (docs/PROTOCOL.md §9.1).
        return run_joiner_server(rank, cranks, cfg, transport, ctl_rank)
    if sc_on and rank == ctl_rank:
        from mpit_tpu.shardctl import RebalancePolicy, ShardController

        spawner = None
        spares: List[int] = []
        if elastic_on:
            from mpit_tpu.ft.elastic import ElasticDirectory

            spares = list(range(np0, size))
            mailbox = ElasticDirectory.from_env()
            if mailbox is not None:
                def spawner(r):
                    # Stamp the spawn request with the live set so the
                    # joiner's TCP rendezvous dials only reachable
                    # peers (train/gang.py child_transport).
                    live = sorted(
                        set(ctl._live_servers())
                        | {c for c in ctl.cranks if c not in ctl._stopped}
                        | {ctl.rank})
                    mailbox.request_spawn(r, {
                        "MPIT_ELASTIC_DIAL":
                            ",".join(str(x) for x in live if x < r)})

                retire_mark = mailbox.mark_retired
            else:
                retire_mark = None
        ctl = ShardController(
            rank, transport, sranks, cranks,
            policy=RebalancePolicy(ratio=float(cfg.get("shardctl_ratio", 3.0))),
            lease_ttl_s=float(cfg.get("shardctl_lease_ttl_s", 0) or 0),
            spawner=spawner,
            spare_ranks=spares,
        )
        if elastic_on and retire_mark is not None:
            # The supervisor must learn a retirement before the rank's
            # exit reaches its budget check — wrap scale_down to mark
            # the mailbox first.
            _scale_down = ctl.scale_down

            def scale_down_marked(r):
                retire_mark(r)
                return _scale_down(r)

            ctl.scale_down = scale_down_marked
        if bool(cfg.get("autoscale", False)):
            ctl.attach_autoscaler(_autoscaler_for(cfg, ctl, size))
        ctl.serve()
        if ctl.autoscaler is not None:
            return {
                "role": "controller",
                "map_version": getattr(ctl.smap, "version", None),
                "rebalances": int(ctl._m_rebal.value),
                "failovers": int(ctl._m_fail.value),
                "membership_epoch": ctl.membership_epoch,
                "elastic_events": {
                    "up": int(ctl._m_up.value),
                    "down": int(ctl._m_down.value),
                    "preempt": int(ctl._m_pre.value),
                },
                "autoscale": ctl.autoscaler.status_section(),
            }
        return {
            "role": "controller",
            "map_version": getattr(ctl.smap, "version", None),
            "rebalances": int(ctl._m_rebal.value),
            "failovers": int(ctl._m_fail.value),
            "membership_epoch": ctl.membership_epoch,
            "elastic_events": {
                "up": int(ctl._m_up.value),
                "down": int(ctl._m_down.value),
                "preempt": int(ctl._m_pre.value),
            },
        }
    if rank == tester_rank:
        from mpit_tpu.train.tester import run_tester

        return {"role": "tester", **run_tester(rank, sranks, cfg, transport, data)}
    import os as _os

    rejoining = _os.environ.get("MPIT_FT_REJOIN", "0") not in ("0", "")
    ft = ft_from_cfg(cfg)
    if elastic_on and rank in sranks and rejoining:
        # A supervisor-restarted server in an elastic gang rejoins as a
        # joiner: its shards already failed over to survivors (or are
        # about to), and shard-oriented checkpoints have no
        # server<rank>_latest alias to resume from.  The controller
        # rebalances onto it once its beats arm (§9.1).
        return run_joiner_server(rank, cranks, cfg, transport, ctl_rank)
    if rank in sranks:
        # The tester counts as a (pull-only) client: it announces shards and
        # participates in the stop protocol like any worker.
        all_clients = cranks + ([tester_rank] if tester_rank is not None else [])
        ckpt_dir = str(cfg.get("server_ckpt_dir", "") or "")
        server = ParamServer(
            rank, all_clients, transport, rule=server_rule_for(cfg),
            single_mode=single_mode, dtype=cfg.get("dtype", "float32"),
            ckpt_dir=ckpt_dir or None,
            ckpt_interval=float(cfg.get("server_ckpt_interval", 30.0)),
            codec=str(cfg.get("codec", "") or "") or None,
            ft=ft,
            controller_rank=ctl_rank,
            # With a cell fabric the readers attach to the CELLS, not
            # here — the server's serving surface is one diff stream
            # per assigned cell (§11.2).
            reader_ranks=(None if cell_ranks else (reader_ranks or None)),
            cell_ranks=(cell_map_for(sranks, cell_ranks)[rank]
                        if cell_ranks else None),
            serve=serve_cfg_for(cfg) if (reader_ranks and not cell_ranks)
            else None,
            preempt=_maybe_preemption(cfg),
            dplane=(_dplane_cfg(cfg) if int(cfg.get("dplane", 0)) else None),
        )
        if bool(cfg.get("resume", False)):
            import pathlib

            path = pathlib.Path(ckpt_dir) / f"server{rank}_latest.npz"
            if not ckpt_dir or not path.exists():
                raise FileNotFoundError(
                    f"--resume needs --server_ckpt_dir with a "
                    f"server{rank}_latest.npz (looked at {path})"
                )
            server.restore_state(path)
            log.info("restored shard from %s", path)
        log.info("server for clients %s", cranks)
        server.start()
        return {
            "role": "server",
            "grads_applied": server.grads_applied,
            "params_served": server.params_served,
            "ckpts_written": server.ckpts_written,
        }
    # On resume the restored servers are authoritative for params — no
    # client re-seeds (ps/server.py restore_state contract).  Same for a
    # supervisor-restarted worker rejoining mid-run (MPIT_FT_REJOIN): the
    # live servers hold the current center, and a re-seed would rewind it.
    pclient = ParamClient(
        rank, sranks, transport,
        seed_servers=(rank == cranks[0])
        and not bool(cfg.get("resume", False)) and not rejoining,
        codec=str(cfg.get("codec", "") or "") or None,
        ft=ft,
        shardctl=sc_on,
        controller_rank=ctl_rank,
        sc_shards_per_server=(
            int(cfg.get("elastic_shards_per_server", 2) or 1)
            if elastic_on else 1),
        # --lm: the weighted aligned-cut layout replaces the equal
        # split on the static path (shardctl owns placement otherwise).
        layout=(lm_layout(cfg, len(sranks)) if lm_on and not sc_on
                else None),
    )
    if int(cfg.get("dplane", 0)):
        from mpit_tpu.dplane import ExchangeClient

        pclient = ExchangeClient(pclient)
    agg_mode = str(cfg.get("agg", "off") or "off")
    if agg_mode != "off":
        from mpit_tpu.agg import AggClient, AggConfig

        if sc_on:
            raise ValueError("--agg composes with the static shard map "
                             "only (run without --shardctl/--elastic)")
        if int(cfg.get("dplane", 0)):
            raise ValueError("--agg and --dplane both wrap the client "
                             "data path; pick one")
        if float(cfg.get("ft_op_deadline_s", 0) or 0) <= 0:
            raise ValueError("--agg needs --ft_op_deadline_s > 0: REDUCE "
                             "hops ride the framed retry machinery")
        groups = parse_agg_groups(str(cfg.get("agg_groups", "") or ""))
        pclient = AggClient(
            pclient, cranks,
            AggConfig(mode=agg_mode, groups=groups,
                      fanin=int(cfg.get("agg_fanin", 2)),
                      tree_seed=int(cfg.get("agg_tree_seed", 0)),
                      deadline_s=float(cfg.get("agg_deadline_s", 5.0)),
                      chunk_bytes=int(cfg.get("agg_chunk_bytes", 0))),
            namespace=str(cfg.get("namespace", "") or ""))
    if lm_on:
        from mpit_tpu.lm import LmTrainer

        trainer = LmTrainer(lm_trainer_cfg(cfg), pclient=pclient, rank=rank)
    else:
        trainer = MnistTrainer(cfg, pclient=pclient, data=data, rank=rank)
    log.info("worker with servers %s", sranks)
    return {"role": "worker", **trainer.run()}


# -- process-mode launcher (the mpirun analog) -------------------------------


def expected_role(rank: int, size: int, cfg: Config) -> str:
    """The role this rank will run, derived the same way run_rank does —
    for labeling introspection endpoints/flight dumps *before* the role
    objects exist.  Best-effort: '' when the split is invalid (run_rank
    raises the real error)."""
    if size == 1:
        return "local"
    elastic_on = bool(cfg.get("elastic", False))
    sc_on = bool(cfg.get("shardctl", False)) or elastic_on
    np0 = (int(cfg.get("elastic_np0", 0) or 0) or size) if elastic_on \
        else size
    if elastic_on and rank >= np0:
        return "server"  # spare joiner slot
    if sc_on and rank == np0 - 1:
        return "controller"
    n_readers = int(cfg.get("serve_readers", 0) or 0)
    n_cells = int(cfg.get("cells", 0) or 0)
    if n_readers and rank >= size - n_readers:
        return "reader"
    if n_cells and rank >= size - n_readers - n_cells:
        return "cell"
    try:
        sranks, _cranks, tester_rank = assign_roles(
            np0 - 1 if sc_on else size - n_readers - n_cells,
            int(cfg.get("master_freq", 2)),
            str(cfg.get("tester", "none")))
    except ValueError:
        return ""
    if rank == tester_rank:
        return "tester"
    return "server" if rank in sranks else "worker"


def _child_main() -> None:
    from mpit_tpu.train.gang import child_env, child_transport, write_result

    rank, size, cfg = child_env()
    # Live introspection (obs/statusd; no-op unless MPIT_OBS_HTTP is
    # set): serve /metrics, /status and /trace on base_port + rank for
    # the whole life of this rank.  Flight dumps inherit the identity.
    from mpit_tpu.obs import get_flight, maybe_start_statusd

    role = expected_role(rank, size, cfg)
    maybe_start_statusd(rank, role=role)
    get_flight().set_identity(rank=rank, role=role)
    transport = child_transport(cfg, rank, size)
    result = run_rank(rank, size, cfg, transport)
    transport.close()
    # Per-rank Chrome-trace part (MPIT_OBS_TRACE; no-op when unset) —
    # the gang parent merges the parts into one timeline at exit.
    from mpit_tpu.obs import maybe_write_rank_trace

    maybe_write_rank_trace(rank, role=str(result.get("role", "")))
    import jax

    result.setdefault("platform", jax.default_backend())
    write_result(result)


def device_env_overrides(cfg: Config, size: int) -> Dict[int, Dict[str, str]]:
    """Per-rank JAX_PLATFORMS assignment from cfg.device_policy."""
    policy = cfg.get("device_policy", "inherit")
    if policy == "inherit":
        return {}
    if policy == "cpu":
        return {r: {"JAX_PLATFORMS": "cpu"} for r in range(size)}
    if policy == "workers_accel":
        # Single-accelerator hosts: exactly ONE rank may own the chip
        # (libtpu holds an exclusive lock) — the tester if present, else
        # the first client; every other rank is forced to CPU.  Multi-chip
        # hosts should pass per-rank visible-device env via launch_gang's
        # env_overrides instead.  Under shardctl the last rank is the
        # controller (a pure host role, never the accelerator owner);
        # under --elastic the split runs over the initial membership
        # (spare joiner slots are host roles).
        role_size = int(cfg.get("elastic_np0", 0) or 0) or size
        role_size = role_size - 1 if (bool(cfg.get("shardctl", False))
                                      or bool(cfg.get("elastic", False))) \
            else role_size
        # readers and replica cells are host roles
        role_size -= int(cfg.get("serve_readers", 0) or 0)
        role_size -= int(cfg.get("cells", 0) or 0)
        sranks, cranks, tester = assign_roles(
            role_size, int(cfg.get("master_freq", 2)),
            str(cfg.get("tester", "none"))
        )
        accel_rank = tester if tester is not None else cranks[0]
        return {
            r: {"JAX_PLATFORMS": "cpu"} for r in range(size) if r != accel_rank
        }
    raise ValueError(
        f"device_policy must be inherit|cpu|workers_accel, got {policy!r}"
    )


def launch_processes(cfg: Config, timeout: float = 3600.0) -> Dict[int, Dict[str, Any]]:
    # Fail fast in the parent: a bad optimizer name discovered only inside a
    # worker child would strand the server children in their stop protocol.
    if int(cfg.get("lm", 0)):
        from mpit_tpu.lm import LmTrainer

        if cfg.opt not in LmTrainer.KNOWN_OPTS:
            raise ValueError(
                f"unknown LM optimizer {cfg.opt!r}; have "
                f"{LmTrainer.KNOWN_OPTS}"
            )
    elif cfg.opt not in MnistTrainer.KNOWN_OPTS:
        raise ValueError(
            f"unknown optimizer {cfg.opt!r}; have {MnistTrainer.KNOWN_OPTS}"
        )
    restarts = int(cfg.get("supervise", 0))
    if bool(cfg.get("autoscale", False)):
        # --autoscale = --elastic + the closed loop on the controller.
        # The loop's telemetry rides the statusd endpoints, so the gang
        # must be serving them; failing here beats a controller that
        # silently samples nothing and never scales.
        from mpit_tpu.obs.statusd import base_port as _obs_base_port

        if _obs_base_port() is None:
            raise ValueError(
                "--autoscale needs MPIT_OBS_HTTP=<base_port>: the "
                "autoscaler samples the gang through the statusd "
                "endpoints (the same read path `mpit top` uses)")
        if not any(float(cfg.get(k, 0) or 0) > 0 for k in
                   ("autoscale_p99_ms", "autoscale_busy_ratio",
                    "autoscale_staleness", "autoscale_sendq")):
            raise ValueError(
                "--autoscale needs at least one SLO target "
                "(--autoscale_p99_ms / _busy_ratio / _staleness / "
                "_sendq)")
        cfg = cfg.merged(elastic=True)
    if bool(cfg.get("elastic", False)):
        # --elastic (docs/PROTOCOL.md §9): shardctl + supervisor + the
        # scale mailbox, over a provisioned rank-space ceiling of
        # np + elastic_spares.  Spare slots spawn only on controller
        # request; membership changes never restart the gang.
        import os
        import tempfile as _tempfile

        from mpit_tpu.ft.elastic import ENV_DIR, ENV_GRACE_S, ElasticDirectory
        from mpit_tpu.ft.supervisor import RestartPolicy, supervise_gang

        if restarts <= 0:
            raise ValueError("--elastic needs --supervise >= 1: the "
                             "supervisor is what spawns and retires ranks")
        if not str(cfg.get("server_ckpt_dir", "") or ""):
            raise ValueError("--elastic needs --server_ckpt_dir: "
                             "checkpoint-on-notice and shard failover "
                             "write there")
        if float(cfg.get("ft_op_deadline_s", 0) or 0) <= 0:
            raise ValueError("--elastic needs --ft_op_deadline_s > 0: "
                             "membership changes ride the retry machinery")
        np0 = int(cfg.np)
        spares = max(int(cfg.get("elastic_spares", 1) or 0), 0)
        total = np0 + spares
        cfg = cfg.merged(np=total, elastic_np0=np0, shardctl=True)
        if spares > 0:
            cfg = cfg.merged(gang_barrier=False)
        mailbox = ElasticDirectory(
            _tempfile.mkdtemp(prefix="mpit_elastic_"))
        env_overrides = device_env_overrides(cfg, total)
        for r in range(total):
            env_overrides.setdefault(r, {})
            env_overrides[r][ENV_DIR] = str(mailbox.root)
            env_overrides[r][ENV_GRACE_S] = str(
                float(cfg.get("elastic_grace_s", 5.0)))
            if str(cfg.get("transport", "shm")) == "tcp":
                # Spare slots join (and rejoiners re-join) through the
                # event loop's persistent accept service — every rank
                # must agree on reconnect mode (it is part of the mesh
                # handshake digest).
                env_overrides[r].setdefault(
                    "MPIT_TCP_RECONNECT_S",
                    os.environ.get("MPIT_TCP_RECONNECT_S", "60"))
        sranks, _cranks, _tester = assign_roles(
            np0 - 1, int(cfg.get("master_freq", 2)), "none")
        return supervise_gang(
            "mpit_tpu.train.launch", cfg, timeout,
            policy=RestartPolicy(max_restarts=restarts),
            env_overrides=env_overrides,
            server_ranks=sranks + list(range(np0, total)),
            initial_ranks=range(np0),
            elastic_dir=mailbox,
        )
    if restarts > 0:
        from mpit_tpu.ft.supervisor import RestartPolicy, supervise_gang

        sranks, _cranks, _tester = assign_roles(
            int(cfg.np), int(cfg.get("master_freq", 2)),
            str(cfg.get("tester", "none")),
        )
        return supervise_gang(
            "mpit_tpu.train.launch", cfg, timeout,
            policy=RestartPolicy(max_restarts=restarts),
            env_overrides=device_env_overrides(cfg, int(cfg.np)),
            server_ranks=sranks,
        )
    from mpit_tpu.train.gang import launch_gang

    return launch_gang(
        "mpit_tpu.train.launch", cfg, timeout,
        env_overrides=device_env_overrides(cfg, int(cfg.np)),
    )


def main(argv: Optional[List[str]] = None) -> None:
    argv = list(sys.argv[1:] if argv is None else argv)
    if "--child" in argv:
        _child_main()
        return
    # Honor JAX_PLATFORMS for the in-process np=1 path (gang children
    # already do via train.gang).
    from mpit_tpu.utils.platform import honor_jax_platforms

    honor_jax_platforms()
    cfg = LAUNCH_DEFAULTS.parse_args(argv)
    t0 = time.monotonic()
    if int(cfg.np) == 1:
        from mpit_tpu.obs import maybe_start_statusd

        maybe_start_statusd(0, role="local")
        result = run_rank(0, 1, cfg, transport=None)
        from mpit_tpu.obs import maybe_merge_rank_traces, maybe_write_rank_trace

        maybe_write_rank_trace(0, role=str(result.get("role", "")))
        maybe_merge_rank_traces()
        print(json.dumps({"rank0": _summarize(result)}, indent=2))
    else:
        results = launch_processes(cfg)
        print(
            json.dumps(
                {str(r): _summarize(res) for r, res in sorted(results.items())},
                indent=2,
            )
        )
    print(f"total wall time: {time.monotonic() - t0:.1f}s")


def _summarize(result: Dict[str, Any]) -> Dict[str, Any]:
    keep = {"role", "final_test_err", "time_to_target", "elapsed",
            "grads_applied", "params_served", "best_test_err",
            "reads", "monotone", "busy_honored",
            "final_loss", "final_eval_loss", "tokens_per_s", "tokens_total"}
    return {k: v for k, v in result.items() if k in keep}


if __name__ == "__main__":
    main()
