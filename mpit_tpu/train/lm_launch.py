"""Long-context causal-LM training CLI — the sequence-parallel workload
launcher.

The reference's launchers drive conv/pool workloads (mlaunch/plaunch);
this is the rebuild's beyond-parity long-context analog: TinyDecoder
over a ``(dp, sp)`` device mesh — batch sharded over ``dp``, the
sequence axis ring-sharded over ``sp``
(:func:`mpit_tpu.parallel.ring_attention.ring_attention` with
``batch_axis="dp"``), local pallas flash attention when ``sp == 1``.
Parameters are replicated; gradients reduce across the mesh inside one
jitted step; the update is the fused Nesterov sweep.

Data is a byte corpus: ``--text_file`` (trained as raw bytes, vocab
256) or a deterministic synthetic stream.  Example (8 virtual devices,
2-way data x 4-way sequence parallel):

    python -m mpit_tpu.train.lm_launch --dp 2 --sp 4 --seq_len 2048 \
        --d_model 256 --n_layers 2 --steps 100

Multi-host: same ``--hostfile`` / ``--coordinator`` surface as
mesh_launch; each process feeds its own dp rows.
"""

from __future__ import annotations

import functools
import json
import pathlib
import sys
import time
from typing import List, Optional

from mpit_tpu.utils.config import Config
from mpit_tpu.utils.logging import get_logger

LM_LAUNCH_DEFAULTS = Config(
    seq_len=1024,
    d_model=256,
    n_heads=8,
    n_layers=2,
    batch=8,  # global batch (rows sharded over dp)
    steps=200,
    lr=1e-3,
    mom=0.9,
    dp=0,  # 0 -> 1 (all devices on sp)
    sp=0,  # 0 -> all remaining devices
    # Causal ring layout: zigzag is the default because the causal ring's
    # wall clock is set by its busiest device and the zigzag (early+late
    # half-chunk) layout cuts that device's work 1.74x measured
    # (docs/KERNEL_BENCH.md §3); contiguous remains for ablation.
    layout="zigzag",  # zigzag | contiguous
    attn_dtype="bfloat16",  # kernel input dtype: bfloat16 | float32
    text_file="",
    compile_cache=1,  # persistent XLA compilation cache (utils.platform)
    seed=1,
    log_every=20,
    ckpt_dir="",
    ckpt_every=100,  # steps
    resume="",  # "auto" -> <ckpt_dir>/lm_latest.npz
    # multi-host bootstrap (parallel.distributed.bootstrap)
    hostfile="",
    coordinator="",
    num_processes=0,
    process_id=-1,
)


_SYNTH_CACHE: dict = {}


def _corpus_key(text_file: str) -> str:
    """Identity of the training corpus for resume guards: the resolved
    path ("" for the synthetic stream).  Stored resolved at save time so
    the comparison is cwd-independent."""
    return str(pathlib.Path(text_file).resolve()) if text_file else ""


def _corpus(cfg: Config, log) -> "np.ndarray":
    import numpy as np

    if cfg.text_file:
        data = np.frombuffer(
            pathlib.Path(cfg.text_file).read_bytes(), np.uint8
        ).astype(np.int32)
        log.info("corpus: %s (%d bytes)", cfg.text_file, len(data))
    else:
        # Markov-ish synthetic bytes: learnable structure, not uniform
        # noise.  Deterministic in n — memoized, the scalar chain costs
        # ~1.5s/MB and every run() call would otherwise regenerate it.
        n = max(1 << 20, 8 * (cfg.seq_len + 1) * cfg.batch)
        data = _SYNTH_CACHE.get(n)
        if data is None:
            rng = np.random.default_rng(1234)
            trans = rng.integers(0, 256, (256, 4))
            data = np.empty(n, np.int32)
            data[0] = 0
            choices = rng.integers(0, 4, n)
            noise = rng.random(n)
            resets = rng.integers(0, 256, n)
            for i in range(1, n):
                data[i] = (trans[data[i - 1], choices[i]]
                           if noise[i] > 0.1 else resets[i])
            _SYNTH_CACHE[n] = data
        log.info("corpus: synthetic markov bytes (%d)", n)
    if len(data) < cfg.batch * (cfg.seq_len + 1):
        raise ValueError(
            f"corpus of {len(data)} tokens < one global batch "
            f"({cfg.batch} x {cfg.seq_len + 1})"
        )
    return data


def run(cfg: Config) -> dict:
    from mpit_tpu.parallel.distributed import bootstrap

    pg = bootstrap(
        coordinator=cfg.coordinator or None,
        num_processes=cfg.num_processes or None,
        process_id=cfg.process_id if cfg.process_id >= 0 else None,
        hostfile=cfg.hostfile or None,
    )

    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from mpit_tpu.models import TinyDecoder, default_attn, flatten_module
    from mpit_tpu.parallel.mesh import (
        process_local_rows, put_global, put_local,
    )
    from mpit_tpu.parallel.ring_attention import ring_attention
    from mpit_tpu.utils.platform import default_devices

    log = get_logger("lm", pg.process_id)
    if cfg.compile_cache:
        from mpit_tpu.utils.platform import enable_compile_cache

        log.info("compile cache: %s", enable_compile_cache())
    devs = default_devices()
    dp = int(cfg.dp) or 1
    sp = int(cfg.sp) or len(devs) // dp
    if dp * sp != len(devs):
        raise ValueError(f"dp*sp = {dp}*{sp} != {len(devs)} devices")
    mesh = Mesh(np.asarray(devs).reshape(dp, sp), ("dp", "sp"))
    log.info("mesh: dp=%d sp=%d", dp, sp)
    if cfg.batch % dp:
        raise ValueError(f"--batch {cfg.batch} not divisible by dp={dp}")
    if cfg.seq_len % max(sp, 1):
        raise ValueError(f"--seq_len {cfg.seq_len} not divisible by sp={sp}")

    cast = jnp.bfloat16 if cfg.attn_dtype == "bfloat16" else None
    inner = (ring_attention(mesh, "sp", causal=True, batch_axis="dp",
                            layout=cfg.layout)
             if sp > 1 else default_attn(causal=True))

    def attn_fn(q, k, v):
        out_dtype = q.dtype
        if cast is not None:
            q, k, v = (t.astype(cast) for t in (q, k, v))
        return inner(q, k, v).astype(out_dtype)

    model = TinyDecoder(
        vocab=256, d_model=cfg.d_model, n_heads=cfg.n_heads,
        n_layers=cfg.n_layers, max_len=cfg.seq_len, attn_fn=attn_fn,
    )
    # ring_attention(batch_axis="dp") shard_maps the init sample's batch
    # axis over dp, so the sample must be dp-divisible exactly like a
    # training batch — a (batch//dp)-row sample would shard over dp
    # *again* and crash for valid configs (e.g. dp=4 sp=2 batch=8:
    # 2 rows % 4 != 0).  dp rows is the smallest valid sample; param
    # shapes don't depend on batch.
    sample = jnp.zeros((dp, cfg.seq_len), jnp.int32)
    flat = flatten_module(model, jax.random.PRNGKey(cfg.seed), sample)
    log.info("flat params: %d", flat.size)

    batch_sharding = NamedSharding(mesh, P("dp", None))

    def loss_fn(w, toks):
        logp = flat.apply_flat(w, toks[:, :-1])
        tgt = toks[:, 1:]
        return -jnp.mean(jnp.take_along_axis(logp, tgt[..., None], -1))

    # Full Nesterov msgd (the framework's split lookahead/commit halves,
    # optim/msgd.py — same math as the mesh trainers).
    from mpit_tpu.optim.msgd import MSGDConfig, msgd_commit, msgd_lookahead

    mcfg = MSGDConfig(lr=cfg.lr, mom=cfg.mom)

    @functools.partial(jax.jit, donate_argnums=(0, 1))
    def train_step(w, vt, k, toks):
        st = {"k": k, "vt": vt}
        w_la, st = msgd_lookahead(w, st, mcfg)
        loss, g = jax.value_and_grad(loss_fn)(w_la, toks)
        w2, st2 = msgd_commit(w_la, g, st, mcfg)
        return w2, st2["vt"], k + 1, loss

    # Replicated placement over the global mesh: a multi-host program
    # cannot place host-local arrays on non-addressable devices
    # (put_global docstring, parallel/mesh.py).
    rep = NamedSharding(mesh, P())
    w = put_global(flat.w0, rep)
    vt = put_global(jnp.zeros_like(flat.w0), rep)
    k_step = put_global(jnp.zeros((), jnp.int32), rep)
    start_step = 0
    prev_elapsed = 0.0
    resume_path = cfg.resume
    if resume_path == "auto":
        if not cfg.ckpt_dir:
            raise ValueError("--resume auto requires --ckpt_dir")
        resume_path = str(pathlib.Path(cfg.ckpt_dir) / "lm_latest.npz")
    if resume_path:
        from mpit_tpu.utils.checkpoint import load_state_dict

        saved, meta = load_state_dict(resume_path)
        if saved["w"].shape != tuple(flat.w0.shape):
            raise ValueError(
                f"checkpoint params {saved['w'].shape} != model "
                f"{tuple(flat.w0.shape)} — different --d_model/--n_layers/"
                "--seq_len?"
            )
        want = {"d_model": cfg.d_model, "n_heads": cfg.n_heads,
                "n_layers": cfg.n_layers, "seq_len": cfg.seq_len}
        if "model" in meta and meta["model"] != want:
            raise ValueError(
                f"checkpoint model config {meta['model']} != {want} — "
                "same flat size does not make the same model (n_heads "
                "changes the attention head split silently)"
            )
        if "seed" in meta and int(meta["seed"]) != int(cfg.seed):
            raise ValueError(
                f"checkpoint was trained with --seed {meta['seed']}, "
                f"resuming with --seed {cfg.seed} would silently diverge "
                "the data stream — pass the original seed"
            )
        # The skipped-step burn draws cfg.batch starts per step and the
        # synthetic corpus size depends on batch: a different --batch (or
        # corpus) silently diverges the stream exactly like a seed change.
        if "batch" in meta and int(meta["batch"]) != int(cfg.batch):
            raise ValueError(
                f"checkpoint was trained with --batch {meta['batch']}, "
                f"resuming with --batch {cfg.batch} would silently diverge "
                "the data stream — pass the original batch"
            )
        # meta stores the save-time *resolved* path; resolving the saved
        # string here against the resume-time cwd would compare the wrong
        # file whenever the cwds differ.
        if ("text_file" in meta
                and meta["text_file"] != _corpus_key(cfg.text_file)):
            raise ValueError(
                f"checkpoint was trained on {meta['text_file']!r}, "
                f"resuming on {cfg.text_file!r} is a different corpus"
            )
        w = put_global(jnp.asarray(saved["w"]), rep)
        vt = put_global(jnp.asarray(saved["vt"]), rep)
        k_step = put_global(jnp.asarray(saved["k"]), rep)
        start_step = int(meta.get("step", -1)) + 1
        prev_elapsed = float(meta.get("elapsed", 0.0))
        log.info("resumed at step %d", start_step)

    data = _corpus(cfg, log)
    rng = np.random.default_rng(cfg.seed)
    # Burn the skipped steps' sampling so a resumed run continues the
    # stream (one draw of cfg.batch starts per step).
    for _ in range(start_step):
        rng.integers(0, len(data) - cfg.seq_len - 1, cfg.batch)

    rows = (process_local_rows(batch_sharding, cfg.batch)
            if pg.num_processes > 1 else slice(None))

    # Compile + warm the step program before t0 (mesh_launch's
    # precompile discipline): the jits donate w/vt, so copies run
    # through them and are discarded — tokens_per_sec measures training,
    # not XLA, and compile_s is reported separately.
    t_c = time.perf_counter()
    warm_tokens = put_local(
        jnp.zeros((cfg.batch, cfg.seq_len + 1), jnp.int32)[rows],
        batch_sharding)
    warm_out = train_step(jnp.copy(w), jnp.copy(vt), jnp.copy(k_step),
                          warm_tokens)
    # Host fetch fences the warm execution (block_until_ready lies on
    # tunneled platforms, utils/timing.py) — without it compile_s stops
    # early and the warm step bleeds into the timed region.
    from mpit_tpu.utils.timing import fetch_scalar

    fetch_scalar(warm_out[-1])
    compile_s = time.perf_counter() - t_c
    log.info("precompile: %.2fs", compile_s)

    losses: List = []
    history: List[dict] = []
    t0 = time.perf_counter()
    for step in range(start_step, cfg.steps):
        starts = rng.integers(0, len(data) - cfg.seq_len - 1, cfg.batch)
        toks = np.stack([data[s:s + cfg.seq_len + 1] for s in starts])
        toks = put_local(jnp.asarray(toks[rows], jnp.int32), batch_sharding)
        w, vt, k_step, loss = train_step(w, vt, k_step, toks)
        losses.append(loss)
        if (step + 1) % max(int(cfg.log_every), 1) == 0:
            avg = float(jnp.mean(jnp.stack(losses)))
            losses.clear()
            log.info("step %d loss %.4f (%.1fs)", step, avg,
                     time.perf_counter() - t0 + prev_elapsed)
            history.append({"step": step, "avg_loss": avg})
        if (cfg.ckpt_dir and pg.process_id == 0
                and (step + 1) % max(int(cfg.ckpt_every), 1) == 0):
            from mpit_tpu.utils.checkpoint import save_state_dict

            save_state_dict(
                cfg.ckpt_dir,
                {"w": np.asarray(w), "vt": np.asarray(vt),
                 "k": np.asarray(k_step)},
                meta={"step": step, "seed": cfg.seed,
                      "batch": cfg.batch,
                      "text_file": _corpus_key(cfg.text_file),
                      "model": {"d_model": cfg.d_model,
                                "n_heads": cfg.n_heads,
                                "n_layers": cfg.n_layers,
                                "seq_len": cfg.seq_len},
                      "elapsed": round(time.perf_counter() - t0
                                       + prev_elapsed, 3)},
                prefix="lm",
            )
    elapsed = time.perf_counter() - t0 + prev_elapsed
    if losses:
        history.append({
            "step": cfg.steps - 1,
            "avg_loss": float(jnp.mean(jnp.stack(losses))),
        })
    trained = (cfg.steps - start_step) * cfg.batch * cfg.seq_len
    return {
        "history": history,
        "final_loss": history[-1]["avg_loss"] if history else None,
        "elapsed": round(elapsed, 3),
        "tokens_trained": trained,
        "tokens_per_sec": round(trained / max(elapsed - prev_elapsed, 1e-9), 1),
        "compile_s": round(compile_s, 3),
        "mesh": {"dp": dp, "sp": sp},
        "params": flat.size,
        "processes": pg.num_processes,
    }


def main(argv: Optional[List[str]] = None) -> None:
    cfg = LM_LAUNCH_DEFAULTS.parse_args(
        list(sys.argv[1:] if argv is None else argv)
    )
    print(json.dumps(run(cfg), indent=2))


if __name__ == "__main__":
    main()
