"""L4/L5 — trainers and launchers."""

from mpit_tpu.train.trainer import MnistTrainer, TRAINER_DEFAULTS
from mpit_tpu.train.launch import assign_roles, run_rank, server_rule_for

__all__ = [
    "MnistTrainer",
    "TRAINER_DEFAULTS",
    "assign_roles",
    "run_rank",
    "server_rule_for",
]
