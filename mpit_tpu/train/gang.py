"""Process-gang spawner — the built-in ``mpirun -np N`` analog.

Forks N role processes wired over the native shm transport, monitors them
as a gang (one dead rank starves its peers: servers wait for STOPs that
never arrive — the same failure shape mpirun handles by killing the job),
collects per-rank JSON results from files, and tears everything down on
failure or timeout.  Shared by the MNIST launcher
(:mod:`mpit_tpu.train.launch`) and the BiCNN launcher
(:mod:`mpit_tpu.train.bicnn_launch`).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import tempfile
import time
from typing import Any, Dict, Optional

from mpit_tpu.utils.config import Config


def child_transport(cfg: Config, rank: int, size: int):
    """The gang's wire: shm rings on one host (default), TCP across hosts
    (``transport=tcp`` + ``tcp_addrs=host:port,...`` — one address per
    rank, the hostfile-deployment analog).

    Every gang synchronizes on a startup barrier
    (:class:`mpit_tpu.comm.collectives.HostCollectives`) before any role
    traffic, so a slow-to-spawn rank can't race the PS seeding protocol
    (the mpirun-gives-you-this guarantee; disable with gang_barrier=0).
    """
    if cfg.get("transport", "shm") == "tcp":
        from mpit_tpu.comm.tcp import TcpTransport

        addrs = [a for a in str(cfg.get("tcp_addrs", "")).split(",") if a]
        if len(addrs) != size:
            raise ValueError(
                f"transport=tcp needs {size} comma-separated tcp_addrs, "
                f"got {len(addrs)}"
            )
        dial_peers = None
        reconnect = None
        if bool(cfg.get("elastic", False)):
            # Elastic gangs (PROTOCOL.md §9): the mesh rendezvous must
            # never wait on a spare slot that has not spawned.  Initial
            # members dial only lower *initial* ranks; a
            # controller-spawned joiner dials exactly the live set the
            # controller stamped into its spawn request
            # (MPIT_ELASTIC_DIAL) — a retired or dead rank would burn
            # the whole connect deadline.  Later arrivals (spares, a
            # rejoiner) come through the loop's persistent accept
            # service, so reconnect mode is forced on.
            np0 = int(cfg.get("elastic_np0", 0) or 0) or size
            dial_env = os.environ.get("MPIT_ELASTIC_DIAL", "")
            if dial_env:
                dial_peers = [int(x) for x in dial_env.split(",") if x]
            else:
                dial_peers = list(range(min(rank, np0)))
            reconnect = float(os.environ.get("MPIT_TCP_RECONNECT_S", "60"))
        elif os.environ.get("MPIT_FT_REJOIN", "0") not in ("0", ""):
            # A supervisor-restarted worker joins a mid-run gang: only
            # its servers must be reachable — a sibling worker that
            # already finished and exited is not a failure (PS traffic
            # is client<->server only; the barrier is skipped on rejoin).
            from mpit_tpu.train.launch import assign_roles

            sranks, _cranks, _tester = assign_roles(
                size, int(cfg.get("master_freq", 2)),
                str(cfg.get("tester", "none")),
            )
            if rank not in sranks:
                dial_peers = [r for r in sranks if r < rank]
        transport = TcpTransport(rank, size, addrs, dial_peers=dial_peers,
                                 reconnect=reconnect)
    else:
        from mpit_tpu.comm.shm import ShmTransport

        transport = ShmTransport(
            cfg.namespace, rank, size,
            ring_bytes=int(cfg.get("ring_mb", 64)) << 20,
        )
    if bool(cfg.get("gang_barrier", True)):
        from mpit_tpu.comm.collectives import HostCollectives

        HostCollectives(transport).barrier()
    return transport


def spawn_rank(
    child_module: str, cfg: Config, rank: int, size: int, logdir: str,
    extra_env: Optional[Dict[str, str]] = None,
) -> tuple:
    """Spawn one ``--child`` rank process; returns (proc, logpath,
    resultpath).  The single spawn path shared by :func:`launch_gang`
    and the fault-tolerance supervisor (mpit_tpu.ft.supervisor), which
    re-invokes it to restart a dead rank — logs open in append mode so a
    restarted incarnation continues the same rank log.  ``cfg`` is
    serialized per call, so a restart may carry a modified config
    (barrier off, resume on) without touching its gang-mates."""
    logpath = os.path.join(logdir, f"rank{rank}.log")
    resultpath = os.path.join(logdir, f"rank{rank}.result.json")
    env = {
        **os.environ,
        "MPIT_SIZE": str(size),
        "MPIT_CFG": json.dumps(cfg.to_dict()),
        "MPIT_RANK": str(rank),
        "MPIT_RESULT_FILE": resultpath,
    }
    env.update(extra_env or {})
    with open(logpath, "a") as fh:
        proc = subprocess.Popen(
            [sys.executable, "-m", child_module, "--child"],
            env=env, stdout=fh, stderr=subprocess.STDOUT, text=True,
        )
    return proc, logpath, resultpath


def launch_gang(
    child_module: str, cfg: Config, timeout: float = 3600.0,
    env_overrides: Optional[Dict[int, Dict[str, str]]] = None,
) -> Dict[int, Dict[str, Any]]:
    """Spawn ``python -m <child_module> --child`` per rank; gang-monitor.

    ``env_overrides`` maps rank -> extra env vars for that child — the
    device-assignment hook (the reference's per-rank GPU map,
    mlaunch.lua:56-62, expressed as per-rank platform/visible-device
    env)."""
    size = int(cfg.np)
    namespace = cfg.get("namespace") or f"mpit{os.getpid()}"
    cfg = cfg.merged(namespace=namespace)
    # Children write to per-rank log files, not pipes: nobody needs to
    # drain them while the gang runs, so a log-heavy child can never block
    # on a full pipe buffer mid-run.
    logdir = tempfile.mkdtemp(prefix=f"{namespace}_logs_")
    procs, logfiles, resultfiles = [], [], []
    for rank in range(size):
        proc, logpath, resultpath = spawn_rank(
            child_module, cfg, rank, size, logdir,
            extra_env=(env_overrides or {}).get(rank),
        )
        procs.append(proc)
        logfiles.append(logpath)
        resultfiles.append(resultpath)
    deadline = time.monotonic() + timeout
    failed: Optional[int] = None
    timed_out = False
    states = [None] * size
    while True:
        states = [p.poll() for p in procs]
        if all(s is not None for s in states):
            break
        bad = next((i for i, s in enumerate(states) if s not in (None, 0)), None)
        timed_out = time.monotonic() > deadline
        if bad is not None or timed_out:
            failed = bad
            for p in procs:
                if p.poll() is None:
                    p.terminate()
            break
        time.sleep(0.2)
    for proc in procs:
        try:
            proc.wait(timeout=30)
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.wait()
    results: Dict[int, Dict[str, Any]] = {}
    for rank, (logpath, resultpath) in enumerate(zip(logfiles, resultfiles)):
        with open(logpath) as fh:
            for line in fh:
                print(line.rstrip("\n"))
        if os.path.exists(resultpath):
            with open(resultpath) as fh:
                results[rank] = json.load(fh)
    if timed_out and failed is None:
        alive = [r for r, s in enumerate(states) if s is None]
        raise RuntimeError(
            f"gang timed out after {timeout:.0f}s; ranks still running at "
            f"teardown: {alive}; gang torn down (logs: {logdir})"
        )
    if failed is not None:
        raise RuntimeError(
            f"rank {failed} exited with {procs[failed].returncode}; "
            f"gang torn down (logs: {logdir})"
        )
    for rank, proc in enumerate(procs):
        if proc.returncode != 0:
            raise RuntimeError(f"rank {rank} exited with {proc.returncode}")
    missing = [r for r in range(size) if r not in results]
    if missing:
        raise RuntimeError(
            f"ranks {missing} exited 0 but reported no result (logs: {logdir})"
        )
    # Merge the children's per-rank Chrome-trace parts (MPIT_OBS_TRACE)
    # into one timeline — only after a clean gang, so a failure leaves
    # the parts on disk next to the logs for postmortem.
    from mpit_tpu.obs import maybe_merge_rank_traces

    maybe_merge_rank_traces()
    import shutil

    shutil.rmtree(logdir, ignore_errors=True)  # only useful on failure
    return results


def child_env() -> tuple[int, int, Config]:
    """(rank, size, cfg) from the gang environment, for ``--child`` mains.

    Also applies the child's JAX_PLATFORMS assignment — a preloaded
    accelerator plugin would otherwise override the env var and every
    rank would contend for the same chip."""
    from mpit_tpu.utils.platform import honor_jax_platforms

    honor_jax_platforms()
    rank = int(os.environ["MPIT_RANK"])
    size = int(os.environ["MPIT_SIZE"])
    cfg = Config(**json.loads(os.environ["MPIT_CFG"]))
    return rank, size, cfg


def write_result(result: Dict[str, Any]) -> None:
    """Results travel over a dedicated file, not stdout: log lines from
    library threads could interleave with (and corrupt) a stdout protocol."""
    result_file = os.environ.get("MPIT_RESULT_FILE")
    if result_file:
        with open(result_file, "w") as fh:
            json.dump(result, fh)
    else:
        print(f"MPIT_RESULT {os.environ.get('MPIT_RANK')} {json.dumps(result)}", flush=True)
