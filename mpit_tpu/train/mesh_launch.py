"""On-mesh distributed MNIST training CLI — the mlaunch analog on ICI.

Where :mod:`mpit_tpu.train.launch` reproduces the reference's
process-gang shape (pServer/pClient ranks over the host transport,
reference asyncsgd/mlaunch.lua), this entry point runs the same
algorithms as *sharded XLA programs* over a device mesh — the BASELINE
north-star configuration: MNIST EASGD with workers on the ``dp`` axis
and parameter/center shards on the ``shard`` axis, trained to a target
test error using only ICI collectives, with wall-clock-to-target
reported.

Multi-host: pass ``--hostfile`` (the reference's host:slots format,
BiCNN/hostfiles) or ``--coordinator/--num_processes/--process_id``
(or MPIT_* env) and run the same command on every host —
``jax.distributed`` forms the group before any backend use and the mesh
then spans all hosts (DCN for cross-host hops).

Example (single host, all local devices):

    python -m mpit_tpu.train.mesh_launch --opt easgd --su 10 \
        --mva 0.15 --epochs 10
"""

from __future__ import annotations

import json
import pathlib
import sys
import time
from typing import List, Optional

from mpit_tpu.utils.config import Config
from mpit_tpu.utils.logging import get_logger
from mpit_tpu.obs import profiler_trace

MESH_LAUNCH_DEFAULTS = Config(
    model="cnn",  # linear | mlp | cnn
    opt="easgd",  # easgd | syncdp
    lr=1e-2,
    mom=0.99,
    mommax=1.0,
    momdecay=0.0,
    l2wd=0.0,
    mva=0.0,  # 0 -> beta/p with beta=0.9 (mlaunch.lua:42)
    su=10,
    epochs=10,
    batch=128,  # per-worker batch (easgd) / global batch (syncdp)
    seed=1,
    side=32,
    dp=0,  # 0 -> inferred from device count
    shard=0,
    target_test_err=0.01,
    stop_at_target=0,  # 1 -> stop training once target_test_err is reached
    device_stream=0,  # 1 -> stage each epoch's batches on device up front
    epoch_scan=1,  # with device_stream: whole epoch as ONE jitted scan
    device_loop=0,  # 1 -> the WHOLE train-to-target run as one device
    # program (lax.while_loop over epochs: on-device shuffle, epoch scan,
    # test eval, early exit at target).  RTT-proof time-to-target;
    # single-process only, no mid-run checkpoint/resume (_device_loop_train)
    measure_throughput=0,  # 1 -> post-training steady-state samples/s leg
    ckpt_dir="",  # save full trainer state every ckpt_every epochs
    ckpt_every=1,
    resume="",  # path to a mesh_*.npz (or "auto": <ckpt_dir>/mesh_latest.npz)
    dtype="float32",
    profile_dir="",
    compile_cache=1,  # persistent XLA compilation cache (utils.platform)
    precompile=0,  # 1 -> compile+warm the step/eval programs before t0
    # multi-host bootstrap (parallel.distributed.bootstrap)
    hostfile="",
    coordinator="",
    num_processes=0,
    process_id=-1,
)

# The flagship benchmark training config (mlaunch.lua:39-47 analog) —
# ONE definition shared by bench.py (throughput/time-to-target) and
# tools/accuracy_table.py (3-seed test_err), so the accuracy evidence
# always describes the benchmarked trainer.
FLAGSHIP_BENCH_KWARGS = dict(
    opt="easgd", model="cnn", batch=128, side=32,
    su=10, mom=0.99, lr=1e-2, device_stream=1, precompile=1,
)


def _epoch_layout(cfg, n_dp, trainer, mesh, nsteps):
    """Staged-epoch leading shape + sharding — ONE definition shared by
    the host path's ``stage_epoch`` and the device-loop gather, which
    must agree on the batch layout or the two modes silently diverge."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    shape = ((nsteps, n_dp, cfg.batch)
             if cfg.opt == "easgd" else (nsteps, cfg.batch))
    return shape, NamedSharding(mesh, P(None, *trainer.batch_sharding.spec))


def _device_loop_train(*, cfg, trainer, state, eval_params, err_fn, mesh,
                       n_dp, x_train, y_train, x_test, y_test, dtype,
                       steps_per_epoch, per_step, log):
    """Train-to-target as ONE device program: a ``lax.while_loop`` over
    epochs with the on-device shuffle (``jax.random.permutation``), the
    whole-epoch scan, and the test-error eval all inside the loop body,
    early-exiting once the error meets the target (``stop_at_target``).

    Why: the host epoch loop pays >=2 blocking host<->device round trips
    per epoch (loss + error fetches) plus an H2D epoch stage; on a
    tunneled chip those RTTs dominate short epochs — round 5 measured
    the SAME training going 3.47 s -> 8.58 s to target purely on tunnel
    weather (docs/NORTHSTAR_r5.md).  Here the full run is one
    AOT-compiled dispatch and one result fetch, so time-to-target
    reflects the device, not the link.  (The reference's loop is
    host-driven by construction — goot.lua:129-146; a device-resident
    data-dependent training loop is XLA-native ground.)

    On-chip A/B on the flagship bench config (3 reps each mode,
    benchmarks/device_loop_ab.py, 2026-07-31): host loop median
    time-to-target 4.28 s (runs 6.07/4.28/4.12), device_loop **1.01 s**
    (0.94/1.01/1.21) — the whole gap was per-epoch host round trips.
    bench.py therefore defaults to device_loop=1 for the headline
    time_to_target_s (MPIT_BENCH_DEVICE_LOOP=0 restores the host loop).

    Trade-offs (why the host loop remains the general default): the shuffle is
    jax.random rather than the host path's numpy rng (equally random,
    but trajectories are not bit-comparable across modes), per-epoch
    wall timestamps do not exist (only the final ``at`` is real), and
    mid-run checkpoint/profiling hooks cannot fire.
    """
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    n = len(x_train)
    take = steps_per_epoch * per_step
    shape, ep_sharding = _epoch_layout(cfg, n_dp, trainer, mesh,
                                       steps_per_epoch)
    x_all = jnp.asarray(
        np.asarray(x_train, np.float32).reshape(n, -1), dtype)
    y_all = jnp.asarray(np.asarray(y_train))
    epochs = int(cfg.epochs)
    # The early exit happens ON DEVICE: a sentinel no error reaches keeps
    # the loop running every epoch when stop_at_target is off.
    target = jnp.float32(
        cfg.target_test_err if cfg.stop_at_target else -1.0)

    def _body(carry):
        ep, st, key, errs, losses = carry
        key, sub = jax.random.split(key)
        order = jax.random.permutation(sub, n)[:take]
        x_ep = jax.lax.with_sharding_constraint(
            x_all[order].reshape(*shape, -1), ep_sharding)
        y_ep = jax.lax.with_sharding_constraint(
            y_all[order].reshape(shape), ep_sharding)
        st, ep_losses = trainer.run_epoch(st, x_ep, y_ep)
        err = err_fn(eval_params(st), x_test, y_test)
        return (ep + 1, st, key, errs.at[ep].set(err),
                losses.at[ep].set(jnp.mean(ep_losses)))

    def _cond(carry):
        ep, _st, _key, errs, _losses = carry
        hit = jnp.logical_and(
            ep > 0, errs[jnp.maximum(ep - 1, 0)] <= target)
        return jnp.logical_and(ep < epochs, jnp.logical_not(hit))

    def _train(st, key):
        carry = (jnp.asarray(0, jnp.int32), st, key,
                 jnp.full((epochs,), jnp.inf, jnp.float32),
                 jnp.zeros((epochs,), jnp.float32))
        ep, st, _key, errs, losses = jax.lax.while_loop(
            _cond, _body, carry)
        return ep, st, errs, losses

    key0 = jax.random.PRNGKey(cfg.seed)
    t_c = time.perf_counter()
    compiled = jax.jit(_train, donate_argnums=(0,)).lower(
        state, key0).compile()
    compile_s = time.perf_counter() - t_c
    log.info("device-loop compile: %.2fs (whole train-to-target program)",
             compile_s)

    t0 = time.perf_counter()
    ep_d, state, errs_d, losses_d = compiled(state, key0)
    ep = int(ep_d)  # the fetch that fences the whole program
    wall = time.perf_counter() - t0
    errs, losses = np.asarray(errs_d), np.asarray(losses_d)
    # run_epoch's host-side counter advanced once at TRACE time, not once
    # per executed epoch — resynchronize it with the device-resident
    # schedule so any subsequent step()/run_epoch use (e.g. the
    # measure_throughput leg) continues the true global sync phase.
    trainer.set_steps(ep * steps_per_epoch)

    history = [
        {"epoch": i, "avg_loss": float(losses[i]),
         "test_err": float(errs[i]),
         # One program ran every epoch: only the final wall is real.
         "at": round(wall, 3) if i == ep - 1 else None}
        for i in range(ep)
    ]
    for h in history:
        log.info("epoch %d avg_loss %.5f test_err %.4f",
                 h["epoch"], h["avg_loss"], h["test_err"])
    hit_target = bool(ep and errs[ep - 1] <= float(cfg.target_test_err))
    # Contract difference vs the host loop: with stop_at_target=0 the
    # host loop reports time_to_target at whichever epoch first met the
    # target mid-run; inside one device program no per-epoch wall
    # timestamp exists, so a mid-run hit has no honest wall time to
    # report — time_to_target is defined here ONLY when the program
    # early-exits at the target (stop_at_target=1).
    time_to_target = wall if (cfg.stop_at_target and hit_target) else None
    if (not cfg.stop_at_target
            and any(errs[:ep] <= float(cfg.target_test_err))):
        log.warning(
            "device_loop: target %.4f was reached mid-run but "
            "stop_at_target=0 — no per-epoch wall times exist inside the "
            "device program, so time_to_target stays None (use "
            "stop_at_target=1 or the host loop to measure it)",
            float(cfg.target_test_err))
    log.info("device-loop: %d epoch(s) in %.2fs wall (one dispatch)",
             ep, wall)
    return state, history, time_to_target, compile_s, wall, ep * take, t0


def run(cfg: Config) -> dict:
    # Bootstrap BEFORE any jax backend use (multi-host group formation).
    from mpit_tpu.parallel.distributed import bootstrap

    pg = bootstrap(
        coordinator=cfg.coordinator or None,
        num_processes=cfg.num_processes or None,
        process_id=cfg.process_id if cfg.process_id >= 0 else None,
        hostfile=cfg.hostfile or None,
    )

    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    from mpit_tpu.data.mnist import load_mnist
    from mpit_tpu.models import MnistCNN, MnistLinear, MnistMLP, flatten_module
    from mpit_tpu.optim.msgd import MSGDConfig
    from mpit_tpu.parallel import MeshEASGD, SyncDataParallel, make_mesh
    from mpit_tpu.parallel.mesh import put_local

    log = get_logger("mesh", pg.process_id)
    log.info("%s", pg.describe())
    if cfg.compile_cache:
        from mpit_tpu.utils.platform import enable_compile_cache

        log.info("compile cache: %s", enable_compile_cache())
    mesh = make_mesh(
        dp=cfg.dp or None, shard=cfg.shard or None
    )
    n_dp = mesh.shape["dp"]
    log.info("mesh: dp=%d shard=%d", n_dp, mesh.shape["shard"])

    (x_train, y_train, x_test, y_test), source = load_mnist(side=cfg.side)
    log.info("data source: %s", source)
    dtype = jnp.dtype(cfg.dtype)
    x_test, y_test = jnp.asarray(x_test, dtype), jnp.asarray(y_test)

    models = {"linear": MnistLinear, "mlp": MnistMLP}
    if cfg.model == "cnn":
        module = MnistCNN(side=cfg.side, num_classes=10)
    elif cfg.model in models:
        module = models[cfg.model](num_classes=10)
    else:
        raise ValueError(f"model must be linear|mlp|cnn, got {cfg.model!r}")
    flat = flatten_module(
        module, jax.random.PRNGKey(cfg.seed), jnp.asarray(x_train[:2], dtype)
    )
    log.info("flat params: %d", flat.size)

    def vgf(w, xb, yb):
        def loss_fn(w):
            logp = flat.apply_flat(w, xb)
            return -jnp.mean(jnp.take_along_axis(logp, yb[:, None], axis=1))

        return jax.value_and_grad(loss_fn)(w)

    msgd = MSGDConfig(
        lr=cfg.lr, mom=cfg.mom, mommax=cfg.mommax, momdecay=cfg.momdecay,
        l2wd=cfg.l2wd,
    )
    mva = cfg.mva or 0.9 / max(n_dp, 1)
    if cfg.opt == "easgd":
        trainer = MeshEASGD(mesh, vgf, msgd, mva=mva, su=cfg.su)
        eval_params = trainer.center_params
    elif cfg.opt == "syncdp":
        trainer = SyncDataParallel(mesh, vgf, msgd)
        eval_params = lambda state: state["w"]
    else:
        raise ValueError(f"opt must be easgd|syncdp, got {cfg.opt!r}")
    state = trainer.init(flat.w0.astype(dtype))

    # Checkpoint backend: single-process uses the portable npz state
    # dict; multi-process uses orbax, which writes each shard from the
    # process holding it (host-local numpy round-trips of globally-
    # sharded state are invalid, and the npz _latest publish would race
    # across hosts).
    use_orbax = pg.num_processes > 1

    def _meta_path():
        return pathlib.Path(cfg.ckpt_dir) / "mesh_meta.json"

    if cfg.device_loop:
        if pg.num_processes > 1:
            raise ValueError(
                "device_loop=1 is single-process: the while_loop body "
                "gathers epoch batches from the replicated dataset, which "
                "multi-host feeding (process-local rows) cannot express"
            )
        if cfg.ckpt_dir or cfg.resume or cfg.profile_dir:
            raise ValueError(
                "device_loop=1 runs every epoch inside one device program "
                "— there are no host epoch boundaries for checkpointing, "
                "resume, or per-epoch profiling; use the host loop for "
                "ckpt_dir/resume/profile_dir"
            )

    start_epoch = 0
    prev_elapsed = 0.0  # cumulative training seconds from resumed runs
    resume_path = cfg.resume
    if resume_path == "auto" and not cfg.ckpt_dir:
        raise ValueError("--resume auto requires --ckpt_dir")
    if resume_path:
        from mpit_tpu.utils.checkpoint import latest_pytree_step

        # Resume backend is detected from what is ON DISK, not from the
        # current topology: a single process can restore orbax step dirs
        # (load_pytree re-places to this run's shardings), while a
        # multi-process group can never round-trip host-local npz.
        disk_step = (latest_pytree_step(cfg.ckpt_dir)
                     if cfg.ckpt_dir and resume_path == "auto" else None)
        if disk_step is not None and not use_orbax:
            # Mixed directory (multi-host steps + later single-process
            # npz saves): prefer the newest artifact.
            npz_latest = pathlib.Path(cfg.ckpt_dir) / "mesh_latest.npz"
            step_dir = pathlib.Path(cfg.ckpt_dir) / f"step_{disk_step}"
            if (npz_latest.exists()
                    and npz_latest.stat().st_mtime > step_dir.stat().st_mtime):
                disk_step = None
        if resume_path == "auto" and disk_step is not None:
            from mpit_tpu.utils.checkpoint import load_pytree

            if not _meta_path().exists():
                raise ValueError(
                    f"step_{disk_step} exists but {_meta_path()} is "
                    "missing — cannot validate opt/seed; the meta is "
                    "written before every step, so this directory is "
                    "corrupt or foreign"
                )
            ck_meta = json.loads(_meta_path().read_text())
            if ck_meta.get("opt", cfg.opt) != cfg.opt:
                raise ValueError(
                    f"checkpoint was trained with --opt {ck_meta['opt']}, "
                    f"not {cfg.opt}"
                )
            state = load_pytree(cfg.ckpt_dir, disk_step, state)
            # The step number, not the (separately written, possibly
            # stale) meta file, defines where training resumes — a crash
            # between the step write and the meta write must not cause
            # silent double-training.
            ck_meta["epoch"] = disk_step
        else:
            if use_orbax:
                raise ValueError(
                    "multi-process resume needs orbax step_* checkpoints "
                    f"under --ckpt_dir (found none in {cfg.ckpt_dir!r}); "
                    "host-local .npz checkpoints cannot restore a "
                    "multi-process mesh"
                )
            from mpit_tpu.utils.checkpoint import load_state_dict

            if resume_path == "auto":
                resume_path = str(
                    pathlib.Path(cfg.ckpt_dir) / "mesh_latest.npz")
            saved, ck_meta = load_state_dict(resume_path)
            if set(saved) != set(state):
                raise ValueError(
                    f"checkpoint keys {sorted(saved)} do not match trainer "
                    f"state {sorted(state)} — wrong --opt or model?"
                )
            # Re-place each array with its mesh sharding (init produced
            # the placement template; shapes must match exactly).
            for key, arr in saved.items():
                if tuple(arr.shape) != tuple(state[key].shape):
                    raise ValueError(
                        f"checkpoint {key} shape {arr.shape} != trainer "
                        f"{tuple(state[key].shape)} (different mesh/model?)"
                    )
                state[key] = jax.device_put(
                    jnp.asarray(arr), state[key].sharding
                )
        if "seed" in ck_meta and int(ck_meta["seed"]) != int(cfg.seed):
            raise ValueError(
                f"checkpoint was trained with --seed {ck_meta['seed']}, "
                f"resuming with --seed {cfg.seed} would silently diverge "
                "the data order — pass the original seed"
            )
        start_epoch = int(ck_meta.get("epoch", -1)) + 1
        prev_elapsed = float(ck_meta.get("elapsed", 0.0))
        log.info("resumed at epoch %d (%.1fs of prior training)",
                 start_epoch, prev_elapsed)

    err_fn = jax.jit(
        lambda w, xb, yb: jnp.mean(
            (jnp.argmax(flat.apply_flat(w, xb), axis=1) != yb).astype(jnp.float32)
        )
    )

    n = len(x_train)
    if cfg.opt == "easgd":
        # Per-worker disjoint streams (each reference client walks its own
        # shuffled copy, goot.lua:129-146).
        per_step = n_dp * cfg.batch
    else:
        per_step = cfg.batch
    if n < per_step:
        raise ValueError(
            f"dataset has {n} samples but one global step needs {per_step} "
            f"({'dp x batch' if cfg.opt == 'easgd' else 'batch'}); lower "
            "--batch or --dp"
        )
    steps_per_epoch = n // per_step

    rng = np.random.default_rng(cfg.seed)
    history: List[dict] = []
    time_to_target: Optional[float] = None
    epoch_train_s: List[float] = []  # step-loop only, per epoch
    samples_trained = 0
    # Multi-process batch feeding: every process builds the same global
    # shuffle (same seed) but hands shard_batch only the leading-axis
    # rows its own devices hold (put_local's contract).
    if pg.num_processes > 1:
        from mpit_tpu.parallel.mesh import process_local_rows

        lead = n_dp if cfg.opt == "easgd" else cfg.batch
        rows = process_local_rows(trainer.batch_sharding, lead)
    else:
        rows = slice(None)

    def stage_epoch(idx, nsteps=None):
        """One HBM placement of a shuffled epoch, step axis in front of
        the batch sharding — per-step slices are already correctly
        sharded and feed the trainer directly (each process contributes
        only its local rows)."""
        nsteps = steps_per_epoch if nsteps is None else nsteps
        shape, ep_sharding = _epoch_layout(cfg, n_dp, trainer, mesh, nsteps)
        x_ep = put_local(
            x_train[idx].reshape(*shape, -1)[:, rows].astype(dtype),
            ep_sharding)
        y_ep = put_local(
            y_train[idx].reshape(shape)[:, rows], ep_sharding)
        return x_ep, y_ep

    compile_s = None
    if cfg.device_loop:
        (state, history, time_to_target, compile_s, dl_wall,
         samples_trained, t0) = _device_loop_train(
            cfg=cfg, trainer=trainer, state=state, eval_params=eval_params,
            err_fn=err_fn, mesh=mesh, n_dp=n_dp, x_train=x_train,
            y_train=y_train, x_test=x_test, y_test=y_test, dtype=dtype,
            steps_per_epoch=steps_per_epoch, per_step=per_step, log=log)
        epoch_train_s = [dl_wall]
    if cfg.precompile and not cfg.device_loop:
        # Compile + warm every program the timed region will run — the
        # step program(s) against the exact training shardings and the
        # eval — so t0 measures training, not XLA.  The north star is
        # still a user-honest wall clock: compile_s is reported
        # separately in the result dict, and with the persistent cache
        # warm this whole block costs well under a second.
        t_c = time.perf_counter()
        if cfg.device_stream and cfg.epoch_scan:
            x_w, y_w = stage_epoch(np.arange(steps_per_epoch * per_step)
                                   % len(x_train))
            trainer.precompile_epoch(state, x_w, y_w)
            del x_w, y_w  # free the warm epoch from HBM before training
            warm_batch = None
        elif cfg.device_stream:
            x_w, y_w = stage_epoch(np.arange(per_step), nsteps=1)
            warm_batch = (x_w[0], y_w[0])
        else:
            xw = np.asarray(x_train[:per_step], np.float32)
            yw = np.asarray(y_train[:per_step])
            if cfg.opt == "easgd":
                xw = xw.reshape(n_dp, cfg.batch, -1)
                yw = yw.reshape(n_dp, cfg.batch)
            warm_batch = trainer.shard_batch(
                jnp.asarray(xw[rows], dtype), jnp.asarray(yw[rows]))
        if warm_batch is not None:
            trainer.precompile(state, *warm_batch)
        float(err_fn(eval_params(state), x_test, y_test))
        compile_s = time.perf_counter() - t_c
        log.info("precompile: %.2fs (step + eval programs warm)", compile_s)

    if not cfg.device_loop:
        t0 = time.perf_counter()  # device_loop sets its own t0

    # Resume reproducibility: burn the skipped epochs' permutations so
    # the data order continues exactly where the checkpointed run left it.
    for _ in range(start_epoch):
        rng.permutation(n)
    with profiler_trace(cfg.profile_dir):
        # device_loop already trained inside its one program: skip.
        for epoch in range(start_epoch,
                           0 if cfg.device_loop else cfg.epochs):
            order = rng.permutation(n)
            losses = []
            t_ep = time.perf_counter()
            if cfg.device_stream:
                # The shuffle is still fresh every epoch — staging
                # changes where batches are assembled, not what is
                # trained (regression-tested against the host path).
                x_ep, y_ep = stage_epoch(order[: steps_per_epoch * per_step])
                if cfg.epoch_scan:
                    # One dispatch per epoch: the whole pass runs as a
                    # jitted lax.scan on device (regression-tested
                    # against the step loop).
                    state, ep_losses = trainer.run_epoch(state, x_ep, y_ep)
                    losses.append(ep_losses)
                else:
                    for step in range(steps_per_epoch):
                        state, loss = trainer.step(
                            state, x_ep[step], y_ep[step])
                        losses.append(loss)
            else:
                for step in range(steps_per_epoch):
                    idx = order[step * per_step:(step + 1) * per_step]
                    xb = np.asarray(x_train[idx], np.float32)
                    yb = np.asarray(y_train[idx])
                    if cfg.opt == "easgd":
                        xb = xb.reshape(n_dp, cfg.batch, -1)
                        yb = yb.reshape(n_dp, cfg.batch)
                    state, loss = trainer.step(state, *trainer.shard_batch(
                        jnp.asarray(xb[rows], dtype), jnp.asarray(yb[rows])
                    ))
                    losses.append(loss)
            avg_loss = float(jnp.mean(jnp.stack(losses)))
            epoch_train_s.append(time.perf_counter() - t_ep)
            samples_trained += steps_per_epoch * per_step
            test_err = float(err_fn(eval_params(state), x_test, y_test))
            # Cumulative across resumes (the reference's prevtime
            # convention, bicnn.lua:259-261) so time_to_target stays the
            # true wall-clock from the ORIGINAL start.
            at = time.perf_counter() - t0 + prev_elapsed
            if time_to_target is None and test_err <= cfg.target_test_err:
                time_to_target = at
            history.append({
                "epoch": epoch, "avg_loss": avg_loss,
                "test_err": test_err, "at": round(at, 3),
            })
            log.info("epoch %d avg_loss %.5f test_err %.4f (%.1fs)",
                     epoch, avg_loss, test_err, at)
            if cfg.ckpt_dir and (epoch + 1) % max(int(cfg.ckpt_every), 1) == 0:
                meta = {"epoch": epoch, "opt": cfg.opt,
                        "test_err": test_err, "seed": cfg.seed,
                        "elapsed": round(at, 3)}
                if use_orbax:
                    from mpit_tpu.utils.checkpoint import save_pytree

                    # Meta BEFORE the step dir: the resume epoch comes
                    # from the step number, so a crash in between leaves
                    # a slightly-ahead meta (harmless) rather than a
                    # step with no seed guard.
                    if pg.process_id == 0:
                        tmp = _meta_path().with_suffix(".tmp")
                        tmp.write_text(json.dumps(meta))
                        tmp.replace(_meta_path())
                    save_pytree(cfg.ckpt_dir, state, step=epoch)
                    path = f"{cfg.ckpt_dir}/step_{epoch}"
                else:
                    from mpit_tpu.utils.checkpoint import save_state_dict

                    path = save_state_dict(
                        cfg.ckpt_dir,
                        {k: np.asarray(v) for k, v in state.items()},
                        meta=meta,
                    )
                log.info("checkpoint: %s", path)
            if cfg.stop_at_target and time_to_target is not None:
                break
    train_time = sum(epoch_train_s)
    # Wall-clock throughput: epoch 0 pays jit compile, drop it when there
    # is anything else to measure.  Includes the one loss fetch per epoch
    # — on a tunneled platform that round-trip can dominate short epochs,
    # which is why the steady-state leg below exists.
    ss = epoch_train_s[1:] if len(epoch_train_s) > 1 else epoch_train_s
    per_epoch = steps_per_epoch * per_step
    if cfg.device_loop:
        # One wall covers every epoch (single dispatch); compile was AOT,
        # outside the wall.  NOT comparable with the host-loop figure:
        # this wall includes the per-epoch on-device eval + shuffle and
        # the dispatch/fetch RTT, where the host path times training
        # only (eval after the per-epoch timer stops) — the result dict
        # carries train_wall_mode so readers of samples_per_sec know
        # which definition they got; samples_per_sec_steady is the
        # mode-independent rate.
        sps = samples_trained / train_time if train_time > 0 else None
    else:
        sps = len(ss) * per_epoch / sum(ss) if ss and sum(ss) > 0 else None

    sps_steady = None
    if cfg.measure_throughput:
        # Latency-cancelled steady-state throughput
        # (:func:`mpit_tpu.utils.timing.timed_chained`): whole passes
        # over one freshly shuffled epoch staged in HBM — every step
        # sees a different batch, the per-pass fetch round-trip is
        # differenced away, and the jits are the already-compiled
        # training programs.
        from mpit_tpu.utils.timing import timed_chained

        x_ep, y_ep = stage_epoch(
            rng.permutation(n)[: steps_per_epoch * per_step])

        if cfg.device_stream and cfg.epoch_scan:
            def one_pass(st):
                st, _losses = trainer.run_epoch(st, x_ep, y_ep)
                return st
        else:
            def one_pass(st):
                for s in range(steps_per_epoch):
                    st, _loss = trainer.step(st, x_ep[s], y_ep[s])
                return st

        # auto_scale + min_ratio: one scan pass is ~ms-scale, far below
        # the tunnel's dispatch jitter — iters grows until the
        # differenced legs clear 8x the observed jitter, bounding the
        # estimator's relative error near 1/8 (51% -> single-digit %
        # run-to-run spread measured).
        # max_iters=128: one iteration here is a whole epoch — the cap
        # bounds escalation cost, and expensive passes stop on the first
        # round anyway (their delta dwarfs jitter by construction).
        per_pass = timed_chained(
            one_pass, state, iters=4, base_iters=1, repeats=3,
            auto_scale=True, min_ratio=8.0, max_iters=128,
        )
        sps_steady = per_epoch / per_pass
    return {
        "history": history,
        "final_test_err": history[-1]["test_err"] if history else None,
        "time_to_target": time_to_target,
        "elapsed": time.perf_counter() - t0 + prev_elapsed,
        "train_time": round(train_time, 3),
        "samples_trained": samples_trained,
        "samples_per_sec": round(sps, 1) if sps else None,
        "samples_per_sec_steady": round(sps_steady, 1) if sps_steady else None,
        # Which wall fed samples_per_sec: "device_loop" includes eval +
        # shuffle inside the one program's wall; "host_loop" times
        # training only.  steady is mode-independent.
        "train_wall_mode": "device_loop" if cfg.device_loop else "host_loop",
        "compile_s": round(compile_s, 3) if compile_s is not None else None,
        "data_source": source,
        "mesh": {"dp": n_dp, "shard": mesh.shape["shard"]},
        "processes": pg.num_processes,
    }


def main(argv: Optional[List[str]] = None) -> None:
    cfg = MESH_LAUNCH_DEFAULTS.parse_args(
        list(sys.argv[1:] if argv is None else argv)
    )
    result = run(cfg)
    print(json.dumps(result, indent=2))


if __name__ == "__main__":
    main()
