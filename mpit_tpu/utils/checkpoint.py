"""Checkpoint/resume — the tester-rank save/load analog.

The reference checkpoints whole-param tensors from the tester rank with a
runtime-stamped filename and resumes via ``-loadmodel`` + ``-prevtime``
(reference bicnn.lua:590-594, plaunch.lua:61-63); optimizer/server state is
not checkpointed there.  Here checkpoints carry the flat param vector plus
a metadata dict (step, metric, cumulative runtime), with orbax available
for full-pytree checkpoints when models outgrow the flat path.
"""

from __future__ import annotations

import json
import pathlib
import time
from typing import Any, Dict, Optional, Tuple

import numpy as np


def _stamped_atomic_publish(
    directory: str | pathlib.Path, prefix: str, payload: Dict[str, Any]
) -> pathlib.Path:
    """Write ``payload`` (np.savez keys) to a millisecond-stamped file
    (sub-second saves must not overwrite each other) and atomically
    publish it as ``<prefix>_latest.npz`` — a concurrent loader (resume,
    tester) must never see a half-written file."""
    import os
    import shutil

    directory = pathlib.Path(directory)
    directory.mkdir(parents=True, exist_ok=True)
    stamp = time.time_ns() // 1_000_000
    path = directory / f"{prefix}_{stamp}.npz"
    tmp = directory / f".{prefix}_{stamp}.npz.tmp"
    with open(tmp, "wb") as fh:
        np.savez(fh, **payload)
    os.replace(tmp, path)
    tmp2 = directory / f".{prefix}_latest.npz.tmp"
    shutil.copyfile(path, tmp2)
    os.replace(tmp2, directory / f"{prefix}_latest.npz")
    return path


def save_flat(
    directory: str | pathlib.Path,
    w: Any,
    meta: Optional[Dict[str, Any]] = None,
    prefix: str = "ckpt",
) -> pathlib.Path:
    """Save the flat param vector; filename stamped with cumulative runtime
    (the reference's timestamped torch.save, bicnn.lua:590-594)."""
    meta = dict(meta or {})
    meta.setdefault("runtime", time.time())
    arr = np.asarray(w)
    # Store raw bytes + dtype name, not the array: np.savez silently
    # round-trips ml_dtypes arrays (bfloat16 & co) as anonymous void
    # records, which load as unusable '|V2' data.
    return _stamped_atomic_publish(directory, prefix, {
        "w_raw": np.frombuffer(arr.tobytes(), np.uint8),
        "w_dtype": str(arr.dtype),
        "w_shape": np.asarray(arr.shape, np.int64),
        "meta": json.dumps(meta),
    })


def load_flat(path: str | pathlib.Path) -> Tuple[np.ndarray, Dict[str, Any]]:
    from mpit_tpu.utils.serialize import resolve_dtype

    with np.load(path, allow_pickle=False) as z:
        if "w" in z:  # legacy layout (native-dtype arrays only)
            return z["w"], json.loads(str(z["meta"]))
        dtype = resolve_dtype(str(z["w_dtype"]))
        # copy(): frombuffer over bytes is read-only; callers resume
        # training into this array.
        w = np.frombuffer(z["w_raw"].tobytes(), dtype).reshape(z["w_shape"]).copy()
        return w, json.loads(str(z["meta"]))


def _pack_array(prefix: str, arr: Any, out: Dict[str, Any]) -> None:
    """Raw-bytes triplet for one array (the ml_dtypes-safe layout of
    save_flat)."""
    arr = np.asarray(arr)
    out[f"{prefix}__raw"] = np.frombuffer(arr.tobytes(), np.uint8)
    out[f"{prefix}__dtype"] = str(arr.dtype)
    out[f"{prefix}__shape"] = np.asarray(arr.shape, np.int64)


def _unpack_array(prefix: str, z) -> np.ndarray:
    from mpit_tpu.utils.serialize import resolve_dtype

    dtype = resolve_dtype(str(z[f"{prefix}__dtype"]))
    shape = tuple(int(s) for s in z[f"{prefix}__shape"])
    return np.frombuffer(z[f"{prefix}__raw"].tobytes(), dtype).reshape(shape).copy()


def save_server_state(
    directory: str | pathlib.Path,
    rank: int,
    offset: int,
    size: int,
    param: Any,
    rule_state: Optional[Dict[str, Any]],
    meta: Optional[Dict[str, Any]] = None,
    keep: int = 3,
) -> pathlib.Path:
    """Checkpoint one server's shard: param slice + rule (optimizer) state.

    The reference never checkpoints server state (SURVEY §5 — only whole
    params from the tester); this closes that gap so an Adam/RMSProp
    server resumes with its moments instead of cold ones.  Published via
    :func:`_stamped_atomic_publish`: a millisecond-stamped version plus
    the ``server<rank>_latest.npz`` alias a loader (resume, a supervisor
    restarting the rank) can always open mid-write-free.  The stamped
    history is pruned to the newest ``keep`` — a fault-tolerant server
    snapshots every ``ckpt_interval`` seconds indefinitely, and an
    unbounded history would fill the disk long before anyone needed a
    snapshot older than a restart or two."""
    payload: Dict[str, Any] = {}
    _pack_array("param", param, payload)
    state = dict(rule_state or {})
    for key, value in state.items():
        _pack_array(f"state_{key}", value, payload)
    payload["meta"] = json.dumps({
        "rank": rank, "offset": offset, "size": size,
        "state_keys": sorted(state), "runtime": time.time(),
        **(meta or {}),
    })
    prefix = f"server{rank}"
    path = _stamped_atomic_publish(directory, prefix, payload)
    if keep > 0:
        stamped = sorted(
            p for p in pathlib.Path(directory).glob(f"{prefix}_*.npz")
            if p.name[len(prefix) + 1 : -len(".npz")].isdigit()
        )
        for old in stamped[:-keep]:
            old.unlink(missing_ok=True)
    return path


def load_server_state(
    path: str | pathlib.Path,
) -> Tuple[int, int, np.ndarray, Dict[str, np.ndarray], Dict[str, Any]]:
    """Inverse of :func:`save_server_state`:
    ``(offset, size, param, rule_state, meta)``."""
    with np.load(path, allow_pickle=False) as z:
        meta = json.loads(str(z["meta"]))
        param = _unpack_array("param", z)
        state = {
            key: _unpack_array(f"state_{key}", z)
            for key in meta["state_keys"]
        }
        return int(meta["offset"]), int(meta["size"]), param, state, meta


def save_pytree(directory: str | pathlib.Path, pytree: Any, step: int) -> None:
    """Full-pytree checkpoint via orbax (params + optimizer state).
    Handles globally-sharded jax arrays — every process of a multi-host
    mesh calls this collectively and orbax writes each shard from the
    process that holds it."""
    import orbax.checkpoint as ocp

    path = pathlib.Path(directory).resolve() / f"step_{step}"
    checkpointer = ocp.StandardCheckpointer()
    checkpointer.save(path, pytree)
    checkpointer.wait_until_finished()


def load_pytree(directory: str | pathlib.Path, step: int, like: Any) -> Any:
    """Restore a :func:`save_pytree` checkpoint.  ``like`` supplies the
    target structure/shardings (sharded jax arrays restore sharded)."""
    import orbax.checkpoint as ocp

    path = pathlib.Path(directory).resolve() / f"step_{step}"
    checkpointer = ocp.StandardCheckpointer()
    return checkpointer.restore(path, like)


def latest_pytree_step(directory: str | pathlib.Path) -> Optional[int]:
    """Highest ``step_N`` under an orbax checkpoint dir, or None."""
    directory = pathlib.Path(directory)
    steps = [
        int(p.name.split("_", 1)[1])
        for p in directory.glob("step_*")
        if p.name.split("_", 1)[1].isdigit()
    ]
    return max(steps) if steps else None


def save_state_dict(
    directory: str | pathlib.Path,
    state: Dict[str, Any],
    meta: Optional[Dict[str, Any]] = None,
    prefix: str = "mesh",
) -> pathlib.Path:
    """Checkpoint a flat dict of arrays (e.g. a mesh trainer's full state
    — per-worker params, velocities, counters, center) with the same
    ml_dtypes-safe packing and atomic ``_latest`` publish as
    :func:`save_flat`.  The reference has no mesh analog to checkpoint
    (mlaunch trains fire-and-forget, asyncsgd/mlaunch.lua); this is the
    beyond-parity resume path for the flagship on-mesh trainers."""
    payload: Dict[str, Any] = {"meta": json.dumps(dict(meta or {}))}
    payload["keys"] = json.dumps(sorted(state))
    for key, value in state.items():
        _pack_array(f"s_{key}", value, payload)
    return _stamped_atomic_publish(directory, prefix, payload)


def load_state_dict(
    path: str | pathlib.Path,
) -> Tuple[Dict[str, np.ndarray], Dict[str, Any]]:
    """Inverse of :func:`save_state_dict`: ``(state, meta)``."""
    with np.load(path, allow_pickle=False) as z:
        keys = json.loads(str(z["keys"]))
        state = {k: _unpack_array(f"s_{k}", z) for k in keys}
        return state, json.loads(str(z["meta"]))
