"""Typed configuration system.

The reference uses two styles: ad-hoc Lua ``opt`` tables with ``opt.x or
default`` fallbacks (reference asyncsgd/mlaunch.lua:33-47, goot.lua:4-17) and
a ~50-flag torch.CmdLine surface (reference BiCNN/plaunch.lua:7-69).  Here
there is one system from day one: a dataclass-like ``Config`` that is

- attribute- and item-accessible with defaults (``cfg.get("lr", 1e-2)``),
- convertible to/from flat CLI args (``--lr 1e-2 --opt easgd``),
- mergeable (launcher defaults < experiment overrides < CLI).
"""

from __future__ import annotations

import argparse
from typing import Any, Dict, Iterator, Mapping, Optional


class Config:
    """A mapping with attribute access and typed CLI parsing."""

    def __init__(self, **kwargs: Any) -> None:
        self.__dict__["_data"] = dict(kwargs)

    # -- mapping protocol ---------------------------------------------------
    def __getitem__(self, key: str) -> Any:
        return self._data[key]

    def __setitem__(self, key: str, value: Any) -> None:
        self._data[key] = value

    def __contains__(self, key: str) -> bool:
        return key in self._data

    def __iter__(self) -> Iterator[str]:
        return iter(self._data)

    def __len__(self) -> int:
        return len(self._data)

    def keys(self):
        return self._data.keys()

    def items(self):
        return self._data.items()

    def get(self, key: str, default: Any = None) -> Any:
        return self._data.get(key, default)

    # -- attribute access ---------------------------------------------------
    def __getattr__(self, key: str) -> Any:
        try:
            return self.__dict__["_data"][key]
        except KeyError:
            raise AttributeError(key) from None

    def __setattr__(self, key: str, value: Any) -> None:
        self._data[key] = value

    # -- composition --------------------------------------------------------
    def merged(self, other: Optional[Mapping[str, Any]] = None, **kwargs: Any) -> "Config":
        """New Config = self overridden by ``other`` then ``kwargs``."""
        data: Dict[str, Any] = dict(self._data)
        if other:
            data.update(other)
        data.update(kwargs)
        return Config(**data)

    def to_dict(self) -> Dict[str, Any]:
        return dict(self._data)

    def __repr__(self) -> str:
        body = ", ".join(f"{k}={v!r}" for k, v in sorted(self._data.items()))
        return f"Config({body})"

    # -- CLI ----------------------------------------------------------------
    def parse_args(self, argv: Optional[list[str]] = None) -> "Config":
        """Parse ``--key value`` flags typed from this config's defaults.

        Bools accept true/false; unknown flags are an error.  Returns a new
        merged Config (the analog of torch.CmdLine:parse, reference
        BiCNN/plaunch.lua:70).
        """
        parser = argparse.ArgumentParser()
        exposed = []
        for key, default in self._data.items():
            flag = "--" + key
            if isinstance(default, bool):
                parser.add_argument(flag, type=_parse_bool, default=default)
            elif default is None:
                parser.add_argument(flag, type=str, default=None)
            elif isinstance(default, (int, float, str)):
                parser.add_argument(flag, type=type(default), default=default)
            else:
                continue  # non-scalar defaults are not CLI-settable
            exposed.append(key)
        ns = parser.parse_args(argv)
        return self.merged({k: getattr(ns, k) for k in exposed})


def _parse_bool(text: str) -> bool:
    lowered = text.lower()
    if lowered in ("1", "true", "yes", "on"):
        return True
    if lowered in ("0", "false", "no", "off"):
        return False
    raise argparse.ArgumentTypeError(f"not a bool: {text!r}")
