"""Rank-prefixed structured logging.

The reference prints to stdout with hand-rolled rank prefixes everywhere
(reference asyncsgd/goot.lua:144-145, BiCNN/bicnn.lua:414-418).  Here one
logger factory gives every role-process a ``[role rank]``-prefixed logger
with levels, so launcher, server, client and tester output interleave
legibly in a multi-process run.
"""

from __future__ import annotations

import logging
import os
import sys

_FORMAT = "%(asctime)s %(name)s %(levelname).1s %(message)s"


def get_logger(role: str = "proc", rank: int | None = None) -> logging.Logger:
    name = f"mpit[{role}{'' if rank is None else f' {rank}'}]"
    logger = logging.getLogger(name)
    if not logger.handlers:
        # MPIT_LOG_STREAM=stderr keeps stdout machine-parseable for
        # callers whose contract is one JSON line there (bench.py).
        stream = (sys.stderr
                  if os.environ.get("MPIT_LOG_STREAM") == "stderr"
                  else sys.stdout)
        handler = logging.StreamHandler(stream)
        handler.setFormatter(logging.Formatter(_FORMAT, datefmt="%H:%M:%S"))
        logger.addHandler(handler)
        logger.propagate = False
        logger.setLevel(os.environ.get("MPIT_LOGLEVEL", "INFO").upper())
    return logger
