"""Object and array (de)serialization for the wire.

Analog of the reference's ``mpiT.serialize``/``deserialize`` via Torch
MemoryFile (reference init.lua:104-126).  Two tiers:

- **Arrays** travel as raw little-endian bytes with a tiny header (dtype,
  shape) — the hot path; payloads are written straight from device buffers
  (``np.asarray(jax_array)`` is zero-copy for host-resident committed data).
- **Pytrees / control objects** travel as header-tagged pickled payloads —
  only on cold control paths (init, config exchange), never per-step.
"""

from __future__ import annotations

import pickle
import struct
from typing import Any, Tuple

import numpy as np

_ARRAY_MAGIC = b"MTA1"  # mpit-tpu array v1
_OBJECT_MAGIC = b"MTO1"  # mpit-tpu object v1


def _dtype_name(dtype: np.dtype) -> str:
    # np.dtype.str loses identity for extension types (bfloat16/fp8 from
    # ml_dtypes map to '<V2'/'|V1'); the name round-trips via resolve_dtype.
    return dtype.name


def resolve_dtype(name) -> np.dtype:
    """np.dtype from a name, covering ml_dtypes extension types
    (bfloat16, fp8) that plain numpy doesn't know."""
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes  # ships with jax

        return np.dtype(getattr(ml_dtypes, name))





def encode_array(array: Any) -> bytes:
    """Array -> bytes.  Accepts numpy or JAX arrays (devices -> host copy)."""
    host = np.ascontiguousarray(np.asarray(array))
    dtype = _dtype_name(host.dtype).encode()  # e.g. b'float32', b'bfloat16'
    header = struct.pack("<4sB", _ARRAY_MAGIC, len(dtype)) + dtype
    header += struct.pack("<B", host.ndim)
    header += struct.pack(f"<{host.ndim}q", *host.shape)
    return header + host.tobytes()


def decode_array(blob: bytes | memoryview, out: np.ndarray | None = None) -> np.ndarray:
    """Bytes -> numpy array; fills ``out`` in place when given (zero-alloc path)."""
    view = memoryview(blob)
    magic, dlen = struct.unpack_from("<4sB", view, 0)
    if magic != _ARRAY_MAGIC:
        raise ValueError(f"bad array magic {magic!r}")
    offset = 5
    dtype = resolve_dtype(bytes(view[offset : offset + dlen]).decode())
    offset += dlen
    (ndim,) = struct.unpack_from("<B", view, offset)
    offset += 1
    shape: Tuple[int, ...] = struct.unpack_from(f"<{ndim}q", view, offset)
    offset += 8 * ndim
    flat = np.frombuffer(view, dtype=dtype, offset=offset)
    array = flat.reshape(shape)
    if out is not None:
        if out.shape != array.shape or out.dtype != array.dtype:
            raise ValueError(
                f"payload shape/dtype {array.shape}/{array.dtype} does not "
                f"match out buffer {out.shape}/{out.dtype}"
            )
        np.copyto(out, array)
        return out
    return array.copy()  # decouple from the transport buffer


def encode_object(obj: Any) -> bytes:
    return _OBJECT_MAGIC + pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)


def decode_object(blob: bytes | memoryview) -> Any:
    view = memoryview(blob)
    if bytes(view[:4]) != _OBJECT_MAGIC:
        raise ValueError("bad object magic")
    return pickle.loads(view[4:])


def encode(obj: Any) -> bytes:
    """Dispatch: arrays by value, everything else pickled."""
    if isinstance(obj, np.ndarray) or type(obj).__module__.startswith("jax"):
        return encode_array(obj)
    return encode_object(obj)


def decode(blob: bytes | memoryview) -> Any:
    head = bytes(memoryview(blob)[:4])
    if head == _ARRAY_MAGIC:
        return decode_array(blob)
    if head == _OBJECT_MAGIC:
        return decode_object(blob)
    raise ValueError(f"unknown payload magic {head!r}")
