"""Platform selection helper.

Some environments preload an accelerator plugin whose platform wins over
the ``JAX_PLATFORMS`` env var (observed with tunneled-TPU plugins); the
reliable override is the live config knob.  Call before any jax backend
use — process entry points (gang children, benchmark scripts, the graft
entry) all route through this.
"""

from __future__ import annotations

import os


def honor_jax_platforms() -> str | None:
    """Force the platform named by ``JAX_PLATFORMS`` (if set) through
    jax.config, returning it.  No-op when unset."""
    plat = os.environ.get("JAX_PLATFORMS")
    if plat:
        import jax

        jax.config.update("jax_platforms", plat)
    return plat or None
