"""Platform selection helper.

Some environments preload an accelerator plugin whose platform wins over
the ``JAX_PLATFORMS`` env var (observed with tunneled-TPU plugins); the
reliable override is the live config knob.  Call before any jax backend
use — process entry points (gang children, benchmark scripts, the graft
entry) all route through this.
"""

from __future__ import annotations

import os


def honor_jax_platforms() -> str | None:
    """Force the platform named by ``JAX_PLATFORMS`` (if set) through
    jax.config, returning it.  No-op when unset."""
    plat = os.environ.get("JAX_PLATFORMS")
    if plat:
        import jax

        jax.config.update("jax_platforms", plat)
    return plat or None


# -- virtual-CPU-mesh headroom ------------------------------------------------
#
# XLA:CPU sizes the PjRt client's execution thread pool to the virtual
# device count (``--xla_force_host_platform_device_count``).  A program
# sharded over *every* virtual device needs one pool thread per partition
# simultaneously; when any pool thread is busy with other client work, one
# partition never starts, every other partition blocks inside the
# cross-device collective rendezvous, and after a 40 s timeout XLA calls
# ``LOG(FATAL)`` -> ``Fatal Python error: Aborted`` (xla rendezvous.cc:127,
# ``InProcessCommunicator::AllReduce``).  Observed ~1 in 500 executions of
# an 8-way-sharded all-reduce program on an 8-device pool; zero in >10^4
# executions once the pool exceeds the mesh.  See
# docs/xla_cpu_rendezvous_abort.md for the full investigation.
#
# Workaround convention: register more virtual devices than any mesh uses,
# and have mesh builders draw from ``default_devices()`` (the first
# ``MPIT_MESH_DEVICES`` devices) rather than ``jax.devices()``.

CPU_POOL_HEADROOM = 4


def ensure_cpu_device_headroom(n_mesh_devices: int, extra: int = CPU_POOL_HEADROOM) -> None:
    """Append a ``--xla_force_host_platform_device_count`` override so the
    host-CPU platform exposes ``n_mesh_devices + extra`` virtual devices
    (the later duplicate flag wins), and pin ``MPIT_MESH_DEVICES`` so mesh
    builders keep using only ``n_mesh_devices``.

    Must run before the jax backend initializes; harmless (ignored by
    XLA) afterwards.  Both knobs only ever affect the host-CPU platform:
    the XLA flag is ignored by accelerator backends, and
    :func:`default_devices` applies the ``MPIT_MESH_DEVICES`` cap only
    when the resolved device pool is CPU — so calling this on a real-TPU
    host cannot shrink the accelerator mesh.
    """
    flags = os.environ.get("XLA_FLAGS", "")
    os.environ["XLA_FLAGS"] = (
        flags + f" --xla_force_host_platform_device_count={n_mesh_devices + extra}"
    ).strip()
    os.environ["MPIT_MESH_DEVICES"] = str(n_mesh_devices)


def enable_compile_cache(path: str | None = None) -> str:
    """Point jax at a persistent compilation cache and drop the size/time
    thresholds so every program is cached.

    Motivation: on the tunneled-TPU platform a cold jit of the flagship
    trainer costs ~13 s of the north-star's wall-clock-to-target; a warm
    persistent cache turns that into ~0.3 s of deserialization (measured:
    9.15 s -> 0.35 s for a first jit call in a fresh process).  Safe to
    call any time before the first compile; idempotent.

    Resolution order: explicit ``path`` > ``MPIT_COMPILE_CACHE`` env >
    ``.jax_cache/`` next to the repo root (derived from this package's
    location).  Returns the directory used.
    """
    import pathlib

    import jax

    cache = (path or os.environ.get("MPIT_COMPILE_CACHE")
             or str(pathlib.Path(__file__).resolve().parents[2] / ".jax_cache"))
    jax.config.update("jax_compilation_cache_dir", cache)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    # Eviction is DISABLED by default (-1).  jax's LRU eviction keeps a
    # per-entry ``*-atime`` sentinel and, on every put, stats the whole
    # directory — any entry written by a process that ran with eviction
    # off (jax's own default) has no sentinel, which makes every
    # subsequent eviction-enabled put fail with a FileNotFoundError
    # warning; concurrent writers (gang children, pytest) race the same
    # way.  Measured growth is ~7 MB/round, so an unbounded cache is the
    # cheaper contract.  Set ``MPIT_COMPILE_CACHE_MAX`` (bytes) to opt
    # back into a cap; missing sentinels are healed first so the put
    # path cannot warn about pre-existing orphans.
    max_size = int(os.environ.get("MPIT_COMPILE_CACHE_MAX", "-1"))
    jax.config.update("jax_compilation_cache_max_size", max_size)
    if max_size != -1:
        import time

        stamp = time.time_ns().to_bytes(8, "little")
        for entry in pathlib.Path(cache).glob("*-cache"):
            sentinel = entry.with_name(
                entry.name.removesuffix("-cache") + "-atime")
            if not sentinel.exists():
                sentinel.write_bytes(stamp)
    return cache


def default_devices():
    """The device pool meshes should span: the first ``MPIT_MESH_DEVICES``
    of ``jax.devices()`` when that env var is set *and* the pool is the
    host-CPU platform (the headroom convention above only ever registers
    extra CPU devices), else all devices — a stale cap can never shrink a
    real accelerator mesh."""
    import jax

    devs = jax.devices()
    cap = os.environ.get("MPIT_MESH_DEVICES")
    if cap and devs and devs[0].platform == "cpu":
        devs = devs[: int(cap)]
    return devs
