"""Honest device timing under async dispatch — and under runtimes where
``block_until_ready`` lies.

jax dispatch is async, so the standard recipe is "loop N dispatches, then
``block_until_ready``".  On tunneled device platforms (e.g. the axon TPU
proxy) that recipe silently breaks: ``block_until_ready`` returns before
the device has executed anything, producing impossible numbers (a 160 MB
elementwise sweep "measured" at 14 TB/s; an 8k matmul at 16 PFLOP/s).
What *cannot* lie is a host fetch of result data — the value can only be
served after the work that produces it has run, and devices execute their
queue in order, so fetching one element of the last result fences the
whole loop.

The remaining distortion is the fixed dispatch+fetch round-trip latency
(~100 ms through a tunnel).  :func:`timed_per_call` cancels it by timing
two loop lengths and differencing:

    t(n) = overhead + n * per_call   =>   per_call = (t(b+n) - t(b)) / n

Verified on the tunneled v5e: an 8192^3 bf16 matmul measures 6.05 ms
per call = 181.7 TFLOP/s = 92% of the chip's 197 TFLOP/s peak, where the
block_until_ready recipe reported 0.07 ms.
"""

from __future__ import annotations

import time
from typing import Any, Callable

import numpy as np


def fetch_scalar(out: Any) -> float:
    """Force completion of everything queued before ``out`` by fetching a
    single element of its first array leaf to the host."""
    import jax

    leaf = jax.tree_util.tree_leaves(out)[0]
    return float(np.asarray(leaf[(0,) * getattr(leaf, "ndim", 0)]))


# Smallest per-call time the estimator will ever report.  A differenced
# estimate at or below zero means the extra iterations were lost in
# timer/scheduler noise; reporting a strictly-positive floor keeps
# machine-read JSON out of the nonsensical "0.0 ms" / negative regime.
MIN_RESOLVABLE_S = 1e-9


def _auto_scaled_estimate(
    measure: Callable[[int], tuple[list, list]],
    iters: int,
    auto_scale: bool,
    max_iters: int,
    min_ratio: float,
) -> float:
    """Shared escalation loop of both timing helpers.  ``measure(iters)``
    returns (small-leg times, big-leg times); the per-call estimate is
    the difference of the per-leg minima, and ``iters`` doubles until
    that difference clears ``min_ratio`` x the observed per-leg jitter
    (or ``max_iters``).  Floored at :data:`MIN_RESOLVABLE_S`."""
    while True:
        smalls, bigs = measure(iters)
        delta = min(bigs) - min(smalls)
        jitter = max(max(smalls) - min(smalls), max(bigs) - min(bigs))
        if (not auto_scale or delta > min_ratio * jitter
                or iters * 2 > max_iters):
            return max(delta, MIN_RESOLVABLE_S * iters) / iters
        iters *= 2


def timed_per_call(
    fn: Callable[..., Any],
    *args: Any,
    iters: int = 10,
    base_iters: int = 1,
    repeats: int = 3,
    auto_scale: bool = False,
    max_iters: int = 2000,
    min_ratio: float = 1.0,
) -> float:
    """Seconds per call of ``fn(*args)`` on device, latency-cancelled.

    ``fn`` is called with the same arguments every iteration; results are
    discarded (the runtime still executes every queued call — the final
    fetch fences them all).  Each leg is measured ``repeats`` times and
    the difference is taken between the per-leg minima: jitter is
    additive-positive, so min() per leg filters it, whereas min over
    *differences* would lock in exactly the repeat whose short leg
    caught a spike (an overestimate of speed).

    With ``auto_scale``, when the big-leg/small-leg difference does not
    exceed the observed per-leg jitter (sub-resolution: the measured op
    is too fast for ``iters`` at the current load), ``iters`` doubles and
    the measurement reruns, up to ``max_iters`` — fast ops on a loaded
    host otherwise difference two minima into a ≤0 estimate.  The result
    is always floored at :data:`MIN_RESOLVABLE_S`.

    ``min_ratio`` sharpens the stop rule: ``delta > min_ratio * jitter``.
    The default (1) only guarantees signal exceeds noise — up to ~100%
    relative error.  Callers that publish the number should pass 5-10:
    the relative error is bounded by roughly ``jitter/delta <
    1/min_ratio`` (measured on the tunnel: min_ratio=1 let one rep of a
    ~2.9 ms op read 1.7x fast; min_ratio=8 held reps within a few %).
    """
    fetch_scalar(fn(*args))  # compile + warm

    def run(n: int) -> float:
        t0 = time.perf_counter()
        out = None
        for _ in range(n):
            out = fn(*args)
        fetch_scalar(out)
        return time.perf_counter() - t0

    def measure(n: int):
        # the small leg is deliberately re-measured every escalation
        # round: its minimum and spread anchor the jitter estimate, and
        # host load drifts over the seconds an escalated measurement
        # takes — stale smalls would difference against old conditions.
        smalls = [run(base_iters) for _ in range(repeats)]
        bigs = [run(base_iters + n) for _ in range(repeats)]
        return smalls, bigs

    return _auto_scaled_estimate(measure, iters, auto_scale, max_iters,
                                 min_ratio)


def timed_chained(
    fn: Callable[..., Any],
    state: Any,
    *args: Any,
    iters: int = 10,
    base_iters: int = 1,
    repeats: int = 3,
    auto_scale: bool = False,
    max_iters: int = 2000,
    min_ratio: float = 1.0,
) -> float:
    """Like :func:`timed_per_call` for state-threading calls:
    ``state = fn(state, *args)`` each iteration.  This is the honest way
    to time donated/in-place update kernels — calling them repeatedly on
    the *same* buffers would either fault (donated input reuse) or force
    the runtime to insert defensive copies that a real training loop
    never pays.  Per-leg minima and ``auto_scale`` semantics as in
    :func:`timed_per_call` (state keeps threading through escalation
    rounds — fine for update steps, whose cost is state-independent)."""
    state = fn(state, *args)  # compile + warm
    fetch_scalar(state)

    def run(n: int, st: Any) -> tuple[float, Any]:
        t0 = time.perf_counter()
        for _ in range(n):
            st = fn(st, *args)
        fetch_scalar(st)
        return time.perf_counter() - t0, st

    st = [state]  # threaded through every leg across escalation rounds

    def measure(n: int):
        smalls, bigs = [], []
        for _ in range(repeats):
            t_small, st[0] = run(base_iters, st[0])
            smalls.append(t_small)
            t_big, st[0] = run(base_iters + n, st[0])
            bigs.append(t_big)
        return smalls, bigs

    return _auto_scaled_estimate(measure, iters, auto_scale, max_iters,
                                 min_ratio)
