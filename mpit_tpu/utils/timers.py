"""Back-compat shim — the phase timers moved to :mod:`mpit_tpu.obs.timers`
when observability unified under ``mpit_tpu.obs`` (registry, op spans,
Chrome-trace export).  Import from ``mpit_tpu.obs`` in new code."""

from mpit_tpu.obs.timers import (  # noqa: F401
    PhaseTimers,
    profiler_trace,
    trace_annotation,
)

__all__ = ["PhaseTimers", "profiler_trace", "trace_annotation"]
