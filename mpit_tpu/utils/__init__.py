"""Shared utilities: typed config, logging, serialization.  The phase
timers live in :mod:`mpit_tpu.obs` now; re-exported here for back-compat."""

from mpit_tpu.obs.timers import PhaseTimers, profiler_trace, trace_annotation
from mpit_tpu.utils.config import Config

__all__ = ["Config", "PhaseTimers", "profiler_trace", "trace_annotation"]
