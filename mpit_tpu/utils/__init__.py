"""Shared utilities: typed config, phase timers, logging, serialization."""

from mpit_tpu.utils.config import Config
from mpit_tpu.utils.timers import PhaseTimers, profiler_trace, trace_annotation

__all__ = ["Config", "PhaseTimers", "profiler_trace", "trace_annotation"]
