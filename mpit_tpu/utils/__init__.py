"""Shared utilities: typed config, phase timers, logging, serialization."""

from mpit_tpu.utils.config import Config
from mpit_tpu.utils.timers import PhaseTimers

__all__ = ["Config", "PhaseTimers"]
