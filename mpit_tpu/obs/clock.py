"""Cross-rank clock alignment — the causal-tracing time base.

Per-rank traces are wall-anchored (monotonic span times shifted by a
captured wall offset), which is good enough to *display* two ranks side
by side but not to *subtract* their timestamps: host NTP skew of a few
milliseconds swamps the sub-millisecond wire/queue phases the latency
decomposition (obs/causal.py) wants to attribute.  This module owns the
fix, in two halves:

- **One time base per process.**  :func:`epoch_offset` captures the
  monotonic→wall offset exactly once at import; :func:`wall_us` stamps
  wall-clock microseconds derived from it.  The span recorder, the
  flight recorder and the FLAG_TIMING wire stamps all use *this* offset,
  so every timestamp a process emits — trace events, flight dumps, ack
  tails — lives on a single self-consistent timeline (two independent
  ``time.time() - time.monotonic()`` captures can disagree by the NTP
  slew between them).

- **A per-peer offset estimator** (:class:`ClockEstimator`), NTP-style:
  every FLAG_TIMING exchange yields the classic four marks
  ``(t1, t2, t3, t4)`` — client send, server receive, server ack-send,
  client ack-receive — from which ``offset = ((t2-t1)+(t3-t4))/2`` and
  ``rtt = (t4-t1)-(t3-t2)``.  The true offset provably lies within
  ``offset ± rtt/2``, so the estimator keeps the **minimum-RTT**
  exchange (Cristian's algorithm), aging the stored sample by a drift
  allowance so a stale best eventually yields to fresher ones.  Samples
  arrive from every op ack and from the heartbeat echo stream, so the
  estimate refreshes even while a client is compute-bound.

Estimators register themselves here by name; the trace exporter embeds
:func:`snapshot_all` into ``otherData.clock`` and flight dumps carry it
too, so the offline joiner can align ranks without re-deriving offsets
(it still can, from joined span pairs, when a trace predates the wire
extension — see obs/causal.py).

Everything is stdlib, allocation-light, and independent of obs
enablement: FLAG_TIMING is a *wire* feature, negotiated per pair, and
the estimator must run (cheaply) even when the registry is off.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, Optional, Tuple

#: monotonic → wall offset, captured exactly once per process (see
#: module docstring: one time base for traces, dumps and wire stamps).
_EPOCH_OFFSET = time.time() - time.monotonic()

#: drift allowance for aging the stored minimum-RTT sample: a retained
#: best exchange's effective RTT grows by this many microseconds per
#: second of age (100 ppm — generous for quartz, conservative for NTP-
#: disciplined hosts), so a fresher, slightly-slower exchange eventually
#: replaces a stale fast one and the estimate tracks clock drift.
DRIFT_US_PER_S = 100.0


def epoch_offset() -> float:
    """The process's one monotonic→wall offset (seconds)."""
    return _EPOCH_OFFSET


def wall_us() -> int:
    """Wall-clock microseconds on the process time base — the stamp the
    FLAG_TIMING wire carries (int64-friendly)."""
    return int((time.monotonic() + _EPOCH_OFFSET) * 1e6)


class PeerClock:
    """Offset estimate against one peer, from minimum-RTT exchanges.

    ``offset_us`` is **peer clock minus local clock**: a peer timestamp
    maps onto the local timeline as ``t_local = t_peer - offset_us``.
    ``uncertainty_us`` is the rtt/2 bound of the exchange the estimate
    came from."""

    __slots__ = ("offset_us", "uncertainty_us", "rtt_us", "samples",
                 "accepted", "_best_t4_us")

    def __init__(self) -> None:
        self.offset_us = 0.0
        self.uncertainty_us = float("inf")
        self.rtt_us = float("inf")
        self.samples = 0
        self.accepted = 0
        self._best_t4_us = 0.0

    def add(self, t1_us: float, t2_us: float, t3_us: float,
            t4_us: float) -> bool:
        """One exchange: local send, peer recv, peer reply-send, local
        reply-recv.  Returns True when it became the new best estimate.
        Garbage (non-positive RTT: a stamp from a different attempt, a
        stepped clock) is counted and dropped — the min-RTT filter's
        whole job is that bad samples only ever look *slow*."""
        self.samples += 1
        rtt = (t4_us - t1_us) - (t3_us - t2_us)
        if rtt <= 0 or t4_us < t1_us:
            return False
        aged = self.rtt_us + DRIFT_US_PER_S * max(
            (t4_us - self._best_t4_us) / 1e6, 0.0)
        if rtt >= aged:
            return False
        self.offset_us = ((t2_us - t1_us) + (t3_us - t4_us)) / 2.0
        self.rtt_us = rtt
        self.uncertainty_us = rtt / 2.0
        self._best_t4_us = t4_us
        self.accepted += 1
        return True

    def snapshot(self) -> Dict[str, float]:
        return {
            "offset_us": self.offset_us,
            "uncertainty_us": self.uncertainty_us,
            "rtt_us": self.rtt_us,
            "samples": self.samples,
            "accepted": self.accepted,
        }


class ClockEstimator:
    """Per-peer :class:`PeerClock` map for one role endpoint (a client
    holds one, keyed by server rank).  Thread-compatible the same way
    the metrics instruments are: updates are plain attribute writes
    from one role thread; snapshots from the introspection thread read
    a consistent-enough view."""

    def __init__(self) -> None:
        self.peers: Dict[int, PeerClock] = {}

    def peer(self, peer: int) -> PeerClock:
        clock = self.peers.get(peer)
        if clock is None:
            clock = self.peers[peer] = PeerClock()
        return clock

    def add_exchange(self, peer: int, t1_us: float, t2_us: float,
                     t3_us: float, t4_us: float) -> bool:
        return self.peer(peer).add(t1_us, t2_us, t3_us, t4_us)

    def offset_us(self, peer: int) -> Optional[Tuple[float, float]]:
        """(offset, uncertainty) in µs for ``peer``, or None before the
        first accepted exchange."""
        clock = self.peers.get(peer)
        if clock is None or not clock.accepted:
            return None
        return clock.offset_us, clock.uncertainty_us

    def snapshot(self) -> Dict[str, Dict[str, float]]:
        return {str(p): c.snapshot() for p, c in sorted(self.peers.items())
                if c.samples}


#: process-level estimator directory: name (e.g. "client3") -> estimator.
#: The trace exporter and flight dumps embed snapshot_all(); registration
#: is unconditional (a dict put) because FLAG_TIMING is a wire feature,
#: not an obs feature.
_ESTIMATORS: Dict[str, ClockEstimator] = {}
_LOCK = threading.Lock()


def register(name: str, estimator: ClockEstimator) -> None:
    """Publish an endpoint's estimator under ``name`` (re-registering
    replaces — a rejoined incarnation supersedes its old clocks)."""
    with _LOCK:
        _ESTIMATORS[name] = estimator


def snapshot_all() -> Dict[str, Dict[str, Dict[str, float]]]:
    """name -> peer -> estimate, for every registered estimator that
    has seen at least one sample (empty estimators are dropped so an
    untimed gang adds nothing to its trace)."""
    with _LOCK:
        items = list(_ESTIMATORS.items())
    out = {}
    for name, est in items:
        snap = est.snapshot()
        if snap:
            out[name] = snap
    return out


def reset() -> None:
    """Drop registered estimators (tests; via obs.configure)."""
    with _LOCK:
        _ESTIMATORS.clear()
