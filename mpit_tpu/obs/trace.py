"""Chrome trace-event export — spans + task lifecycles on one timeline.

Output is the Chrome trace-event *JSON Object Format*
(``{"traceEvents": [...], "displayTimeUnit": "ms", "otherData": {...}}``),
readable by Perfetto (https://ui.perfetto.dev) and chrome://tracing:

- one **pid per rank** (process metadata names it ``rank N <role>``);
- one **tid per op channel / task** (thread metadata carries the
  channel name, e.g. ``client:0:GRAD`` or ``task:recv_grad:2.g0``);
- op spans emit a ``B``/``E`` pair (begin args carry the op identity —
  peer, epoch, seq; end args carry the outcome and retry count) with
  their phases as nested ``X`` complete events (``GRAD.encode``,
  ``GRAD.send``, ...); task lifecycles emit one ``X`` each;
- timestamps are wall-clock microseconds (monotonic span times shifted
  by the recorder's captured epoch offset), so per-rank part files
  merge onto a single timeline, and a concurrently captured
  ``jax.profiler`` trace (also wall-anchored) lines up beside it.

Flow: each rank writes ``$MPIT_OBS_TRACE.rank<N>.json`` at exit
(:func:`maybe_write_rank_trace`, called from the launch child mains);
the gang parent merges the parts into ``$MPIT_OBS_TRACE``
(:func:`maybe_merge_rank_traces`).  ``python -m mpit_tpu.obs.trace
<file>`` validates a trace (well-formed events, balanced begin/end
pairs) — the CI smoke job gates on it.
"""

from __future__ import annotations

import glob as _glob
import json
import os
import sys
from typing import Dict, List, Optional

from mpit_tpu.obs import clock as _clock
from mpit_tpu.obs import metrics as _metrics
from mpit_tpu.obs import profile as _profile
from mpit_tpu.obs import spans as _spans

ENV = _metrics.TRACE_ENV  # MPIT_OBS_TRACE


def chrome_events(recorder, pid: int, label: str = "",
                  profiler=None) -> List[dict]:
    """Flatten one recorder (plus the profiler's counter-track samples,
    when profiling ran — obs/profile.py) into trace events for process
    ``pid``."""
    events: List[dict] = [{
        "ph": "M", "name": "process_name", "pid": pid, "tid": 0,
        "args": {"name": label or f"rank {pid}"},
    }]
    tids: Dict[str, int] = {}

    def tid_of(name: str) -> int:
        t = tids.get(name)
        if t is None:
            t = tids[name] = len(tids) + 1
            events.append({
                "ph": "M", "name": "thread_name", "pid": pid, "tid": t,
                "args": {"name": name},
            })
        return t

    off = recorder.epoch_offset

    def us(t: float) -> float:
        return (t + off) * 1e6

    for sp in list(recorder.spans):
        t = tid_of(sp.tid)
        events.append({
            "ph": "B", "name": sp.name, "cat": "ps_op", "pid": pid,
            "tid": t, "ts": us(sp.t0),
            "args": {k: v for k, v in sp.args.items()},
        })
        marks = sp.marks
        # CPU attribution rider: when the span stamped the CPU clock
        # alongside its wall marks (profiling on), each phase X event
        # carries its on-CPU share and the E carries the span total.
        cpu_stamps = None
        if sp.cpu0 is not None and len(sp.cpu_marks) == len(marks):
            cpu_stamps = list(sp.cpu_marks) + [sp.cpu1]
        for i, (phase, mt) in enumerate(marks):
            end = marks[i + 1][1] if i + 1 < len(marks) else sp.t1
            ev = {
                "ph": "X", "name": f"{sp.name}.{phase}", "cat": "ps_phase",
                "pid": pid, "tid": t, "ts": us(mt),
                "dur": max((end - mt) * 1e6, 0.0),
            }
            if cpu_stamps is not None:
                ev["args"] = {"cpu_us": max(
                    (cpu_stamps[i + 1] - cpu_stamps[i]) * 1e6, 0.0)}
            events.append(ev)
        end_args: Dict[str, object] = {"outcome": sp.outcome}
        if sp.cpu_us is not None:
            end_args["cpu_us"] = sp.cpu_us
        events.append({
            "ph": "E", "name": sp.name, "cat": "ps_op", "pid": pid,
            "tid": t, "ts": us(sp.t1), "args": end_args,
        })
    for name, t0, t1, state, cpu_us in list(recorder.tasks):
        args: Dict[str, object] = {"state": state}
        if cpu_us:
            args["cpu_us"] = cpu_us
        events.append({
            "ph": "X", "name": name, "cat": "task", "pid": pid,
            "tid": tid_of(f"task:{name}"), "ts": us(t0),
            "dur": max((t1 - t0) * 1e6, 0.0), "args": args,
        })
    # Counter tracks (ph:"C"): the profiler's sampled pool/scheduler
    # utilization series.  Chrome keys counters by (pid, name), so the
    # same four track names stay distinct per rank after a merge.
    prof = profiler if profiler is not None else _profile.get_profiler()
    for ts_mono, track, value in list(prof.samples):
        events.append({
            "ph": "C", "name": track, "cat": "resource", "pid": pid,
            "tid": 0, "ts": us(ts_mono), "args": {"value": value},
        })
    # Stable sort on ts only: a span's B was appended before its E, so
    # equal timestamps (zero-length spans) keep begin-before-end order.
    events.sort(key=lambda e: e.get("ts", -1.0))
    return events


def write_rank_trace(path: str, rank: int, role: str = "",
                     recorder=None, registry=None) -> str:
    """Dump this process's spans + tasks (+ a metrics snapshot rider in
    ``otherData``) as one rank's trace file."""
    rec = recorder if recorder is not None else _spans.get_recorder()
    reg = registry if registry is not None else _metrics.get_registry()
    label = f"rank {rank}" + (f" ({role})" if role else "")
    obj = {
        "traceEvents": chrome_events(rec, pid=rank, label=label),
        "displayTimeUnit": "ms",
        "otherData": {
            "ranks": {str(rank): {"role": role, "metrics": reg.snapshot()}},
            # Per-peer clock-offset estimates (obs/clock.py): the causal
            # joiner aligns ranks from these instead of re-deriving
            # offsets from span pairs (obs/causal.py).
            "clock": _clock.snapshot_all(),
        },
    }
    with open(path, "w") as fh:
        json.dump(obj, fh)
    return path


def part_path(base: str, rank: int) -> str:
    return f"{base}.rank{rank}.json"


def maybe_write_rank_trace(rank: int, role: str = "") -> Optional[str]:
    """When ``MPIT_OBS_TRACE`` is set, write this rank's part file next
    to the requested path; the gang parent merges at exit."""
    base = os.environ.get(ENV, "")
    if not base:
        return None
    return write_rank_trace(part_path(base, rank), rank, role)


def merge_traces(out_path: str, parts: List[str]) -> int:
    """Concatenate per-rank part files (each already stamped with its
    own pid) into one merged trace; returns the merged event count."""
    events: List[dict] = []
    ranks: Dict[str, dict] = {}
    clock: Dict[str, dict] = {}
    for p in parts:
        with open(p) as fh:
            obj = json.load(fh)
        events.extend(obj.get("traceEvents", []))
        other = obj.get("otherData") or {}
        ranks.update(other.get("ranks", {}))
        clock.update(other.get("clock", {}))
    events.sort(key=lambda e: e.get("ts", -1.0))
    with open(out_path, "w") as fh:
        json.dump({"traceEvents": events, "displayTimeUnit": "ms",
                   "otherData": {"ranks": ranks, "clock": clock}}, fh)
    return len(events)


def maybe_merge_rank_traces(cleanup: bool = True) -> Optional[str]:
    """Gang-parent exit hook: merge every ``$MPIT_OBS_TRACE.rank*.json``
    part into ``$MPIT_OBS_TRACE`` (no-op when unset or no parts — e.g.
    a child crashed before its dump; parts are kept on failure paths
    because the launcher only merges after a clean gang)."""
    base = os.environ.get(ENV, "")
    if not base:
        return None
    parts = sorted(_glob.glob(f"{base}.rank*.json"))
    if not parts:
        return None
    merge_traces(base, parts)
    if cleanup:
        for p in parts:
            try:
                os.remove(p)
            except OSError:
                pass
    return base


def validate_trace(path_or_obj) -> Dict[str, object]:
    """Structural validation: the file parses, events are well-formed
    Chrome trace format (ph/name/pid/tid, numeric ts on non-metadata
    events, non-negative dur on X), and B/E pairs balance per
    (pid, tid) with matching names.  Returns summary stats; raises
    ``ValueError`` on any violation."""
    if isinstance(path_or_obj, (str, os.PathLike)):
        with open(path_or_obj) as fh:
            obj = json.load(fh)
    else:
        obj = path_or_obj
    if isinstance(obj, list):
        events = obj
    elif isinstance(obj, dict) and isinstance(obj.get("traceEvents"), list):
        events = obj["traceEvents"]
    else:
        raise ValueError("trace is neither an event array nor an object "
                         "with a traceEvents list")
    stacks: Dict[tuple, List[str]] = {}
    pids, ops, tasks, counters = set(), 0, 0, 0
    for i, ev in enumerate(events):
        if not isinstance(ev, dict):
            raise ValueError(f"event {i} is not an object")
        missing = {"ph", "name", "pid", "tid"} - set(ev)
        if missing:
            raise ValueError(f"event {i} missing {sorted(missing)}")
        ph = ev["ph"]
        pids.add(ev["pid"])
        if ph != "M" and not isinstance(ev.get("ts"), (int, float)):
            raise ValueError(f"event {i} ({ev['name']!r}) has no numeric ts")
        if ph == "C":
            args = ev.get("args")
            if not isinstance(args, dict) or not isinstance(
                    args.get("value"), (int, float)):
                raise ValueError(
                    f"event {i} ({ev['name']!r}) C without numeric "
                    "args.value")
            counters += 1
        elif ph == "X":
            if not isinstance(ev.get("dur"), (int, float)) or ev["dur"] < 0:
                raise ValueError(
                    f"event {i} ({ev['name']!r}) X without dur >= 0")
            if ev.get("cat") == "task":
                tasks += 1
        elif ph == "B":
            stacks.setdefault((ev["pid"], ev["tid"]), []).append(ev["name"])
            ops += 1
        elif ph == "E":
            stack = stacks.setdefault((ev["pid"], ev["tid"]), [])
            if not stack:
                raise ValueError(
                    f"event {i}: E {ev['name']!r} with no open B on "
                    f"(pid={ev['pid']}, tid={ev['tid']})")
            top = stack.pop()
            if top != ev["name"]:
                raise ValueError(
                    f"event {i}: E {ev['name']!r} closes B {top!r} on "
                    f"(pid={ev['pid']}, tid={ev['tid']})")
    unbalanced = {k: v for k, v in stacks.items() if v}
    if unbalanced:
        raise ValueError(f"unclosed B spans at EOF: {unbalanced}")
    return {"events": len(events), "pids": len(pids), "ops": ops,
            "tasks": tasks, "counters": counters}


def main(argv: Optional[List[str]] = None) -> int:
    """``python -m mpit_tpu.obs.trace <file...>`` — validate traces."""
    argv = list(sys.argv[1:] if argv is None else argv)
    if not argv:
        print("usage: python -m mpit_tpu.obs.trace <trace.json>...",
              file=sys.stderr)
        return 2
    rc = 0
    for path in argv:
        try:
            stats = validate_trace(path)
        except (OSError, ValueError) as exc:
            print(f"{path}: INVALID: {exc}", file=sys.stderr)
            rc = 1
            continue
        print(f"{path}: ok — {stats['events']} events, "
              f"{stats['pids']} rank(s), {stats['ops']} op span(s), "
              f"{stats['tasks']} task(s), "
              f"{stats['counters']} counter sample(s)")
    return rc


if __name__ == "__main__":
    sys.exit(main())
