"""Op spans and task lifecycles — who spent how long in which phase.

A counter says *how many* retries happened; a span says *which op*
retried, against *which peer*, and where its time went.  Two record
kinds:

- **Op spans** (:class:`OpSpan`): one per PS op.  Created when the op
  starts processing, phase-marked at each transition (client:
  ``encode`` → ``send`` → ``ack``, with ``backoff``/``send``/``ack``
  repeating per retry attempt; server: ``apply`` → ``ack``), annotated
  with the op's wire identity (peer, ``[epoch, seq]``) and closed with
  an outcome (``ok`` / ``applied`` / ``dup`` / ``stale`` / ``aborted``
  / ``exhausted``).  Closing also feeds the ``mpit_ps_op_seconds``
  histogram, so the metrics and the trace always agree.
- **Task lifecycles**: the cooperative scheduler records each task's
  spawn→completion window and terminal state — service loops, pumps,
  and reapers show up as rows in the exported trace.

The recorder owns every clock read.  Role files (``ps/``, ``ft/``,
``comm/``) never call ``time.monotonic()`` to measure — the MT-O4xx
lint family enforces it — so a disabled recorder (the default) means
zero clock reads on the hot path: :data:`NULL_SPAN` and
:data:`NULL_RECORDER` are shared do-nothing objects.

Cross-process alignment: spans are recorded on the monotonic clock, and
the recorder captures a wall-clock offset at construction; the trace
exporter adds it so per-rank files merge onto one timeline (host NTP
skew applies, which is fine at the phase granularity traced here).
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional, Tuple

from mpit_tpu.obs import clock as _clock
from mpit_tpu.obs import flight as _flight
from mpit_tpu.obs import metrics as _metrics
from mpit_tpu.obs import profile as _profile


class NullSpan:
    """Shared no-op span — the disabled path's op object."""

    __slots__ = ()

    def mark(self, phase: str) -> None:
        pass

    def note(self, **kw) -> None:
        pass

    def end(self, outcome: str = "ok", **kw) -> None:
        pass


NULL_SPAN = NullSpan()


class OpSpan:
    __slots__ = ("_rec", "name", "tid", "t0", "t1", "marks", "args",
                 "outcome", "cpu0", "cpu1", "cpu_marks", "cpu_us")

    def __init__(self, rec: "SpanRecorder", name: str, tid: str,
                 args: Dict[str, object]):
        self._rec = rec
        self.name = name
        self.tid = tid
        self.t0 = time.monotonic()
        self.t1: Optional[float] = None
        self.marks: List[Tuple[str, float]] = []
        self.args = args
        self.outcome = ""
        # CPU attribution (obs/profile.py): when profiling is enabled
        # the span stamps the stepping thread's CPU clock alongside
        # every wall stamp, so the exporter can split each phase into
        # on-cpu vs off-cpu.  Off (cpu0 None): zero extra clock reads.
        self.cpu0: Optional[float] = (
            rec._prof.cpu_now() if rec._prof.enabled else None)
        self.cpu1: float = 0.0
        self.cpu_marks: List[float] = []
        self.cpu_us: Optional[float] = None

    def mark(self, phase: str) -> None:
        """Phase ``phase`` begins now (it runs until the next mark or
        the end of the span)."""
        self.marks.append((phase, time.monotonic()))
        if self.cpu0 is not None:
            self.cpu_marks.append(self._rec._prof.cpu_now())

    def note(self, **kw) -> None:
        """Attach args discovered mid-op (e.g. seq assigned after the
        encode, retry counts)."""
        self.args.update(kw)

    def end(self, outcome: str = "ok", **kw) -> None:
        if self.t1 is not None:
            return  # idempotent: error paths may end defensively
        self.t1 = time.monotonic()
        if self.cpu0 is not None:
            self.cpu1 = self._rec._prof.cpu_now()
            self.cpu_us = max((self.cpu1 - self.cpu0) * 1e6, 0.0)
        self.outcome = outcome
        if kw:
            self.args.update(kw)
        self._rec._finish(self)


class SpanRecorder:
    """Process-local span sink (one per process; role threads share it —
    appends are GIL-atomic and records are immutable once finished)."""

    enabled = True

    def __init__(self, registry=None):
        self.registry = registry if registry is not None \
            else _metrics.get_registry()
        self.spans: List[OpSpan] = []
        #: (name, t0, t1, state, cpu_us) — cpu_us is 0.0 unless the
        #: profiler was live (obs/profile.py) and the scheduler fed
        #: the task's accumulated thread-time through task_end.
        self.tasks: List[Tuple[str, float, float, str, float]] = []
        #: the CPU clock source for op spans — the null profiler when
        #: profiling is off, so spans stamp no thread-time by default.
        self._prof = _profile.get_profiler()
        #: monotonic -> wall offset for cross-rank trace merging — the
        #: process-wide time base (obs/clock.py), shared with the flight
        #: recorder and the FLAG_TIMING wire stamps so every timestamp
        #: this process emits subtracts cleanly against the others.
        self.epoch_offset = _clock.epoch_offset()
        self.flight = _flight.get_flight()
        self._hist_lock = threading.Lock()
        self._hists: Dict[Tuple[str, str], object] = {}
        #: spans begun but not yet ended — the live in-flight op table
        #: served by the /status introspection endpoint (obs/statusd.py)
        #: and attached to flight-recorder dumps.
        self._open: Dict[int, OpSpan] = {}

    def op(self, name: str, peer: object = "?", side: str = "client",
           **args) -> OpSpan:
        """Begin an op span.  ``tid`` groups ops into trace rows — one
        per (role rank, side, peer, tag) channel, which the protocol
        already keeps strictly sequential (client pump FIFO, per-channel
        server loops), so begin/end events nest cleanly.  The role's own
        rank (``rank=`` arg) is part of the channel id: in a
        single-process multi-role gang (thread tests, np=1) two servers
        otherwise share e.g. ``server:2:GRAD`` and their interleaved
        B/E events scramble the channel."""
        args["peer"] = peer
        args["side"] = side
        rank = args.get("rank")
        prefix = f"r{rank}:" if rank is not None else ""
        span = OpSpan(self, name, f"{prefix}{side}:{peer}:{name}", args)
        self._open[id(span)] = span
        return span

    def open_ops(self) -> List[Dict[str, object]]:
        """Snapshot of the in-flight ops: identity args, current phase,
        the full wall-anchored phase-mark chain (the open half of the
        op's causal chain — a flight dump can say which phase an op died
        in and line it up against a sibling rank's timeline), and
        seconds in flight so far (one clock read per request — this runs
        on the introspection path, never the hot path)."""
        now = time.monotonic()
        off = self.epoch_offset
        out = []
        for span in list(self._open.values()):
            out.append({
                "op": span.name,
                "elapsed_s": now - span.t0,
                "phase": span.marks[-1][0] if span.marks else "",
                "t0": span.t0 + off,
                "marks": [[phase, t + off] for phase, t in list(span.marks)],
                **{k: v for k, v in span.args.items()},
            })
        return out

    def _finish(self, span: OpSpan) -> None:
        self._open.pop(id(span), None)
        self.spans.append(span)
        self.flight.record(
            "op", name=span.name, outcome=span.outcome,
            dur_s=span.t1 - span.t0, t0=span.t0,
            **{k: v for k, v in span.args.items()})
        key = (span.name, str(span.args.get("side", "")))
        hist = self._hists.get(key)
        if hist is None:
            with self._hist_lock:
                hist = self._hists.get(key)
                if hist is None:
                    hist = self.registry.histogram(
                        "mpit_ps_op_seconds", op=key[0], side=key[1])
                    self._hists[key] = hist
        hist.observe(span.t1 - span.t0)

    # -- task lifecycles (driven by aio.Scheduler) ---------------------------

    def task_begin(self, name: str) -> float:
        return time.monotonic()

    def task_end(self, token: Optional[float], name: str, state: str,
                 cpu_us: float = 0.0) -> None:
        if token is None:
            return  # task spawned while recording was disabled
        now = time.monotonic()
        self.tasks.append((name, token, now, state, cpu_us))
        self.flight.record("task", name=name, state=state,
                           dur_s=now - token, t0=token)


class NullRecorder:
    """The disabled recorder: hands out :data:`NULL_SPAN`, records
    nothing, reads no clock."""

    enabled = False
    spans: tuple = ()
    tasks: tuple = ()
    epoch_offset = 0.0

    def op(self, name: str, peer: object = "?", side: str = "client",
           **args) -> NullSpan:
        return NULL_SPAN

    def open_ops(self) -> list:
        return []

    def task_begin(self, name: str) -> None:
        return None

    def task_end(self, token, name: str, state: str,
                 cpu_us: float = 0.0) -> None:
        pass


NULL_RECORDER = NullRecorder()

_GLOBAL: Optional[SpanRecorder] = None
_LOCK = threading.Lock()


def get_recorder():
    """The process-global recorder when obs is enabled, else the null
    recorder.  Same capture-at-construction contract as the registry."""
    if not _metrics.obs_enabled():
        return NULL_RECORDER
    global _GLOBAL
    if _GLOBAL is None:
        with _LOCK:
            if _GLOBAL is None:
                _GLOBAL = SpanRecorder()
    return _GLOBAL


def reset() -> None:
    """Drop the global recorder (tests; called by obs.configure)."""
    global _GLOBAL
    _GLOBAL = None
