"""mpit_tpu.obs — unified observability: metrics, op spans, tracing.

The reference framework's only instrumentation is ad-hoc wall-clock
tables (``tm.feval``/``tm.sync`` in the MNIST trainer, an 11-bucket
table in BiCNN), and the async-PS literature is unambiguous that the
pathologies that matter at scale — stragglers, skewed arrival, retry
storms (MXNET-MPI arxiv 1801.03855, the imbalanced-arrival study arxiv
1804.05349) — are diagnosable only with per-op timing and per-peer
counters.  This package is the one place the stack reports through:

- :mod:`mpit_tpu.obs.metrics` — a process-local **registry** of
  counters, gauges and fixed-log2-bucket histograms.  Zero-dep,
  lock-cheap, snapshot-to-dict plus Prometheus-style text exposition.
  Disabled (the default) it is a **no-op object**: every instrument is
  one shared null singleton whose methods do nothing — hot paths pay a
  method call, never a branch tree or a clock read.
- :mod:`mpit_tpu.obs.spans` — **op spans**: every PS op records
  start/end, per-phase marks (encode → send → ack on the client,
  apply → ack on the server), its ``[epoch, seq]`` identity and an
  outcome, so a straggling or retried op is attributable to a phase
  and a peer.  Scheduler task lifecycles record alongside.
- :mod:`mpit_tpu.obs.trace` — a **Chrome trace-event exporter**: spans
  plus task lifecycles dump as trace JSON (one pid per rank, one tid
  per op channel / task), merged across ranks by the gang launcher at
  exit (``MPIT_OBS_TRACE=path``) and viewable in Perfetto /
  chrome://tracing next to a ``jax.profiler`` device timeline.
- :mod:`mpit_tpu.obs.timers` — the old ``utils/timers.py``
  (``PhaseTimers``, ``trace_annotation``, ``profiler_trace``), folded
  in; ``mpit_tpu.utils.timers`` re-exports for back-compat.
- :mod:`mpit_tpu.obs.statusd` — the **live half**: a per-rank HTTP
  introspection endpoint (``MPIT_OBS_HTTP=<base_port>``; base+rank per
  process) serving ``/metrics`` (Prometheus exposition), ``/status``
  (role/lease/map state + the in-flight op table) and ``/trace``
  (dump-on-demand) while the gang runs.
- :mod:`mpit_tpu.obs.flight` — a bounded **flight recorder** of recent
  span/task/FT events, dumped to disk on ``RetryExhausted``, eviction,
  and scheduler stall — a hang produces a postmortem instead of
  nothing.
- :mod:`mpit_tpu.obs.top` — ``python -m mpit_tpu.obs top``: a gang-wide
  aggregator polling every rank's endpoint into one table (throughput,
  staleness, retries, shard load, p99 op latency, send-queue depth).
- :mod:`mpit_tpu.obs.clock` — the process time base plus the per-peer
  **clock-offset estimator** fed by the FLAG_TIMING wire extension
  (NTP-style minimum-RTT exchanges over op acks and heartbeat echoes).
- :mod:`mpit_tpu.obs.causal` — ``python -m mpit_tpu.obs analyze``: the
  offline **causal joiner**: merges per-rank trace halves into op
  chains keyed by wire identity, aligns rank clocks, decomposes each
  op's latency onto the encode → send-queue → wire → server-queue →
  apply → ack-wire → client-wait taxonomy, reports per-phase
  percentiles and the critical path, and emits Perfetto flow arrows.
- :mod:`mpit_tpu.obs.profile` — the **CPU/utilization attribution
  plane** (``MPIT_OBS_PROFILE=1``): per-task ``time.thread_time()``
  accounting stamped by the cooperative scheduler, ``cpu_us`` riders
  on op spans and their phases, Chrome counter tracks (pool_util /
  pool_depth / sched_runq / task_cpu) sampled into the trace, and
  ``python -m mpit_tpu.obs profile`` — per-rank core utilization,
  on/off-CPU phase split, pool overlap efficiency, top tasks by CPU.

Enablement: ``MPIT_OBS=1`` (or ``MPIT_OBS_TRACE=<path>``, which implies
it) turns the global registry + recorder on; :func:`configure` does the
same programmatically for tests.  Components capture the registry at
construction, so enable *before* building transports/roles.  See
docs/OBSERVABILITY.md for the metric catalog and trace schema.
"""

from mpit_tpu.obs.clock import ClockEstimator, PeerClock, wall_us
from mpit_tpu.obs.flight import (
    NULL_FLIGHT,
    FlightRecorder,
    get_flight,
    validate_dump,
)
from mpit_tpu.obs.metrics import (
    NULL_REGISTRY,
    Counter,
    Gauge,
    Histogram,
    NullRegistry,
    Registry,
    configure,
    get_registry,
    obs_enabled,
    registry_or_local,
)
from mpit_tpu.obs.profile import (
    NULL_PROFILER,
    Profiler,
    get_profiler,
    profile_enabled,
    resource_snapshot,
)
from mpit_tpu.obs.spans import (
    NULL_RECORDER,
    NULL_SPAN,
    OpSpan,
    SpanRecorder,
    get_recorder,
)
from mpit_tpu.obs.statusd import StatusServer
from mpit_tpu.obs.statusd import maybe_start as maybe_start_statusd
from mpit_tpu.obs.statusd import register_action as register_status_action
from mpit_tpu.obs.statusd import register_provider as register_status_provider
from mpit_tpu.obs.timers import PhaseTimers, profiler_trace, trace_annotation
from mpit_tpu.obs.trace import (
    maybe_merge_rank_traces,
    maybe_write_rank_trace,
    merge_traces,
    validate_trace,
    write_rank_trace,
)

__all__ = [
    "Registry", "NullRegistry", "NULL_REGISTRY",
    "Counter", "Gauge", "Histogram",
    "get_registry", "registry_or_local", "obs_enabled", "configure",
    "SpanRecorder", "OpSpan", "NULL_RECORDER", "NULL_SPAN", "get_recorder",
    "FlightRecorder", "NULL_FLIGHT", "get_flight", "validate_dump",
    "StatusServer", "maybe_start_statusd", "register_status_provider",
    "register_status_action",
    "write_rank_trace", "merge_traces", "validate_trace",
    "maybe_write_rank_trace", "maybe_merge_rank_traces",
    "PhaseTimers", "trace_annotation", "profiler_trace",
    "ClockEstimator", "PeerClock", "wall_us",
    "Profiler", "NULL_PROFILER", "get_profiler", "profile_enabled",
    "resource_snapshot",
]
