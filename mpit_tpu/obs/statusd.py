"""statusd — per-rank HTTP introspection endpoint (live gang telemetry).

PR 4's trace exporter answers "what happened" after a clean exit; the
straggler/skew/churn failure modes the async-PS literature cares about
(MXNET-MPI arxiv 1801.03855; imbalanced arrival arxiv 1804.05349) need
gang state *while it runs*.  ``MPIT_OBS_HTTP=<base_port>`` makes every
rank serve, on ``base_port + rank`` (loopback by default), three routes:

- ``GET /metrics`` — the registry's Prometheus text exposition (the
  exact format a scrape config or ``mpit top`` consumes);
- ``GET /status`` — JSON: rank/role/pid identity, the span recorder's
  **in-flight op table** (op, peer, ``[epoch, seq]``, current phase,
  seconds in flight), and whatever the role objects registered as
  status providers (server: lease/epoch per client, shard map version,
  owned shards, live task table; client: epoch, map version, pending
  tasks);
- ``GET /trace`` — dump-on-demand of the span recorder's trace buffer
  as Chrome trace JSON (same schema as the exit-time export), so a
  *running* gang can be profiled without waiting for it to finish.

Serving runs on one stdlib ``ThreadingHTTPServer`` daemon thread per
process — the cooperative scheduler never sees it, and the GIL makes the
reads (plain attributes, registry snapshots) safe without locking.  A
request costs the *requester* a snapshot; the role hot paths pay
nothing.  When ``MPIT_OBS_HTTP`` is unset, :func:`maybe_start` returns
``None`` without creating a socket, and provider registration is
skipped at the call sites (obs off), so the disabled path stays
null-object free.

This read path is deliberately reusable: ``python -m mpit_tpu.obs top``
polls it, and the shardctl controller / future admission control can
consume the same endpoints (:func:`mpit_tpu.obs.top.poll_rank`).
"""

from __future__ import annotations

import json
import os
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Dict, Optional

from mpit_tpu.obs import clock as _clock
from mpit_tpu.obs import metrics as _metrics
from mpit_tpu.obs import profile as _profile
from mpit_tpu.obs import spans as _spans

ENV = _metrics.HTTP_ENV  # MPIT_OBS_HTTP

#: name -> zero-arg callable returning a JSON-serializable dict.  Role
#: objects register themselves here (obs-enabled processes only); the
#: /status handler calls every provider per request.
_PROVIDERS: Dict[str, Callable[[], dict]] = {}
_PROVIDERS_LOCK = threading.Lock()

#: name -> callable(params dict) -> JSON-serializable dict.  Operator
#: verbs served as ``GET /<name>?k=v`` — e.g. the shard controller's
#: ``/scale`` route.  Handlers run on the HTTP thread, so they must
#: only *enqueue* work (a thread-safe deque the role's own loop
#: drains), never touch the cooperative scheduler.
_ACTIONS: Dict[str, Callable[[Dict[str, str]], dict]] = {}


def register_provider(name: str, fn: Callable[[], dict]) -> None:
    """Attach a status section (``/status`` key ``name``).  Re-registering
    a name replaces it (a restarted role supersedes its old section)."""
    with _PROVIDERS_LOCK:
        _PROVIDERS[name] = fn


def register_action(name: str, fn: Callable[[Dict[str, str]], dict]) -> None:
    """Attach an operator verb at ``GET /<name>`` (query params become
    the handler's dict).  Same replace-on-re-register rule as
    providers."""
    with _PROVIDERS_LOCK:
        _ACTIONS[name] = fn


def clear_providers() -> None:
    """Drop every registered provider and action (tests; via
    obs.configure)."""
    with _PROVIDERS_LOCK:
        _PROVIDERS.clear()
        _ACTIONS.clear()


def _action_for(route: str) -> "Optional[Callable[[Dict[str, str]], dict]]":
    with _PROVIDERS_LOCK:
        return _ACTIONS.get(route.lstrip("/"))


def _provider_sections() -> Dict[str, object]:
    with _PROVIDERS_LOCK:
        items = list(_PROVIDERS.items())
    out: Dict[str, object] = {}
    for name, fn in items:
        try:
            out[name] = fn()
        except Exception as exc:  # noqa: BLE001 — introspection never kills a role
            out[name] = {"error": repr(exc)}
    return out


class StatusServer:
    """One rank's endpoint: a ThreadingHTTPServer on a daemon thread."""

    def __init__(self, port: int, rank: Optional[int] = None,
                 role: str = "", host: str = "127.0.0.1"):
        self.rank = rank
        self.role = role
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):  # silence per-request stderr
                pass

            def _reply(self, code: int, body: bytes, ctype: str) -> None:
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):  # noqa: N802 — http.server API
                route = self.path.split("?", 1)[0].rstrip("/") or "/"
                try:
                    if route in ("/", "/metrics"):
                        body = _metrics.get_registry().exposition().encode()
                        self._reply(200, body, "text/plain; version=0.0.4")
                    elif route == "/status":
                        self._reply(200, json.dumps(outer.status()).encode(),
                                    "application/json")
                    elif route == "/trace":
                        self._reply(200, json.dumps(outer.trace()).encode(),
                                    "application/json")
                    elif (action := _action_for(route)) is not None:
                        from urllib.parse import parse_qsl, urlsplit

                        params = dict(parse_qsl(urlsplit(self.path).query))
                        self._reply(200, json.dumps(action(params)).encode(),
                                    "application/json")
                    else:
                        self._reply(404, b"routes: /metrics /status /trace"
                                    b" (+ registered actions)\n",
                                    "text/plain")
                except Exception as exc:  # noqa: BLE001 — see _provider_sections
                    self._reply(500, repr(exc).encode(), "text/plain")

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self._httpd.daemon_threads = True
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            kwargs={"poll_interval": 0.5},
            name=f"mpit-statusd:{self.port}", daemon=True)
        self._thread.start()

    def status(self) -> dict:
        rec = _spans.get_recorder()
        return {
            "rank": self.rank,
            "role": self.role,
            "pid": os.getpid(),
            "obs": _metrics.obs_enabled(),
            "inflight_ops": rec.open_ops(),
            "clock": _clock.snapshot_all(),
            # Where the cores are right now (obs/profile.py): pool
            # threads/depth/busy, scheduler runq/CPU, top-5 tasks by
            # cpu_us.  Pool-only when profiling is off.
            "resources": _profile.resource_snapshot(),
            **_provider_sections(),
        }

    def trace(self) -> dict:
        from mpit_tpu.obs import trace as _trace

        rec = _spans.get_recorder()
        pid = self.rank if self.rank is not None else os.getpid()
        label = (f"rank {self.rank}" + (f" ({self.role})" if self.role
                                        else "")) if self.rank is not None \
            else f"pid {pid}"
        return {
            "traceEvents": _trace.chrome_events(rec, pid=pid, label=label),
            "displayTimeUnit": "ms",
            "otherData": {"ranks": {str(pid): {
                "role": self.role,
                "metrics": _metrics.get_registry().snapshot()}},
                "clock": _clock.snapshot_all()},
        }

    def close(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=5)


def base_port() -> Optional[int]:
    """The announced base port, or None when MPIT_OBS_HTTP is unset."""
    raw = os.environ.get(ENV, "")
    if not raw:
        return None
    try:
        return int(raw)
    except ValueError as exc:
        raise ValueError(
            f"{ENV} must be an integer base port, got {raw!r}") from exc


def maybe_start(rank: int, role: str = "") -> Optional[StatusServer]:
    """Start this rank's endpoint on ``base_port + rank`` when
    ``MPIT_OBS_HTTP`` is set; None (and no socket) otherwise.  A bind
    failure logs and returns None — introspection must never take a
    training rank down with it."""
    base = base_port()
    if base is None:
        return None
    try:
        server = StatusServer(base + int(rank), rank=int(rank), role=role)
    except OSError as exc:
        from mpit_tpu.utils.logging import get_logger

        get_logger("statusd", rank).warning(
            "could not bind introspection endpoint on port %d: %s "
            "(rank runs without one)", base + int(rank), exc)
        return None
    from mpit_tpu.obs import flight as _flight

    _flight.get_flight().set_identity(rank=rank, role=role)
    return server
