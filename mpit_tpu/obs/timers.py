"""Per-phase wall-clock timers + jax.profiler hooks (moved here from
``mpit_tpu/utils/timers.py`` when observability unified under
``mpit_tpu.obs``; that module re-exports for back-compat).

The reference tracks phase times in ad-hoc tables — ``tm.feval``/
``tm.sync`` in the MNIST trainer (reference asyncsgd/goot.lua:20-22,
152-157), an 11-bucket table in BiCNN (reference BiCNN/bicnn.lua:17-28),
and optimizers accumulate blocking sync time around every wait
(reference optim-downpour.lua:39-41).  :class:`PhaseTimers` is the same
cheap mechanism with a context manager — the *trainer-loop* timer,
where the registry/spans in :mod:`mpit_tpu.obs.metrics` /
:mod:`mpit_tpu.obs.spans` cover the comm/PS stack.

:func:`trace_annotation` is the jax.profiler bridge: wrap host-side
work in it while capturing a device trace (:func:`profiler_trace`) and
the host phase shows up on the device timeline — the exported obs trace
(``MPIT_OBS_TRACE``) is wall-anchored so the two line up side-by-side
in Perfetto.
"""

from __future__ import annotations

import contextlib
import time
from collections import defaultdict
from typing import Dict, Iterator


class PhaseTimers:
    """Accumulate wall-clock seconds per named phase."""

    def __init__(self) -> None:
        self.total: Dict[str, float] = defaultdict(float)
        self.count: Dict[str, int] = defaultdict(int)
        self._t0 = time.monotonic()

    @contextlib.contextmanager
    def phase(self, name: str) -> Iterator[None]:
        start = time.monotonic()
        try:
            yield
        finally:
            self.total[name] += time.monotonic() - start
            self.count[name] += 1

    def add(self, name: str, seconds: float) -> None:
        self.total[name] += seconds
        self.count[name] += 1

    def elapsed(self) -> float:
        """Seconds since this timer set was created."""
        return time.monotonic() - self._t0

    def summary(self) -> str:
        lines = [f"total elapsed {self.elapsed():.3f}s"]
        for name in sorted(self.total):
            tot, cnt = self.total[name], self.count[name]
            avg = tot / max(cnt, 1)
            lines.append(f"  {name:<16} {tot:9.3f}s  n={cnt:<8d} avg={avg * 1e3:8.3f}ms")
        return "\n".join(lines)


@contextlib.contextmanager
def trace_annotation(name: str) -> Iterator[None]:
    """jax.profiler annotation when available, no-op otherwise."""
    try:
        import jax.profiler as _prof

        annotation = _prof.TraceAnnotation(name)
    except Exception:  # pragma: no cover - profiler unavailable
        annotation = contextlib.nullcontext()
    with annotation:
        yield


@contextlib.contextmanager
def profiler_trace(log_dir: str | None) -> Iterator[None]:
    """Capture a jax.profiler trace into ``log_dir`` (view with
    TensorBoard / xprof) around the enclosed block; no-op when
    ``log_dir`` is falsy.  The deep-trace companion to
    :class:`PhaseTimers` — trainers accept a ``profile_dir`` config knob
    and wrap their hot loop with this (the rebuild's answer to the
    reference's print-only timing, SURVEY.md §5 tracing)."""
    if not log_dir:
        yield
        return
    import jax.profiler as _prof

    _prof.start_trace(str(log_dir))
    try:
        yield
    finally:
        _prof.stop_trace()
