"""``python -m mpit_tpu.obs top`` — one table for a whole running gang.

Polls every rank's statusd endpoint (``/metrics`` + ``/status``,
obs/statusd.py) and renders per-rank throughput, gradient staleness,
retries/evictions and shard load side by side — the live view of the
failure modes the PS literature says matter at scale (stragglers show
up as one rank's ops/s collapsing; skewed arrival as a staleness tail;
retry storms in the retries column; shard imbalance in the load column).

The collection half (:func:`parse_exposition`, :func:`poll_rank`,
:func:`collect`) is a library surface on purpose: the shardctl
controller and the planned admission-control tier read the same
endpoints, so "what the operator sees" and "what the control plane
acts on" cannot drift apart.

Usage::

    MPIT_OBS_HTTP=8780 python -m mpit_tpu.train.launch --np 4 ... &
    python -m mpit_tpu.obs top --np 4 --base-port 8780

``--iters N`` bounds the refresh loop (0 = until interrupted);
``--json`` emits one machine-readable snapshot per refresh instead of
the table (CI and scripts); ``--retry-s`` keeps polling an endpoint
that is not up yet (gang still importing jax) before giving up.
"""

from __future__ import annotations

import argparse
import json
import re
import sys
import time
import urllib.error
import urllib.request
from typing import Dict, List, Optional, Tuple

DEFAULT_BASE_PORT = 8780

_LINE = re.compile(
    r'^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)'
    r'(?:\{(?P<labels>[^}]*)\})?\s+(?P<value>[^\s]+)$')
_LABEL = re.compile(r'(\w+)="([^"]*)"')


def parse_exposition(text: str) -> List[Tuple[str, Dict[str, str], float]]:
    """Prometheus text exposition -> [(name, labels, value)].  Ignores
    comments and anything that does not parse as a sample line."""
    out: List[Tuple[str, Dict[str, str], float]] = []
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        m = _LINE.match(line)
        if not m:
            continue
        try:
            value = float(m.group("value"))
        except ValueError:
            continue
        labels = dict(_LABEL.findall(m.group("labels") or ""))
        out.append((m.group("name"), labels, value))
    return out


def metric_sum(samples, name: str, **match) -> float:
    """Sum of every series of ``name`` whose labels include ``match``."""
    total = 0.0
    for n, labels, value in samples:
        if n == name and all(labels.get(k) == str(v)
                             for k, v in match.items()):
            total += value
    return total


def hist_mean(samples, name: str) -> Optional[float]:
    """Mean of a histogram from its ``_sum``/``_count`` series (all
    label sets pooled); None when it never observed."""
    count = metric_sum(samples, name + "_count")
    if count <= 0:
        return None
    return metric_sum(samples, name + "_sum") / count


def hist_quantile(samples, name: str, q: float) -> Optional[float]:
    """Quantile estimate from a histogram's cumulative ``_bucket{le=}``
    series (all label sets pooled): the smallest bucket upper bound
    whose pooled cumulative count covers rank ``q``.  Exact up to the
    log2 bucket width; None when the histogram never observed."""
    per_le: Dict[float, float] = {}
    for n, labels, value in samples:
        if n != name + "_bucket":
            continue
        le = labels.get("le", "")
        bound = float("inf") if le == "+Inf" else float(le)
        # Cumulative series pool by summing per bound across label sets.
        per_le[bound] = per_le.get(bound, 0.0) + value
    if not per_le:
        return None
    total = metric_sum(samples, name + "_count")
    if total <= 0:
        return None
    target = q * total
    best = None
    for bound in sorted(per_le):
        if per_le[bound] >= target:
            best = bound
            break
    if best is None or best == float("inf"):
        # Everything above the largest finite bucket: report the max
        # finite bound (the histogram clamps there too).
        finite = [b for b in per_le if b != float("inf")]
        best = max(finite) if finite else None
    return best


def hist_quantile_between(prev, cur, name: str, q: float) -> Optional[float]:
    """Quantile of a histogram over the *window* between two sample
    snapshots: cumulative ``_bucket{le=}`` counts are differenced per
    bound (pooled across label sets) before the rank walk, so the
    estimate describes what happened since ``prev`` — the sliding-window
    read the autoscaler acts on — rather than the run's whole history.
    None when nothing was observed in the window."""
    per_le: Dict[float, float] = {}
    for samples, sign in ((cur, 1.0), (prev, -1.0)):
        for n, labels, value in samples:
            if n != name + "_bucket":
                continue
            le = labels.get("le", "")
            bound = float("inf") if le == "+Inf" else float(le)
            per_le[bound] = per_le.get(bound, 0.0) + sign * value
    total = (metric_sum(cur, name + "_count")
             - metric_sum(prev, name + "_count"))
    if not per_le or total <= 0:
        return None
    target = q * total
    best = None
    for bound in sorted(per_le):
        if per_le[bound] >= target:
            best = bound
            break
    if best is None or best == float("inf"):
        finite = [b for b in per_le if b != float("inf")]
        best = max(finite) if finite else None
    return best


def _get(url: str, timeout: float) -> bytes:
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return resp.read()


def poll_rank(host: str, port: int, timeout: float = 2.0) -> dict:
    """One rank's full readout: parsed /metrics samples + /status JSON.
    Raises OSError/URLError when the endpoint is unreachable."""
    metrics = parse_exposition(
        _get(f"http://{host}:{port}/metrics", timeout).decode())
    status = json.loads(_get(f"http://{host}:{port}/status", timeout))
    return {"metrics": metrics, "status": status, "port": port}


def collect(host: str, base: int, nranks: int,
            timeout: float = 2.0) -> Dict[int, Optional[dict]]:
    """Poll ranks 0..nranks-1; unreachable ranks map to None (a rank
    that exited or has not bound yet is a row, not a crash)."""
    out: Dict[int, Optional[dict]] = {}
    for rank in range(nranks):
        try:
            out[rank] = poll_rank(host, base + rank, timeout)
        except (OSError, ValueError, urllib.error.URLError):
            out[rank] = None
    return out


def _rank_row(rank: int, sample: Optional[dict],
              prev: Optional[dict], dt: Optional[float],
              p99_target_ms: Optional[float] = None) -> Dict[str, object]:
    """One rank's table row (also the --json record).
    ``p99_target_ms`` (from the controller's autoscale SLO, when one is
    running) turns the p99 column into a vs-target verdict."""
    if sample is None:
        return {"rank": rank, "up": False}
    m = sample["metrics"]
    status = sample["status"]
    ops = (metric_sum(m, "mpit_ps_grads_applied_total")
           + metric_sum(m, "mpit_ps_params_served_total"))
    row: Dict[str, object] = {
        "rank": rank,
        "up": True,
        "role": status.get("role") or "",
        "ops_total": int(ops),
        "ops_per_s": None,
        "staleness_mean": hist_mean(m, "mpit_ps_grad_staleness"),
        # Queueing-pressure columns: p99 op latency from the
        # mpit_ps_op_seconds log2 buckets, and the frames still queued
        # to writer threads (tcp gangs; shm sends complete into the
        # ring, so the column reads 0 there).
        "p99_s": hist_quantile(m, "mpit_ps_op_seconds", 0.99),
        "send_queue": int(metric_sum(m, "mpit_tcp_send_queue_depth")),
        # Serving-tier pair (PROTOCOL.md §8): live connection fan-out on
        # the event-loop transport, and admission-control rejections.
        "conns": int(metric_sum(m, "mpit_tcp_connections")),
        "busy": int(metric_sum(m, "mpit_ps_busy_replies_total")),
        "retries": int(metric_sum(m, "mpit_ft_retries_total")),
        "evictions": int(metric_sum(m, "mpit_ft_evictions_total")),
        "shards": int(metric_sum(m, "mpit_shardctl_owned_shards")),
        "shard_busy_s": metric_sum(m, "mpit_shardctl_shard_busy_seconds_sum"),
        "map_version": int(metric_sum(m, "mpit_shardctl_map_version")),
        # Elastic membership (PROTOCOL.md §9): the controller rank
        # publishes the live server count; everyone else reads 0.
        "gang_size": int(metric_sum(m, "mpit_gang_size", role="server")),
        # Multi-cell fabric (PROTOCOL.md §11): a cell rank publishes
        # its serving version and lag vs the upstream head; readers
        # attached ride the shared mpit_ps_readers gauge, and reader
        # ranks publish their fail-over/GOODBYE reroutes.
        "cell_version": int(metric_sum(m, "mpit_cell_version")),
        "cell_lag": int(metric_sum(m, "mpit_cell_lag")),
        "readers": int(metric_sum(m, "mpit_ps_readers")),
        "reroutes": int(metric_sum(m, "mpit_ps_reader_reroutes_total")),
        # Aggregation columns (PROTOCOL.md §13): a reducing client rank
        # publishes its last round's fan-in, the contributions it
        # excluded at its straggler deadline, and the direct-push
        # fallbacks it took after being excluded itself.
        "agg_fanin": int(metric_sum(m, "mpit_agg_fanin")),
        "agg_late": int(metric_sum(m, "mpit_agg_late_folds_total")),
        "agg_fallbacks": int(
            metric_sum(m, "mpit_agg_direct_fallbacks_total")),
        "inflight": len(status.get("inflight_ops") or []),
        # Pooled data plane (comm/pool.py): chunk kernels dispatched to
        # the native worker pool — 0 on serial-fallback ranks.
        "pool_jobs": int(metric_sum(m, "mpit_pool_jobs_total")),
        # CPU attribution plane (obs/profile.py): scheduler run-queue
        # depth; cpu%/pool-util% are windowed below (None first poll).
        "sched_runq": int(metric_sum(m, "mpit_sched_runq")),
        "cpu_pct": None,
        "pool_util": None,
    }
    # SLO columns (ISSUE 11): BUSY-reply ratio (admission rejections
    # over ops — windowed against the previous refresh when one exists)
    # and the per-rank p99-vs-target verdict read off the autoscaler's
    # published SLO.
    busy_all = (metric_sum(m, "mpit_ps_busy_replies_total")
                + metric_sum(m, "mpit_shardctl_busy_replies_total"))
    if prev is not None:
        pm = prev["metrics"]
        d_busy = busy_all - (metric_sum(pm, "mpit_ps_busy_replies_total")
                             + metric_sum(pm,
                                          "mpit_shardctl_busy_replies_total"))
        d_ops = ops - (metric_sum(pm, "mpit_ps_grads_applied_total")
                       + metric_sum(pm, "mpit_ps_params_served_total"))
        denom = d_busy + max(d_ops, 0.0)
        row["busy_ratio"] = (d_busy / denom) if denom > 0 else 0.0
        row["p99_s"] = hist_quantile_between(pm, m, "mpit_ps_op_seconds",
                                             0.99) or row["p99_s"]
    else:
        denom = busy_all + ops
        row["busy_ratio"] = (busy_all / denom) if denom > 0 else 0.0
    row["p99_target_ms"] = p99_target_ms
    p99 = row.get("p99_s")
    if p99_target_ms and p99 is not None:
        row["slo"] = "hot" if p99 * 1000.0 > p99_target_ms else "ok"
    else:
        row["slo"] = None
    if prev is not None and dt and dt > 0:
        prev_ops = (metric_sum(prev["metrics"], "mpit_ps_grads_applied_total")
                    + metric_sum(prev["metrics"],
                                 "mpit_ps_params_served_total"))
        row["ops_per_s"] = (ops - prev_ops) / dt
        # Windowed core use (obs/profile.py): Δ scheduler-attributed
        # CPU seconds per wall second (fraction of one core), and Δ
        # pool busy-seconds over the window's thread-capacity.
        pm = prev["metrics"]
        d_cpu = (metric_sum(m, "mpit_sched_cpu_seconds_total")
                 - metric_sum(pm, "mpit_sched_cpu_seconds_total"))
        if d_cpu > 0 or metric_sum(m, "mpit_sched_cpu_seconds_total") > 0:
            row["cpu_pct"] = max(d_cpu, 0.0) / dt * 100.0
        threads = metric_sum(m, "mpit_pool_threads")
        if threads > 0:
            d_busy = (metric_sum(m, "mpit_pool_busy_seconds")
                      - metric_sum(pm, "mpit_pool_busy_seconds"))
            row["pool_util"] = max(d_busy, 0.0) / (dt * threads) * 100.0
    return row


def autoscale_status(samples: Dict[int, Optional[dict]]) -> Optional[dict]:
    """The gang's autoscale section, from whichever rank runs the
    controller (None when no autoscaler is attached) — the source of
    the status line and the --json ``autoscale`` field."""
    for sample in samples.values():
        if sample is None:
            continue
        section = (sample["status"].get("controller") or {}).get("autoscale")
        if section:
            return section
    return None


def render_autoscale_line(section: Optional[dict]) -> str:
    """One status line: last decision, cooldown remaining, SLO targets
    (the gang-level half of the SLO columns)."""
    if not section:
        return "autoscale: (not running)"
    last = section.get("last") or {}
    slo = section.get("slo") or {}
    counts = section.get("decisions") or {}
    targets = " ".join(f"{k}<={v:g}" for k, v in sorted(slo.items()))
    action = last.get("action", "-")
    reason = last.get("reason", "-")
    return (f"autoscale: last={action}({reason}) "
            f"cooldown={section.get('cooldown_s', 0):.1f}s "
            f"up/down/hold={counts.get('up', 0)}/{counts.get('down', 0)}"
            f"/{counts.get('hold', 0)} "
            f"operator_calls={section.get('operator_calls', 0)}"
            + (f" slo[{targets}]" if targets else ""))


_COLUMNS = ("rank", "role", "ops", "ops/s", "p99ms", "slo", "busy%",
            "sendq", "conns",
            "busy", "stale", "retry", "evict", "shards", "busy_s", "mapv",
            "gang", "cellv", "lag", "rdrs", "rrt", "fanin", "late", "fb",
            "pool", "cpu%", "putl%", "runq", "infl")


def render_table(rows: List[Dict[str, object]]) -> str:
    def fmt(row: Dict[str, object]) -> List[str]:
        if not row.get("up"):
            return [str(row["rank"]), "(down)"] + ["-"] * (len(_COLUMNS) - 2)
        stale = row["staleness_mean"]
        ops_s = row["ops_per_s"]
        p99 = row.get("p99_s")
        busy_ratio = row.get("busy_ratio")
        return [
            str(row["rank"]), str(row["role"]) or "?",
            str(row["ops_total"]),
            f"{ops_s:.1f}" if ops_s is not None else "-",
            f"{p99 * 1000.0:.2f}" if p99 is not None else "-",
            # p99 vs the autoscaler's published target: HOT above it,
            # ok within, '-' when no SLO is running on this gang.
            ("HOT" if row["slo"] == "hot" else "ok")
            if row.get("slo") else "-",
            f"{busy_ratio * 100.0:.0f}" if busy_ratio else "-",
            str(row["send_queue"]) if row.get("send_queue") else "-",
            str(row["conns"]) if row.get("conns") else "-",
            str(row["busy"]) if row.get("busy") else "-",
            f"{stale:.2f}" if stale is not None else "-",
            str(row["retries"]), str(row["evictions"]),
            str(row["shards"]) if row["shards"] else "-",
            f"{row['shard_busy_s']:.2f}" if row["shard_busy_s"] else "-",
            str(row["map_version"]) if row["map_version"] else "-",
            str(row["gang_size"]) if row.get("gang_size") else "-",
            # Cell-fabric columns (§11): only meaningful on cell /
            # reader rows — everyone else shows '-'.
            (str(row["cell_version"]) if row.get("role") == "cell"
             else "-"),
            (str(row["cell_lag"]) if row.get("role") == "cell" else "-"),
            str(row["readers"]) if row.get("readers") else "-",
            str(row["reroutes"]) if row.get("reroutes") else "-",
            # Aggregation columns (§13): only meaningful on reducing
            # client ranks — everyone else shows '-'.
            str(row["agg_fanin"]) if row.get("agg_fanin") else "-",
            str(row["agg_late"]) if row.get("agg_late") else "-",
            str(row["agg_fallbacks"]) if row.get("agg_fallbacks") else "-",
            # Worker-pool column: pooled kernel jobs dispatched —
            # serial-fallback ranks show '-'.
            str(row["pool_jobs"]) if row.get("pool_jobs") else "-",
            # CPU attribution columns (obs/profile.py): windowed
            # scheduler CPU (% of one core), windowed pool utilization
            # (% of thread capacity), current run-queue depth — all
            # '-' unless profiling is on and a window exists.
            (f"{row['cpu_pct']:.0f}" if row.get("cpu_pct") is not None
             else "-"),
            (f"{row['pool_util']:.0f}" if row.get("pool_util") is not None
             else "-"),
            str(row["sched_runq"]) if row.get("sched_runq") else "-",
            str(row["inflight"]),
        ]

    cells = [list(_COLUMNS)] + [fmt(r) for r in rows]
    widths = [max(len(row[i]) for row in cells)
              for i in range(len(_COLUMNS))]
    return "\n".join(
        "  ".join(cell.ljust(w) for cell, w in zip(row, widths)).rstrip()
        for row in cells)


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m mpit_tpu.obs top",
        description="live per-rank telemetry for a running gang")
    parser.add_argument("--np", type=int, required=True,
                        help="gang size (ranks 0..np-1 are polled)")
    parser.add_argument("--base-port", type=int, default=None,
                        help=f"statusd base port (default: $MPIT_OBS_HTTP "
                             f"or {DEFAULT_BASE_PORT})")
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--interval", type=float, default=2.0,
                        help="seconds between refreshes")
    parser.add_argument("--iters", type=int, default=0,
                        help="number of refreshes (0 = until interrupted)")
    parser.add_argument("--retry-s", type=float, default=0.0,
                        help="keep polling this long for the first rank to "
                             "come up before the first render")
    parser.add_argument("--min-up", type=int, default=0,
                        help="exit 1 unless at least this many ranks "
                             "responded on the final refresh")
    parser.add_argument("--json", action="store_true",
                        help="emit one JSON snapshot per refresh")
    args = parser.parse_args(argv)
    import os

    base = args.base_port
    if base is None:
        env = os.environ.get("MPIT_OBS_HTTP", "")
        base = int(env) if env else DEFAULT_BASE_PORT

    if args.retry_s > 0:
        deadline = time.monotonic() + args.retry_s
        while time.monotonic() < deadline:
            if any(s is not None
                   for s in collect(args.host, base, args.np).values()):
                break
            time.sleep(0.5)

    prev: Dict[int, Optional[dict]] = {}
    prev_t: Optional[float] = None
    i = 0
    up = 0
    try:
        while True:
            i += 1
            now = time.monotonic()
            samples = collect(args.host, base, args.np)
            dt = (now - prev_t) if prev_t is not None else None
            autoscale = autoscale_status(samples)
            target = (autoscale or {}).get("slo", {}).get("p99_ms")
            rows = [_rank_row(r, samples[r], prev.get(r), dt,
                              p99_target_ms=target)
                    for r in range(args.np)]
            up = sum(1 for r in rows if r.get("up"))
            if args.json:
                print(json.dumps({"ranks": rows, "autoscale": autoscale}))
            else:
                print(render_table(rows))
                print(render_autoscale_line(autoscale))
                print(f"-- {up}/{args.np} rank(s) up; refresh {i}"
                      + (f"/{args.iters}" if args.iters else "") + " --")
            sys.stdout.flush()
            prev, prev_t = samples, now
            if args.iters and i >= args.iters:
                break
            time.sleep(args.interval)
    except KeyboardInterrupt:
        pass
    return 0 if up >= args.min_up else 1


if __name__ == "__main__":
    sys.exit(main())
