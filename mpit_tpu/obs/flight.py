"""Flight recorder — a bounded ring of recent events, dumped on failure.

The merged Chrome trace answers "what happened" only after a *clean*
gang exit; the runs that most need a timeline are exactly the ones that
don't produce one (a hung gang, an evicted client, a RetryExhausted op).
The flight recorder is the postmortem half: whenever obs is enabled,
every finished op span, task lifecycle and FT event also lands in a
bounded per-process ring (:class:`FlightRecorder`), and the failure
paths dump the ring to disk:

- the client retry loops dump on :class:`RetryExhausted` (an op failed
  every allowed attempt — the never-hang guarantee firing);
- the server lease reaper dumps on every eviction (the gang just lost a
  member; the ring shows what its channels were doing);
- the scheduler watchdog dumps when a non-empty task queue accumulates
  ``MPIT_OBS_STALL_S`` seconds of idle backoff without completing a
  single task — a stuck gang produces a task table + recent-event dump
  instead of nothing;
- the autoscaler (shardctl/autoscale.py) dumps on every **executed
  scale action** (``autoscale_up`` / ``autoscale_down``) and once per
  **SLO-breach episode that outlives the settle window**
  (``slo_breach``) — the dump's ``extra`` carries the full decision
  record and the triggering telemetry window, so a mis-scaled gang
  produces a postmortem naming the signal that drove it
  (:func:`validate_dump` checks that shape; docs/OPERATIONS.md walks a
  dump).

Dumps are JSON (:func:`FlightRecorder.dump` schema in
docs/OBSERVABILITY.md): rank/role/pid, the dump reason, the ring's
recent events (wall-anchored like the trace exporter), the live task
table when the dumper has one, the span recorder's in-flight op table,
and a full metrics snapshot.  ``MPIT_OBS_FLIGHT`` names the dump
directory (default: the system temp dir); files are
``mpit_flight_rank<N>_<reason>.json`` and never overwrite an earlier
dump from the same process (a counter suffix disambiguates).

Disabled (obs off) the recorder is the shared :data:`NULL_FLIGHT` null
object: ``record``/``dump`` do nothing, read no clock, allocate nothing
— the same contract as the null registry/recorder.
"""

from __future__ import annotations

import json
import os
import tempfile
import threading
import time
from collections import deque
from typing import Dict, List, Optional, Tuple

from mpit_tpu.obs import clock as _clock
from mpit_tpu.obs import metrics as _metrics

ENV_DIR = "MPIT_OBS_FLIGHT"
#: ring capacity (events); enough for a few hundred ops of context
#: without letting a dump grow past postmortem-readable size.
CAPACITY = int(os.environ.get("MPIT_OBS_FLIGHT_EVENTS", "512"))


class NullFlight:
    """Shared do-nothing flight recorder — the disabled path."""

    __slots__ = ()
    enabled = False
    events: tuple = ()
    last_dump_path: Optional[str] = None

    def record(self, kind: str, **fields) -> None:
        pass

    def dump(self, reason: str, tasks: Optional[List[Tuple[str, str]]] = None,
             **extra) -> Optional[str]:
        return None

    def set_identity(self, rank=None, role=None) -> None:
        pass


NULL_FLIGHT = NullFlight()


class FlightRecorder:
    """Bounded ring of recent events plus the dump-to-disk machinery.

    Appends are GIL-atomic deque operations; the ring is shared by the
    role threads of one process exactly like the span recorder.  Events
    are recorded on the monotonic clock and wall-anchored at dump time
    with the same epoch offset the trace exporter uses, so a flight dump
    and a sibling rank's trace line up on one timeline."""

    enabled = True

    def __init__(self, capacity: int = CAPACITY):
        self.events: deque = deque(maxlen=capacity)
        self.epoch_offset = _clock.epoch_offset()  # the shared time base
        self.rank: Optional[int] = None
        self.role: str = ""
        self.last_dump_path: Optional[str] = None
        self._dump_seq = 0
        self._dump_lock = threading.Lock()

    def set_identity(self, rank=None, role=None) -> None:
        """Stamp the dump filenames/payloads with this process's gang
        identity (launch children call this before building roles)."""
        if rank is not None:
            self.rank = int(rank)
        if role is not None:
            self.role = str(role)

    def record(self, kind: str, **fields) -> None:
        """Append one event.  ``kind`` is a short slug (``op``, ``task``,
        ``eviction``, ``retry_exhausted``, ``scheduler_stall``, ...)."""
        self.events.append((time.monotonic(), kind, fields))

    # -- dump ----------------------------------------------------------------

    def _dir(self) -> str:
        return os.environ.get(ENV_DIR, "") or tempfile.gettempdir()

    def dump(self, reason: str, tasks: Optional[List[Tuple[str, str]]] = None,
             **extra) -> Optional[str]:
        """Write the ring (+ live task table + in-flight ops + metrics
        snapshot) to disk; returns the path.  Never raises: a failing
        postmortem writer must not mask the failure being reported."""
        with self._dump_lock:
            self._dump_seq += 1
            seq = self._dump_seq
        who = f"rank{self.rank}" if self.rank is not None else f"pid{os.getpid()}"
        suffix = "" if seq == 1 else f"_{seq}"
        path = os.path.join(self._dir(),
                            f"mpit_flight_{who}_{reason}{suffix}.json")
        off = self.epoch_offset
        from mpit_tpu.obs import spans as _spans

        rec = _spans.get_recorder()
        obj = {
            "schema": "mpit_flight/1",
            "reason": reason,
            "rank": self.rank,
            "role": self.role,
            "pid": os.getpid(),
            "wall_time": time.time(),
            "events": [
                {"t": t + off, "kind": kind, **fields}
                for t, kind, fields in list(self.events)
            ],
            "tasks": [list(t) for t in tasks] if tasks is not None else None,
            # The open causal chains: each in-flight op's wall-anchored
            # phase-mark history plus any echoed server stamps in its
            # args — a hang postmortem names the phase the op died in.
            "inflight_ops": rec.open_ops(),
            # Per-peer clock-offset estimates (obs/clock.py), so the
            # chain above maps onto a sibling rank's dump/trace.
            "clock": _clock.snapshot_all(),
            "metrics": _metrics.get_registry().snapshot(),
            # Where the cores were at death (obs/profile.py): native
            # pool threads/depth/busy, scheduler runq/CPU totals, and
            # the top tasks by CPU — a stall postmortem names the hog
            # (pool-only when profiling was off; {} with no pool).
            "resources": _resource_snapshot(),
        }
        if extra:
            obj["extra"] = extra
        try:
            with open(path, "w") as fh:
                json.dump(obj, fh)
        except OSError:
            return None
        self.last_dump_path = path
        return path


def _resource_snapshot() -> Dict[str, object]:
    """The obs/profile.py resource section; a failing snapshot must
    never mask the failure the dump reports."""
    try:
        from mpit_tpu.obs import profile as _profile

        return _profile.resource_snapshot()
    except Exception:  # pragma: no cover - defensive postmortem path
        return {}


_GLOBAL: Optional[FlightRecorder] = None
_LOCK = threading.Lock()


def get_flight():
    """The process-global flight recorder when obs is enabled, else the
    null recorder — same capture-at-construction contract as the
    registry and the span recorder."""
    if not _metrics.obs_enabled():
        return NULL_FLIGHT
    global _GLOBAL
    if _GLOBAL is None:
        with _LOCK:
            if _GLOBAL is None:
                _GLOBAL = FlightRecorder()
    return _GLOBAL


def reset() -> None:
    """Drop the global flight recorder (tests; via obs.configure)."""
    global _GLOBAL
    _GLOBAL = None


def validate_dump(path_or_obj) -> Dict[str, object]:
    """Structural validation of a flight dump: schema tag, identity
    fields, well-formed event list (numeric wall ``t`` + ``kind`` per
    event), task table shape, and a dict metrics snapshot.  Returns
    summary stats; raises ``ValueError`` on any violation."""
    if isinstance(path_or_obj, (str, os.PathLike)):
        with open(path_or_obj) as fh:
            obj = json.load(fh)
    else:
        obj = path_or_obj
    if not isinstance(obj, dict) or obj.get("schema") != "mpit_flight/1":
        raise ValueError("not a flight dump (missing schema mpit_flight/1)")
    for key in ("reason", "pid", "wall_time", "events", "metrics"):
        if key not in obj:
            raise ValueError(f"flight dump missing {key!r}")
    if not isinstance(obj["events"], list):
        raise ValueError("events is not a list")
    for i, ev in enumerate(obj["events"]):
        if not isinstance(ev, dict) or "kind" not in ev \
                or not isinstance(ev.get("t"), (int, float)):
            raise ValueError(f"event {i} malformed (needs numeric t + kind)")
    tasks = obj.get("tasks")
    if tasks is not None:
        if not isinstance(tasks, list) or any(
                not isinstance(t, list) or len(t) != 2 for t in tasks):
            raise ValueError("tasks is not a list of [name, state] pairs")
    if not isinstance(obj["metrics"], dict):
        raise ValueError("metrics snapshot is not a dict")
    reason = str(obj.get("reason", ""))
    if reason.startswith("autoscale_") or reason == "slo_breach":
        # Autoscale postmortems must carry the decision that drove them
        # and the telemetry window that justified it — a dump without
        # them names no signal and explains nothing.
        extra = obj.get("extra")
        if not isinstance(extra, dict):
            raise ValueError(f"{reason} dump has no extra payload")
        decision = extra.get("decision")
        if not isinstance(decision, dict) or "action" not in decision \
                or "reason" not in decision:
            raise ValueError(
                f"{reason} dump extra.decision must be a dict with "
                "action + reason")
        if "window" not in extra:
            raise ValueError(
                f"{reason} dump extra must carry the telemetry window "
                "(window key; null allowed for a no-data decision)")
        if reason == "slo_breach" and "breach_for_s" not in extra:
            raise ValueError(
                "slo_breach dump extra must carry breach_for_s")
    if reason == "scheduler_stall":
        # A stall postmortem must say where the cores were: the
        # resources section (obs/profile.py) with well-formed pool /
        # scheduler / top-task subsections when present.  Pool-only
        # (or empty) is legal — profiling may have been off — but a
        # malformed section would poison every stall triage tool.
        resources = obj.get("resources")
        if not isinstance(resources, dict):
            raise ValueError(
                "scheduler_stall dump has no resources section (dict "
                "required; may be empty)")
        pool = resources.get("pool")
        if pool is not None and (
                not isinstance(pool, dict)
                or not {"threads", "depth", "busy_seconds"} <= set(pool)):
            raise ValueError(
                "scheduler_stall dump resources.pool must carry "
                "threads + depth + busy_seconds")
        sched = resources.get("sched")
        if sched is not None and (
                not isinstance(sched, dict)
                or not {"runq", "cpu_seconds"} <= set(sched)):
            raise ValueError(
                "scheduler_stall dump resources.sched must carry "
                "runq + cpu_seconds")
        top = resources.get("top_tasks")
        if top is not None and (
                not isinstance(top, list) or any(
                    not isinstance(row, list) or len(row) != 2
                    or not isinstance(row[1], (int, float))
                    for row in top)):
            raise ValueError(
                "scheduler_stall dump resources.top_tasks must be "
                "[name, cpu_us] pairs")
    if reason in ("cell_failover", "cell_lag_shed"):
        # Cell-fabric postmortems (PROTOCOL.md §11): a dead or lagging
        # cell must leave its version window behind — which version was
        # being served, against which head, under which bound — or the
        # dump explains nothing about the staleness envelope crossed.
        extra = obj.get("extra")
        if not isinstance(extra, dict):
            raise ValueError(f"{reason} dump has no extra payload")
        window = extra.get("window")
        if not isinstance(window, dict) or "version" not in window:
            raise ValueError(
                f"{reason} dump extra.window must be a dict carrying "
                "the cell's version window (version key required)")
        if reason == "cell_lag_shed" and not {"head",
                                              "max_lag"} <= set(window):
            raise ValueError(
                "cell_lag_shed dump extra.window must carry head + "
                "max_lag alongside version")
    return {
        "reason": obj["reason"],
        "rank": obj.get("rank"),
        "events": len(obj["events"]),
        "tasks": len(tasks) if tasks is not None else 0,
        "inflight_ops": len(obj.get("inflight_ops") or []),
        "metrics": len(obj["metrics"]),
    }
