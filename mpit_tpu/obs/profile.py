"""CPU / utilization attribution — where do the cores actually go?

The causal decomposition (obs/causal.py) splits an op's *wall* time
onto the phase taxonomy, and the native pool (comm/pool.py) exports
busy-seconds — but neither answers the post-PR-17 questions: which
task burned the CPU, was the pool actually saturated, and did encode
*overlap* the wire or merely time-slice against it?  This module is
the attribution plane that makes those answerable:

- **Per-task CPU accounting** (:class:`Profiler`): the cooperative
  scheduler stamps ``time.thread_time()`` deltas around every task
  step (aio/scheduler.py), so each task — and, via the span recorder,
  each op span and its phases — carries ``cpu_us`` next to its wall
  time.  The clocks live *here*, never in role files (the MT-O4xx
  contract), and the disabled path is the shared
  :data:`NULL_PROFILER`: zero clock reads, zero branches beyond one
  attribute test.
- **Counter-track sampling**: a throttled sampler turns the pool's
  busy-clock/depth bindings plus the scheduler's run-queue depth into
  wall-anchorable samples; the trace exporter renders them as Chrome
  ``ph:"C"`` counter tracks (``pool_util``, ``pool_depth``,
  ``sched_runq``, ``task_cpu``) — one set per rank (counters are
  keyed per pid), merging and rendering under the existing B/E spans
  in Perfetto.
- **Overlap-efficiency reporting**: ``python -m mpit_tpu.obs profile
  <trace>`` computes per-rank core utilization (pool busy-seconds ÷
  wall × threads), the per-phase on-CPU vs off-CPU split (non-negative
  and sums-to-wall by the same clamped construction as the causal
  decomposition), the encode-while-wire fraction of chunked streams,
  and a top-tasks-by-CPU table.  ptest attaches the same figures to
  recorded boundaries under ``MPIT_BENCH_PROFILE=1`` (BENCH_r17).

Enablement: ``MPIT_OBS_PROFILE`` truthy (which implies obs, like a
trace request does), or :func:`configure` for tests.  Profiling stays
**off even when obs is on** — the thread-time stamps are a real (if
small) per-step cost the plain metrics path must not pay.

CPU times are per-thread (``time.thread_time``): a task or span is
stamped on the thread that steps it, which the cooperative scheduler
guarantees is one thread per scheduler.  A mark taken on a foreign
thread yields a negative delta, which the exporters clamp to zero —
attribution degrades, it never goes negative.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import Dict, List, Optional, Tuple

from mpit_tpu.obs import metrics as _metrics

PROFILE_ENV = "MPIT_OBS_PROFILE"

#: counter-track sampling rate cap (Hz).  200 Hz ~ 5 ms: fine enough to
#: see a 64 MB transfer's pipeline, coarse enough that a 2 s bench leg
#: stays at a few hundred samples per track.
SAMPLE_HZ = float(os.environ.get("MPIT_OBS_PROFILE_HZ", "200"))

#: sample ring capacity — (ts, track, value) tuples across all tracks;
#: bounds a long-lived process's trace rider the same way the flight
#: ring bounds a dump.
MAX_SAMPLES = int(os.environ.get("MPIT_OBS_PROFILE_SAMPLES", "32768"))

#: the counter tracks the sampler emits (one instance per rank/pid).
TRACKS = ("pool_util", "pool_depth", "sched_runq", "task_cpu")


def _current_pool():
    """The process's native worker pool if one was ever created — the
    sampler observes, it must never *instantiate* a pool."""
    try:
        from mpit_tpu.comm import pool as _pool
    except Exception:  # pragma: no cover - import cycle / stripped build
        return None
    return _pool.current_pool()


class NullProfiler:
    """Shared do-nothing profiler — the disabled path.  Reads no clock,
    accumulates nothing; hot paths test ``enabled`` once and skip the
    thread-time stamps entirely."""

    __slots__ = ()
    enabled = False
    samples: tuple = ()
    cpu_seconds = 0.0
    last_runq = 0

    def cpu_now(self) -> float:
        return 0.0

    def step(self, name: str, cpu_s: float) -> None:
        pass

    def sample(self, runq: int = 0) -> None:
        pass

    def top_tasks(self, n: int = 5) -> list:
        return []


NULL_PROFILER = NullProfiler()


class Profiler:
    """Process-local CPU/utilization accumulator (one per process; the
    role threads' schedulers share it the way they share the span
    recorder — per-task adds are GIL-atomic dict updates)."""

    enabled = True

    def __init__(self, registry=None):
        self.registry = registry if registry is not None \
            else _metrics.get_registry()
        #: task name -> accumulated on-CPU seconds (scheduler-stamped)
        self.task_cpu: Dict[str, float] = {}
        self.cpu_seconds = 0.0
        self.last_runq = 0
        #: (monotonic ts, track, value) — rendered as ph:"C" events by
        #: the trace exporter, wall-anchored with the recorder's offset.
        self.samples: deque = deque(maxlen=MAX_SAMPLES)
        self._interval = 1.0 / SAMPLE_HZ if SAMPLE_HZ > 0 else 0.0
        self._last_sample = 0.0
        self._busy_prev = 0.0
        self._busy_prev_t = 0.0
        self._m_cpu = self.registry.counter("mpit_sched_cpu_seconds_total")
        self._m_runq = self.registry.gauge("mpit_sched_runq")

    def cpu_now(self) -> float:
        """The calling thread's CPU clock (seconds).  The only
        thread-time read site in the tree — schedulers and spans stamp
        through here so the clock stays in obs."""
        return time.thread_time()

    def step(self, name: str, cpu_s: float) -> None:
        """Attribute one task step's CPU delta to ``name``."""
        if cpu_s <= 0.0:
            return  # clock noise / foreign-thread stamp: never negative
        self.task_cpu[name] = self.task_cpu.get(name, 0.0) + cpu_s
        self.cpu_seconds += cpu_s
        self._m_cpu.inc(cpu_s)

    def sample(self, runq: int = 0) -> None:
        """One throttled counter-track sample: scheduler run-queue
        depth, cumulative task CPU, and — when a native pool exists —
        its queue depth and windowed utilization (Δbusy / Δt·threads).
        Callers may invoke per ping-pass; the interval cap keeps the
        cost one clock read on the fast exit."""
        now = time.monotonic()
        if now - self._last_sample < self._interval:
            return
        self._last_sample = now
        self.last_runq = int(runq)
        self._m_runq.set(self.last_runq)
        append = self.samples.append
        append((now, "sched_runq", float(runq)))
        append((now, "task_cpu", self.cpu_seconds))
        pool = _current_pool()
        if pool is not None and not pool.serial:
            pool.sample_obs()  # folds the native busy clock + gauges
            busy = pool.busy_seconds()
            append((now, "pool_depth", float(pool.depth())))
            dt = now - self._busy_prev_t
            if self._busy_prev_t > 0.0 and dt > 0.0:
                util = (busy - self._busy_prev) / (dt * max(pool.threads, 1))
                append((now, "pool_util", min(max(util, 0.0), 1.0)))
            self._busy_prev, self._busy_prev_t = busy, now

    def top_tasks(self, n: int = 5) -> List[List[object]]:
        """``[[name, cpu_us], ...]`` — the n hottest tasks by on-CPU
        time (the flight/statusd ``resources`` table)."""
        rows = sorted(self.task_cpu.items(), key=lambda kv: -kv[1])[:n]
        return [[name, cpu * 1e6] for name, cpu in rows]


_GLOBAL: Optional[Profiler] = None
_LOCK = threading.Lock()
#: tri-state programmatic override: None = follow the environment.
_FORCED: Optional[bool] = None


def profile_enabled() -> bool:
    """True when the profiler should be live: forced via
    :func:`configure`, or ``MPIT_OBS_PROFILE`` truthy.  Profiling
    always implies obs (metrics.obs_enabled honours the same env), but
    obs alone never implies profiling."""
    if _FORCED is not None:
        return bool(_FORCED) and _metrics.obs_enabled()
    return (os.environ.get(PROFILE_ENV, "") not in ("", "0")
            and _metrics.obs_enabled())


def get_profiler():
    """The process-global profiler when profiling is enabled, else the
    null profiler — the capture-at-construction contract of the
    registry/recorder applies."""
    if not profile_enabled():
        return NULL_PROFILER
    global _GLOBAL
    if _GLOBAL is None:
        with _LOCK:
            if _GLOBAL is None:
                _GLOBAL = Profiler()
    return _GLOBAL


def configure(enabled: Optional[bool] = None, reset: bool = False) -> None:
    """Programmatic profiling enablement (tests, ptest's in-process agg
    legs).  ``enabled=None`` returns control to the environment."""
    global _FORCED, _GLOBAL
    _FORCED = enabled
    if reset:
        _GLOBAL = None


def reset() -> None:
    """Drop the global profiler and the override (via obs.configure)."""
    global _GLOBAL, _FORCED
    _GLOBAL = None
    _FORCED = None


def resource_snapshot() -> Dict[str, object]:
    """The resource section flight dumps and statusd serve: the native
    pool's live status (threads/depth/busy — sampled, never created),
    the scheduler's run-queue/CPU totals, and the top-5 tasks by CPU.
    Pool-only when profiling is off; empty when there is no pool either
    — the shape is additive so consumers probe keys, not versions."""
    out: Dict[str, object] = {}
    pool = _current_pool()
    if pool is not None:
        pool.sample_obs()
        out["pool"] = pool.status()
    prof = get_profiler()
    if prof.enabled:
        out["sched"] = {"runq": prof.last_runq,
                        "cpu_seconds": prof.cpu_seconds}
        out["top_tasks"] = prof.top_tasks(5)
    return out


# -- the offline report: python -m mpit_tpu.obs profile <trace> --------------


def _rank_windows(events) -> Dict[object, Tuple[float, float]]:
    """pid -> (first ts, last ts) over non-metadata events (µs)."""
    win: Dict[object, Tuple[float, float]] = {}
    for ev in events:
        if ev.get("ph") == "M":
            continue
        ts = ev.get("ts")
        if not isinstance(ts, (int, float)):
            continue
        te = ts + float(ev.get("dur", 0.0) or 0.0)
        pid = ev.get("pid")
        lo, hi = win.get(pid, (ts, te))
        win[pid] = (min(lo, ts), max(hi, te))
    return win


def _metric_value(snap: dict, name: str) -> float:
    """Sum of a metric across label sets in a trace metrics snapshot."""
    total = 0.0
    for full, v in (snap or {}).items():
        base = full.split("{", 1)[0]
        if base == name and isinstance(v, (int, float)):
            total += v
    return total


def _encode_while_wire(spans) -> Optional[dict]:
    """How much of the chunked clients' encode CPU-work ran *after* the
    first chunk was already on the wire — the direct client-side
    measure of the §12 pipeline (1.0 = every later chunk encoded while
    bytes moved; 0.0 = encode strictly preceded the transfer, i.e. no
    overlap was won).  Same-rank timestamps only: no clock alignment
    enters, so the fraction is exact up to mark granularity."""
    total = overlapped = 0.0
    ops = 0
    for s in spans:
        if s.side != "client" or int(s.args.get("chunks", 0) or 0) < 2:
            continue
        first_send_end = None
        for phase, ts, dur in s.phases:
            if phase == "send":
                first_send_end = ts + dur
                break
        if first_send_end is None:
            continue
        ops += 1
        for phase, ts, dur in s.phases:
            if phase != "encode" or dur <= 0:
                continue
            total += dur
            lo = max(ts, first_send_end)
            hi = ts + dur
            if hi > lo:
                overlapped += hi - lo
    if not ops or total <= 0:
        return None
    return {"ops": ops, "encode_us": total, "overlapped_us": overlapped,
            "fraction": overlapped / total}


def analyze_trace(path_or_obj, top: int = 10) -> dict:
    """The utilization report for one (merged or per-rank) trace."""
    from mpit_tpu.obs import causal as _causal

    events, other = _causal.load_trace(path_or_obj)
    spans = _causal.extract_spans(events)
    windows = _rank_windows(events)
    # counter-track census: pid -> track -> sample count
    tracks: Dict[object, Dict[str, int]] = {}
    for ev in events:
        if ev.get("ph") == "C":
            per = tracks.setdefault(ev.get("pid"), {})
            name = str(ev.get("name", ""))
            per[name] = per.get(name, 0) + 1
    ranks: Dict[str, dict] = {}
    busy_total = capacity_total = 0.0
    for rank, info in sorted((other.get("ranks") or {}).items()):
        snap = (info or {}).get("metrics") or {}
        lo, hi = windows.get(_as_pid(rank), (0.0, 0.0))
        wall_s = max(hi - lo, 0.0) / 1e6
        threads = _metric_value(snap, "mpit_pool_threads")
        busy = _metric_value(snap, "mpit_pool_busy_seconds")
        cpu = _metric_value(snap, "mpit_sched_cpu_seconds_total")
        row: Dict[str, object] = {
            "role": (info or {}).get("role", ""),
            "wall_s": wall_s,
            "cpu_s": cpu,
            "cpu_util": (cpu / wall_s) if wall_s > 0 else 0.0,
            "counter_samples": tracks.get(_as_pid(rank), {}),
        }
        if threads > 0:
            row["pool"] = {
                "threads": threads,
                "busy_s": busy,
                "util": (busy / (wall_s * threads)) if wall_s > 0 else 0.0,
            }
            busy_total += busy
            capacity_total += wall_s * threads
        ranks[str(rank)] = row
    # per-op cpu vs wall (side-split) from the span-level cpu_us rider
    ops: Dict[str, dict] = {}
    for s in spans:
        if s.cpu_us is None:
            continue
        key = f"{s.name}/{s.side or '?'}"
        wall = max(s.t1 - s.t0, 0.0)
        on = min(max(s.cpu_us, 0.0), wall)
        e = ops.setdefault(key, {"count": 0, "wall_us": 0.0,
                                 "cpu_us": 0.0, "off_cpu_us": 0.0})
        e["count"] += 1
        e["wall_us"] += wall
        e["cpu_us"] += on
        e["off_cpu_us"] += wall - on
    # top tasks by CPU across ranks (task X events carry cpu_us)
    per_task: Dict[Tuple[object, str], List[float]] = {}
    for ev in events:
        if ev.get("ph") != "X" or ev.get("cat") != "task":
            continue
        cpu = (ev.get("args") or {}).get("cpu_us")
        if not isinstance(cpu, (int, float)):
            continue
        e = per_task.setdefault((ev.get("pid"), str(ev.get("name"))),
                                [0.0, 0.0, 0.0])
        e[0] += 1
        e[1] += float(cpu)
        e[2] += float(ev.get("dur", 0.0) or 0.0)
    tasks = [{"rank": pid, "task": name, "count": int(n),
              "cpu_us": cpu, "wall_us": wall}
             for (pid, name), (n, cpu, wall) in per_task.items()]
    tasks.sort(key=lambda r: -r["cpu_us"])
    return {
        "ranks": ranks,
        "pool_overlap_efficiency": (
            busy_total / capacity_total if capacity_total > 0 else None),
        "cpu_phases": _causal.cpu_attribution(spans),
        "ops": dict(sorted(ops.items())),
        "tasks": tasks[:top],
        "streaming": _encode_while_wire(spans),
        "counter_events": sum(sum(per.values()) for per in tracks.values()),
    }


def _as_pid(rank):
    """otherData.ranks keys are strings; event pids are ints."""
    try:
        return int(rank)
    except (TypeError, ValueError):
        return rank


def render_profile(report: dict, top: int = 10) -> str:
    lines: List[str] = []
    for rank, row in report["ranks"].items():
        pool = row.get("pool")
        pool_txt = (
            f"  pool {pool['util']:.1%} of {pool['threads']:.0f} thread(s)"
            f" ({pool['busy_s']:.3f}s busy)" if pool else "  pool -")
        samples = sum(row.get("counter_samples", {}).values())
        lines.append(
            f"rank {rank} ({row.get('role') or '?'}): wall {row['wall_s']:.3f}s"
            f"  sched-cpu {row['cpu_s']:.3f}s ({row['cpu_util']:.1%} of a core)"
            f"{pool_txt}  [{samples} counter sample(s)]")
    eff = report.get("pool_overlap_efficiency")
    if eff is not None:
        lines.append(f"pool overlap efficiency: {eff:.1%} "
                     "(busy-seconds / wall x threads, all pooled ranks)")
    stream = report.get("streaming")
    if stream:
        lines.append(
            f"encode-while-wire: {stream['fraction']:.1%} of "
            f"{stream['encode_us'] / 1e3:.3f}ms encode across "
            f"{stream['ops']} chunked op(s) ran after chunk 0 shipped")
    for key, e in report.get("ops", {}).items():
        if not e["wall_us"]:
            continue
        lines.append(
            f"op {key}: n={e['count']}  wall {e['wall_us'] / 1e3:.3f}ms  "
            f"cpu {e['cpu_us'] / 1e3:.3f}ms "
            f"({e['cpu_us'] / e['wall_us']:.1%} on-cpu)")
    phases = report.get("cpu_phases")
    if phases:
        lines.append(f"  {'op/side.phase':<32}{'wall ms':>10}{'cpu ms':>10}"
                     f"{'off ms':>10}{'on-cpu':>8}")
        for key, per in phases.items():
            for phase, e in per.items():
                share = e["cpu_us"] / e["wall_us"] if e["wall_us"] else 0.0
                lines.append(
                    f"  {key + '.' + phase:<32}"
                    f"{e['wall_us'] / 1e3:>10.3f}{e['cpu_us'] / 1e3:>10.3f}"
                    f"{e['off_cpu_us'] / 1e3:>10.3f}{share:>8.1%}")
    for row in report.get("tasks", [])[:top]:
        lines.append(
            f"task r{row['rank']}:{row['task']}: cpu "
            f"{row['cpu_us'] / 1e3:.3f}ms over {row['count']} run(s) "
            f"({row['wall_us'] / 1e3:.3f}ms wall)")
    if not report.get("counter_events"):
        lines.append("counter tracks: none (profiling was off, or the "
                     "trace predates them)")
    return "\n".join(lines)


def main(argv: Optional[List[str]] = None) -> int:
    """``python -m mpit_tpu.obs profile`` entry point."""
    import argparse
    import sys

    parser = argparse.ArgumentParser(
        prog="python -m mpit_tpu.obs profile",
        description="CPU/utilization attribution for a merged trace: "
                    "per-rank core use, on/off-CPU phase split, pool "
                    "overlap efficiency, top tasks by CPU")
    parser.add_argument("trace", help="merged Chrome trace (obs/trace.py)")
    parser.add_argument("--json", action="store_true", dest="as_json",
                        help="emit the machine-readable report")
    parser.add_argument("--top", type=int, default=10,
                        help="task rows to print")
    parser.add_argument("--require-counters", action="store_true",
                        help="exit 1 unless the trace carries ph:'C' "
                             "counter samples (CI gate)")
    args = parser.parse_args(argv)
    try:
        report = analyze_trace(args.trace, top=args.top)
    except (OSError, ValueError) as exc:
        print(f"{args.trace}: cannot profile: {exc}", file=sys.stderr)
        return 2
    if args.as_json:
        print(json.dumps(report))
    else:
        print(render_profile(report, top=args.top))
    if args.require_counters and not report.get("counter_events"):
        print("no counter-track samples in trace (MPIT_OBS_PROFILE off?)",
              file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
