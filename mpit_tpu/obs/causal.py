"""Causal op tracing — join both halves of every PS op, decompose its
latency, find the critical path.

The merged Chrome trace (obs/trace.py) holds every rank's op spans, but
each span only knows its own side: "this GRAD took 40 ms on the client"
and "a GRAD from client 3 took 2 ms to apply" are separate rows nobody
connects.  This module is the offline joiner that connects them:

1. **Parse** a trace (merged file, part file, or in-memory object) back
   into op spans: B/E pairs with their args, plus the nested ``X``
   phase events.
2. **Join** the client half and the server half of the same framed op
   on its wire identity — ``(op, client rank, server|shard, epoch,
   seq)`` — into a *causal chain*.  A retried op contributes one client
   span (its attempts segmented by the ``backoff`` marks) and every
   server span its frames produced (the apply plus any dup re-acks).
3. **Align clocks.**  Cross-rank subtractions use the per-pair offset:
   primarily the FLAG_TIMING estimator state embedded in
   ``otherData.clock`` (obs/clock.py), falling back to the same
   minimum-RTT estimate derived from the joined span pairs themselves
   (client send-complete / server receive / server ack-send / client
   ack-receive are the four NTP marks), so traces captured without the
   wire extension still align.
4. **Decompose** each joined op's client wall time onto the fixed phase
   taxonomy — ``encode`` → ``send-queue`` → ``wire`` → ``server-queue``
   → ``apply`` → ``ack-wire`` → ``client-wait``, plus ``retry`` for the
   attempts that died (docs/OBSERVABILITY.md, *Causal phase taxonomy*).
   Durations are non-negative and sum to the op's client wall time by
   construction; a raw segment more negative than the pair's clock
   uncertainty is reported as a **violation** (it means the join or the
   clock model is wrong — CI fails on it).
5. **Analyze**: per-(op, phase) percentiles, each op's dominant phase,
   the slowest chains, and the per-client phase attribution whose
   worst row is the gang's critical path.  Rendered as a text report or
   ``--json``; ``--emit-flow`` writes the trace back out with Chrome
   flow events (``ph:"s"``/``ph:"f"``) so Perfetto draws the
   client→server and server→client arrows along every chain.

CLI: ``python -m mpit_tpu.obs analyze <trace.json> [--json]
[--min-join F] [--top N] [--emit-flow PATH]``.  Exit 1 on negative
phases beyond clock uncertainty, or a join rate below ``--min-join``.

Stdlib-only on purpose: runs on CI boxes and laptops with nothing but
the trace file.
"""

from __future__ import annotations

import json
import os
from typing import Dict, List, Optional, Tuple

from mpit_tpu.obs.clock import PeerClock

#: the phase taxonomy, in causal order.  ``retry`` holds the time spent
#: in dead attempts + backoff (zero for ops that succeeded first try);
#: ``client_wait`` is the residual that makes the decomposition sum to
#: the op's client wall time (decode, scheduler resumption latency, and
#: whatever clock error the uncertainty bound absorbs).
PHASES = ("encode", "send_queue", "wire", "stream", "server_queue",
          "apply", "ack_wire", "retry", "client_wait")

#: ops the joiner considers (framed PS data ops; MIGRATE spans carry no
#: [epoch, seq] and are not point-to-point client ops).
JOINABLE_OPS = ("GRAD", "PARAM", "PARAM_PUSH")


class Span:
    """One reconstructed op span from the trace."""

    __slots__ = ("pid", "tid", "name", "t0", "t1", "args", "outcome",
                 "phases", "phase_cpu", "cpu_us")

    def __init__(self, pid, tid, name, t0, args):
        self.pid = pid
        self.tid = tid
        self.name = name
        self.t0 = float(t0)  # wall µs
        self.t1: float = float(t0)
        self.args = dict(args or {})
        self.outcome = ""
        #: [(phase, ts_us, dur_us)] in trace order
        self.phases: List[Tuple[str, float, float]] = []
        #: per-phase on-CPU µs, aligned with ``phases`` (None for
        #: entries whose X event carried no cpu rider — profiling off)
        self.phase_cpu: List[Optional[float]] = []
        #: span-level on-CPU µs from the E event rider (None when the
        #: trace predates profiling or it was off)
        self.cpu_us: Optional[float] = None

    @property
    def side(self) -> str:
        return str(self.args.get("side", ""))

    def mark_ts(self, phase: str, last: bool = True) -> Optional[float]:
        """Timestamp of the last (or first) mark named ``phase``."""
        hits = [ts for name, ts, _ in self.phases if name == phase]
        if not hits:
            return None
        return hits[-1] if last else hits[0]


def load_trace(path_or_obj):
    """The trace's (events, otherData) from a path or in-memory object."""
    if isinstance(path_or_obj, (str, os.PathLike)):
        with open(path_or_obj) as fh:
            obj = json.load(fh)
    else:
        obj = path_or_obj
    if isinstance(obj, list):
        return obj, {}
    return obj.get("traceEvents", []), obj.get("otherData", {}) or {}


def extract_spans(events) -> List[Span]:
    """Rebuild op spans from B/E pairs, attaching the ``ps_phase`` X
    events that fall inside them.  Channels are protocol-sequential per
    (pid, tid), so one open-span slot per channel suffices."""
    spans: List[Span] = []
    open_span: Dict[Tuple, Span] = {}
    for ev in events:
        ph = ev.get("ph")
        key = (ev.get("pid"), ev.get("tid"))
        if ph == "B" and ev.get("cat") == "ps_op":
            open_span[key] = Span(ev.get("pid"), ev.get("tid"),
                                  ev.get("name"), ev.get("ts", 0.0),
                                  ev.get("args"))
        elif ph == "X" and ev.get("cat") == "ps_phase":
            span = open_span.get(key)
            if span is not None:
                name = str(ev.get("name", ""))
                phase = name.rsplit(".", 1)[-1]
                span.phases.append((phase, float(ev.get("ts", 0.0)),
                                    float(ev.get("dur", 0.0))))
                cpu = (ev.get("args") or {}).get("cpu_us")
                span.phase_cpu.append(
                    float(cpu) if isinstance(cpu, (int, float)) else None)
        elif ph == "E" and ev.get("cat") == "ps_op":
            span = open_span.pop(key, None)
            if span is not None:
                span.t1 = float(ev.get("ts", span.t0))
                end_args = ev.get("args") or {}
                span.outcome = str(end_args.get("outcome", ""))
                cpu = end_args.get("cpu_us")
                if isinstance(cpu, (int, float)):
                    span.cpu_us = float(cpu)
                spans.append(span)
    return spans


def _chain_key(span: Span):
    """The wire identity both halves share: (op, client rank,
    server|shard, epoch, seq).  Client spans name the server (or shard)
    in ``peer`` and themselves in ``rank``; server spans the reverse."""
    a = span.args
    epoch, seq = a.get("epoch"), a.get("seq")
    if epoch is None or seq is None:
        return None
    if span.side == "client":
        client = a.get("rank", span.pid)
        server = (("shard", a["shard"]) if "shard" in a
                  else ("srv", a.get("peer")))
    elif span.side == "server":
        client = a.get("peer")
        server = (("shard", a["shard"]) if "shard" in a
                  else ("srv", a.get("rank", span.pid)))
    else:
        return None
    return (span.name, client, server, epoch, seq)


class Chain:
    """One causal op chain: the client span plus every server span its
    frames produced, with the attempt segmentation."""

    __slots__ = ("key", "client", "servers")

    def __init__(self, key):
        self.key = key
        self.client: Optional[Span] = None
        self.servers: List[Span] = []

    @property
    def op(self) -> str:
        return self.key[0]

    @property
    def joined(self) -> bool:
        return self.client is not None and bool(self.servers)

    @property
    def server(self) -> Optional[Span]:
        """The server span that did the work (applied/served), else the
        first echo (a dup re-ack still timestamps the server side)."""
        for sp in self.servers:
            if sp.outcome in ("applied", "served"):
                return sp
        return self.servers[0] if self.servers else None

    def attempts(self) -> List[List[Tuple[str, float, float]]]:
        """The client span's marks segmented into attempts: a new
        attempt starts at each ``backoff`` mark (the retry loop marks
        backoff before re-sending), so a drop-every-k plan yields
        1 + retries separate attempt chains."""
        if self.client is None:
            return []
        segs: List[List[Tuple[str, float, float]]] = [[]]
        for mark in self.client.phases:
            if mark[0] == "backoff" and segs[-1]:
                segs.append([])
            segs[-1].append(mark)
        return segs


def join_spans(spans: List[Span]) -> Tuple[List[Chain], List[Span]]:
    """(chains keyed by wire identity, spans that carry no identity —
    unframed legacy ops, MIGRATE handshakes)."""
    chains: Dict[Tuple, Chain] = {}
    unkeyed: List[Span] = []
    for span in spans:
        if span.name not in JOINABLE_OPS:
            unkeyed.append(span)
            continue
        key = _chain_key(span)
        if key is None:
            unkeyed.append(span)
            continue
        chain = chains.get(key)
        if chain is None:
            chain = chains[key] = Chain(key)
        if span.side == "client":
            chain.client = span  # seqs are unique per channel
        else:
            chain.servers.append(span)
    return list(chains.values()), unkeyed


# -- clock alignment ---------------------------------------------------------


def _send_complete_ts(client: Span, last: bool = True) -> Optional[float]:
    """When an attempt's frame left the client: the end of the last
    (or first) ``send`` phase (aio_send completed; the following mark
    is the ack/recv wait — or the first ``chunk`` post for streamed
    ops)."""
    marks = reversed(client.phases) if last else client.phases
    for name, ts, dur in marks:
        if name == "send":
            return ts + dur
    return None


def _ack_done_ts(client: Span) -> float:
    """When the server's reply reached the client: the ``decode`` mark
    for reads (the reply is in hand before decoding), the span end for
    writes (the ack receive is the last thing the op does)."""
    ts = client.mark_ts("decode")
    return client.t1 if ts is None else ts


def derive_offsets(chains: List[Chain]) -> Dict[Tuple[int, int], PeerClock]:
    """Per (client, server-rank) offset estimated from the joined spans
    themselves: each chain contributes one NTP-style exchange (client
    send-complete, server span start, server last mark, client ack
    receive) and the minimum-RTT filter picks the cleanest.  Offsets
    follow the obs/clock.py convention: server clock minus client
    clock."""
    clocks: Dict[Tuple[int, int], PeerClock] = {}
    for chain in chains:
        server = chain.server
        if chain.client is None or server is None:
            continue
        t1 = _send_complete_ts(chain.client)
        if t1 is None:
            continue
        t2 = server.t0
        t3 = server.phases[-1][1] if server.phases else server.t1
        t4 = _ack_done_ts(chain.client)
        pair = (_client_rank(chain), _server_rank(chain))
        clock = clocks.get(pair)
        if clock is None:
            clock = clocks[pair] = PeerClock()
        clock.add(t1, t2, t3, t4)
    return clocks


def _client_rank(chain: Chain):
    return chain.key[1]


def _server_rank(chain: Chain):
    server = chain.server
    if server is not None:
        return server.args.get("rank", server.pid)
    kind, val = chain.key[2]
    return val if kind == "srv" else None


def recorded_offsets(other_data: dict) -> Dict[Tuple[int, int], dict]:
    """(client, server) -> estimate from the trace's embedded
    FLAG_TIMING estimator state (otherData.clock, obs/clock.py)."""
    out: Dict[Tuple[int, int], dict] = {}
    for name, peers in (other_data.get("clock") or {}).items():
        if not str(name).startswith("client"):
            continue
        try:
            crank = int(str(name)[len("client"):])
        except ValueError:
            continue
        for peer, est in (peers or {}).items():
            try:
                srank = int(peer)
            except (TypeError, ValueError):
                continue
            if est.get("accepted"):
                out[(crank, srank)] = est
    return out


class OffsetTable:
    """The per-pair offsets the decomposition subtracts with: recorded
    (wire-level) estimates where the trace carries them, span-derived
    ones otherwise."""

    def __init__(self, chains: List[Chain], other_data: dict):
        self.recorded = recorded_offsets(other_data)
        self.derived = derive_offsets(chains)

    def lookup(self, client, server) -> Tuple[float, float, str]:
        """(offset_us, uncertainty_us, source) — offset is server minus
        client; unknown pairs fall back to (0, inf) so their phases are
        reported but never counted as violations."""
        est = self.recorded.get((client, server))
        if est is not None:
            return (float(est["offset_us"]), float(est["uncertainty_us"]),
                    "wire")
        clock = self.derived.get((client, server))
        if clock is not None and clock.accepted:
            return clock.offset_us, clock.uncertainty_us, "derived"
        return 0.0, float("inf"), "none"

    def snapshot(self) -> List[dict]:
        pairs = sorted(set(self.recorded) | set(self.derived))
        out = []
        for client, server in pairs:
            offset, unc, source = self.lookup(client, server)
            out.append({"client": client, "server": server,
                        "offset_us": offset, "uncertainty_us": unc,
                        "source": source})
        return out


# -- the latency decomposition ----------------------------------------------


def decompose(chain: Chain, offsets: OffsetTable) -> Optional[dict]:
    """One joined chain onto the phase taxonomy.  Returns None when the
    chain has no client half (an orphan server span cannot anchor a
    client wall time).  All values µs, non-negative; ``neg_us`` records
    how far below zero any raw segment fell (violations are judged
    against the pair's clock uncertainty by the caller)."""
    client, server = chain.client, chain.server
    if client is None:
        return None
    wall = client.t1 - client.t0
    offset, unc, source = (0.0, float("inf"), "none")
    raw: Dict[str, float] = dict.fromkeys(PHASES, 0.0)
    neg = 0.0
    first_send = client.mark_ts("send", last=False)
    last_send = client.mark_ts("send", last=True)
    encode_ts = client.mark_ts("encode", last=False)
    if encode_ts is not None and first_send is not None:
        raw["encode"] = first_send - encode_ts
    # Dead attempts + backoff: everything between the first and the
    # last send mark belongs to retries (zero when they coincide).
    if first_send is not None and last_send is not None:
        raw["retry"] = last_send - first_send
    send_done = _send_complete_ts(client)
    ack_done = _ack_done_ts(client)
    if last_send is not None and send_done is not None:
        raw["send_queue"] = send_done - last_send
    chunked = int(client.args.get("chunks", 0) or 0) >= 2
    if server is not None:
        offset, unc, source = offsets.lookup(
            _client_rank(chain), _server_rank(chain))
        # Server timestamps mapped onto the client timeline.
        srv_t0 = server.t0 - offset
        srv_first = (server.phases[0][1] - offset if server.phases
                     else srv_t0)
        srv_last = (server.phases[-1][1] - offset if server.phases
                    else server.t1 - offset)
        if chunked:
            # Streamed op (§12): after chunk 0 reaches the server, the
            # transfer, the per-chunk applies, the client's remaining
            # encodes — and any chunk resends — all run CONCURRENTLY,
            # so they cannot be summed as disjoint serial phases.  The
            # serial skeleton is: chunk-0 encode → chunk-0 handoff →
            # chunk-0 flight (``wire``) → the pipelined window
            # (``stream``: first server receipt to its last mark) →
            # the final ack's flight.  Per-chunk apply cost and the
            # measured wire/apply concurrency live in the report's
            # ``streaming`` section instead; ``retry`` stays 0 —
            # chunk resends are interleaved *inside* the stream
            # window by design (the span args still carry retries).
            send_first = _send_complete_ts(client, last=False)
            if send_first is not None:
                handoff = min(send_first, srv_t0)
                raw["wire"] = srv_t0 - handoff
                if first_send is not None:
                    raw["send_queue"] = handoff - first_send
            raw["retry"] = 0.0
            raw["stream"] = srv_last - srv_t0
            raw["ack_wire"] = ack_done - srv_last
        else:
            if send_done is not None:
                # The send-queue/wire boundary is the causal handoff:
                # the server can legitimately *receive* the frame
                # before the client's cooperative scheduler observes
                # its own send completion (shm ring handoff + poll
                # latency), so the boundary is min(send-complete,
                # server-receive).  Only server-receive preceding the
                # send *start* breaks causality — that is what the
                # violation check catches.
                handoff = min(send_done, srv_t0)
                raw["wire"] = srv_t0 - handoff
                if last_send is not None:
                    raw["send_queue"] = handoff - last_send
            raw["server_queue"] = srv_first - srv_t0
            raw["apply"] = srv_last - srv_first
            raw["ack_wire"] = ack_done - srv_last
    clamped = {}
    for phase in PHASES:
        value = raw[phase]
        if value < 0:
            neg = max(neg, -value)
            value = 0.0
        clamped[phase] = value
    spent = sum(clamped.values())
    clamped["client_wait"] = max(wall - spent, 0.0)
    if spent > wall:
        neg = max(neg, spent - wall)
    return {
        "op": chain.op,
        "client": _client_rank(chain),
        "server": _server_rank(chain),
        "epoch": chain.key[3],
        "seq": chain.key[4],
        "wall_us": wall,
        "phases": clamped,
        "retries": int(client.args.get("retries", 0) or 0),
        "attempts": len(chain.attempts()),
        "outcome": client.outcome,
        "joined": server is not None,
        "offset_source": source,
        "uncertainty_us": unc,
        "neg_us": neg,
    }


def _percentile(sorted_values: List[float], q: float) -> float:
    if not sorted_values:
        return 0.0
    idx = min(int(q * len(sorted_values)), len(sorted_values) - 1)
    return sorted_values[idx]


# -- CPU attribution (obs/profile.py riders) ---------------------------------


def cpu_attribution(spans: List[Span]) -> Optional[dict]:
    """The on-CPU vs off-CPU split of every marked phase, aggregated
    per ``op/side`` — the CPU sibling of the wall decomposition.  Uses
    the ``cpu_us`` riders the trace exporter attaches when profiling
    ran; same-thread stamps, so no clock alignment enters.  Each row is
    non-negative and sums to its phase wall by construction: on-CPU is
    the rider clamped to ``[0, wall]``, off-CPU the remainder (the
    same clamping discipline as :func:`decompose`).  None when no span
    carried a rider (profiling was off)."""
    per: Dict[Tuple[str, str], Dict[str, List[float]]] = {}
    found = False
    for span in spans:
        rows = per.setdefault((span.name, span.side or "?"), {})
        for (phase, _ts, dur), cpu in zip(span.phases, span.phase_cpu):
            if cpu is None:
                continue
            found = True
            wall = max(dur, 0.0)
            on = min(max(cpu, 0.0), wall)
            acc = rows.setdefault(phase, [0.0, 0.0, 0.0])
            acc[0] += 1
            acc[1] += wall
            acc[2] += on
        if span.cpu_us is not None:
            found = True
            wall = max(span.t1 - span.t0, 0.0)
            on = min(max(span.cpu_us, 0.0), wall)
            acc = rows.setdefault("(span)", [0.0, 0.0, 0.0])
            acc[0] += 1
            acc[1] += wall
            acc[2] += on
    if not found:
        return None
    out: Dict[str, dict] = {}
    for (op, side), rows in sorted(per.items()):
        if not rows:
            continue
        out[f"{op}/{side}"] = {
            phase: {
                "count": int(n),
                "wall_us": wall,
                "cpu_us": on,
                "off_cpu_us": wall - on,
            }
            for phase, (n, wall, on) in sorted(rows.items())
        }
    return out or None


# -- streaming overlap (FLAG_CHUNKED, docs/PROTOCOL.md §12) ------------------


def streaming_overlap(chain: Chain,
                      offsets: "OffsetTable") -> Optional[dict]:
    """Phase-overlap evidence for one chunked write chain: how long the
    server had *already been applying* chunks while this client was
    still moving bytes.  The client marks ``flush`` when its last chunk
    send completed (ps/client.py); the server's first ``apply`` mark is
    when chunk 0 started folding in.  ``overlap_us = flush − aligned
    first-apply`` — positive means wire and apply ran concurrently,
    which is exactly the pipelining the chunked transfer exists to buy
    (an unchunked op has the whole apply strictly after the whole
    transfer, so this quantity is necessarily ≤ 0 there)."""
    client, server = chain.client, chain.server
    if client is None or server is None:
        return None
    chunks = int(client.args.get("chunks", 0) or 0)
    if chunks < 2:
        return None
    flush = client.mark_ts("flush")
    first_apply = server.mark_ts("apply", last=False)
    if flush is None or first_apply is None:
        return None
    offset, unc, source = offsets.lookup(
        _client_rank(chain), _server_rank(chain))
    return {
        "op": chain.op,
        "client": _client_rank(chain),
        "server": _server_rank(chain),
        "epoch": chain.key[3],
        "seq": chain.key[4],
        "chunks": chunks,
        "overlap_us": flush - (first_apply - offset),
        "uncertainty_us": unc,
        "offset_source": source,
    }


def aggregation_section(rows: List[Span]) -> Optional[dict]:
    """The §13 REDUCE summary: reduction rounds per rank, fan-in, the
    fold→forward window, stragglers excluded and fallbacks taken.
    REDUCE spans are client↔client — they never join a server half, so
    they get this section instead of entering the join-rate accounting
    (which would read every reduction as a failed join)."""
    if not rows:
        return None
    walls = sorted(s.t1 - s.t0 for s in rows)
    folds = []
    for s in rows:
        start = s.mark_ts("fold", last=False)
        end = s.mark_ts("forward") or s.mark_ts("send") or s.t1
        if start is not None and end is not None and end >= start:
            folds.append(end - start)
    fanins = sorted(float(s.args.get("nfold", 0)) for s in rows
                    if s.args.get("nfold"))
    return {
        "rounds": len(rows),
        "ranks": len({s.args.get("rank") for s in rows}),
        "ok": sum(1 for s in rows if s.outcome == "ok"),
        "late_folds": int(sum(float(s.args.get("late", 0))
                              + float(s.args.get("group_late", 0))
                              for s in rows)),
        "fallbacks": sum(1 for s in rows if s.args.get("fallback")),
        "fanin_p50": _percentile(fanins, 0.50) if fanins else 0.0,
        "wall_p50_us": _percentile(walls, 0.50),
        "fold_p50_us": _percentile(sorted(folds), 0.50) if folds else 0.0,
    }


def analyze(path_or_obj, min_join: float = 0.0) -> dict:
    """The full analysis of one trace.  Returns the report dict (the
    ``--json`` payload); rendering and exit-code policy live in
    :func:`main`."""
    events, other = load_trace(path_or_obj)
    spans = extract_spans(events)
    # CPU attribution covers every span kind (REDUCE hops burn CPU in
    # their folds too), so it is computed before the REDUCE filter.
    cpu_section = cpu_attribution(spans)
    # REDUCE spans (§13) are summarized separately — a reduction hop has
    # no server half to join.
    agg_rows = [s for s in spans if s.name == "REDUCE"]
    spans = [s for s in spans if s.name != "REDUCE"]
    chains, _unkeyed = join_spans(spans)
    offsets = OffsetTable(chains, other)
    decomposed = [d for d in (decompose(c, offsets) for c in chains)
                  if d is not None]
    # Join accounting: a framed client op that *completed* must have a
    # server half somewhere in the trace.  Ops that died client-side
    # (aborted shutdown races, exhausted retries) legitimately may not —
    # they are reported, not counted against the join rate.
    completed = [d for d in decomposed
                 if d["outcome"] not in ("aborted", "exhausted")]
    joined = [d for d in completed if d["joined"]]
    join_rate = (len(joined) / len(completed)) if completed else 1.0
    # Violations: a raw segment below zero by more than the pair's
    # clock uncertainty (plus 1 µs of timestamp quantization).
    violations = [
        {"op": d["op"], "client": d["client"], "server": d["server"],
         "epoch": d["epoch"], "seq": d["seq"], "neg_us": d["neg_us"],
         "uncertainty_us": d["uncertainty_us"]}
        for d in decomposed
        if d["neg_us"] > d["uncertainty_us"] + 1.0
    ]
    # Per-(op, phase) stats over the joined chains.
    stats: Dict[str, Dict[str, dict]] = {}
    for op in sorted({d["op"] for d in joined}):
        rows = [d for d in joined if d["op"] == op]
        per_phase = {}
        for phase in PHASES:
            values = sorted(d["phases"][phase] for d in rows)
            per_phase[phase] = {
                "count": len(values),
                "total_us": sum(values),
                "p50_us": _percentile(values, 0.50),
                "p90_us": _percentile(values, 0.90),
                "p99_us": _percentile(values, 0.99),
            }
        walls = sorted(d["wall_us"] for d in rows)
        stats[op] = {"phases": per_phase, "count": len(rows),
                     "wall_p50_us": _percentile(walls, 0.50),
                     "wall_p99_us": _percentile(walls, 0.99)}
    # Dominant phase per op + the gang critical path: the client rank
    # whose ops spent the most total time, with its phase attribution.
    dominant: Dict[str, int] = {}
    per_client: Dict[object, Dict[str, float]] = {}
    for d in joined:
        top = max(PHASES, key=lambda p: d["phases"][p])
        dominant[top] = dominant.get(top, 0) + 1
        acc = per_client.setdefault(d["client"], dict.fromkeys(PHASES, 0.0))
        for phase in PHASES:
            acc[phase] += d["phases"][phase]
    critical = None
    if per_client:
        worst = max(per_client, key=lambda c: sum(per_client[c].values()))
        phases = per_client[worst]
        critical = {
            "client": worst,
            "total_us": sum(phases.values()),
            "phases": phases,
            "dominant": max(PHASES, key=lambda p: phases[p]),
        }
    # Streaming overlap (§12): chunked write chains report how much of
    # the server's apply ran while the client was still sending — the
    # causal decomposition's direct view of the pipeline.
    stream_rows = [r for r in (streaming_overlap(c, offsets)
                               for c in chains) if r is not None]
    streaming = None
    if stream_rows:
        overlaps = sorted(r["overlap_us"] for r in stream_rows)
        streaming = {
            "ops": len(stream_rows),
            "overlapped": sum(1 for r in stream_rows
                              if r["overlap_us"] > 0),
            "overlap_p50_us": _percentile(overlaps, 0.50),
            "overlap_p90_us": _percentile(overlaps, 0.90),
            "chunks_p50": _percentile(
                sorted(float(r["chunks"]) for r in stream_rows), 0.50),
        }
    slowest = sorted(joined, key=lambda d: -d["wall_us"])[:16]
    return {
        "spans": len(spans),
        "ops": {
            "framed": len(decomposed),
            "completed": len(completed),
            "joined": len(joined),
            "join_rate": join_rate,
            "min_join": min_join,
        },
        "offsets": offsets.snapshot(),
        "phase_stats": stats,
        "dominant_phases": dominant,
        "critical_path": critical,
        "streaming": streaming,
        "aggregation": aggregation_section(agg_rows),
        "cpu_attribution": cpu_section,
        "slowest": slowest,
        "violations": violations,
        "chains": decomposed,
    }


# -- flow events (Perfetto arrows) ------------------------------------------


def flow_events(chains: List[Chain]) -> List[dict]:
    """Chrome flow-event pairs for every joined chain: a request arrow
    from the client's send-complete to the server span start, and a
    reply arrow from the server's last mark back to the client's ack
    receipt.  ``ph:"s"`` starts a flow, ``ph:"f"`` with ``bp:"e"``
    finishes it *enclosed* in the span under the cursor."""
    events: List[dict] = []
    flow_id = 0
    for chain in chains:
        client, server = chain.client, chain.server
        if client is None or server is None:
            continue
        send_done = _send_complete_ts(client)
        if send_done is None:
            continue
        flow_id += 1
        name = f"{chain.op} [{chain.key[3]},{chain.key[4]}]"
        common = {"cat": "causal", "name": name}
        events.append({**common, "ph": "s", "id": flow_id,
                       "pid": client.pid, "tid": client.tid,
                       "ts": send_done})
        events.append({**common, "ph": "f", "bp": "e", "id": flow_id,
                       "pid": server.pid, "tid": server.tid,
                       "ts": server.t0})
        flow_id += 1
        srv_last = (server.phases[-1][1] if server.phases else server.t1)
        events.append({**common, "ph": "s", "id": flow_id,
                       "pid": server.pid, "tid": server.tid,
                       "ts": srv_last})
        events.append({**common, "ph": "f", "bp": "e", "id": flow_id,
                       "pid": client.pid, "tid": client.tid,
                       "ts": _ack_done_ts(client)})
    return events


def emit_flow(path_or_obj, out_path: str) -> int:
    """Write the trace back out with flow events appended; returns the
    number of flow events added."""
    events, other = load_trace(path_or_obj)
    chains, _ = join_spans(extract_spans(events))
    flows = flow_events(chains)
    merged = sorted(events + flows, key=lambda e: e.get("ts", -1.0))
    with open(out_path, "w") as fh:
        json.dump({"traceEvents": merged, "displayTimeUnit": "ms",
                   "otherData": other}, fh)
    return len(flows)


# -- rendering ---------------------------------------------------------------


def _ms(us: float) -> str:
    return f"{us / 1000.0:8.3f}"


def render_report(report: dict, top: int = 5) -> str:
    lines: List[str] = []
    ops = report["ops"]
    lines.append(
        f"framed ops: {ops['framed']}  completed: {ops['completed']}  "
        f"joined: {ops['joined']}  join rate: {ops['join_rate']:.1%}")
    for entry in report["offsets"]:
        unc = entry["uncertainty_us"]
        lines.append(
            f"clock: client {entry['client']} <-> server {entry['server']}"
            f": offset {entry['offset_us']:+.1f}us"
            + (f" +-{unc:.1f}us" if unc != float("inf") else " (unbounded)")
            + f" [{entry['source']}]")
    for op, st in report["phase_stats"].items():
        lines.append(
            f"{op}: n={st['count']}  wall p50 {_ms(st['wall_p50_us'])}ms"
            f"  p99 {_ms(st['wall_p99_us'])}ms")
        lines.append(f"  {'phase':<13}{'p50 ms':>10}{'p99 ms':>10}"
                     f"{'total ms':>11}{'share':>8}")
        wall_total = sum(p["total_us"] for p in st["phases"].values()) or 1.0
        for phase in PHASES:
            p = st["phases"][phase]
            if not p["count"] and not p["total_us"]:
                continue
            lines.append(
                f"  {phase:<13}{_ms(p['p50_us']):>10}{_ms(p['p99_us']):>10}"
                f"{_ms(p['total_us']):>11}"
                f"{p['total_us'] / wall_total:>8.1%}")
    if report["dominant_phases"]:
        ranked = sorted(report["dominant_phases"].items(),
                        key=lambda kv: -kv[1])
        lines.append("dominant phases: " + ", ".join(
            f"{phase}={count}" for phase, count in ranked))
    crit = report["critical_path"]
    if crit:
        lines.append(
            f"critical path: client {crit['client']} "
            f"({crit['total_us'] / 1000.0:.3f}ms attributed, "
            f"dominant phase {crit['dominant']})")
    stream = report.get("streaming")
    if stream:
        lines.append(
            f"streaming: {stream['ops']} chunked op(s), "
            f"{stream['overlapped']} with wire/apply overlap "
            f"(overlap p50 {stream['overlap_p50_us'] / 1000.0:.3f}ms, "
            f"p90 {stream['overlap_p90_us'] / 1000.0:.3f}ms, "
            f"~{stream['chunks_p50']:.0f} chunks/op)")
    agg = report.get("aggregation")
    if agg:
        lines.append(
            f"aggregation: {agg['rounds']} reduce round(s) across "
            f"{agg['ranks']} rank(s), fan-in p50 {agg['fanin_p50']:.0f}, "
            f"fold p50 {agg['fold_p50_us'] / 1000.0:.3f}ms, "
            f"late folds {agg['late_folds']}, "
            f"fallbacks {agg['fallbacks']}")
    cpu = report.get("cpu_attribution")
    if cpu:
        lines.append("cpu attribution (on-cpu / wall per marked phase):")
        for key, rows in cpu.items():
            parts = []
            for phase, e in rows.items():
                if not e["wall_us"]:
                    continue
                parts.append(
                    f"{phase}={e['cpu_us'] / 1000.0:.3f}/"
                    f"{e['wall_us'] / 1000.0:.3f}ms")
            if parts:
                lines.append(f"  {key}: " + "  ".join(parts))
    for d in report["slowest"][:top]:
        decomp = "  ".join(f"{phase}={d['phases'][phase] / 1000.0:.3f}"
                           for phase in PHASES if d["phases"][phase] > 0)
        lines.append(
            f"slow: {d['op']} c{d['client']}->s{d['server']} "
            f"[{d['epoch']},{d['seq']}] wall {d['wall_us'] / 1000.0:.3f}ms"
            f" ({decomp})")
    if report["violations"]:
        for v in report["violations"][:top]:
            lines.append(
                f"VIOLATION: {v['op']} c{v['client']}->s{v['server']} "
                f"[{v['epoch']},{v['seq']}] segment {v['neg_us']:.1f}us "
                f"below zero (uncertainty {v['uncertainty_us']:.1f}us)")
        lines.append(f"{len(report['violations'])} violation(s)")
    else:
        lines.append("violations: none")
    return "\n".join(lines)


def main(argv: Optional[List[str]] = None) -> int:
    """``python -m mpit_tpu.obs analyze`` entry point."""
    import argparse
    import sys

    parser = argparse.ArgumentParser(
        prog="python -m mpit_tpu.obs analyze",
        description="join per-rank trace halves into causal op chains "
                    "and decompose their latency")
    parser.add_argument("trace", help="merged Chrome trace (obs/trace.py)")
    parser.add_argument("--json", action="store_true", dest="as_json",
                        help="emit the machine-readable report")
    parser.add_argument("--min-join", type=float, default=0.0,
                        help="exit 1 unless at least this fraction of "
                             "completed framed ops joined (CI gate)")
    parser.add_argument("--top", type=int, default=5,
                        help="slowest chains to print")
    parser.add_argument("--emit-flow", default="",
                        help="write the trace + Perfetto flow arrows here")
    args = parser.parse_args(argv)
    try:
        report = analyze(args.trace, min_join=args.min_join)
    except (OSError, ValueError) as exc:
        print(f"{args.trace}: cannot analyze: {exc}", file=sys.stderr)
        return 2
    if args.emit_flow:
        n = emit_flow(args.trace, args.emit_flow)
        print(f"{args.emit_flow}: wrote trace + {n} flow event(s)",
              file=sys.stderr)
    if args.as_json:
        # chains can be large; the JSON consumer gets everything else
        # plus bounded samples.
        payload = dict(report)
        payload["chains"] = payload["chains"][:256]
        print(json.dumps(payload))
    else:
        print(render_report(report, top=args.top))
    rc = 0
    if report["violations"]:
        rc = 1
    ops = report["ops"]
    if ops["completed"] and ops["join_rate"] < args.min_join:
        print(f"join rate {ops['join_rate']:.1%} below --min-join "
              f"{args.min_join:.1%}", file=sys.stderr)
        rc = 1
    return rc


if __name__ == "__main__":
    import sys

    sys.exit(main())
