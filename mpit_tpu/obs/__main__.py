"""``python -m mpit_tpu.obs <trace.json>...`` — validate Chrome traces
(the warning-free spelling of ``python -m mpit_tpu.obs.trace``, which
runpy grumbles about because the package imports the submodule)."""

import sys

from mpit_tpu.obs.trace import main

sys.exit(main())
