"""``python -m mpit_tpu.obs <subcommand>`` — the obs toolbox CLI.

Subcommands:

- ``validate <trace.json>...`` — structural Chrome-trace validation
  (balanced B/E pairs, well-formed events); also the default when the
  first argument is not a subcommand name, so the historical spelling
  ``python -m mpit_tpu.obs trace.json`` keeps working (CI relies on it).
- ``merge <base>`` — assemble leftover ``<base>.rank<N>.json`` part
  files from a *crashed* gang into ``<base>`` (the launcher only merges
  after a clean exit; this is the hand-assembly it replaces).  Parts are
  kept by default for further postmortem; ``--cleanup`` removes them
  after a successful merge.
- ``top --np N [--base-port P]`` — live gang telemetry table polled
  from every rank's statusd endpoint (obs/top.py).
- ``flight <dump.json>...`` — validate flight-recorder dumps
  (obs/flight.py schema).
- ``analyze <trace.json> [--json] [--min-join F] [--emit-flow PATH]``
  — join the client and server halves of every framed op into causal
  chains, align rank clocks, decompose per-op latency onto the phase
  taxonomy and report the critical path (obs/causal.py).  Exit 1 on
  negative phase durations beyond clock uncertainty or a join rate
  below ``--min-join`` — the CI obs-trace job gates on both.
- ``profile <trace.json> [--json] [--top N] [--require-counters]`` —
  CPU/utilization attribution (obs/profile.py): per-rank core use,
  the on/off-CPU split of every marked phase, pool overlap efficiency
  (busy-seconds ÷ wall × threads), encode-while-wire fraction, and the
  top tasks by CPU.  ``--require-counters`` exits 1 unless the trace
  carries ``ph:"C"`` counter-track samples (the CI profile-smoke gate).
"""

import glob as _glob
import sys


def _merge_main(argv) -> int:
    from mpit_tpu.obs import trace as obs_trace

    cleanup = "--cleanup" in argv
    argv = [a for a in argv if a != "--cleanup"]
    if len(argv) != 1:
        print("usage: python -m mpit_tpu.obs merge [--cleanup] <base-path>",
              file=sys.stderr)
        return 2
    base = argv[0]
    parts = sorted(_glob.glob(f"{base}.rank*.json"))
    if not parts:
        print(f"{base}: no {base}.rank*.json part files found",
              file=sys.stderr)
        return 1
    n = obs_trace.merge_traces(base, parts)
    stats = obs_trace.validate_trace(base)
    print(f"{base}: merged {len(parts)} part(s), {n} events, "
          f"{stats['pids']} rank(s), {stats['ops']} op span(s)")
    if cleanup:
        import os

        for p in parts:
            try:
                os.remove(p)
            except OSError:
                pass
    return 0


def _flight_main(argv) -> int:
    from mpit_tpu.obs import flight as obs_flight

    if not argv:
        print("usage: python -m mpit_tpu.obs flight <dump.json>...",
              file=sys.stderr)
        return 2
    rc = 0
    for path in argv:
        try:
            stats = obs_flight.validate_dump(path)
        except (OSError, ValueError) as exc:
            print(f"{path}: INVALID: {exc}", file=sys.stderr)
            rc = 1
            continue
        print(f"{path}: ok — reason={stats['reason']!r} "
              f"rank={stats['rank']} {stats['events']} event(s), "
              f"{stats['tasks']} task(s), {stats['inflight_ops']} "
              f"in-flight op(s), {stats['metrics']} metric(s)")
    return rc


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if argv and argv[0] == "merge":
        return _merge_main(argv[1:])
    if argv and argv[0] == "top":
        from mpit_tpu.obs.top import main as top_main

        return top_main(argv[1:])
    if argv and argv[0] == "flight":
        return _flight_main(argv[1:])
    if argv and argv[0] == "analyze":
        from mpit_tpu.obs.causal import main as analyze_main

        return analyze_main(argv[1:])
    if argv and argv[0] == "profile":
        from mpit_tpu.obs.profile import main as profile_main

        return profile_main(argv[1:])
    if argv and argv[0] == "validate":
        argv = argv[1:]
    from mpit_tpu.obs.trace import main as validate_main

    return validate_main(argv)


if __name__ == "__main__":
    sys.exit(main())
