"""Process-local metrics registry — counters, gauges, log2 histograms.

Design rules, in priority order:

- **Disabled is a no-op object.**  ``get_registry()`` returns
  :data:`NULL_REGISTRY` unless obs is enabled; every instrument it hands
  out is the one shared :data:`NULL` singleton whose methods do nothing
  and read no clock.  Hot paths hold instrument references and call
  ``.inc()`` unconditionally — the null object *is* the off switch.
- **Lock-cheap.**  Instrument creation (get-or-create by name+labels)
  takes the registry lock; the instruments themselves update plain
  attributes with single bytecode-level operations, which the GIL makes
  safe for the counting we do (transport reader threads + role threads).
  Call sites on hot paths cache their instruments at construction.
- **Zero-dep.**  Stdlib only; importable from the analyzer, the bench
  children, and CI boxes without jax or the native build.

Histograms use **fixed log2 buckets**: bucket ``i`` counts values in
``[2^(i + LO_EXP - 1), 2^(i + LO_EXP))`` — one ``math.frexp`` per
observe, no per-histogram bucket-bound configuration to disagree on,
and the same scheme serves seconds (2^-20 ≈ 1 µs granularity floor) and
byte sizes (top bucket ≥ 2^31).  Snapshots render only non-empty
buckets, keyed by their upper-bound exponent.
"""

from __future__ import annotations

import math
import os
import threading
import time
from typing import Dict, Optional, Tuple

ENV = "MPIT_OBS"
TRACE_ENV = "MPIT_OBS_TRACE"
HTTP_ENV = "MPIT_OBS_HTTP"
PROFILE_ENV = "MPIT_OBS_PROFILE"

#: log2 histogram layout (see module docstring).
HIST_LO_EXP = -20
HIST_BUCKETS = 52


def bucket_index(value: float) -> int:
    """Bucket for ``value``: values in [2^(e-1), 2^e) land in the bucket
    whose exponent is ``e`` (clamped to the fixed range; <= 0 -> 0)."""
    if value <= 0.0:
        return 0
    e = math.frexp(value)[1]
    return min(max(e - HIST_LO_EXP, 0), HIST_BUCKETS - 1)


def bucket_upper(index: int) -> float:
    """Exclusive upper bound of bucket ``index`` (2.0 ** exponent)."""
    return 2.0 ** (index + HIST_LO_EXP)


def _render_name(name: str, labels: Tuple[Tuple[str, object], ...]) -> str:
    if not labels:
        return name
    inner = ",".join(f'{k}="{v}"' for k, v in labels)
    return f"{name}{{{inner}}}"


class Counter:
    """Monotone accumulator (ints or float sums like idle seconds)."""

    __slots__ = ("name", "labels", "value")

    def __init__(self, name: str, labels: Tuple[Tuple[str, object], ...] = ()):
        self.name = name
        self.labels = labels
        self.value = 0

    def inc(self, n=1) -> None:
        self.value += n


class Gauge:
    """Last-written value (queue depths, staged bytes, lease horizons)."""

    __slots__ = ("name", "labels", "value")

    def __init__(self, name: str, labels: Tuple[Tuple[str, object], ...] = ()):
        self.name = name
        self.labels = labels
        self.value = 0

    def set(self, v) -> None:
        self.value = v

    def add(self, dv) -> None:
        self.value += dv


class Histogram:
    """Fixed-log2-bucket distribution with count/sum/min/max."""

    __slots__ = ("name", "labels", "buckets", "count", "total", "vmin", "vmax")

    def __init__(self, name: str, labels: Tuple[Tuple[str, object], ...] = ()):
        self.name = name
        self.labels = labels
        self.buckets = [0] * HIST_BUCKETS
        self.count = 0
        self.total = 0.0
        self.vmin = math.inf
        self.vmax = -math.inf

    def observe(self, v: float) -> None:
        self.buckets[bucket_index(v)] += 1
        self.count += 1
        self.total += v
        if v < self.vmin:
            self.vmin = v
        if v > self.vmax:
            self.vmax = v

    def snapshot(self) -> Dict[str, object]:
        return {
            "count": self.count,
            "sum": self.total,
            "min": self.vmin if self.count else None,
            "max": self.vmax if self.count else None,
            # non-empty buckets only, keyed by upper-bound exponent
            "buckets": {
                i + HIST_LO_EXP: n
                for i, n in enumerate(self.buckets) if n
            },
        }


class _Timer:
    """``with registry.timer(name, **labels):`` — observes the block's
    wall seconds into a histogram.  The clock lives *here*, not at the
    call site: role files route every duration through obs (the MT-O4xx
    lint contract) instead of hand-rolling ``time.monotonic()`` pairs."""

    __slots__ = ("hist", "t0")

    def __init__(self, hist: Histogram):
        self.hist = hist
        self.t0 = 0.0

    def __enter__(self) -> "_Timer":
        self.t0 = time.perf_counter()
        return self

    def __exit__(self, *exc) -> None:
        self.hist.observe(time.perf_counter() - self.t0)


class _NullInstrument:
    """The shared do-nothing instrument AND null timer context.  One
    object serves every disabled counter/gauge/histogram/timer so the
    disabled path allocates nothing and reads no clock."""

    __slots__ = ()
    name = ""
    labels = ()
    value = 0
    count = 0
    total = 0.0

    def inc(self, n=1) -> None:
        pass

    def set(self, v) -> None:
        pass

    def add(self, dv) -> None:
        pass

    def observe(self, v) -> None:
        pass

    def snapshot(self) -> Dict[str, object]:
        return {}

    def __enter__(self) -> "_NullInstrument":
        return self

    def __exit__(self, *exc) -> None:
        pass


NULL = _NullInstrument()


class Registry:
    """One process-local metric namespace.  Instruments are get-or-create
    by (name, sorted labels); re-requesting with a different kind is a
    loud error (a counter silently shadowing a histogram would corrupt
    both streams)."""

    enabled = True

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._metrics: Dict[Tuple[str, Tuple], object] = {}

    def _get(self, cls, name: str, labels: Dict[str, object]):
        key = (name, tuple(sorted(labels.items())))
        inst = self._metrics.get(key)
        if inst is None:
            with self._lock:
                inst = self._metrics.get(key)
                if inst is None:
                    inst = cls(name, key[1])
                    self._metrics[key] = inst
        if type(inst) is not cls:
            raise TypeError(
                f"metric {_render_name(name, key[1])!r} already registered "
                f"as {type(inst).__name__}, requested as {cls.__name__}"
            )
        return inst

    def counter(self, name: str, **labels) -> Counter:
        return self._get(Counter, name, labels)

    def gauge(self, name: str, **labels) -> Gauge:
        return self._get(Gauge, name, labels)

    def histogram(self, name: str, **labels) -> Histogram:
        return self._get(Histogram, name, labels)

    def timer(self, name: str, **labels) -> _Timer:
        return _Timer(self._get(Histogram, name, labels))

    # -- export --------------------------------------------------------------

    def instruments(self):
        with self._lock:
            return list(self._metrics.values())

    def snapshot(self) -> Dict[str, object]:
        """Full-name -> value (counters/gauges) or histogram dict."""
        out: Dict[str, object] = {}
        for inst in self.instruments():
            full = _render_name(inst.name, inst.labels)
            if isinstance(inst, Histogram):
                out[full] = inst.snapshot()
            else:
                out[full] = inst.value
        return dict(sorted(out.items()))

    def format_summary(self, prefix: Optional[str] = None) -> str:
        """Compact one-line ``name=value`` rendering for log lines
        (histograms render as count/sum)."""
        parts = []
        for full, v in self.snapshot().items():
            if prefix and not full.startswith(prefix):
                continue
            if isinstance(v, dict):
                parts.append(f"{full}=n{v.get('count', 0)}/"
                             f"{float(v.get('sum') or 0.0):.3g}s")
            else:
                parts.append(f"{full}={v:g}" if isinstance(v, float)
                             else f"{full}={v}")
        return ", ".join(parts) if parts else "(no metrics)"

    def exposition(self) -> str:
        """Prometheus-style text exposition (counters as ``_total``-named
        gauges of their value; histograms as cumulative ``_bucket{le=}``
        plus ``_sum``/``_count``)."""
        lines = []
        for inst in sorted(self.instruments(),
                           key=lambda i: (i.name, i.labels)):
            base = dict(inst.labels)
            if isinstance(inst, Histogram):
                cum = 0
                for i, n in enumerate(inst.buckets):
                    if not n:
                        continue
                    cum += n
                    lines.append(_render_name(
                        inst.name + "_bucket",
                        tuple(sorted({**base, "le": f"{bucket_upper(i):g}"}
                                     .items()))) + f" {cum}")
                if inst.count:
                    lines.append(_render_name(
                        inst.name + "_bucket",
                        tuple(sorted({**base, "le": "+Inf"}.items())))
                        + f" {inst.count}")
                lines.append(_render_name(inst.name + "_sum", inst.labels)
                             + f" {inst.total:g}")
                lines.append(_render_name(inst.name + "_count", inst.labels)
                             + f" {inst.count}")
            else:
                v = inst.value
                lines.append(_render_name(inst.name, inst.labels)
                             + (f" {v:g}" if isinstance(v, float) else f" {v}"))
        return "\n".join(lines) + ("\n" if lines else "")


class NullRegistry:
    """The disabled registry: every instrument is the shared null
    singleton; exports are empty.  Never counts, never locks."""

    enabled = False

    def counter(self, name: str, **labels) -> _NullInstrument:
        return NULL

    def gauge(self, name: str, **labels) -> _NullInstrument:
        return NULL

    def histogram(self, name: str, **labels) -> _NullInstrument:
        return NULL

    def timer(self, name: str, **labels) -> _NullInstrument:
        return NULL

    def instruments(self):
        return []

    def snapshot(self) -> Dict[str, object]:
        return {}

    def format_summary(self, prefix: Optional[str] = None) -> str:
        return "(obs disabled)"

    def exposition(self) -> str:
        return ""


NULL_REGISTRY = NullRegistry()

_GLOBAL = Registry()
#: tri-state programmatic override: None = follow the environment.
_FORCED: Optional[bool] = None


def obs_enabled() -> bool:
    """True when the global registry/recorder should be live: forced via
    :func:`configure`, ``MPIT_OBS`` truthy, ``MPIT_OBS_TRACE`` set (a
    trace request implies spans, which imply metrics),
    ``MPIT_OBS_HTTP`` set (a live introspection endpoint serving an
    empty registry would be a lie), or ``MPIT_OBS_PROFILE`` truthy (a
    CPU-attribution request implies the spans/metrics it annotates —
    obs/profile.py; the reverse implication does not hold)."""
    if _FORCED is not None:
        return _FORCED
    if os.environ.get(ENV, "") not in ("", "0"):
        return True
    if os.environ.get(PROFILE_ENV, "") not in ("", "0"):
        return True
    return bool(os.environ.get(TRACE_ENV, "")
                or os.environ.get(HTTP_ENV, ""))


def get_registry():
    """The process-global registry when obs is enabled, else the null
    registry.  Capture at construction time — enabling obs after a
    component was built does not retrofit its instruments."""
    return _GLOBAL if obs_enabled() else NULL_REGISTRY


def registry_or_local(registry: Optional[Registry] = None) -> Registry:
    """An always-real registry: the explicit one > the enabled global >
    a fresh private ``Registry``.  For components whose counters are
    load-bearing *results* (PS servers/clients report them in result
    dicts and tests assert on them): they always count for real; global
    enablement only decides whether they join the process-wide
    exposition and trace dump."""
    if registry is not None:
        return registry
    reg = get_registry()
    return reg if reg.enabled else Registry()


def configure(enabled: Optional[bool] = None, reset: bool = False) -> None:
    """Programmatic enablement (tests, notebooks).  ``enabled=None``
    returns control to the environment; ``reset=True`` discards the
    global registry's instruments (and the span recorder — see
    :func:`mpit_tpu.obs.spans.reset`, which this calls)."""
    global _FORCED, _GLOBAL
    _FORCED = enabled
    if reset:
        _GLOBAL = Registry()
        from mpit_tpu.obs import clock, flight, profile, spans, statusd

        spans.reset()
        flight.reset()
        statusd.clear_providers()
        clock.reset()
        profile.reset()
