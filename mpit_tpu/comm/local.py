"""In-process transport: instant mailboxes between role endpoints.

The test/fake backend (SURVEY.md section 4: the reference uses MPI's
shared-memory transport as its de-facto fake; here single-process tests get
an even lighter one).  Also the backend for single-process multi-role runs
where server and client live on different threads of one Python process.

Semantics match the Transport contract: sends complete after delivery into
the destination mailbox; receives match by (src, tag) FIFO; probes see only
fully-delivered messages.  A configurable ``delay`` (number of polls before
progress) lets tests exercise the pending paths deterministically.
"""

from __future__ import annotations

import threading
from collections import defaultdict, deque
from typing import Any, Deque, Dict, Tuple

import numpy as np

from mpit_tpu.comm.transport import Handle, Transport, as_bytes_view, as_writable_view


class LocalRouter:
    """Shared mailbox fabric for a set of LocalTransport endpoints."""

    def __init__(self, nranks: int, delay: int = 0):
        self.nranks = nranks
        self.delay = delay
        self.lock = threading.Lock()
        # mail[dst][(src, tag)] = deque of byte payloads
        self.mail: Dict[int, Dict[Tuple[int, int], Deque[bytes]]] = {
            r: defaultdict(deque) for r in range(nranks)
        }

    def endpoint(self, rank: int) -> "LocalTransport":
        return LocalTransport(self, rank)

    def endpoints(self) -> list["LocalTransport"]:
        return [self.endpoint(r) for r in range(self.nranks)]


class LocalTransport(Transport):
    def __init__(self, router: LocalRouter, rank: int):
        self.router = router
        self.rank = rank
        self.nranks = router.nranks

    def isend(self, data: Any, dst: int, tag: int) -> Handle:
        handle = Handle(kind="send", peer=dst, tag=tag, buf=data)
        handle.meta["polls"] = 0
        return handle

    def irecv(self, src: int, tag: int, out: Any | None = None) -> Handle:
        return Handle(kind="recv", peer=src, tag=tag, out=out)

    def iprobe(self, src: int, tag: int) -> bool:
        with self.router.lock:
            return bool(self.router.mail[self.rank][(src, tag)])

    def test(self, handle: Handle) -> bool:
        if handle.done or handle.cancelled:
            return handle.done
        if handle.kind == "send":
            handle.meta["polls"] += 1
            if handle.meta["polls"] <= self.router.delay:
                return False
            payload = bytes(as_bytes_view(handle.buf))
            with self.router.lock:
                self.router.mail[handle.peer][(self.rank, handle.tag)].append(payload)
            handle.done = True
            handle.buf = None  # release ownership back to the caller
            return True
        # recv
        with self.router.lock:
            box = self.router.mail[self.rank][(handle.peer, handle.tag)]
            if not box:
                return False
            payload = box.popleft()
        if handle.out is not None:
            view = as_writable_view(handle.out)
            if len(view) != len(payload):
                raise ValueError(
                    f"recv size mismatch: message {len(payload)}B, "
                    f"buffer {len(view)}B (src={handle.peer}, tag={handle.tag})"
                )
            view[:] = payload
        else:
            handle.payload = payload
        handle.done = True
        return True

    def cancel(self, handle: Handle) -> None:
        handle.cancelled = True
        handle.buf = None
