"""TcpTransport — cross-host transport for the PS protocol (DCN analog).

The shm transport covers the reference's one-host ``mpirun -np N`` shape;
this covers its multi-node hostfile deployments (reference
BiCNN/hostfiles, README.md:57-61) for the *host-mediated* asynchronous PS
path — the traffic XLA collectives can't express.  (On-mesh trainers
already cross hosts via jax.distributed + DCN; this is the transport for
the ParamServer/ParamClient role topology.)

Same contract and semantics as :class:`mpit_tpu.comm.shm.ShmTransport`:
nonblocking (rank, tag)-addressed messaging, FIFO per channel, exact-size
receives, buffer ownership until ``test`` is True, cancel-on-shutdown.

Wire format per message: 24-byte header (tag, size, seq — int64 little
endian) + payload.  Connections form a full mesh at construction: every
rank listens on its ``host:port`` from the address book; rank i dials
every rank j < i and accepts from every j > i (each side identifies
itself with a 24-byte handshake: rank, instance nonce, and — for the
reconnect protocol — the highest sequence it has received from the
other side).  One reader thread per peer
drains frames into per-channel queues; sends run on a per-peer writer
thread so ``isend`` never blocks on a slow peer.  The outbox is
zero-copy — queued entries view the caller's buffer (owned by the
transport until ``test`` is True), so a deep backlog costs O(1)
transport-owned memory per message, not a payload copy.
"""

from __future__ import annotations

import socket
import struct
import threading
import time
from collections import defaultdict, deque
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from mpit_tpu.comm.transport import (
    Handle,
    Transport,
    as_bytes_view,
    as_writable_view,
)
from mpit_tpu.obs import metrics as _obs

_HDR = struct.Struct("<qqq")  # tag, size, seq
# rank, instance nonce, last-seq-from-you, address-book digest (the
# digest authenticates the MESH: a stale redial thread from a dead
# transport instance, or any foreign client, that reaches a reassigned
# port must not be installed as a peer).
_RANK_HDR = struct.Struct("<qqqq")
_EMPTY = memoryview(b"")
# Reserved wire tag: an orderly close() announces itself so the peer's
# reader can distinguish graceful shutdown (old silent-cancel semantics)
# from a crash (fail-loud semantics).  User tags are non-negative
# (ps/tags.py, collectives' 2^16+ range), so the sentinel can't collide.
_GOODBYE_TAG = -(1 << 62)
# Scatter-gather frame writes (one syscall for header+payload, zero
# concatenation): POSIX-only; Windows sockets lack sendmsg.
_HAS_SENDMSG = hasattr(socket.socket, "sendmsg")


class MeshMismatchError(ConnectionError):
    """The peer answered the handshake with a different address-book /
    reconnect-mode digest: it belongs to another mesh (or the two sides
    disagree on reconnect mode, which would deadlock ack-based sends).
    Raised immediately — never retried."""
# Reserved wire tag for delivery acknowledgements (reconnect mode): the
# header's seq field carries the highest data sequence received; no
# payload.  Acks are neither retained nor themselves acked — a lost ack
# is superseded by the next one or by the reconnect handshake.
_ACK_TAG = _GOODBYE_TAG + 1


def allocate_local_addresses(nranks: int) -> Tuple[List[str], List[socket.socket]]:
    """Pre-bound localhost listeners with OS-assigned ports, for tests and
    same-host runs: returns (addresses, listeners); pass ``listeners[r]``
    to rank r's transport so no port is lost to a rebind race."""
    addrs, socks = [], []
    for _ in range(nranks):
        s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        s.bind(("127.0.0.1", 0))
        s.listen(nranks)
        addrs.append(f"127.0.0.1:{s.getsockname()[1]}")
        socks.append(s)
    return addrs, socks


def _recv_exact(sock: socket.socket, n: int) -> Optional[bytes]:
    buf = bytearray(n)
    view = memoryview(buf)
    got = 0
    while got < n:
        r = sock.recv_into(view[got:], n - got)
        if r == 0:
            return None  # peer closed
        got += r
    return bytes(buf)


class _Channel:
    __slots__ = ("msgs", "pending")

    def __init__(self):
        self.msgs: deque = deque()      # fully-assembled payloads (bytes)
        self.pending: deque = deque()   # posted recv handles, FIFO


class TcpTransport(Transport):
    """See module docstring.  ``reconnect`` (seconds, default from
    ``MPIT_TCP_RECONNECT_S``, 0 = off) adds bounded fault recovery: on a
    torn connection the dialing side (higher rank) redials with backoff
    and the accepting side's persistent accept loop re-handshakes, the
    writer resends every frame not yet fully written (frames carry
    sequence numbers; the receiver drops duplicates), and a fresh
    process re-binding a dead rank's address rejoins the mesh (the
    handshake nonce tells a resumed connection from a restarted peer,
    which resets the dedup horizon).  Only after the window expires does
    the transport fall back to the fail-loud contract below."""

    def __init__(
        self,
        rank: int,
        nranks: int,
        addresses: Sequence[str],
        *,
        listener: Optional[socket.socket] = None,
        connect_timeout: float = 60.0,
        reconnect: Optional[float] = None,
        dial_peers: Optional[Sequence[int]] = None,
    ):
        import os as _os
        import secrets

        if len(addresses) != nranks:
            raise ValueError(f"need {nranks} addresses, got {len(addresses)}")
        self.rank = rank
        self.nranks = nranks
        self.addresses = list(addresses)
        self.reconnect = (
            float(_os.environ.get("MPIT_TCP_RECONNECT_S", "0"))
            if reconnect is None else float(reconnect)
        )
        self._nonce = secrets.randbits(62)
        import hashlib

        # The digest covers the address book AND the reconnect mode: a
        # reconnect>0 sender retains frames until acked, so a mixed-mode
        # pairing (one side acking, one not) would deadlock sends — make
        # it a connect-time refusal instead.
        self._book_hash = int.from_bytes(
            hashlib.blake2b(
                (",".join(self.addresses)
                 + f"|reconnect={'on' if self.reconnect > 0 else 'off'}"
                 ).encode(), digest_size=7).digest(), "little")
        self._lock = threading.Lock()
        self._channels: Dict[Tuple[int, int], _Channel] = defaultdict(_Channel)
        self._peers: Dict[int, socket.socket] = {}
        self._gen: Dict[int, int] = {r: 0 for r in range(nranks)}
        self._peer_nonce: Dict[int, int] = {}
        self._last_seq: Dict[int, int] = {r: 0 for r in range(nranks)}
        self._send_seq: Dict[int, int] = {r: 0 for r in range(nranks)}
        self._outboxes: Dict[int, deque] = {r: deque() for r in range(nranks)}
        # Reconnect mode: frames sent to the kernel but not yet
        # acknowledged by the peer (sendall != delivered) — resent after
        # a reconnect, released (handle.done) by acks.
        self._unacked: Dict[int, deque] = {r: deque() for r in range(nranks)}
        self._pending_ack: Dict[int, Any] = {}
        # Highest seq each peer has acked — consulted when retaining a
        # just-sent frame: the ack can RACE the retention (arrive between
        # sendall returning and the cv re-acquire), and a frame retained
        # after its own ack would wait forever.
        self._acked_high: Dict[int, int] = {r: 0 for r in range(nranks)}
        self._out_cv: Dict[int, threading.Condition] = {
            r: threading.Condition() for r in range(nranks)
        }
        # Peers whose writer thread has died (socket error): new isends
        # are cancelled immediately instead of queueing into a box nobody
        # drains.
        self._dead_peers: set = set()
        # Peers whose reader has died mid-run: pending receives with no
        # message to match fail loudly (raise-once from test) instead of
        # polling forever on a connection that can never deliver.
        self._dead_readers: set = set()
        self._threads: List[threading.Thread] = []
        self._disconnect_seen: set = set()
        self._closed = False
        # Per-peer traffic counters (mpit_tpu.obs): indexed by rank so
        # the hot paths never hash a label dict; the shared null
        # instrument fills every slot when obs is disabled.
        _reg = _obs.get_registry()
        self._m_tx_msgs = [_reg.counter("mpit_tcp_tx_messages_total",
                                        rank=rank, peer=r)
                           for r in range(nranks)]
        self._m_tx_bytes = [_reg.counter("mpit_tcp_tx_bytes_total",
                                         rank=rank, peer=r)
                            for r in range(nranks)]
        self._m_rx_msgs = [_reg.counter("mpit_tcp_rx_messages_total",
                                        rank=rank, peer=r)
                           for r in range(nranks)]
        self._m_rx_bytes = [_reg.counter("mpit_tcp_rx_bytes_total",
                                         rank=rank, peer=r)
                            for r in range(nranks)]
        # Send-queue depth (frames queued to each peer's writer) — the
        # live queueing-pressure signal `mpit top` renders: a peer whose
        # writer cannot drain shows a growing depth long before ops
        # start missing deadlines.
        self._m_sendq = [_reg.gauge("mpit_tcp_send_queue_depth",
                                    rank=rank, peer=r)
                         for r in range(nranks)]

        host, _, port = addresses[rank].rpartition(":")
        if listener is None:
            listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            bind_deadline = time.monotonic() + connect_timeout
            while True:
                try:
                    listener.bind((host or "0.0.0.0", int(port)))
                    break
                except OSError as e:
                    import errno as _errno

                    # A replacement process rebinding a crashed rank's
                    # address can race the old listener's teardown (a
                    # thread still blocked in accept holds the port for
                    # a moment) — retry EADDRINUSE within the window;
                    # anything else (bad host, privileged port) is a
                    # misconfiguration and fails immediately.
                    if (e.errno != _errno.EADDRINUSE
                            or time.monotonic() >= bind_deadline):
                        raise
                    time.sleep(0.1)
            listener.listen(nranks)
        self._listener = listener

        # Dial lower ranks, accept higher ranks (deadlock-free full mesh).
        # ``dial_peers`` (FT rejoin path) restricts construction to the
        # connections this endpoint actually needs: a worker restarted
        # mid-run must reach its *servers*, but a sibling worker may have
        # finished and exited — demanding its listener would turn normal
        # completion into a rejoin failure.  Skipped lower ranks are
        # marked dead (sends fail loudly, not silently queue); skipped
        # higher ranks arrive later through the persistent accept loop,
        # which is why the restriction requires reconnect mode.
        deadline = time.monotonic() + connect_timeout
        if dial_peers is None:
            to_dial = list(range(rank))
            n_accept = nranks - rank - 1
        else:
            if self.reconnect <= 0:
                raise ValueError(
                    "dial_peers needs reconnect mode (MPIT_TCP_RECONNECT_S"
                    " > 0): undialed peers can only join via the "
                    "persistent accept loop"
                )
            to_dial = sorted({int(p) for p in dial_peers} & set(range(rank)))
            self._dead_peers.update(set(range(rank)) - set(to_dial))
            n_accept = 0
        for peer in to_dial:
            conn, pnonce, peer_last = self._dial(addresses[peer], deadline,
                                                 peer)
            self._install_socket(peer, conn, pnonce, peer_last)
        for _ in range(n_accept):
            conn, _addr = self._accept(deadline)
            conn.settimeout(None)  # accepted sockets must block
            got = self._handshake_accept(conn)
            if got is None:
                raise ConnectionError("peer closed during handshake")
            self._install_socket(got[0], conn, got[1], got[2])
        if self.reconnect > 0:
            self._spawn(self._accept_loop)

    # -- connection plumbing -------------------------------------------------

    def _dial(self, address: str, deadline: float,
              peer_rank: int) -> Tuple[socket.socket, int, int]:
        """Returns (socket, peer nonce, peer's last-received seq from us)."""
        host, _, port = address.rpartition(":")
        last_err: Optional[Exception] = None
        while time.monotonic() < deadline and not self._closed:
            try:
                conn = socket.create_connection((host, int(port)), timeout=5.0)
                conn.settimeout(None)
                with self._lock:
                    my_last = self._last_seq[peer_rank]
                conn.sendall(_RANK_HDR.pack(self.rank, self._nonce, my_last,
                                            self._book_hash))
                reply = _recv_exact(conn, _RANK_HDR.size)
                if reply is None:
                    raise ConnectionError("peer closed during handshake")
                _prank, pnonce, peer_last, book = _RANK_HDR.unpack(reply)
                if book != self._book_hash:
                    conn.close()
                    raise MeshMismatchError(
                        "peer handshake digest mismatch: different mesh "
                        "or mismatched reconnect mode"
                    )
                return conn, int(pnonce), int(peer_last)
            except MeshMismatchError:
                raise  # misconfiguration — retrying cannot fix it
            except OSError as e:  # peer not up yet
                last_err = e
                time.sleep(0.05)
        raise ConnectionError(f"could not reach {address}: {last_err!r}")

    def _handshake_accept(
        self, conn: socket.socket
    ) -> Optional[Tuple[int, int, int]]:
        """Returns (peer rank, peer nonce, peer's last seq from us)."""
        peer_hdr = _recv_exact(conn, _RANK_HDR.size)
        if peer_hdr is None:
            return None
        peer, pnonce, peer_last, book = _RANK_HDR.unpack(peer_hdr)
        if not 0 <= peer < self.nranks or book != self._book_hash:
            return None
        with self._lock:
            my_last = self._last_seq[int(peer)]
        conn.sendall(_RANK_HDR.pack(self.rank, self._nonce, my_last,
                                    self._book_hash))
        return int(peer), int(pnonce), int(peer_last)

    def _install_socket(self, peer: int, conn: socket.socket,
                        pnonce: Optional[int], peer_last: int,
                        expect_gen: Optional[int] = None) -> bool:
        """Adopt ``conn`` as the live socket for ``peer`` (initial setup
        and every reconnect), revive the peer's fail-loud state, settle
        the unacked window against the peer's reported horizon, and
        start a reader/writer generation bound to this socket.  With
        ``expect_gen`` (a redial) the install is refused when the
        generation moved on (another install won, or the watchdog
        poisoned it)."""
        conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        cv = self._out_cv[peer]
        with self._lock:
            if self._closed or (expect_gen is not None
                                and self._gen[peer] != expect_gen):
                conn.close()
                return False
            old = self._peers.get(peer)
            nonce_reset = (pnonce is not None
                           and self._peer_nonce.get(peer) is not None
                           and self._peer_nonce.get(peer) != pnonce)
            if pnonce is not None and self._peer_nonce.get(peer) != pnonce:
                # A RESTARTED peer (fresh process, fresh sequence space),
                # not a resumed connection: reset the dedup horizon.
                self._peer_nonce[peer] = pnonce
                self._last_seq[peer] = 0
            self._peers[peer] = conn
            self._gen[peer] += 1
            gen = self._gen[peer]
            self._dead_readers.discard(peer)
        done_handles = []
        with cv:
            if nonce_reset:
                # Acks already queued for the DEAD instance carry
                # horizons from its sequence space; delivered to the
                # replacement they would release (and un-retain) its
                # entire early window.  Purge them.
                kept = [e for e in self._outboxes[peer]
                        if e[0].tag != _ACK_TAG]
                self._outboxes[peer].clear()
                self._outboxes[peer].extend(kept)
                self._pending_ack[peer] = None
            # Settle the unacked window: frames the peer already holds
            # (seq <= its reported horizon) are delivered; the rest go
            # back to the FRONT of the outbox, in order, for resend.
            ua = self._unacked[peer]
            resend = []
            while ua:
                entry = ua.popleft()
                if entry[3] is not None and entry[3] <= peer_last:
                    done_handles.append(entry[0])
                else:
                    resend.append(entry)
            self._outboxes[peer].extendleft(reversed(resend))
            self._dead_peers.discard(peer)
            cv.notify_all()
        for h in done_handles:
            h.done = True
            h.buf = None
        if old is not None and old is not conn:
            try:
                old.close()
            except OSError:
                pass
        self._spawn(self._reader, peer, conn, gen)
        self._spawn(self._writer, peer, conn, gen)
        return True

    def _accept(self, deadline: float) -> Tuple[socket.socket, Any]:
        self._listener.settimeout(max(deadline - time.monotonic(), 0.1))
        try:
            return self._listener.accept()
        except socket.timeout:
            raise ConnectionError("timed out waiting for peer connections")

    def _accept_loop(self) -> None:
        """Persistent re-handshake service (reconnect mode): any peer —
        resumed socket or restarted process — can dial in and replace
        its connection at any time."""
        self._listener.settimeout(0.5)
        while not self._closed:
            try:
                conn, _addr = self._listener.accept()
            except socket.timeout:
                continue
            except OSError:
                return  # listener closed
            try:
                # Bounded handshake: a connector that never sends its
                # header must not wedge the (single) accept loop.
                conn.settimeout(2.0)
                got = self._handshake_accept(conn)
                conn.settimeout(None)
            except OSError:
                conn.close()
                continue
            if got is None:
                conn.close()
                continue
            self._install_socket(got[0], conn, got[1], got[2])

    def _spawn(self, fn, *args) -> None:
        # Role-named (e.g. "_reader-1"): observable teardown for tests
        # and thread dumps.
        name = f"{fn.__name__}-{args[0] if args else ''}"
        t = threading.Thread(target=fn, args=args, daemon=True, name=name)
        t.start()
        with self._lock:
            # Prune finished threads (under the lock — concurrent spawns
            # rebuilding the list lock-free could drop each other's
            # entries) so a flapping link cannot grow it without bound.
            self._threads = [x for x in self._threads if x.is_alive()]
            self._threads.append(t)

    def _current_gen(self, peer: int) -> int:
        with self._lock:
            return self._gen[peer]

    def _on_disconnect(self, peer: int, gen: int) -> None:
        """Reader/writer generation ``gen`` observed the connection die.
        Without reconnect: fail loudly now.  With reconnect: the dialing
        side redials; both sides arm a watchdog that falls back to the
        fail-loud path if no replacement arrives in the window."""
        if self._closed or self._current_gen(peer) != gen:
            return  # stale generation or shutdown
        with self._lock:
            # Reader and writer both observe the same death; recover once.
            if (peer, gen) in self._disconnect_seen:
                return
            self._disconnect_seen = {
                (p, g) for (p, g) in self._disconnect_seen if p != peer
            }
            self._disconnect_seen.add((peer, gen))
        if self.reconnect <= 0:
            self._fail_unmatched_recvs(peer)
            self._drain_outbox(
                peer, error=f"send to rank {peer} failed: connection lost"
            )
            return
        if peer < self.rank:
            self._spawn(self._redial, peer, gen)
        self._spawn(self._reconnect_watchdog, peer, gen)

    def _redial(self, peer: int, gen: int) -> None:
        deadline = time.monotonic() + self.reconnect
        backoff = 0.05
        while (not self._closed and self._current_gen(peer) == gen
               and time.monotonic() < deadline):
            try:
                conn, pnonce, peer_last = self._dial(
                    self.addresses[peer],
                    min(time.monotonic() + backoff + 5.0, deadline), peer,
                )
            except MeshMismatchError:
                return  # foreign mesh on a reassigned port: stop redialing
            except (OSError, ConnectionError):
                time.sleep(backoff)
                backoff = min(backoff * 2, 1.0)
                continue
            # expect_gen: refused atomically if the accept loop beat us
            # or the watchdog already poisoned this generation.
            self._install_socket(peer, conn, pnonce, peer_last,
                                 expect_gen=gen)
            return

    def _reconnect_watchdog(self, peer: int, gen: int) -> None:
        deadline = time.monotonic() + self.reconnect
        while time.monotonic() < deadline:
            if self._closed or self._current_gen(peer) != gen:
                return  # replaced (or shutting down) — recovery done
            time.sleep(0.05)
        with self._lock:
            if self._closed or self._gen[peer] != gen:
                return
            # Poison the generation: a redial racing this expiry cannot
            # install afterwards (fail everything or recover everything).
            # A LATER fresh connection through the accept loop may still
            # revive the peer — the shm transport's late-resurrection
            # semantics — but never one tied to this failed window.
            self._gen[peer] += 1
        self._fail_unmatched_recvs(peer)
        self._drain_outbox(
            peer,
            error=(f"send to rank {peer} failed: connection lost "
                   f"(no reconnect within {self.reconnect}s)"),
        )

    def _reader(self, peer: int, conn: socket.socket, gen: int) -> None:
        graceful = False
        try:
            while True:
                hdr = _recv_exact(conn, _HDR.size)
                if hdr is None:
                    return
                tag, size, seq = _HDR.unpack(hdr)
                if tag == _GOODBYE_TAG:
                    graceful = True  # peer is closing in an orderly way
                    return
                if tag == _ACK_TAG:
                    # Delivery confirmation: release every retained frame
                    # up to the acked sequence.  (Stale-generation acks
                    # are ignored — _process_ack checks.)
                    self._process_ack(peer, seq, gen)
                    continue
                payload = _recv_exact(conn, int(size)) if size else b""
                if payload is None:
                    return
                with self._lock:
                    if self._gen[peer] != gen:
                        # Superseded connection (e.g. the peer restarted
                        # and the dedup horizon was reset): frames still
                        # draining from the old socket's kernel buffer
                        # must not advance state in the new seq space.
                        return
                    if seq > self._last_seq[peer]:
                        self._last_seq[peer] = seq
                        self._channels[(peer, int(tag))].msgs.append(payload)
                        self._m_rx_msgs[peer].inc()
                        self._m_rx_bytes[peer].inc(len(payload))
                    # else: duplicate from a reconnect resend — drop it,
                    # but still re-ack (the original ack may be exactly
                    # what the tear swallowed).
                    ack_val = self._last_seq[peer]
                if self.reconnect > 0:
                    self._enqueue_ack(peer, ack_val, gen)
        except OSError:
            return  # socket torn down by close() or connection loss
        finally:
            if graceful:
                # The peer is gone by protocol: frames retained for acks
                # can never be released — settle them silently (the
                # done-or-cancelled contract; same as close()'s drain).
                cv = self._out_cv[peer]
                with cv:
                    ua = self._unacked[peer]
                    while ua:
                        h = ua.popleft()[0]
                        h.cancelled = True
                        h.buf = None
                return
            if self._closed:
                return
            self._on_disconnect(peer, gen)

    def _process_ack(self, peer: int, acked: int, gen: int) -> None:
        cv = self._out_cv[peer]
        done = []
        with cv:
            with self._lock:
                if self._gen[peer] != gen:
                    return  # ack from a superseded connection
            if acked > self._acked_high[peer]:
                self._acked_high[peer] = acked
            ua = self._unacked[peer]
            while ua and ua[0][3] is not None and ua[0][3] <= acked:
                done.append(ua.popleft()[0])
        for h in done:
            h.done = True
            h.buf = None

    def _enqueue_ack(self, peer: int, acked: int, gen: int) -> None:
        cv = self._out_cv[peer]
        with cv:
            if peer in self._dead_peers or self._closed:
                return
            with self._lock:
                if self._gen[peer] != gen:
                    # A replacement connection installed between the
                    # reader's gen check and this enqueue.  If the peer
                    # RESTARTED, ``acked`` is a horizon from the dead
                    # instance's sequence space — queued onto the new
                    # connection it would release the restarted peer's
                    # entire unacked window (silent loss under the
                    # exactly-once contract).  Drop it; the new reader
                    # generation acks its own deliveries.
                    return
            pending = self._pending_ack.get(peer)
            if pending is not None:
                # Acks are cumulative: overwrite the still-queued ack's
                # horizon instead of queueing another (a gradient storm
                # would otherwise double the writer's syscall count).
                pending[1] = _HDR.pack(_ACK_TAG, 0, acked)
                return
            entry = [Handle(kind="send", peer=peer, tag=_ACK_TAG),
                     _HDR.pack(_ACK_TAG, 0, acked), _EMPTY, None]
            self._pending_ack[peer] = entry
            self._outboxes[peer].append(entry)
            cv.notify()

    def _fail_unmatched_recvs(self, peer: int) -> None:
        """A mid-run reader death (peer crashed / link dropped): every
        pending recv beyond the already-delivered backlog can never
        complete — fail them with the raise-once convention, and make
        later irecvs from this peer fail the same way.  Messages that
        arrived before the death still serve matching receives (same
        drain-what-landed semantics as the shm transport's remap)."""
        err = f"recv from rank {peer} failed: connection lost"
        with self._lock:
            self._dead_readers.add(peer)
            for (src, _tag), chan in self._channels.items():
                if src != peer:
                    continue
                live = [h for h in chan.pending if not h.cancelled]
                for h in live[len(chan.msgs):]:
                    h.cancelled = True
                    h.meta["error"] = err

    @staticmethod
    def _send_frame(conn: socket.socket, header: bytes, payload) -> None:
        """Write one frame with a scatter-gather ``sendmsg``: header and
        payload go to the kernel in a single syscall from their own
        buffers — no concatenation copy, and no separate header write
        for TCP_NODELAY to flush as its own small packet.  Loops on
        partial writes (sendmsg, like send, may stop mid-buffer)."""
        if not _HAS_SENDMSG:  # pragma: no cover - non-POSIX fallback
            conn.sendall(header)
            if payload.nbytes:
                conn.sendall(payload)
            return
        bufs = [memoryview(header)]
        if payload.nbytes:
            bufs.append(payload)
        while bufs:
            sent = conn.sendmsg(bufs)
            while bufs and sent >= bufs[0].nbytes:
                sent -= bufs[0].nbytes
                bufs.pop(0)
            if sent and bufs:
                bufs[0] = bufs[0][sent:]

    def _writer(self, peer: int, conn: socket.socket, gen: int) -> None:
        cv = self._out_cv[peer]
        box = self._outboxes[peer]
        while True:
            with cv:
                while (not box and not self._closed
                       and self._gen[peer] == gen):
                    cv.wait(0.5)
                if self._gen[peer] != gen:
                    return  # superseded: the replacement writer owns the box
                if self._closed and not box:
                    return
                if not box:
                    continue
                # PEEK, don't pop: the frame stays queued until fully
                # written, so a reconnect's replacement writer resends it
                # whole (the receiver dedups by sequence number).
                entry = box[0]
                if entry is self._pending_ack.get(peer):
                    # Detach from coalescing NOW, under the cv: the
                    # header bytes are captured on the next line, and a
                    # reader overwriting the horizon after that would be
                    # silently lost — the sender it acks would deadlock.
                    self._pending_ack[peer] = None
                handle, header, payload, retain_seq = entry
            try:
                self._send_frame(conn, header, payload)
            except OSError:
                if self.reconnect > 0 and not self._closed:
                    # Leave the frame at the head for the successor.
                    self._on_disconnect(peer, gen)
                    return
                # Dead peer/socket: cancel this and every queued send with
                # a recorded error so blocking senders get a raise from
                # test() (the shm transport's raise-once convention)
                # instead of spinning forever.
                err = f"send to rank {peer} failed: connection lost"
                handle.cancelled = True
                handle.buf = None
                handle.meta["error"] = err
                self._drain_outbox(peer, error=err)
                return
            popped = retained = False
            with cv:
                with self._lock:
                    if self._gen[peer] != gen:
                        # A reconnect installed while we were in sendall:
                        # whatever we wrote went to a dead socket, and
                        # the successor's settle owns the box — touching
                        # it (or _unacked) here would strand the frame.
                        return
                # Only settle the entry if it is still ours to settle: a
                # reconnect's settle may have already reshuffled the box
                # while we were in sendall — then the successor owns it,
                # and retaining here would corrupt _unacked's ordering.
                if box and box[0] is entry:
                    box.popleft()
                    self._m_sendq[peer].set(len(box))
                    popped = True
                    if (retain_seq is not None and self.reconnect > 0
                            and retain_seq > self._acked_high[peer]):
                        # Delivered to the kernel is NOT delivered to
                        # the peer: retain until the peer's ack (or the
                        # reconnect-handshake horizon) releases it.  (A
                        # frame whose ack already landed — the ack can
                        # race this retention — completes right away.)
                        self._unacked[peer].append(entry)
                        retained = True
            if popped and not retained:
                handle.done = True
                handle.buf = None  # ownership back to the caller

    def _drain_outbox(self, peer: int, error: str | None = None) -> None:
        """Cancel every queued send to ``peer``.  With ``error`` (dead
        peer) the handles raise from ``test``; without (orderly close)
        they cancel silently."""
        cv = self._out_cv[peer]
        with cv:
            self._dead_peers.add(peer)
            cv.notify_all()
            for q in (self._unacked[peer], self._outboxes[peer]):
                while q:
                    h = q.popleft()[0]
                    h.cancelled = True
                    h.buf = None
                    if error:
                        h.meta["error"] = error
            self._m_sendq[peer].set(0)

    # -- Transport -----------------------------------------------------------

    def isend(self, data: Any, dst: int, tag: int) -> Handle:
        if dst == self.rank or not 0 <= dst < self.nranks:
            raise ValueError(f"isend to invalid rank {dst}")
        if self._closed:
            raise RuntimeError("isend on a closed transport")
        view = as_bytes_view(b"" if data is None else data)
        handle = Handle(kind="send", peer=dst, tag=tag, buf=data)
        # Zero-copy queue: the outbox holds a *view* over the caller's
        # buffer, not a snapshot — the ownership contract already forbids
        # the caller touching it until test() is True (reported only
        # after sendall), so transport-owned memory stays O(1) per queued
        # message however deep the backlog, and isend never blocks.
        cv = self._out_cv[dst]
        with cv:
            if dst in self._dead_peers:
                handle.cancelled = True
                handle.buf = None
                handle.meta["error"] = f"rank {dst} unreachable (writer dead)"
                return handle
            self._send_seq[dst] += 1
            self._outboxes[dst].append(
                (handle, _HDR.pack(tag, view.nbytes, self._send_seq[dst]),
                 view, self._send_seq[dst])
            )
            self._m_sendq[dst].set(len(self._outboxes[dst]))
            cv.notify()
        self._m_tx_msgs[dst].inc()
        self._m_tx_bytes[dst].inc(view.nbytes)
        return handle

    def irecv(self, src: int, tag: int, out: Any | None = None) -> Handle:
        if src == self.rank or not 0 <= src < self.nranks:
            raise ValueError(f"irecv from invalid rank {src}")
        handle = Handle(kind="recv", peer=src, tag=tag, out=out)
        if out is None:
            handle.meta["as_bytes"] = True
        with self._lock:
            chan = self._channels[(src, tag)]
            if src in self._dead_readers:
                # Only the already-delivered backlog can satisfy receives.
                live = sum(1 for h in chan.pending if not h.cancelled)
                if live >= len(chan.msgs):
                    handle.cancelled = True
                    handle.meta["error"] = (
                        f"recv from rank {src} failed: connection lost"
                    )
                    return handle
            chan.pending.append(handle)
        return handle

    def iprobe(self, src: int, tag: int) -> bool:
        with self._lock:
            if self._channels[(src, tag)].msgs:
                return True
            if src in self._dead_readers:
                # A probe loop on a dead, drained channel can never turn
                # true — fail loudly (the aio schedulers' probe-then-recv
                # pattern, aio/scheduler.py, would otherwise poll forever;
                # the error surfaces from Scheduler.wait with the task
                # attached).
                raise RuntimeError(
                    f"recv from rank {src} failed: connection lost"
                )
            return False

    def test(self, handle: Handle) -> bool:
        if handle.cancelled:
            err = handle.meta.pop("error", None)
            if err:  # raise exactly once, then report not-done quietly
                raise RuntimeError(err)
            return False
        if handle.done:
            return True
        if handle.kind == "send":
            return handle.done
        with self._lock:
            chan = self._channels[(handle.peer, handle.tag)]
            while chan.pending and chan.pending[0].cancelled:
                chan.pending.popleft()
            if not chan.pending or chan.pending[0] is not handle or not chan.msgs:
                return False
            msg = chan.msgs[0]
            if handle.meta.get("as_bytes"):
                chan.msgs.popleft()
                chan.pending.popleft()
                handle.payload = msg
                handle.done = True
                return True
            view = as_writable_view(handle.out)
            if view.nbytes != len(msg):
                handle.cancelled = True
                chan.pending.popleft()  # message stays for a correct recv
                raise ValueError(
                    f"recv size mismatch: message {len(msg)}B does not fit "
                    f"buffer {view.nbytes}B (src={handle.peer}, tag={handle.tag})"
                )
            chan.msgs.popleft()
            chan.pending.popleft()
            view[:] = msg
            handle.done = True
            return True

    def cancel(self, handle: Handle) -> None:
        handle.cancelled = True
        handle.buf = None  # pending-queue entries are reaped lazily in test

    def close(self) -> None:
        if self._closed:
            return
        # Goodbye frames: queue one to every live peer (FIFO after any
        # still-queued user sends) and give the writers a bounded grace
        # period to flush, so readers on the other side see an orderly
        # shutdown rather than a crash.  Best-effort: a dead or
        # backlogged peer just misses the goodbye and reports
        # connection-lost, which is accurate for it.
        zero = np.empty(0, np.uint8)
        for peer in range(self.nranks):
            if peer == self.rank:
                continue
            cv = self._out_cv[peer]
            with cv:
                if peer not in self._dead_peers:
                    self._outboxes[peer].append(
                        (Handle(kind="send", peer=peer, tag=_GOODBYE_TAG),
                         _HDR.pack(_GOODBYE_TAG, 0, 0), zero.view(), None)
                    )
                    cv.notify()
        deadline = time.monotonic() + 1.0
        while time.monotonic() < deadline and any(
            self._outboxes[p] for p in range(self.nranks) if p != self.rank
        ):
            time.sleep(0.005)
        self._closed = True
        # Cancel every queued send left — a blocking sender must observe
        # done-or-cancelled, never an orphaned handle.
        for peer in range(self.nranks):
            if peer != self.rank:
                self._drain_outbox(peer)
        for cv in self._out_cv.values():
            with cv:
                cv.notify_all()
        for conn in self._peers.values():
            try:
                conn.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            conn.close()
        try:
            self._listener.close()
        except OSError:
            pass
        for t in self._threads:
            t.join(2)
