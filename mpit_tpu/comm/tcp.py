"""TcpTransport — cross-host transport for the PS protocol (DCN analog).

The shm transport covers the reference's one-host ``mpirun -np N`` shape;
this covers its multi-node hostfile deployments (reference
BiCNN/hostfiles, README.md:57-61) for the *host-mediated* asynchronous PS
path — the traffic XLA collectives can't express.  (On-mesh trainers
already cross hosts via jax.distributed + DCN; this is the transport for
the ParamServer/ParamClient role topology.)

Same contract and semantics as :class:`mpit_tpu.comm.shm.ShmTransport`:
nonblocking (rank, tag)-addressed messaging, FIFO per channel, exact-size
receives, buffer ownership until ``test`` is True, cancel-on-shutdown.

Wire format per message: 24-byte header (tag, size, seq — int64 little
endian) + payload.  Connections form a full mesh at construction: every
rank listens on its ``host:port`` from the address book; rank i dials
every rank j < i and accepts from every j > i (each side identifies
itself with a 32-byte handshake: rank, instance nonce, the highest
sequence it has received from the other side, and the address-book
digest).

**I/O model: one event-loop thread per rank**, multiplexing every peer
through an epoll selector (``selectors.DefaultSelector``) — thread count
is O(1) in the peer count, which is what lets one server rank hold
hundreds of reader connections (the serving tier, docs/PROTOCOL.md §8).
Per peer the loop runs a read state machine (24-byte header, then the
payload assembled incrementally into its own buffer — never a
concatenating byte-string accumulator) and a write state machine that
drains the peer's outbox with scatter-gather ``sendmsg`` (header +
payload to the kernel from their own buffers, partial writes resumed on
the next writable event).  Post-construction accepts, redials and
handshakes are nonblocking state machines inside the same loop; the
only blocking socket work is the construction-time rendezvous, which
runs on the constructing thread before the loop starts.

Loop-callback discipline (machine-checked: mtlint MT-P203): every
selector-dispatch callback is named ``_el_*`` and may only touch sockets
through the ``_nb_*`` nonblocking helpers — a blocking call inside a
callback would stall every peer's I/O at once.

The outbox is zero-copy — queued entries view the caller's buffer
(owned by the transport until ``test`` is True), so a deep backlog costs
O(1) transport-owned memory per message, not a payload copy.
"""

from __future__ import annotations

import errno
import selectors
import socket
import struct
import threading
import time
from collections import defaultdict, deque
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from mpit_tpu.comm.transport import (
    Handle,
    Transport,
    as_bytes_view,
    as_writable_view,
)
from mpit_tpu.obs import metrics as _obs
from mpit_tpu.utils.logging import get_logger

_HDR = struct.Struct("<qqq")  # tag, size, seq
# rank, instance nonce, last-seq-from-you, address-book digest (the
# digest authenticates the MESH: a stale redial from a dead transport
# instance, or any foreign client, that reaches a reassigned port must
# not be installed as a peer).
_RANK_HDR = struct.Struct("<qqqq")
_EMPTY = memoryview(b"")
# Reserved wire tag: an orderly close() announces itself so the peer's
# read state machine can distinguish graceful shutdown (old
# silent-cancel semantics) from a crash (fail-loud semantics).  User
# tags are non-negative (ps/tags.py, collectives' 2^16+ range), so the
# sentinel can't collide.
_GOODBYE_TAG = -(1 << 62)
# Scatter-gather frame writes (one syscall for header+payload, zero
# concatenation): POSIX-only; Windows sockets lack sendmsg.
_HAS_SENDMSG = hasattr(socket.socket, "sendmsg")
# Per-readable/writable-event byte budgets: a firehose peer must not
# starve its siblings inside one dispatch (level-triggered epoll
# re-reports whatever is left).
_RX_BUDGET = 1 << 20
_TX_BUDGET = 1 << 22
# Nonblocking-connect handshake bounds.
_HS_TIMEOUT_S = 2.0
_DIAL_ATTEMPT_S = 5.0


class MeshMismatchError(ConnectionError):
    """The peer answered the handshake with a different address-book /
    reconnect-mode digest: it belongs to another mesh (or the two sides
    disagree on reconnect mode, which would deadlock ack-based sends).
    Raised immediately — never retried."""
# Reserved wire tag for delivery acknowledgements (reconnect mode): the
# header's seq field carries the highest data sequence received; no
# payload.  Acks are neither retained nor themselves acked — a lost ack
# is superseded by the next one or by the reconnect handshake.
_ACK_TAG = _GOODBYE_TAG + 1


def allocate_local_addresses(nranks: int) -> Tuple[List[str], List[socket.socket]]:
    """Pre-bound localhost listeners with OS-assigned ports, for tests and
    same-host runs: returns (addresses, listeners); pass ``listeners[r]``
    to rank r's transport so no port is lost to a rebind race."""
    addrs, socks = [], []
    for _ in range(nranks):
        s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        s.bind(("127.0.0.1", 0))
        s.listen(max(nranks, 64))  # serving-tier gangs burst-dial
        addrs.append(f"127.0.0.1:{s.getsockname()[1]}")
        socks.append(s)
    return addrs, socks


def _recv_exact(sock: socket.socket, n: int) -> Optional[bytes]:
    """Blocking exact-size read — construction-time handshakes only
    (never called from the event loop)."""
    buf = bytearray(n)
    view = memoryview(buf)
    got = 0
    while got < n:
        r = sock.recv_into(view[got:], n - got)
        if r == 0:
            return None  # peer closed
        got += r
    return bytes(buf)


class _Channel:
    __slots__ = ("msgs", "pending")

    def __init__(self):
        self.msgs: deque = deque()      # fully-assembled payloads (bytes)
        self.pending: deque = deque()   # posted recv handles, FIFO


class _Conn:
    """One live peer connection's loop-side state: the read state
    machine's partial header/payload and the write state machine's
    partial frame.  A fresh generation gets a fresh ``_Conn``, so a
    reconnect can never resume mid-frame state from a dead socket."""

    __slots__ = ("peer", "sock", "gen", "graceful", "want_w",
                 "rx_hdr", "rx_hmv", "rx_got", "rx_tag", "rx_seq",
                 "rx_body", "rx_bgot", "tx_entry", "tx_bufs")

    def __init__(self, peer: int, sock: socket.socket, gen: int):
        self.peer = peer
        self.sock = sock
        self.gen = gen
        self.graceful = False   # peer announced an orderly close
        self.want_w = False
        self.rx_hdr = bytearray(_HDR.size)
        self.rx_hmv = memoryview(self.rx_hdr)
        self.rx_got = 0
        self.rx_tag = 0
        self.rx_seq = 0
        self.rx_body: Optional[bytearray] = None
        self.rx_bgot = 0
        self.tx_entry: Optional[Any] = None
        self.tx_bufs: Optional[List[memoryview]] = None


class _Hs:
    """An accepted socket mid-handshake (nonblocking): read the peer's
    32-byte hello, write the 32-byte reply, install."""

    __slots__ = ("sock", "deadline", "state", "inb", "igot", "out",
                 "peer", "pnonce", "peer_last")

    def __init__(self, sock: socket.socket, deadline: float):
        self.sock = sock
        self.deadline = deadline
        self.state = "hello"
        self.inb = bytearray(_RANK_HDR.size)
        self.igot = 0
        self.out: List[memoryview] = []
        self.peer = -1
        self.pnonce = 0
        self.peer_last = 0


class _Dial:
    """A nonblocking redial state machine (reconnect mode): connect_ex →
    write hello → read reply → install, with capped backoff between
    attempts, all inside the event loop (no per-fault dialer thread)."""

    __slots__ = ("peer", "gen", "deadline", "state", "next_at", "backoff",
                 "attempt_deadline", "sock", "out", "inb", "igot")

    def __init__(self, peer: int, gen: int, deadline: float, now: float):
        self.peer = peer
        self.gen = gen
        self.deadline = deadline
        self.state = "wait"
        self.next_at = now
        self.backoff = 0.05
        self.attempt_deadline = 0.0
        self.sock: Optional[socket.socket] = None
        self.out: List[memoryview] = []
        self.inb = bytearray(_RANK_HDR.size)
        self.igot = 0


class TcpTransport(Transport):
    """See module docstring.  ``reconnect`` (seconds, default from
    ``MPIT_TCP_RECONNECT_S``, 0 = off) adds bounded fault recovery: on a
    torn connection the dialing side (higher rank) redials with backoff
    and the accepting side's persistent accept service re-handshakes,
    the write state machine resends every frame not yet fully written
    (frames carry sequence numbers; the receiver drops duplicates), and
    a fresh process re-binding a dead rank's address rejoins the mesh
    (the handshake nonce tells a resumed connection from a restarted
    peer, which resets the dedup horizon).  Only after the window
    expires does the transport fall back to the fail-loud contract.

    ``listen=False`` builds a pure-dialer endpoint (no listener socket
    at all): the serving tier's reader clients dial their servers and
    are never dialed, so hundreds of them don't each burn a listening
    port.  Requires ``dial_peers`` (nobody can connect *in*)."""

    def __init__(
        self,
        rank: int,
        nranks: int,
        addresses: Sequence[str],
        *,
        listener: Optional[socket.socket] = None,
        connect_timeout: float = 60.0,
        reconnect: Optional[float] = None,
        dial_peers: Optional[Sequence[int]] = None,
        listen: bool = True,
    ):
        import os as _os
        import secrets

        if len(addresses) != nranks:
            raise ValueError(f"need {nranks} addresses, got {len(addresses)}")
        self.rank = rank
        self.nranks = nranks
        self.addresses = list(addresses)
        self.reconnect = (
            float(_os.environ.get("MPIT_TCP_RECONNECT_S", "0"))
            if reconnect is None else float(reconnect)
        )
        self._log = get_logger("tcp", rank)
        self._nonce = secrets.randbits(62)
        import hashlib

        # The digest covers the address book AND the reconnect mode: a
        # reconnect>0 sender retains frames until acked, so a mixed-mode
        # pairing (one side acking, one not) would deadlock sends — make
        # it a connect-time refusal instead.
        self._book_hash = int.from_bytes(
            hashlib.blake2b(
                (",".join(self.addresses)
                 + f"|reconnect={'on' if self.reconnect > 0 else 'off'}"
                 ).encode(), digest_size=7).digest(), "little")
        self._lock = threading.Lock()
        self._channels: Dict[Tuple[int, int], _Channel] = defaultdict(_Channel)
        self._peers: Dict[int, socket.socket] = {}
        self._gen: Dict[int, int] = {r: 0 for r in range(nranks)}
        self._peer_nonce: Dict[int, int] = {}
        self._last_seq: Dict[int, int] = {r: 0 for r in range(nranks)}
        self._send_seq: Dict[int, int] = {r: 0 for r in range(nranks)}
        self._outboxes: Dict[int, deque] = {r: deque() for r in range(nranks)}
        # Reconnect mode: frames sent to the kernel but not yet
        # acknowledged by the peer (written != delivered) — resent after
        # a reconnect, released (handle.done) by acks.
        self._unacked: Dict[int, deque] = {r: deque() for r in range(nranks)}
        self._pending_ack: Dict[int, Any] = {}
        # Highest seq each peer has acked — consulted when retaining a
        # just-sent frame: the ack can RACE the retention (arrive between
        # the write completing and the settle), and a frame retained
        # after its own ack would wait forever.
        self._acked_high: Dict[int, int] = {r: 0 for r in range(nranks)}
        self._out_cv: Dict[int, threading.Condition] = {
            r: threading.Condition() for r in range(nranks)
        }
        # Peers whose connection has been declared dead: new isends are
        # cancelled immediately instead of queueing into a box nobody
        # will ever drain.
        self._dead_peers: set = set()
        # Peers whose inbound side has died mid-run: pending receives
        # with no message to match fail loudly (raise-once from test)
        # instead of polling forever on a connection that can never
        # deliver.
        self._dead_readers: set = set()
        self._threads: List[threading.Thread] = []
        self._disconnect_seen: set = set()
        self._closed = False
        # close() handshake: the loop owns connection state, so the loop
        # decides when the goodbye flush is done (a caller-side guess
        # would race the install queue) and signals the event.
        self._closing = False
        self._flushed = threading.Event()
        # -- event-loop plumbing (loop-thread-owned unless noted) ------------
        self._sel = selectors.DefaultSelector()
        self._wake_r, self._wake_w = socket.socketpair()
        self._wake_r.setblocking(False)
        self._wake_w.setblocking(False)
        self._conns: Dict[int, _Conn] = {}       # loop-owned
        self._installq: deque = deque()          # any thread appends; loop drains
        self._dirty: set = set()                 # peers with fresh tx (any thread)
        self._watchdogs: Dict[int, Tuple[int, float]] = {}  # loop-owned
        self._dials: Dict[int, _Dial] = {}       # loop-owned
        self._hss: set = set()                   # loop-owned
        # Per-peer traffic counters (mpit_tpu.obs): indexed by rank so
        # the hot paths never hash a label dict; the shared null
        # instrument fills every slot when obs is disabled.
        _reg = _obs.get_registry()
        self._m_tx_msgs = [_reg.counter("mpit_tcp_tx_messages_total",
                                        rank=rank, peer=r)
                           for r in range(nranks)]
        self._m_tx_bytes = [_reg.counter("mpit_tcp_tx_bytes_total",
                                         rank=rank, peer=r)
                            for r in range(nranks)]
        self._m_rx_msgs = [_reg.counter("mpit_tcp_rx_messages_total",
                                        rank=rank, peer=r)
                           for r in range(nranks)]
        self._m_rx_bytes = [_reg.counter("mpit_tcp_rx_bytes_total",
                                         rank=rank, peer=r)
                            for r in range(nranks)]
        # Send-queue depth (frames queued to each peer's write state
        # machine) — the live queueing-pressure signal `mpit top`
        # renders: a peer that cannot drain shows a growing depth long
        # before ops start missing deadlines.
        self._m_sendq = [_reg.gauge("mpit_tcp_send_queue_depth",
                                    rank=rank, peer=r)
                         for r in range(nranks)]
        # Live established connections + per-wakeup dispatch time of the
        # one I/O thread: the scale-out health pair (`mpit top`'s conns
        # column; a loop lag histogram drifting up means one rank's
        # event loop is saturating).
        self._m_conns = _reg.gauge("mpit_tcp_connections", rank=rank)
        self._m_lag = _reg.timer("mpit_tcp_event_loop_lag_seconds",
                                 rank=rank)

        if not listen:
            if dial_peers is None:
                raise ValueError(
                    "listen=False builds a pure-dialer endpoint; pass "
                    "dial_peers so it knows who to reach (nobody can "
                    "connect in)")
            self._listener: Optional[socket.socket] = None
        else:
            host, _, port = addresses[rank].rpartition(":")
            if listener is None:
                listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
                listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
                bind_deadline = time.monotonic() + connect_timeout
                while True:
                    try:
                        listener.bind((host or "0.0.0.0", int(port)))
                        break
                    except OSError as e:
                        # A replacement process rebinding a crashed
                        # rank's address can race the old listener's
                        # teardown — retry EADDRINUSE within the window;
                        # anything else (bad host, privileged port) is a
                        # misconfiguration and fails immediately.
                        if (e.errno != errno.EADDRINUSE
                                or time.monotonic() >= bind_deadline):
                            raise
                        time.sleep(0.1)
                listener.listen(max(nranks, 64))
            self._listener = listener

        # Dial lower ranks, accept higher ranks (deadlock-free full mesh).
        # ``dial_peers`` (FT rejoin / serving-tier attach) restricts
        # construction to the connections this endpoint actually needs: a
        # worker restarted mid-run must reach its *servers*, but a
        # sibling worker may have finished and exited — demanding its
        # listener would turn normal completion into a rejoin failure.
        # Skipped lower ranks are marked dead (sends fail loudly, not
        # silently queue); skipped higher ranks arrive later through the
        # loop's persistent accept service, which is why the restriction
        # requires reconnect mode.
        deadline = time.monotonic() + connect_timeout
        if dial_peers is None:
            to_dial = list(range(rank))
            n_accept = nranks - rank - 1
        else:
            if self.reconnect <= 0:
                raise ValueError(
                    "dial_peers needs reconnect mode (MPIT_TCP_RECONNECT_S"
                    " > 0): undialed peers can only join via the "
                    "persistent accept service"
                )
            to_dial = sorted({int(p) for p in dial_peers} & set(range(rank)))
            self._dead_peers.update(set(range(rank)) - set(to_dial))
            n_accept = 0
        for peer in to_dial:
            conn, pnonce, peer_last = self._dial(addresses[peer], deadline,
                                                 peer)
            self._install_socket(peer, conn, pnonce, peer_last)
        for _ in range(n_accept):
            conn, _addr = self._accept(deadline)
            conn.settimeout(None)  # construction handshakes block
            got = self._handshake_accept(conn)
            if got is None:
                raise ConnectionError("peer closed during handshake")
            self._install_socket(got[0], conn, got[1], got[2])
        # The one I/O thread: every socket from here on is driven by the
        # selector loop.  (Role-named for thread dumps and tests.)
        t = threading.Thread(target=self._io_loop, daemon=True,
                             name=f"_io_loop-{rank}")
        self._threads.append(t)
        t.start()

    # -- construction-time (blocking) connection plumbing --------------------

    def _dial(self, address: str, deadline: float,
              peer_rank: int) -> Tuple[socket.socket, int, int]:
        """Returns (socket, peer nonce, peer's last-received seq from us).
        Construction-thread only; the loop's redial path is the
        nonblocking :class:`_Dial` machine."""
        host, _, port = address.rpartition(":")
        last_err: Optional[Exception] = None
        while time.monotonic() < deadline and not self._closed:
            try:
                conn = socket.create_connection((host, int(port)), timeout=5.0)
                if conn.getsockname() == conn.getpeername():
                    # TCP simultaneous-connect to our own ephemeral
                    # port: the peer's listener is not up yet and the
                    # kernel handed us a loopback self-connection —
                    # worse than useless, it also squats the very port
                    # the peer is trying to bind.  Close (freeing the
                    # port) and retry like any not-up-yet peer.
                    conn.close()
                    raise ConnectionRefusedError(
                        errno.ECONNREFUSED,
                        "self-connect: peer listener not up yet")
                conn.settimeout(None)
                with self._lock:
                    my_last = self._last_seq[peer_rank]
                conn.sendall(_RANK_HDR.pack(self.rank, self._nonce, my_last,
                                            self._book_hash))
                reply = _recv_exact(conn, _RANK_HDR.size)
                if reply is None:
                    raise ConnectionError("peer closed during handshake")
                _prank, pnonce, peer_last, book = _RANK_HDR.unpack(reply)
                if book != self._book_hash:
                    conn.close()
                    raise MeshMismatchError(
                        "peer handshake digest mismatch: different mesh "
                        "or mismatched reconnect mode"
                    )
                return conn, int(pnonce), int(peer_last)
            except MeshMismatchError:
                raise  # misconfiguration — retrying cannot fix it
            except OSError as e:  # peer not up yet
                last_err = e
                time.sleep(0.05)
        raise ConnectionError(f"could not reach {address}: {last_err!r}")

    def _handshake_accept(
        self, conn: socket.socket
    ) -> Optional[Tuple[int, int, int]]:
        """Returns (peer rank, peer nonce, peer's last seq from us).
        Construction-thread only (blocking); the loop accepts through
        the nonblocking :class:`_Hs` machine."""
        peer_hdr = _recv_exact(conn, _RANK_HDR.size)
        if peer_hdr is None:
            return None
        peer, pnonce, peer_last, book = _RANK_HDR.unpack(peer_hdr)
        if not 0 <= peer < self.nranks or book != self._book_hash:
            return None
        with self._lock:
            my_last = self._last_seq[int(peer)]
        conn.sendall(_RANK_HDR.pack(self.rank, self._nonce, my_last,
                                    self._book_hash))
        return int(peer), int(pnonce), int(peer_last)

    def _accept(self, deadline: float) -> Tuple[socket.socket, Any]:
        self._listener.settimeout(max(deadline - time.monotonic(), 0.1))
        try:
            return self._listener.accept()
        except socket.timeout:
            raise ConnectionError("timed out waiting for peer connections")

    def _install_socket(self, peer: int, conn: socket.socket,
                        pnonce: Optional[int], peer_last: int,
                        expect_gen: Optional[int] = None) -> bool:
        """Adopt ``conn`` as the live socket for ``peer`` (initial setup
        and every reconnect), revive the peer's fail-loud state, settle
        the unacked window against the peer's reported horizon, and hand
        the socket to the event loop under a fresh generation.  With
        ``expect_gen`` (a redial) the install is refused when the
        generation moved on (another install won, or the watchdog
        poisoned it)."""
        conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        cv = self._out_cv[peer]
        with self._lock:
            if self._closed or (expect_gen is not None
                                and self._gen[peer] != expect_gen):
                conn.close()
                return False
            nonce_reset = (pnonce is not None
                           and self._peer_nonce.get(peer) is not None
                           and self._peer_nonce.get(peer) != pnonce)
            if pnonce is not None and self._peer_nonce.get(peer) != pnonce:
                # A RESTARTED peer (fresh process, fresh sequence space),
                # not a resumed connection: reset the dedup horizon.
                self._peer_nonce[peer] = pnonce
                self._last_seq[peer] = 0
            self._peers[peer] = conn
            self._gen[peer] += 1
            gen = self._gen[peer]
            self._dead_readers.discard(peer)
        done_handles = []
        with cv:
            if nonce_reset:
                # Acks already queued for the DEAD instance carry
                # horizons from its sequence space; delivered to the
                # replacement they would release (and un-retain) its
                # entire early window.  Purge them.
                kept = [e for e in self._outboxes[peer]
                        if e[0].tag != _ACK_TAG]
                self._outboxes[peer].clear()
                self._outboxes[peer].extend(kept)
                self._pending_ack[peer] = None
            # Settle the unacked window: frames the peer already holds
            # (seq <= its reported horizon) are delivered; the rest go
            # back to the FRONT of the outbox, in order, for resend.
            ua = self._unacked[peer]
            resend = []
            while ua:
                entry = ua.popleft()
                if entry[3] is not None and entry[3] <= peer_last:
                    done_handles.append(entry[0])
                else:
                    resend.append(entry)
            self._outboxes[peer].extendleft(reversed(resend))
            self._dead_peers.discard(peer)
            cv.notify_all()
        for h in done_handles:
            h.done = True
            h.buf = None
        conn.setblocking(False)
        self._installq.append((peer, _Conn(peer, conn, gen)))
        self._wake()
        return True

    def _current_gen(self, peer: int) -> int:
        with self._lock:
            return self._gen[peer]

    # -- event loop ----------------------------------------------------------

    def _wake(self) -> None:
        """Nudge the loop out of select (any thread; lossy by design —
        a full pipe means a wakeup is already pending)."""
        try:
            self._wake_w.send(b"\0")
        except (BlockingIOError, InterruptedError, OSError):
            pass

    def _mark_dirty(self, peer: int) -> None:
        self._dirty.add(peer)
        self._wake()

    def _io_loop(self) -> None:
        sel = self._sel
        sel.register(self._wake_r, selectors.EVENT_READ, ("wake", None))
        if self._listener is not None and self.reconnect > 0:
            # Persistent accept service (reconnect mode): any peer —
            # resumed socket, restarted process, late-attaching reader —
            # can dial in and (re)handshake at any time.  (A transport
            # torn down before the loop even starts — tests simulating a
            # hard death — may have closed the listener already.)
            try:
                self._listener.setblocking(False)
                sel.register(self._listener, selectors.EVENT_READ,
                             ("accept", None))
            except (OSError, ValueError, KeyError):
                pass
        try:
            while True:
                self._drain_control()
                if self._closed:
                    return
                events = sel.select(self._timer_timeout())
                if events:
                    with self._m_lag:
                        for key, mask in events:
                            kind, obj = key.data
                            if kind == "wake":
                                self._el_wake()
                            elif kind == "accept":
                                self._el_accept()
                            elif kind == "hs":
                                self._el_hs_event(obj)
                            elif kind == "dial":
                                self._el_dial_event(obj)
                            elif kind == "conn":
                                if mask & selectors.EVENT_READ:
                                    self._el_conn_readable(obj)
                                if (mask & selectors.EVENT_WRITE
                                        and self._conns.get(obj.peer) is obj):
                                    self._el_conn_writable(obj)
                self._run_timers()
                if self._closing and not self._flushed.is_set():
                    # Orderly-shutdown flush: done when no peer the loop
                    # can still reach has queued frames left.
                    reachable = set(self._conns) | {
                        p for p, _c in self._installq}
                    if not any(self._outboxes[p] for p in reachable
                               if p != self.rank):
                        self._flushed.set()
        except Exception:  # pragma: no cover - defensive: loop must not die silently
            if not self._closed:
                self._log.exception("event loop died; transport is wedged")
        finally:
            pass

    def _timer_timeout(self) -> float:
        deadline = time.monotonic() + 0.5
        for hs in self._hss:
            deadline = min(deadline, hs.deadline)
        for d in self._dials.values():
            if d.state == "wait":
                deadline = min(deadline, d.next_at, d.deadline)
            else:
                deadline = min(deadline, d.attempt_deadline, d.deadline)
        for _gen, dl in self._watchdogs.values():
            deadline = min(deadline, dl)
        return max(deadline - time.monotonic(), 0.0)

    def _drain_control(self) -> None:
        """Loop-top housekeeping: adopt handed-off sockets and refresh
        write interest for peers with fresh outbox entries."""
        while self._installq:
            peer, conn = self._installq.popleft()
            old = self._conns.get(peer)
            if old is not None and old.sock is not conn.sock:
                self._drop_conn(old)
            with self._lock:
                stale = self._closed or self._gen[peer] != conn.gen
            if stale:
                try:
                    conn.sock.close()
                except OSError:
                    pass
                continue
            want_w = bool(self._outboxes[peer])
            mask = selectors.EVENT_READ | (
                selectors.EVENT_WRITE if want_w else 0)
            try:
                self._sel.register(conn.sock, mask, ("conn", conn))
            except (KeyError, ValueError, OSError):
                continue
            conn.want_w = want_w
            self._conns[peer] = conn
            self._m_conns.set(len(self._conns))
        if self._dirty:
            dirty, self._dirty = self._dirty, set()
            for peer in dirty:
                conn = self._conns.get(peer)
                if conn is not None and self._outboxes[peer]:
                    self._set_w(conn, True)

    def _set_w(self, conn: _Conn, want: bool) -> None:
        if conn.want_w == want:
            return
        mask = selectors.EVENT_READ | (selectors.EVENT_WRITE if want else 0)
        try:
            self._sel.modify(conn.sock, mask, ("conn", conn))
        except (KeyError, ValueError, OSError):
            return
        conn.want_w = want

    def _drop_conn(self, conn: _Conn) -> None:
        try:
            self._sel.unregister(conn.sock)
        except (KeyError, ValueError, OSError):
            pass
        try:
            conn.sock.close()
        except OSError:
            pass
        if self._conns.get(conn.peer) is conn:
            del self._conns[conn.peer]
            self._m_conns.set(len(self._conns))

    def _run_timers(self) -> None:
        now = time.monotonic()
        for hs in list(self._hss):
            if now >= hs.deadline:
                self._drop_hs(hs)
        for peer, d in list(self._dials.items()):
            with self._lock:
                cur = self._gen[peer]
            if cur != d.gen or self._closed or now >= d.deadline:
                self._drop_dial(d)
                continue
            if d.state == "wait" and now >= d.next_at:
                self._dial_connect(d, now)
            elif d.state != "wait" and now >= d.attempt_deadline:
                self._dial_retry(d, now)
        for peer, (gen, dl) in list(self._watchdogs.items()):
            with self._lock:
                cur = self._gen[peer]
            if cur != gen:
                del self._watchdogs[peer]  # replaced — recovery done
                continue
            if now >= dl:
                del self._watchdogs[peer]
                self._expire_window(peer, gen)

    # -- nonblocking socket helpers (the only raw socket calls the loop
    # callbacks may reach — the MT-P203 contract) ----------------------------

    @staticmethod
    def _nb_recv_into(sock: socket.socket, view: memoryview) -> Optional[int]:
        """Bytes read, 0 on EOF, None when the socket has nothing now."""
        try:
            return sock.recv_into(view)
        except (BlockingIOError, InterruptedError):
            return None

    @staticmethod
    def _nb_send(sock: socket.socket, bufs: List[memoryview]) -> Optional[int]:
        """Bytes the kernel took (scatter-gather where available), None
        when the socket cannot take more now."""
        try:
            if _HAS_SENDMSG:
                return sock.sendmsg(bufs)
            return sock.send(bufs[0])
        except (BlockingIOError, InterruptedError):
            return None

    @staticmethod
    def _nb_accept(listener: socket.socket):
        try:
            return listener.accept()
        except (BlockingIOError, InterruptedError):
            return None
        except OSError:
            return None

    @staticmethod
    def _advance(bufs: List[memoryview], sent: int) -> None:
        while bufs and sent >= bufs[0].nbytes:
            sent -= bufs[0].nbytes
            bufs.pop(0)
        if sent and bufs:
            bufs[0] = bufs[0][sent:]

    # -- event-loop callbacks (_el_*: nonblocking ops only — MT-P203) --------

    @staticmethod
    def _nb_drain(sock: socket.socket) -> None:
        """Drain pending wakeup bytes; never blocks."""
        while True:
            try:
                if not sock.recv(4096):
                    return
            except (BlockingIOError, InterruptedError, OSError):
                return

    def _el_wake(self) -> None:
        self._nb_drain(self._wake_r)

    def _el_accept(self) -> None:
        while True:
            got = self._nb_accept(self._listener)
            if got is None:
                return
            conn, _addr = got
            conn.setblocking(False)
            hs = _Hs(conn, time.monotonic() + _HS_TIMEOUT_S)
            try:
                self._sel.register(conn, selectors.EVENT_READ, ("hs", hs))
            except (KeyError, ValueError, OSError):
                conn.close()
                continue
            self._hss.add(hs)

    def _drop_hs(self, hs: _Hs) -> None:
        try:
            self._sel.unregister(hs.sock)
        except (KeyError, ValueError, OSError):
            pass
        try:
            hs.sock.close()
        except OSError:
            pass
        self._hss.discard(hs)

    def _el_hs_event(self, hs: _Hs) -> None:
        if hs.state == "hello":
            try:
                n = self._nb_recv_into(hs.sock,
                                       memoryview(hs.inb)[hs.igot:])
            except OSError:
                self._drop_hs(hs)
                return
            if n is None:
                return
            if n == 0:
                self._drop_hs(hs)
                return
            hs.igot += n
            if hs.igot < _RANK_HDR.size:
                return
            peer, pnonce, peer_last, book = _RANK_HDR.unpack(hs.inb)
            if not 0 <= peer < self.nranks or book != self._book_hash:
                self._drop_hs(hs)
                return
            hs.peer, hs.pnonce, hs.peer_last = (int(peer), int(pnonce),
                                                int(peer_last))
            with self._lock:
                my_last = self._last_seq[hs.peer]
            hs.out = [memoryview(_RANK_HDR.pack(
                self.rank, self._nonce, my_last, self._book_hash))]
            hs.state = "reply"
            try:
                self._sel.modify(hs.sock, selectors.EVENT_WRITE, ("hs", hs))
            except (KeyError, ValueError, OSError):
                self._drop_hs(hs)
                return
        if hs.state == "reply":
            try:
                sent = self._nb_send(hs.sock, hs.out)
            except OSError:
                self._drop_hs(hs)
                return
            if sent is None:
                return
            self._advance(hs.out, sent)
            if hs.out:
                return
            try:
                self._sel.unregister(hs.sock)
            except (KeyError, ValueError, OSError):
                pass
            self._hss.discard(hs)
            if not self._install_socket(hs.peer, hs.sock, hs.pnonce,
                                        hs.peer_last):
                try:
                    hs.sock.close()
                except OSError:
                    pass

    # -- redial machine ------------------------------------------------------

    def _start_dial(self, peer: int, gen: int) -> None:
        if peer in self._dials:
            return
        now = time.monotonic()
        self._dials[peer] = _Dial(peer, gen, now + self.reconnect, now)

    def _drop_dial(self, d: _Dial) -> None:
        if d.sock is not None:
            try:
                self._sel.unregister(d.sock)
            except (KeyError, ValueError, OSError):
                pass
            try:
                d.sock.close()
            except OSError:
                pass
            d.sock = None
        self._dials.pop(d.peer, None)

    def _dial_retry(self, d: _Dial, now: float) -> None:
        if d.sock is not None:
            try:
                self._sel.unregister(d.sock)
            except (KeyError, ValueError, OSError):
                pass
            try:
                d.sock.close()
            except OSError:
                pass
            d.sock = None
        d.state = "wait"
        d.next_at = now + d.backoff
        d.backoff = min(d.backoff * 2, 1.0)
        d.igot = 0
        d.out = []

    def _dial_connect(self, d: _Dial, now: float) -> None:
        host, _, port = self.addresses[d.peer].rpartition(":")
        sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        sock.setblocking(False)
        try:
            err = sock.connect_ex((host, int(port)))
        except OSError:
            sock.close()
            self._dial_retry(d, now)
            return
        if err not in (0, errno.EINPROGRESS, errno.EWOULDBLOCK,
                       errno.EALREADY):
            sock.close()
            self._dial_retry(d, now)
            return
        d.sock = sock
        d.state = "connecting"
        d.attempt_deadline = now + _DIAL_ATTEMPT_S
        try:
            self._sel.register(sock, selectors.EVENT_WRITE, ("dial", d))
        except (KeyError, ValueError, OSError):
            sock.close()
            d.sock = None
            self._dial_retry(d, now)

    def _el_dial_event(self, d: _Dial) -> None:
        now = time.monotonic()
        if d.state == "connecting":
            err = d.sock.getsockopt(socket.SOL_SOCKET, socket.SO_ERROR)
            if err:
                self._dial_retry(d, now)
                return
            try:
                if d.sock.getsockname() == d.sock.getpeername():
                    # Loopback self-connect (see _dial): drop it so the
                    # peer can bind its listener, then redial.
                    self._dial_retry(d, now)
                    return
            except OSError:
                self._dial_retry(d, now)
                return
            with self._lock:
                my_last = self._last_seq[d.peer]
            d.out = [memoryview(_RANK_HDR.pack(
                self.rank, self._nonce, my_last, self._book_hash))]
            d.state = "hello"
        if d.state == "hello":
            try:
                sent = self._nb_send(d.sock, d.out)
            except OSError:
                self._dial_retry(d, now)
                return
            if sent is None:
                return
            self._advance(d.out, sent)
            if d.out:
                return
            d.state = "reply"
            d.igot = 0
            try:
                self._sel.modify(d.sock, selectors.EVENT_READ, ("dial", d))
            except (KeyError, ValueError, OSError):
                self._dial_retry(d, now)
            return
        if d.state == "reply":
            try:
                n = self._nb_recv_into(d.sock, memoryview(d.inb)[d.igot:])
            except OSError:
                self._dial_retry(d, now)
                return
            if n is None:
                return
            if n == 0:
                self._dial_retry(d, now)
                return
            d.igot += n
            if d.igot < _RANK_HDR.size:
                return
            _prank, pnonce, peer_last, book = _RANK_HDR.unpack(d.inb)
            sock = d.sock
            try:
                self._sel.unregister(sock)
            except (KeyError, ValueError, OSError):
                pass
            d.sock = None
            self._dials.pop(d.peer, None)
            if book != self._book_hash:
                # Foreign mesh on a reassigned port: stop redialing (the
                # watchdog fails the window — same as the thread era).
                sock.close()
                return
            # expect_gen: refused atomically if the accept service beat
            # us or the watchdog already poisoned this generation.
            if not self._install_socket(d.peer, sock, int(pnonce),
                                        int(peer_last), expect_gen=d.gen):
                sock.close()

    # -- established-connection callbacks ------------------------------------

    def _el_conn_readable(self, conn: _Conn) -> None:
        budget = _RX_BUDGET
        while budget > 0:
            if conn.rx_body is None:
                try:
                    n = self._nb_recv_into(conn.sock,
                                           conn.rx_hmv[conn.rx_got:])
                except OSError:
                    self._el_conn_dead(conn)
                    return
                if n is None:
                    return
                if n == 0:
                    self._el_conn_dead(conn)
                    return
                conn.rx_got += n
                budget -= n
                if conn.rx_got < _HDR.size:
                    continue
                tag, size, seq = _HDR.unpack(conn.rx_hdr)
                conn.rx_got = 0
                if tag == _GOODBYE_TAG:
                    # The peer is gone by protocol: frames retained for
                    # acks can never be released — settle them silently
                    # (the done-or-cancelled contract), and treat the
                    # coming EOF as orderly.
                    conn.graceful = True
                    self._settle_unacked_silently(conn.peer)
                    continue
                if tag == _ACK_TAG:
                    # Delivery confirmation: release every retained
                    # frame up to the acked sequence.  (Stale-generation
                    # acks are ignored — _process_ack checks.)
                    self._process_ack(conn.peer, int(seq), conn.gen)
                    continue
                conn.rx_tag, conn.rx_seq = int(tag), int(seq)
                if size:
                    conn.rx_body = bytearray(int(size))
                    conn.rx_bgot = 0
                else:
                    self._deliver(conn, b"")
                continue
            try:
                n = self._nb_recv_into(
                    conn.sock, memoryview(conn.rx_body)[conn.rx_bgot:])
            except OSError:
                self._el_conn_dead(conn)
                return
            if n is None:
                return
            if n == 0:
                self._el_conn_dead(conn)
                return
            conn.rx_bgot += n
            budget -= n
            if conn.rx_bgot == len(conn.rx_body):
                payload = bytes(conn.rx_body)
                conn.rx_body = None
                self._deliver(conn, payload)

    def _deliver(self, conn: _Conn, payload: bytes) -> None:
        peer, gen = conn.peer, conn.gen
        with self._lock:
            if self._gen[peer] != gen:
                # Superseded connection (e.g. the peer restarted and the
                # dedup horizon was reset): frames still draining from
                # the old socket's kernel buffer must not advance state
                # in the new seq space.
                return
            if conn.rx_seq > self._last_seq[peer]:
                self._last_seq[peer] = conn.rx_seq
                self._channels[(peer, conn.rx_tag)].msgs.append(payload)
                self._m_rx_msgs[peer].inc()
                self._m_rx_bytes[peer].inc(len(payload))
            # else: duplicate from a reconnect resend — drop it, but
            # still re-ack (the original ack may be exactly what the
            # tear swallowed).
            ack_val = self._last_seq[peer]
        if self.reconnect > 0:
            self._enqueue_ack(peer, ack_val, gen)
            self._set_w(conn, True)

    def _el_conn_writable(self, conn: _Conn) -> None:
        peer, gen = conn.peer, conn.gen
        cv = self._out_cv[peer]
        box = self._outboxes[peer]
        budget = _TX_BUDGET
        while budget > 0:
            if conn.tx_bufs is None:
                with cv:
                    with self._lock:
                        if self._gen[peer] != gen:
                            return  # superseded: successor owns the box
                    if not box:
                        self._set_w(conn, False)
                        return
                    # PEEK, don't pop: the frame stays queued until fully
                    # written, so a reconnect's replacement resends it
                    # whole (the receiver dedups by sequence number).
                    entry = box[0]
                    if entry is self._pending_ack.get(peer):
                        # Detach from coalescing NOW, under the cv: the
                        # header bytes are captured below, and a
                        # delivery overwriting the horizon after that
                        # would be silently lost — the sender it acks
                        # would deadlock.
                        self._pending_ack[peer] = None
                    header, payload = entry[1], entry[2]
                bufs = [memoryview(header)]
                if payload.nbytes:
                    bufs.append(payload)
                conn.tx_entry, conn.tx_bufs = entry, bufs
            try:
                sent = self._nb_send(conn.sock, conn.tx_bufs)
            except OSError:
                self._el_conn_dead(conn)
                return
            if sent is None:
                self._set_w(conn, True)
                return
            budget -= max(sent, 1)
            self._advance(conn.tx_bufs, sent)
            if conn.tx_bufs:
                continue  # partial frame: try again (EAGAIN stops us)
            entry = conn.tx_entry
            conn.tx_entry = conn.tx_bufs = None
            self._settle_sent(conn, entry)

    def _settle_sent(self, conn: _Conn, entry) -> None:
        """One frame fully handed to the kernel: pop it, and in
        reconnect mode retain it until the peer's ack releases it
        (written-to-kernel is NOT delivered-to-peer)."""
        peer, gen = conn.peer, conn.gen
        cv = self._out_cv[peer]
        box = self._outboxes[peer]
        handle, retain_seq = entry[0], entry[3]
        popped = retained = False
        with cv:
            with self._lock:
                if self._gen[peer] != gen:
                    # A reconnect installed mid-write: whatever we wrote
                    # went to a dead socket, and the successor's settle
                    # owns the box — touching it here would strand the
                    # frame.
                    return
            if box and box[0] is entry:
                box.popleft()
                self._m_sendq[peer].set(len(box))
                popped = True
                if (retain_seq is not None and self.reconnect > 0
                        and retain_seq > self._acked_high[peer]):
                    # A frame whose ack already landed — the ack can
                    # race this retention — completes right away.
                    self._unacked[peer].append(entry)
                    retained = True
        if popped and not retained:
            handle.done = True
            handle.buf = None  # ownership back to the caller

    def _el_conn_dead(self, conn: _Conn) -> None:
        peer, gen = conn.peer, conn.gen
        graceful = conn.graceful
        self._drop_conn(conn)
        if graceful or self._closed:
            return
        self._on_disconnect(peer, gen)

    # -- disconnect / recovery ----------------------------------------------

    def _on_disconnect(self, peer: int, gen: int) -> None:
        """Generation ``gen``'s connection died.  Without reconnect:
        fail loudly now.  With reconnect: the dialing side starts the
        in-loop redial machine; both sides arm a watchdog deadline that
        falls back to the fail-loud path if no replacement installs in
        the window.  (Loop-thread only.)"""
        if self._closed or self._current_gen(peer) != gen:
            return  # stale generation or shutdown
        with self._lock:
            if (peer, gen) in self._disconnect_seen:
                return
            self._disconnect_seen = {
                (p, g) for (p, g) in self._disconnect_seen if p != peer
            }
            self._disconnect_seen.add((peer, gen))
        if self.reconnect <= 0:
            self._fail_unmatched_recvs(peer)
            self._drain_outbox(
                peer, error=f"send to rank {peer} failed: connection lost"
            )
            return
        if peer < self.rank:
            self._start_dial(peer, gen)
        self._watchdogs[peer] = (gen, time.monotonic() + self.reconnect)

    def _expire_window(self, peer: int, gen: int) -> None:
        with self._lock:
            if self._closed or self._gen[peer] != gen:
                return
            # Poison the generation: a redial racing this expiry cannot
            # install afterwards (fail everything or recover everything).
            # A LATER fresh connection through the accept service may
            # still revive the peer — the shm transport's
            # late-resurrection semantics — but never one tied to this
            # failed window.
            self._gen[peer] += 1
        d = self._dials.get(peer)
        if d is not None:
            self._drop_dial(d)
        self._fail_unmatched_recvs(peer)
        self._drain_outbox(
            peer,
            error=(f"send to rank {peer} failed: connection lost "
                   f"(no reconnect within {self.reconnect}s)"),
        )

    def _settle_unacked_silently(self, peer: int) -> None:
        cv = self._out_cv[peer]
        with cv:
            ua = self._unacked[peer]
            while ua:
                h = ua.popleft()[0]
                h.cancelled = True
                h.buf = None

    def _process_ack(self, peer: int, acked: int, gen: int) -> None:
        cv = self._out_cv[peer]
        done = []
        with cv:
            with self._lock:
                if self._gen[peer] != gen:
                    return  # ack from a superseded connection
            if acked > self._acked_high[peer]:
                self._acked_high[peer] = acked
            ua = self._unacked[peer]
            while ua and ua[0][3] is not None and ua[0][3] <= acked:
                done.append(ua.popleft()[0])
        for h in done:
            h.done = True
            h.buf = None

    def _enqueue_ack(self, peer: int, acked: int, gen: int) -> None:
        cv = self._out_cv[peer]
        with cv:
            if peer in self._dead_peers or self._closed:
                return
            with self._lock:
                if self._gen[peer] != gen:
                    # A replacement connection installed between the
                    # delivery's gen check and this enqueue.  If the
                    # peer RESTARTED, ``acked`` is a horizon from the
                    # dead instance's sequence space — queued onto the
                    # new connection it would release the restarted
                    # peer's entire unacked window (silent loss under
                    # the exactly-once contract).  Drop it; the new
                    # generation acks its own deliveries.
                    return
            pending = self._pending_ack.get(peer)
            if pending is not None:
                # Acks are cumulative: overwrite the still-queued ack's
                # horizon instead of queueing another (a gradient storm
                # would otherwise double the write syscall count).
                pending[1] = _HDR.pack(_ACK_TAG, 0, acked)
                return
            entry = [Handle(kind="send", peer=peer, tag=_ACK_TAG),
                     _HDR.pack(_ACK_TAG, 0, acked), _EMPTY, None]
            self._pending_ack[peer] = entry
            self._outboxes[peer].append(entry)
            cv.notify()
        self._mark_dirty(peer)

    def _fail_unmatched_recvs(self, peer: int) -> None:
        """A mid-run connection death (peer crashed / link dropped):
        every pending recv beyond the already-delivered backlog can
        never complete — fail them with the raise-once convention, and
        make later irecvs from this peer fail the same way.  Messages
        that arrived before the death still serve matching receives
        (same drain-what-landed semantics as the shm transport's
        remap)."""
        err = f"recv from rank {peer} failed: connection lost"
        with self._lock:
            self._dead_readers.add(peer)
            for (src, _tag), chan in self._channels.items():
                if src != peer:
                    continue
                live = [h for h in chan.pending if not h.cancelled]
                for h in live[len(chan.msgs):]:
                    h.cancelled = True
                    h.meta["error"] = err

    def _drain_outbox(self, peer: int, error: str | None = None) -> None:
        """Cancel every queued send to ``peer``.  With ``error`` (dead
        peer) the handles raise from ``test``; without (orderly close)
        they cancel silently."""
        cv = self._out_cv[peer]
        with cv:
            self._dead_peers.add(peer)
            cv.notify_all()
            for q in (self._unacked[peer], self._outboxes[peer]):
                while q:
                    h = q.popleft()[0]
                    h.cancelled = True
                    h.buf = None
                    if error:
                        h.meta["error"] = error
            self._m_sendq[peer].set(0)

    # -- Transport -----------------------------------------------------------

    def isend(self, data: Any, dst: int, tag: int) -> Handle:
        if dst == self.rank or not 0 <= dst < self.nranks:
            raise ValueError(f"isend to invalid rank {dst}")
        if self._closed:
            raise RuntimeError("isend on a closed transport")
        view = as_bytes_view(b"" if data is None else data)
        handle = Handle(kind="send", peer=dst, tag=tag, buf=data)
        # Zero-copy queue: the outbox holds a *view* over the caller's
        # buffer, not a snapshot — the ownership contract already forbids
        # the caller touching it until test() is True (reported only
        # after the write completes), so transport-owned memory stays
        # O(1) per queued message however deep the backlog, and isend
        # never blocks.
        cv = self._out_cv[dst]
        with cv:
            if dst in self._dead_peers:
                handle.cancelled = True
                handle.buf = None
                handle.meta["error"] = f"rank {dst} unreachable (writer dead)"
                return handle
            self._send_seq[dst] += 1
            self._outboxes[dst].append(
                (handle, _HDR.pack(tag, view.nbytes, self._send_seq[dst]),
                 view, self._send_seq[dst])
            )
            self._m_sendq[dst].set(len(self._outboxes[dst]))
            cv.notify()
        self._mark_dirty(dst)
        self._m_tx_msgs[dst].inc()
        self._m_tx_bytes[dst].inc(view.nbytes)
        return handle

    def irecv(self, src: int, tag: int, out: Any | None = None) -> Handle:
        if src == self.rank or not 0 <= src < self.nranks:
            raise ValueError(f"irecv from invalid rank {src}")
        handle = Handle(kind="recv", peer=src, tag=tag, out=out)
        if out is None:
            handle.meta["as_bytes"] = True
        with self._lock:
            chan = self._channels[(src, tag)]
            if src in self._dead_readers:
                # Only the already-delivered backlog can satisfy receives.
                live = sum(1 for h in chan.pending if not h.cancelled)
                if live >= len(chan.msgs):
                    handle.cancelled = True
                    handle.meta["error"] = (
                        f"recv from rank {src} failed: connection lost"
                    )
                    return handle
            chan.pending.append(handle)
        return handle

    def iprobe(self, src: int, tag: int) -> bool:
        with self._lock:
            if self._channels[(src, tag)].msgs:
                return True
            if src in self._dead_readers:
                # A probe loop on a dead, drained channel can never turn
                # true — fail loudly (the aio schedulers' probe-then-recv
                # pattern, aio/scheduler.py, would otherwise poll forever;
                # the error surfaces from Scheduler.wait with the task
                # attached).
                raise RuntimeError(
                    f"recv from rank {src} failed: connection lost"
                )
            return False

    def test(self, handle: Handle) -> bool:
        if handle.cancelled:
            err = handle.meta.pop("error", None)
            if err:  # raise exactly once, then report not-done quietly
                raise RuntimeError(err)
            return False
        if handle.done:
            return True
        if handle.kind == "send":
            return handle.done
        with self._lock:
            chan = self._channels[(handle.peer, handle.tag)]
            while chan.pending and chan.pending[0].cancelled:
                chan.pending.popleft()
            if not chan.pending or chan.pending[0] is not handle or not chan.msgs:
                return False
            msg = chan.msgs[0]
            if handle.meta.get("as_bytes"):
                chan.msgs.popleft()
                chan.pending.popleft()
                handle.payload = msg
                handle.done = True
                return True
            view = as_writable_view(handle.out)
            if view.nbytes != len(msg):
                handle.cancelled = True
                chan.pending.popleft()  # message stays for a correct recv
                raise ValueError(
                    f"recv size mismatch: message {len(msg)}B does not fit "
                    f"buffer {view.nbytes}B (src={handle.peer}, tag={handle.tag})"
                )
            chan.msgs.popleft()
            chan.pending.popleft()
            view[:] = msg
            handle.done = True
            return True

    def cancel(self, handle: Handle) -> None:
        handle.cancelled = True
        handle.buf = None  # pending-queue entries are reaped lazily in test

    def close(self) -> None:
        if self._closed:
            return
        # Goodbye frames: queue one to every live peer (FIFO after any
        # still-queued user sends) and give the loop a bounded grace
        # period to flush, so readers on the other side see an orderly
        # shutdown rather than a crash.  Best-effort: a dead or
        # backlogged peer just misses the goodbye and reports
        # connection-lost, which is accurate for it.
        zero = np.empty(0, np.uint8)
        for peer in range(self.nranks):
            if peer == self.rank:
                continue
            cv = self._out_cv[peer]
            with cv:
                if peer not in self._dead_peers:
                    self._outboxes[peer].append(
                        (Handle(kind="send", peer=peer, tag=_GOODBYE_TAG),
                         _HDR.pack(_GOODBYE_TAG, 0, 0), zero.view(), None)
                    )
                    cv.notify()
            self._mark_dirty(peer)
        # The loop owns connection state (including installs still in
        # flight right after construction), so the loop decides when the
        # flush is complete; a dead loop just costs the bounded wait.
        self._closing = True
        self._wake()
        self._flushed.wait(1.0)
        self._closed = True
        self._wake()
        for t in self._threads:
            t.join(2)
        # The loop is gone: sockets and selector are ours to tear down.
        # Cancel every queued send left — a blocking sender must observe
        # done-or-cancelled, never an orphaned handle.
        for peer in range(self.nranks):
            if peer != self.rank:
                self._drain_outbox(peer)
        for cv in self._out_cv.values():
            with cv:
                cv.notify_all()
        for conn in self._peers.values():
            try:
                conn.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            conn.close()
        for d in list(self._dials.values()):
            if d.sock is not None:
                try:
                    d.sock.close()
                except OSError:
                    pass
        for hs in list(self._hss):
            try:
                hs.sock.close()
            except OSError:
                pass
        for peer, conn in list(self._installq):
            try:
                conn.sock.close()
            except OSError:
                pass
        if self._listener is not None:
            try:
                self._listener.close()
            except OSError:
                pass
        try:
            self._sel.close()
        except OSError:
            pass
        for s in (self._wake_r, self._wake_w):
            try:
                s.close()
            except OSError:
                pass
