"""TcpTransport — cross-host transport for the PS protocol (DCN analog).

The shm transport covers the reference's one-host ``mpirun -np N`` shape;
this covers its multi-node hostfile deployments (reference
BiCNN/hostfiles, README.md:57-61) for the *host-mediated* asynchronous PS
path — the traffic XLA collectives can't express.  (On-mesh trainers
already cross hosts via jax.distributed + DCN; this is the transport for
the ParamServer/ParamClient role topology.)

Same contract and semantics as :class:`mpit_tpu.comm.shm.ShmTransport`:
nonblocking (rank, tag)-addressed messaging, FIFO per channel, exact-size
receives, buffer ownership until ``test`` is True, cancel-on-shutdown.

Wire format per message: 16-byte header (tag int64, size int64, little
endian) + payload.  Connections form a full mesh at construction: every
rank listens on its ``host:port`` from the address book; rank i dials
every rank j < i and accepts from every j > i (each side identifies
itself with an 8-byte rank handshake).  One reader thread per peer
drains frames into per-channel queues; sends run on a per-peer writer
thread so ``isend`` never blocks on a slow peer.  The outbox is
zero-copy — queued entries view the caller's buffer (owned by the
transport until ``test`` is True), so a deep backlog costs O(1)
transport-owned memory per message, not a payload copy.
"""

from __future__ import annotations

import socket
import struct
import threading
import time
from collections import defaultdict, deque
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from mpit_tpu.comm.transport import (
    Handle,
    Transport,
    as_bytes_view,
    as_writable_view,
)

_HDR = struct.Struct("<qq")  # tag, size
_RANK_HDR = struct.Struct("<q")
# Reserved wire tag: an orderly close() announces itself so the peer's
# reader can distinguish graceful shutdown (old silent-cancel semantics)
# from a crash (fail-loud semantics).  User tags are non-negative
# (ps/tags.py, collectives' 2^16+ range), so the sentinel can't collide.
_GOODBYE_TAG = -(1 << 62)


def allocate_local_addresses(nranks: int) -> Tuple[List[str], List[socket.socket]]:
    """Pre-bound localhost listeners with OS-assigned ports, for tests and
    same-host runs: returns (addresses, listeners); pass ``listeners[r]``
    to rank r's transport so no port is lost to a rebind race."""
    addrs, socks = [], []
    for _ in range(nranks):
        s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        s.bind(("127.0.0.1", 0))
        s.listen(nranks)
        addrs.append(f"127.0.0.1:{s.getsockname()[1]}")
        socks.append(s)
    return addrs, socks


def _recv_exact(sock: socket.socket, n: int) -> Optional[bytes]:
    buf = bytearray(n)
    view = memoryview(buf)
    got = 0
    while got < n:
        r = sock.recv_into(view[got:], n - got)
        if r == 0:
            return None  # peer closed
        got += r
    return bytes(buf)


class _Channel:
    __slots__ = ("msgs", "pending")

    def __init__(self):
        self.msgs: deque = deque()      # fully-assembled payloads (bytes)
        self.pending: deque = deque()   # posted recv handles, FIFO


class TcpTransport(Transport):
    def __init__(
        self,
        rank: int,
        nranks: int,
        addresses: Sequence[str],
        *,
        listener: Optional[socket.socket] = None,
        connect_timeout: float = 60.0,
    ):
        if len(addresses) != nranks:
            raise ValueError(f"need {nranks} addresses, got {len(addresses)}")
        self.rank = rank
        self.nranks = nranks
        self._lock = threading.Lock()
        self._channels: Dict[Tuple[int, int], _Channel] = defaultdict(_Channel)
        self._peers: Dict[int, socket.socket] = {}
        self._outboxes: Dict[int, deque] = {r: deque() for r in range(nranks)}
        self._out_cv: Dict[int, threading.Condition] = {
            r: threading.Condition() for r in range(nranks)
        }
        # Peers whose writer thread has died (socket error): new isends
        # are cancelled immediately instead of queueing into a box nobody
        # drains.
        self._dead_peers: set = set()
        # Peers whose reader has died mid-run: pending receives with no
        # message to match fail loudly (raise-once from test) instead of
        # polling forever on a connection that can never deliver.
        self._dead_readers: set = set()
        self._threads: List[threading.Thread] = []
        self._closed = False

        host, _, port = addresses[rank].rpartition(":")
        if listener is None:
            listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            listener.bind((host or "0.0.0.0", int(port)))
            listener.listen(nranks)
        self._listener = listener

        # Dial lower ranks, accept higher ranks (deadlock-free full mesh).
        deadline = time.monotonic() + connect_timeout
        for peer in range(rank):
            self._peers[peer] = self._dial(addresses[peer], deadline)
        for _ in range(nranks - rank - 1):
            conn, _addr = self._accept(deadline)
            peer_hdr = _recv_exact(conn, _RANK_HDR.size)
            if peer_hdr is None:
                raise ConnectionError("peer closed during handshake")
            (peer,) = _RANK_HDR.unpack(peer_hdr)
            self._peers[int(peer)] = conn
        for peer, conn in self._peers.items():
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            self._spawn(self._reader, peer, conn)
            self._spawn(self._writer, peer, conn)

    # -- connection plumbing -------------------------------------------------

    def _dial(self, address: str, deadline: float) -> socket.socket:
        host, _, port = address.rpartition(":")
        last_err: Optional[Exception] = None
        while time.monotonic() < deadline:
            try:
                conn = socket.create_connection((host, int(port)), timeout=5.0)
                conn.settimeout(None)
                conn.sendall(_RANK_HDR.pack(self.rank))
                return conn
            except OSError as e:  # peer not up yet
                last_err = e
                time.sleep(0.05)
        raise ConnectionError(f"could not reach {address}: {last_err!r}")

    def _accept(self, deadline: float) -> Tuple[socket.socket, Any]:
        self._listener.settimeout(max(deadline - time.monotonic(), 0.1))
        try:
            return self._listener.accept()
        except socket.timeout:
            raise ConnectionError("timed out waiting for peer connections")

    def _spawn(self, fn, *args) -> None:
        t = threading.Thread(target=fn, args=args, daemon=True)
        t.start()
        self._threads.append(t)

    def _reader(self, peer: int, conn: socket.socket) -> None:
        graceful = False
        try:
            while True:
                hdr = _recv_exact(conn, _HDR.size)
                if hdr is None:
                    return
                tag, size = _HDR.unpack(hdr)
                if tag == _GOODBYE_TAG:
                    graceful = True  # peer is closing in an orderly way
                    return
                payload = _recv_exact(conn, int(size)) if size else b""
                if payload is None:
                    return
                with self._lock:
                    self._channels[(peer, int(tag))].msgs.append(payload)
        except OSError:
            return  # socket torn down by close()
        finally:
            if not graceful and not self._closed:
                self._fail_unmatched_recvs(peer)

    def _fail_unmatched_recvs(self, peer: int) -> None:
        """A mid-run reader death (peer crashed / link dropped): every
        pending recv beyond the already-delivered backlog can never
        complete — fail them with the raise-once convention, and make
        later irecvs from this peer fail the same way.  Messages that
        arrived before the death still serve matching receives (same
        drain-what-landed semantics as the shm transport's remap)."""
        err = f"recv from rank {peer} failed: connection lost"
        with self._lock:
            self._dead_readers.add(peer)
            for (src, _tag), chan in self._channels.items():
                if src != peer:
                    continue
                live = [h for h in chan.pending if not h.cancelled]
                for h in live[len(chan.msgs):]:
                    h.cancelled = True
                    h.meta["error"] = err

    def _writer(self, peer: int, conn: socket.socket) -> None:
        cv = self._out_cv[peer]
        box = self._outboxes[peer]
        while True:
            with cv:
                while not box and not self._closed:
                    cv.wait(0.5)
                if self._closed and not box:
                    return
                handle, header, payload = box.popleft()
            try:
                conn.sendall(header)
                if payload.nbytes:
                    conn.sendall(payload)
            except OSError:
                # Dead peer/socket: cancel this and every queued send with
                # a recorded error so blocking senders get a raise from
                # test() (the shm transport's raise-once convention)
                # instead of spinning forever.
                err = f"send to rank {peer} failed: connection lost"
                handle.cancelled = True
                handle.buf = None
                handle.meta["error"] = err
                self._drain_outbox(peer, error=err)
                return
            handle.done = True
            handle.buf = None  # ownership back to the caller

    def _drain_outbox(self, peer: int, error: str | None = None) -> None:
        """Cancel every queued send to ``peer``.  With ``error`` (dead
        peer) the handles raise from ``test``; without (orderly close)
        they cancel silently."""
        cv = self._out_cv[peer]
        with cv:
            self._dead_peers.add(peer)
            cv.notify_all()
            while self._outboxes[peer]:
                h, _hdr, _payload = self._outboxes[peer].popleft()
                h.cancelled = True
                h.buf = None
                if error:
                    h.meta["error"] = error

    # -- Transport -----------------------------------------------------------

    def isend(self, data: Any, dst: int, tag: int) -> Handle:
        if dst == self.rank or not 0 <= dst < self.nranks:
            raise ValueError(f"isend to invalid rank {dst}")
        if self._closed:
            raise RuntimeError("isend on a closed transport")
        view = as_bytes_view(b"" if data is None else data)
        handle = Handle(kind="send", peer=dst, tag=tag, buf=data)
        # Zero-copy queue: the outbox holds a *view* over the caller's
        # buffer, not a snapshot — the ownership contract already forbids
        # the caller touching it until test() is True (reported only
        # after sendall), so transport-owned memory stays O(1) per queued
        # message however deep the backlog, and isend never blocks.
        cv = self._out_cv[dst]
        with cv:
            if dst in self._dead_peers:
                handle.cancelled = True
                handle.buf = None
                handle.meta["error"] = f"rank {dst} unreachable (writer dead)"
                return handle
            self._outboxes[dst].append(
                (handle, _HDR.pack(tag, view.nbytes), view)
            )
            cv.notify()
        return handle

    def irecv(self, src: int, tag: int, out: Any | None = None) -> Handle:
        if src == self.rank or not 0 <= src < self.nranks:
            raise ValueError(f"irecv from invalid rank {src}")
        handle = Handle(kind="recv", peer=src, tag=tag, out=out)
        if out is None:
            handle.meta["as_bytes"] = True
        with self._lock:
            chan = self._channels[(src, tag)]
            if src in self._dead_readers:
                # Only the already-delivered backlog can satisfy receives.
                live = sum(1 for h in chan.pending if not h.cancelled)
                if live >= len(chan.msgs):
                    handle.cancelled = True
                    handle.meta["error"] = (
                        f"recv from rank {src} failed: connection lost"
                    )
                    return handle
            chan.pending.append(handle)
        return handle

    def iprobe(self, src: int, tag: int) -> bool:
        with self._lock:
            if self._channels[(src, tag)].msgs:
                return True
            if src in self._dead_readers:
                # A probe loop on a dead, drained channel can never turn
                # true — fail loudly (the aio schedulers' probe-then-recv
                # pattern, aio/scheduler.py, would otherwise poll forever;
                # the error surfaces from Scheduler.wait with the task
                # attached).
                raise RuntimeError(
                    f"recv from rank {src} failed: connection lost"
                )
            return False

    def test(self, handle: Handle) -> bool:
        if handle.cancelled:
            err = handle.meta.pop("error", None)
            if err:  # raise exactly once, then report not-done quietly
                raise RuntimeError(err)
            return False
        if handle.done:
            return True
        if handle.kind == "send":
            return handle.done
        with self._lock:
            chan = self._channels[(handle.peer, handle.tag)]
            while chan.pending and chan.pending[0].cancelled:
                chan.pending.popleft()
            if not chan.pending or chan.pending[0] is not handle or not chan.msgs:
                return False
            msg = chan.msgs[0]
            if handle.meta.get("as_bytes"):
                chan.msgs.popleft()
                chan.pending.popleft()
                handle.payload = msg
                handle.done = True
                return True
            view = as_writable_view(handle.out)
            if view.nbytes != len(msg):
                handle.cancelled = True
                chan.pending.popleft()  # message stays for a correct recv
                raise ValueError(
                    f"recv size mismatch: message {len(msg)}B does not fit "
                    f"buffer {view.nbytes}B (src={handle.peer}, tag={handle.tag})"
                )
            chan.msgs.popleft()
            chan.pending.popleft()
            view[:] = msg
            handle.done = True
            return True

    def cancel(self, handle: Handle) -> None:
        handle.cancelled = True
        handle.buf = None  # pending-queue entries are reaped lazily in test

    def close(self) -> None:
        if self._closed:
            return
        # Goodbye frames: queue one to every live peer (FIFO after any
        # still-queued user sends) and give the writers a bounded grace
        # period to flush, so readers on the other side see an orderly
        # shutdown rather than a crash.  Best-effort: a dead or
        # backlogged peer just misses the goodbye and reports
        # connection-lost, which is accurate for it.
        zero = np.empty(0, np.uint8)
        for peer in range(self.nranks):
            if peer == self.rank:
                continue
            cv = self._out_cv[peer]
            with cv:
                if peer not in self._dead_peers:
                    self._outboxes[peer].append(
                        (Handle(kind="send", peer=peer, tag=_GOODBYE_TAG),
                         _HDR.pack(_GOODBYE_TAG, 0), zero.view())
                    )
                    cv.notify()
        deadline = time.monotonic() + 1.0
        while time.monotonic() < deadline and any(
            self._outboxes[p] for p in range(self.nranks) if p != self.rank
        ):
            time.sleep(0.005)
        self._closed = True
        # Cancel every queued send left — a blocking sender must observe
        # done-or-cancelled, never an orphaned handle.
        for peer in range(self.nranks):
            if peer != self.rank:
                self._drain_outbox(peer)
        for cv in self._out_cv.values():
            with cv:
                cv.notify_all()
        for conn in self._peers.values():
            try:
                conn.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            conn.close()
        try:
            self._listener.close()
        except OSError:
            pass
        for t in self._threads:
            t.join(2)
