"""Wire codecs for the PS hot path — quantized shard transfer.

Every GRAD / PARAM / PARAM_PUSH message used to ship the full fp32
shard.  This registry provides the EQuARX-style alternative (PAPERS.md:
block-quantized comms inside the collective): a codec turns a float32
shard slice into a smaller wire frame and back, selected by name via
``MPIT_PS_CODEC`` and negotiated per client<->server pair through the
INIT v2 announcement (``[offset, size, codec_id]`` — ps/tags.py).

Codecs
------
- ``none``  (wire id 0) — identity.  The client/server hot paths special
  -case it (``identity=True``) to keep today's zero-copy sends.
- ``bf16``  (wire id 1) — fp32 -> bfloat16 by mantissa truncation (the
  top 16 bits of the IEEE-754 word).  2x smaller, ~2^-8 relative error.
- ``int8``  (wire id 2) — per-block absmax scaling: each 1024-element
  block ships one fp32 scale (absmax/127) plus int8 codes, ~3.9x
  smaller.  Lossy enough to need **error feedback** on the gradient
  path: the client keeps a per-shard residual, adds it to the next
  gradient before quantizing, and stores the fresh quantization error
  back (``encode_into(..., residual=r)``).  The compression error is
  then re-shipped instead of lost, which preserves DOWNPOUR/EASGD
  convergence (the standard EF-SGD argument; see docs/PROTOCOL.md).

Frame layout (``int8``, for an n-element slice with B=1024)::

    [ scales: ceil(n/B) x f32 | codes: n x i8 ]

The layout is a pure function of ``size``, so both sides derive buffer
sizes from the INIT announcement — frames carry no per-message header.
A codec mismatch therefore shows up as a wire-size mismatch and fails
loudly in the transports' exact-size receive contract (never as
silently corrupt parameters); negotiation itself is validated at INIT
time (ps/server.py).

Decode on the server gradient path is **fused into the jitted shard
update**: ``decode_parts`` is pure jax-traceable math over the typed
views of the staging buffer (``split_wire``), so one XLA call per
gradient decodes + applies, exactly as the fp32 path does today.
"""

from __future__ import annotations

import os
import sys
from typing import Dict, List, Optional, Tuple

import numpy as np

from mpit_tpu.obs import metrics as _obs

_LITTLE = sys.byteorder == "little"

# Native kernels (comm/native/transport.cpp, mt_codec_*): the same math
# as the numpy paths below in 2 cache-resident passes per block instead
# of ~8 per tile — measured ~3x encode throughput at the 640 MB ptest
# scale, and ctypes releases the GIL for the call.  Results are
# bit-identical to the numpy paths (build.py pins -ffp-contract=off;
# parity-tested in tests/test_codec.py), so the numpy code stays as the
# fallback (no g++ on the host, MPIT_PS_CODEC_NATIVE=0) and the oracle.
_NATIVE_ENV = "MPIT_PS_CODEC_NATIVE"
_native_lib: Optional[object] = None  # None: untried; False: unavailable


def _native():
    global _native_lib
    if _native_lib is None:
        if os.environ.get(_NATIVE_ENV, "1") == "0" or not _LITTLE:
            _native_lib = False
        else:
            try:
                from mpit_tpu.comm.native import build
                from mpit_tpu.comm.native._bindings import NativeTransportLib

                _native_lib = NativeTransportLib(build.ensure_built())
            except Exception:  # no g++ / unwritable tree: numpy fallback
                _native_lib = False
    return _native_lib or None

#: int8 per-block absmax granularity.  4 bytes of scale per 1024 codes
#: keeps the overhead at ~0.4% while bounding each element's error by
#: its own block's absmax/254 (tighter than one whole-shard scale).
BLOCK = 1024

#: int8 host-codec tile: elements processed per pass so the working
#: temporaries (~2 f32 tiles = 2 MB) stay cache-resident — the encoder's
#: DRAM traffic then approaches the compulsory read/write minimum
#: instead of one full sweep per ufunc (measured ~1.8x encode throughput
#: on the 640 MB ptest host, 1-core Xeon with 2 MB L2).
_TILE = 256 * BLOCK

ENV = "MPIT_PS_CODEC"


def _nblocks(size: int) -> int:
    return (size + BLOCK - 1) // BLOCK


class Codec:
    """One wire format.  Stateless — error-feedback residuals live with
    the caller (the client owns one per shard)."""

    name: str = "?"
    wire_id: int = -1
    identity: bool = False  # hot paths skip encode/decode entirely
    uses_residual: bool = False

    def wire_nbytes(self, size: int) -> int:
        """Exact frame bytes for ``size`` float32 elements."""
        raise NotImplementedError

    def encode_into(
        self,
        x: np.ndarray,
        wire: np.ndarray,
        residual: Optional[np.ndarray] = None,
    ) -> None:
        """Encode float32 ``x`` into the uint8 ``wire`` buffer.  With
        ``residual`` (same shape as ``x``), quantize ``x + residual``
        and store the new quantization error back into ``residual``
        (error feedback — gradient path only).

        Observability: encode time and produced wire bytes feed the obs
        registry (``mpit_codec_*``) when obs is enabled; disabled, the
        wrap is one ``enabled`` attribute read per call (the clock lives
        in the registry timer, never here — the MT-O4xx contract)."""
        reg = _obs.get_registry()
        if not reg.enabled:
            self._encode_into(x, wire, residual)
            return
        with reg.timer("mpit_codec_encode_seconds", codec=self.name):
            self._encode_into(x, wire, residual)
        reg.counter("mpit_codec_encode_bytes_total",
                    codec=self.name).inc(int(wire.nbytes))

    def decode_into(self, wire: np.ndarray, out: np.ndarray) -> None:
        """Decode a frame into the float32 ``out`` buffer (host path).
        Timed into the obs registry like :meth:`encode_into`."""
        reg = _obs.get_registry()
        if not reg.enabled:
            self._decode_into(wire, out)
            return
        with reg.timer("mpit_codec_decode_seconds", codec=self.name):
            self._decode_into(wire, out)
        reg.counter("mpit_codec_decode_bytes_total",
                    codec=self.name).inc(int(wire.nbytes))

    def _encode_into(self, x, wire, residual=None) -> None:
        raise NotImplementedError

    def _decode_into(self, wire, out) -> None:
        raise NotImplementedError

    def split_wire(self, wire: np.ndarray, size: int) -> List[np.ndarray]:
        """Typed zero-copy views over a staging buffer, in the order
        ``decode_parts`` consumes them."""
        raise NotImplementedError

    #: bytes per element for codecs whose frame is one homogeneous
    #: region (none: 4, bf16: 2); frame-structured codecs override
    #: chunk_regions instead.
    _flat_stride: int = 0

    def chunk_regions(self, size: int, lo: int,
                      hi: int) -> "List[Tuple[int, int, int]]":
        """``(full_off, chunk_off, nbytes)`` copy spans mapping the
        *independent* chunk frame for elements ``[lo, hi)`` onto the
        full-``size`` frame's byte regions (streaming transfers,
        docs/PROTOCOL.md §12).  ``lo`` must sit on a BLOCK boundary —
        the invariant that makes a per-chunk encode bit-identical to
        the same region of a whole-shard encode, residual fold
        included."""
        if lo % BLOCK:
            raise ValueError(
                f"chunk start {lo} is not BLOCK({BLOCK})-aligned — "
                "chunk frames are only bit-stable on block boundaries")
        stride = self._flat_stride
        if not stride:
            raise NotImplementedError
        return [(stride * lo, 0, stride * (hi - lo))]

    def decode_parts(self, parts: List, size: int):
        """jax-traceable decode of ``split_wire`` parts -> float32[size].
        Called inside the server's jitted update program."""
        raise NotImplementedError


class NoneCodec(Codec):
    name = "none"
    wire_id = 0
    identity = True
    _flat_stride = 4

    def wire_nbytes(self, size: int) -> int:
        return 4 * size

    def _encode_into(self, x, wire, residual=None):
        wire.view(np.float32)[: x.size] = x

    def _decode_into(self, wire, out):
        out[:] = wire.view(np.float32)[: out.size]

    def split_wire(self, wire, size):
        return [wire.view(np.float32)[:size]]

    def decode_parts(self, parts, size):
        return parts[0]


class Bf16Codec(Codec):
    name = "bf16"
    wire_id = 1
    _flat_stride = 2

    def wire_nbytes(self, size: int) -> int:
        return 2 * size

    def _encode_into(self, x, wire, residual=None):
        # Truncation: keep the top 16 bits of the fp32 word.  On a
        # little-endian host that is one strided copy of the high
        # half-words — no whole-shard uint32 temporaries, which at the
        # 640 MB ptest scale would cost two extra DRAM sweeps plus the
        # allocations.  (Residual is accepted for interface uniformity
        # but bf16's ~2^-8 relative error needs no feedback; it stays
        # zero.)
        lib = _native()
        if lib is not None:
            lib.mt_codec_bf16_encode(x, x.size, wire)
        elif _LITTLE:
            wire.view(np.uint16)[: x.size] = x.view(np.uint16)[1::2]
        else:  # pragma: no cover - big-endian fallback
            wire.view(np.uint16)[: x.size] = (
                x.view(np.uint32) >> 16
            ).astype(np.uint16)

    def _decode_into(self, wire, out):
        lib = _native()
        if lib is not None:
            lib.mt_codec_bf16_decode(wire, out.size, out)
        elif _LITTLE:
            o16 = out.view(np.uint16)
            o16[0::2] = 0  # low mantissa halves
            o16[1::2] = wire.view(np.uint16)[: out.size]
        else:  # pragma: no cover - big-endian fallback
            out.view(np.uint32)[:] = (
                wire.view(np.uint16)[: out.size].astype(np.uint32) << 16
            )

    def split_wire(self, wire, size):
        import ml_dtypes  # ships with jax

        return [wire.view(ml_dtypes.bfloat16)[:size]]

    def decode_parts(self, parts, size):
        import jax.numpy as jnp

        return parts[0].astype(jnp.float32)


class Int8Codec(Codec):
    name = "int8"
    wire_id = 2
    uses_residual = True

    def wire_nbytes(self, size: int) -> int:
        return 4 * _nblocks(size) + size

    def _views(self, wire: np.ndarray, size: int):
        nb = _nblocks(size)
        scales = wire[: 4 * nb].view(np.float32)
        codes = wire[4 * nb : 4 * nb + size].view(np.int8)
        return scales, codes

    def _encode_into(self, x, wire, residual=None):
        # Cache-tiled and pass-frugal on purpose: the encoder competes
        # with the wire for the same memory bandwidth, so every DRAM
        # sweep shows up 1:1 in PS throughput.  The slice is processed
        # in _TILE-element tiles whose temporaries stay cache-resident —
        # DRAM traffic approaches the compulsory minimum (read x[/r],
        # write codes[/r]) instead of one full sweep per ufunc.  absmax
        # uses max/min (no |x| temp); codes come from one multiply by
        # the reciprocal scale + in-place rint; no clip pass — |work| <=
        # block absmax guarantees |rint(work * (1/scale))| <= 127.
        size = x.size
        nb = _nblocks(size)
        nfull, main = size // BLOCK, (size // BLOCK) * BLOCK
        scales, codes = self._views(wire, size)
        lib = _native()
        if lib is not None:
            lib.mt_codec_int8_encode(x, residual, size, scales, codes)
            return
        if nfull:
            work = np.empty(min(_TILE, main), np.float32)
            q = np.empty_like(work)
            inv = np.empty(min(_TILE, main) // BLOCK, np.float32)
            for lo in range(0, main, _TILE):
                hi = min(lo + _TILE, main)
                tb = (hi - lo) // BLOCK  # tile block count
                w2 = work[: hi - lo].reshape(tb, BLOCK)
                q2 = q[: hi - lo].reshape(tb, BLOCK)
                if residual is None:
                    np.copyto(work[: hi - lo], x[lo:hi])
                else:
                    np.add(x[lo:hi], residual[lo:hi],
                           out=work[: hi - lo])
                sc = scales[lo // BLOCK : lo // BLOCK + tb]
                np.max(w2, axis=1, out=sc)
                np.min(w2, axis=1, out=inv[:tb])
                np.maximum(sc, -inv[:tb], out=sc)
                # scale = absmax/127; zero blocks keep scale 1.0 (codes
                # are all zero either way; avoids inf reciprocals).
                np.divide(sc, 127.0, out=sc)
                sc[sc == 0.0] = 1.0
                np.divide(1.0, sc, out=inv[:tb])
                np.multiply(w2, inv[:tb, None], out=q2)
                np.rint(q2, out=q2)
                np.copyto(codes[lo:hi].reshape(tb, BLOCK), q2,
                          casting="unsafe")
                if residual is not None:
                    q2 *= sc[:, None]  # q2 is now the dequantized value
                    np.subtract(w2, q2,
                                out=residual[lo:hi].reshape(tb, BLOCK))
        if main < size:
            # Pure-f32 scalar math, same op order as the full blocks and
            # the native kernel — the tail frame is bit-identical to
            # what mt_codec_int8_encode produces.
            tail = (x[main:] if residual is None
                    else x[main:] + residual[main:])
            absmax = np.float32(max(tail.max(initial=0.0),
                                    -tail.min(initial=0.0)))
            scales[nb - 1] = (np.float32(1.0) if absmax == 0.0
                              else absmax / np.float32(127.0))
            t = tail * (np.float32(1.0) / scales[nb - 1])
            np.rint(t, out=t)
            np.copyto(codes[main:], t, casting="unsafe")
            if residual is not None:
                t *= scales[nb - 1]
                np.subtract(tail, t, out=residual[main:])

    def _decode_into(self, wire, out):
        # Tiled like encode_into: dequantize straight into the caller's
        # slice, int8->f32 cast riding the same cache-resident pass as
        # the scale multiply.
        size = out.size
        nb = _nblocks(size)
        nfull, main = size // BLOCK, (size // BLOCK) * BLOCK
        scales, codes = self._views(wire, size)
        lib = _native()
        if lib is not None:
            lib.mt_codec_int8_decode(scales, codes, size, out)
            return
        for lo in range(0, main, _TILE):
            hi = min(lo + _TILE, main)
            tb = (hi - lo) // BLOCK
            o2 = out[lo:hi].reshape(tb, BLOCK)
            np.copyto(o2, codes[lo:hi].reshape(tb, BLOCK), casting="unsafe")
            o2 *= scales[lo // BLOCK : lo // BLOCK + tb, None]
        if main < size:
            out[main:] = codes[main:].astype(np.float32) * scales[nb - 1]

    def split_wire(self, wire, size):
        return list(self._views(wire, size))

    def chunk_regions(self, size, lo, hi):
        # The chunk frame is itself an int8 frame for (hi - lo)
        # elements: [chunk scales | chunk codes].  Block alignment of
        # ``lo`` makes its scale blocks a contiguous run of the full
        # frame's scale region — two copy spans, no per-block walk.
        if lo % BLOCK:
            raise ValueError(
                f"chunk start {lo} is not BLOCK({BLOCK})-aligned — "
                "chunk frames are only bit-stable on block boundaries")
        nb_chunk = _nblocks(hi - lo)
        return [
            (4 * (lo // BLOCK), 0, 4 * nb_chunk),
            (4 * _nblocks(size) + lo, 4 * nb_chunk, hi - lo),
        ]

    def decode_parts(self, parts, size):
        import jax.numpy as jnp

        scales, codes = parts
        nfull, main = size // BLOCK, (size // BLOCK) * BLOCK
        pieces = []
        if nfull:
            pieces.append(
                (codes[:main].reshape(nfull, BLOCK).astype(jnp.float32)
                 * scales[:nfull, None]).reshape(-1)
            )
        if main < size:
            pieces.append(codes[main:].astype(jnp.float32) * scales[-1])
        return pieces[0] if len(pieces) == 1 else jnp.concatenate(pieces)


_REGISTRY: Dict[str, Codec] = {}
_BY_WIRE_ID: Dict[int, Codec] = {}

for _codec in (NoneCodec(), Bf16Codec(), Int8Codec()):
    _REGISTRY[_codec.name] = _codec
    _BY_WIRE_ID[_codec.wire_id] = _codec


def get(name: Optional[str] = None) -> Codec:
    """Codec by name; None/'' falls back to ``$MPIT_PS_CODEC`` (default
    'none').  Unknown names fail loudly — a typo must not silently train
    uncompressed."""
    if not name:
        name = os.environ.get(ENV, "none") or "none"
    try:
        return _REGISTRY[name]
    except KeyError:
        raise ValueError(
            f"unknown PS codec {name!r}; available: {sorted(_REGISTRY)}"
        ) from None


def by_wire_id(wire_id: int) -> Codec:
    """Codec from an INIT v2 announcement id.  Unknown ids fail loudly —
    decoding with the wrong codec would corrupt parameters."""
    try:
        return _BY_WIRE_ID[wire_id]
    except KeyError:
        raise ValueError(
            f"unknown codec wire id {wire_id} in INIT announcement; "
            f"known: { {c.wire_id: c.name for c in _REGISTRY.values()} }"
        ) from None


def names() -> List[str]:
    return sorted(_REGISTRY)


# -- chunk-frame <-> full-frame copies (streaming transfers, §12) ------------


def _chunk_copy_spans(codec: Codec, size: int, lo: int, hi: int,
                      itemsize: int) -> List[Tuple[int, int, int]]:
    """Identity codecs carry arbitrary dtypes — their regions scale by
    the *registered* itemsize, not the f32 the quantizers assume."""
    if codec.identity:
        if lo % BLOCK:
            raise ValueError(
                f"chunk start {lo} is not BLOCK({BLOCK})-aligned — "
                "chunk frames are only bit-stable on block boundaries")
        return [(itemsize * lo, 0, itemsize * (hi - lo))]
    return codec.chunk_regions(size, lo, hi)


def gather_chunk(codec: Codec, full: np.ndarray, size: int, lo: int,
                 hi: int, chunk: np.ndarray, itemsize: int = 4) -> None:
    """Copy the ``[lo, hi)`` chunk's independent frame out of a
    full-shard frame (the PARAM serve path: one shared snapshot encode,
    per-chunk frames cut from it)."""
    for full_off, chunk_off, nbytes in _chunk_copy_spans(
            codec, size, lo, hi, itemsize):
        chunk[chunk_off:chunk_off + nbytes] = full[full_off:full_off + nbytes]


def scatter_chunk(codec: Codec, full: np.ndarray, size: int, lo: int,
                  hi: int, chunk: np.ndarray, itemsize: int = 4) -> None:
    """Copy a chunk frame into its regions of a full-shard frame (the
    PARAM_PUSH assembly path: chunks land in staging, one decode+seed
    at completion)."""
    for full_off, chunk_off, nbytes in _chunk_copy_spans(
            codec, size, lo, hi, itemsize):
        full[full_off:full_off + nbytes] = chunk[chunk_off:chunk_off + nbytes]
