"""Transport interface: nonblocking (rank, tag)-addressed messaging.

This is the contract the async engine's ``aio_send``/``aio_recv`` poll
(mpit_tpu/aio/scheduler.py) and the parameter-server layer builds on.  It
deliberately mirrors the slice of MPI the reference actually uses — Isend,
Irecv, Iprobe, Test, Cancel (reference mpifuncs.c:1532,1499,1488,1936,197
via init.lua:40-102) — rather than the full MPI-2 surface, because on TPU
the collective paths go through XLA, not through this host transport.

Buffer discipline (the reference's zero-copy rule, lua-mpi.h:70-78): the
caller passes numpy arrays / memoryviews; the transport reads from or
writes into them directly.  A send buffer must stay alive and unmodified
until ``test`` returns True; handles hold a reference to enforce liveness.
"""

from __future__ import annotations

import abc
from dataclasses import dataclass, field
from typing import Any, Optional

import numpy as np


@dataclass
class Handle:
    """An in-flight transfer.  ``buf`` keeps the caller buffer alive."""

    kind: str  # "send" | "recv"
    peer: int
    tag: int
    buf: Any = None
    out: Any = None
    done: bool = False
    cancelled: bool = False
    payload: Optional[Any] = None
    native_id: int = -1
    meta: dict = field(default_factory=dict)


def as_bytes_view(data: Any) -> memoryview:
    """A contiguous byte view over array/bytes-like data.

    Fail-loud zero-copy rule: a non-contiguous ndarray would need a
    silent ``ascontiguousarray`` copy — after which the documented
    liveness contract ("buffer stays alive and unmodified until test()")
    binds the caller to the *wrong* buffer: mutations between isend and
    completion would be invisibly dropped.  Raise like the recv path
    does instead; callers own making their send buffers contiguous."""
    if isinstance(data, np.ndarray):
        if not data.flags["C_CONTIGUOUS"]:
            raise ValueError(
                "send buffer must be C-contiguous (zero-copy rule: a "
                "hidden copy would break buffer-liveness semantics)"
            )
        return memoryview(data).cast("B")
    return memoryview(data).cast("B") if not isinstance(data, memoryview) else data.cast("B")


def as_writable_view(out: Any) -> memoryview:
    if isinstance(out, np.ndarray):
        if not out.flags["C_CONTIGUOUS"]:
            raise ValueError("recv target must be C-contiguous (zero-copy rule)")
        return memoryview(out).cast("B")
    return memoryview(out).cast("B")


class Transport(abc.ABC):
    """Nonblocking point-to-point transport for one endpoint (rank)."""

    rank: int
    nranks: int

    @abc.abstractmethod
    def isend(self, data: Any, dst: int, tag: int) -> Handle:
        """Post a nonblocking send of the buffer's bytes."""

    @abc.abstractmethod
    def irecv(self, src: int, tag: int, out: Any | None = None) -> Handle:
        """Post a nonblocking receive.  With ``out`` the payload is written
        in place (sizes must match); otherwise ``payload`` returns bytes."""

    @abc.abstractmethod
    def iprobe(self, src: int, tag: int) -> bool:
        """True when a fully-assembled matching message is available.

        Fail-loud contract: when the transport *knows* ``src`` can never
        deliver again (dead peer, torn connection) and no matching message
        is buffered, implementations should raise ``RuntimeError`` rather
        than return ``False`` — the schedulers' probe-then-recv loops
        (aio/scheduler.py) would otherwise poll a drained channel forever.
        TcpTransport implements this; ShmTransport relies on its
        EOWNERDEAD remap to resurrect the peer instead, so a probe there
        keeps returning ``False`` while recovery is in progress.
        """

    @abc.abstractmethod
    def test(self, handle: Handle) -> bool:
        """Advance progress; True when the transfer has completed."""

    @abc.abstractmethod
    def cancel(self, handle: Handle) -> None:
        """Abort an in-flight transfer, releasing buffer ownership
        (the reference's shutdown path, init.lua:50-58)."""

    def payload(self, handle: Handle) -> Any:
        """The received data (the ``out`` buffer if one was given)."""
        if not handle.done:
            raise RuntimeError("payload requested before completion")
        return handle.out if handle.out is not None else handle.payload

    def close(self) -> None:  # pragma: no cover - backends override
        pass

    # -- blocking conveniences (cold paths: init, tests) --------------------
    def send(self, data: Any, dst: int, tag: int) -> None:
        handle = self.isend(data, dst, tag)
        while not self.test(handle):
            pass

    def recv(self, src: int, tag: int, out: Any | None = None) -> Any:
        handle = self.irecv(src, tag, out=out)
        while not self.test(handle):
            pass
        return self.payload(handle)
