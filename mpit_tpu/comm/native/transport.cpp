// mt_transport — native shared-memory message transport for mpit_tpu.
//
// The role the reference fills with its Lua<->MPI C binding (reference
// mpiT.c, lua-mpi.h, mpifuncs.c): a nonblocking, (rank, tag)-addressed,
// zero-copy-into-caller-buffers transport driven by poll-style Test calls,
// here for same-host role processes (the `mpirun -np N` single-host shape
// the reference is exercised in, reference README.md:28-31).  Cross-host
// paths ride XLA collectives over ICI/DCN and are not this file's job.
//
// Design (deliberately not an MPI clone):
//  * One POSIX shm ring buffer per rank (its inbox).  Senders append
//    variable-size chunks under a process-shared mutex; only the owner
//    drains.  Chunking bounds ring residency so messages larger than the
//    ring (the reference ships 640 MB parameter vectors, ptest.lua:3)
//    stream through a small ring without deadlock.
//  * Message assembly, (rank, tag) matching, and handle state live in
//    process-local memory — the ring is purely a mailbox, so a receiver
//    polling one tag never head-of-line-blocks other tags.
//  * Per-destination FIFO send queues give MPI-style non-overtaking order
//    between any (src, dst) pair.
//  * All progress happens inside mt_iprobe/mt_test calls from the caller's
//    cooperative scheduler — single-threaded per process, like the
//    reference's coroutine polling (reference init.lua:147-185).
//
// Exported C API (ctypes bindings are generated from specs/*.json by
// gen_bindings.py, mirroring the reference's readspec.py codegen).

#include <atomic>
#include <cerrno>
#include <cmath>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <deque>
#include <map>
#include <memory>
#include <mutex>
#include <string>
#include <thread>
#include <utility>
#include <vector>

#include <fcntl.h>
#include <pthread.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <time.h>
#include <unistd.h>

namespace {

constexpr uint64_t kReadyMagic = 0x4d50495454505531ull;  // "MPITTPU1"
constexpr uint64_t kMaxChunk = 1ull << 22;               // 4 MB

struct RingHeader {
  std::atomic<uint64_t> ready;  // kReadyMagic once initialized
  pthread_mutex_t mutex;        // process-shared
  uint64_t capacity;            // data-area bytes
  uint64_t head;                // absolute bytes written (mod capacity)
  uint64_t tail;                // absolute bytes consumed
};

struct ChunkHeader {
  int32_t src;
  int32_t tag;
  uint64_t msg_id;      // per-sender sequence, for reassembly
  uint32_t chunk_idx;
  uint32_t nchunks;
  uint64_t chunk_bytes;
  uint64_t total_bytes;
};

struct Ring {
  RingHeader* hdr = nullptr;
  uint8_t* data = nullptr;
  size_t map_bytes = 0;
};

// Message payload storage: a plain heap buffer, deliberately NOT a
// std::vector — vector's value-initialization would memset every byte
// before the ring copy overwrites it, a whole extra DRAM sweep at the
// 640 MB ptest scale.  Big buffers are recycled through Ctx::buf_cache
// so the steady-state hot path stops paying mmap+page-fault churn for
// every multi-hundred-MB message.
struct Buffer {
  std::unique_ptr<uint8_t[]> data;
  uint64_t len = 0;  // message bytes (<= cap)
  uint64_t cap = 0;  // allocation size
};

struct Message {
  Buffer buf;
};

struct Partial {
  uint64_t total = 0;
  uint64_t filled = 0;  // bytes assembled so far (chunks arrive in order)
  uint32_t seen = 0;
  int32_t tag = 0;
  Buffer buf;
};

struct SendOp {
  int dst = -1;
  int tag = 0;
  const uint8_t* data = nullptr;
  uint64_t len = 0;
  uint64_t written = 0;  // payload bytes already placed in the ring
  uint64_t msg_id = 0;
  uint32_t nchunks = 0;
  uint32_t next_chunk = 0;
  bool done = false;
  bool cancelled = false;
  uint32_t stalls = 0;  // consecutive zero-progress pump attempts
};

// After this many consecutive zero-progress attempts on a full peer ring,
// suspect a stale mapping (peer crashed and recreated its segment) and
// remap.  Normal backpressure resets the counter on any progress.
constexpr uint32_t kStallRemapThreshold = 4096;

struct RecvOp {
  int src = -1;
  int tag = 0;
  uint8_t* out = nullptr;
  uint64_t cap = 0;
  uint64_t size = 0;
  bool done = false;
  bool cancelled = false;
  bool size_mismatch = false;
};

struct Ctx {
  std::string ns;
  int rank = -1;
  int nranks = 0;
  uint64_t ring_bytes = 0;
  Ring own;
  std::vector<Ring> peers;  // lazily opened inboxes of other ranks
  std::map<std::pair<int, int>, std::deque<Message>> ready;      // (src,tag)
  std::map<std::pair<int, uint64_t>, Partial> partial;           // (src,msg_id)
  std::map<int64_t, SendOp> sends;
  std::map<int64_t, RecvOp> recvs;
  std::map<int, std::deque<int64_t>> send_q;  // per-destination FIFO
  std::vector<Buffer> buf_cache;  // recycled big message buffers
  int64_t next_handle = 1;
  uint64_t next_msg_id = 1;
  std::string last_error;
};

// Only buffers this big are worth recycling (below it, allocator churn is
// cheap and caching would let one huge cached buffer serve tiny acks).
constexpr uint64_t kBufCacheMin = 1ull << 20;
constexpr size_t kBufCacheSlots = 8;

Buffer alloc_buffer(Ctx* ctx, uint64_t n) {
  Buffer buf;
  if (n >= kBufCacheMin) {
    size_t best = SIZE_MAX;
    for (size_t i = 0; i < ctx->buf_cache.size(); ++i) {
      uint64_t cap = ctx->buf_cache[i].cap;
      if (cap >= n && (best == SIZE_MAX || cap < ctx->buf_cache[best].cap)) {
        best = i;
      }
    }
    if (best != SIZE_MAX) {
      buf = std::move(ctx->buf_cache[best]);
      ctx->buf_cache.erase(ctx->buf_cache.begin() + (ptrdiff_t)best);
      buf.len = n;
      return buf;
    }
  }
  buf.data.reset(n > 0 ? new uint8_t[n] : nullptr);  // uninitialized
  buf.cap = n;
  buf.len = n;
  return buf;
}

void recycle_buffer(Ctx* ctx, Buffer&& buf) {
  if (buf.cap >= kBufCacheMin && ctx->buf_cache.size() < kBufCacheSlots) {
    ctx->buf_cache.push_back(std::move(buf));
  }
}

std::string shm_name(const std::string& ns, int rank) {
  return "/mt_" + ns + "_r" + std::to_string(rank);
}

bool map_ring(const std::string& name, uint64_t ring_bytes, bool create,
              Ring* out, std::string* err) {
  int flags = create ? (O_CREAT | O_RDWR) : O_RDWR;
  int fd = shm_open(name.c_str(), flags, 0600);
  if (fd < 0) {
    if (err) *err = "shm_open " + name + ": " + std::strerror(errno);
    return false;
  }
  size_t total = sizeof(RingHeader) + ring_bytes;
  if (create && ftruncate(fd, (off_t)total) != 0) {
    if (err) *err = "ftruncate " + name + ": " + std::strerror(errno);
    close(fd);
    return false;
  }
  if (!create) {
    // The creator sizes the segment; wait for a nonzero size.
    struct stat st;
    if (fstat(fd, &st) != 0 || (size_t)st.st_size < sizeof(RingHeader)) {
      close(fd);
      if (err) *err = "peer segment not sized yet";
      return false;
    }
    total = (size_t)st.st_size;
  }
  void* mem = mmap(nullptr, total, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  close(fd);
  if (mem == MAP_FAILED) {
    if (err) *err = "mmap " + name + ": " + std::strerror(errno);
    return false;
  }
  out->hdr = reinterpret_cast<RingHeader*>(mem);
  out->data = reinterpret_cast<uint8_t*>(mem) + sizeof(RingHeader);
  out->map_bytes = total;
  return true;
}

void circ_write(Ring& ring, uint64_t pos, const void* src, uint64_t n) {
  uint64_t cap = ring.hdr->capacity;
  uint64_t off = pos % cap;
  uint64_t first = (off + n <= cap) ? n : cap - off;
  std::memcpy(ring.data + off, src, first);
  if (first < n) {
    std::memcpy(ring.data, reinterpret_cast<const uint8_t*>(src) + first,
                n - first);
  }
}

void circ_read(Ring& ring, uint64_t pos, void* dst, uint64_t n) {
  uint64_t cap = ring.hdr->capacity;
  uint64_t off = pos % cap;
  uint64_t first = (off + n <= cap) ? n : cap - off;
  std::memcpy(dst, ring.data + off, first);
  if (first < n) {
    std::memcpy(reinterpret_cast<uint8_t*>(dst) + first, ring.data, n - first);
  }
}

Ring* peer_ring(Ctx* ctx, int dst) {
  if (dst < 0 || dst >= ctx->nranks) return nullptr;
  Ring& ring = ctx->peers[dst];
  if (ring.hdr == nullptr) {
    std::string err;
    if (!map_ring(shm_name(ctx->ns, dst), ctx->ring_bytes, /*create=*/false,
                  &ring, &err)) {
      return nullptr;  // peer not up yet; caller retries on next progress
    }
  }
  if (ring.hdr->ready.load(std::memory_order_acquire) != kReadyMagic) {
    return nullptr;
  }
  return &ring;
}

void unmap_peer(Ctx* ctx, int dst) {
  Ring& ring = ctx->peers[dst];
  if (ring.hdr != nullptr) {
    munmap(ring.hdr, ring.map_bytes);
    ring = Ring{};
  }
}

// Robust lock: if the previous holder died mid-critical-section, take
// ownership, mark the mutex consistent, and reset the ring indices (the
// in-flight bytes are garbage after a crash; post-crash message loss is the
// accepted semantic — the PS protocol's acks surface it to the caller).
void lock_ring(RingHeader* hdr) {
  int rc = pthread_mutex_lock(&hdr->mutex);
  if (rc == EOWNERDEAD) {
    hdr->head = 0;
    hdr->tail = 0;
    pthread_mutex_consistent(&hdr->mutex);
  }
}

// Drain the own inbox: move complete chunks into partial/ready maps.
// Payload bytes go straight from the ring into their final message
// buffer — one copy, into uninitialized storage (the old vector path
// value-initialized every byte and copied multi-chunk payloads twice).
void drain_inbox(Ctx* ctx) {
  Ring& ring = ctx->own;
  lock_ring(ring.hdr);
  uint64_t head = ring.hdr->head;
  uint64_t tail = ring.hdr->tail;
  while (tail < head) {
    ChunkHeader ch;
    circ_read(ring, tail, &ch, sizeof(ch));
    tail += sizeof(ch);
    if (ch.chunk_bytes == ch.total_bytes) {  // complete in one chunk
      Buffer buf = alloc_buffer(ctx, ch.total_bytes);
      if (ch.chunk_bytes > 0) circ_read(ring, tail, buf.data.get(), ch.chunk_bytes);
      ctx->ready[{ch.src, ch.tag}].push_back(Message{std::move(buf)});
    } else {
      auto key = std::make_pair(ch.src, ch.msg_id);
      Partial& part = ctx->partial[key];
      if (part.seen == 0) {
        part.total = ch.total_bytes;
        part.tag = ch.tag;
        part.buf = alloc_buffer(ctx, ch.total_bytes);
      }
      uint64_t n = ch.chunk_bytes;  // clamp defensively; completion is byte-based
      if (part.filled + n > part.total) n = part.total - part.filled;
      if (n > 0) circ_read(ring, tail, part.buf.data.get() + part.filled, n);
      part.filled += ch.chunk_bytes;
      part.seen++;
      if (part.filled >= part.total) {
        ctx->ready[{ch.src, part.tag}].push_back(Message{std::move(part.buf)});
        ctx->partial.erase(key);
      }
    }
    tail += ch.chunk_bytes;
  }
  ring.hdr->tail = tail;
  pthread_mutex_unlock(&ring.hdr->mutex);
}

// Try to place more chunks of the front send op for each destination.
void pump_sends(Ctx* ctx) {
  for (auto& [dst, queue] : ctx->send_q) {
    while (!queue.empty()) {
      int64_t handle = queue.front();
      auto it = ctx->sends.find(handle);
      if (it == ctx->sends.end() || it->second.cancelled || it->second.done) {
        queue.pop_front();
        continue;
      }
      SendOp& op = it->second;
      Ring* ring = peer_ring(ctx, dst);
      if (ring == nullptr) break;  // destination not up yet
      // A chunk must fit in the destination ring with its header; cap at
      // half the ring so two senders can interleave without livelock.
      uint64_t ring_cap = ring->hdr->capacity;
      uint64_t fit_max = ring_cap > 2 * sizeof(ChunkHeader)
                             ? (ring_cap - 2 * sizeof(ChunkHeader)) / 2
                             : 1;
      uint64_t max_chunk = kMaxChunk < fit_max ? kMaxChunk : fit_max;
      bool progressed = true;
      while (!op.done && progressed) {
        progressed = false;
        uint64_t remaining = op.len - op.written;
        uint64_t chunk = remaining < max_chunk ? remaining : max_chunk;
        uint64_t need = sizeof(ChunkHeader) + chunk;
        lock_ring(ring->hdr);
        uint64_t used = ring->hdr->head - ring->hdr->tail;
        uint64_t free_bytes = ring->hdr->capacity - used;
        if (free_bytes >= need) {
          ChunkHeader ch;
          ch.src = ctx->rank;
          ch.tag = op.tag;
          ch.msg_id = op.msg_id;
          ch.chunk_idx = op.next_chunk;
          ch.nchunks = 0;  // informational; completion is byte-based
          ch.chunk_bytes = chunk;
          ch.total_bytes = op.len;
          circ_write(*ring, ring->hdr->head, &ch, sizeof(ch));
          if (chunk > 0) {
            circ_write(*ring, ring->hdr->head + sizeof(ch), op.data + op.written,
                       chunk);
          }
          ring->hdr->head += need;
          op.written += chunk;
          op.next_chunk++;
          op.stalls = 0;
          progressed = true;
          if (op.written >= op.len) op.done = true;
        }
        pthread_mutex_unlock(&ring->hdr->mutex);
      }
      if (!op.done) {
        // Zero progress with a full ring: count stalls; past the threshold
        // assume a stale mapping (peer recreated its segment) and remap.
        if (++op.stalls >= kStallRemapThreshold) {
          op.stalls = 0;
          unmap_peer(ctx, dst);
        }
        break;  // keep FIFO order, stop for this dst
      }
      queue.pop_front();
    }
  }
}

void progress(Ctx* ctx) {
  drain_inbox(ctx);
  pump_sends(ctx);
}

}  // namespace

extern "C" {

void* mt_init(const char* ns, int rank, int nranks, uint64_t ring_bytes) {
  auto* ctx = new Ctx();
  ctx->ns = ns;
  ctx->rank = rank;
  ctx->nranks = nranks;
  ctx->ring_bytes = ring_bytes;
  ctx->peers.resize(nranks);
  std::string name = shm_name(ctx->ns, rank);
  shm_unlink(name.c_str());  // clear any stale segment from a crashed run
  std::string err;
  if (!map_ring(name, ring_bytes, /*create=*/true, &ctx->own, &err)) {
    std::fprintf(stderr, "mt_init: %s\n", err.c_str());
    delete ctx;
    return nullptr;
  }
  pthread_mutexattr_t attr;
  pthread_mutexattr_init(&attr);
  pthread_mutexattr_setpshared(&attr, PTHREAD_PROCESS_SHARED);
  pthread_mutexattr_setrobust(&attr, PTHREAD_MUTEX_ROBUST);
  pthread_mutex_init(&ctx->own.hdr->mutex, &attr);
  pthread_mutexattr_destroy(&attr);
  ctx->own.hdr->capacity = ring_bytes;
  ctx->own.hdr->head = 0;
  ctx->own.hdr->tail = 0;
  ctx->own.hdr->ready.store(kReadyMagic, std::memory_order_release);
  return ctx;
}

void mt_finalize(void* vctx) {
  auto* ctx = static_cast<Ctx*>(vctx);
  if (ctx == nullptr) return;
  if (ctx->own.hdr != nullptr) {
    munmap(ctx->own.hdr, ctx->own.map_bytes);
    shm_unlink(shm_name(ctx->ns, ctx->rank).c_str());
  }
  for (Ring& ring : ctx->peers) {
    if (ring.hdr != nullptr) munmap(ring.hdr, ring.map_bytes);
  }
  delete ctx;
}

int mt_rank(void* vctx) { return static_cast<Ctx*>(vctx)->rank; }
int mt_nranks(void* vctx) { return static_cast<Ctx*>(vctx)->nranks; }

int64_t mt_isend(void* vctx, int dst, int tag, const void* data, uint64_t len) {
  auto* ctx = static_cast<Ctx*>(vctx);
  if (dst < 0 || dst >= ctx->nranks) return -1;
  SendOp op;
  op.dst = dst;
  op.tag = tag;
  op.data = static_cast<const uint8_t*>(data);
  op.len = len;
  op.msg_id = ctx->next_msg_id++;
  int64_t handle = ctx->next_handle++;
  ctx->sends[handle] = op;
  ctx->send_q[dst].push_back(handle);
  progress(ctx);
  return handle;
}

int64_t mt_irecv(void* vctx, int src, int tag, void* out, uint64_t cap) {
  auto* ctx = static_cast<Ctx*>(vctx);
  if (src < 0 || src >= ctx->nranks) return -1;
  RecvOp op;
  op.src = src;
  op.tag = tag;
  op.out = static_cast<uint8_t*>(out);
  op.cap = cap;
  int64_t handle = ctx->next_handle++;
  ctx->recvs[handle] = op;
  return handle;
}

int mt_iprobe(void* vctx, int src, int tag) {
  auto* ctx = static_cast<Ctx*>(vctx);
  progress(ctx);
  auto it = ctx->ready.find({src, tag});
  return (it != ctx->ready.end() && !it->second.empty()) ? 1 : 0;
}

int64_t mt_probe_size(void* vctx, int src, int tag) {
  auto* ctx = static_cast<Ctx*>(vctx);
  progress(ctx);
  auto it = ctx->ready.find({src, tag});
  if (it == ctx->ready.end() || it->second.empty()) return -1;
  return (int64_t)it->second.front().buf.len;
}

// Returns 1 complete, 0 pending, -1 unknown handle, -2 size mismatch.
int mt_test(void* vctx, int64_t handle) {
  auto* ctx = static_cast<Ctx*>(vctx);
  progress(ctx);
  auto sit = ctx->sends.find(handle);
  if (sit != ctx->sends.end()) {
    if (sit->second.cancelled) return -1;
    if (sit->second.done) {
      ctx->sends.erase(sit);
      return 1;
    }
    return 0;
  }
  auto rit = ctx->recvs.find(handle);
  if (rit != ctx->recvs.end()) {
    RecvOp& op = rit->second;
    if (op.cancelled) return -1;
    if (op.done) return 1;
    auto box = ctx->ready.find({op.src, op.tag});
    if (box == ctx->ready.end() || box->second.empty()) return 0;
    Message& msg = box->second.front();
    if (msg.buf.len != op.cap) {
      op.size_mismatch = true;
      op.size = msg.buf.len;
      return -2;
    }
    if (op.cap > 0) std::memcpy(op.out, msg.buf.data.get(), op.cap);
    op.size = msg.buf.len;
    op.done = true;
    Buffer freed = std::move(msg.buf);
    box->second.pop_front();
    recycle_buffer(ctx, std::move(freed));
    return 1;
  }
  return -1;
}

int64_t mt_recv_size(void* vctx, int64_t handle) {
  auto* ctx = static_cast<Ctx*>(vctx);
  auto rit = ctx->recvs.find(handle);
  if (rit == ctx->recvs.end()) return -1;
  return (int64_t)rit->second.size;
}

void mt_cancel(void* vctx, int64_t handle) {
  auto* ctx = static_cast<Ctx*>(vctx);
  auto sit = ctx->sends.find(handle);
  if (sit != ctx->sends.end()) {
    // Chunks already in the peer ring stay (the receiver discards partial
    // messages at finalize); the op stops producing more.
    sit->second.cancelled = true;
    ctx->sends.erase(sit);
    return;
  }
  auto rit = ctx->recvs.find(handle);
  if (rit != ctx->recvs.end()) ctx->recvs.erase(rit);
}

void mt_release(void* vctx, int64_t handle) {
  auto* ctx = static_cast<Ctx*>(vctx);
  ctx->recvs.erase(handle);
  ctx->sends.erase(handle);
}

// Monotonic wall clock in seconds (the MPI_Wtime analog,
// reference mpifuncs.c:2500-2513).
double mt_time(void) {
  struct timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return (double)ts.tv_sec + 1e-9 * (double)ts.tv_nsec;
}

// -- wire-codec kernels (mpit_tpu/comm/codec.py hot paths) -------------------
//
// Single-translation-unit home for the codec inner loops: the numpy
// reference implementations in codec.py make ~8 full passes per tile
// (measured 0.66 s to int8-encode 640 MB with residual on the 1-core
// bench host), and on a host where the encoder competes with the wire
// for the same core that cost lands 1:1 on PS throughput.  These loops
// do the same math in 2 passes per 1024-element block (absmax, then
// quantize+residual) with block-cache-resident reads, ctypes releases
// the GIL for the duration, and codec.py keeps the numpy path as the
// fallback (and as the parity oracle in tests/test_codec.py).
//
// Float semantics match numpy exactly: scale = absmax/127 (1.0 for
// all-zero blocks), code = rintf(w * (1/scale)) (round-half-to-even,
// same as np.rint), residual = w - code*scale evaluated without fp
// contraction (build.py passes -ffp-contract=off) so native and numpy
// frames are bit-identical.

constexpr uint64_t kCodecBlock = 1024;  // == codec.BLOCK

void mt_codec_int8_encode(const void* vx, void* vresidual, uint64_t n,
                          void* vscales, void* vcodes) {
  const float* x = static_cast<const float*>(vx);
  float* r = static_cast<float*>(vresidual);  // nullable (param path)
  float* scales = static_cast<float*>(vscales);
  int8_t* codes = static_cast<int8_t*>(vcodes);
  uint64_t nb = (n + kCodecBlock - 1) / kCodecBlock;
  for (uint64_t b = 0; b < nb; ++b) {
    uint64_t lo = b * kCodecBlock;
    uint64_t hi = lo + kCodecBlock < n ? lo + kCodecBlock : n;
    float absmax = 0.0f;
    if (r != nullptr) {
      for (uint64_t i = lo; i < hi; ++i) {
        float w = x[i] + r[i];
        float a = fabsf(w);
        if (a > absmax) absmax = a;
      }
    } else {
      for (uint64_t i = lo; i < hi; ++i) {
        float a = fabsf(x[i]);
        if (a > absmax) absmax = a;
      }
    }
    float scale = absmax == 0.0f ? 1.0f : absmax / 127.0f;
    float inv = 1.0f / scale;
    scales[b] = scale;
    if (r != nullptr) {
      for (uint64_t i = lo; i < hi; ++i) {
        float w = x[i] + r[i];
        float q = rintf(w * inv);
        codes[i] = (int8_t)q;
        r[i] = w - q * scale;
      }
    } else {
      for (uint64_t i = lo; i < hi; ++i) {
        codes[i] = (int8_t)rintf(x[i] * inv);
      }
    }
  }
}

void mt_codec_int8_decode(const void* vscales, const void* vcodes, uint64_t n,
                          void* vout) {
  const float* scales = static_cast<const float*>(vscales);
  const int8_t* codes = static_cast<const int8_t*>(vcodes);
  float* out = static_cast<float*>(vout);
  uint64_t nb = (n + kCodecBlock - 1) / kCodecBlock;
  for (uint64_t b = 0; b < nb; ++b) {
    uint64_t lo = b * kCodecBlock;
    uint64_t hi = lo + kCodecBlock < n ? lo + kCodecBlock : n;
    float scale = scales[b];
    for (uint64_t i = lo; i < hi; ++i) {
      out[i] = (float)codes[i] * scale;
    }
  }
}

void mt_codec_bf16_encode(const void* vx, uint64_t n, void* vwire) {
  // Truncation: the high half-word of each little-endian fp32.
  const uint16_t* src = static_cast<const uint16_t*>(vx);
  uint16_t* dst = static_cast<uint16_t*>(vwire);
  for (uint64_t i = 0; i < n; ++i) {
    dst[i] = src[2 * i + 1];
  }
}

void mt_codec_bf16_decode(const void* vwire, uint64_t n, void* vout) {
  const uint16_t* src = static_cast<const uint16_t*>(vwire);
  uint32_t* dst = static_cast<uint32_t*>(vout);
  for (uint64_t i = 0; i < n; ++i) {
    dst[i] = (uint32_t)src[i] << 16;
  }
}

// -- data-plane kernels for the worker pool ----------------------------------
//
// Byte-wise XOR delta (cells FrameHistory DELTA production and apply) and
// the fused f32 add-fold (agg interior-node per-chunk fold).  Both are
// single-pass replacements for multi-pass numpy pipelines; both must stay
// bit-identical to the numpy reference (tests/test_pool.py parity suite):
// XOR trivially is, and the fold keeps numpy's association order
// ((own[i] + c0[i]) + c1[i]) + ... element-wise with -ffp-contract=off,
// so no FMA ever merges an add pair the serial path keeps separate.

void mt_xor_bytes(const void* va, const void* vb, void* vout, int64_t n) {
  const uint8_t* a = static_cast<const uint8_t*>(va);
  const uint8_t* b = static_cast<const uint8_t*>(vb);
  uint8_t* out = static_cast<uint8_t*>(vout);
  int64_t i = 0;
  for (; i + 8 <= n; i += 8) {
    uint64_t x, y;
    memcpy(&x, a + i, 8);
    memcpy(&y, b + i, 8);
    x ^= y;
    memcpy(out + i, &x, 8);
  }
  for (; i < n; ++i) out[i] = (uint8_t)(a[i] ^ b[i]);
}

// vptrs: uint64_t[nchildren] raw child-buffer addresses, each f32[n].
// The serial agg fold does copyto(acc, own) then one `acc += child` pass
// per child — nchildren+1 DRAM round trips over the chunk.  This fuses
// them into one read pass over every operand and one write pass, keeping
// the exact per-element association order of the serial loop.
void mt_fold_f32(const void* vown, const void* vptrs, int32_t nchildren,
                 void* vout, int64_t n) {
  const float* own = static_cast<const float*>(vown);
  const uint64_t* ptrs = static_cast<const uint64_t*>(vptrs);
  float* out = static_cast<float*>(vout);
  for (int64_t i = 0; i < n; ++i) {
    float acc = own[i];
    for (int32_t c = 0; c < nchildren; ++c) {
      acc += reinterpret_cast<const float*>((uintptr_t)ptrs[c])[i];
    }
    out[i] = acc;
  }
}

// Bumped whenever specs/*.json and this file change together; the
// generated _bindings.py refuses a stale .so (loud rebuild message)
// instead of failing with a confusing missing-symbol AttributeError.
// Keep in sync with MT_API_VERSION in gen_bindings.py.
int64_t mt_api_version(void) { return 17001; }

}  // extern "C"

// -- worker-pool data plane --------------------------------------------------
//
// A persistent native thread pool so chunk encode/decode/XOR/fold runs off
// the Python critical thread (the GIL cap recorded by BENCH_r15/r16).  Jobs
// are pure: owned input pointers -> owned output pointers, all regions
// disjoint per job, per-block int8 EF state (the residual slice) carried in
// the job.  Completion order therefore never influences byte content; the
// Python seam (mpit_tpu/comm/pool.py) collects results in submission order.

namespace {

enum PoolJobKind {
  kJobInt8Enc = 1,
  kJobInt8Dec = 2,
  kJobBf16Enc = 3,
  kJobBf16Dec = 4,
  kJobXor = 5,
  kJobFoldF32 = 6,
  kJobCopy = 7,
};
constexpr int32_t kJobKinds = 8;  // valid kinds are 1..kJobKinds-1

struct PoolJob {
  uint64_t handle = 0;
  int32_t kind = 0;
  const void* a = nullptr;  // primary input
  const void* b = nullptr;  // secondary input (residual / xor rhs / ptrs)
  void* c = nullptr;        // primary output
  void* d = nullptr;        // secondary output (int8 codes)
  int64_t n = 0;
  int64_t aux = 0;                // fold: nchildren
  std::vector<uint64_t> ptrs;     // fold: owned copy of child addresses
};

struct Pool {
  std::mutex mu;
  std::condition_variable cv_work;  // workers: queue non-empty or closing
  std::condition_variable cv_done;  // waiters: a job completed
  std::deque<PoolJob> queue;
  std::map<uint64_t, int> state;  // handle -> 0 pending, 1 done
  std::vector<std::thread> threads;
  uint64_t next_handle = 1;
  bool closing = false;
  int64_t running = 0;
  uint64_t jobs_by_kind[kJobKinds] = {0};
  std::atomic<uint64_t> busy_ns{0};
};

void pool_run(const PoolJob& job) {
  switch (job.kind) {
    case kJobInt8Enc:
      mt_codec_int8_encode(job.a, const_cast<void*>(job.b), (uint64_t)job.n,
                           job.c, job.d);
      break;
    case kJobInt8Dec:
      mt_codec_int8_decode(job.a, job.b, (uint64_t)job.n, job.c);
      break;
    case kJobBf16Enc:
      mt_codec_bf16_encode(job.a, (uint64_t)job.n, job.c);
      break;
    case kJobBf16Dec:
      mt_codec_bf16_decode(job.a, (uint64_t)job.n, job.c);
      break;
    case kJobXor:
      mt_xor_bytes(job.a, job.b, job.c, job.n);
      break;
    case kJobFoldF32:
      mt_fold_f32(job.a, job.ptrs.data(), (int32_t)job.aux, job.c, job.n);
      break;
    case kJobCopy:
      memcpy(job.c, job.a, (size_t)job.n);
      break;
    default:
      break;
  }
}

void pool_worker(Pool* pool) {
  for (;;) {
    PoolJob job;
    {
      std::unique_lock<std::mutex> lk(pool->mu);
      pool->cv_work.wait(
          lk, [pool] { return pool->closing || !pool->queue.empty(); });
      if (pool->queue.empty()) return;  // closing and fully drained
      job = std::move(pool->queue.front());
      pool->queue.pop_front();
      pool->running++;
    }
    struct timespec t0, t1;
    clock_gettime(CLOCK_MONOTONIC, &t0);
    pool_run(job);
    clock_gettime(CLOCK_MONOTONIC, &t1);
    uint64_t ns = (uint64_t)(t1.tv_sec - t0.tv_sec) * 1000000000ull +
                  (uint64_t)(t1.tv_nsec - t0.tv_nsec);
    {
      std::lock_guard<std::mutex> lk(pool->mu);
      pool->running--;
      pool->state[job.handle] = 1;
      pool->jobs_by_kind[job.kind]++;
      pool->busy_ns.fetch_add(ns, std::memory_order_relaxed);
    }
    pool->cv_done.notify_all();
  }
}

}  // namespace

extern "C" {

// Spawn a pool with nthreads workers; NULL when nthreads <= 0 (callers
// treat that as "stay serial").  Pools are instance-scoped like mt_init
// contexts so tests can run several geometries side by side.
void* mt_pool_start(int32_t nthreads) {
  if (nthreads <= 0) return nullptr;
  Pool* pool = new Pool();
  pool->threads.reserve((size_t)nthreads);
  for (int32_t i = 0; i < nthreads; ++i) {
    pool->threads.emplace_back(pool_worker, pool);
  }
  return pool;
}

// Drain every queued job, join all workers, free the pool.  Submitting to
// a closed pool is the caller's error (the Python seam raises before it
// can reach a freed pointer).
void mt_pool_close(void* vpool) {
  auto* pool = static_cast<Pool*>(vpool);
  if (pool == nullptr) return;
  {
    std::lock_guard<std::mutex> lk(pool->mu);
    pool->closing = true;
  }
  pool->cv_work.notify_all();
  for (auto& t : pool->threads) t.join();
  delete pool;
}

int32_t mt_pool_threads(void* vpool) {
  auto* pool = static_cast<Pool*>(vpool);
  return pool == nullptr ? 0 : (int32_t)pool->threads.size();
}

// Enqueue one pure job; returns a handle (> 0), or 0 when the pool is
// closing or the job is malformed.  Operand meaning by kind:
//   INT8_ENC  a=x f32[n], b=residual f32[n]|NULL, c=scales, d=codes
//   INT8_DEC  a=scales, b=codes, c=out f32[n]
//   BF16_ENC  a=x f32[n], c=wire u16[n]      BF16_DEC a=wire, c=out
//   XOR       a, b, c = out, n bytes
//   FOLD_F32  a=own f32[n], b=u64[aux] child addresses (copied), c=out
//   COPY      a=src, c=dst, n bytes
// Buffers must stay alive until the job completes (zero-copy rule; the
// Python Job object holds the references).
uint64_t mt_pool_submit(void* vpool, int32_t kind, const void* a,
                        const void* b, void* c, void* d, int64_t n,
                        int64_t aux) {
  auto* pool = static_cast<Pool*>(vpool);
  if (pool == nullptr || kind <= 0 || kind >= kJobKinds || n < 0) return 0;
  PoolJob job;
  job.kind = kind;
  job.a = a;
  job.b = b;
  job.c = c;
  job.d = d;
  job.n = n;
  job.aux = aux;
  if (kind == kJobFoldF32) {
    if (b == nullptr || aux < 0) return 0;
    const uint64_t* ptrs = static_cast<const uint64_t*>(b);
    job.ptrs.assign(ptrs, ptrs + aux);  // owned copy: caller may free b
  }
  uint64_t handle;
  {
    std::lock_guard<std::mutex> lk(pool->mu);
    if (pool->closing) return 0;
    handle = pool->next_handle++;
    job.handle = handle;
    pool->state[handle] = 0;
    pool->queue.push_back(std::move(job));
  }
  pool->cv_work.notify_one();
  return handle;
}

// 1 done (handle retired), 0 pending, -1 unknown.
int32_t mt_pool_poll(void* vpool, uint64_t handle) {
  auto* pool = static_cast<Pool*>(vpool);
  if (pool == nullptr) return -1;
  std::lock_guard<std::mutex> lk(pool->mu);
  auto it = pool->state.find(handle);
  if (it == pool->state.end()) return -1;
  if (it->second == 0) return 0;
  pool->state.erase(it);
  return 1;
}

// Block until the job completes (ctypes drops the GIL for the duration);
// 0 ok (handle retired), -1 unknown.
int32_t mt_pool_wait(void* vpool, uint64_t handle) {
  auto* pool = static_cast<Pool*>(vpool);
  if (pool == nullptr) return -1;
  std::unique_lock<std::mutex> lk(pool->mu);
  auto it = pool->state.find(handle);
  if (it == pool->state.end()) return -1;
  pool->cv_done.wait(lk, [pool, handle] {
    auto jt = pool->state.find(handle);
    return jt == pool->state.end() || jt->second == 1;
  });
  pool->state.erase(handle);
  return 0;
}

// Jobs submitted but not yet finished (queued + running).
int64_t mt_pool_depth(void* vpool) {
  auto* pool = static_cast<Pool*>(vpool);
  if (pool == nullptr) return 0;
  std::lock_guard<std::mutex> lk(pool->mu);
  return (int64_t)pool->queue.size() + pool->running;
}

// Completed-job count for one kind, or the total when kind == 0.
uint64_t mt_pool_jobs(void* vpool, int32_t kind) {
  auto* pool = static_cast<Pool*>(vpool);
  if (pool == nullptr || kind < 0 || kind >= kJobKinds) return 0;
  std::lock_guard<std::mutex> lk(pool->mu);
  if (kind != 0) return pool->jobs_by_kind[kind];
  uint64_t total = 0;
  for (int32_t k = 1; k < kJobKinds; ++k) total += pool->jobs_by_kind[k];
  return total;
}

// Cumulative worker seconds spent inside kernels.
double mt_pool_busy_seconds(void* vpool) {
  auto* pool = static_cast<Pool*>(vpool);
  if (pool == nullptr) return 0.0;
  return 1e-9 * (double)pool->busy_ns.load(std::memory_order_relaxed);
}

}  // extern "C"
