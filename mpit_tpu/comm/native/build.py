"""Build the native transport shared library (the CMakeLists analog,
reference CMakeLists.txt:25-29 — one translation unit, one artifact).

Compiled lazily on first use and cached by source mtime; force with
``python -m mpit_tpu.comm.native.build``.
"""

from __future__ import annotations

import pathlib
import subprocess
import threading

HERE = pathlib.Path(__file__).resolve().parent
SRC = HERE / "transport.cpp"
LIB = HERE / "libmt_transport.so"

_lock = threading.Lock()

CXXFLAGS = ["-std=c++17", "-O2", "-fPIC", "-shared", "-pthread", "-Wall"]


def ensure_built(force: bool = False) -> pathlib.Path:
    with _lock:
        if not force and LIB.exists() and LIB.stat().st_mtime >= SRC.stat().st_mtime:
            return LIB
        cmd = ["g++", *CXXFLAGS, str(SRC), "-o", str(LIB), "-lrt"]
        proc = subprocess.run(cmd, capture_output=True, text=True)
        if proc.returncode != 0:
            raise RuntimeError(
                f"native transport build failed:\n$ {' '.join(cmd)}\n{proc.stderr}"
            )
        return LIB


def main() -> None:
    path = ensure_built(force=True)
    print(f"built {path}")


if __name__ == "__main__":
    main()
