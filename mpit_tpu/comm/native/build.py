"""Build the native transport shared library (the CMakeLists analog,
reference CMakeLists.txt:25-29 — one translation unit, one artifact).

Compiled lazily on first use and cached by source mtime; force with
``python -m mpit_tpu.comm.native.build``.
"""

from __future__ import annotations

import pathlib
import subprocess
import threading

HERE = pathlib.Path(__file__).resolve().parent
SRC = HERE / "transport.cpp"
LIB = HERE / "libmt_transport.so"

_lock = threading.Lock()

# -O3 for the auto-vectorizer (GCC<12 does not vectorize at -O2; the codec
# kernels need it), -march=native because the library is built lazily on
# the host that runs it (baseline x86-64 is SSE2, which has no vector
# rounding insn — the int8 quantize loop needs SSE4.1+ vroundps),
# -fno-math-errno so rintf lowers to that insn, and -ffp-contract=off so
# the codec's float results stay bit-identical to the numpy reference
# implementations (tests/test_codec.py parity oracle).
CXXFLAGS = ["-std=c++17", "-O3", "-march=native", "-fPIC", "-shared",
            "-pthread", "-Wall", "-fno-math-errno", "-ffp-contract=off"]


def ensure_built(force: bool = False) -> pathlib.Path:
    with _lock:
        if not force and LIB.exists() and LIB.stat().st_mtime >= SRC.stat().st_mtime:
            return LIB
        cmd = ["g++", *CXXFLAGS, str(SRC), "-o", str(LIB), "-lrt"]
        proc = subprocess.run(cmd, capture_output=True, text=True)
        if proc.returncode != 0:
            raise RuntimeError(
                f"native transport build failed:\n$ {' '.join(cmd)}\n{proc.stderr}"
            )
        return LIB


def main() -> None:
    path = ensure_built(force=True)
    print(f"built {path}")


if __name__ == "__main__":
    main()
