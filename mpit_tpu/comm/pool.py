"""Worker-pool submission seam for the chunk data plane.

The GIL cap recorded by BENCH_r15/r16: every per-chunk encode, decode,
XOR delta and tree fold ran serially on the one Python thread, so chunk
k's CPU work could never overlap chunk k+1's wire time.  This module is
the narrow seam between the protocol code and the native worker pool in
``comm/native/transport.cpp`` (mt_pool_*): call sites submit pure kernel
jobs and collect them in submission order; the pool runs them GIL-free
on persistent native threads.

Determinism is the design center, not an afterthought:

* **Jobs are pure.**  A job reads only the buffers captured at submit
  time and writes only its own disjoint output region; per-block int8
  error-feedback state (the residual slice) is carried in the job.  The
  caller guarantees input buffers are quiescent until the job is
  collected — buffers that are mutated across the submit window must be
  snapshotted through an owning constructor first (machine-checked at
  the declared seams: ``OwnedPath``/``OwnedSink`` rows named
  ``pool-*`` in mpit_tpu/analysis/disciplines.py).
* **Completion order never influences bytes.**  Outputs are disjoint
  and call sites collect jobs in submission order, so any interleaving
  of worker threads produces the identical frame.  Pooled-vs-serial
  bitwise equality is asserted per kernel x codec x chunk geometry x
  thread count by tests/test_pool.py.
* **Serial is the same bytes, not a different path.**  With
  ``MPIT_POOL_THREADS=0`` (or no compiled library) every submit runs
  the kernel inline through the exact code the call site used before
  the pool existed, and returns an already-completed job.

Blocking discipline: :meth:`Job.result` blocks the calling thread (the
native wait drops the GIL but not the cooperative scheduler), so it must
never be reachable while holding a lock or inside a declared no-yield
window — that is lint rule MT-C204 (mpit_tpu/analysis/concurrency.py).
Scheduler-driven code polls :meth:`Job.done` between ``yield EXEC``
turns instead; atomic sections use the ``*_sync`` entry points, which
never queue.

Env: ``MPIT_POOL_THREADS`` — worker count; default ``min(4, cores-1)``,
``0`` = serial fallback.
"""

from __future__ import annotations

import os
import threading
from typing import Optional, Sequence

import numpy as np

from mpit_tpu.comm import codec as codec_mod
from mpit_tpu.obs import metrics as _obs

ENV_THREADS = "MPIT_POOL_THREADS"

#: job kinds — must match the PoolJobKind enum in transport.cpp.
KIND_INT8_ENC = 1
KIND_INT8_DEC = 2
KIND_BF16_ENC = 3
KIND_BF16_DEC = 4
KIND_XOR = 5
KIND_FOLD_F32 = 6
KIND_COPY = 7

#: metric label per kind (mpit_pool_jobs_total{kind}).
KIND_NAMES = {
    KIND_INT8_ENC: "int8_enc",
    KIND_INT8_DEC: "int8_dec",
    KIND_BF16_ENC: "bf16_enc",
    KIND_BF16_DEC: "bf16_dec",
    KIND_XOR: "xor",
    KIND_FOLD_F32: "fold_f32",
    KIND_COPY: "copy",
}


def default_threads() -> int:
    """``min(4, cores-1)`` — zero on a 1-core host, i.e. serial."""
    return min(4, max(0, (os.cpu_count() or 1) - 1))


def configured_threads() -> int:
    raw = os.environ.get(ENV_THREADS, "")
    if raw == "":
        return default_threads()
    try:
        return max(0, int(raw))
    except ValueError:
        return default_threads()


class PoolClosedError(RuntimeError):
    """Submit after close() — queued work would be silently lost."""


class Job:
    """Future for one submitted kernel (or a span group of COPY jobs).

    Holds references to every buffer the native job touches until the
    job is collected — the zero-copy rule of ``_as_pointer``: the pool
    reads the caller's storage directly, so the Job keeps it alive.
    """

    __slots__ = ("_pool", "_handles", "_refs")

    def __init__(self, pool: Optional["WorkerPool"],
                 handles: Sequence[int], refs: tuple):
        self._pool = pool
        self._handles = list(handles)
        self._refs = refs

    def done(self) -> bool:
        """Nonblocking completion probe (scheduler-friendly: poll this
        between ``yield EXEC`` turns)."""
        if self._pool is None:
            return True
        remaining = []
        for h in self._handles:
            if self._pool._poll(h) == 0:
                remaining.append(h)
        self._handles = remaining
        if not remaining:
            self._retire()
            return True
        return False

    def result(self) -> None:
        """Block until the job completes.  The native wait drops the
        GIL but stalls this thread — never call it while holding a lock
        or inside a declared no-yield window (lint rule MT-C204); those
        contexts poll :meth:`done` or use the ``*_sync`` entries."""
        if self._pool is None:
            return
        for h in self._handles:
            self._pool._wait(h)
        self._handles = []
        self._retire()

    def _retire(self) -> None:
        self._pool = None
        self._refs = ()


#: completed-at-submit job (serial fallback, empty span groups).
def _done_job() -> Job:
    return Job(None, (), ())


class WorkerPool:
    """One native worker pool plus the serial fallback that replaces it
    byte-for-byte when ``threads == 0`` or the library is absent."""

    def __init__(self, threads: Optional[int] = None):
        self.requested = configured_threads() if threads is None else threads
        self._lib = None
        self._pool = None
        self._mu = threading.Lock()
        self._closed = False
        self._busy_sampled = 0.0
        if self.requested > 0:
            lib = _load_native()
            if lib is not None:
                self._lib = lib
                self._pool = lib.mt_pool_start(self.requested)

    @property
    def serial(self) -> bool:
        """True when submits run inline (no native threads)."""
        return self._pool is None

    @property
    def threads(self) -> int:
        if self._pool is None:
            return 0
        return int(self._lib.mt_pool_threads(self._pool))

    # -- submission -----------------------------------------------------------

    def submit_encode(self, codec, x: np.ndarray, wire: np.ndarray,
                      residual: Optional[np.ndarray] = None) -> Job:
        """Encode f32 ``x`` into the chunk frame ``wire`` off-thread.
        The int8 residual slice rides in the job (error-feedback state is
        per-block, and chunks are BLOCK-aligned, so chunk jobs stay
        independent)."""
        self._check_open()
        if self._pool is None:
            self.encode_sync(codec, x, wire, residual)
            return _done_job()
        n = int(x.size)
        if codec.identity:
            h = self._submit(KIND_COPY, x, None, wire[: 4 * n], None, 4 * n, 0)
        elif codec.name == "bf16":
            h = self._submit(KIND_BF16_ENC, x, None, wire, None, n, 0)
        elif codec.name == "int8":
            scales, codes = codec._views(wire, n)
            h = self._submit(KIND_INT8_ENC, x, residual, scales, codes, n, 0)
        else:
            self.encode_sync(codec, x, wire, residual)
            return _done_job()
        return Job(self, (h,), (x, wire, residual))

    def submit_decode(self, codec, wire: np.ndarray, out: np.ndarray) -> Job:
        """Decode a chunk frame into the f32 ``out`` slice off-thread."""
        self._check_open()
        if self._pool is None:
            self.decode_sync(codec, wire, out)
            return _done_job()
        n = int(out.size)
        if codec.identity:
            h = self._submit(KIND_COPY, wire[: 4 * n], None,
                             out.view(np.uint8), None, 4 * n, 0)
        elif codec.name == "bf16":
            h = self._submit(KIND_BF16_DEC, wire, None, out, None, n, 0)
        elif codec.name == "int8":
            scales, codes = codec._views(wire, n)
            h = self._submit(KIND_INT8_DEC, scales, codes, out, None, n, 0)
        else:
            self.decode_sync(codec, wire, out)
            return _done_job()
        return Job(self, (h,), (wire, out))

    def submit_copy(self, src: np.ndarray, dst: np.ndarray) -> Job:
        """Byte copy ``dst[:] = src`` off-thread (identity-codec chunk
        staging)."""
        self._check_open()
        if self._pool is None:
            dst[:] = src
            return _done_job()
        h = self._submit(KIND_COPY, src, None, dst, None, int(src.nbytes), 0)
        return Job(self, (h,), (src, dst))

    def submit_xor(self, a: np.ndarray, b: np.ndarray,
                   out: np.ndarray) -> Job:
        """``out = a ^ b`` byte-wise (cells DELTA production/apply)."""
        self._check_open()
        if self._pool is None:
            self.xor_sync(a, b, out)
            return _done_job()
        h = self._submit(KIND_XOR, a, b, out, None, int(a.nbytes), 0)
        return Job(self, (h,), (a, b, out))

    def submit_fold_f32(self, own: np.ndarray,
                        children: Sequence[np.ndarray],
                        out: np.ndarray) -> Job:
        """Fused ``out = own + sum(children)`` in declared child order
        (the agg fold; association order is the bitwise anchor)."""
        self._check_open()
        if self._pool is None:
            self.fold_f32_sync(own, children, out)
            return _done_job()
        ptrs = _child_ptrs(children)
        h = self._submit(KIND_FOLD_F32, own, ptrs, out, None,
                         int(own.size), len(children))
        # ptrs itself is copied inside mt_pool_submit; the child buffers
        # are not — the Job pins them.
        return Job(self, (h,), (own, tuple(children), out))

    def submit_gather(self, codec, full: np.ndarray, size: int, lo: int,
                      hi: int, chunk: np.ndarray, itemsize: int = 4) -> Job:
        """Cut the ``[lo, hi)`` chunk frame out of a full-shard frame
        (PARAM serve path) as one COPY job per region span."""
        self._check_open()
        if self._pool is None:
            codec_mod.gather_chunk(codec, full, size, lo, hi, chunk,
                                   itemsize=itemsize)
            return _done_job()
        handles = [
            self._submit(KIND_COPY, full[full_off:full_off + nbytes], None,
                         chunk[chunk_off:chunk_off + nbytes], None, nbytes, 0)
            for full_off, chunk_off, nbytes
            in codec_mod._chunk_copy_spans(codec, size, lo, hi, itemsize)]
        return Job(self, handles, (full, chunk))

    def submit_scatter(self, codec, full: np.ndarray, size: int, lo: int,
                       hi: int, chunk: np.ndarray, itemsize: int = 4) -> Job:
        """Scatter a chunk frame into a full-shard staging frame
        (PARAM_PUSH assembly path)."""
        self._check_open()
        if self._pool is None:
            codec_mod.scatter_chunk(codec, full, size, lo, hi, chunk,
                                    itemsize=itemsize)
            return _done_job()
        handles = [
            self._submit(KIND_COPY, chunk[chunk_off:chunk_off + nbytes], None,
                         full[full_off:full_off + nbytes], None, nbytes, 0)
            for full_off, chunk_off, nbytes
            in codec_mod._chunk_copy_spans(codec, size, lo, hi, itemsize)]
        return Job(self, handles, (full, chunk))

    # -- synchronous entries (atomic sections / no-yield windows) -------------
    #
    # These never queue: declared atomic sections (cell-install-atomic,
    # ps-read-path-helpers) may not block on a pool condvar, so inside
    # them the kernels run inline on the calling thread.

    def encode_sync(self, codec, x, wire, residual=None) -> None:
        codec.encode_into(x, wire, residual=residual)

    def decode_sync(self, codec, wire, out) -> None:
        codec.decode_into(wire, out)

    def xor_sync(self, a: np.ndarray, b: np.ndarray,
                 out: np.ndarray) -> None:
        lib = self._lib if self._lib is not None else _load_native()
        if lib is not None:
            lib.mt_xor_bytes(a, b, out, int(a.nbytes))
        else:
            np.bitwise_xor(a, b, out=out)

    def fold_f32_sync(self, own: np.ndarray,
                      children: Sequence[np.ndarray],
                      out: np.ndarray) -> None:
        """Single-pass fused fold when native is available; the numpy
        fallback keeps the identical association order (copyto then one
        ``+=`` per child, sorted caller-side), so both are bit-equal."""
        lib = self._lib if self._lib is not None else _load_native()
        if lib is not None and children:
            lib.mt_fold_f32(own, _child_ptrs(children), len(children),
                            out, int(own.size))
            return
        np.copyto(out, own)
        for child in children:
            out += child

    # -- lifecycle / introspection -------------------------------------------

    def close(self) -> None:
        """Drain every queued job, join the workers.  Idempotent; any
        submit afterwards raises :class:`PoolClosedError` loudly."""
        with self._mu:
            pool, self._pool = self._pool, None
            self._closed = True
        if pool is not None:
            self._sample_busy(pool)
            self._lib.mt_pool_close(pool)

    def depth(self) -> int:
        if self._pool is None:
            return 0
        return int(self._lib.mt_pool_depth(self._pool))

    def jobs_total(self, kind: int = 0) -> int:
        if self._pool is None:
            return 0
        return int(self._lib.mt_pool_jobs(self._pool, kind))

    def busy_seconds(self) -> float:
        if self._pool is None:
            return 0.0
        return float(self._lib.mt_pool_busy_seconds(self._pool))

    def status(self) -> dict:
        """/status section + ``mpit top`` source (obs/statusd.py)."""
        return {
            "threads": self.threads,
            "serial": self.serial,
            "depth": self.depth(),
            "jobs_total": self.jobs_total(),
            "busy_seconds": round(self.busy_seconds(), 6),
        }

    # -- internals ------------------------------------------------------------

    def _check_open(self) -> None:
        if self._closed:
            raise PoolClosedError(
                "worker pool is closed; submit would lose the job")

    def _submit(self, kind: int, a, b, c, d, n: int, aux: int) -> int:
        with self._mu:
            if self._closed or self._pool is None:
                raise PoolClosedError(
                    "worker pool is closed; submit would lose the job")
            handle = int(self._lib.mt_pool_submit(
                self._pool, kind, a, b, c, d, n, aux))
        if handle <= 0:
            raise PoolClosedError(
                f"native pool rejected job kind={kind} n={n}")
        reg = _obs.get_registry()
        if reg.enabled:
            reg.counter("mpit_pool_jobs_total",
                        kind=KIND_NAMES[kind]).inc()
            reg.gauge("mpit_pool_queue_depth").set(self.depth())
        return handle

    def _poll(self, handle: int) -> int:
        if self._pool is None:
            return 1
        return int(self._lib.mt_pool_poll(self._pool, handle))

    def _wait(self, handle: int) -> None:
        if self._pool is None:
            return
        self._lib.mt_pool_wait(self._pool, handle)

    def _sample_busy(self, pool=None) -> None:
        """Fold the cumulative native busy clock into the counter as a
        delta (counters are monotonic; the native side is the truth)."""
        pool = pool if pool is not None else self._pool
        if pool is None or self._lib is None:
            return
        reg = _obs.get_registry()
        if not reg.enabled:
            return
        now = float(self._lib.mt_pool_busy_seconds(pool))
        delta = now - self._busy_sampled
        if delta > 0:
            reg.counter("mpit_pool_busy_seconds").inc(delta)
            self._busy_sampled = now

    def sample_obs(self) -> None:
        """Refresh the pool gauges (called by the /status provider and
        the bench loop; cheap no-op when obs is disabled)."""
        reg = _obs.get_registry()
        if not reg.enabled:
            return
        reg.gauge("mpit_pool_threads").set(self.threads)
        reg.gauge("mpit_pool_queue_depth").set(self.depth())
        self._sample_busy()


def _child_ptrs(children: Sequence[np.ndarray]) -> np.ndarray:
    """Owned u64 address array for a fold's child buffers, in caller
    (i.e. fold) order.  The native submit copies it again into the job,
    so its lifetime only needs to span the submit call."""
    return np.array([c.ctypes.data for c in children], dtype=np.uint64)


_native_lib: Optional[object] = None  # None: untried; False: unavailable


def _load_native():
    """Shared native library, or None (no compiler / big-endian /
    disabled): the pool then stays serial and tier-1 stays green.  A
    stale .so fails the bindings' version-stamp check loudly; that
    message is surfaced once via the module logger, never swallowed."""
    global _native_lib
    if _native_lib is None:
        if os.environ.get(codec_mod._NATIVE_ENV, "1") == "0" \
                or not codec_mod._LITTLE:
            _native_lib = False
        else:
            try:
                from mpit_tpu.comm.native import build
                from mpit_tpu.comm.native._bindings import NativeTransportLib

                _native_lib = NativeTransportLib(build.ensure_built())
            except RuntimeError as exc:  # version-stamp mismatch: loud
                from mpit_tpu.utils.logging import get_logger

                get_logger("pool").warning(
                    "native library unavailable (serial fallback): %s", exc)
                _native_lib = False
            except Exception:  # no g++ / unwritable tree: quiet fallback
                _native_lib = False
    return _native_lib or None


_GLOBAL: Optional[WorkerPool] = None
_GLOBAL_MU = threading.Lock()


def get_pool() -> WorkerPool:
    """Process-wide pool, built once from ``MPIT_POOL_THREADS``."""
    global _GLOBAL
    with _GLOBAL_MU:
        if _GLOBAL is None:
            _GLOBAL = WorkerPool()
            _register_status(_GLOBAL)
        return _GLOBAL


def current_pool() -> Optional[WorkerPool]:
    """The process-wide pool *if one exists* — the observe-only
    accessor the obs samplers use (obs/profile.py): a profiler reading
    utilization must never be the thing that spins worker threads up."""
    return _GLOBAL


def configure(threads: Optional[int]) -> WorkerPool:
    """Replace the process-wide pool (tests, bench A/B legs).  Closes
    the previous one so its workers never leak across configurations."""
    global _GLOBAL
    with _GLOBAL_MU:
        old, _GLOBAL = _GLOBAL, None
    if old is not None:
        old.close()
    with _GLOBAL_MU:
        _GLOBAL = WorkerPool(threads)
        _register_status(_GLOBAL)
        return _GLOBAL


def _register_status(pool: WorkerPool) -> None:
    try:
        from mpit_tpu.obs import statusd

        def _section():
            pool.sample_obs()
            return pool.status()

        statusd.register_provider("pool", _section)
    except Exception:  # obs wiring must never break the data plane
        pass
