"""ShmTransport — the native C++ shared-memory transport, Python side.

Implements the :class:`mpit_tpu.comm.transport.Transport` contract over
libmt_transport.so (mpit_tpu/comm/native/transport.cpp) via the generated
ctypes bindings.  This is the host transport for same-host multi-process
role topologies — the deployment shape the reference exercises with
``mpirun -np N`` on one machine (reference README.md:28-31,57-61), with
the asynchronous one-sided PS semantics XLA collectives can't express
(SURVEY.md section 7 "hard parts").

Zero-copy discipline: sends pass the numpy buffer's raw pointer to C and
the Handle holds the array reference until completion; receives land
directly in the caller's buffer.  Completed native handles are freed
test-once style (like MPI requests); the Python Handle caches completion
so repeated ``test`` stays idempotent.
"""

from __future__ import annotations

import atexit
import functools
import os
from typing import Any, Optional

import numpy as np

from mpit_tpu.comm.transport import Handle, Transport
from mpit_tpu.obs import metrics as _obs


@functools.lru_cache(maxsize=1)
def _load_lib():
    from mpit_tpu.comm.native import build
    from mpit_tpu.comm.native._bindings import NativeTransportLib

    return NativeTransportLib(build.ensure_built())


class ShmTransport(Transport):
    def __init__(
        self,
        namespace: str,
        rank: int,
        nranks: int,
        ring_bytes: int = 64 << 20,
    ):
        self.lib = _load_lib()
        self.rank = rank
        self.nranks = nranks
        self.namespace = namespace
        self._ctx = self.lib.mt_init(namespace, rank, nranks, ring_bytes)
        if not self._ctx:
            raise RuntimeError(
                f"mt_init failed for namespace={namespace!r} rank={rank}"
            )
        self._closed = False
        # Per-peer traffic counters (mpit_tpu.obs): rank-indexed lists,
        # null singletons when obs is disabled (no-op on the hot path).
        _reg = _obs.get_registry()
        self._m_tx_msgs = [_reg.counter("mpit_shm_tx_messages_total",
                                        rank=rank, peer=r)
                           for r in range(nranks)]
        self._m_tx_bytes = [_reg.counter("mpit_shm_tx_bytes_total",
                                         rank=rank, peer=r)
                            for r in range(nranks)]
        self._m_rx_msgs = [_reg.counter("mpit_shm_rx_messages_total",
                                        rank=rank, peer=r)
                           for r in range(nranks)]
        self._m_rx_bytes = [_reg.counter("mpit_shm_rx_bytes_total",
                                         rank=rank, peer=r)
                            for r in range(nranks)]
        atexit.register(self.close)

    # -- Transport ----------------------------------------------------------

    def isend(self, data: Any, dst: int, tag: int) -> Handle:
        buf = self._sendable(data)
        nbytes = buf.nbytes if isinstance(buf, np.ndarray) else len(buf)
        native = self.lib.mt_isend(self._ctx, dst, tag, buf, nbytes)
        if native < 0:
            raise ValueError(f"isend to invalid rank {dst}")
        self._m_tx_msgs[dst].inc()
        self._m_tx_bytes[dst].inc(nbytes)
        return Handle(kind="send", peer=dst, tag=tag, buf=buf, native_id=native)

    def irecv(self, src: int, tag: int, out: Any | None = None) -> Handle:
        if out is None:
            size = self.lib.mt_probe_size(self._ctx, src, tag)
            if size < 0:
                raise RuntimeError(
                    "irecv without a buffer requires a probed message "
                    "(call iprobe first — the reference does the same, "
                    "init.lua:67-102)"
                )
            out_arr = np.empty(int(size), dtype=np.uint8)
            handle = self._post_recv(src, tag, out_arr)
            handle.meta["as_bytes"] = True
            return handle
        return self._post_recv(src, tag, out)

    def _post_recv(self, src: int, tag: int, out: Any) -> Handle:
        if isinstance(out, np.ndarray):
            if not out.flags["WRITEABLE"]:
                raise ValueError("recv buffer must be writable")
            nbytes = out.nbytes
        else:
            view = memoryview(out)
            if view.readonly:
                raise ValueError("recv buffer must be writable")
            nbytes = view.nbytes
        native = self.lib.mt_irecv(self._ctx, src, tag, out, nbytes)
        if native < 0:
            raise ValueError(f"irecv from invalid rank {src}")
        return Handle(kind="recv", peer=src, tag=tag, out=out, native_id=native)

    def iprobe(self, src: int, tag: int) -> bool:
        return bool(self.lib.mt_iprobe(self._ctx, src, tag))

    def test(self, handle: Handle) -> bool:
        if handle.done or handle.cancelled:
            return handle.done
        code = self.lib.mt_test(self._ctx, handle.native_id)
        if code == 0:
            return False
        if code == 1:
            handle.done = True
            if handle.kind == "recv" and handle.meta.get("as_bytes"):
                handle.payload = handle.out.tobytes()
                handle.out = None
            if handle.kind == "recv":
                out = handle.out if handle.out is not None else handle.payload
                self._m_rx_msgs[handle.peer].inc()
                self._m_rx_bytes[handle.peer].inc(
                    int(getattr(out, "nbytes", None) or len(out or b"")))
            if handle.kind == "send":
                handle.buf = None  # release ownership back to the caller
            self.lib.mt_release(self._ctx, handle.native_id)
            return True
        if code == -2:
            size = self.lib.mt_recv_size(self._ctx, handle.native_id)
            # Terminal: release the native op and poison the handle so the
            # error raises exactly once and nothing leaks.
            self.lib.mt_cancel(self._ctx, handle.native_id)
            handle.cancelled = True
            raise ValueError(
                f"recv size mismatch: message {size}B does not fit buffer "
                f"(src={handle.peer}, tag={handle.tag})"
            )
        handle.cancelled = True
        raise RuntimeError(f"native test error {code} on {handle}")

    def cancel(self, handle: Handle) -> None:
        if not handle.done:
            self.lib.mt_cancel(self._ctx, handle.native_id)
        handle.cancelled = True
        handle.buf = None

    def close(self) -> None:
        if not self._closed and self._ctx:
            self.lib.mt_finalize(self._ctx)
            self._closed = True

    # -- helpers ------------------------------------------------------------

    @staticmethod
    def _sendable(data: Any):
        """Keepalive-friendly buffer form: ndarray stays as-is (raw pointer
        + held reference), everything else becomes bytes.  Non-contiguous
        arrays are rejected rather than silently copied — same fail-loud
        zero-copy rule as :func:`mpit_tpu.comm.transport.as_bytes_view`."""
        if data is None:
            return b""
        if isinstance(data, np.ndarray):
            if not data.flags["C_CONTIGUOUS"]:
                raise ValueError(
                    "send buffer must be C-contiguous (zero-copy rule: a "
                    "hidden copy would break buffer-liveness semantics)"
                )
            return data
        if isinstance(data, (bytes, bytearray)):
            return bytes(data)
        if isinstance(data, memoryview):
            return data.tobytes()
        return np.ascontiguousarray(np.asarray(data))

    @staticmethod
    def wtime() -> float:
        return _load_lib().mt_time()
