"""L0 — transports: typed nonblocking message passing between role processes.

The reference's L0 is a generated Lua<->MPI C binding exposing the MPI-2
surface with zero-copy access to tensor storages (reference mpiT.c,
lua-mpi.h:70-78, mpifuncs.c, readspec.py).  Messages are addressed by
``(rank, tag)`` and driven through nonblocking Isend/Irecv/Iprobe/Test/
Cancel (reference init.lua:40-102).

Here the same contract — nonblocking, (rank, tag)-addressed, zero-copy into
caller buffers, cancellable — is provided by three backends:

- :class:`mpit_tpu.comm.local.LocalTransport`: in-process mailboxes for
  tests and single-process multi-role runs (the claunch analog).
- :class:`mpit_tpu.comm.shm.ShmTransport`: the native C++ shared-memory
  ring transport (mpit_tpu/comm/native/) for same-host multi-process runs —
  the analog of how the reference is actually exercised (``mpirun -np N``
  on one host, reference README.md:28-31); ctypes bindings are generated
  from JSON specs, mirroring the reference's readspec.py codegen.
- :class:`mpit_tpu.comm.tcp.TcpTransport`: cross-host sockets with the
  identical contract — the DCN-side transport for the reference's
  multi-node hostfile deployments (reference BiCNN/hostfiles).

On top of any of the three, :class:`mpit_tpu.comm.collectives.
HostCollectives` provides the host-side collectives the reference's rank
processes get from MPI — allreduce/bcast/reduce/barrier plus the
Iallreduce analog (reference mpifuncs.c:83,:145,:1357) — for role-process
coordination with no accelerator in the loop.  (Device collectives ride
XLA over ICI instead: :mod:`mpit_tpu.parallel.collective`.)
"""

from mpit_tpu.comm import codec
from mpit_tpu.comm.transport import Handle, Transport
from mpit_tpu.comm.local import LocalRouter, LocalTransport
from mpit_tpu.comm.tcp import TcpTransport, allocate_local_addresses
from mpit_tpu.comm.collectives import HostCollectives

__all__ = [
    "Transport", "Handle", "LocalRouter", "LocalTransport",
    "TcpTransport", "allocate_local_addresses", "HostCollectives",
    "codec",
]
