"""Host-side collectives over the Transport contract.

The reference's rank processes call MPI collectives directly — Allreduce
(reference mpifuncs.c:83), Bcast (reference mpifuncs.c:145), Iallreduce
(reference mpifuncs.c:1357) — and its test suite times them
(reference test/testreduceall.lua:31-33, test/testireduceall.lua:32-39).
In this framework the *device* collective path rides XLA over ICI
(:mod:`mpit_tpu.parallel.collective`); this module is the deliberate
host-side twin for the traffic XLA cannot express: role processes
(servers, clients, testers) coordinating over the shm/tcp/in-process
transports with no accelerator in the loop.

Algorithms are the standard topology-aware ones, built purely from the
nonblocking Transport primitives (isend/irecv/test):

- :meth:`HostCollectives.allreduce` — ring reduce-scatter + all-gather
  for payloads that dwarf the rank count (bandwidth-optimal: each rank
  moves ``2*(n-1)/n`` of the buffer), binomial reduce + bcast below that;
- :meth:`HostCollectives.bcast` — binomial tree, ``ceil(log2 n)`` rounds;
- :meth:`HostCollectives.reduce` — binomial tree onto ``root``;
- :meth:`HostCollectives.barrier` — dissemination barrier, 0-byte
  messages, ``ceil(log2 n)`` rounds;
- :meth:`HostCollectives.allreduce_async` — the Iallreduce analog: the
  same ring on a worker thread, returning a handle with test/wait.

All array ops are in-place on C-contiguous numpy arrays (the transports'
zero-copy rule).  Tags live in a reserved range far above the PS wire
tags (:mod:`mpit_tpu.ps.tags`), with a per-call round counter so
back-to-back collectives never cross-talk.
"""

from __future__ import annotations

import threading
import time
from typing import Optional

import numpy as np

_OPS = {
    "sum": lambda acc, other: np.add(acc, other, out=acc),
    "max": lambda acc, other: np.maximum(acc, other, out=acc),
    "min": lambda acc, other: np.minimum(acc, other, out=acc),
}

_TAG_BASE = 1 << 16
_STEPS_PER_ROUND = 1024  # ring needs 2*(n-1) tags -> caps n at 512 ranks
_ROUND_SPAN = 2048


class HostCollectives:
    """Collective operations over every rank of one transport."""

    def __init__(self, transport, tag_base: int = _TAG_BASE):
        self.t = transport
        self.rank = transport.rank
        self.n = transport.nranks
        self._tag_base = tag_base
        self._round = 0
        self._round_lock = threading.Lock()
        if self.n > _STEPS_PER_ROUND // 2:
            raise ValueError(f"HostCollectives supports up to 512 ranks, got {self.n}")

    # -- plumbing ------------------------------------------------------------

    def _tags(self):
        """A fresh tag namespace for one collective call.  Locked: an
        ``allreduce_async`` runs on a worker thread and may overlap other
        collectives on this instance — each in-flight call must own a
        distinct tag block or ranks would fold each other's chunks."""
        with self._round_lock:
            rnd = self._round
            self._round += 1
        base = self._tag_base + (rnd % _ROUND_SPAN) * _STEPS_PER_ROUND
        return lambda step: base + step

    def _drive(self, *handles):
        """Poll handles to completion, interleaved: transports may drive
        send progress from the sender's ``test`` (the local mailbox
        transport does), so blocking on a recv before polling the send
        would deadlock a ring where everyone sends then receives.  Backs
        off to short sleeps so ranks parked in a startup barrier don't
        monopolize cores the straggler they wait for needs."""
        pending = list(handles)
        spins = 0
        while pending:
            pending = [h for h in pending if not self.t.test(h)]
            spins += 1
            if pending and spins > 256:
                time.sleep(0.0005)

    def _send(self, buf, dst, tag):
        self._drive(self.t.isend(buf, dst, tag))

    def _recv(self, buf, src, tag):
        self._drive(self.t.irecv(src, tag, out=buf))

    def _sendrecv(self, sbuf, dst, rbuf, src, tag_s, tag_r):
        """Concurrent blocking send+recv (see :meth:`_drive`)."""
        self._drive(
            self.t.isend(sbuf, dst, tag_s), self.t.irecv(src, tag_r, out=rbuf)
        )

    @staticmethod
    def _flat(arr: np.ndarray) -> np.ndarray:
        if not isinstance(arr, np.ndarray) or not arr.flags["C_CONTIGUOUS"]:
            raise ValueError("host collectives need C-contiguous numpy arrays")
        return arr.reshape(-1)

    # -- collectives ---------------------------------------------------------

    def barrier(self) -> None:
        """Dissemination barrier: after round t every rank has heard
        (transitively) from 2^(t+1) predecessors; log2(n) rounds total."""
        if self.n == 1:
            return
        tag = self._tags()
        step = 1
        t_ = 0
        # Explicit 0-byte recv target: the shm transport's bufferless
        # irecv requires a prior iprobe, which a rendezvous can't do.
        zero = np.empty(0, np.uint8)
        while step < self.n:
            dst = (self.rank + step) % self.n
            src = (self.rank - step) % self.n
            self._sendrecv(zero, dst, zero, src, tag(t_), tag(t_))
            step <<= 1
            t_ += 1

    def bcast(self, arr: np.ndarray, root: int = 0) -> np.ndarray:
        """Binomial-tree broadcast, in place (reference mpifuncs.c:145)."""
        flat = self._flat(arr)
        if self.n == 1:
            return arr
        tag = self._tags()
        vr = (self.rank - root) % self.n
        nrounds = (self.n - 1).bit_length()
        for t_ in range(nrounds):
            span = 1 << t_
            if vr < span:
                if vr + span < self.n:
                    self._send(flat, (self.rank + span) % self.n, tag(t_))
            elif vr < span << 1:
                self._recv(flat, (self.rank - span) % self.n, tag(t_))
        return arr

    def reduce(self, arr: np.ndarray, op: str = "sum", root: int = 0) -> np.ndarray:
        """Binomial-tree reduction onto ``root``, in place there (other
        ranks' buffers are scratch afterwards)."""
        fold = _OPS[op]
        flat = self._flat(arr)
        if self.n == 1:
            return arr
        tag = self._tags()
        vr = (self.rank - root) % self.n
        tmp = np.empty_like(flat)
        nrounds = (self.n - 1).bit_length()
        for t_ in range(nrounds):
            span = 1 << t_
            if vr & span:
                self._send(flat, (self.rank - span) % self.n, tag(t_))
                break  # contributed: done
            if vr + span < self.n:
                self._recv(tmp, (self.rank + span) % self.n, tag(t_))
                fold(flat, tmp)
        return arr

    def allreduce(self, arr: np.ndarray, op: str = "sum") -> np.ndarray:
        """In-place allreduce (reference mpifuncs.c:83).

        Ring reduce-scatter + all-gather when the payload is large enough
        for per-rank chunks to amortize message overhead; binomial
        reduce + bcast otherwise (latency-optimal for small payloads).
        """
        flat = self._flat(arr)
        if self.n == 1:
            return arr
        if flat.size < self.n * 64:
            self.reduce(arr, op=op, root=0)
            return self.bcast(arr, root=0)
        fold = _OPS[op]
        tag = self._tags()
        n, r = self.n, self.rank
        right = (r + 1) % n
        left = (r - 1) % n
        bounds = [0] + list(np.cumsum([len(c) for c in np.array_split(flat, n)]))
        chunk = lambda i: flat[bounds[i % n]:bounds[i % n + 1]]
        tmp = np.empty(max(bounds[i + 1] - bounds[i] for i in range(n)), flat.dtype)

        # Reduce-scatter: after n-1 steps rank r owns the full sum of
        # chunk (r+1) mod n.
        for s in range(n - 1):
            sc, rc = (r - s) % n, (r - s - 1) % n
            rbuf = tmp[: bounds[rc + 1] - bounds[rc]]
            self._sendrecv(chunk(sc), right, rbuf, left, tag(s), tag(s))
            fold(chunk(rc), rbuf)
        # All-gather: circulate the owned chunks.
        for s in range(n - 1):
            sc, rc = (r + 1 - s) % n, (r - s) % n
            self._sendrecv(
                chunk(sc), right, chunk(rc), left, tag(n - 1 + s), tag(n - 1 + s)
            )
        return arr

    def allgather(self, send: np.ndarray, recv: np.ndarray) -> np.ndarray:
        """Equal-block allgather (reference mpifuncs.c:47): every rank's
        ``send`` block lands in ``recv`` at block offset == its rank.
        Ring circulation, ``n-1`` neighbor steps (bandwidth-optimal)."""
        sflat, rflat = self._flat(send), self._flat(recv)
        if rflat.size != sflat.size * self.n:
            raise ValueError(
                f"allgather recv must hold n*send ({self.n}x{sflat.size}), "
                f"got {rflat.size}"
            )
        block = lambda i: rflat[(i % self.n) * sflat.size:
                                (i % self.n + 1) * sflat.size]
        np.copyto(block(self.rank), sflat)
        if self.n == 1:
            return recv
        tag = self._tags()
        right, left = (self.rank + 1) % self.n, (self.rank - 1) % self.n
        for s in range(self.n - 1):
            self._sendrecv(block(self.rank - s), right,
                           block(self.rank - s - 1), left, tag(s), tag(s))
        return recv

    def reduce_scatter(self, arr: np.ndarray, out: np.ndarray,
                       op: str = "sum") -> np.ndarray:
        """Equal-block reduce-scatter (reference mpifuncs.c:1716,
        Reduce_scatter_block semantics): ``arr`` is n equal blocks; rank r
        receives the elementwise reduction of every rank's block r in
        ``out``.  The ring reduce-scatter phase of :meth:`allreduce`;
        ``arr`` is scratch afterwards."""
        fold = _OPS[op]
        flat, oflat = self._flat(arr), self._flat(out)
        if flat.size != oflat.size * self.n:
            raise ValueError(
                f"reduce_scatter arr must be n*out ({self.n}x{oflat.size}), "
                f"got {flat.size}"
            )
        if self.n == 1:
            np.copyto(oflat, flat)
            return out
        tag = self._tags()
        n, r = self.n, self.rank
        right, left = (r + 1) % n, (r - 1) % n
        size = oflat.size
        chunk = lambda i: flat[(i % n) * size:(i % n + 1) * size]
        tmp = np.empty(size, flat.dtype)
        # After n-1 steps rank r holds the full sum of chunk (r+1) mod n
        # (same schedule as allreduce); one extra neighbor hop rehomes it
        # so rank r's out is chunk r, the MPI contract.
        for s in range(n - 1):
            sc, rc = (r - s) % n, (r - s - 1) % n
            self._sendrecv(chunk(sc), right, tmp, left, tag(s), tag(s))
            fold(chunk(rc), tmp)
        self._sendrecv(chunk(r + 1), right, oflat, left,
                       tag(n - 1), tag(n - 1))
        return out

    def scatter(self, arr: Optional[np.ndarray], out: np.ndarray,
                root: int = 0) -> np.ndarray:
        """Equal-block scatter from ``root`` (reference mpifuncs.c:1792):
        block i of root's ``arr`` lands in rank i's ``out``."""
        oflat = self._flat(out)
        tag = self._tags()
        if self.rank == root:
            flat = self._flat(arr)
            if flat.size != oflat.size * self.n:
                raise ValueError(
                    f"scatter arr must be n*out ({self.n}x{oflat.size}), "
                    f"got {flat.size}"
                )
            size = oflat.size
            handles = [
                self.t.isend(flat[i * size:(i + 1) * size], i, tag(0))
                for i in range(self.n) if i != root
            ]
            np.copyto(oflat, flat[root * size:(root + 1) * size])
            self._drive(*handles)
        else:
            self._recv(oflat, root, tag(0))
        return out

    def gather(self, send: np.ndarray, recv: Optional[np.ndarray],
               root: int = 0) -> Optional[np.ndarray]:
        """Equal-block gather onto ``root`` (reference mpifuncs.c:1265):
        rank i's ``send`` lands in block i of root's ``recv``."""
        sflat = self._flat(send)
        tag = self._tags()
        if self.rank == root:
            rflat = self._flat(recv)
            if rflat.size != sflat.size * self.n:
                raise ValueError(
                    f"gather recv must hold n*send ({self.n}x{sflat.size}), "
                    f"got {rflat.size}"
                )
            size = sflat.size
            handles = [
                self.t.irecv(i, tag(0), out=rflat[i * size:(i + 1) * size])
                for i in range(self.n) if i != root
            ]
            np.copyto(rflat[root * size:(root + 1) * size], sflat)
            self._drive(*handles)
            return recv
        self._send(sflat, root, tag(0))
        return None

    def scan(self, arr: np.ndarray, op: str = "sum") -> np.ndarray:
        """Inclusive prefix reduction (reference mpifuncs.c:1780 MPI_Scan):
        rank r ends with fold(rank 0..r inputs), in place.  Linear chain —
        latency n-1 hops, which is fine at role-process counts."""
        fold = _OPS[op]
        flat = self._flat(arr)
        if self.n == 1:
            return arr
        tag = self._tags()
        if self.rank > 0:
            tmp = np.empty_like(flat)
            self._recv(tmp, self.rank - 1, tag(self.rank - 1))
            fold(flat, tmp)
        if self.rank + 1 < self.n:
            self._send(flat, self.rank + 1, tag(self.rank))
        return arr

    def allreduce_async(self, arr: np.ndarray, op: str = "sum"):
        """Nonblocking allreduce (reference mpifuncs.c:1357 Iallreduce;
        Test-before/after-Wait shape of test/testireduceall.lua:32-39).
        The returned handle owns ``arr`` until ``wait`` returns."""
        return _AsyncCollective(self, arr, op)


class _AsyncCollective:
    """Thread-backed in-flight collective with MPI Test/Wait semantics."""

    def __init__(self, coll: HostCollectives, arr: np.ndarray, op: str):
        self._err: Optional[BaseException] = None

        def run():
            try:
                coll.allreduce(arr, op=op)
            except BaseException as e:  # surfaced on wait/test
                self._err = e

        self._thread = threading.Thread(target=run, daemon=True)
        self._thread.start()

    def test(self) -> bool:
        done = not self._thread.is_alive()
        if done and self._err is not None:
            raise self._err
        return done

    def wait(self, timeout: Optional[float] = None) -> None:
        self._thread.join(timeout)
        if self._thread.is_alive():
            raise TimeoutError("allreduce_async still in flight")
        if self._err is not None:
            raise self._err
